"""Periodic exact engine: full-traversal histograms from O(1) windows.

The dense/stream engines (sampler/dense.py, sampler/stream.py) measure
every reuse exactly by sorting the whole packed access stream — 6N^3
keys for GEMM — which makes them sort-bound (XLA's CPU sort moves ~1e7
keys/s) and memory-bound (the one-shot sort OOMs at GEMM N=1024).
This engine computes the *same bit-exact histograms* from a handful of
two-period windows:

Per simulated thread, the trace of a rectangular nest is PERIODIC in
the parallel loop: every thread-local parallel iteration m ("period")
executes an identical body, so positions are m * acc_per_level[0] +
(fixed inner offsets) (core/trace.py). Two facts make the histogram a
weighted sum over tiny windows:

1. **Reuse values are translation-invariant.** A reuse from a source
   in period q to a sink in period q or q+1 is a position difference,
   so it depends only on (v0(q+1) - v0(q), v0(q) mod cls/ds) — never
   on q itself.
2. **Reuses never skip a period (checked, not assumed).** If a line is
   touched in periods q and q' > q+1 of the same thread, it is also
   touched in q+1, so the *next* touch of any source lies in its own
   or the following period (or nowhere). This holds whenever, per
   array, (a) all refs share one parallel-loop coefficient, and (b)
   the set of lines touched in one period is a contiguous interval:
   the per-period intervals then shift monotonically with v0, so a
   line present in U(q) and U(q+Delta) is inside U(q+1)'s hull and
   hence touched. `validate_periodic` verifies (a) symbolically and
   (b) numerically per phase; violations raise NotImplementedError and
   callers fall back to the streaming engine.

The engine therefore sorts one two-period window per distinct
signature (delta to next period, v0 phase) — typically 2-3 windows per
nest, each 2 * acc_per_level[0] keys — multiplies each window's
histogram by how many of the thread's periods carry that signature,
and sums. Sources are the window's first period only; a first-period
access with no same-line successor in the window is a cold (-1) line
by fact 2. Results are bit-exact vs run_dense/run_numpy (tests).

The reference has no analog: its exact samplers walk the full trace
(c_lib/test/sampler/gemm-t4-pluss-pro-model-ri-omp-seq.cpp). This is
the closed-form restructuring the TPU design buys — the same move that
turned the r10 walk into vectorized next-use solves (sampler/
sampled.py), applied to the exact path.

Multi-chip note: the sharded variant
(parallel/sharded.py::run_periodic_sharded) stacks the merged windows
of a nest on one axis (jax.vmap of the same kernel body) and lays that
axis over the mesh — each device evaluates its windows, outputs come
back per window (no cross-window reduction exists to fuse), and the
result is bit-identical to the single-device loop because the vmapped
body is the same integer computation. The axis is short (2-3 windows
per nest at one machine geometry; more across phase classes), so the
win is latency overlap, not throughput — the engine's absolute cost is
tiny either way; the sharded form exists so the exact path has the
same mesh-native execution story as the approximate engines.
"""

from __future__ import annotations


import jax
import jax.numpy as jnp
import numpy as np

from ..config import MachineConfig
from ..core.trace import NestTrace, ProgramTrace
from ..ir import Program
from ..ops.histogram import N_EXP_BINS, exp_bin, sorted_k_unique
from ..oracle.serial import OracleResult
from ..runtime import telemetry
from ..runtime.hist import PRIState
from .dense import _REF_BITS, _ceil_log2, nest_geometry, packed_ref_keys


_TIER_B_MAX_REACH = 8  # periods a tier-B numeric window must cover


@telemetry.counted_lru_cache(maxsize=64)
def _validate_nest(program: Program, nest_index: int, machine: MachineConfig):
    """Check the skip-free-reuse precondition for one nest (see module
    docstring fact 2). Raises NotImplementedError when the periodic
    decomposition would be unsound.

    Tiered per (nest, array) — each tier is a sufficient condition for
    "a line touched in two in-tid periods q < q' with q' > q+1 is also
    touched in q+1" (an interval of v0 values intersected with the
    thread's ordered period subsequence is always a consecutive run of
    it, so v0-global contiguity of each line's touch set suffices):

    - c0 == 0 for every ref: the touched-line set is identical every
      period, so any line's next touch is at most one period away.
    - single ref with a per-period contiguous line set: the set of v0
      touching a fixed line is a sliding-window intersection — it
      grows then shrinks monotonically, hence an interval.
    - equal c0 > 0 (stencils): numeric check over a (2R+1)-period
      window per phase that every line's touch set is v0-contiguous;
      equal c0 makes the pattern v0-translation-invariant (mod phase),
      so the window generalizes. R is the closed-form maximum touch
      reach; R > _TIER_B_MAX_REACH falls through to the hull tier.
    - equal c0 > 0, wide reach (hull tier): per-ref contiguous line
      sets + the refs' line intervals chain-overlapping at EVERY v0
      (checked vectorized): interval ends are monotone in v0, so a
      line in U(q) and U(q+D) lies in U(q+1)'s hull = U(q+1).

    Arrays mixing parallel-loop coefficients are rejected outright —
    not for fact 2 but for fact 1 (see _check_array).
    """
    trace = ProgramTrace(program, machine)
    nt = trace.nests[nest_index]
    t = nt.tables
    if nt.tri:
        raise NotImplementedError(
            f"{program.name} nest {nest_index}: triangular nests have "
            "per-period trip counts; the periodic engine needs a "
            "uniform period (use dense/stream)"
        )
    by_array: dict[int, list[int]] = {}
    for ri in range(t.n_refs):
        by_array.setdefault(int(t.ref_arrays[ri]), []).append(ri)
    for arr, refs in by_array.items():
        why = _check_array(nt, arr, refs)
        if why is not None:
            raise NotImplementedError(
                f"{program.name} nest {nest_index}: array {arr} "
                f"(refs {[t.ref_names[ri] for ri in refs]}): {why}; a "
                "reuse could skip a period (use dense/stream)"
            )
    return trace


def _check_array(nt: NestTrace, arr: int, refs: list) -> str | None:
    """None when some tier accepts the array, else the reason string.

    Every tier additionally requires ONE parallel-loop coefficient per
    array — that is what makes fact 1 (window translation invariance)
    hold per array: group structure never crosses arrays (groups are
    (array, line) pairs), and an array whose refs all shift lines at
    the same rate produces the same within-window grouping pattern at
    every period of a phase class. Mixed coefficients (syrk's A[i][k]
    vs A[j][k]) break it — the fixed ref re-touches the translating
    ref's line at a position that depends on the absolute v0 — so the
    representative-window decomposition itself is unsound there even
    when fact 2 holds, and the array is rejected outright."""
    t = nt.tables
    lp0 = nt.nest.loops[0]
    c0s = sorted({int(t.ref_coeffs[ri][0]) for ri in refs})
    if any(c < 0 for c in c0s):
        return f"negative parallel-loop coefficient {c0s[0]}"
    if len(c0s) > 1:
        return (
            f"refs mix parallel-loop coefficients {c0s}; the window "
            "histogram would depend on the absolute parallel value, "
            "not just its phase (no translation invariance)"
        )
    if c0s == [0]:
        return None  # same line set every period
    phases = _phase_count(nt)
    phase_v0s = [
        lp0.start + ph * lp0.step
        for ph in range(min(phases, lp0.trip))
    ]

    def ref_contiguous(ri: int) -> bool:
        for v0 in phase_v0s:
            u = np.unique(_ref_period_lines(nt, ri, v0))
            if len(u) != int(u[-1] - u[0] + 1):
                return False
        return True

    if len(refs) == 1:
        if ref_contiguous(refs[0]):
            return None
        return _check_exhaustive(
            nt, refs,
            "single ref with a non-contiguous per-period line set",
        )

    if len(c0s) == 1:
        # equal c0 > 0: numeric per-line window check
        c0 = c0s[0]
        flats_lo = min(
            int(t.ref_consts[ri]) + _inner_min(nt, ri) for ri in refs
        )
        flats_hi = max(
            int(t.ref_consts[ri]) + _inner_max(nt, ri) for ri in refs
        )
        g = max(1, nt.machine.cls // nt.machine.ds)
        reach = (flats_hi - flats_lo + g) // max(1, c0 * lp0.step) + 1
        if reach <= _TIER_B_MAX_REACH:
            for v0c in phase_v0s:
                pairs = []
                for d in range(-reach, reach + 1):
                    v0 = v0c + d * lp0.step
                    if not (lp0.start <= v0 < lp0.start + lp0.trip * lp0.step):
                        continue
                    ln = np.unique(np.concatenate(
                        [_ref_period_lines(nt, ri, v0) for ri in refs]
                    ))
                    pairs.append(
                        np.stack([ln, np.full_like(ln, d)], axis=1)
                    )
                allp = np.concatenate(pairs)
                order = np.lexsort((allp[:, 1], allp[:, 0]))
                allp = allp[order]
                line, dd = allp[:, 0], allp[:, 1]
                new = np.concatenate([[True], line[1:] != line[:-1]])
                # per line: contiguous iff count == max-min+1
                idx = np.cumsum(new) - 1
                n_lines = int(idx[-1]) + 1
                cnt = np.bincount(idx, minlength=n_lines)
                dmin = np.full(n_lines, 1 << 30)
                dmax = np.full(n_lines, -(1 << 30))
                np.minimum.at(dmin, idx, dd)
                np.maximum.at(dmax, idx, dd)
                if not (cnt == dmax - dmin + 1).all():
                    return (
                        "a line's touch-period set is non-contiguous "
                        f"within the +-{reach}-period window at v0={v0c}"
                    )
            return None
        # reach too wide for the window check: fall through to hull
    # wide-reach equal c0: per-ref contiguity + per-v0 interval chain
    # overlap, vectorized over every v0
    for ri in refs:
        if not ref_contiguous(ri):
            return _check_exhaustive(
                nt, refs,
                f"ref {t.ref_names[ri]} has a non-contiguous "
                "per-period line set (hull tier needs intervals)",
            )
    v0_all = lp0.start + np.arange(lp0.trip, dtype=np.int64) * lp0.step
    los, his = [], []
    for ri in refs:
        base = int(t.ref_consts[ri]) + int(t.ref_coeffs[ri][0]) * v0_all
        los.append((base + _inner_min(nt, ri)) * nt.machine.ds
                   // nt.machine.cls)
        his.append((base + _inner_max(nt, ri)) * nt.machine.ds
                   // nt.machine.cls)
    lo = np.stack(los, axis=1)  # (trip, refs)
    hi = np.stack(his, axis=1)
    order = np.argsort(lo, axis=1)
    lo_s = np.take_along_axis(lo, order, axis=1)
    hi_s = np.take_along_axis(hi, order, axis=1)
    run_hi = np.maximum.accumulate(hi_s, axis=1)
    if (lo_s[:, 1:] > run_hi[:, :-1] + 1).any():
        return _check_exhaustive(
            nt, refs, "per-period line intervals leave a gap at some v0"
        )
    return None


_EXHAUSTIVE_CAP = int(2e8)


def _check_exhaustive(nt: NestTrace, refs: list, why: str) -> str | None:
    """Last-resort sound tier: enumerate (line, v0) touch pairs over
    the WHOLE parallel loop and verify every line's touch set is a
    v0-interval — the property all the analytic tiers imply. Directly
    sound for any c0 structure (an interval of v0 intersected with a
    thread's ordered period subsequence is a consecutive run of it).
    Affordable exactly when the cheaper tiers fail in practice:
    transposed single refs (A[j][i]) touch only ~N/linesize lines per
    period, so trip x per-period-lines stays small. Returns None on
    success; the caller's `why` when the property fails or the
    enumeration would exceed _EXHAUSTIVE_CAP pairs."""
    lp0 = nt.nest.loops[0]
    per_period = sum(
        int(np.prod([nt.nest.loops[l].trip
                     for l in range(1, int(nt.tables.ref_levels[ri]) + 1)],
                    dtype=np.int64))
        for ri in refs
    )
    if lp0.trip * per_period > _EXHAUSTIVE_CAP:
        return why + " (and the nest is too large to verify exhaustively)"
    chunks = []
    for qi in range(lp0.trip):
        v0 = lp0.start + qi * lp0.step
        ln = np.unique(np.concatenate(
            [_ref_period_lines(nt, ri, v0) for ri in refs]
        ))
        chunks.append(np.stack([ln, np.full_like(ln, qi)], axis=1))
    allp = np.concatenate(chunks)
    order = np.lexsort((allp[:, 1], allp[:, 0]))
    allp = allp[order]
    line, qq = allp[:, 0], allp[:, 1]
    new = np.concatenate([[True], line[1:] != line[:-1]])
    idx = np.cumsum(new) - 1
    n_lines = int(idx[-1]) + 1
    cnt = np.bincount(idx, minlength=n_lines)
    qmin = np.full(n_lines, 1 << 62)
    qmax = np.full(n_lines, -(1 << 62))
    np.minimum.at(qmin, idx, qq)
    np.maximum.at(qmax, idx, qq)
    if (cnt == qmax - qmin + 1).all():
        return None
    return why


def _inner_min(nt: NestTrace, ri: int) -> int:
    t = nt.tables
    out = 0
    for l in range(1, int(t.ref_levels[ri]) + 1):
        lp = nt.nest.loops[l]
        c = int(t.ref_coeffs[ri][l])
        vals = (lp.start, lp.start + (lp.trip - 1) * lp.step)
        out += min(c * vals[0], c * vals[1])
    return out


def _inner_max(nt: NestTrace, ri: int) -> int:
    t = nt.tables
    out = 0
    for l in range(1, int(t.ref_levels[ri]) + 1):
        lp = nt.nest.loops[l]
        c = int(t.ref_coeffs[ri][l])
        vals = (lp.start, lp.start + (lp.trip - 1) * lp.step)
        out += max(c * vals[0], c * vals[1])
    return out


def _phase_count(nt: NestTrace) -> int:
    """Distinct per-period structures induced by line-granule rounding.

    The grouping pattern of a period at parallel value v0 depends on
    (c0 * v0) mod (cls/ds) per ref: successive periods differ by
    c0 * step there, so the pattern is identical for EVERY period —
    one phase — exactly when (c0 * step) % granule == 0 for every ref
    (the constant c0 * start offset is shared by all periods and
    cancels). Otherwise v0 mod granule covers every possible class."""
    t = nt.tables
    g = max(1, nt.machine.cls // nt.machine.ds)
    step = nt.nest.loops[0].step
    if all(
        (int(t.ref_coeffs[ri][0]) * step) % g == 0
        for ri in range(t.n_refs)
    ):
        return 1
    return g


def _ref_period_lines(nt: NestTrace, ri: int, v0: int) -> np.ndarray:
    """All cache lines one ref touches during one period (host numpy)."""
    t = nt.tables
    level = int(t.ref_levels[ri])
    flat = np.asarray([int(t.ref_consts[ri]) + int(t.ref_coeffs[ri][0]) * v0])
    for l in range(1, level + 1):
        lp = nt.nest.loops[l]
        vals = lp.start + np.arange(lp.trip, dtype=np.int64) * lp.step
        flat = (flat[:, None] + int(t.ref_coeffs[ri][l]) * vals[None, :]).ravel()
    return flat * nt.machine.ds // nt.machine.cls


def _signatures(nt: NestTrace, tid: int):
    """The thread's period sequence as {(delta, phase): multiplicity}.

    delta = v0 of the next thread-local period minus this one's
    (None for the final period), phase = v0 mod the granule when phases
    matter. Multiplicities are exact; the engine evaluates one window
    per distinct key and scales.
    """
    sched = nt.schedule
    cnt = sched.local_count(tid)
    if cnt == 0:
        return {}
    m = np.arange(cnt, dtype=np.int64)
    K = nt.machine.chunk_size
    v0 = sched.start + (
        ((m // K) * sched.threads + tid) * K + (m % K)
    ) * sched.step
    phases = _phase_count(nt)
    ph = v0 % phases if phases > 1 else np.zeros_like(v0)
    out: dict = {}
    for i in range(cnt):
        delta = int(v0[i + 1] - v0[i]) if i + 1 < cnt else None
        # signature keys carry a representative v0 (the first with that
        # signature) — windows only need *a* v0 realizing the phase
        key = (delta, int(ph[i]))
        if key in out:
            out[key][1] += 1
        else:
            out[key] = [int(v0[i]), 1]
    return {k: (v[0], v[1]) for k, v in out.items()}


def _window_kernel_body(nt: NestTrace, max_share: int, pair: bool):
    """(v0a, v0b) -> histogram contributions of one window, untraced.

    Window-relative positions (mrel 0/1) keep the packed keys narrow:
    grp_bits + ceil_log2(2 * period) + ref bits, independent of N's
    full trace length — which is what lets the periodic engine run at
    sizes whose full packed keys would not fit 63 bits.

    The body is exposed un-jitted so the single-window form
    (_window_kernel) and the mesh-sharded batched form (jax.vmap over
    a stacked window axis, parallel/sharded.py::run_periodic_sharded)
    trace the SAME integer computation — the bit-identity contract
    between them reduces to vmap semantics.
    """
    t = nt.tables
    a0 = int(t.acc_per_level[0])
    n_arrays, max_addr, n_groups = nest_geometry(nt)
    pos_bits = _ceil_log2(2 * a0 + 1)
    grp_bits = _ceil_log2(n_groups + 1)
    assert grp_bits + pos_bits + _REF_BITS <= 63, "window key overflow"
    n_m = 2 if pair else 1

    def kernel(v0a, v0b):
        v0 = jnp.stack([v0a, v0b])[:n_m].astype(jnp.int64)
        mrel = jnp.arange(n_m, dtype=jnp.int64)
        valid_m = jnp.ones(n_m, dtype=bool)
        keys = [
            packed_ref_keys(
                nt, ri, v0, mrel, valid_m, pos_bits, max_addr, n_groups
            )
            for ri in range(t.n_refs)
        ]
        key = jnp.sort(jnp.concatenate(keys))
        ref_s = (key & ((1 << _REF_BITS) - 1)).astype(jnp.int32)
        pos_s = (key >> _REF_BITS) & ((1 << pos_bits) - 1)
        grp_s = key >> (_REF_BITS + pos_bits)
        is_valid = grp_s != (n_groups - 1)
        same = jnp.concatenate(
            [jnp.array([False]), (grp_s[1:] == grp_s[:-1]) & is_valid[1:]]
        )
        prev_pos = jnp.concatenate([jnp.zeros(1, jnp.int64), pos_s[:-1]])
        reuse = jnp.where(same, pos_s - prev_pos, 0)
        # sources live in the window's first period
        src_first = same & (prev_pos < a0)
        thr = jnp.array(t.ref_share_thresholds, dtype=jnp.int64)[ref_s]
        is_share = src_first & (thr > 0) & (
            jnp.abs(reuse) > jnp.abs(reuse - thr)
        )
        is_noshare = src_first & ~is_share
        e = exp_bin(jnp.maximum(reuse, 1))
        noshare_hist = jnp.zeros(N_EXP_BINS, dtype=jnp.int64).at[e].add(
            is_noshare.astype(jnp.int64)
        )
        ratio = jnp.array(t.ref_share_ratios, dtype=jnp.int64)[ref_s]
        share_key = reuse * 8 + ratio
        sk, sc, n_unique = sorted_k_unique(share_key, is_share, max_share)
        # cold: first-period accesses with no same-line successor in
        # the window — by the skip-free property their line is never
        # touched again
        succ_same = jnp.concatenate([same[1:], jnp.array([False])])
        arr_of = jnp.where(is_valid, grp_s // max_addr, n_arrays)
        is_cold = is_valid & (pos_s < a0) & ~succ_same
        cold = jnp.zeros(n_arrays + 1, dtype=jnp.int64).at[
            jnp.where(is_cold, arr_of, n_arrays)
        ].add(1)[:n_arrays]
        return noshare_hist, sk, sc, n_unique, cold

    return kernel


def _window_kernel(nt: NestTrace, max_share: int, pair: bool):
    """jit: (v0a, v0b) -> histogram contributions of one window."""
    return jax.jit(_window_kernel_body(nt, max_share, pair))


@telemetry.counted_lru_cache(maxsize=32)
def _compiled_nest(program: Program, nest_index: int,
                   machine: MachineConfig, max_share: int):
    trace = _validate_nest(program, nest_index, machine)
    nt = trace.nests[nest_index]
    return nt, {
        True: _window_kernel(nt, max_share, pair=True),
        False: _window_kernel(nt, max_share, pair=False),
    }


@telemetry.counted_lru_cache(maxsize=32)
def _compiled_nest_batch(program: Program, nest_index: int,
                         machine: MachineConfig, max_share: int):
    """Batched twins of _compiled_nest's window kernels: jit(vmap) over
    a stacked window axis, the form whose input axis a mesh lays over
    devices (parallel/sharded.py). Same body as the scalar kernels, so
    every output is the same integer computation per window."""
    trace = _validate_nest(program, nest_index, machine)
    nt = trace.nests[nest_index]
    return nt, {
        pair: jax.jit(jax.vmap(_window_kernel_body(nt, max_share, pair)))
        for pair in (True, False)
    }


def validate_periodic(program: Program, machine: MachineConfig) -> None:
    """Raise NotImplementedError if any nest fails the preconditions."""
    for k in range(len(program.nests)):
        _validate_nest(program, k, machine)


def run_exact(program: Program, machine: MachineConfig,
              max_share: int = 64, mesh=None) -> OracleResult:
    """Fastest applicable exact engine: periodic when its
    preconditions hold, then the analytic closed-form engine
    (sampler/analytic.py — covers the periodic rejections: triangular
    nests and mixed parallel coefficients), then dense — whose own
    auto-route covers the memory ceiling by falling to stream. All of
    them produce bit-identical PRIStates (tests), so callers wanting
    "the exact histogram, fast" need no engine knowledge. The CLI's
    `--engine exact` is this function.

    Bit-identity across all routes is PROVEN for the model families
    pinned in tests/test_analytic.py (+ tools/verify_analytic.py
    recorded audits); a new program family routed to the analytic
    engine inherits its probe-backed (not proven) exactness — see the
    verification ledger in sampler/analytic.py.

    `mesh` (a 1-D jax.sharding.Mesh) runs whichever engine the router
    picks in its mesh-sharded form — bit-identical to the single-device
    run (tests/test_parallel.py); `--shard` on the CLI is this
    parameter. The dense fallback shards only when the mesh size
    divides thread_num (its mesh axis is the simulated-thread axis)."""
    try:
        validate_periodic(program, machine)
    except NotImplementedError:
        from .analytic import (
            run_analytic,
            validate_analytic,
            warn_if_unaudited,
        )

        try:
            validate_analytic(program, machine)
        except NotImplementedError:
            from .dense import run_dense

            if (
                mesh is not None
                and machine.thread_num % mesh.devices.size == 0
            ):
                from ..parallel.sharded import run_dense_sharded

                res = run_dense_sharded(
                    program, machine, mesh=mesh, max_share=max_share
                )
            else:
                res = run_dense(program, machine, max_share)
            # run_dense itself may have auto-routed past its memory
            # ceiling; it reports nothing, so the label stays coarse
            res.engine = "dense"
            return res
        # ADVICE round 5 (medium): the analytic engine's exactness is
        # PROVEN only for the audited model families; routing anything
        # else must say so instead of silently claiming bit-exactness
        warn_if_unaudited(program)
        res = run_analytic(program, machine, mesh=mesh)
        res.engine = "analytic"
        return res
    if mesh is not None:
        from ..parallel.sharded import run_periodic_sharded

        res = run_periodic_sharded(program, machine, mesh, max_share)
    else:
        res = run_periodic(program, machine, max_share)
    res.engine = "periodic"
    return res


def run_periodic(program: Program, machine: MachineConfig,
                 max_share: int = 64, window_eval=None) -> OracleResult:
    """Periodic exact engine -> host PRIState (== run_dense exactly).

    `window_eval(program, nest_index, nt, merged) -> {key: outputs}` is
    the evaluation hook the mesh-sharded path plugs in
    (parallel/sharded.py::run_periodic_sharded lays the merged window
    axis over the devices); the default evaluates each merged window as
    one scalar-kernel call. Either way the per-window outputs — and
    hence the folded state — are the same integer results.
    """
    P = machine.thread_num
    state = PRIState(P)
    per_tid = [0] * P
    engine_span = telemetry.span("engine", engine="periodic")
    engine_span.__enter__()
    for k in range(len(program.nests)):
        nt, kernels = _compiled_nest(program, k, machine, max_share)
        # windows are tid-independent: merge every tid's signature set,
        # evaluate each window once, then scale into each tid's state
        with telemetry.span("window_build", nest=k):
            merged: dict = {}
            per_tid_sigs = []
            for tid in range(P):
                sigs = _signatures(nt, tid)
                per_tid_sigs.append(sigs)
                for key, (v0_rep, _) in sigs.items():
                    merged.setdefault(key, v0_rep)
        if window_eval is not None:
            with telemetry.span("kernel", nest=k, windows=len(merged)):
                outs = window_eval(program, k, nt, merged)
        else:
            outs = {}
            with telemetry.span("kernel", nest=k, windows=len(merged)):
                for (delta, _ph), v0_rep in merged.items():
                    pair = delta is not None
                    v0b = v0_rep + (delta if pair else 0)
                    telemetry.count("dispatches")
                    outs[(delta, _ph)] = telemetry.record_fetch(
                        jax.device_get(kernels[pair](
                            jnp.int64(v0_rep), jnp.int64(v0b)
                        ))
                    )
        fold_span = telemetry.span("fold", nest=k)
        fold_span.__enter__()
        for tid in range(P):
            h = state.noshare[tid]
            hs_all = state.share[tid]
            for key, (_v0, mult) in per_tid_sigs[tid].items():
                noshare_hist, sk, sc, n_unique, cold = outs[key]
                if int(n_unique) > sk.shape[0]:
                    raise RuntimeError(
                        "share-value capacity exceeded; raise max_share "
                        f"(needed {int(n_unique)}, have {sk.shape[0]})"
                    )
                for e_idx in np.nonzero(noshare_hist)[0]:
                    kk = 1 << int(e_idx)
                    h[kk] = h.get(kk, 0.0) + float(
                        noshare_hist[e_idx]
                    ) * mult
                c = int(cold.sum())
                if c:
                    h[-1] = h.get(-1, 0.0) + float(c) * mult
                for kv, cnt in zip(sk, sc):
                    if cnt > 0:
                        reuse, ratio = divmod(int(kv), 8)
                        hs = hs_all.setdefault(ratio, {})
                        hs[reuse] = hs.get(reuse, 0.0) + float(cnt) * mult
            per_tid[tid] += nt.tid_length(tid)
        fold_span.__exit__(None, None, None)
    engine_span.__exit__(None, None, None)
    return OracleResult(
        state=state, total_accesses=sum(per_tid), per_tid_accesses=per_tid
    )
