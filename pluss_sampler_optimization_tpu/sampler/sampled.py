"""Random-start sampled TPU sampler — the r10 equivalent, vectorized.

Reproduces the capabilities of the reference's sampled variant
(c_lib/test/sampler/gemm-t4-pluss-pro-model-rs-ri-opt-r10.cpp):

- one sampler per static reference (the reference spawns six OS threads,
  :3203-3251; here each ref is one jitted vector program — the natural
  TPU analog of that task parallelism, and the axis the multi-chip
  path shards);
- num_samples = ceil(prod_l ratio*trip_l): reproduces the generated
  constants 2098 (3-deep) / 164 (2-deep) at N=128, ratio 10% (:156,
  :1688);
- samples drawn uniformly WITHOUT the last iteration of each loop —
  the generated `rand()%(((N-0)/1-((N-0)%1==0)))` draws from
  [0, trip-1) (:159-169); kept behind
  SamplerConfig.exclude_last_iteration;
- duplicate samples are redrawn (sample_names dedupe, :177);
- each sample's reuse interval is the forward distance, in its
  simulated thread's private access clock, to the next same-array
  touch of its cache line (count[tid] - LAT[tid][addr], :333) — here a
  closed-form solve (sampler/nextuse.py) instead of a fast-forwarded
  walk;
- samples whose line is never touched again before the nest's trace
  ends flush as -1 (:196, :671);
- share classification at the sink reference's carried threshold
  (:2482 for B0), recorded at ratio THREAD_NUM-1.

Outputs are exact sparse (reuse, count) pairs per tracked reference via
a fixed-capacity unique reduction, so the host can apply either the
runtime-v1 distribute (default; pluss_utils.h:1204-1208) or the r10
local distribute quirks (runtime/cri.py::R10Quirks) without loss.
"""

from __future__ import annotations

import dataclasses
import functools

import jax
import jax.numpy as jnp
import numpy as np

from ..config import MachineConfig, SamplerConfig
from ..core.trace import NestTrace, ProgramTrace
from ..ir import Program
from ..ops.histogram import fixed_k_unique, merge_pair_sets, sorted_k_unique
from ..runtime import telemetry
from ..runtime.hist import PRIState
from .nextuse import INF

_RATIO_SLOTS = 16  # packed key = reuse * 16 + (ratio | noshare-slot 15)
_NOSHARE_SLOT = _RATIO_SLOTS - 1

# Accelerator per-dispatch sample count; entry points that take
# batch=None resolve default_batch() at call time instead, so warmup()
# and the run compile at the same shapes on every backend. Callers
# overriding batch at one site must override it at both.
DEFAULT_BATCH = 1 << 20


def default_batch() -> int:
    """Per-dispatch sample count. Batch-size sweeps (2^15..2^22, GEMM
    N=2048) peak at 2^17 on the CPU backend — smaller working sets stay
    in cache on a host core — while accelerators amortize dispatch
    better at 2^20. Resolved at call time, after backend selection."""
    return (1 << 17) if jax.default_backend() == "cpu" else DEFAULT_BATCH
# Share-pair slots per dispatch. The PolyBench family yields a handful
# of distinct (reuse, class) pairs per batch (GEMM: <= 3), so 64 keeps
# fixed_k_unique on its 2-round fast path; a model that genuinely
# exceeds it triggers the drain loop's regrow-and-recompile (4x) once,
# not an error.
DEFAULT_CAPACITY = 64


def _place(x):
    """Commit one host buffer to the active replica's device
    (parallel/placement.py): an explicit jax.device_put inside a
    replica device scope, plain jnp.asarray outside one. Imported at
    call time — parallel/__init__ pulls in sharded.py, which imports
    THIS module."""
    from ..parallel.placement import place

    return place(x)


# directories already wired into jax's persistent compilation cache
# (the config update is process-global; re-applying it per run would
# just churn the config lock)
_CACHE_DIRS_APPLIED: set = set()


def _apply_compilation_cache(cfg) -> None:
    """Wire SamplerConfig.compilation_cache_dir into jax's persistent
    compilation cache, dropping the min compile-time threshold to 0 so
    even the CPU engines' fast-compiling kernels persist. A warm
    second process then loads executables instead of recompiling (its
    ledger rows record smaller compile deltas). No-op when the config
    carries no directory."""
    d = getattr(cfg, "compilation_cache_dir", None)
    if not d or d in _CACHE_DIRS_APPLIED:
        return
    _CACHE_DIRS_APPLIED.add(d)
    jax.config.update("jax_compilation_cache_dir", d)
    jax.config.update(
        "jax_persistent_cache_min_compile_time_secs", 0.0
    )


@dataclasses.dataclass
class SampledRefResult:
    """Exact per-tracked-ref sampled histograms (host form)."""

    name: str
    noshare: dict  # raw reuse -> count (bin on insertion for v1 parity)
    share: dict  # ratio -> {raw reuse -> count}
    cold: float  # samples with no further touch (-1 multiplicity)
    n_samples: int


def _sample_plan(nest_trace: NestTrace, ref_idx: int, cfg: SamplerConfig):
    """(bounding-box highs, target sample count, |valid space|) for one
    tracked ref — the single source of truth for both draw paths.

    Triangular nests draw from the rectangular bounding box and reject
    points outside the per-v0 bounds (draw_sample_keys / draw.py); the
    target count generalizes the generated-code expression to
    ceil(ratio^depth * |valid drawable space|) — the same density over
    the space that actually exists (rectangular nests keep the exact
    `ceil(prod(ratio*trip))` form via cfg.num_samples, and their valid
    space IS the box).
    """
    lv = int(nest_trace.tables.ref_levels[ref_idx])
    excl = 1 if cfg.exclude_last_iteration else 0
    if nest_trace.tri and lv >= 1:
        import math

        lp0 = nest_trace.nest.loops[0]
        n0_hi = max(1, lp0.trip - excl)
        highs = [n0_hi] + [
            max(1, nest_trace.max_trips[l] - excl)
            for l in range(1, lv + 1)
        ]
        v0 = lp0.start + np.arange(n0_hi, dtype=np.int64) * lp0.step
        cnt = np.ones(len(v0), dtype=np.int64)
        for l in range(1, lv + 1):
            cnt *= np.maximum(
                0, nest_trace.nest.loops[l].trip_at(v0) - excl
            )
        space = int(cnt.sum())
        if space == 0:
            return highs, 0, 0
        s = max(1, min(
            int(math.ceil((cfg.ratio ** (lv + 1)) * space)), space
        ))
        return highs, s, space
    trips = [nest_trace.nest.loops[l].trip for l in range(lv + 1)]
    highs = [
        max(1, t - 1 if cfg.exclude_last_iteration else t) for t in trips
    ]
    space = 1
    for h in highs:
        space *= h
    return highs, cfg.num_samples(tuple(trips)), space


def _sample_highs(nest_trace: NestTrace, ref_idx: int, cfg: SamplerConfig):
    """(bounding-box highs, target sample count); see _sample_plan."""
    highs, s, _ = _sample_plan(nest_trace, ref_idx, cfg)
    return highs, s


def _tri_valid_keys(nest_trace: NestTrace, ref_idx: int, keys, highs, excl):
    """Filter bounding-box keys down to points inside the triangular
    bounds (n_l < trip_l(v0) - excl for every inner level)."""
    lv = int(nest_trace.tables.ref_levels[ref_idx])
    cols = decode_sample_keys(keys, highs)
    v0 = nest_trace.nest.loops[0].start + cols[:, 0] * (
        nest_trace.nest.loops[0].step
    )
    ok = np.ones(len(keys), dtype=bool)
    for l in range(1, lv + 1):
        ok &= cols[:, l] < (
            nest_trace.nest.loops[l].trip_at(v0) - excl
        )
    return keys[ok]


def draw_sample_keys(
    nest_trace: NestTrace, ref_idx: int, cfg: SamplerConfig, seed: int
):
    """Dedup'd uniform samples as mixed-radix keys, shape (S,) int64.

    The key form is what large runs hold in memory (a GEMM N=8192 ref
    draws ~5.5e8 samples: 4.4 GB of keys vs 13 GB of decoded tuples);
    decode_sample_keys expands one batch at a time at dispatch.
    """
    highs, s = _sample_highs(nest_trace, ref_idx, cfg)
    rng = np.random.default_rng(seed)
    tri = nest_trace.tri and int(nest_trace.tables.ref_levels[ref_idx]) >= 1
    excl = 1 if cfg.exclude_last_iteration else 0
    # Draw-until-s-unique, matching the reference's one-at-a-time
    # redraw loop's sample *set* semantics (r10 :159-185): accumulate
    # uniques, then thin to exactly s with an unbiased random subset
    # (the drawn set is exchangeable, so a uniform subset of it is
    # itself a uniform s-subset of the space; truncating the *sorted*
    # uniques would bias toward small keys). Triangular nests draw the
    # box and reject out-of-bounds points, which preserves uniformity
    # over the valid space.
    #
    # Keys are drawn directly in the flat mixed-radix space — one
    # int64 uniform over prod(highs) IS the per-level composition, one
    # rng call instead of depth calls (a ~2x draw-stage win measured
    # at GEMM N=2048, where drawing was ~45% of engine wall time).
    space = 1
    for h in highs:
        space *= h
    if space >= 1 << 63:
        # a bare assert would vanish under python -O and silently draw
        # from a wrapped range
        raise NotImplementedError(
            f"ref {nest_trace.tables.ref_names[ref_idx]}: sample space "
            f"prod(highs)={space:.3e} exceeds int64 flat keys (2^63); "
            "the flat-space drawing needs a per-level fallback for "
            "nests this deep/wide"
        )
    uniq = np.empty(0, dtype=np.int64)
    while len(uniq) < s:
        need = s - len(uniq)
        batch_keys = rng.integers(0, space, size=max(64, need + need // 8))
        if tri:
            batch_keys = _tri_valid_keys(
                nest_trace, ref_idx, batch_keys, highs, excl
            )
        uniq = (
            np.unique(batch_keys) if len(uniq) == 0
            else np.union1d(uniq, batch_keys)
        )
    if len(uniq) > s:
        # Thin by dropping the complement: (len-s) << s near the target
        # margin, so indexing a uniform drop-set is much cheaper than
        # materializing a permutation of the whole unique set, and a
        # uniform (len-s)-drop leaves exactly a uniform s-subset.
        drop = rng.choice(len(uniq), size=len(uniq) - s, replace=False)
        keep = np.ones(len(uniq), dtype=bool)
        keep[drop] = False
        uniq = uniq[keep]
    return uniq, highs


def decode_sample_keys(keys, highs):
    """Mixed-radix keys -> normalized iteration tuples (len(keys), depth).

    Works on numpy arrays (host) and traced jnp arrays alike: the
    kernels ship one int64 key per sample and decode on device, which
    keeps the host->device transfer at 8 bytes/sample (it crosses a
    network tunnel when the TPU is remote) and moves the divmod chain
    onto the device."""
    xp = jnp if isinstance(keys, jnp.ndarray) else np
    cols = []
    for h in reversed(highs):
        keys, col = xp.divmod(keys, h)
        cols.append(col)
    return xp.stack(cols[::-1], axis=1).astype(xp.int64)


def draw_samples(
    nest_trace: NestTrace, ref_idx: int, cfg: SamplerConfig, seed: int
) -> np.ndarray:
    """Dedup'd uniform normalized iteration tuples, shape (S, depth)."""
    keys, highs = draw_sample_keys(nest_trace, ref_idx, cfg, seed)
    return decode_sample_keys(keys, highs)


def check_packed_ratios(nt: NestTrace) -> None:
    """Every share ratio must fit the packed-key radix."""
    t = nt.tables
    for j in range(t.n_refs):
        if int(t.ref_share_ratios[j]) >= _NOSHARE_SLOT:
            raise NotImplementedError(
                f"ref {t.ref_names[j]}: share ratio "
                f"{int(t.ref_share_ratios[j])} collides with the packed "
                f"noshare slot (must be < {_NOSHARE_SLOT})"
            )


def classify_samples(nt: NestTrace, ref_idx: int, samples, rx=None):
    """Per-sample reuse classification (traced JAX math).

    Returns (packed, ri, is_share, found): the packed
    reuse*_RATIO_SLOTS+slot key, the raw reuse interval, the share
    classification at the sink's carried threshold
    (...ri-omp-seq.cpp:203-207) and the found mask (False = the line is
    never touched again, the -1 flush case, r10 :671). Single source of
    truth for both the single-device and the mesh-sharded kernels.
    `rx` (default ref_idx) is the VALUE-lookup index — a traced operand
    in the shared kernels (see access_position's rx doc).
    """
    t = nt.tables
    tid, p0, line, m0 = _sample_geometry(nt, ref_idx, samples, rx)
    best, best_sink = _best_sink(nt, ref_idx, tid, p0, line, m0)
    found = best < INF
    ri = jnp.where(found, best - p0, 0)
    thr = jnp.asarray(nt.vals["thr"])[best_sink]
    ratio = jnp.array(t.ref_share_ratios, dtype=jnp.int64)[best_sink]
    is_share = found & (thr > 0) & (jnp.abs(ri) > jnp.abs(ri - thr))
    slot = jnp.where(is_share, ratio, _NOSHARE_SLOT)
    packed = ri * _RATIO_SLOTS + slot
    return packed, ri, is_share, found


def pad_keys(
    keys: np.ndarray, n_dev: int, min_per_dev: int = 16,
    total: int | None = None,
):
    """Pad sample keys with repeats of key 0 so each of n_dev equal
    shards gets at least min_per_dev entries (or exactly total/n_dev
    when `total` is given, to keep one compiled shape across batch
    chunks). Returns (padded keys, valid count); the kernels
    reconstruct the padding weight mask from the count on device."""
    s = len(keys)
    if s == 0:
        raise ValueError("pad_keys needs at least one sample key")
    if total is None:
        per_dev = max(min_per_dev, -(-s // n_dev))
        total = per_dev * n_dev
    assert total % n_dev == 0 and total >= s
    out = np.full(total, keys[0], dtype=np.int64)
    out[:s] = keys
    return out, s


def decode_pairs(keys, counts, noshare: dict, share: dict) -> None:
    """Fold device (packed key, count) pairs into host sparse hists."""
    for key, cnt in zip(keys.tolist(), counts.tolist()):
        if cnt <= 0:
            continue
        ri_val, slot = divmod(int(key), _RATIO_SLOTS)
        if slot == _NOSHARE_SLOT:
            noshare[ri_val] = noshare.get(ri_val, 0.0) + cnt
        else:
            h = share.setdefault(slot, {})
            h[ri_val] = h.get(ri_val, 0.0) + cnt


def _pad_highs(highs) -> np.ndarray:
    """Mixed-radix highs padded to MAX_DEPTH with 1s, as an int64
    operand vector: the padded divmods are no-ops (col 0), so one
    compiled decode serves every ref depth and every N."""
    from ..ir import MAX_DEPTH

    out = np.ones(MAX_DEPTH, dtype=np.int64)
    out[: len(highs)] = list(highs)
    return out


def _kernel_sig(nt: NestTrace, ref_idx: int) -> tuple:
    """Everything a compiled kernel bakes in as STRUCTURE, as a hashable
    key. Two (nest, ref) pairs with equal signatures can share one
    compiled kernel — all remaining numeric differences (trips, coeffs,
    consts, thresholds, offsets, ...) ride in through the nt.vals
    operand pytree. In practice the signature is N-invariant (for N
    large enough that the band plans stabilize, N >= ~2 cache lines per
    row), so GEMM at N=256 and N=4096 share kernels, and structurally
    identical refs (e.g. the read and write halves of `C[i][j] +=`)
    collapse to one compile.

    The rule that keeps this safe: every concrete value the traced code
    reads from the nest (rather than from nt.vals) MUST appear here —
    loop starts/steps, affine structure coefficients, npre/npost, the
    schedule's static fields, machine geometry, the _best_sink group
    partition and each group's band plan (sampler/nextuse.py::band_plan).
    """
    from .nextuse import band_plan

    t = nt.tables
    m = nt.machine
    sched = nt.schedule
    W = m.lines_per_element_block
    plans = tuple(
        (tuple(sinks), band_plan(nt, sinks[0], W))
        for sinks in _sink_groups(nt, ref_idx)
    )
    return (
        # source structural key: level + array (the value index rides
        # in as the traced rx operand, so e.g. C[i][j]'s read and write
        # halves share one compile); triangular sources keep their
        # exact index — tri_position reads structural slot offsets
        (
            int(t.ref_levels[ref_idx]),
            int(t.ref_arrays[ref_idx]),
            ref_idx if nt.tri else None,
        ),
        nt.tri,
        int(t.depth),
        nt.npre,
        nt.npost,
        tuple(int(x) for x in t.ref_levels),
        tuple(int(x) for x in t.ref_arrays),
        tuple(int(x) for x in t.ref_share_ratios),
        tuple(r.slot for r in nt.nest.refs),
        tuple(int(x) for x in t.steps),
        tuple(int(x) for x in t.starts),
        tuple(int(x) for x in t.trip_coeffs),
        tuple(int(x) for x in t.start_coeffs),
        (m.thread_num, m.chunk_size, m.ds, m.cls),
        (sched.chunk, sched.threads, sched.start, sched.step),
        nt.tri_base.shape if nt.tri else None,
        plans,
    )


def _sink_groups(nt: NestTrace, ref_idx: int) -> list:
    """Same-array sink refs partitioned by identical flat map
    ((level, coeffs, const) equality), in first-seen order.

    SINGLE source of truth for both _best_sink (the traced group
    solve) and _kernel_sig (the sharing key): kernel-sharing soundness
    requires the signature to capture exactly the partition the traced
    code uses, so they must never diverge."""
    t = nt.tables
    groups: dict[tuple, list[int]] = {}
    for j in range(t.n_refs):
        if t.ref_arrays[j] != t.ref_arrays[ref_idx]:
            continue
        key = (
            int(t.ref_levels[j]),
            tuple(int(c) for c in t.ref_coeffs[j]),
            int(t.ref_consts[j]),
        )
        groups.setdefault(key, []).append(j)
    return list(groups.values())


import collections as _collections


def lru_cached(cache: "_collections.OrderedDict", key, build, maxsize: int):
    """Bounded LRU lookup shared by the kernel signature caches here
    and in parallel/sharded.py: each cached closure pins a whole
    NestTrace (incl. tri_base at triangular N) plus compiled
    executables, so the caches must evict. Hits/misses/evictions land
    in the active telemetry run's kernel_cache_* counters, the same
    names the counted functools caches report."""
    entry = cache.get(key)
    if entry is None:
        telemetry.count("kernel_cache_misses")
        entry = build()
        cache[key] = entry
        while len(cache) > maxsize:
            cache.popitem(last=False)
            telemetry.count("kernel_cache_evictions")
    else:
        telemetry.count("kernel_cache_hits")
        cache.move_to_end(key)
    # occupancy gauge, same names the counted functools caches export
    telemetry.gauge("kernel_cache_size", len(cache))
    telemetry.gauge("kernel_cache_maxsize", maxsize)
    return entry


# signature -> {"plain": ..., "scan": ..., "masked": ..., "raw": ...}
# jitted kernels. The closures hold the FIRST trace that produced the
# signature, for structure only; values always arrive through the vals
# operand.
_SIG_KERNELS: "_collections.OrderedDict" = _collections.OrderedDict()
_SIG_KERNELS_MAX = 64


def _ref_sig_digest(nt: NestTrace, ref_idx: int) -> str:
    """Canonical digest of the ref's kernel signature — the kernel
    cache key AND the cross-ref fusion bucket id (refs of one nest
    sharing a digest share one compiled kernel, so their buffers can
    stack into one vmapped dispatch)."""
    from ..service.fingerprint import structure_digest

    return structure_digest(_kernel_sig(nt, ref_idx))


def _kernels_for(nt: NestTrace, ref_idx: int, digest: str | None = None):
    # keyed by the canonical digest of the signature tuple — the same
    # content-hash discipline the service's result store uses
    # (service/fingerprint.py::structure_digest); distinctness is
    # exactly the signature's, so the sharing contract pinned by
    # tests/test_compile_sharing.py is unchanged
    return lru_cached(
        _SIG_KERNELS,
        digest if digest is not None else _ref_sig_digest(nt, ref_idx),
        lambda: {
            "plain": _build_ref_kernel(nt, ref_idx),
            "scan": _build_ref_kernel_scan(nt, ref_idx),
            "fused": _build_ref_kernel_fused(nt, ref_idx),
            "fused_multi": _build_ref_kernel_fused_multi(nt, ref_idx),
            "masked": _build_ref_kernel_masked(nt, ref_idx),
            "raw": _build_ref_kernel_raw(nt, ref_idx),
        },
        _SIG_KERNELS_MAX,
    )


def _build_ref_kernel_raw(nt: NestTrace, ref_idx: int):
    """Classify only — (packed, found) per sample, no on-device unique
    reduction. The analytic exact engine (sampler/analytic.py) consumes
    whole period boxes whose handful of distinct values it extracts
    host-side with np.unique: on the CPU backend numpy's sort is ~5x
    XLA's, and on accelerators the per-chunk fetch is batch-sized and
    sequential-friendly. The sampled engine keeps the on-device
    reductions (its chunks stream over a possibly tunneled link)."""
    check_packed_ratios(nt)

    @jax.jit
    def kernel(sample_keys, highs, vals, rx):
        snt = nt.with_vals(vals)
        samples = decode_sample_keys(jnp.asarray(sample_keys), highs)
        packed, _, _, found = classify_samples(snt, ref_idx, samples, rx)
        return packed, found

    return kernel


def _build_ref_kernel(nt: NestTrace, ref_idx: int):
    """jitted (sample keys, valid count) -> packed unique pairs + cold.

    Samples arrive as mixed-radix int64 keys, one per sample — the
    minimal wire format (the host->device link crosses a network tunnel
    when the TPU is remote) — and are decoded by the device's divmod
    chain; the padding weight mask is likewise reconstructed on device
    from the valid count. `highs` (padded to MAX_DEPTH) and `vals` (the
    trace's value overlay) are device operands, so one compile serves
    every N and every structurally identical ref (round-4 verdict: the
    per-(ref, N) cold-compile tax through the tunneled AOT helper).
    """
    check_packed_ratios(nt)

    @functools.partial(jax.jit, static_argnames=("capacity",))
    def kernel(sample_keys, n_valid, highs, vals, rx, capacity: int):
        snt = nt.with_vals(vals)
        samples = decode_sample_keys(jnp.asarray(sample_keys), highs)
        packed, _, _, found = classify_samples(snt, ref_idx, samples, rx)
        w = jnp.arange(sample_keys.shape[0], dtype=jnp.int64) < n_valid
        keys, counts, n_unique = fixed_k_unique(packed, found & w, capacity)
        cold = jnp.sum((~found & w).astype(jnp.int64))
        return keys, counts, n_unique, cold

    return kernel


def _build_ref_kernel_scan(nt: NestTrace, ref_idx: int):
    """Whole-buffer twin of the masked kernel: the chunk loop lives
    inside the jit as a lax.scan, with the sparse (key, count) pairs
    merged ON DEVICE between chunks (weighted fixed_k_unique over the
    2*capacity concatenated pair sets — a few hundred elements).

    One dispatch + one result fetch per ref, instead of one fetch per
    chunk: over a tunneled link every round trip costs ~70 ms, so at
    GEMM N=4096 (~280 chunks across refs) the per-chunk drain alone
    was a ~20 s latency floor. Memory stays chunk-bounded — scan keeps
    one chunk's classify intermediates live at a time.

    Returns (keys, counts, max_nu, cold) where max_nu is the maximum
    of every per-chunk and merged unique count — the host regrows
    capacity and reruns when it exceeds the dispatch capacity, same
    contract as the other kernel forms.
    """
    check_packed_ratios(nt)

    @functools.partial(
        jax.jit, static_argnames=("capacity", "n_chunks")
    )
    def kernel(keys_B, mask_B, highs, vals, rx, capacity: int,
               n_chunks: int):
        snt = nt.with_vals(vals)
        kb = keys_B.reshape(n_chunks, -1)
        mb = mask_B.reshape(n_chunks, -1)

        def step(carry, xm):
            ck, cc, cold, max_nu = carry
            x, msk = xm
            samples = decode_sample_keys(x, highs)
            packed, _, _, found = classify_samples(snt, ref_idx, samples, rx)
            k2, c2, nu = fixed_k_unique(packed, found & msk, capacity)
            mk, mc, mnu = merge_pair_sets(ck, cc, k2, c2, capacity)
            cold = cold + jnp.sum((~found & msk).astype(jnp.int64))
            max_nu = jnp.maximum(max_nu, jnp.maximum(nu, mnu))
            return (mk, mc, cold, max_nu), None

        init = (
            jnp.full(capacity, -1, dtype=jnp.int64),
            jnp.zeros(capacity, dtype=jnp.int64),
            jnp.int64(0),
            jnp.int64(0),
        )
        (mk, mc, cold, max_nu), _ = jax.lax.scan(step, init, (kb, mb))
        return mk, mc, max_nu, cold

    return kernel


def _build_ref_kernel_fused(nt: NestTrace, ref_idx: int):
    """Cross-ref bucket twin of _build_ref_kernel_scan: the stacked
    (R, B) key/mask buffers of every ref in one kernel-signature bucket
    are classified by ONE dispatch, vmapped over the leading ref axis
    (the value-lookup index arrives as an (R,) rx operand — the same
    trick that lets structurally identical refs share a compile,
    batched). Per-ref (keys, counts, max_nu, cold) come back stacked;
    the host decodes each row into its own ref's histograms.

    Inside vmap the unique reductions are sorted_k_unique, not
    fixed_k_unique: under vmap the latter's lax.cond fallback lowers to
    a select that executes its sort branch on every call (see the
    fixed_k_unique docstring), so the hash rounds would be pure
    overhead here. Both reductions are exact with identical
    (keys, counts, n_unique) outputs, so the fused path stays
    bit-identical to the serial kernels at the decoded-result level —
    the fusion on/off tests pin it.

    The stacked key/mask buffers are donated on accelerator backends
    (the CPU runtime does not implement donation and would warn):
    regrows and back-to-back bucket dispatches then reuse the pages
    instead of double-allocating. The drain loop re-materializes
    inputs through its make_inputs thunk when it must re-dispatch.
    """
    check_packed_ratios(nt)
    donate = () if jax.default_backend() == "cpu" else (0, 1)

    @functools.partial(
        jax.jit, static_argnames=("capacity", "n_chunks"),
        donate_argnums=donate,
    )
    def kernel(keys_RB, mask_RB, highs, vals, rx_R, capacity: int,
               n_chunks: int):
        snt = nt.with_vals(vals)

        def one_ref(keys_B, mask_B, rx):
            kb = keys_B.reshape(n_chunks, -1)
            mb = mask_B.reshape(n_chunks, -1)

            def step(carry, xm):
                ck, cc, cold, max_nu = carry
                x, msk = xm
                samples = decode_sample_keys(x, highs)
                packed, _, _, found = classify_samples(
                    snt, ref_idx, samples, rx
                )
                k2, c2, nu = sorted_k_unique(
                    packed, found & msk, capacity
                )
                w = jnp.concatenate([cc, c2])
                mk, mc, mnu = sorted_k_unique(
                    jnp.concatenate([ck, k2]), w > 0, capacity,
                    weights=w,
                )
                cold = cold + jnp.sum((~found & msk).astype(jnp.int64))
                max_nu = jnp.maximum(max_nu, jnp.maximum(nu, mnu))
                return (mk, mc, cold, max_nu), None

            init = (
                jnp.full(capacity, -1, dtype=jnp.int64),
                jnp.zeros(capacity, dtype=jnp.int64),
                jnp.int64(0),
                jnp.int64(0),
            )
            (mk, mc, cold, max_nu), _ = jax.lax.scan(
                step, init, (kb, mb)
            )
            return mk, mc, max_nu, cold

        return jax.vmap(one_ref, in_axes=(0, 0, 0))(
            keys_RB, mask_RB, rx_R
        )

    return kernel


def _build_ref_kernel_fused_multi(nt: NestTrace, ref_idx: int):
    """Cross-REQUEST twin of _build_ref_kernel_fused: one vmapped scan
    dispatch over rows drawn from DIFFERENT programs/machines that
    share this kernel signature.

    Where the single-program fused kernel broadcasts one (highs, vals)
    pair across the stacked rows, here each row carries its own:
    highs_R is the (R, MAX_DEPTH) stacked radix operand and vals_R the
    leading-axis-stacked value overlay. The signature contract
    (_kernel_sig: "every concrete value the traced code reads from the
    nest rather than from nt.vals MUST appear here") is what makes
    this sound — equal signatures guarantee equal vals leaf shapes, so
    numeric differences between requests (trips, coeffs, thresholds;
    e.g. gemm N=256 vs N=4096, or gemm and 2mm rows whose nests lower
    to one signature) ride entirely in the per-row operands. The scan
    body per row is the one each member would run solo, so the batched
    dispatch stays exact at the decoded-pair level.
    """
    check_packed_ratios(nt)
    donate = () if jax.default_backend() == "cpu" else (0, 1)

    @functools.partial(
        jax.jit, static_argnames=("capacity", "n_chunks"),
        donate_argnums=donate,
    )
    def kernel(keys_RB, mask_RB, highs_R, vals_R, rx_R, capacity: int,
               n_chunks: int):

        def one_ref(keys_B, mask_B, highs, vals, rx):
            snt = nt.with_vals(vals)
            kb = keys_B.reshape(n_chunks, -1)
            mb = mask_B.reshape(n_chunks, -1)

            def step(carry, xm):
                ck, cc, cold, max_nu = carry
                x, msk = xm
                samples = decode_sample_keys(x, highs)
                packed, _, _, found = classify_samples(
                    snt, ref_idx, samples, rx
                )
                k2, c2, nu = sorted_k_unique(
                    packed, found & msk, capacity
                )
                w = jnp.concatenate([cc, c2])
                mk, mc, mnu = sorted_k_unique(
                    jnp.concatenate([ck, k2]), w > 0, capacity,
                    weights=w,
                )
                cold = cold + jnp.sum((~found & msk).astype(jnp.int64))
                max_nu = jnp.maximum(max_nu, jnp.maximum(nu, mnu))
                return (mk, mc, cold, max_nu), None

            init = (
                jnp.full(capacity, -1, dtype=jnp.int64),
                jnp.zeros(capacity, dtype=jnp.int64),
                jnp.int64(0),
                jnp.int64(0),
            )
            (mk, mc, cold, max_nu), _ = jax.lax.scan(
                step, init, (kb, mb)
            )
            return mk, mc, max_nu, cold

        return jax.vmap(one_ref, in_axes=(0, 0, 0, 0, 0))(
            keys_RB, mask_RB, highs_R, vals_R, rx_R
        )

    return kernel


def _build_ref_kernel_masked(nt: NestTrace, ref_idx: int):
    """Masked twin of _build_ref_kernel for device-drawn samples.

    Device-side drawing (sampler/draw.py) produces a full candidate
    buffer plus a boolean selection mask instead of a compacted
    prefix, so downstream shapes stay one-per-batch across every ref
    and N; this kernel consumes (keys chunk, mask chunk) directly —
    the buffer never round-trips through the host.

    NOT on the production path: sampled_outputs routes device-drawn
    buffers through _build_ref_kernel_scan only. This form is kept as
    the scan kernel's single-chunk parity oracle — tests/test_draw.py
    pins the two bit-identical, which anchors the scan's on-device
    merge against the simplest possible masked classify.
    """
    check_packed_ratios(nt)

    @functools.partial(jax.jit, static_argnames=("capacity",))
    def kernel(sample_keys, mask, highs, vals, rx, capacity: int):
        snt = nt.with_vals(vals)
        samples = decode_sample_keys(sample_keys, highs)
        packed, _, _, found = classify_samples(snt, ref_idx, samples, rx)
        keys, counts, n_unique = fixed_k_unique(
            packed, found & mask, capacity
        )
        cold = jnp.sum((~found & mask).astype(jnp.int64))
        return keys, counts, n_unique, cold

    return kernel


def _sample_geometry(nt: NestTrace, ref_idx: int, samples, rx=None):
    """Sample tuples -> (tid, p0, line, m) in the thread-local trace.

    `rx` (default ref_idx) indexes the value overlay — a traced scalar
    in the shared kernels, so refs that differ only in offsets/affine
    constants (e.g. the read/write halves of `C[i][j] +=`) reuse one
    compiled kernel; ref_idx supplies the static structure (level,
    slot layout)."""
    t = nt.tables
    sched = nt.schedule
    rx = ref_idx if rx is None else rx
    lv = int(t.ref_levels[ref_idx])
    n = [samples[:, l] for l in range(lv + 1)]
    tid = sched.owner_tid(n[0])
    m = sched.local_index(n[0])
    v0 = sched.value(n[0])
    vals = [v0] + [
        nt.start_at(l, v0) + n[l] * nt.nest.loops[l].step
        for l in range(1, lv + 1)
    ]
    if nt.tri:
        base = jnp.asarray(nt.vals["tri_base"])[tid, m]
        p0 = nt.tri_position(
            ref_idx, v0, base, n[1] if lv >= 1 else 0,
            n[2] if lv >= 2 else 0,
        )
    else:
        p0 = nt.access_position(
            ref_idx, m, n[1] if lv >= 1 else 0, n[2] if lv >= 2 else 0,
            rx=rx,
        )
    flat = jnp.zeros_like(p0) + nt.vals["const"][rx]
    for l in range(lv + 1):
        flat = flat + vals[l] * nt.vals["coeff"][rx][l]
    line = flat * nt.machine.ds // nt.machine.cls
    return tid, p0, line, m


def _best_sink(nt: NestTrace, ref_idx: int, tid, p0, line, m0):
    """Min next-use position over same-array sink refs + argmin sink.

    Sinks sharing one flat map (e.g. the read and write halves of an
    accumulator statement) are solved as a group: the band candidates
    and level specs are built once, each member pays only its own
    position reduction.
    """
    from .nextuse import next_use_candidates_group, next_use_candidates_tri_group

    best = jnp.full_like(p0, INF)
    best_sink = jnp.zeros_like(p0, dtype=jnp.int32)
    for sinks in _sink_groups(nt, ref_idx):
        if nt.tri:
            bests = next_use_candidates_tri_group(
                nt, tuple(sinks), tid, p0, line, m0
            )
        else:
            bests = next_use_candidates_group(
                nt, tuple(sinks), tid, p0, line
            )
        for j in sinks:
            pj = bests[j]
            take = pj < best
            best = jnp.where(take, pj, best)
            best_sink = jnp.where(take, jnp.int32(j), best_sink)
    return best, best_sink


def per_sample_ri(
    program: Program, machine: MachineConfig, nest_idx: int, ref_idx: int,
    samples: np.ndarray,
):
    """Debug/tracing surface: per-sample (position, reuse, sink, found).

    The DEBUG builds of the reference print per-sample reuse pairs
    ("[reuse] src -> sink", ...rs-ri-opt-r10.cpp:566-568); this exposes
    the same information from the vectorized engine.
    """
    trace = ProgramTrace(program, machine)
    nt = trace.nests[nest_idx]
    samples = _place(np.asarray(samples, dtype=np.int64))
    tid, p0, line, m0 = _sample_geometry(nt, ref_idx, samples)
    best, best_sink = _best_sink(nt, ref_idx, tid, p0, line, m0)
    found = best < INF
    return (
        np.asarray(p0),
        np.where(np.asarray(found), np.asarray(best - p0), -1),
        np.asarray(best_sink),
        np.asarray(found),
        np.asarray(tid),
        np.asarray(line),
    )


@telemetry.counted_lru_cache(maxsize=64)
def _program_kernels(program: Program, machine: MachineConfig):
    trace = ProgramTrace(program, machine)
    kernels = []
    for k, nt in enumerate(trace.nests):
        if nt.tri and any(lp.step != 1 for lp in nt.nest.loops):
            raise NotImplementedError(
                f"{program.name}: the closed-form next-use supports "
                "triangular nests with unit steps only; use the dense "
                "or stream engine"
            )
        for ri in range(nt.tables.n_refs):
            sig = _ref_sig_digest(nt, ri)
            ks = _kernels_for(nt, ri, sig)
            kernels.append((k, ri, ks, sig))
    return trace, kernels


def _bucket_rows(trace: ProgramTrace, rows) -> "_collections.OrderedDict":
    """Group _program_kernels rows into cross-ref fusion buckets:
    (nest index, signature digest) -> [(row index, ref index), ...].

    Refs in one bucket classify under ONE compiled kernel and share
    one draw plan (the signature pins level/structure, so highs and
    the target sample count match), which is exactly what lets their
    buffers stack along a leading ref axis. Ordered by first
    appearance: per-ref seeds (cfg.seed * 1000003 + row index) and the
    result order are those of the serial path."""
    buckets: "_collections.OrderedDict" = _collections.OrderedDict()
    for idx, (k, ri, ks, sig) in enumerate(rows):
        buckets.setdefault((k, sig), []).append((idx, ri))
    return buckets


def _bucket_rows_multi(job_plans) -> "_collections.OrderedDict":
    """Cross-REQUEST extension of _bucket_rows: group the rows of
    several (trace, rows) program plans into UNION kernel-signature
    buckets, sig -> [(job index, row index, nest index, ref index)].

    Keys by signature digest alone: across programs a nest index means
    nothing, and the digest already captures everything a compiled
    kernel bakes in as structure — every numeric difference between
    member nests (trips, coeffs, geometry values) rides the per-row
    (highs, vals) operands of the fused_multi kernel. Unlike a
    single-program bucket, members need NOT share a draw plan: each is
    planned with its own nest/config. Ordered by first appearance, so
    per-member seeds (cfg.seed * 1000003 + row index within the
    member's OWN program) and per-job result order stay exactly those
    of each job's solo run."""
    buckets: "_collections.OrderedDict" = _collections.OrderedDict()
    for j, (trace, rows) in enumerate(job_plans):
        for idx, (k, ri, ks, sig) in enumerate(rows):
            buckets.setdefault(sig, []).append((j, idx, k, ri))
    return buckets


def _stack_vals(vals_list):
    """Stack the vals overlays of signature-equal rows along a new
    leading axis for the fused_multi kernel. Equal signatures
    guarantee equal pytree structure and leaf shapes (_kernel_sig:
    every concrete value the traced code reads outside vals is in the
    signature), so the stack is always well-formed."""
    return jax.tree_util.tree_map(
        lambda *xs: jnp.stack([jnp.asarray(x) for x in xs]), *vals_list
    )


# Max batch-sized chunks folded into ONE fused host-path dispatch
# (scanned on device). Bounds the stacked buffer at
# R * _FUSED_HOST_CHUNKS * batch int64 slots per dispatch while still
# collapsing the host path's per-chunk dispatch storm; the device-draw
# path ships its whole bucketed buffer in one dispatch regardless,
# exactly as the per-ref scan form always has.
_FUSED_HOST_CHUNKS = 8


def _host_fuse_plan(s: int, batch: int) -> tuple[int, int]:
    """(chunks per fused host dispatch, dispatch count) for a ref with
    s drawn samples: the chunk group grows geometrically (1, 2, 4, ...,
    capped at _FUSED_HOST_CHUNKS) so every model/N lands on a handful
    of compiled (R, group*batch) shapes — the same reasoning as the
    draw buffers' geometric bucketing (draw.py::bucket_size)."""
    n_chunks = -(-s // batch)
    g = 1
    while g < n_chunks and g < _FUSED_HOST_CHUNKS:
        g *= 2
    return g, -(-n_chunks // g)


def warmup(
    program: Program,
    machine: MachineConfig,
    cfg: SamplerConfig | None = None,
    batch: int | None = None,
    capacity: int = DEFAULT_CAPACITY,
) -> None:
    """Compile every per-ref kernel at the exact shapes a subsequent
    sampled_outputs run will use, on dummy batches sized through the
    same pad_keys logic — orders of magnitude cheaper than a full
    warm-up run when the sample count is large (the benchmark's N=4096
    warm-up dropped from ~15 min of re-drawing 275M samples to
    seconds). Only the base `capacity` is compiled: the rare
    capacity-regrow recompile (drain loop in sampled_outputs) lands in
    the subsequent run, a deliberately conservative accounting."""
    cfg = cfg or SamplerConfig()
    _apply_compilation_cache(cfg)
    if batch is None:
        batch = default_batch()
    with telemetry.span("warmup", engine="sampled"):
        _warmup_kernels(program, machine, cfg, batch, capacity)


def _warmup_kernels(program, machine, cfg, batch, capacity) -> None:
    trace, rows = _program_kernels(program, machine)
    if _use_fused(cfg):
        _warmup_fused(trace, rows, cfg, batch, capacity)
        return
    drawn_buckets: set = set()
    for k, ri, ks, sig in rows:
        kernel, kernel_s = ks["plain"], ks["scan"]
        nt = trace.nests[k]
        highs, s = _sample_highs(nt, ri, cfg)
        if s == 0:  # no drawable points (degenerate triangular ref)
            continue
        if _use_device_draw(cfg):
            # compile the scan-fused kernel at the ref's planned
            # (buffer, n_chunks) shape and the draw kernel at its
            # bucket size (rect buckets are shared across refs, so the
            # set dedups; tri kernels are per-ref closures)
            from .draw import _get_tri_kernel, _rect_draw_kernel, plan_draw

            plan = plan_draw(nt, ri, cfg, batch)
            if plan is not None:
                B, tri, s_plan, highs_t, excl, space_box = plan
                if tri:
                    jax.block_until_ready(_get_tri_kernel(
                        nt, ri, highs_t, excl, B
                    )(jax.random.key(0), jnp.int64(s_plan)))
                elif B not in drawn_buckets:
                    drawn_buckets.add(B)
                    jax.block_until_ready(_rect_draw_kernel(B)(
                        jax.random.key(0), jnp.int64(space_box),
                        jnp.int64(s_plan),
                    ))
                dummy = _place(jnp.zeros(B, dtype=jnp.int64))
                jax.block_until_ready(kernel_s(
                    dummy, dummy < 0, _pad_highs(highs), nt.vals,
                    np.int64(ri), capacity, B // batch,
                ))
                continue
            # over-budget refs take the host path below
        keys = np.zeros(min(s, batch), dtype=np.int64)
        chunk, n_valid = pad_keys(
            keys, 1, total=batch if s > batch else None
        )
        # _place, like the run's chunk commit: inside a replica scope
        # a committed input is part of the jit cache key, so an
        # unplaced warmup would compile a signature the routed run
        # cannot reuse
        jax.block_until_ready(
            kernel(
                _place(chunk), n_valid, _pad_highs(highs), nt.vals,
                np.int64(ri), capacity,
            )
        )


def _warmup_fused(trace, rows, cfg, batch, capacity) -> None:
    """Warm the fused path at the exact per-bucket stacked shapes a
    subsequent fused run dispatches: (R, B) with the device draw's
    bucketed buffer, (R, group*batch) with the host draw's chunk
    groups. Pinned by tests/test_compile_sharing.py: a post-warmup
    fused run adds zero jit cache entries."""
    from .draw import (
        _get_tri_kernel,
        _rect_draw_kernel,
        _rect_draw_kernel_batch,
        plan_draw,
    )

    drawn_buckets: set = set()
    for (k, sig), members in _bucket_rows(trace, rows).items():
        nt = trace.nests[k]
        ri0 = members[0][1]
        fused = rows[members[0][0]][2]["fused"]
        highs, s = _sample_highs(nt, ri0, cfg)
        if s == 0:  # no drawable points (degenerate triangular ref)
            continue
        R = len(members)
        ph = _pad_highs(highs)
        rx_R = jnp.asarray([ri for _, ri in members], dtype=jnp.int64)
        if _use_device_draw(cfg):
            plan = plan_draw(nt, ri0, cfg, batch)
            if plan is not None:
                B, tri, s_plan, highs_t, excl, space_box = plan
                if tri:
                    jax.block_until_ready(_get_tri_kernel(
                        nt, ri0, highs_t, excl, B
                    )(jax.random.key(0), jnp.int64(s_plan)))
                elif R == 1 and B not in drawn_buckets:
                    # singleton buckets draw through the per-ref kernel
                    drawn_buckets.add(B)
                    jax.block_until_ready(_rect_draw_kernel(B)(
                        jax.random.key(0), jnp.int64(space_box),
                        jnp.int64(s_plan),
                    ))
                elif R > 1 and (R, B) not in drawn_buckets:
                    drawn_buckets.add((R, B))
                    jax.block_until_ready(_rect_draw_kernel_batch(R, B)(
                        jnp.stack([jax.random.key(i) for i in range(R)]),
                        jnp.int64(space_box), jnp.int64(s_plan),
                    ))
                dummy = _place(jnp.zeros((R, B), dtype=jnp.int64))
                jax.block_until_ready(fused(
                    dummy, dummy < 0, ph, nt.vals, rx_R, capacity,
                    B // batch,
                ))
                continue
            # over-budget buckets take the host path below
        g, _ = _host_fuse_plan(s, batch)
        # _place matches the run's make_inputs commit (replica scope)
        dummy = _place(jnp.zeros((R, g * batch), dtype=jnp.int64))
        msk = _place(jnp.zeros((R, g * batch), dtype=bool))
        jax.block_until_ready(fused(
            dummy, msk, ph, nt.vals, rx_R, capacity, g
        ))


# Bump whenever the engine's RESULT semantics change (packing, share
# thresholds, histogram encoding, seeded sample stream, ...): the
# version is folded into every checkpoint tag, so stale files from an
# older engine are recomputed instead of silently reused — the tag
# otherwise only captures inputs. v3: flat-space key drawing changed
# the per-seed sample sets. v4: device-side threefry drawing
# (cfg.device_draw) changed them again. v5: the 2^46 device-draw bias
# cap (draw.py::_DEVICE_DRAW_MAX_SPACE) reroutes huge-box refs to the
# host stream, changing their per-seed sample sets under device_draw.
# v6: geometric draw-buffer bucketing (draw.py::bucket_size) changed
# the device-drawn buffer sizes and with them the per-seed sample sets.
_CHECKPOINT_SCHEMA = 6


def _use_device_draw(cfg) -> bool:
    """Resolve cfg.device_draw (None = auto): device-side drawing on
    accelerator backends, host numpy on CPU — each backend's measured
    best (see SamplerConfig.device_draw)."""
    if cfg.device_draw is None:
        return jax.default_backend() != "cpu"
    return cfg.device_draw


def _use_fused(cfg) -> bool:
    """Resolve cfg.fuse_refs (None = auto, same shape as device_draw):
    cross-ref fused dispatch on accelerator backends, where every
    dispatch pays a round trip worth amortizing; the serial per-ref
    loop on CPU, where dispatch is cheap and the vmap-safe sorted
    merge costs more than the dispatches it saves (see
    SamplerConfig.fuse_refs)."""
    if cfg.fuse_refs is None:
        return jax.default_backend() != "cpu"
    return cfg.fuse_refs


_KERNEL_BACKENDS = ("auto", "xla", "pallas", "native")


def _resolve_kernel_backend(cfg, raw_noshare: bool = False) -> str:
    """Resolve cfg.kernel_backend (None = "auto") to a concrete
    backend name: "xla", "pallas", or "native".

    The contract (SamplerConfig.kernel_backend): every backend folds
    to bit-identical PRIStates/MRCs, so this is a pure speed knob and
    stays OUT of the request fingerprint. Resolution:

    - v2 raw-noshare runs force "xla": the hist backends pow2-bin
      noshare on accumulation by construction (a warn_once fires if a
      different backend was explicitly requested);
    - "auto" resolves to "xla". Not to "native", deliberately: the
      hist backends ladder-bin noshare reuse in the per-ref RESULT
      objects (folded PRIStates/MRCs stay bit-identical, but the raw
      SampledRefResults are a different exact representation), and
      several standing contracts compare raw results across code
      paths that would otherwise resolve differently (fused-vs-serial
      in tests/test_fusion.py, batched-vs-solo in
      tests/test_batching.py, checkpoint replay). "native" is a
      per-call opt-in (bench kernel_roofline, --kernel-backend, the
      service request field) where the caller consumes folded states;
    - explicit "native" off-CPU or without the library falls back to
      "xla" with a warn_once (never an error: the knob must stay a
      speed knob);
    - explicit "pallas"/"xla" are honored as-is ("pallas" runs in
      interpret mode on CPU).
    """
    choice = cfg.kernel_backend if cfg.kernel_backend is not None else "auto"
    if choice not in _KERNEL_BACKENDS:
        raise ValueError(
            f"kernel_backend must be one of {_KERNEL_BACKENDS}, "
            f"got {choice!r}"
        )
    if raw_noshare:
        if choice not in ("auto", "xla"):
            telemetry.warn_once(
                "kernel_backend_v2",
                f"kernel_backend={choice!r} ignored: v2 raw-noshare "
                "runs require the xla kernels (hist backends pow2-bin "
                "noshare)",
            )
        return "xla"
    on_cpu = jax.default_backend() == "cpu"
    if choice == "auto":
        return "xla"
    if choice == "native":
        from .. import native

        if not on_cpu or not native.available():
            telemetry.warn_once(
                "kernel_backend_native",
                "kernel_backend='native' unavailable "
                + ("off the CPU backend" if not on_cpu
                   else "(shared library failed to build)")
                + "; falling back to xla",
            )
            return "xla"
        return "native"
    return choice


def _checkpoint_tagger(program, machine, cfg, batch):
    """(idx, name) -> checkpoint tag; the program-structure hash (loops,
    refs, thresholds — same-named programs can differ structurally,
    e.g. gemm's share_threshold_variant) is computed once per run.

    The device draw's sample stream depends on the buffer bucketing
    (B = bucket_size(m, batch)), so the batch joins the tag on that
    path — a resume under a different batch (or another backend's
    default_batch) must recompute, not mix two streams under one
    seed. The host numpy stream is batch-independent and keeps its
    batch-free tag."""
    import hashlib

    struct = hashlib.sha256(repr(program).encode()).hexdigest()[:16]
    dev = _use_device_draw(cfg)
    prefix = (
        f"v{_CHECKPOINT_SCHEMA}|{program.name}/{struct}|{machine.thread_num},"
        f"{machine.chunk_size},{machine.ds},{machine.cls}|{cfg.ratio},"
        f"{cfg.seed},{cfg.exclude_last_iteration},{dev}"
        + (f",b{batch}" if dev else "")
    )
    return lambda idx, name: f"{prefix}|{idx}|{name}"


def _checkpoint_load(path: str, tag: str):
    import json
    import os

    if not os.path.exists(path):
        return None
    try:
        with open(path) as f:
            d = json.load(f)
        if d.get("tag") != tag:
            return None
        return SampledRefResult(
            name=d["name"],
            noshare={int(k): v for k, v in d["noshare"].items()},
            share={
                int(r): {int(k): v for k, v in h.items()}
                for r, h in d["share"].items()
            },
            cold=d["cold"],
            n_samples=d["n_samples"],
        )
    except Exception:
        return None  # unreadable/foreign/odd-shaped file: recompute


def _checkpoint_store(path: str, tag: str, r: SampledRefResult) -> None:
    import json
    import os

    tmp = path + ".tmp"
    with open(tmp, "w") as f:
        json.dump({
            "tag": tag, "name": r.name, "noshare": r.noshare,
            "share": r.share, "cold": r.cold, "n_samples": r.n_samples,
        }, f)
    os.replace(tmp, path)


def sampled_outputs(
    program: Program,
    machine: MachineConfig,
    cfg: SamplerConfig,
    batch: int | None = None,
    capacity: int = DEFAULT_CAPACITY,
    checkpoint_dir: str | None = None,
    raw_noshare: bool = False,
):
    """Run the sampled engine; one SampledRefResult per reference.

    `checkpoint_dir` persists each tracked reference's finished result
    (atomic JSON per ref, keyed by a program/machine/sampler-config
    tag) and resumes an interrupted run by skipping refs whose
    checkpoint matches — a long multi-hour N run survives preemption
    at per-ref granularity. The reference framework has no
    checkpointing (its only persisted artifact is the final MRC,
    pluss_utils.h:885-913); this goes beyond parity by design.

    cfg.fuse_refs (auto: ON off-CPU) routes through the cross-ref
    fused runner: refs sharing a kernel-signature bucket are stacked and
    classified by one vmapped dispatch per bucket, and dispatches
    stream through a depth-bounded async pipeline
    (cfg.pipeline_depth). Both runners produce bit-identical results
    — the fused path is a pure dispatch/overlap optimization, and
    fuse_refs=False keeps the serial per-ref loop as the parity
    oracle.

    cfg.kernel_backend selects the classify+histogram kernel
    implementation (_resolve_kernel_backend): "pallas" rides the
    fused runner with the on-chip accumulation kernel
    (ops/pallas_sampled.py, interpret mode on CPU), "native" rides
    the serial runner with the C++ batched classify+histogram entry
    (native.classify_reduce), "xla" (and v2 raw-noshare runs, which
    force it via `raw_noshare`) keeps the jit kernels. All backends
    fold bit-identically — the knob never changes the MRC.
    """
    import os

    if batch is None:
        batch = default_batch()
    backend = _resolve_kernel_backend(cfg, raw_noshare)
    telemetry.event("kernel_backend", backend=backend)
    trace, rows = _program_kernels(program, machine)
    tag_of = None
    if checkpoint_dir is not None:
        os.makedirs(checkpoint_dir, exist_ok=True)
        tag_of = _checkpoint_tagger(program, machine, cfg, batch)
    if backend == "pallas":
        return _sampled_outputs_fused(
            trace, rows, cfg, batch, capacity, checkpoint_dir, tag_of,
            kernel_form="hist",
        )
    if backend != "native" and _use_fused(cfg):
        return _sampled_outputs_fused(
            trace, rows, cfg, batch, capacity, checkpoint_dir, tag_of
        )
    return _sampled_outputs_serial(
        trace, rows, cfg, batch, capacity, checkpoint_dir, tag_of,
        native=backend == "native",
    )


def _sampled_outputs_serial(
    trace, rows, cfg, batch, capacity, checkpoint_dir, tag_of,
    native: bool = False,
):
    """The legacy per-ref loop (cfg.fuse_refs=False): one dispatch
    chain per ref, pipelined only within a ref's own host chunks. Kept
    verbatim as the fused runner's bit-identity oracle.

    `native` (kernel_backend="native", CPU only) keeps the classify in
    XLA (the "raw" kernel form: packed keys + found mask, no on-device
    unique reduction) and replaces the sort-based reduction with ONE
    vectorized C++ pass per chunk (native.classify_reduce): pow2 bins
    accumulate in a flat per-ref array, share and sub-1 noshare
    samples collect in an exact residual hash map — on a host core the
    XLA sort dominates the chunk wall, so this is the CPU fast path.
    Telemetry: dispatches_native counts chunk dispatches and
    native_chunk_plan the planned per-ref chunk counts, audited by
    tools/check_dispatch_stats.py (dispatches_native <= plan)."""
    import os

    depth = max(1, cfg.pipeline_depth)
    results = []
    for idx, (k, ri, ks, sig) in enumerate(rows):
        kernel, kernel_s = ks["plain"], ks["scan"]
        nt = trace.nests[k]
        name = nt.tables.ref_names[ri]
        ck_path = ck_tag = None
        if checkpoint_dir is not None:
            ck_tag = tag_of(idx, name)
            ck_path = os.path.join(checkpoint_dir, f"ref_{idx:03d}.json")
            prior = _checkpoint_load(ck_path, ck_tag)
            if prior is not None:
                results.append(prior)
                continue
        ref_span = telemetry.span("ref", engine="sampled", ref=name)
        ref_span.__enter__()
        # Device path first: draw + dedup + thin on the device, then
        # ONE scan-fused dispatch over the whole buffer with on-device
        # chunk merging (sampler/draw.py + _build_ref_kernel_scan —
        # the host<->device link can be a network tunnel at ~70 MB/s
        # with ~70 ms round trips, while the device-side compute for a
        # batch is ~0.1 ms). Falls back to the host numpy draw when
        # disabled or when the ref's buffer would exceed the device
        # budget.
        drawn = None
        if _use_device_draw(cfg):
            from .draw import draw_sample_keys_device

            with telemetry.span("draw", where="device"):
                drawn = draw_sample_keys_device(
                    nt, ri, cfg, seed=cfg.seed * 1000003 + idx,
                    batch=batch,
                )
        if drawn is None:
            # device drawing disabled, over the device budget, or s==0
            with telemetry.span("draw", where="host"):
                keys_all, highs = draw_sample_keys(
                    nt, ri, cfg, seed=cfg.seed * 1000003 + idx
                )
            n_samples = len(keys_all)
        else:
            dev_keys, dev_mask, n_samples, highs = drawn
        noshare: dict[int, float] = {}
        share: dict[int, dict[int, float]] = {}
        cold = 0.0
        cap = capacity
        pending: list = []  # pipelined async dispatches (depth-bounded)

        def drain(entry):
            nonlocal cold, cap
            out, redo, dispatch_cap = entry
            with telemetry.span("fetch"):
                keys, counts, n_unique, c = telemetry.record_fetch(
                    jax.device_get(out)
                )
            while int(n_unique) > dispatch_cap:
                # rare: more distinct (reuse, class) pairs than slots —
                # recompile with a larger capacity rather than abort
                dispatch_cap = max(dispatch_cap * 4, int(n_unique))
                cap = max(cap, dispatch_cap)
                telemetry.count("capacity_regrows")
                with telemetry.span("fetch", regrow=True):
                    keys, counts, n_unique, c = telemetry.record_fetch(
                        jax.device_get(redo(dispatch_cap))
                    )
            cold += float(c)
            with telemetry.span("merge"):
                decode_pairs(keys, counts, noshare, share)

        ph = _pad_highs(highs)
        rxv = np.int64(ri)
        if native:
            from .. import native as native_mod

            kernel_r = ks["raw"]
            bins = np.zeros(native_mod._NOSHARE_SLOTS, dtype=np.int64)
            if drawn is not None:
                n_chunks = dev_keys.shape[0] // batch
                chunks = (
                    (dev_keys[c * batch:(c + 1) * batch],
                     dev_mask[c * batch:(c + 1) * batch])
                    for c in range(n_chunks)
                )
            else:
                n_chunks = -(-n_samples // batch)
                chunks = (
                    (_place(pad_keys(
                        keys_all[s0:s0 + batch], 1,
                        total=batch if n_samples > batch else None,
                    )[0]), None)
                    for s0 in range(0, n_samples, batch)
                )
                valids = [
                    min(batch, n_samples - s0)
                    for s0 in range(0, n_samples, batch)
                ]
            telemetry.count("native_chunk_plan", n_chunks)
            for ci, (ck, cm) in enumerate(chunks):
                telemetry.count("dispatches")
                telemetry.count("dispatches_native")
                with telemetry.span("dispatch", form="native"):
                    packed, found = kernel_r(ck, ph, nt.vals, rxv)
                with telemetry.span("fetch"):
                    packed, found, cm = telemetry.record_fetch(
                        jax.device_get((packed, found, cm))
                    )
                if cm is None:
                    # host chunk: padding sits past the valid prefix
                    nv = valids[ci]
                    packed, found = packed[:nv], found[:nv]
                with telemetry.span("merge", where="native"):
                    pk, pc, cap, regrows = native_mod.classify_reduce(
                        packed, found, bins, mask=cm, share_cap=cap
                    )
                    if regrows:
                        telemetry.count("capacity_regrows", regrows)
                    decode_pairs(pk, pc, noshare, share)
            # pow2 bins -> {2^e: count}: fold_results' hist_update
            # re-bins to pow2_floor(2^e) == 2^e, so the folded state
            # is bit-identical to the raw-key stream's
            for e in np.nonzero(bins[:native_mod.N_NOSHARE_BINS])[0]:
                key = 1 << int(e)
                noshare[key] = noshare.get(key, 0.0) + float(bins[e])
            cold += float(bins[native_mod.N_NOSHARE_BINS])
        elif drawn is not None:
            n_chunks = dev_keys.shape[0] // batch

            def redo(c2, dk=dev_keys, dm=dev_mask, nc=n_chunks, ph=ph,
                     nv=nt.vals, rxv=rxv):
                telemetry.count("dispatches")
                return kernel_s(dk, dm, ph, nv, rxv, c2, nc)

            with telemetry.span("dispatch", form="scan"):
                pending.append((redo(cap), redo, cap))
        else:
            for s0 in range(0, n_samples, batch):
                chunk, n_valid = pad_keys(
                    keys_all[s0 : s0 + batch], 1,
                    total=batch if n_samples > batch else None,
                )
                chunk = _place(chunk)

                def redo(c2, chunk=chunk, n_valid=n_valid, ph=ph,
                         nv=nt.vals, rxv=rxv):
                    telemetry.count("dispatches")
                    return kernel(chunk, n_valid, ph, nv, rxv, c2)

                with telemetry.span("dispatch", form="chunk"):
                    pending.append((redo(cap), redo, cap))
                if len(pending) >= depth:
                    # the depth bound forces a synchronous drain of the
                    # oldest in-flight dispatch before the next one
                    telemetry.count("pipeline_stalls")
                    drain(pending.pop(0))
        for entry in pending:
            drain(entry)
        ref_span.__exit__(None, None, None)
        result = SampledRefResult(
            name=name, noshare=noshare, share=share, cold=cold,
            n_samples=n_samples,
        )
        if ck_path is not None:
            _checkpoint_store(ck_path, ck_tag, result)
        results.append(result)
    return results


def _sampled_outputs_fused(
    trace, rows, cfg, batch, capacity, checkpoint_dir, tag_of,
    kernel_form: str = "fused",
):
    """Cross-ref fused, pipelined form of the sampled engine.

    Structure (cfg.fuse_refs on — the off-CPU default):

    - rows are grouped into kernel-signature buckets (_bucket_rows);
      each bucket's refs draw their per-ref sample streams (unchanged
      seeds: cfg.seed * 1000003 + row index), stack them along a
      leading ref axis, and classify in ONE vmapped scan-fused
      dispatch (_build_ref_kernel_fused) instead of one chain per ref;
    - dispatches enter a GLOBAL depth-bounded async pipeline: outputs
      start their device->host copy immediately (copy_to_host_async),
      and while they transfer the next bucket draws and dispatches.
      Only when the depth bound (cfg.pipeline_depth) is hit does the
      host block on the oldest entry (counted as pipeline_stalls);
    - the capacity-regrow drain loop runs per bucket dispatch — one
      regrown re-dispatch covers every member, so capacity_regrows
      counts once per bucket, not once per ref;
    - already-checkpointed refs are masked out of their bucket's stack
      (the bucket dispatches with fewer rows); refs whose device draw
      falls back to the host stream form their own stacked sub-group,
      exactly mirroring the serial path's per-ref fallback.

    Results are bit-identical to _sampled_outputs_serial: same sample
    streams, and every reduction along both paths is exact.

    Telemetry: dispatches_fused / refs_fused counters, pipeline_stalls,
    and end-of-run gauges ref_buckets, expected_chunks (max dispatches
    any bucket planned), refs_per_dispatch, pipeline_overlap_s (summed
    in-flight time the host spent off the critical path) —
    tools/check_dispatch_stats.py audits `dispatches` against
    ref_buckets * expected_chunks (+ regrows).

    kernel_form="hist" (kernel_backend="pallas") swaps each bucket's
    fused XLA kernel for the Pallas on-chip classify+accumulate kernel
    (ops/pallas_sampled.py, interpret mode on CPU). Its outputs extend
    the fused form with a fifth per-ref pow2 noshare histogram; share
    and sub-1 noshare samples still arrive as exact pairs, so the
    drain/regrow contract and bit-identity both carry over unchanged.
    """
    import os
    import time

    depth = max(1, cfg.pipeline_depth)
    results: dict[int, SampledRefResult] = {}
    pending: list = []
    cap = capacity
    overlap_s = 0.0
    n_buckets = 0
    max_bucket_dispatches = 0
    n_fused = 0
    n_refs_fused = 0

    def finalize(idx, name, acc):
        result = SampledRefResult(
            name=name, noshare=acc["noshare"], share=acc["share"],
            cold=acc["cold"], n_samples=acc["n_samples"],
        )
        if checkpoint_dir is not None:
            _checkpoint_store(
                os.path.join(checkpoint_dir, f"ref_{idx:03d}.json"),
                tag_of(idx, name), result,
            )
        results[idx] = result

    def drain(entry):
        nonlocal cap, overlap_s
        # time this dispatch spent in flight while the host worked on
        # other buckets — the overlap the pipeline exists to buy
        overlap_s += max(0.0, time.perf_counter() - entry["t0"])
        dispatch_cap = entry["cap"]
        with telemetry.span("fetch", fused=True):
            mk, mc, max_nu, cold, *rest = telemetry.record_fetch(
                jax.device_get(entry["out"])
            )
        while int(max_nu.max()) > dispatch_cap:
            # rare: some member saw more distinct (reuse, class) pairs
            # than slots — regrow ONCE for the whole bucket dispatch
            dispatch_cap = max(dispatch_cap * 4, int(max_nu.max()))
            cap = max(cap, dispatch_cap)
            telemetry.count("capacity_regrows")
            with telemetry.span("fetch", fused=True, regrow=True):
                mk, mc, max_nu, cold, *rest = telemetry.record_fetch(
                    jax.device_get(entry["redo"](dispatch_cap))
                )
        # the hist kernel form returns a fifth output: per-ref pow2
        # noshare histograms accumulated on-chip
        nh = rest[0] if rest else None
        with telemetry.span("merge"):
            for j, (idx, name, acc) in enumerate(entry["members"]):
                acc["cold"] += float(cold[j])
                decode_pairs(mk[j], mc[j], acc["noshare"], acc["share"])
                if nh is not None:
                    # {2^e: count}: hist_update's pow2_floor(2^e) is
                    # 2^e, so the fold is bit-identical to raw keys
                    ns = acc["noshare"]
                    for e in np.nonzero(nh[j])[0]:
                        key = 1 << int(e)
                        ns[key] = ns.get(key, 0.0) + float(nh[j][e])
                acc["left"] -= 1
                if acc["left"] == 0:
                    finalize(idx, name, acc)

    def dispatch_group(fused, mem, make_inputs, ph, nv, rx_R, n_chunks):
        nonlocal n_fused, n_refs_fused

        def redo(c2):
            keys_RB, mask_RB = make_inputs()
            telemetry.count("dispatches")
            telemetry.count("dispatches_fused")
            return fused(keys_RB, mask_RB, ph, nv, rx_R, c2, n_chunks)

        with telemetry.span("dispatch", form="fused", refs=len(mem)):
            out = redo(cap)
        for arr in out:
            # start the device->host transfer now, so it overlaps the
            # next bucket's draw + dispatch; the drain's device_get
            # then just waits instead of initiating
            try:
                arr.copy_to_host_async()
            except AttributeError:
                pass
        n_fused += 1
        n_refs_fused += len(mem)
        pending.append({
            "out": out, "redo": redo, "cap": cap, "members": mem,
            "t0": time.perf_counter(),
        })
        while len(pending) >= depth:
            telemetry.count("pipeline_stalls")
            drain(pending.pop(0))

    for (k, sig), members_all in _bucket_rows(trace, rows).items():
        nt = trace.nests[k]
        names = {idx: nt.tables.ref_names[ri] for idx, ri in members_all}
        members = []
        for idx, ri in members_all:
            if checkpoint_dir is not None:
                prior = _checkpoint_load(
                    os.path.join(checkpoint_dir, f"ref_{idx:03d}.json"),
                    tag_of(idx, names[idx]),
                )
                if prior is not None:
                    # resumed ref: masked out of the bucket's stack —
                    # the remaining members still dispatch fused
                    results[idx] = prior
                    continue
            members.append((idx, ri))
        if not members:
            continue
        ri0 = members[0][1]
        highs, s = _sample_highs(nt, ri0, cfg)
        accs = {
            idx: {"noshare": {}, "share": {}, "cold": 0.0,
                  "n_samples": 0, "left": 0}
            for idx, _ in members
        }
        if s == 0:  # no drawable points (degenerate triangular ref)
            for idx, _ in members:
                finalize(idx, names[idx], accs[idx])
            continue
        n_buckets += 1
        bspan = telemetry.span(
            "bucket", engine="sampled", nest=k,
            refs=",".join(names[idx] for idx, _ in members),
        )
        bspan.__enter__()
        drawn = None
        if _use_device_draw(cfg):
            from .draw import draw_bucket_keys_device

            with telemetry.span("draw", where="device"):
                drawn = draw_bucket_keys_device(
                    nt, [ri for _, ri in members], cfg,
                    [cfg.seed * 1000003 + idx for idx, _ in members],
                    batch,
                )
        host_members = []
        dev_groups: dict[int, list] = {}
        if drawn is None:
            host_members = members
        else:
            for (idx, ri), d in zip(members, drawn):
                if d is None:
                    # over the device budget: this member joins the
                    # host stream, exactly like the serial fallback
                    host_members.append((idx, ri))
                    continue
                sk, chosen, s_m, _hi = d
                accs[idx]["n_samples"] = s_m
                # retries can grow one member's buffer past the
                # bucket's planned B; equal-B members stack together
                dev_groups.setdefault(int(sk.shape[0]), []).append(
                    (idx, ri, sk, chosen)
                )
        ph = _pad_highs(highs)
        if kernel_form == "hist":
            from ..ops.pallas_sampled import hist_kernel_for

            fused = hist_kernel_for(
                nt, members[0][1], sig,
                interpret=jax.default_backend() == "cpu",
            )
        else:
            fused = rows[members[0][0]][2][kernel_form]
        bucket_dispatches = 0
        for B, grp in dev_groups.items():
            rx_R = jnp.asarray([ri for _, ri, _, _ in grp], jnp.int64)
            mem = []
            for idx, _, _, _ in grp:
                accs[idx]["left"] += 1
                mem.append((idx, names[idx], accs[idx]))

            def make_inputs(grp=grp):
                return (
                    jnp.stack([sk for _, _, sk, _ in grp]),
                    jnp.stack([ch for _, _, _, ch in grp]),
                )

            dispatch_group(
                fused, mem, make_inputs, ph, nt.vals, rx_R, B // batch
            )
            bucket_dispatches += 1
        if host_members:
            with telemetry.span("draw", where="host"):
                keys_list = []
                for idx, ri in host_members:
                    keys_all, _hi = draw_sample_keys(
                        nt, ri, cfg, seed=cfg.seed * 1000003 + idx
                    )
                    accs[idx]["n_samples"] = len(keys_all)
                    keys_list.append(keys_all)
            n_samples = len(keys_list[0])
            g, n_groups = _host_fuse_plan(n_samples, batch)
            span_len = g * batch
            rx_R = jnp.asarray([ri for _, ri in host_members], jnp.int64)
            mem = []
            for idx, _ in host_members:
                accs[idx]["left"] += n_groups
                mem.append((idx, names[idx], accs[idx]))
            for gi in range(n_groups):
                lo = gi * span_len

                def make_inputs(lo=lo, kl=keys_list, span_len=span_len):
                    buf = np.empty((len(kl), span_len), dtype=np.int64)
                    msk = np.zeros((len(kl), span_len), dtype=bool)
                    for j, ka in enumerate(kl):
                        seg = ka[lo:lo + span_len]
                        buf[j, :len(seg)] = seg
                        buf[j, len(seg):] = ka[0]  # decodable padding
                        msk[j, :len(seg)] = True
                    return _place(buf), _place(msk)

                dispatch_group(
                    fused, mem, make_inputs, ph, nt.vals, rx_R, g
                )
                bucket_dispatches += 1
        bspan.__exit__(None, None, None)
        max_bucket_dispatches = max(max_bucket_dispatches,
                                    bucket_dispatches)
    while pending:
        drain(pending.pop(0))
    telemetry.gauge("fuse_refs", 1)
    telemetry.gauge("pipeline_depth", depth)
    telemetry.gauge("ref_buckets", n_buckets)
    telemetry.gauge("expected_chunks", max_bucket_dispatches)
    telemetry.gauge("pipeline_overlap_s", overlap_s)
    if n_fused:
        telemetry.gauge("refs_per_dispatch", n_refs_fused / n_fused)
    return [results[idx] for idx in range(len(rows))]


def results_from_samples(
    program: Program,
    machine: MachineConfig,
    samples_by_ref: dict,
) -> list[SampledRefResult]:
    """Explicit-sample surface: classify caller-provided sample tuples.

    `samples_by_ref` maps tracked reference name -> (S, depth) array of
    normalized iteration tuples; each provided ref is classified with
    the same closed-form kernels sampled_outputs uses and folded into a
    SampledRefResult. Refs not present in the mapping are skipped.

    This exists for external anchoring: the suite determinizes the
    reference r10 binary's RNG, replicates its draw loop in Python, and
    hands the *identical* sample sets to both sides, so the comparison
    isolates the reuse/distribute model from sampling noise
    (tests/test_reference_diff.py). Requires tracked ref names to be
    unique across nests (true for every registered model).
    """
    trace, kernels = _program_kernels(program, machine)
    seen: set[str] = set()
    results = []
    for k, ri, _, _ in kernels:
        nt = trace.nests[k]
        name = nt.tables.ref_names[ri]
        if name not in samples_by_ref:
            continue
        if name in seen:
            raise ValueError(
                f"tracked ref name {name!r} is not unique across nests; "
                "explicit sample routing would be ambiguous"
            )
        seen.add(name)
        samples = jnp.asarray(np.asarray(samples_by_ref[name], np.int64))
        packed, _, _, found = classify_samples(nt, ri, samples)
        packed, found = np.asarray(packed), np.asarray(found)
        keys, counts = np.unique(packed[found], return_counts=True)
        noshare: dict[int, float] = {}
        share: dict[int, dict[int, float]] = {}
        decode_pairs(keys, counts, noshare, share)
        results.append(SampledRefResult(
            name=name, noshare=noshare, share=share,
            cold=float((~found).sum()), n_samples=len(samples),
        ))
    missing = set(samples_by_ref) - seen
    if missing:
        raise ValueError(f"unknown tracked refs: {sorted(missing)}")
    return results


def fold_results(
    results: list[SampledRefResult], thread_num: int, v2: bool = False
) -> PRIState:
    """Per-ref sampled results -> PRIState in runtime-v1 form (noshare
    pow2-binned on insertion, share raw), all counts attributed to
    simulated thread 0 — the distribute/print stages only ever consume
    thread-merged histograms (pluss_utils.h:1013-1022, :1042-1058), and
    the r10 variant likewise keeps per-ref (not per-thread) histograms.
    v2=True keeps noshare keys raw (pluss_utils_v2.h:915-918)."""
    from ..runtime.hist import hist_update

    state = PRIState(thread_num, bin_noshare=not v2)
    for r in results:
        for ri_val, cnt in r.noshare.items():
            state.update_noshare(0, ri_val, cnt)
        if r.cold:
            hist_update(state.noshare[0], -1, r.cold, in_log_format=False)
        for ratio, h in r.share.items():
            for ri_val, cnt in h.items():
                state.update_share(0, int(ratio), ri_val, cnt)
    return state


def run_sampled(
    program: Program,
    machine: MachineConfig,
    cfg: SamplerConfig | None = None,
    v2: bool = False,
    **kw,
) -> tuple[PRIState, list[SampledRefResult]]:
    """Sampled engine -> PRIState (see fold_results for the v1 form)."""
    cfg = cfg or SamplerConfig()
    _apply_compilation_cache(cfg)
    with telemetry.span("engine", engine="sampled"):
        # v2 keeps raw noshare keys: force the xla kernels (the hist
        # backends pow2-bin noshare on accumulation)
        results = sampled_outputs(
            program, machine, cfg, raw_noshare=v2, **kw
        )
        with telemetry.span("merge", stage="fold_results"):
            state = fold_results(results, machine.thread_num, v2)
    return state, results


def _stream_order(keys: np.ndarray, seed: int) -> np.ndarray:
    """Deterministic uniform round-assignment order for one ref's
    drawn key set: argsort by a splitmix64 hash of (key, seed).

    draw_sample_keys returns the sample SET sorted by key (np.unique),
    so a plain prefix would be the smallest iteration points — a
    biased subsample no confidence band could speak for. Hashing makes
    every prefix of the reordered stream an (exchangeable) uniform
    subset of the full set, while the UNION over all rounds is the set
    itself — which is all the final-round bit-identity needs (every
    consumer of the folded histograms iterates in sorted-key order,
    and integer-count float accumulation is exact, so processing
    order never reaches the MRC bytes). Pure integer arithmetic:
    replays exactly from (keys, seed) on every platform."""
    x = keys.astype(np.uint64) + np.uint64(seed & ((1 << 64) - 1))
    x = x + np.uint64(0x9E3779B97F4A7C15)
    x = (x ^ (x >> np.uint64(30))) * np.uint64(0xBF58476D1CE4E5B9)
    x = (x ^ (x >> np.uint64(27))) * np.uint64(0x94D049BB133111EB)
    x = x ^ (x >> np.uint64(31))
    # lexsort's final key (the hash) is primary; ties (hash collisions)
    # break on the raw key so the order is total and deterministic
    return np.lexsort((keys, x))


def _classify_slice(nt, kernel, keys: np.ndarray, batch: int, ph,
                    rxv, cap_box: list):
    """Classify one contiguous slice of a ref's (reordered) key stream
    through the plain per-ref kernel, mirroring the serial runner's
    chunk/drain/regrow loop, into a fresh sub-histogram block.
    `cap_box` is the run-wide mutable [capacity] so a regrow sticks
    for later slices. Returns (noshare, share, cold)."""
    noshare: dict[int, float] = {}
    share: dict[int, dict[int, float]] = {}
    cold = 0.0
    n = len(keys)
    for s0 in range(0, n, batch):
        chunk, n_valid = pad_keys(keys[s0:s0 + batch], 1, total=batch)
        chunk = _place(chunk)
        telemetry.count("dispatches")
        with telemetry.span("dispatch", form="progressive"):
            out = kernel(chunk, n_valid, ph, nt.vals, rxv, cap_box[0])
        with telemetry.span("fetch"):
            pk, pc, n_unique, c = telemetry.record_fetch(
                jax.device_get(out)
            )
        while int(n_unique) > cap_box[0]:
            cap_box[0] = max(cap_box[0] * 4, int(n_unique))
            telemetry.count("capacity_regrows")
            with telemetry.span("fetch", regrow=True):
                pk, pc, n_unique, c = telemetry.record_fetch(
                    jax.device_get(kernel(
                        chunk, n_valid, ph, nt.vals, rxv, cap_box[0]
                    ))
                )
        cold += float(c)
        with telemetry.span("merge"):
            decode_pairs(pk, pc, noshare, share)
    return noshare, share, cold


def _sum_blocks(blocks) -> tuple:
    """Union of sub-histogram blocks (sorted-key accumulation; counts
    are integers, so the float sums are exact and order-free)."""
    noshare: dict[int, float] = {}
    share: dict[int, dict[int, float]] = {}
    cold = 0.0
    for ns, sh, c in blocks:
        for k in sorted(ns):
            noshare[k] = noshare.get(k, 0.0) + ns[k]
        for ratio in sorted(sh):
            d = share.setdefault(ratio, {})
            h = sh[ratio]
            for k in sorted(h):
                d[k] = d.get(k, 0.0) + h[k]
        cold += c
    return noshare, share, cold


def run_sampled_progressive(
    program: Program,
    machine: MachineConfig,
    cfg: SamplerConfig | None = None,
    v2: bool = False,
    *,
    batch: int | None = None,
    capacity: int = DEFAULT_CAPACITY,
    on_round=None,
    should_stop=None,
    fault_key=None,
) -> tuple[PRIState, list[SampledRefResult], dict]:
    """Round-based sampled engine with confidence-banded early exit.

    Each ref draws its FULL final-ratio sample stream once, with the
    one-shot host-draw convention (numpy PCG, seed = cfg.seed *
    1000003 + row index) — so the stream IS the one-shot sample set —
    then classifies it across rounds of increasing prefixes of a
    seeded reorder (_stream_order) of that stream. Per round, each
    ref's new slice lands in SUB_BLOCKS_PER_ROUND independent
    sub-histogram blocks; sampler/confidence.py bootstraps an MRC
    band over them between rounds. The run stops early when the band
    width drops under cfg.tolerance, or at a round boundary when
    `should_stop()` (the executor's request-deadline probe) returns
    True; either way the cumulative union state is returned. A run
    that completes the whole schedule folds the exact one-shot sample
    set, so its PRIState/MRC is bit-identical to run_sampled at the
    same (ratio, seed) on the host draw path.

    `on_round(info)` fires after every completed round with the round
    index, cumulative (state, results), interim MRC, and the
    monotone-clamped band width — the hook the serving layer streams
    `partial` frames from. `fault_key` keys the `round_exec` chaos
    site (runtime/faults.py) fired at each round start.

    Returns (state, results, info) with info = {"rounds" completed,
    "rounds_total", "band_width", "converged", "stopped"
    (None | "converged" | "deadline")}.
    """
    from ..runtime import faults
    from . import confidence

    cfg = cfg or SamplerConfig()
    _apply_compilation_cache(cfg)
    if batch is None:
        batch = default_batch()
    if _use_device_draw(cfg):
        # the progressive stream is the HOST draw stream: prefix
        # extension needs the whole set materialized host-side, and
        # the bit-identity anchor is the host-path one-shot run
        telemetry.warn_once(
            "progressive_host_draw",
            "progressive sampling always draws on the host; "
            "device_draw ignored for this run",
        )
    schedule = confidence.resolve_schedule(cfg)
    n_rounds = len(schedule)
    tol = getattr(cfg, "tolerance", None)
    trace, rows = _program_kernels(program, machine)
    cap_box = [capacity]
    refs = []
    with telemetry.span("engine", engine="sampled"):
        for idx, (k, ri, ks, sig) in enumerate(rows):
            nt = trace.nests[k]
            with telemetry.span("draw", where="host"):
                keys_all, highs = draw_sample_keys(
                    nt, ri, cfg, seed=cfg.seed * 1000003 + idx
                )
            order = _stream_order(keys_all, cfg.seed * 1000003 + idx)
            refs.append({
                "nt": nt,
                "name": nt.tables.ref_names[ri],
                "kernel": ks["plain"],
                "keys": keys_all[order],
                "ph": _pad_highs(highs),
                "rxv": np.int64(ri),
                "counts": confidence.round_counts(
                    len(keys_all), schedule
                ),
            })
        blocks: list[list] = [[] for _ in refs]
        state = None
        results: list[SampledRefResult] = []
        band_width = None
        stopped = None
        done = 0
        for r in range(n_rounds):
            # chaos site: one occurrence per (request, round); a
            # latency/hang here overruns the deadline the boundary
            # check below observes
            faults.fire("round_exec", key=fault_key, round=r,
                        model=program.name)
            if r > 0 and should_stop is not None and should_stop():
                stopped = "deadline"
                break
            telemetry.count("progressive_rounds")
            for ref, ref_blocks in zip(refs, blocks):
                lo = 0 if r == 0 else ref["counts"][r - 1]
                hi = ref["counts"][r]
                for a, b in confidence.block_bounds(lo, hi):
                    ref_blocks.append(_classify_slice(
                        ref["nt"], ref["kernel"], ref["keys"][a:b],
                        batch, ref["ph"], ref["rxv"], cap_box,
                    ))
            done = r + 1
            results = [
                SampledRefResult(
                    name=ref["name"], noshare=ns, share=sh, cold=cold,
                    n_samples=ref["counts"][r],
                )
                for ref, (ns, sh, cold) in zip(
                    refs, (_sum_blocks(rb) for rb in blocks)
                )
            ]
            with telemetry.span("merge", stage="fold_results"):
                state = fold_results(results, machine.thread_num, v2)
            raw = confidence.bootstrap_band(
                blocks, machine, seed=cfg.seed, round_idx=r, v2=v2,
            )
            # monotone non-widening by construction: more samples
            # never REPORT more uncertainty than an earlier round did
            band_width = (
                raw if band_width is None else min(band_width, raw)
            )
            early = (
                tol is not None and band_width < tol
                and r < n_rounds - 1
            )
            if on_round is not None:
                on_round({
                    "round": done,
                    "rounds_total": n_rounds,
                    "band_width": band_width,
                    "converged": early or done == n_rounds,
                    "state": state,
                    "results": results,
                    "mrc": confidence.mrc_from_state(state, machine),
                })
            if early:
                stopped = "converged"
                break
    converged = stopped == "converged" or done == n_rounds
    telemetry.gauge("progressive_band_width",
                    band_width if band_width is not None else -1.0)
    if state is None:
        # should_stop before any round completed — nothing to return;
        # the caller treats this like any engine failure
        raise RuntimeError(
            "progressive run stopped before its first round completed"
        )
    return state, results, {
        "rounds": done,
        "rounds_total": n_rounds,
        "band_width": band_width,
        "converged": converged,
        "stopped": stopped,
    }


def sampled_outputs_multi(
    jobs, batch: int | None = None, capacity: int = DEFAULT_CAPACITY
) -> list[list[SampledRefResult]]:
    """Cross-REQUEST fused runner: several (program, machine, cfg) jobs
    share one dispatch plan.

    The engine half of the service's continuous batching
    (service/executor.py::BatchScheduler): rows from every job are
    planned into the UNION of kernel-signature buckets
    (_bucket_rows_multi) and each bucket issues stacked vmapped
    dispatches (the fused_multi kernel) whose rows mix members from all
    jobs, padded to the dispatch's key-buffer shape with masked — hence
    merge-inert — slots. Member exactness is preserved end to end:

    - sample streams: each member draws with its OWN seed
      (cfg.seed * 1000003 + its row index in its own program), its own
      highs and target count — the same streams its solo run uses.
      Device rows are bit-identical by the threefry counter-per-key
      property (grouped only with equal planned buffer sizes B); host
      draws happen per member on the numpy PCG stream.
    - classification: the per-row scan body equals the solo fused
      kernel's, with per-row (highs, vals) operands; cross-job numeric
      differences ride vals, structure is pinned by the shared
      signature.
    - capacity regrows re-dispatch the whole batched group (counted
      once per regrown dispatch, same as the fused path) and re-decode
      deterministically, so a regrow under batching changes nothing at
      member grain.
    - decode/fold: pair counts are exact integers, dict accumulation is
      order-insensitive, and cri_distribute iterates canonically — the
      folded MRC bytes equal solo (tests/test_batching.py pins this
      across mixed models, mixed N, and regrow).

    A host member shorter than the group's unified chunk plan rides the
    later dispatches fully masked (its padding rows merge nothing), so
    chunk-layout differences vs its solo plan cannot change results.

    Returns one result list per job, ordered like that job's solo
    sampled_outputs. Telemetry mirrors the fused gauges computed over
    the union plan — ref_buckets == ref_buckets_union, so the
    tools/check_dispatch_stats.py bound applies unchanged — plus
    batch_jobs and a dispatches_batched counter.
    """
    import time

    if batch is None:
        batch = default_batch()
    plans = [
        _program_kernels(program, machine)
        for program, machine, _cfg in jobs
    ]
    depth = max(1, max((cfg.pipeline_depth for _p, _m, cfg in jobs),
                       default=1))
    results: dict[tuple[int, int], SampledRefResult] = {}
    pending: list = []
    cap = capacity
    overlap_s = 0.0
    n_buckets = 0
    max_bucket_dispatches = 0
    n_fused = 0
    n_refs_fused = 0

    def finalize(key, name, acc):
        results[key] = SampledRefResult(
            name=name, noshare=acc["noshare"], share=acc["share"],
            cold=acc["cold"], n_samples=acc["n_samples"],
        )

    def drain(entry):
        nonlocal cap, overlap_s
        overlap_s += max(0.0, time.perf_counter() - entry["t0"])
        dispatch_cap = entry["cap"]
        with telemetry.span("fetch", fused=True, batched=True):
            mk, mc, max_nu, cold = telemetry.record_fetch(
                jax.device_get(entry["out"])
            )
        while int(max_nu.max()) > dispatch_cap:
            dispatch_cap = max(dispatch_cap * 4, int(max_nu.max()))
            cap = max(cap, dispatch_cap)
            telemetry.count("capacity_regrows")
            with telemetry.span("fetch", fused=True, regrow=True):
                mk, mc, max_nu, cold = telemetry.record_fetch(
                    jax.device_get(entry["redo"](dispatch_cap))
                )
        with telemetry.span("merge"):
            for row, (key, name, acc) in enumerate(entry["members"]):
                acc["cold"] += float(cold[row])
                decode_pairs(mk[row], mc[row], acc["noshare"],
                             acc["share"])
                acc["left"] -= 1
                if acc["left"] == 0:
                    finalize(key, name, acc)

    def dispatch_group(fused, mem, make_inputs, ph_R, nv_R, rx_R,
                       n_chunks):
        nonlocal n_fused, n_refs_fused

        def redo(c2):
            keys_RB, mask_RB = make_inputs()
            telemetry.count("dispatches")
            telemetry.count("dispatches_fused")
            telemetry.count("dispatches_batched")
            return fused(keys_RB, mask_RB, ph_R, nv_R, rx_R, c2,
                         n_chunks)

        with telemetry.span("dispatch", form="fused_multi",
                            refs=len(mem)):
            out = redo(cap)
        for arr in out:
            try:
                arr.copy_to_host_async()
            except AttributeError:
                pass
        n_fused += 1
        n_refs_fused += len(mem)
        pending.append({
            "out": out, "redo": redo, "cap": cap, "members": mem,
            "t0": time.perf_counter(),
        })
        while len(pending) >= depth:
            telemetry.count("pipeline_stalls")
            drain(pending.pop(0))

    for sig, members_all in _bucket_rows_multi(plans).items():
        members = []
        for j, idx, k, ri in members_all:
            trace, rows = plans[j]
            nt = trace.nests[k]
            cfg = jobs[j][2]
            highs, s = _sample_highs(nt, ri, cfg)
            members.append({
                "key": (j, idx), "nt": nt, "ri": ri, "cfg": cfg,
                "name": nt.tables.ref_names[ri], "highs": highs,
                "s": s, "seed": cfg.seed * 1000003 + idx,
                "ks": rows[idx][2], "drawn": None,
                "acc": {"noshare": {}, "share": {}, "cold": 0.0,
                        "n_samples": 0, "left": 0},
            })
        live = []
        for m in members:
            if m["s"] == 0:  # degenerate ref: nothing to draw
                finalize(m["key"], m["name"], m["acc"])
            else:
                live.append(m)
        if not live:
            continue
        n_buckets += 1
        bspan = telemetry.span(
            "bucket", engine="sampled", batched=True,
            refs=",".join(m["name"] for m in live),
        )
        bspan.__enter__()
        dev_entries = [m for m in live if _use_device_draw(m["cfg"])]
        if dev_entries:
            from .draw import draw_bucket_keys_device_multi

            with telemetry.span("draw", where="device"):
                out = draw_bucket_keys_device_multi(
                    [(m["nt"], m["ri"], m["cfg"], m["seed"])
                     for m in dev_entries],
                    batch,
                )
            for m, d in zip(dev_entries, out):
                m["drawn"] = d
        host_members = [m for m in live if m["drawn"] is None]
        dev_groups: dict[int, list] = {}
        for m in live:
            if m["drawn"] is None:
                continue
            sk, chosen, s_m, _hi = m["drawn"]
            m["acc"]["n_samples"] = s_m
            # only equal planned buffer sizes stack — the threefry
            # stream of a row depends on its B, so a member keeps the
            # exact buffer its solo run would have drawn
            dev_groups.setdefault(int(sk.shape[0]), []).append(
                (m, sk, chosen)
            )
        fused = live[0]["ks"]["fused_multi"]
        bucket_dispatches = 0
        for B, grp in dev_groups.items():
            rx_R = jnp.asarray([m["ri"] for m, _, _ in grp], jnp.int64)
            ph_R = jnp.asarray(
                np.stack([_pad_highs(m["highs"]) for m, _, _ in grp])
            )
            nv_R = _stack_vals([m["nt"].vals for m, _, _ in grp])
            mem = []
            for m, _, _ in grp:
                m["acc"]["left"] += 1
                mem.append((m["key"], m["name"], m["acc"]))

            def make_inputs(grp=grp):
                return (
                    jnp.stack([sk for _, sk, _ in grp]),
                    jnp.stack([ch for _, _, ch in grp]),
                )

            dispatch_group(fused, mem, make_inputs, ph_R, nv_R, rx_R,
                           B // batch)
            bucket_dispatches += 1
        if host_members:
            with telemetry.span("draw", where="host"):
                for m in host_members:
                    keys_all, _hi = draw_sample_keys(
                        m["nt"], m["ri"], m["cfg"], seed=m["seed"]
                    )
                    m["acc"]["n_samples"] = len(keys_all)
                    m["keys"] = keys_all
            g, n_groups = _host_fuse_plan(
                max(len(m["keys"]) for m in host_members), batch
            )
            span_len = g * batch
            rx_R = jnp.asarray([m["ri"] for m in host_members],
                               jnp.int64)
            ph_R = jnp.asarray(
                np.stack([_pad_highs(m["highs"])
                          for m in host_members])
            )
            nv_R = _stack_vals([m["nt"].vals for m in host_members])
            mem = []
            for m in host_members:
                m["acc"]["left"] += n_groups
                mem.append((m["key"], m["name"], m["acc"]))
            for gi in range(n_groups):
                lo = gi * span_len

                def make_inputs(lo=lo, hm=host_members,
                                span_len=span_len):
                    buf = np.empty((len(hm), span_len), dtype=np.int64)
                    msk = np.zeros((len(hm), span_len), dtype=bool)
                    for row, m in enumerate(hm):
                        seg = m["keys"][lo:lo + span_len]
                        buf[row, :len(seg)] = seg
                        buf[row, len(seg):] = m["keys"][0]
                        msk[row, :len(seg)] = True
                    return _place(buf), _place(msk)

                dispatch_group(fused, mem, make_inputs, ph_R, nv_R,
                               rx_R, g)
                bucket_dispatches += 1
        bspan.__exit__(None, None, None)
        max_bucket_dispatches = max(max_bucket_dispatches,
                                    bucket_dispatches)
    while pending:
        drain(pending.pop(0))
    telemetry.gauge("fuse_refs", 1)
    telemetry.gauge("pipeline_depth", depth)
    telemetry.gauge("ref_buckets", n_buckets)
    telemetry.gauge("ref_buckets_union", n_buckets)
    telemetry.gauge("expected_chunks", max_bucket_dispatches)
    telemetry.gauge("pipeline_overlap_s", overlap_s)
    telemetry.gauge("batch_jobs", len(jobs))
    if n_fused:
        telemetry.gauge("refs_per_dispatch", n_refs_fused / n_fused)
    return [
        [results[(j, idx)] for idx in range(len(rows))]
        for j, (_trace, rows) in enumerate(plans)
    ]


def run_sampled_multi(
    jobs, batch: int | None = None, capacity: int = DEFAULT_CAPACITY
) -> list[tuple[PRIState, list[SampledRefResult]]]:
    """Batched engine entry point: jobs is
    [(program, machine, cfg | None, v2)], the return is one
    (PRIState, results) per job — each bit-identical to
    run_sampled(program, machine, cfg, v2=v2) on its own (the service
    batcher's contract; see sampled_outputs_multi)."""
    norm = [
        (p, m, c if c is not None else SamplerConfig(), bool(v2))
        for p, m, c, v2 in jobs
    ]
    for _p, _m, c, _v2 in norm:
        _apply_compilation_cache(c)
    with telemetry.span("engine", engine="sampled",
                        batch_members=len(norm)):
        outs = sampled_outputs_multi(
            [(p, m, c) for p, m, c, _v2 in norm],
            batch=batch, capacity=capacity,
        )
        folded = []
        with telemetry.span("merge", stage="fold_results"):
            for (_p, m, _c, v2), res in zip(norm, outs):
                folded.append((fold_results(res, m.thread_num, v2), res))
    return folded
