"""Streaming dense engine: exact full traversal at large N.

sampler/dense.py materializes each simulated thread's whole access
stream for one sort — at GEMM N=4096 that is ~7e10 accesses per
thread, far beyond HBM. This engine streams the same computation over
chunks of the parallel loop with `lax.scan`:

- the scan carry holds, per (array, cache line), the line's last
  global access position — a dense int64 vector replacing the
  reference's LAT hash maps (LAT_A/B/C, ...ri-omp-seq.cpp:47-49) —
  plus the running noshare histogram and access count;
- each step enumerates one m-chunk, sorts it (chunk-local positions so
  the packed keys stay within 63 bits), measures within-chunk reuses as
  adjacent diffs, and joins chunk-boundary reuses against the carry:
  first-of-group accesses look up the carried last position, exactly
  `count[tid] - LAT[addr]` across the boundary (:110);
- share-classified intervals exit per step through the fixed-capacity
  unique reduction (stacked scan outputs, merged on host);
- after the scan, surviving carry entries flush as the per-array -1
  cold counts (:305-319).

The result is bit-identical to sampler/dense.py (tests pin it at
several chunk sizes) while memory scales with chunk size, not trace
length — the framework's long-trace analog of sequence-parallel
streaming. Simulated threads are vmapped as in the dense engine.
"""

from __future__ import annotations


import jax
import jax.numpy as jnp
import numpy as np

from ..config import MachineConfig
from ..core.trace import NestTrace, ProgramTrace
from ..ir import Program
from ..ops.histogram import N_EXP_BINS, exp_bin, sorted_k_unique
from ..oracle.serial import OracleResult
from ..runtime import telemetry
from ..runtime.hist import PRIState
from .dense import _REF_BITS, _ceil_log2, nest_geometry, packed_ref_keys

# Per-chunk element budget: chunk_m = max(1, _ELEM_BUDGET // acc[0]).
_ELEM_BUDGET = 1 << 22


def _stream_nest_kernel(nt: NestTrace, chunk_m: int, max_share: int):
    """Build the jitted per-tid scan over m-chunks of one nest."""
    t = nt.tables
    sched = nt.schedule
    machine = nt.machine
    lmax = sched.max_local_count()
    n_arrays, max_addr, n_groups = nest_geometry(nt)
    n_steps = -(-lmax // chunk_m)
    # chunk-local positions for key packing (the full-trace position
    # would overflow 63 bits at large N); positions leave the packed
    # domain as plain int64 before reuse arithmetic
    if nt.tri:
        # max accesses any chunk_m-window of any thread performs
        b = nt.tri_base
        span = max(
            int((b[:, min(m0 + chunk_m, b.shape[1] - 1)] - b[:, m0]).max())
            for m0 in range(0, lmax, chunk_m)
        ) if lmax else 1
        pos_bits = _ceil_log2(span + 1)
        base_tab = jnp.asarray(nt.tri_base)
    else:
        a0 = int(t.acc_per_level[0])
        pos_bits = _ceil_log2(chunk_m * a0 + 1)
        base_tab = None
    grp_bits = _ceil_log2(n_groups + 1)
    assert grp_bits + pos_bits + _REF_BITS <= 63, "key packing overflow"

    local_counts = jnp.array(
        [sched.local_count(tt) for tt in range(sched.threads)],
        dtype=jnp.int64,
    )
    thr_table = jnp.array(t.ref_share_thresholds, dtype=jnp.int64)
    ratio_table = jnp.array(t.ref_share_ratios, dtype=jnp.int64)
    K = machine.chunk_size
    P = sched.threads
    step0, start0 = sched.step, sched.start

    def enumerate_chunk(tid, m0):
        """Packed sort keys of the m-range [m0, m0+chunk_m)."""
        m = m0 + jnp.arange(chunk_m, dtype=jnp.int64)
        valid_m = m < local_counts[tid]
        v0 = start0 + (((m // K) * P + tid) * K + (m % K)) * step0
        mrel = jnp.arange(chunk_m, dtype=jnp.int64)
        base = (
            base_tab[tid, jnp.minimum(m, lmax)] - base_tab[tid, m0]
            if nt.tri else None
        )
        keys = [
            packed_ref_keys(
                nt, ri, v0, mrel, valid_m, pos_bits, max_addr, n_groups,
                base=base,
            )
            for ri in range(t.n_refs)
        ]
        return jnp.sort(jnp.concatenate(keys))

    def step_fn(tid, carry, m0):
        last_pos, nosh, n_acc = carry
        key = enumerate_chunk(tid, m0)
        ref_s = (key & ((1 << _REF_BITS) - 1)).astype(jnp.int32)
        pos_rel = (key >> _REF_BITS) & ((1 << pos_bits) - 1)
        grp_s = key >> (_REF_BITS + pos_bits)
        is_valid = grp_s != (n_groups - 1)
        # position in the thread's nest-local clock (reuse intervals are
        # position differences, so any constant offset cancels)
        chunk_base = base_tab[tid, m0] if nt.tri else m0 * a0
        pos_g = pos_rel + chunk_base
        same = jnp.concatenate(
            [jnp.array([False]), (grp_s[1:] == grp_s[:-1]) & is_valid[1:]]
        )
        prev_in_chunk = jnp.concatenate([jnp.zeros(1, jnp.int64), pos_g[:-1]])
        # chunk-boundary join: first-of-group looks up the carry
        carried = last_pos[grp_s]
        is_first = is_valid & ~same
        has_prev = same | (is_first & (carried >= 0))
        prev = jnp.where(same, prev_in_chunk, carried)
        reuse = jnp.where(has_prev, pos_g - prev, 0)
        thr = thr_table[ref_s]
        is_share = has_prev & (thr > 0) & (
            jnp.abs(reuse) > jnp.abs(reuse - thr)
        )
        is_noshare = has_prev & ~is_share
        e = exp_bin(jnp.maximum(reuse, 1))
        nosh = nosh.at[e].add(is_noshare.astype(jnp.int64))
        share_key = reuse * 8 + ratio_table[ref_s]
        sk, sc, nu = sorted_k_unique(share_key, is_share, max_share)
        # carry update: last touch per group (positions ascend in-group;
        # invalid entries scatter -1 into the invalid group, a no-op)
        last_pos = last_pos.at[grp_s].max(
            jnp.where(is_valid, pos_g, jnp.int64(-1))
        )
        n_acc = n_acc + jnp.sum(is_valid.astype(jnp.int64))
        return (last_pos, nosh, n_acc), (sk, sc, nu)

    @jax.jit
    def run_tid(tid, last_pos):
        """Scan all chunks of one (tid, nest); returns final carry + ys."""
        nosh = jnp.zeros(N_EXP_BINS, dtype=jnp.int64)
        n_acc = jnp.int64(0)
        m0s = jnp.arange(n_steps, dtype=jnp.int64) * chunk_m
        (last_pos, nosh, n_acc), ys = jax.lax.scan(
            lambda c, m0: step_fn(tid, c, m0),
            (last_pos, nosh, n_acc),
            m0s,
        )
        # -1 flush: surviving lines per array (...ri-omp-seq.cpp:305-319)
        arr_of = (
            jnp.arange(n_groups - 1, dtype=jnp.int64) // max_addr
        )
        cold = jnp.zeros(n_arrays + 1, dtype=jnp.int64).at[
            jnp.where(last_pos[:-1] >= 0, arr_of, n_arrays)
        ].add(1)[:n_arrays]
        return nosh, ys, cold, n_acc

    def fresh_carry():
        return jnp.full(n_groups, -1, dtype=jnp.int64)

    return run_tid, fresh_carry, n_steps


@telemetry.counted_lru_cache(maxsize=32)
def _compiled_stream(
    program: Program, machine: MachineConfig, chunk_m: int | None,
    max_share: int,
):
    """Kernels cached per (program, machine, chunking) so repeated runs
    (e.g. the CLI's speed mode) reuse the jitted executables."""
    trace = ProgramTrace(program, machine)
    kernels = []
    for nt in trace.nests:
        cm = chunk_m or max(1, _ELEM_BUDGET // max(1, nt.max_body0))
        cm = min(cm, max(1, nt.schedule.max_local_count()))
        kernels.append(_stream_nest_kernel(nt, cm, max_share))
    return trace, kernels


def run_stream(
    program: Program,
    machine: MachineConfig,
    chunk_m: int | None = None,
    max_share: int = 64,
) -> OracleResult:
    """Streaming dense engine -> OracleResult (== run_dense exactly)."""
    trace, kernels = _compiled_stream(program, machine, chunk_m, max_share)
    P = machine.thread_num
    state = PRIState(P)
    per_tid = [0] * P
    engine_span = telemetry.span("engine", engine="stream")
    engine_span.__enter__()
    for nest_k, (run_tid, fresh_carry, _) in enumerate(kernels):
        for tid in range(P):
            with telemetry.span("scan", nest=nest_k, tid=tid):
                telemetry.count("dispatches")
                out = run_tid(jnp.int64(tid), fresh_carry())
                with telemetry.span("fetch"):
                    nosh, ys, cold, n_acc = telemetry.record_fetch(
                        jax.device_get(out)
                    )
            sk, sc, nu = ys
            if int(nu.max(initial=0)) > sk.shape[1]:
                raise RuntimeError(
                    "share-value capacity exceeded; raise max_share "
                    f"(needed {int(nu.max())}, have {sk.shape[1]})"
                )
            h = state.noshare[tid]
            for e_idx in np.nonzero(nosh)[0]:
                key = 1 << int(e_idx)
                h[key] = h.get(key, 0.0) + float(nosh[e_idx])
            c = int(cold.sum())
            if c:
                h[-1] = h.get(-1, 0.0) + float(c)
            for s in range(sk.shape[0]):
                for key, cnt in zip(sk[s], sc[s]):
                    if cnt > 0:
                        reuse, ratio = divmod(int(key), 8)
                        hs = state.share[tid].setdefault(ratio, {})
                        hs[reuse] = hs.get(reuse, 0.0) + float(cnt)
            per_tid[tid] += int(n_acc)
    engine_span.__exit__(None, None, None)
    return OracleResult(
        state=state, total_accesses=sum(per_tid), per_tid_accesses=per_tid
    )
