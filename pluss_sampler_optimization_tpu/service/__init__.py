"""Request-level analysis service.

Turns the engines into an on-demand system: content-addressed result
caching (two-tier, versioned, corruption-tolerant — service/cache.py),
canonical request fingerprints (service/fingerprint.py), singleflight
request execution with deadlines and engine degradation
(service/executor.py), replica-pool device partitioning with
load-aware routing, work stealing, and breaker-gated recovery
(service/replicas.py), chaos-grade resilience — per-attempt timeouts
with seeded-backoff retries, hedged dispatch, circuit breakers with
half-open probation (service/breakers.py), and admission-controlled
load shedding — and the submit/result + JSONL serving API with
graceful drain (service/api.py). CLI entry points: `serve` mode,
`--cache-dir`, `--replicas`, `--fault-spec`, and the resilience
flags (cli.py); store audits: tools/check_service_store.py; the
seeded chaos gate: tools/check_chaos.py.
"""

from .api import (
    AnalysisRequest,
    AnalysisResponse,
    AnalysisService,
    AnalysisTicket,
    GracefulShutdown,
    parse_request_line,
    serve_jsonl,
)
from .breakers import CircuitBreaker
from .cache import STORE_VERSION, ResultCache, validate_record
from .executor import (
    DEGRADE_CHAINS,
    PRIORITY_CLASSES,
    SERVICE_ENGINES,
    RequestExecutor,
    default_runner,
    execute_request,
)
from .fingerprint import (
    FINGERPRINT_VERSION,
    canonical_json,
    content_digest,
    request_fingerprint,
    structure_digest,
)
from .replicas import Replica, ReplicaPool, current_replica_id

__all__ = [
    "AnalysisRequest",
    "AnalysisResponse",
    "AnalysisService",
    "AnalysisTicket",
    "GracefulShutdown",
    "CircuitBreaker",
    "PRIORITY_CLASSES",
    "parse_request_line",
    "serve_jsonl",
    "STORE_VERSION",
    "ResultCache",
    "validate_record",
    "DEGRADE_CHAINS",
    "SERVICE_ENGINES",
    "RequestExecutor",
    "default_runner",
    "execute_request",
    "FINGERPRINT_VERSION",
    "canonical_json",
    "content_digest",
    "request_fingerprint",
    "structure_digest",
    "Replica",
    "ReplicaPool",
    "current_replica_id",
]
