"""Request-level analysis service.

Turns the engines into an on-demand system: content-addressed result
caching (two-tier, versioned, corruption-tolerant — service/cache.py),
canonical request fingerprints (service/fingerprint.py), singleflight
request execution with deadlines and engine degradation
(service/executor.py), replica-pool device partitioning with
load-aware routing, work stealing, and failure quarantine
(service/replicas.py), and the submit/result + JSONL serving API
(service/api.py). CLI entry points: `serve` mode, `--cache-dir`, and
`--replicas` (cli.py); store audits: tools/check_service_store.py.
"""

from .api import (
    AnalysisRequest,
    AnalysisResponse,
    AnalysisService,
    AnalysisTicket,
    parse_request_line,
    serve_jsonl,
)
from .cache import STORE_VERSION, ResultCache, validate_record
from .executor import (
    DEGRADE_CHAINS,
    SERVICE_ENGINES,
    RequestExecutor,
    default_runner,
    execute_request,
)
from .fingerprint import (
    FINGERPRINT_VERSION,
    canonical_json,
    content_digest,
    request_fingerprint,
    structure_digest,
)
from .replicas import Replica, ReplicaPool, current_replica_id

__all__ = [
    "AnalysisRequest",
    "AnalysisResponse",
    "AnalysisService",
    "AnalysisTicket",
    "parse_request_line",
    "serve_jsonl",
    "STORE_VERSION",
    "ResultCache",
    "validate_record",
    "DEGRADE_CHAINS",
    "SERVICE_ENGINES",
    "RequestExecutor",
    "default_runner",
    "execute_request",
    "FINGERPRINT_VERSION",
    "canonical_json",
    "content_digest",
    "request_fingerprint",
    "structure_digest",
    "Replica",
    "ReplicaPool",
    "current_replica_id",
]
