"""AnalysisService: the request-level serving API.

`AnalysisService` owns one two-tier result cache and one request
executor; `submit()` returns a ticket immediately and `result()`
blocks for the response (`analyze()` is both). Identical concurrent
submissions coalesce to one engine execution; warm repeats are served
from the content-addressed store with zero engine work and a
bit-identical MRC (the acceptance invariants, pinned by
tests/test_service.py through telemetry counters).

`serve_jsonl` is the CLI `serve` mode's engine: it reads one JSON
request per line, submits the whole batch up front (so duplicate
requests inside a batch coalesce), then emits one JSON response per
request in input order. Request schema (README "Serving"):

    {"id": "r1", "model": "gemm", "n": 128, "engine": "exact",
     "threads": 4, "chunk": 4, "ratio": 0.1, "seed": 0,
     "deadline_s": 30.0}

Every field except `model` has a default; a malformed line — invalid
JSON, unknown fields, a bad model — is a structured error response
for that line (with the request `id` echoed whenever the line parsed
far enough to carry one), never a crash of the batch. Instead of a
registry `model`, a line may carry an inline `program` document
(frontend/schema.py — README "Custom loop nests"); oversize lines,
over-deep JSON, and hostile bounds products are refused with the
same structured errors plus a `frontend_rejected` counter.

Three introspection request types ride the same protocol:

    {"id": "h1", "type": "healthz"}   -> liveness + engine roster
    {"id": "s1", "type": "stats"}     -> executor queue depth /
        in-flight / coalesce counters, cache tier stats, ledger tail
    {"id": "m1", "type": "metrics"}   -> live metrics registry
        snapshot (rolling-window counters, gauges, per-stage request
        histograms, Prometheus text, latest SLO report)

All answer from the service's instance-local counters / the live
registry (no telemetry run required) with the snapshot taken at the
moment the line is READ — a mid-batch `stats` line observes the
requests submitted before it.
"""

from __future__ import annotations

import dataclasses
import json
import re
import threading
import time
from concurrent.futures import CancelledError
from typing import IO

import numpy as np

from ..config import MachineConfig
from ..ir import Program
from ..models import build as build_model
from ..runtime import faults
from .cache import ResultCache
from .executor import (
    PRIORITY_CLASSES,
    SERVICE_ENGINES,
    RequestExecutor,
    default_runner,
    progressive_requested,
)
from .fingerprint import request_fingerprint


class GracefulShutdown(BaseException):
    """Raised by the CLI's SIGTERM/SIGINT handlers to unwind
    serve_jsonl. A BaseException on purpose: the serve loop's
    per-line `except Exception` robustness handlers must NOT swallow
    a shutdown into a structured error response — only the dedicated
    handlers in serve_jsonl may catch it."""

# The reserved model name for inline-program requests. Not a registry
# entry: a request carries EITHER a registry model name (model/n/
# tsteps address the builder) OR an inline frontend document
# (`program`), in which case the model field is forced to this
# sentinel so ledger rows, stats, and caches have a uniform label.
CUSTOM_MODEL = "custom"

# Hard per-line budget for the serve protocol. A frontend document
# for any sane nest is a few KB; a line this long is hostile or a
# client bug, and is refused BEFORE json.loads sees it.
MAX_REQUEST_LINE_BYTES = 1 << 20


@dataclasses.dataclass(frozen=True)
class AnalysisRequest:
    """One analysis request. `id`, `deadline_s`, and `trace_id` are
    serving metadata — they identify/bound the request but do not
    change the result, so they stay OUT of the fingerprint and the
    stored record. A caller-supplied `trace_id` propagates through
    singleflight coalescing and batching into the execution span and
    the ledger row; when absent the executor mints one at submit."""

    model: str
    n: int = 128
    tsteps: int = 1
    engine: str = "exact"
    runtime: str = "v1"
    threads: int = 4
    chunk: int = 4
    ds: int = 8
    cls: int = 64
    cache_kb: int = 2560
    ratio: float = 0.1
    seed: int = 0
    device_draw: bool | None = None
    # Dispatch-shape knobs for the sampled engine (None = config
    # default). Pure performance: fused results are bit-identical to
    # the per-ref path, so — unlike device_draw — these MUST NOT
    # enter params()/the fingerprint; a cached result answers both
    # settings.
    fuse_refs: bool | None = None
    pipeline_depth: int | None = None
    # kernel_backend rides with them: all backends fold bit-identical
    # PRIStates (pinned by tests/test_pallas.py), so it too must stay
    # out of the fingerprint
    kernel_backend: str | None = None
    # Progressive-precision knobs (sampled engine; any one set opts
    # into the round-based driver): stop early once the bootstrap MRC
    # band is narrower than `tolerance`; `max_rounds`/`round_schedule`
    # shape the round ladder (sampler/confidence.py). Like fuse_refs
    # these stay OUT of params()/the fingerprint: a converged
    # progressive run is bit-identical to the one-shot sampled result
    # at the final ratio (and a deadline-truncated partial_final is
    # degraded, hence never cached), so the cached record answers
    # every knob setting.
    tolerance: float | None = None
    max_rounds: int | None = None
    round_schedule: list | None = None
    # Inline frontend document (frontend/schema.py) — the
    # "MRC-as-a-service" path. Mutually exclusive with addressing a
    # registry model: when set, `model` is the CUSTOM_MODEL sentinel
    # and n/tsteps are ignored (the document IS the program). The
    # fingerprint is taken over the canonical parsed IR, so two users
    # submitting structurally identical nests coalesce/cache-hit
    # exactly like repeat registry requests.
    program: dict | None = None
    deadline_s: float | None = None
    # Admission priority class (executor.py::PRIORITY_CLASSES): under
    # overload, low-priority work is shed first and high-priority
    # last. Pure serving policy — never in the fingerprint
    priority: str = "normal"
    id: str | None = None
    trace_id: str | None = None

    def __post_init__(self) -> None:
        if self.engine not in SERVICE_ENGINES:
            raise ValueError(
                f"unknown service engine {self.engine!r} "
                f"(have {', '.join(SERVICE_ENGINES)})"
            )
        if self.priority not in PRIORITY_CLASSES:
            raise ValueError(
                f"unknown priority {self.priority!r} "
                f"(have {', '.join(PRIORITY_CLASSES)})"
            )
        if self.runtime not in ("v1", "v2"):
            raise ValueError("runtime must be 'v1' or 'v2'")
        if self.kernel_backend not in (
            None, "auto", "xla", "pallas", "native"
        ):
            raise ValueError(
                f"unknown kernel_backend {self.kernel_backend!r} "
                "(have auto, xla, pallas, native)"
            )
        if self.tolerance is not None and (
            not isinstance(self.tolerance, (int, float))
            or isinstance(self.tolerance, bool)
            or self.tolerance < 0
        ):
            raise ValueError("tolerance must be a non-negative number")
        if self.max_rounds is not None and (
            not isinstance(self.max_rounds, int)
            or isinstance(self.max_rounds, bool)
            or self.max_rounds < 1
        ):
            raise ValueError("max_rounds must be a positive integer")
        if self.round_schedule is not None:
            sched = self.round_schedule
            ok = (
                isinstance(sched, (list, tuple)) and len(sched) > 0
                and all(
                    isinstance(f, (int, float))
                    and not isinstance(f, bool) for f in sched
                )
            )
            if ok:
                fr = [float(f) for f in sched]
                ok = (
                    fr[0] > 0.0 and fr[-1] == 1.0
                    and all(b > a for a, b in zip(fr, fr[1:]))
                )
            if not ok:
                raise ValueError(
                    "round_schedule must be a strictly increasing "
                    "list of fractions in (0, 1] ending at 1.0"
                )
        if self.program is not None:
            if not isinstance(self.program, dict):
                raise ValueError("'program' must be a JSON object")
            if self.model != CUSTOM_MODEL:
                raise ValueError(
                    "inline 'program' requests use model "
                    f"{CUSTOM_MODEL!r}, not {self.model!r}"
                )
        elif self.model == CUSTOM_MODEL:
            raise ValueError(
                f"model {CUSTOM_MODEL!r} requires an inline 'program'"
            )

    def build_program(self) -> Program:
        if self.program is not None:
            from ..frontend.parse import parse_program

            return parse_program(self.program)
        return build_model(self.model, self.n, self.tsteps)

    def machine(self) -> MachineConfig:
        base = MachineConfig(
            thread_num=self.threads, chunk_size=self.chunk,
            ds=self.ds, cls=self.cls, cache_kb=self.cache_kb,
        )
        if self.program is not None:
            # document machine knobs override the request-level
            # fields — a frontend document is a complete scenario on
            # its own (the merged config is what gets fingerprinted)
            from ..frontend.schema import machine_from_doc

            return machine_from_doc(self.program, base)
        return base

    def params(self) -> dict:
        """Engine parameters that shape the RESULT, and only those: an
        exact request's fingerprint must not vary with sampling knobs
        it never reads."""
        p: dict = {}
        if self.engine in ("oracle", "sampled"):
            p["runtime"] = self.runtime
        if self.engine == "sampled":
            p["ratio"] = self.ratio
            p["seed"] = self.seed
            # the requested selector (None = per-backend auto); the
            # two draw paths yield different deterministic sample
            # sets, so an explicit choice must split the address.
            # fuse_refs / pipeline_depth stay OUT: fused dispatch is
            # pinned bit-identical, so they cannot shape the result
            p["device_draw"] = self.device_draw
        return p

    def payload(self) -> dict:
        """The request as stored in the result record (no serving
        metadata)."""
        d = dataclasses.asdict(self)
        d.pop("id")
        d.pop("deadline_s")
        d.pop("trace_id")
        d.pop("priority")
        if d.get("program") is None:
            # registry records keep their pre-frontend shape exactly
            # (store bytes pinned); custom records embed the document
            # so warm_from_ledger can replay them
            d.pop("program")
        for k in ("tolerance", "max_rounds", "round_schedule"):
            # unset progressive knobs are dropped the same way, so
            # every pre-progressive request keeps its exact payload
            # (and stored-record) bytes
            if d.get(k) is None:
                d.pop(k)
        return d

    def fingerprint(self, program: Program | None = None) -> str:
        return request_fingerprint(
            program if program is not None else self.build_program(),
            self.machine(),
            self.engine,
            self.params(),
        )


@dataclasses.dataclass
class AnalysisTicket:
    request: AnalysisRequest
    fingerprint: str
    future: object  # concurrent.futures.Future resolving to a dict


@dataclasses.dataclass
class AnalysisResponse:
    id: str | None
    ok: bool
    fingerprint: str | None
    engine_requested: str | None
    engine_used: str | None
    cache: str | None  # "mem" | "disk" | "miss"
    degraded: list
    latency_s: float | None
    total_accesses: int | None
    access_label: str | None
    mrc: "np.ndarray | None"
    mrc_digest: str | None  # 16-hex digest of the MRC (ledger key)
    rih: dict | None  # int key -> count
    dump_lines: list | None
    per_ref_lines: list | None
    error: str | None
    # trace context: trace_id identifies the request end to end;
    # span_id the (possibly shared — batching/singleflight) engine
    # execution that produced the result. Both null for pure cache
    # hits with no execution.
    trace_id: str | None = None
    span_id: str | None = None
    # the replica whose device group executed the request (None:
    # cache hit, no pool, or failure before execution). Serving
    # metadata only — MRC bytes are identical whichever replica ran
    replica_id: int | None = None
    # ir-preflight summary ({"verdict": "ok"|"race", "races": N}) from
    # the static-analysis gate; None when preflight is disabled.
    # Serving metadata: the verdict never shapes the MRC bytes
    preflight: dict | None = None
    # resilience outcomes (serving metadata): shed = refused at the
    # admission gate (ok is False but nothing failed — the service
    # declined the work); retries/hedged report what the executor
    # spent getting the (bit-identical) result
    shed: bool = False
    retries: int = 0
    hedged: bool = False
    # worker-side stage timings (serving metadata, monotonic deltas on
    # the executing process's clock). Over a fabric these let a client
    # split end-to-end latency into worker time vs routing + wire
    # overhead without any clock agreement (tools/loadgen.py --connect
    # reports exactly that)
    queue_s: float | None = None
    execute_s: float | None = None
    # progressive-precision outcome (serving metadata): rounds the
    # driver completed, the tightest confidence-band width reached,
    # and whether the run converged (band under tolerance / full
    # schedule). partial_final marks a deadline-truncated answer —
    # served at the band above, recorded as a precision:* degrade
    # hop, never cached.
    rounds: int | None = None
    band_width: float | None = None
    converged: bool | None = None
    partial_final: bool = False

    def to_jsonl_dict(self) -> dict:
        """The wire form `serve` emits: compact — the MRC ships in the
        reference's run-length print form (runtime/report.py), not as
        the dense curve (cache_lines can reach 327k entries)."""
        from ..runtime import report

        d: dict = {
            "id": self.id,
            "ok": self.ok,
            "fingerprint": self.fingerprint,
            "engine_requested": self.engine_requested,
            "engine_used": self.engine_used,
            "cache": self.cache,
            "degraded": self.degraded,
            "latency_s": self.latency_s,
            "total_accesses": self.total_accesses,
            "access_label": self.access_label,
        }
        if self.trace_id is not None:
            d["trace_id"] = self.trace_id
        if self.span_id is not None:
            d["span_id"] = self.span_id
        if self.replica_id is not None:
            d["replica_id"] = self.replica_id
        if self.preflight is not None:
            d["preflight"] = self.preflight
        if self.shed:
            d["shed"] = True
        if self.retries:
            d["retries"] = self.retries
        if self.hedged:
            d["hedged"] = True
        if self.queue_s is not None:
            d["queue_s"] = self.queue_s
        if self.execute_s is not None:
            d["execute_s"] = self.execute_s
        if self.rounds is not None:
            d["rounds"] = self.rounds
        if self.band_width is not None:
            d["band_width"] = self.band_width
        if self.converged is not None:
            d["converged"] = self.converged
        if self.partial_final:
            d["partial_final"] = True
        if self.mrc is not None:
            d["mrc_len"] = int(len(self.mrc))
            d["mrc_lines"] = report.mrc_lines(self.mrc, header=False)
        if self.mrc_digest is not None:
            # ties the wire response to its ledger row: a degraded
            # response's digest is attributable after the fact
            d["mrc_digest"] = self.mrc_digest
        if self.error is not None:
            d["error"] = self.error
        return d


def _response_from_outcome(request: AnalysisRequest, fingerprint: str,
                           outcome: dict) -> AnalysisResponse:
    record = outcome.get("record")
    if record is None:
        return AnalysisResponse(
            id=request.id, ok=False, fingerprint=fingerprint,
            engine_requested=request.engine, engine_used=None,
            cache=outcome.get("cache"),
            degraded=outcome.get("degraded") or [],
            latency_s=outcome.get("latency_s"),
            total_accesses=None, access_label=None, mrc=None,
            mrc_digest=None, rih=None, dump_lines=None,
            per_ref_lines=None,
            error=outcome.get("error") or "execution failed",
            trace_id=outcome.get("trace_id"),
            span_id=outcome.get("span_id"),
            replica_id=outcome.get("replica_id"),
            preflight=outcome.get("preflight"),
            shed=bool(outcome.get("shed")),
            retries=int(outcome.get("retries") or 0),
            hedged=bool(outcome.get("hedged")),
            queue_s=outcome.get("queue_s"),
            execute_s=outcome.get("execute_s"),
            rounds=outcome.get("rounds"),
            band_width=outcome.get("band_width"),
            converged=outcome.get("converged"),
            partial_final=bool(outcome.get("partial_final")),
        )
    return AnalysisResponse(
        id=request.id,
        ok=True,
        fingerprint=fingerprint,
        engine_requested=request.engine,
        engine_used=record["engine_used"],
        cache=outcome.get("cache"),
        degraded=outcome.get("degraded") or [],
        latency_s=outcome.get("latency_s"),
        total_accesses=record["total_accesses"],
        access_label=record["access_label"],
        mrc=np.asarray(record["mrc"], dtype=np.float64),
        mrc_digest=outcome.get("mrc_digest"),
        rih={int(k): v for k, v in record["rih"].items()},
        dump_lines=list(record["dump_lines"]),
        per_ref_lines=list(record.get("per_ref_lines", [])) or None,
        error=None,
        trace_id=outcome.get("trace_id"),
        span_id=outcome.get("span_id"),
        replica_id=outcome.get("replica_id"),
        preflight=outcome.get("preflight"),
        retries=int(outcome.get("retries") or 0),
        hedged=bool(outcome.get("hedged")),
        queue_s=outcome.get("queue_s"),
        execute_s=outcome.get("execute_s"),
        rounds=outcome.get("rounds"),
        band_width=outcome.get("band_width"),
        converged=outcome.get("converged"),
        partial_final=bool(outcome.get("partial_final")),
    )


class AnalysisService:
    """submit()/result() over the cache + executor pair, plus the
    healthz/stats introspection the serve protocol exposes."""

    def __init__(self, cache_dir: str | None = None,
                 max_workers: int = 4, mem_entries: int = 128,
                 runner=default_runner,
                 ledger_path: str | None = None,
                 batch_window_ms: float | None = None,
                 batch_max_refs: int = 64,
                 replicas=None,
                 preflight: bool = True,
                 resilience=None,
                 worker_id: int | None = None):
        from ..config import BatchConfig

        self.cache = ResultCache(cache_dir, mem_entries=mem_entries)
        self.ledger_path = ledger_path
        # static-analysis gate (analysis/__init__.py): validates the
        # IR before fingerprint/cache and attaches the verdict to
        # responses/ledger rows. Off is a debugging escape hatch —
        # MRC bytes are bit-identical either way (the analyzer never
        # touches the engines; pinned by tests/test_analysis.py)
        self.preflight = preflight
        self._preflight_memo: dict = {}
        # optional runtime/obs/slo.py sentinel, attached by the CLI
        # serve mode so the `metrics` request can report the latest
        # SLO evaluation alongside the registry snapshot
        self.slo_sentinel = None
        self.executor = RequestExecutor(
            self.cache, max_workers=max_workers, runner=runner,
            ledger_path=ledger_path,
            batching=(
                BatchConfig(window_ms=batch_window_ms,
                            max_refs=batch_max_refs)
                if batch_window_ms is not None else None
            ),
            # int | ReplicaConfig | None (None = no pool, the PR 9
            # single-device-set behavior)
            replicas=replicas,
            # ResilienceConfig | None (None = every layer off/neutral:
            # no retries, no hedging, no admission limit — the
            # pre-resilience behavior, bit for bit)
            resilience=resilience,
            # fabric attribution: set when this service is one worker
            # of a multi-process fabric (cli serve-worker); ledger
            # rows carry it so a shared ledger shards by worker
            worker_id=worker_id,
        )

    def begin_shutdown(self) -> None:
        """Enter graceful drain: later submits shed at the admission
        gate, queued-but-unstarted work cancels (its waiters get
        structured shed responses from serve_jsonl), executions
        already running finish and are answered normally. Idempotent;
        `close()` still performs the final teardown."""
        self.executor.drain()

    def warm_from_ledger(self, top_n: int) -> int:
        """Ledger-driven warm start: pre-compile the sampled kernel
        signatures of the `top_n` most frequent fingerprints in the
        ledger tail, so the first real request after a restart skips
        cold jit (its ledger row then records near-zero compile
        deltas — the property tests/test_replicas.py pins). Rows
        written before the ledger carried request payloads, and
        non-sampled rows (their engines have no warmup entry point),
        are skipped. Returns the number of warmup executions run."""
        import collections as _collections

        from ..runtime.obs import ledger as obs_ledger
        from .executor import sampler_config

        if not self.ledger_path or top_n <= 0:
            return 0
        try:
            rows = obs_ledger.read_rows(self.ledger_path)
        except Exception:
            return 0
        by_fp: dict = {}
        freq: _collections.Counter = _collections.Counter()
        for row in rows:
            if row.get("kind") != "request":
                continue
            payload = row.get("request")
            if not isinstance(payload, dict):
                continue
            if payload.get("engine") != "sampled":
                continue
            fp = row.get("fingerprint")
            if not fp:
                continue
            freq[fp] += 1 + int(row.get("coalesced") or 0)
            by_fp[fp] = payload
        jobs = []
        for fp, _ in freq.most_common(top_n):
            try:
                req = AnalysisRequest(**by_fp[fp])
                jobs.append((
                    req.build_program(), req.machine(),
                    sampler_config(req),
                ))
            except Exception:
                continue
        return self.executor.warm_structures(jobs)

    def healthz(self) -> dict:
        """Liveness + capability roster (the `healthz` request type).
        """
        from .executor import SERVICE_ENGINES
        from .cache import STORE_VERSION

        ex = self.executor.stats()
        reps = ex.get("replicas") or {}
        return {
            "status": "ok",
            "engines": list(SERVICE_ENGINES),
            "store_version": STORE_VERSION,
            "in_flight": ex["in_flight"],
            "queue_depth": ex["queue_depth"],
            "batch_queue_depth": ex["batch_queue_depth"],
            "replicas": reps.get("count", 0),
            "replicas_quarantined": reps.get("quarantined", 0),
            "ledger": self.ledger_path,
        }

    def stats(self, ledger_tail: int = 5) -> dict:
        """Full introspection snapshot (the `stats` request type):
        executor queue/coalesce/degradation counters incl. batch
        occupancy and batched-vs-solo latency, cache tier stats, the
        ledger tail, and — when a ledger is configured — the ledger's
        cross-run batching aggregate (joined on batch_id rows)."""
        from ..runtime.obs import ledger as obs_ledger

        out = {
            "executor": self.executor.stats(),
            "cache": self.cache.stats(),
            "ledger": self.ledger_path,
            "ledger_tail": (
                obs_ledger.tail(self.ledger_path, ledger_tail)
                if self.ledger_path else []
            ),
        }
        if self.ledger_path:
            try:
                agg = obs_ledger.aggregate(
                    obs_ledger.read_rows(self.ledger_path)
                )
                out["batching"] = agg.get("batching")
            except Exception:
                out["batching"] = None
        return out

    def metrics(self) -> dict:
        """Live-registry snapshot (the `metrics` request type):
        counters with rolling windows, gauges, per-stage request
        histograms, the Prometheus exposition text, and — when a
        sentinel is attached — the latest SLO report. `enabled: false`
        when no registry is installed (metrics.enable() not called)."""
        from ..runtime.obs import metrics as obs_metrics

        reg = obs_metrics.get()
        if reg is None:
            return {"enabled": False}
        out = {"enabled": True}
        out.update(reg.snapshot())
        out["prometheus"] = reg.prometheus_text()
        if self.slo_sentinel is not None:
            out["slo"] = self.slo_sentinel.last_report
        return out

    def dump_debug(self) -> dict:
        """Explicit post-mortem dump (the `dump_debug` request type):
        ask the flight recorder (runtime/obs/recorder.py) to write one
        bundle NOW, bypassing the trigger rate limit, and return its
        path plus the recorder's state and bundle index. `enabled:
        false` when no recorder is installed (serve mode without
        --debug-bundle-dir)."""
        from ..runtime.obs import recorder as obs_recorder

        rec = obs_recorder.get()
        if rec is None:
            return {"enabled": False}
        path = rec.dump("dump_debug")
        return {
            "enabled": True,
            "bundle": path,
            "bundle_dir": rec.bundle_dir,
            "recorder": rec.stats(),
            "bundles": rec.bundle_index(),
        }

    def _run_preflight(self, request: AnalysisRequest,
                       program: Program) -> dict:
        """The static-analysis gate, run before fingerprint/cache.

        Returns the compact preflight summary that rides the outcome/
        response/ledger row; raises `analysis.PreflightError` (with
        machine-readable diagnostics attached) for invalid IR —
        nothing is fingerprinted, cached, or executed for a rejected
        request, and the rejection leaves its own ledger row.

        The verdict is a pure function of (IR, machine), so it is
        memoized per (model, n, tsteps, machine): repeat submissions
        of a warm request skip the analyzer entirely. The per-request
        preflight latency (memo hits included) lands in the
        `request_preflight_s` stage histogram."""
        from .. import analysis
        from ..runtime import telemetry
        from ..runtime.obs import metrics as obs_metrics

        t0 = time.perf_counter()
        if request.program is not None:
            # custom requests have no (model, n) address — memoize on
            # the canonical IR content instead, so identical documents
            # (whatever their JSON spelling) share one verdict
            from .fingerprint import content_digest, program_payload

            key = (CUSTOM_MODEL,
                   content_digest(program_payload(program)),
                   dataclasses.astuple(request.machine()))
        else:
            key = (request.model, request.n, request.tsteps,
                   dataclasses.astuple(request.machine()))
        summary = self._preflight_memo.get(key)
        if summary is None:
            with telemetry.span("ir_preflight", model=request.model,
                                program=program.name,
                                trace_id=request.trace_id):
                report = analysis.analyze_program(
                    program, request.machine()
                )
            summary = report.summary()
            if request.program is not None:
                # the structural signature (16-hex digest form) rides
                # the summary into the outcome and the ledger row, so
                # model:"custom" rows stay attributable to a nest
                # shape without replaying the document
                from .fingerprint import structure_digest

                summary = dict(summary)
                summary["signature"] = structure_digest(
                    report.signature)
            if len(self._preflight_memo) >= 256:
                self._preflight_memo.clear()
            self._preflight_memo[key] = summary
        obs_metrics.observe("request_preflight_s",
                            time.perf_counter() - t0,
                            exemplar=request.trace_id)
        if summary["verdict"] == analysis.VERDICT_INVALID:
            diags = summary.get("diagnostics") or []
            first = diags[0]
            msg = (f"ir preflight rejected {program.name!r}: "
                   f"{first['code']} at {first['path']}: "
                   f"{first['message']}")
            if len(diags) > 1:
                msg += f" (+{len(diags) - 1} more)"
            self.executor._count("preflight_rejected")
            self._ledger_rejection(request, msg)
            raise analysis.PreflightError(msg, diagnostics=diags)
        if summary.get("races"):
            self.executor._count("race_warnings", summary["races"])
        return summary

    def _ledger_rejection(self, request: AnalysisRequest,
                          msg: str) -> None:
        """One `preflight: invalid` request row per rejection — the
        ledger's view of the `ir_preflight_failures` counter
        (check_ledger --stats aggregates it). Never sinks the
        rejection response."""
        if not self.ledger_path:
            return
        from ..runtime.obs import ledger as obs_ledger

        row = {
            "kind": "request", "source": "service", "ok": False,
            "fingerprint": None,
            "engine_requested": request.engine, "engine_used": None,
            "model": request.model, "n": request.n,
            "latency_s": None, "cache": None, "degraded": [],
            "mrc_digest": None,
            "preflight": "invalid",
            "error": msg[:300],
        }
        if request.trace_id is not None:
            row["trace_id"] = request.trace_id
        try:
            obs_ledger.append(self.ledger_path, row)
            self.executor._count("ledger_rows")
        except Exception:
            self.executor._count("ledger_write_failed")

    def submit(self, request: AnalysisRequest,
               on_partial=None) -> AnalysisTicket:
        """Validate, preflight, fingerprint, and schedule (or join) a
        request. Raises ValueError/KeyError for malformed requests
        (PreflightError for invalid IR) — `serve` turns those into
        per-line error responses.

        `on_partial` (progressive-precision requests only) receives
        one interim-round doc per completed round of the (possibly
        shared) execution; see RequestExecutor.submit."""
        if request.program is not None:
            from ..frontend.parse import FrontendError

            try:
                program = request.build_program()
            except FrontendError as e:
                # the frontend's own gate (JSON shape / limits /
                # hostile bounds): counted separately from IR
                # preflight so operators can tell bad documents from
                # bad nests, but ledgered the same way
                self.executor._count("frontend_rejected")
                self._ledger_rejection(request, str(e))
                raise
        else:
            program = request.build_program()
        preflight = (
            self._run_preflight(request, program)
            if self.preflight else None
        )
        fp = request.fingerprint(program)
        fut = self.executor.submit(
            request, program, request.machine(), fp,
            preflight=preflight, on_partial=on_partial,
        )
        return AnalysisTicket(request=request, fingerprint=fp,
                              future=fut)

    def result(self, ticket: AnalysisTicket,
               timeout: float | None = None) -> AnalysisResponse:
        outcome = ticket.future.result(timeout=timeout)
        return _response_from_outcome(
            ticket.request, ticket.fingerprint, outcome
        )

    def analyze(self, request: AnalysisRequest,
                timeout: float | None = None) -> AnalysisResponse:
        return self.result(self.submit(request), timeout=timeout)

    def close(self) -> None:
        self.executor.shutdown()

    def __enter__(self) -> "AnalysisService":
        return self

    def __exit__(self, *exc) -> None:
        self.close()


CONTROL_TYPES = ("healthz", "stats", "metrics", "dump_debug")

# Control types answered in the RESPONSE pass (after every request
# line above them has been awaited) instead of as the line is read:
# `metrics` so its live-histogram snapshot is deterministic within a
# batch, `dump_debug` so the bundle's ring records include every
# request the batch completed before the dump line.
_DEFERRED_CONTROL_TYPES = ("metrics", "dump_debug")


def parse_request_line(line: str) -> AnalysisRequest:
    doc = json.loads(line)
    if not isinstance(doc, dict):
        raise ValueError("request line must be a JSON object")
    fields = {f.name for f in dataclasses.fields(AnalysisRequest)}
    unknown = set(doc) - fields
    if unknown:
        raise ValueError(
            f"unknown request fields: {', '.join(sorted(unknown))}"
        )
    if "program" in doc:
        # an inline document IS the scenario; a model/n/tsteps
        # address alongside it would be ambiguous
        clash = sorted({"model", "n", "tsteps"} & set(doc))
        if clash:
            raise ValueError(
                "'program' is mutually exclusive with "
                f"{', '.join(repr(c) for c in clash)}"
            )
        doc = dict(doc)
        doc["model"] = CUSTOM_MODEL
    elif "model" not in doc:
        raise ValueError(
            "request needs a 'model' (or an inline 'program')"
        )
    return AnalysisRequest(**doc)


def _error_msg(e: Exception) -> str:
    # KeyError's str() wraps the message in repr quotes; prefer the
    # raw message for every single-arg exception
    return str(e.args[0]) if len(e.args) == 1 else str(e)


def serve_jsonl(service: AnalysisService, in_stream: IO,
                out_stream: IO) -> int:
    """Process one JSONL request batch; returns the failure count.

    All parseable requests are submitted BEFORE any result is awaited,
    so duplicates inside the batch coalesce onto one execution, and
    responses come out in input order regardless of completion order.

    Robustness contract: NOTHING on a request line aborts the stream.
    Invalid JSON, a non-object line, unknown fields, a bad model, or
    an execution blow-up each yield one structured error response
    (`ok: false`, `line`, `error`) with the request `id` echoed
    whenever the line parsed far enough to carry one. `healthz` /
    `stats` lines (CONTROL_TYPES) answer inline from the service's
    introspection snapshot taken as the line is read; `metrics` and
    `dump_debug` lines evaluate at response time instead, after every
    request line above them has been awaited, so the live histograms
    (and the post-mortem bundle's ring records) they report are
    deterministic within a batch.

    Graceful shutdown: a GracefulShutdown raised into either pass
    (the CLI's SIGTERM/SIGINT handlers) stops reading, drains
    in-flight work to completion, and answers everything already
    submitted — finished results normally, queued-then-cancelled work
    with structured `shed: true` responses. Every submitted request
    resolves exactly once either way.

    Progressive-precision requests (tolerance / max_rounds /
    round_schedule set) additionally STREAM one `"partial": true` doc
    per completed round — `{"id", "partial": true, "round",
    "rounds_total", "band_width", "converged", "mrc_digest",
    "mrc_lines", ...}` — interleaved ahead of the in-order final
    responses (all writes share one lock, so lines never tear). The
    final response for such a request carries `rounds`/`band_width`/
    `converged`, plus `partial_final: true` with a `precision:*`
    degrade hop when its deadline expired mid-schedule.
    """
    # each entry: {"line", "id", and one of "ticket"+"request" |
    # "control" | "error"}
    entries: list[dict] = []
    # partial frames are written from executor threads while this
    # thread is still reading/awaiting: one lock serializes every
    # out_stream write
    wlock = threading.Lock()

    def _write(doc: dict) -> None:
        with wlock:
            out_stream.write(json.dumps(doc) + "\n")
            out_stream.flush()

    def _partial_writer(req_id):
        def cb(doc: dict) -> None:
            msg = dict(doc)
            msg["id"] = req_id
            _write(msg)
        return cb
    try:
        for line_no, line in enumerate(in_stream, start=1):
            line = line.strip()
            if not line:
                continue
            entry: dict = {"line": line_no, "id": None}
            entries.append(entry)
            if len(line) > MAX_REQUEST_LINE_BYTES:
                # refused before json.loads: the size cap is the OOM
                # guard, so the oversize payload is never materialized
                # as objects. Best-effort id echo from the head only.
                m = re.search(r'"id"\s*:\s*"([^"\\]{1,120})"',
                              line[:4096])
                if m:
                    entry["id"] = m.group(1)
                entry["error"] = (
                    f"request line of {len(line)} bytes exceeds the "
                    f"{MAX_REQUEST_LINE_BYTES}-byte limit"
                )
                service.executor._count("frontend_rejected")
                continue
            try:
                # chaos site: a raise-kind fault on this line is one
                # structured error response, never a stream abort —
                # the same robustness contract malformed JSON gets
                faults.fire("serve_line", key=line_no)
                doc = json.loads(line)
            except faults.FaultInjected as e:
                entry["error"] = f"fault injected: {e}"
                continue
            except RecursionError:
                # hostile nesting deep enough to blow the json
                # parser's stack — same refusal as any bad document
                m = re.search(r'"id"\s*:\s*"([^"\\]{1,120})"',
                              line[:4096])
                if m:
                    entry["id"] = m.group(1)
                entry["error"] = "invalid JSON: nesting too deep"
                service.executor._count("frontend_rejected")
                continue
            except ValueError as e:
                entry["error"] = f"invalid JSON: {e}"
                continue
            if isinstance(doc, dict):
                # echo the id on EVERY response for this line, even
                # when the rest of the request is malformed
                entry["id"] = doc.get("id")
            if isinstance(doc, dict) and doc.get("type") is not None:
                kind = doc.get("type")
                if kind not in CONTROL_TYPES:
                    entry["error"] = (
                        f"unknown request type {kind!r} "
                        f"(have {', '.join(CONTROL_TYPES)})"
                    )
                    continue
                if kind in _DEFERRED_CONTROL_TYPES:
                    # deferred to the response pass: every request
                    # line ABOVE this one has been awaited by then,
                    # so a metrics snapshot deterministically includes
                    # their stage histograms and a dump_debug bundle
                    # includes their ring records (read-time
                    # evaluation would race with worker completion)
                    entry["control"] = {"type": kind, "payload": None}
                    continue
                try:
                    payload = (
                        service.healthz() if kind == "healthz"
                        else service.stats()
                    )
                    entry["control"] = {"type": kind,
                                        "payload": payload}
                except Exception as e:
                    entry["error"] = f"introspection failed: {e!r}"
                continue
            try:
                request = parse_request_line(line)
                cb = None
                if progressive_requested(request):
                    cb = _partial_writer(request.id)
                entry["ticket"] = service.submit(request, on_partial=cb)
                entry["request"] = request
            except Exception as e:
                entry["error"] = _error_msg(e)
                # preflight rejections carry machine-readable
                # diagnostics (code / nest-ref path / message) —
                # surface them on the structured error response
                diags = getattr(e, "diagnostics", None)
                if diags:
                    entry["diagnostics"] = diags
    except GracefulShutdown:
        # stop READING and start draining; every line read so far
        # still gets its response below (in-flight work finishes,
        # queued work sheds). If the interrupted line never produced
        # an entry beyond the placeholder, answer it as shed too.
        service.begin_shutdown()
        if entries and not any(
            k in entries[-1] for k in ("ticket", "control", "error")
        ):
            entries[-1]["error"] = (
                "shed: service shutting down (line not processed)"
            )
            entries[-1]["shed"] = True
    failures = 0
    for entry in entries:
        if "control" in entry:
            payload = entry["control"]["payload"]
            kind = entry["control"]["type"]
            if kind in _DEFERRED_CONTROL_TYPES:
                try:
                    payload = (
                        service.metrics() if kind == "metrics"
                        else service.dump_debug()
                    )
                except Exception as e:
                    payload = {"enabled": False,
                               "error": f"introspection failed: {e!r}"}
            doc = {
                "id": entry["id"],
                "ok": True,
                "type": entry["control"]["type"],
                entry["control"]["type"]: payload,
            }
        elif "ticket" in entry:
            while True:
                try:
                    response = service.result(entry["ticket"])
                    doc = response.to_jsonl_dict()
                except GracefulShutdown:
                    # the signal landed while awaiting a result:
                    # enter the drain and keep answering — every
                    # submitted entry still gets exactly one response
                    service.begin_shutdown()
                    continue
                except CancelledError:
                    # this entry's queued work was cancelled by the
                    # drain before it started executing
                    doc = {
                        "id": entry["request"].id,
                        "ok": False,
                        "line": entry["line"],
                        "shed": True,
                        "error": ("shed: service shutting down "
                                  "(queued request cancelled)"),
                    }
                except Exception as e:
                    # a result()/serialization blow-up is THIS
                    # request's error, never the batch's
                    doc = {
                        "id": entry["request"].id,
                        "ok": False,
                        "line": entry["line"],
                        "error": f"execution failed: {e!r}",
                    }
                break
            if not doc.get("ok"):
                failures += 1
        else:
            failures += 1
            doc = {
                "id": entry["id"],
                "ok": False,
                "line": entry["line"],
                "error": entry["error"],
            }
            if entry.get("diagnostics"):
                doc["diagnostics"] = entry["diagnostics"]
            if entry.get("shed"):
                doc["shed"] = True
        _write(doc)
    return failures
