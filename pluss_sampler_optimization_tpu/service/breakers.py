"""Circuit breakers with half-open probation.

One `CircuitBreaker` guards one failure domain — the executor keeps
one per ENGINE (an engine whose attempts keep failing is skipped
cheaply down the degrade chain instead of burning an attempt budget
per request), and the replica pool embeds the same state machine per
REPLICA (service/replicas.py), replacing the one-shot quarantine of
PR 10 with recover-after-probe.

State machine:

    closed      normal service; `failures` CONSECUTIVE failures open
    open        fail fast for `probation_s`; no attempts pass
    half_open   probation elapsed: exactly ONE probe is admitted.
                Probe success -> closed (probation resets); probe
                failure -> open again with probation escalated
                (x escalation, capped at probation_max_s)

All transitions are reported back to the caller (`failure()` returns
True when it OPENED the breaker, `success()` returns True when it
RE-CLOSED it) so the owner can count breaker_opened /
breaker_reclosed on its own counter surfaces without the breaker
knowing about telemetry. The clock is injectable for tests.
"""

from __future__ import annotations

import threading
import time

from ..runtime import lockwitness


class CircuitBreaker:
    """Thread-safe closed/open/half-open breaker."""

    def __init__(self, failures: int = 8, probation_s: float = 30.0,
                 escalation: float = 2.0,
                 probation_max_s: float = 300.0,
                 clock=time.monotonic):
        self.failures = max(1, int(failures))
        self.base_probation_s = float(probation_s)
        self.escalation = float(escalation)
        self.probation_max_s = float(probation_max_s)
        self._clock = clock
        self._lock = lockwitness.make_lock("CircuitBreaker._lock")
        self._state = "closed"
        self._consecutive = 0
        self._probation_s = self.base_probation_s
        self._reopen_at = 0.0
        self._opened = 0
        self._reclosed = 0

    # -- introspection ------------------------------------------------

    def state(self) -> str:
        """Current state; an open breaker past its probation reports
        half_open (the next allow() admits the probe)."""
        with self._lock:
            if (self._state == "open"
                    and self._clock() >= self._reopen_at):
                return "half_open"
            return self._state

    def snapshot(self) -> dict:
        with self._lock:
            out = {
                "state": self._state,
                "consecutive_failures": self._consecutive,
                "opened": self._opened,
                "reclosed": self._reclosed,
            }
            if self._state == "open":
                out["reopen_in_s"] = round(
                    max(0.0, self._reopen_at - self._clock()), 3
                )
            return out

    # -- the gate -----------------------------------------------------

    def allow(self) -> bool:
        """May one attempt proceed now? Closed: always. Open: only
        once probation has elapsed, and then exactly one caller wins
        the half-open probe slot until success()/failure() resolves
        it."""
        with self._lock:
            if self._state == "closed":
                return True
            if self._state == "half_open":
                return False  # a probe is already in flight
            if self._clock() >= self._reopen_at:
                self._state = "half_open"
                return True
            return False

    def success(self) -> bool:
        """Record a success; True when this re-closed an open/half-
        open breaker (the probe succeeded)."""
        with self._lock:
            reclosed = self._state != "closed"
            self._state = "closed"
            self._consecutive = 0
            self._probation_s = self.base_probation_s
            if reclosed:
                self._reclosed += 1
            return reclosed

    def failure(self) -> bool:
        """Record a failure; True when this opened (or re-opened) the
        breaker."""
        with self._lock:
            if self._state == "half_open":
                # failed probe: back to open, probation escalated
                self._probation_s = min(
                    self._probation_s * self.escalation,
                    self.probation_max_s,
                )
                self._state = "open"
                self._reopen_at = self._clock() + self._probation_s
                self._opened += 1
                return True
            if self._state == "open":
                return False
            self._consecutive += 1
            if self._consecutive >= self.failures:
                self._state = "open"
                self._reopen_at = self._clock() + self._probation_s
                self._opened += 1
                return True
            return False
