"""Two-tier content-addressed result store.

Tier 1 is a bounded in-memory LRU (OrderedDict, same discipline as the
kernel signature caches in sampler/sampled.py); tier 2 is an on-disk
store addressed by fingerprint — `<dir>/<fp[:2]>/<fp>.json`, the
standard content-address fan-out so a hot directory never accumulates
hundreds of thousands of siblings.

Records are versioned JSON (STORE_VERSION) written atomically
(runtime/io.py::atomic_write_json — a killed process never leaves a
truncated record). Loads are corruption-tolerant by contract: any
unreadable/unparseable/wrong-version/mis-addressed record is a MISS
(counted as `service_cache_corrupt`), never an exception — the
executor simply recomputes and overwrites. A corrupt file is also
QUARANTINED: atomically renamed to `<fp>.json.corrupt` (counted
`cache_corrupt_quarantined`), so a record that keeps failing
validation is parsed once, not on every subsequent hit, and the
damaged bytes survive for post-mortem while `put` rewrites the live
address. `tools/check_service_store.py` audits and garbage-collects
a store offline with the same validation.

Chaos: the disk tier carries the `cache_load` / `cache_store`
injection sites (runtime/faults.py): a corrupt-kind fault mangles the
just-parsed record (driving the real quarantine path end to end), a
raise-kind store fault exercises the degrade-to-memory-only path.
Both are inert no-ops unless an injector is installed.

Telemetry: `service_cache_hit_mem` / `service_cache_hit_disk` /
`service_cache_miss` / `service_cache_corrupt` /
`service_cache_corrupt_quarantined` / `service_cache_evictions`
counters land in the active run, so a serve session's JSON export
shows its hit ratio next to the engines' own dispatch counters.
"""

from __future__ import annotations

import collections
import json
import os
import threading

from ..runtime import faults, lockwitness, telemetry
from ..runtime.io import atomic_write_json

# Version of the RESULT RECORD shape (the dict produced by
# service/executor.py::execute_request). Bump together with any change
# to that shape; fingerprint.FINGERPRINT_VERSION covers the KEY side.
STORE_VERSION = 1

# Keys every stored record must carry to be served from cache.
REQUIRED_KEYS = (
    "store_version",
    "fingerprint",
    "engine_used",
    "total_accesses",
    "access_label",
    "rih",
    "mrc",
    "dump_lines",
    "created_at",
)


def validate_record(record, fingerprint: str | None = None) -> list[str]:
    """All schema violations of one parsed record (empty = valid).

    Single source of truth for the in-process load path AND the
    offline store checker (tools/check_service_store.py), exactly the
    pattern tools/check_telemetry_schema.py::validate set.
    """
    errors: list[str] = []
    if not isinstance(record, dict):
        return ["record is not a JSON object"]
    if record.get("store_version") != STORE_VERSION:
        errors.append(
            f"store_version must be {STORE_VERSION}, got "
            f"{record.get('store_version')!r}"
        )
    for key in REQUIRED_KEYS:
        if key not in record:
            errors.append(f"missing required key '{key}'")
    if fingerprint is not None and record.get("fingerprint") != fingerprint:
        errors.append(
            f"fingerprint mismatch: record says "
            f"{record.get('fingerprint')!r}, address is {fingerprint!r}"
        )
    mrc = record.get("mrc")
    if not (
        isinstance(mrc, list)
        and all(
            isinstance(v, (int, float)) and not isinstance(v, bool)
            for v in mrc
        )
    ):
        errors.append("'mrc' must be a list of numbers")
    rih = record.get("rih")
    if not (
        isinstance(rih, dict)
        and all(
            isinstance(k, str)
            and isinstance(v, (int, float))
            and not isinstance(v, bool)
            for k, v in rih.items()
        )
    ):
        errors.append("'rih' must be an object of numeric counts")
    if not isinstance(record.get("dump_lines"), list) or not all(
        isinstance(ln, str) for ln in record.get("dump_lines", [])
    ):
        errors.append("'dump_lines' must be a list of strings")
    ta = record.get("total_accesses")
    if not isinstance(ta, (int, float)) or isinstance(ta, bool):
        errors.append("'total_accesses' must be a number")
    if not isinstance(record.get("engine_used"), str):
        errors.append("'engine_used' must be a string")
    return errors


class ResultCache:
    """Thread-safe two-tier store; `cache_dir=None` is memory-only."""

    def __init__(self, cache_dir: str | None = None,
                 mem_entries: int = 128):
        self.cache_dir = os.fspath(cache_dir) if cache_dir else None
        self.mem_entries = mem_entries
        self._mem: collections.OrderedDict = collections.OrderedDict()
        self._lock = lockwitness.make_lock("ResultCache._lock")
        # instance-local mirror of the telemetry counters: the serve
        # introspection protocol (`stats` request) must report cache
        # health even when no telemetry run is active
        self._stats = collections.Counter()
        if self.cache_dir:
            os.makedirs(self.cache_dir, exist_ok=True)

    def stats(self) -> dict:
        """Lifetime counters + current occupancy, for the service's
        `stats` introspection response."""
        with self._lock:
            out = dict(self._stats)
            out.setdefault("hit_mem", 0)
            out.setdefault("hit_disk", 0)
            out.setdefault("miss", 0)
            out.setdefault("corrupt", 0)
            out.setdefault("corrupt_quarantined", 0)
            out.setdefault("evictions", 0)
            out.setdefault("write_failed", 0)
            out["mem_entries"] = len(self._mem)
        out["mem_capacity"] = self.mem_entries
        out["disk_tier"] = bool(self.cache_dir)
        return out

    def _count(self, key: str) -> None:
        with self._lock:
            self._stats[key] += 1

    def path_for(self, fingerprint: str) -> str:
        if not self.cache_dir:
            raise ValueError("cache has no disk tier")
        return os.path.join(
            self.cache_dir, fingerprint[:2], fingerprint + ".json"
        )

    # -- lookup -------------------------------------------------------

    def get(self, fingerprint: str):
        """(record, tier) with tier in {"mem", "disk"}, or (None,
        "miss"). Corrupt disk entries are misses; the caller
        recomputes and `put` overwrites them."""
        with self._lock:
            rec = self._mem.get(fingerprint)
            if rec is not None:
                self._mem.move_to_end(fingerprint)
                self._stats["hit_mem"] += 1
        if rec is not None:
            # sink emission stays outside the critical section: the
            # metrics registry has its own lock and the flight
            # recorder does real work (C_SINK_UNDER_LOCK)
            telemetry.count("service_cache_hit_mem")
            return rec, "mem"
        if self.cache_dir:
            rec = self._load_disk(fingerprint)
            if rec is not None:
                with self._lock:
                    evicted = self._mem_put_locked(fingerprint, rec)
                self._emit_evictions(evicted)
                self._count("hit_disk")
                telemetry.count("service_cache_hit_disk")
                return rec, "disk"
        self._count("miss")
        telemetry.count("service_cache_miss")
        return None, "miss"

    def _load_disk(self, fingerprint: str):
        path = self.path_for(fingerprint)
        try:
            with open(path) as f:
                rec = json.load(f)
        except FileNotFoundError:
            return None
        except (OSError, ValueError):
            self._corrupt(path)
            return None
        rec = faults.mangle("cache_load", rec, key=fingerprint)
        if validate_record(rec, fingerprint):
            self._corrupt(path)
            return None
        return rec

    def _corrupt(self, path: str) -> None:
        """Count one corrupt record and quarantine the file: an atomic
        rename to `*.corrupt` so the bad bytes are (a) never re-parsed
        on the next lookup — the address misses cleanly until `put`
        rewrites it — and (b) preserved for offline post-mortem
        (tools/check_service_store.py reports them as stray files)."""
        self._count("corrupt")
        telemetry.count("service_cache_corrupt")
        try:
            os.replace(path, path + ".corrupt")
        except OSError:
            return
        self._count("corrupt_quarantined")
        telemetry.count("service_cache_corrupt_quarantined")

    # -- store --------------------------------------------------------

    def put(self, fingerprint: str, record: dict) -> None:
        with self._lock:
            evicted = self._mem_put_locked(fingerprint, record)
        self._emit_evictions(evicted)
        if self.cache_dir:
            path = self.path_for(fingerprint)
            os.makedirs(os.path.dirname(path), exist_ok=True)
            try:
                faults.fire("cache_store", key=fingerprint)
                atomic_write_json(path, record)
            except (OSError, faults.FaultInjected):
                # a full/readonly disk (or an injected store fault)
                # degrades to memory-only serving; the result itself
                # still reaches the caller
                self._count("write_failed")
                telemetry.count("service_cache_write_failed")

    def _mem_put_locked(self, fingerprint: str, record: dict) -> int:
        """Install + LRU-evict; caller holds `_lock`. Returns the
        eviction count so the caller can emit telemetry after
        release."""
        self._mem[fingerprint] = record
        self._mem.move_to_end(fingerprint)
        evicted = 0
        while len(self._mem) > self.mem_entries:
            self._mem.popitem(last=False)
            self._stats["evictions"] += 1
            evicted += 1
        return evicted

    @staticmethod
    def _emit_evictions(evicted: int) -> None:
        for _ in range(evicted):
            telemetry.count("service_cache_evictions")
