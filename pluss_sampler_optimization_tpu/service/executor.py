"""Request execution: engines behind a degradation chain, under
singleflight coalescing, bounded concurrency, and per-request
deadlines.

This is the layer that turns "a sampler you run" into "a service you
query":

- **One pipeline per request.** `execute_request` runs the selected
  engine, folds the state through the reference pipeline
  (cri_distribute -> aet_mrc), and assembles the versioned result
  record service/cache.py stores — including the byte-exact acc dump
  lines, so a cache hit can serve the CLI's accuracy protocol without
  touching an engine.
- **Deadline-driven degradation.** Each request may carry a deadline;
  when the preferred engine fails or overruns it, the executor falls
  down the chain (exact -> sampled, periodic -> analytic -> sampled,
  ...) and records every downgrade in the response AND as a
  `service_degraded` telemetry event. An overrun attempt is abandoned
  (its thread finishes into the void — Python cannot cancel a running
  XLA dispatch), counted as `service_deadline_abandoned`. Degraded
  results are NOT written to the persistent cache: the fingerprint
  addresses the canonical result of the REQUESTED engine, and a
  sampled stand-in must not masquerade as it on the next warm hit.
- **Singleflight.** N identical in-flight requests coalesce onto one
  execution future keyed by fingerprint; every caller shares the one
  result (counted as `service_coalesced`). Combined with the cache
  this gives the acceptance invariant: a warm repeat performs ZERO
  engine executions, and N concurrent identical submissions perform
  exactly ONE.
- **Bounded concurrency.** A ThreadPoolExecutor caps concurrent
  pipelines; `service_queue_depth` gauges the in-flight count.

The engine table and the runner hook are module-level / constructor
injection points so tests can wrap them (e.g. add a barrier to force
overlap, or a sleep to force a deadline) without monkeypatching
engine internals.
"""

from __future__ import annotations

import collections
import threading
import time
from concurrent.futures import Future, ThreadPoolExecutor

from ..config import MachineConfig, SamplerConfig
from ..ir import Program
from ..runtime import report, telemetry
from ..runtime.aet import aet_mrc
from ..runtime.cri import cri_distribute
from ..runtime.obs import ledger as obs_ledger
from .cache import STORE_VERSION, ResultCache

# Fallback order per requested engine: the exact family degrades
# toward the sampled engine (cheap, approximate, always applicable).
# Engines absent here (oracle, numpy, sampled, ...) have no fallback —
# a failure is the response's error.
DEGRADE_CHAINS = {
    "exact": ("exact", "sampled"),
    "periodic": ("periodic", "analytic", "sampled"),
    "analytic": ("analytic", "sampled"),
    "dense": ("dense", "stream", "sampled"),
    "stream": ("stream", "sampled"),
}

SERVICE_ENGINES = (
    "oracle", "numpy", "dense", "stream", "periodic", "analytic",
    "exact", "sampled",
)


def degrade_chain(engine: str) -> tuple[str, ...]:
    return DEGRADE_CHAINS.get(engine, (engine,))


def default_runner(engine: str, program: Program,
                   machine: MachineConfig, request):
    """Run one engine -> (result-with-.state/.total_accesses, per_ref).

    The same engine dispatch cli.py::_run_engine performs, restricted
    to the service's request schema (no r10/checkpoint/shard knobs)."""
    v2 = request.runtime == "v2"
    if engine == "oracle":
        from ..oracle.serial import run_serial

        return run_serial(program, machine, v2=v2), None
    if engine == "numpy":
        from ..oracle.numpy_ref import run_numpy

        return run_numpy(program, machine), None
    if engine == "dense":
        from ..sampler.dense import run_dense

        return run_dense(program, machine), None
    if engine == "stream":
        from ..sampler.stream import run_stream

        return run_stream(program, machine), None
    if engine == "periodic":
        from ..sampler.periodic import run_periodic

        return run_periodic(program, machine), None
    if engine == "analytic":
        from ..sampler.analytic import run_analytic

        return run_analytic(program, machine), None
    if engine == "exact":
        from ..sampler.periodic import run_exact

        return run_exact(program, machine), None
    if engine == "sampled":
        import types

        from ..sampler.sampled import run_sampled

        kw = {}
        if request.device_draw is not None:
            kw["device_draw"] = request.device_draw
        if request.fuse_refs is not None:
            kw["fuse_refs"] = request.fuse_refs
        if request.pipeline_depth is not None:
            kw["pipeline_depth"] = request.pipeline_depth
        cfg = SamplerConfig(
            ratio=request.ratio, seed=request.seed, **kw
        )
        state, results = run_sampled(program, machine, cfg, v2=v2)
        res = types.SimpleNamespace(
            state=state,
            total_accesses=sum(r.n_samples for r in results),
            engine="sampled",
        )
        return res, results
    raise ValueError(f"unknown service engine {engine!r}")


def execute_request(request, program: Program, machine: MachineConfig,
                    engine: str, fingerprint: str,
                    runner=default_runner) -> dict:
    """One engine execution folded into a versioned result record.

    `engine` is the chain element actually being attempted (it may
    differ from request.engine after degradation)."""
    telemetry.count("service_exec_started")
    with telemetry.span("service_exec", engine=engine,
                        program=program.name):
        res, per_ref = runner(engine, program, machine, request)
        rih = cri_distribute(
            res.state, machine.thread_num, machine.thread_num
        )
        mrc = aet_mrc(rih, machine)
    telemetry.count("service_exec_done")
    label = "samples" if per_ref is not None else "accesses"
    dump_lines = []
    dump_lines += report.noshare_dump(res.state)
    dump_lines += report.share_dump(res.state)
    dump_lines += report.rih_dump(rih)
    dump_lines += report.mrc_lines(mrc)
    dump_lines.append(
        f"max iteration count: {res.total_accesses} {label}"
    )
    record = {
        "store_version": STORE_VERSION,
        "fingerprint": fingerprint,
        "request": request.payload(),
        "engine_requested": request.engine,
        "engine_used": getattr(res, "engine", None) or engine,
        "total_accesses": int(res.total_accesses),
        "access_label": label,
        "rih": {str(k): float(v) for k, v in sorted(rih.items())},
        "mrc": [float(v) for v in mrc],
        "dump_lines": dump_lines,
        "created_at": time.time(),
    }
    if per_ref is not None:
        record["per_ref_lines"] = [
            f"ref {r.name}: {r.n_samples} samples, cold {r.cold:g}"
            for r in per_ref
        ]
    return record


class RequestExecutor:
    """Singleflight + bounded concurrency + deadlines over
    `execute_request`. One instance backs one AnalysisService."""

    def __init__(self, cache: ResultCache | None = None,
                 max_workers: int = 4, runner=default_runner,
                 ledger_path: str | None = None):
        self.cache = cache if cache is not None else ResultCache()
        self.runner = runner
        self.max_workers = max_workers
        self.ledger_path = ledger_path
        self._pool = ThreadPoolExecutor(
            max_workers=max_workers,
            thread_name_prefix="pluss-service",
        )
        self._inflight: dict[str, Future] = {}
        self._lock = threading.Lock()
        # instance-local counters backing the serve `stats`/`healthz`
        # introspection protocol — telemetry counters only exist while
        # a run is enabled, but a long-lived service must answer
        # introspection requests at any time
        self._stats = collections.Counter()
        if ledger_path:
            # compile-counter deltas in ledger rows need the
            # process-global jax.monitoring listeners; without jax the
            # deltas simply stay empty
            try:
                telemetry.register_jax_hooks()
            except Exception:
                pass

    def stats(self) -> dict:
        """Executor health snapshot: queue depth (submitted futures
        not yet executing), in-flight count, singleflight coalesces,
        and the lifetime execution/degradation counters."""
        with self._lock:
            out = dict(self._stats)
            inflight = len(self._inflight)
        for key in ("submitted", "coalesced", "completed", "failed",
                    "degraded", "deadline_abandoned", "active",
                    "ledger_rows", "ledger_write_failed"):
            out.setdefault(key, 0)
        active = out.pop("active")
        out["in_flight"] = inflight
        out["executing"] = active
        out["queue_depth"] = max(0, inflight - active)
        out["max_workers"] = self.max_workers
        return out

    def _count(self, key: str, inc: int = 1) -> None:
        with self._lock:
            self._stats[key] += inc

    # -- public -------------------------------------------------------

    def submit(self, request, program: Program,
               machine: MachineConfig, fingerprint: str) -> Future:
        """Schedule (or join) the execution for one fingerprint.

        The returned future resolves to the full response dict (record
        + serving metadata). Identical fingerprints submitted while
        one is in flight share its future."""
        telemetry.count("service_requests")
        with self._lock:
            self._stats["submitted"] += 1
            fut = self._inflight.get(fingerprint)
            if fut is not None:
                self._stats["coalesced"] += 1
                telemetry.count("service_coalesced")
                return fut
            fut = self._pool.submit(
                self._process, request, program, machine, fingerprint
            )
            self._inflight[fingerprint] = fut
            telemetry.gauge("service_queue_depth", len(self._inflight))

        def _done(_f, fp=fingerprint):
            with self._lock:
                self._inflight.pop(fp, None)
                telemetry.gauge(
                    "service_queue_depth", len(self._inflight)
                )

        # registered OUTSIDE the lock: a future that already finished
        # runs the callback synchronously on this thread, and the
        # callback itself takes the lock
        fut.add_done_callback(_done)
        return fut

    def shutdown(self) -> None:
        self._pool.shutdown(wait=True)

    # -- worker -------------------------------------------------------

    def _process(self, request, program, machine,
                 fingerprint: str) -> dict:
        t0 = time.perf_counter()
        self._count("active")
        compiles0 = (
            telemetry.compile_counters_snapshot()
            if self.ledger_path else None
        )
        try:
            with telemetry.span("service_request",
                                engine=request.engine,
                                program=program.name):
                record, tier = self.cache.get(fingerprint)
                degraded: list[dict] = []
                error = None
                if record is None:
                    record, degraded, error = self._run_chain(
                        request, program, machine, fingerprint
                    )
                    if record is not None and not degraded:
                        self.cache.put(fingerprint, record)
        finally:
            self._count("active", -1)
        self._count("completed" if record is not None else "failed")
        outcome = {
            "record": record,
            "cache": tier,
            "degraded": degraded,
            "error": error,
            "latency_s": round(time.perf_counter() - t0, 6),
            "mrc_digest": (
                obs_ledger.mrc_digest(record["mrc"])
                if record is not None else None
            ),
        }
        if self.ledger_path:
            self._append_ledger_row(
                request, fingerprint, outcome, compiles0
            )
        return outcome

    def _append_ledger_row(self, request, fingerprint: str,
                           outcome: dict, compiles0: dict) -> None:
        """One ledger row per execution (cache hits included, since a
        served response is an execution of the SERVICE even when the
        engine never ran; coalesced callers share the executing row).
        A ledger failure must never sink the request — it is counted
        and dropped."""
        record = outcome["record"]
        now = telemetry.compile_counters_snapshot()
        compile_delta = {
            k: v - compiles0.get(k, 0)
            for k, v in now.items()
            if v - compiles0.get(k, 0)
        }
        row = {
            "kind": "request",
            "source": "service",
            "ok": record is not None,
            "fingerprint": fingerprint,
            "engine_requested": request.engine,
            "engine_used": (
                record.get("engine_used") if record else None
            ),
            "model": request.model,
            "n": request.n,
            "latency_s": outcome["latency_s"],
            "cache": outcome["cache"],
            "degraded": outcome["degraded"],
            "compile_delta": {
                k: round(v, 4) if isinstance(v, float) else v
                for k, v in compile_delta.items()
            },
            "mrc_digest": outcome["mrc_digest"],
        }
        if outcome["error"] is not None:
            row["error"] = str(outcome["error"])[:300]
        try:
            obs_ledger.append(self.ledger_path, row)
            self._count("ledger_rows")
        except Exception:
            self._count("ledger_write_failed")
            telemetry.count("service_ledger_write_failed")

    def _run_chain(self, request, program, machine, fingerprint):
        """Walk the degradation chain under the request deadline.
        Returns (record|None, degraded events, error|None)."""
        chain = degrade_chain(request.engine)
        deadline = (
            None if request.deadline_s is None
            else time.perf_counter() + request.deadline_s
        )
        degraded: list[dict] = []
        last_error = None
        for i, engine in enumerate(chain):
            is_last = i == len(chain) - 1
            remaining = (
                None if deadline is None
                else deadline - time.perf_counter()
            )
            if remaining is not None and remaining <= 0 and not is_last:
                # budget already spent: jump toward the cheapest
                # engine rather than starting one we would abandon
                self._note_degrade(
                    degraded, fingerprint, engine, chain[i + 1],
                    "deadline exhausted before attempt",
                )
                continue
            try:
                if remaining is None or is_last:
                    # no budget to enforce (or nothing to fall back
                    # to): run inline on this worker
                    return (
                        execute_request(
                            request, program, machine, engine,
                            fingerprint, self.runner,
                        ),
                        degraded,
                        None,
                    )
                record = self._attempt_with_timeout(
                    request, program, machine, engine, fingerprint,
                    remaining,
                )
                if record is not None:
                    return record, degraded, None
                self._note_degrade(
                    degraded, fingerprint, engine, chain[i + 1],
                    f"deadline {request.deadline_s}s overrun",
                )
            except Exception as e:
                last_error = repr(e)
                telemetry.count("service_exec_failed")
                if is_last:
                    return None, degraded, last_error
                self._note_degrade(
                    degraded, fingerprint, engine, chain[i + 1],
                    f"engine failed: {last_error[:200]}",
                )
        return None, degraded, last_error or "no engine attempted"

    def _attempt_with_timeout(self, request, program, machine, engine,
                              fingerprint, budget_s: float):
        """Run one attempt in a side thread and wait at most budget_s.
        None = overrun (the attempt thread is abandoned; Python offers
        no preemption, so its work completes unobserved)."""
        box: dict = {}

        def target():
            try:
                box["record"] = execute_request(
                    request, program, machine, engine, fingerprint,
                    self.runner,
                )
            except Exception as e:
                box["error"] = e

        t = threading.Thread(
            target=target, daemon=True,
            name=f"pluss-service-attempt-{engine}",
        )
        t.start()
        t.join(budget_s)
        if t.is_alive():
            self._count("deadline_abandoned")
            telemetry.count("service_deadline_abandoned")
            return None
        if "error" in box:
            raise box["error"]
        return box["record"]

    def _note_degrade(self, degraded, fingerprint, from_engine,
                      to_engine, reason: str) -> None:
        info = {
            "from": from_engine,
            "to": to_engine,
            "reason": reason,
        }
        degraded.append(info)
        self._count("degraded")
        telemetry.count("service_degraded")
        telemetry.event(
            "service_degraded", fingerprint=fingerprint, **info
        )
