"""Request execution: engines behind a degradation chain, under
singleflight coalescing, bounded concurrency, and per-request
deadlines.

This is the layer that turns "a sampler you run" into "a service you
query":

- **One pipeline per request.** `execute_request` runs the selected
  engine, folds the state through the reference pipeline
  (cri_distribute -> aet_mrc), and assembles the versioned result
  record service/cache.py stores — including the byte-exact acc dump
  lines, so a cache hit can serve the CLI's accuracy protocol without
  touching an engine.
- **Deadline-driven degradation.** Each request may carry a deadline;
  when the preferred engine fails or overruns it, the executor falls
  down the chain (exact -> sampled, periodic -> analytic -> sampled,
  ...) and records every downgrade in the response AND as a
  `service_degraded` telemetry event. An overrun attempt is abandoned
  (its thread finishes into the void — Python cannot cancel a running
  XLA dispatch), counted as `service_deadline_abandoned`. Degraded
  results are NOT written to the persistent cache: the fingerprint
  addresses the canonical result of the REQUESTED engine, and a
  sampled stand-in must not masquerade as it on the next warm hit.
- **Singleflight.** N identical in-flight requests coalesce onto one
  execution future keyed by fingerprint; every caller shares the one
  result (counted as `service_coalesced`). Combined with the cache
  this gives the acceptance invariant: a warm repeat performs ZERO
  engine executions, and N concurrent identical submissions perform
  exactly ONE.
- **Bounded concurrency.** A ThreadPoolExecutor caps concurrent
  pipelines; `service_queue_depth` gauges the in-flight count.
- **Replica routing.** With a replica pool configured
  (service/replicas.py), every engine execution — a solo chain
  attempt or a whole flushed batch window — runs inside ONE replica's
  device scope: least-loaded routing, work stealing between idle
  replicas, and failure quarantine. A quarantine re-route lands in
  the request's degradation chain (`{"from": "replica:K", ...}`), so
  the completion is counted `service_degraded` and the SLO sentinel's
  error budget sees it; like other degraded results it is never
  persisted to the cache. max_workers is clamped UP to the replica
  count — fewer pool threads than replicas would strand replicas
  idle with work queued behind busy ones.
- **Resilience (config.py::ResilienceConfig).** Four layers, all
  off/neutral by default and all pure serving policy (never in the
  fingerprint; retried/hedged results are seed-derived and therefore
  bit-identical — tools/check_chaos.py pins it):
  * per-attempt timeouts + bounded retry with deterministic seeded
    exponential backoff (runtime/faults.py::backoff_delay — jitter
    from a counter hash, never the wall clock);
  * hedged dispatch: a routed execution still unresolved after
    `hedge_after_s` is duplicated onto a second replica; first result
    wins, the still-queued loser is cancelled
    (`service_hedged`/`service_hedge_wins`);
  * per-engine circuit breakers (service/breakers.py) with half-open
    probation: a repeatedly-failing engine is skipped cheaply down
    the degrade chain (`service_breaker_open_skips`) until a probe
    re-closes it — the replica pool runs the same state machine per
    replica;
  * admission control: with a `queue_limit`, a submit that would
    queue past its priority class's share is SHED at the gate —
    a structured `shed: true` outcome in microseconds instead of a
    deadline timeout after seconds of queueing (`service_shed`).
  Every outcome (retried/hedged/shed/broken-open) is counted on all
  three counter surfaces and stamped on the request's ledger row.
- **Chaos.** Engine attempts pass the `engine_execute` fault-
  injection site (runtime/faults.py) — a no-op unless a chaos spec is
  installed, so the default path stays zero-overhead and
  bit-identical.

The engine table and the runner hook are module-level / constructor
injection points so tests can wrap them (e.g. add a barrier to force
overlap, or a sleep to force a deadline) without monkeypatching
engine internals.
"""

from __future__ import annotations

import collections
import dataclasses
import math
import threading
import time
import uuid
from concurrent.futures import (
    FIRST_COMPLETED,
    Future,
    ThreadPoolExecutor,
    TimeoutError as FuturesTimeoutError,
    wait as futures_wait,
)

from ..config import (
    BatchConfig, MachineConfig, ReplicaConfig, ResilienceConfig,
    SamplerConfig,
)
from ..ir import Program
from ..runtime import faults, lockwitness, report, telemetry
from ..runtime.aet import aet_mrc
from ..runtime.cri import cri_distribute
from ..runtime.obs import ledger as obs_ledger
from .breakers import CircuitBreaker
from .cache import STORE_VERSION, ResultCache
from .replicas import ReplicaPool

# Fallback order per requested engine: the exact family degrades
# toward the sampled engine (cheap, approximate, always applicable).
# Engines absent here (oracle, numpy, sampled, ...) have no fallback —
# a failure is the response's error.
DEGRADE_CHAINS = {
    "exact": ("exact", "sampled"),
    "periodic": ("periodic", "analytic", "sampled"),
    "analytic": ("analytic", "sampled"),
    "dense": ("dense", "stream", "sampled"),
    "stream": ("stream", "sampled"),
}

SERVICE_ENGINES = (
    "oracle", "numpy", "dense", "stream", "periodic", "analytic",
    "exact", "sampled",
)


def degrade_chain(engine: str) -> tuple[str, ...]:
    return DEGRADE_CHAINS.get(engine, (engine,))


class _AttemptTimeout(Exception):
    """Internal: one chain attempt overran its per-attempt budget."""


# Priority classes and the fraction of the admission queue_limit each
# may fill before it sheds: low-priority work sheds first, high last,
# so a saturated queue keeps serving its most important traffic.
PRIORITY_CLASSES = ("low", "normal", "high")
_PRIORITY_HEADROOM = {"low": 0.5, "normal": 0.75, "high": 1.0}


def default_runner(engine: str, program: Program,
                   machine: MachineConfig, request):
    """Run one engine -> (result-with-.state/.total_accesses, per_ref).

    The same engine dispatch cli.py::_run_engine performs, restricted
    to the service's request schema (no r10/checkpoint/shard knobs)."""
    v2 = request.runtime == "v2"
    if engine == "oracle":
        from ..oracle.serial import run_serial

        return run_serial(program, machine, v2=v2), None
    if engine == "numpy":
        from ..oracle.numpy_ref import run_numpy

        return run_numpy(program, machine), None
    if engine == "dense":
        from ..sampler.dense import run_dense

        return run_dense(program, machine), None
    if engine == "stream":
        from ..sampler.stream import run_stream

        return run_stream(program, machine), None
    if engine == "periodic":
        from ..sampler.periodic import run_periodic

        return run_periodic(program, machine), None
    if engine == "analytic":
        from ..sampler.analytic import run_analytic

        return run_analytic(program, machine), None
    if engine == "exact":
        from ..sampler.periodic import run_exact

        return run_exact(program, machine), None
    if engine == "sampled":
        from ..sampler.sampled import run_sampled

        state, results = run_sampled(
            program, machine, sampler_config(request), v2=v2
        )
        return _sampled_namespace(state, results), results
    raise ValueError(f"unknown service engine {engine!r}")


def sampler_config(request) -> SamplerConfig:
    """The SamplerConfig one request's sampled execution uses — shared
    by the solo runner and the batch runner so a member's config (and
    hence its sample streams) cannot depend on which path served it."""
    kw = {}
    if request.device_draw is not None:
        kw["device_draw"] = request.device_draw
    if request.fuse_refs is not None:
        kw["fuse_refs"] = request.fuse_refs
    if request.pipeline_depth is not None:
        kw["pipeline_depth"] = request.pipeline_depth
    if getattr(request, "kernel_backend", None) is not None:
        kw["kernel_backend"] = request.kernel_backend
    if getattr(request, "tolerance", None) is not None:
        kw["tolerance"] = request.tolerance
    if getattr(request, "max_rounds", None) is not None:
        kw["max_rounds"] = request.max_rounds
    if getattr(request, "round_schedule", None) is not None:
        kw["round_schedule"] = tuple(request.round_schedule)
    return SamplerConfig(ratio=request.ratio, seed=request.seed, **kw)


def progressive_requested(request) -> bool:
    """Whether this request opted into the progressive-precision
    driver: any of the three knobs set on a sampled request. Like
    fuse_refs, the knobs stay out of the fingerprint — a converged
    progressive run is bit-identical to the one-shot result at the
    final ratio, so the cached record answers both forms."""
    return request.engine == "sampled" and any(
        getattr(request, k, None) is not None
        for k in ("tolerance", "max_rounds", "round_schedule")
    )


def _sampled_namespace(state, results):
    import types

    return types.SimpleNamespace(
        state=state,
        total_accesses=sum(r.n_samples for r in results),
        engine="sampled",
    )


def default_batch_runner(jobs):
    """Run several sampled requests as ONE batched engine execution.

    `jobs` is [(request, program, machine)]; the return is one
    (result-namespace, per_ref) pair per job, each bit-identical to
    default_runner("sampled", ...) on that job alone
    (sampler/sampled.py::run_sampled_multi)."""
    from ..sampler.sampled import run_sampled_multi

    outs = run_sampled_multi([
        (program, machine, sampler_config(request),
         request.runtime == "v2")
        for request, program, machine in jobs
    ])
    return [
        (_sampled_namespace(state, results), results)
        for state, results in outs
    ]


def execute_request(request, program: Program, machine: MachineConfig,
                    engine: str, fingerprint: str,
                    runner=default_runner, trace_id: str | None = None,
                    span_id: str | None = None) -> dict:
    """One engine execution folded into a versioned result record.

    `engine` is the chain element actually being attempted (it may
    differ from request.engine after degradation). The optional trace
    context lands in the `service_exec` span attrs so the run's trace
    export joins the execution to its request(s) and ledger row(s)."""
    telemetry.count("service_exec_started")
    attrs = {"engine": engine, "program": program.name}
    if trace_id is not None:
        attrs["trace_id"] = trace_id
    if span_id is not None:
        attrs["span_id"] = span_id
    with telemetry.span("service_exec", **attrs):
        # chaos site: one occurrence per attempt of this fingerprint,
        # so retries/hedges draw fresh (but deterministic) decisions
        faults.fire("engine_execute", key=fingerprint,
                    engine=engine, model=program.name)
        res, per_ref = runner(engine, program, machine, request)
        record = build_record(
            request, machine, engine, fingerprint, res, per_ref
        )
    telemetry.count("service_exec_done")
    return record


def build_record(request, machine: MachineConfig, engine: str,
                 fingerprint: str, res, per_ref) -> dict:
    """Fold one engine result (state + per-ref outputs) through the
    reference pipeline into the versioned record service/cache.py
    stores. Shared by the solo path and the batch path, so a batch
    member's record is byte-for-byte the one its solo run would
    cache."""
    rih = cri_distribute(
        res.state, machine.thread_num, machine.thread_num
    )
    mrc = aet_mrc(rih, machine)
    label = "samples" if per_ref is not None else "accesses"
    dump_lines = []
    dump_lines += report.noshare_dump(res.state)
    dump_lines += report.share_dump(res.state)
    dump_lines += report.rih_dump(rih)
    dump_lines += report.mrc_lines(mrc)
    dump_lines.append(
        f"max iteration count: {res.total_accesses} {label}"
    )
    record = {
        "store_version": STORE_VERSION,
        "fingerprint": fingerprint,
        "request": request.payload(),
        "engine_requested": request.engine,
        "engine_used": getattr(res, "engine", None) or engine,
        "total_accesses": int(res.total_accesses),
        "access_label": label,
        "rih": {str(k): float(v) for k, v in sorted(rih.items())},
        "mrc": [float(v) for v in mrc],
        "dump_lines": dump_lines,
        "created_at": time.time(),
    }
    if per_ref is not None:
        record["per_ref_lines"] = [
            f"ref {r.name}: {r.n_samples} samples, cold {r.cold:g}"
            for r in per_ref
        ]
    return record


@dataclasses.dataclass
class _BatchEntry:
    """One request queued in the batch admission window."""

    request: object
    program: Program
    machine: MachineConfig
    fingerprint: str
    future: Future
    refs: int  # tracked refs this member contributes to max_refs
    enqueued_at: float  # perf_counter at submit
    deadline: float | None  # absolute perf_counter bound, or None
    # perf_counter when the admission window flushed this entry; the
    # enqueued_at..flushed_at interval is the member's batch_wait
    # stage, flushed_at..execution-start its (pool) queue stage
    flushed_at: float | None = None
    # ir-preflight summary dict (verdict/races) from the service's
    # static-analysis gate, riding along to outcome/response/ledger
    preflight: object = None


class BatchScheduler:
    """Bounded admission window between submit and engine execution.

    Compatible concurrent requests (today: every sampled request — the
    engine batches at kernel-signature grain, so ANY mix of models/N
    is mergeable) queue here instead of going straight to the pool.
    A batch flushes when the OLDEST member has waited window_ms, or
    earlier when the summed tracked-ref count reaches max_refs; the
    overflow remainder seeds the next batch (overflow splitting).
    A member whose deadline expires while queued is evicted and failed
    immediately with deadline_abandoned counted — it never rides the
    batch just to have its result discarded.

    Purely a scheduler: WHAT each member computes is pinned bit-equal
    to its solo run by the engine layer (run_sampled_multi), so the
    only observable trade-off is latency (up to window_ms of added
    wait) against dispatch amortization (batch_occupancy refs per
    fused dispatch).
    """

    def __init__(self, executor: "RequestExecutor",
                 window_ms: float, max_refs: int):
        self._executor = executor
        self._window_s = max(0.0, window_ms) / 1000.0
        self._max_refs = max(1, max_refs)
        self._queue: list[_BatchEntry] = []
        self._cv = lockwitness.make_condition("BatchScheduler._cv")
        self._closed = False
        self._thread = threading.Thread(
            target=self._loop, daemon=True,
            name="pluss-batch-window",
        )
        self._thread.start()

    def queue_depth(self) -> int:
        with self._cv:
            return len(self._queue)

    def enqueue(self, entry: _BatchEntry) -> None:
        with self._cv:
            if self._closed:
                raise RuntimeError("batch scheduler is closed")
            self._queue.append(entry)
            depth = len(self._queue)
            self._cv.notify()
        # gauge outside the condition lock (C_SINK_UNDER_LOCK): the
        # sink takes the metrics-registry lock
        telemetry.gauge("batch_queue_depth", depth)

    def close(self) -> None:
        """Stop admitting; the loop flushes whatever is queued before
        exiting, so no enqueued future is ever left unresolved."""
        with self._cv:
            self._closed = True
            self._cv.notify()
        self._thread.join(timeout=5.0)

    # -- window loop --------------------------------------------------

    def _pop_batch_locked(self) -> list[_BatchEntry]:
        """Greedy prefix up to max_refs. The first entry is always
        taken (an oversize single request still runs — max_refs bounds
        merging, not admissible work); the remainder re-queues and,
        its window having effectively elapsed, flushes on the next
        loop iteration."""
        batch: list[_BatchEntry] = []
        total = 0
        while self._queue:
            e = self._queue[0]
            if batch and total + e.refs > self._max_refs:
                break
            batch.append(self._queue.pop(0))
            total += e.refs
        return batch

    def _loop(self) -> None:
        while True:
            expired: list[_BatchEntry] = []
            batch: list[_BatchEntry] = []
            with self._cv:
                while not self._queue and not self._closed:
                    self._cv.wait()
                if not self._queue and self._closed:
                    return
                flush_at = self._queue[0].enqueued_at + self._window_s
                while not self._closed:
                    now = time.perf_counter()
                    live = []
                    for e in self._queue:
                        if e.deadline is not None and e.deadline <= now:
                            expired.append(e)
                        else:
                            live.append(e)
                    if expired:
                        # fail the expiries NOW (their futures resolve
                        # outside the lock below) instead of holding
                        # them until the window flushes; the survivors
                        # keep waiting on the next outer iteration
                        self._queue = live
                        break
                    if now >= flush_at or (
                        sum(e.refs for e in self._queue)
                        >= self._max_refs
                    ):
                        batch = self._pop_batch_locked()
                        break
                    wake = flush_at
                    for e in self._queue:
                        if e.deadline is not None:
                            wake = min(wake, e.deadline)
                    self._cv.wait(timeout=max(0.0, wake - now))
                else:
                    # closed: drain whatever is still queued (one
                    # max_refs-bounded batch per outer iteration)
                    batch = self._pop_batch_locked()
                depth = len(self._queue)
            # executor work — and telemetry, whose sinks take their
            # own locks — runs OUTSIDE the condition lock: expiry
            # resolves futures (whose callbacks take executor locks)
            # and _submit_batch touches the pool
            telemetry.gauge("batch_queue_depth", depth)
            for e in expired:
                self._executor._expire_queued(e)
            if batch:
                self._executor._submit_batch(batch)


class RequestExecutor:
    """Singleflight + bounded concurrency + deadlines over
    `execute_request`. One instance backs one AnalysisService."""

    def __init__(self, cache: ResultCache | None = None,
                 max_workers: int = 4, runner=default_runner,
                 ledger_path: str | None = None,
                 batching: BatchConfig | None = None,
                 batch_runner=default_batch_runner,
                 replicas: ReplicaConfig | int | None = None,
                 resilience: ResilienceConfig | None = None,
                 worker_id: int | None = None):
        self.cache = cache if cache is not None else ResultCache()
        self.runner = runner
        self.batch_runner = batch_runner
        self.ledger_path = ledger_path
        # fabric attribution: when this executor is one worker of a
        # multi-process fabric, every ledger row it appends carries the
        # worker id, so a shared ledger shards cleanly by the router's
        # ring assignment (tools/check_ledger.py --stats validates it)
        self.worker_id = worker_id
        self._resilience = (
            resilience if resilience is not None else ResilienceConfig()
        )
        self._draining = False
        # per-engine circuit breakers, created lazily on first attempt
        self._breakers: dict[str, CircuitBreaker] = {}
        self._replicas: ReplicaPool | None = None
        if replicas is not None:
            cfg = (
                replicas if isinstance(replicas, ReplicaConfig)
                else ReplicaConfig(count=replicas)
            )
            self._replicas = ReplicaPool(
                cfg, resilience=self._resilience
            )
            n = len(self._replicas)
            if max_workers < n:
                # fewer pool threads than replicas silently strands
                # replicas: a replica only receives work a pool thread
                # submits, so an unreachable replica sits idle while
                # work queues behind the few reachable ones
                telemetry.warn_once(
                    f"max_workers_clamped:{max_workers}:{n}",
                    f"--max-workers {max_workers} < {n} replicas "
                    f"would strand replicas idle; clamped to {n}",
                    requested=max_workers, replicas=n,
                )
                telemetry.count("max_workers_clamped")
                max_workers = n
        self.max_workers = max_workers
        self._pool = ThreadPoolExecutor(
            max_workers=max_workers,
            thread_name_prefix="pluss-service",
        )
        self._inflight: dict[str, Future] = {}
        self._lock = lockwitness.make_lock("RequestExecutor._lock")
        # instance-local counters backing the serve `stats`/`healthz`
        # introspection protocol — telemetry counters only exist while
        # a run is enabled, but a long-lived service must answer
        # introspection requests at any time
        self._stats = collections.Counter()
        # singleflight joiners per in-flight fingerprint, drained into
        # the executing request's ledger row (`coalesced`) so the
        # ledger aggregate reproduces the live submitted/coalesced
        # counters exactly
        self._coalesced_by_fp = collections.Counter()
        # progressive-precision partial-frame subscribers per in-flight
        # fingerprint: every submit (executor AND coalesced joiners)
        # may register a callback; the executing round loop fires all
        # of them after each completed round
        self._partial_subs: dict[str, list] = {}
        # batching observability for stats(): per-batch member counts
        # and cold (cache-miss) latencies batched vs solo, bounded so a
        # long-lived service cannot grow them without limit
        self._batch_occupancy: list[int] = []
        self._lat_batched: list[float] = []
        self._lat_solo: list[float] = []
        self._obs_cap = 512
        self._batcher = (
            BatchScheduler(self, batching.window_ms, batching.max_refs)
            if batching is not None else None
        )
        if ledger_path:
            # compile-counter deltas in ledger rows need the
            # process-global jax.monitoring listeners; without jax the
            # deltas simply stay empty
            try:
                telemetry.register_jax_hooks()
            except Exception:
                pass

    def stats(self) -> dict:
        """Executor health snapshot: queue depth (submitted futures
        not yet executing), in-flight count, singleflight coalesces,
        and the lifetime execution/degradation counters."""
        with self._lock:
            out = dict(self._stats)
            inflight = len(self._inflight)
            occupancy = sorted(self._batch_occupancy)
            lat_b = sorted(self._lat_batched)
            lat_s = sorted(self._lat_solo)
        for key in ("submitted", "coalesced", "completed", "failed",
                    "degraded", "deadline_abandoned", "active",
                    "ledger_rows", "ledger_write_failed",
                    "batches_formed", "batch_members",
                    "batch_fallback_solo", "preflight_rejected",
                    "frontend_rejected", "race_warnings",
                    "shed", "retried", "hedged", "hedge_wins",
                    "hedge_cancelled", "breaker_opened",
                    "breaker_reclosed", "breaker_open_skips",
                    "partial_final", "progressive_converged",
                    "partials_emitted"):
            out.setdefault(key, 0)
        active = out.pop("active")
        out["in_flight"] = inflight
        out["executing"] = active
        out["queue_depth"] = max(0, inflight - active)
        out["max_workers"] = self.max_workers
        out["batch_queue_depth"] = (
            self._batcher.queue_depth() if self._batcher else 0
        )
        if occupancy:
            out["batch_occupancy_p50"] = obs_ledger._percentile(
                occupancy, 0.50
            )
            out["batch_occupancy_p95"] = obs_ledger._percentile(
                occupancy, 0.95
            )
        if lat_b:
            out["batched_p50_latency_s"] = round(
                obs_ledger._percentile(lat_b, 0.50), 6
            )
        if lat_s:
            out["solo_p50_latency_s"] = round(
                obs_ledger._percentile(lat_s, 0.50), 6
            )
        if self._replicas is not None:
            # per-replica occupancy — the instance-local face of the
            # same counts /metrics exports (requests_routed_r*) and
            # check_ledger --stats aggregates (rows' replica_id)
            out["replicas"] = self._replicas.snapshot()
        out["draining"] = self._draining
        out["queue_limit"] = self._resilience.queue_limit
        with self._lock:
            brs = dict(self._breakers)
        if brs:
            out["breakers"] = {
                eng: br.snapshot() for eng, br in sorted(brs.items())
            }
        return out

    def _note_latency(self, outcome: dict, batched: bool) -> None:
        """Collect cold-execution latencies for the batched-vs-solo
        stats comparison (warm cache hits would swamp both sides)."""
        if outcome["record"] is None or outcome["cache"] != "miss":
            return
        dest = self._lat_batched if batched else self._lat_solo
        with self._lock:
            if len(dest) < self._obs_cap:
                dest.append(outcome["latency_s"])

    # Instance-counter -> telemetry/registry name, the one write path
    # behind the three counter surfaces (serve `stats`, the Prometheus
    # export, the ledger aggregate): every _count lands in the
    # instance snapshot AND — via telemetry.count, which mirrors into
    # the live metrics registry — in both exported views, under one
    # name. "active" is a +/-1 level, not a monotone counter, so it
    # stays instance-local (stats() reports it as `executing`).
    _TELE_COUNTS = {
        "submitted": "service_submitted",
        "coalesced": "service_coalesced",
        "completed": "service_completed",
        "failed": "service_failed",
        "degraded": "service_degraded",
        "deadline_abandoned": "service_deadline_abandoned",
        "ledger_rows": "service_ledger_rows",
        "ledger_write_failed": "service_ledger_write_failed",
        "batches_formed": "batches_formed",
        "batch_members": "batch_members",
        "batch_fallback_solo": "service_batch_fallback_solo",
        "preflight_rejected": "ir_preflight_failures",
        "frontend_rejected": "frontend_rejected",
        "race_warnings": "race_warnings",
        "shed": "service_shed",
        "retried": "service_retried",
        "hedged": "service_hedged",
        "hedge_wins": "service_hedge_wins",
        "hedge_cancelled": "service_hedge_cancelled",
        "breaker_opened": "service_breaker_opened",
        "breaker_reclosed": "service_breaker_reclosed",
        "breaker_open_skips": "service_breaker_open_skips",
        "partial_final": "service_partial_final",
        "progressive_converged": "service_progressive_converged",
        "partials_emitted": "service_partials_emitted",
        "partial_emit_failed": "service_partial_emit_failed",
    }

    def _count(self, key: str, inc: int = 1) -> None:
        with self._lock:
            self._stats[key] += inc
        name = self._TELE_COUNTS.get(key)
        if name is not None:
            telemetry.count(name, inc)

    # -- public -------------------------------------------------------

    def submit(self, request, program: Program,
               machine: MachineConfig, fingerprint: str,
               preflight: dict | None = None,
               on_partial=None) -> Future:
        """Schedule (or join) the execution for one fingerprint.

        The returned future resolves to the full response dict (record
        + serving metadata). Identical fingerprints submitted while
        one is in flight share its future (and its trace/span ids —
        one execution, one span, N joined callers). `preflight` is the
        service's static-analysis summary (verdict/races); it rides
        the outcome into the response and the ledger row. Coalesced
        joiners share the executing request's summary — same
        fingerprint, same IR, same verdict.

        `on_partial` (progressive-precision requests) is called with
        one interim-result doc per completed round, from the executing
        thread; coalesced joiners register their own callback on the
        shared execution, so every subscriber streams the same
        rounds."""
        telemetry.count("service_requests")
        telemetry.count("service_submitted")
        if getattr(request, "trace_id", None) is None:
            # mint the trace context here so every downstream surface
            # (span attrs, ledger row, exemplars, response) can join
            # on it even for callers that never set one
            request = dataclasses.replace(
                request, trace_id=uuid.uuid4().hex[:16]
            )
        submitted_at = time.perf_counter()
        batchable = (
            self._batcher is not None and self._batchable(request)
        )
        entry = None
        shed_reason = None
        with self._lock:
            self._stats["submitted"] += 1
            fut = self._inflight.get(fingerprint)
            if fut is not None:
                self._stats["coalesced"] += 1
                # joiners ride the executing request's ledger row —
                # remembered per fingerprint so the row can report how
                # many submissions it answered
                self._coalesced_by_fp[fingerprint] += 1
                if on_partial is not None:
                    self._partial_subs.setdefault(
                        fingerprint, []
                    ).append(on_partial)
            else:
                # admission gate — AFTER the coalesce join (joining an
                # in-flight execution costs nothing, so it is never
                # shed) and BEFORE any queue/pool state is touched, so
                # a shed is a cheap structured refusal, not an
                # expensive timeout
                priority = getattr(request, "priority", "normal")
                if self._draining:
                    shed_reason = (
                        "service draining (shutdown in progress)"
                    )
                elif (self._resilience.queue_limit is not None
                        and self._resilience.shed_enabled):
                    depth = (len(self._inflight)
                             - self._stats.get("active", 0))
                    limit = self._admission_limit(priority)
                    if depth >= limit:
                        shed_reason = (
                            f"queue depth {depth} at admission limit "
                            f"{limit} for priority {priority!r}"
                        )
        if fut is not None:
            # count outside the lock (C_SINK_UNDER_LOCK): the sink
            # takes the metrics-registry lock
            telemetry.count("service_coalesced")
            return fut
        if shed_reason is not None:
            return self._shed(request, fingerprint, shed_reason,
                              preflight, submitted_at)
        with self._lock:
            # re-check the singleflight join: the gate ran outside
            # the first critical section, so an identical fingerprint
            # may have landed in between
            coalesced = self._inflight.get(fingerprint)
            if on_partial is not None and (
                coalesced is not None or not batchable
            ):
                self._partial_subs.setdefault(
                    fingerprint, []
                ).append(on_partial)
            if coalesced is not None:
                self._stats["coalesced"] += 1
                self._coalesced_by_fp[fingerprint] += 1
            elif batchable:
                # the admission window resolves this future itself;
                # singleflight still coalesces identical fingerprints
                # onto it while it waits or runs
                fut = Future()
                fut.set_running_or_notify_cancel()
                entry = _BatchEntry(
                    request=request, program=program, machine=machine,
                    fingerprint=fingerprint, future=fut,
                    refs=sum(len(n.refs) for n in program.nests),
                    enqueued_at=submitted_at,
                    deadline=(
                        None if request.deadline_s is None
                        else time.perf_counter() + request.deadline_s
                    ),
                    preflight=preflight,
                )
                self._inflight[fingerprint] = fut
            else:
                fut = self._pool.submit(
                    self._process, request, program, machine,
                    fingerprint, submitted_at, preflight,
                )
                self._inflight[fingerprint] = fut
            depth = len(self._inflight)
        # sinks outside the lock (C_SINK_UNDER_LOCK)
        if coalesced is not None:
            telemetry.count("service_coalesced")
            return coalesced
        telemetry.gauge("service_queue_depth", depth)

        def _done(_f, fp=fingerprint):
            with self._lock:
                self._inflight.pop(fp, None)
                self._partial_subs.pop(fp, None)
                depth = len(self._inflight)
            telemetry.gauge("service_queue_depth", depth)

        # registered OUTSIDE the lock: a future that already finished
        # runs the callback synchronously on this thread, and the
        # callback itself takes the lock
        fut.add_done_callback(_done)
        if entry is not None:
            self._batcher.enqueue(entry)
        return fut

    @staticmethod
    def _batchable(request) -> bool:
        """The compatibility predicate: which requests may share a
        batched execution. Today exactly the sampled engine — the only
        one with a multi-job runner; kernel-signature bucketing makes
        any mix of models/N/configs mergeable within it. Progressive
        requests run their own round loop (deadline checks and partial
        streaming between rounds), so they always execute solo."""
        return (request.engine == "sampled"
                and not progressive_requested(request))

    def _admission_limit(self, priority: str) -> int:
        """Queue slots this priority class may fill before shedding
        (a fraction of queue_limit; high priority gets the full
        limit, so under saturation low-priority traffic sheds
        first)."""
        frac = _PRIORITY_HEADROOM.get(
            priority, _PRIORITY_HEADROOM["normal"]
        )
        return max(1, math.ceil(self._resilience.queue_limit * frac))

    def _shed(self, request, fingerprint: str, reason: str,
              preflight, submitted_at: float) -> Future:
        """Refuse one submission at the admission gate with a
        STRUCTURED outcome, never an exception: counted `shed` (not
        `failed` — the service declined the work, it did not botch
        it), stamped on its own ledger row, and resolved in
        microseconds instead of timing out after seconds of
        queueing."""
        self._count("shed")
        telemetry.event(
            "service_shed", fingerprint=fingerprint, reason=reason,
            priority=getattr(request, "priority", "normal"),
        )
        outcome = {
            "record": None,
            "cache": None,
            "degraded": [],
            "error": f"shed: {reason}",
            "shed": True,
            "latency_s": round(time.perf_counter() - submitted_at, 6),
            "mrc_digest": None,
            "trace_id": getattr(request, "trace_id", None),
            "span_id": None,
            "queue_s": None,
            "execute_s": None,
            "replica_id": None,
            "preflight": preflight,
        }
        self._record_flight(request, outcome, extra={"shed": True})
        if self.ledger_path:
            self._append_ledger_row(
                request, fingerprint, outcome,
                telemetry.compile_counters_snapshot(),
            )
        fut: Future = Future()
        fut.set_running_or_notify_cancel()
        fut.set_result(outcome)
        return fut

    @property
    def draining(self) -> bool:
        return self._draining

    def drain(self) -> None:
        """Begin graceful shutdown: every LATER submit sheds at the
        admission gate, and work still queued in the pool (submitted
        but not yet executing) is cancelled — its waiters observe
        CancelledError, which the serve loop answers with a structured
        shed response. Executions already running finish normally:
        this drains the service, it does not abort it."""
        with self._lock:
            already = self._draining
            self._draining = True
            pending = list(self._inflight.values())
        if already:
            return
        telemetry.event("service_draining")
        for fut in pending:
            # queued pool futures cancel; executing (and batch-window)
            # futures refuse and resolve normally during the drain
            if fut.cancel():
                self._count("shed")

    def shutdown(self) -> None:
        if self._batcher is not None:
            # flush the admission window through the pool BEFORE the
            # pool stops accepting work
            self._batcher.close()
        self._pool.shutdown(wait=True)
        if self._replicas is not None:
            # last: every pool worker has returned, so no execution
            # is still waiting on a replica future
            self._replicas.close()

    # -- replica routing ----------------------------------------------

    def _execute_routed(self, fn, trace_id=None, members: int = 1,
                        meta: dict | None = None):
        """Run one engine execution (a solo chain attempt or a whole
        batch window) on the replica pool when one exists, inline
        otherwise. Returns (fn's result, replica_id|None, re-route
        degradation events).

        Hedging: with `hedge_after_s` configured and >= 2 replicas, a
        routed dispatch still unresolved after the hedge delay is
        duplicated onto a second replica (tail-latency insurance
        against a straggler). First result wins; the losing copy is
        cancelled while still queued (ReplicaPool.try_cancel) or, if
        already executing, finishes into the void. Both copies compute
        the same seed-derived bytes, so whichever wins the response is
        bit-identical — hedging can only change WHEN the answer
        arrives, never WHAT it is."""
        if self._replicas is None:
            return fn(), None, []
        hedge_s = self._resilience.hedge_after_s
        if hedge_s is None or len(self._replicas) < 2:
            return self._replicas.run(
                fn, trace_id=trace_id, members=members
            )
        primary = self._replicas.submit(
            fn, trace_id=trace_id, members=members
        )
        try:
            return primary.result(timeout=hedge_s)
        except FuturesTimeoutError:
            pass
        self._count("hedged")
        if meta is not None:
            meta["hedged"] = True
        telemetry.event("service_hedged", trace_id=trace_id,
                        hedge_after_s=hedge_s)
        hedge = self._replicas.submit(
            fn, trace_id=trace_id, members=members
        )
        futures_wait((primary, hedge), return_when=FIRST_COMPLETED)
        winner, loser = (
            (primary, hedge) if primary.done() else (hedge, primary)
        )
        if winner is hedge:
            self._count("hedge_wins")
        if self._replicas.try_cancel(loser):
            self._count("hedge_cancelled")
        else:
            # the loser is executing (or finished) — let it resolve in
            # the background so its replica bookkeeping stays honest
            loser.add_done_callback(lambda f: f.exception())
        return winner.result()

    def _absorb_replica_events(self, degraded: list, events,
                               fingerprint: str) -> None:
        """Fold the pool's quarantine re-route events into a request's
        degradation chain, mirroring engine downgrades: each lands in
        the response/ledger `degraded` list AND as a
        `service_degraded` telemetry event (the completion is then
        counted degraded, which is what the SLO error budget reads)."""
        for info in events:
            degraded.append(dict(info))
            telemetry.event(
                "service_degraded", fingerprint=fingerprint, **info
            )

    def warm_structures(self, jobs) -> int:
        """Pre-compile sampled kernel signatures: `jobs` is
        [(program, machine, SamplerConfig|None)]. With a pool, every
        replica compiles on ITS devices (structure-keyed, so repeats
        are free); without one, a single inline warmup. Returns the
        number of warmup executions performed. Used by ledger-driven
        warm start (`--warmup-from-ledger`)."""
        done = 0
        for program, machine, cfg in jobs:
            if self._replicas is not None:
                done += self._replicas.warmup(program, machine, cfg)
            else:
                from ..sampler.sampled import warmup

                warmup(program, machine, cfg)
                done += 1
        return done

    # -- worker -------------------------------------------------------

    def _process(self, request, program, machine,
                 fingerprint: str,
                 submitted_at: float | None = None,
                 preflight: dict | None = None) -> dict:
        start = time.perf_counter()
        t0 = submitted_at if submitted_at is not None else start
        queue_s = None if submitted_at is None else start - submitted_at
        trace_id = getattr(request, "trace_id", None)
        span_id = None
        execute_s = None
        self._count("active")
        compiles0 = (
            telemetry.compile_counters_snapshot()
            if self.ledger_path else None
        )
        try:
            with telemetry.span("service_request",
                                engine=request.engine,
                                program=program.name,
                                trace_id=trace_id):
                fetch_t0 = time.perf_counter()
                record, tier = self.cache.get(fingerprint)
                fetch_s = time.perf_counter() - fetch_t0
                degraded: list[dict] = []
                error = None
                replica_id = None
                meta = {"retries": 0, "hedged": False}
                if record is None:
                    span_id = uuid.uuid4().hex[:16]
                    exec_t0 = time.perf_counter()
                    record, degraded, error, replica_id = (
                        self._run_chain(
                            request, program, machine, fingerprint,
                            trace_id=trace_id, span_id=span_id,
                            meta=meta,
                        )
                    )
                    execute_s = time.perf_counter() - exec_t0
                    if record is not None and not degraded:
                        self.cache.put(fingerprint, record)
        finally:
            self._count("active", -1)
        self._count("completed" if record is not None else "failed")
        if degraded:
            self._count("degraded")
        outcome = {
            "record": record,
            "cache": tier,
            "degraded": degraded,
            "error": error,
            "latency_s": round(time.perf_counter() - t0, 6),
            "mrc_digest": (
                obs_ledger.mrc_digest(record["mrc"])
                if record is not None else None
            ),
            "trace_id": trace_id,
            "span_id": span_id,
            "queue_s": queue_s,
            "execute_s": execute_s,
            "replica_id": replica_id,
            "preflight": preflight,
            "retries": meta["retries"],
            "hedged": meta["hedged"],
        }
        prog = meta.get("progressive")
        if prog is not None:
            # progressive-precision outcome fields (schema-v2
            # optional): rounds completed, tightest band reached,
            # whether the run converged; partial_final marks the
            # deadline-truncated form (already a precision:* degrade
            # hop above, so it was kept out of the cache)
            outcome["rounds"] = prog["rounds"]
            outcome["band_width"] = prog["band_width"]
            outcome["converged"] = prog["converged"]
            if prog.get("partial_final"):
                outcome["partial_final"] = True
        self._attribute_utilization(outcome, compiles0,
                                    fetch_s=fetch_s)
        self._observe_stages(outcome, queue_s=queue_s,
                             execute_s=execute_s, fetch_s=fetch_s)
        self._record_flight(request, outcome)
        self._note_latency(outcome, batched=False)
        if self.ledger_path:
            self._append_ledger_row(
                request, fingerprint, outcome, compiles0
            )
        return outcome

    def _observe_stages(self, outcome: dict, queue_s=None,
                        batch_wait_s=None, execute_s=None,
                        fetch_s=None) -> None:
        """Record the per-stage request histograms into the live
        registry (no-op when metrics are disabled), with the request's
        trace_id as the exemplar."""
        from ..runtime.obs import metrics as obs_metrics

        if obs_metrics.get() is None:
            return
        ex = outcome.get("trace_id")
        for name, value in (
            ("request_queue_s", queue_s),
            ("request_batch_wait_s", batch_wait_s),
            ("request_execute_s", execute_s),
            ("request_fetch_s", fetch_s),
            ("request_total_s", outcome.get("latency_s")),
        ):
            if value is not None:
                obs_metrics.observe(name, value, exemplar=ex)

    def _attribute_utilization(self, outcome: dict, compiles0,
                               fetch_s=None) -> None:
        """Fold the request's stage seconds into a `utilization`
        block (runtime/obs/attribution.py) on the outcome — wall vs
        executing vs queue/batch-wait vs fetch, plus the execution's
        jit-compile seconds when a compile baseline was snapped — and
        mirror the busy/idle/unattributed fractions into the live
        gauges. Attribution is observation only: it must never sink
        the request."""
        from ..runtime.obs import attribution

        try:
            compile_s = None
            if compiles0 is not None:
                now = telemetry.compile_counters_snapshot()
                delta = (
                    now.get("backend_compile_s", 0.0)
                    - compiles0.get("backend_compile_s", 0.0)
                )
                if delta > 0:
                    compile_s = round(delta, 6)
            block = attribution.request_utilization(
                wall_s=outcome.get("latency_s"),
                execute_s=outcome.get("execute_s"),
                queue_s=outcome.get("queue_s"),
                batch_wait_s=outcome.get("batch_wait_s"),
                fetch_s=fetch_s,
                compile_s=compile_s,
            )
            if block is not None:
                outcome["utilization"] = block
                attribution.record_gauges(block)
        except Exception:
            self._count("utilization_failed")

    def _record_flight(self, request, outcome: dict,
                       extra: dict | None = None) -> None:
        """Feed one per-request record into the flight recorder
        (runtime/obs/recorder.py); no-op when disabled. The record is
        the outcome minus the payload-heavy `record` field, plus the
        request identity — what a post-mortem needs to reconstruct the
        request's path without shipping MRC arrays into every bundle.
        A failed request fires the recorder's request_failure trigger
        from inside record()."""
        from ..runtime.obs import recorder as obs_recorder

        if obs_recorder.get() is None:
            return
        rec = {
            "trace_id": outcome.get("trace_id"),
            "span_id": outcome.get("span_id"),
            "model": request.model,
            "n": request.n,
            "engine_requested": request.engine,
            "engine_used": (
                outcome["record"].get("engine_used")
                if outcome.get("record") else None
            ),
            "ok": outcome.get("record") is not None,
            "error": outcome.get("error"),
            "cache": outcome.get("cache"),
            "degraded": outcome.get("degraded"),
            "latency_s": outcome.get("latency_s"),
            "queue_s": outcome.get("queue_s"),
            "batch_wait_s": outcome.get("batch_wait_s"),
            "execute_s": outcome.get("execute_s"),
            "replica_id": outcome.get("replica_id"),
            "mrc_digest": outcome.get("mrc_digest"),
        }
        if outcome.get("utilization") is not None:
            rec["utilization"] = outcome["utilization"]
        pf = outcome.get("preflight")
        if isinstance(pf, dict) and pf.get("verdict"):
            rec["preflight"] = pf["verdict"]
        if extra:
            rec.update(extra)
        obs_recorder.record(rec)

    # -- batched worker -----------------------------------------------

    def _submit_batch(self, entries: list[_BatchEntry]) -> None:
        """Hand one flushed admission window to the pool (called by
        the BatchScheduler loop, never under its condition lock)."""
        now = time.perf_counter()
        for e in entries:
            e.flushed_at = now
        self._pool.submit(self._process_batch, entries)

    def _process_batch(self, entries: list[_BatchEntry]) -> None:
        """Run one flushed window as (at most) one batched engine
        execution, resolving every member's future.

        Members are peeled off first when the batch cannot or need not
        carry them: warm cache hits are served immediately (zero
        executions — the singleflight/caching invariant), queued
        deadline expiries fail immediately, and members whose program
        fails to lower (pre-flight kernel build) fall back to the solo
        chain. Everything left runs through ONE batch_runner call; a
        batch-level failure degrades every member to solo execution
        rather than failing them collectively."""
        exec_start = time.perf_counter()
        compiles0 = (
            telemetry.compile_counters_snapshot()
            if self.ledger_path else None
        )
        runnable: list[_BatchEntry] = []
        for e in entries:
            if e.deadline is not None and e.deadline <= time.perf_counter():
                self._expire_queued(e)
                continue
            fetch_t0 = time.perf_counter()
            record, tier = self.cache.get(e.fingerprint)
            fetch_s = time.perf_counter() - fetch_t0
            if record is not None:
                self._count("completed")
                outcome = {
                    "record": record,
                    "cache": tier,
                    "degraded": [],
                    "error": None,
                    "latency_s": round(
                        time.perf_counter() - e.enqueued_at, 6
                    ),
                    "mrc_digest": obs_ledger.mrc_digest(record["mrc"]),
                    "trace_id": getattr(e.request, "trace_id", None),
                    "span_id": None,
                    "batch_wait_s": self._batch_wait_s(e),
                    "queue_s": self._queue_wait_s(e, exec_start),
                }
                self._observe_stages(
                    outcome, queue_s=outcome["queue_s"],
                    batch_wait_s=outcome["batch_wait_s"],
                    fetch_s=fetch_s,
                )
                self._finish(e, outcome, compiles0)
                continue
            try:
                # pre-flight: an unlowerable program must not poison
                # the shared dispatch — send it down the solo chain
                # (whose own error handling owns the failure)
                from ..sampler.sampled import _program_kernels

                _program_kernels(e.program, e.machine)
            except Exception:
                self._solo_fallback(e, compiles0)
                continue
            runnable.append(e)
        if not runnable:
            return
        batch_id = uuid.uuid4().hex[:8]
        # ONE span for the shared execution: every member's ledger row
        # and response joins it on span_id (the trace-context upgrade
        # over the coarse batch_id join)
        span_id = uuid.uuid4().hex[:16]
        self._count("batches_formed")
        self._count("batch_members", len(runnable))
        with self._lock:
            if len(self._batch_occupancy) < self._obs_cap:
                self._batch_occupancy.append(len(runnable))
        telemetry.gauge("batch_occupancy", len(runnable))
        self._count("active")
        telemetry.count("service_exec_started")

        def _run_window():
            # the span opens on the EXECUTING thread (a replica worker
            # when a pool routes the window), so its attrs carry the
            # replica's device scope implicitly
            with telemetry.span("service_exec", engine="sampled",
                                batch=len(runnable), batch_id=batch_id,
                                span_id=span_id):
                return self.batch_runner([
                    (e.request, e.program, e.machine) for e in runnable
                ])

        meta = {"retries": 0, "hedged": False}
        try:
            exec_t0 = time.perf_counter()
            outs, batch_rid, batch_events = self._execute_routed(
                _run_window,
                trace_id=getattr(runnable[0].request, "trace_id", None),
                members=len(runnable), meta=meta,
            )
            execute_s = time.perf_counter() - exec_t0
            telemetry.count("service_exec_done")
        except Exception:
            # one shared dispatch failed: no member is served a
            # collective error — each re-runs solo
            telemetry.count("service_batch_failed")
            for e in runnable:
                self._solo_fallback(e, compiles0)
            return
        finally:
            self._count("active", -1)
        for e, (res, per_ref) in zip(runnable, outs):
            try:
                fetch_t0 = time.perf_counter()
                record = build_record(
                    e.request, e.machine, "sampled", e.fingerprint,
                    res, per_ref,
                )
                # per-member cache write: EVERY member lands in the
                # store under its own fingerprint, so a warm repeat of
                # any of them is a hit with zero executions — except
                # after a quarantine re-route, which (like any other
                # degradation) is served but never persisted
                if not batch_events:
                    self.cache.put(e.fingerprint, record)
                fetch_s = time.perf_counter() - fetch_t0
            except Exception:
                self._solo_fallback(e, compiles0)
                continue
            self._count("completed")
            degraded: list[dict] = []
            self._absorb_replica_events(
                degraded, batch_events, e.fingerprint
            )
            if degraded:
                self._count("degraded")
            outcome = {
                "record": record,
                "cache": "miss",
                "degraded": degraded,
                "error": None,
                # from enqueue: the member's latency honestly includes
                # its admission-window wait — the trade-off the
                # batched-vs-solo stats exist to show
                "latency_s": round(
                    time.perf_counter() - e.enqueued_at, 6
                ),
                "mrc_digest": obs_ledger.mrc_digest(record["mrc"]),
                "trace_id": getattr(e.request, "trace_id", None),
                # the SHARED execution span: N member rows, one span
                "span_id": span_id,
                "batch_wait_s": self._batch_wait_s(e),
                "queue_s": self._queue_wait_s(e, exec_start),
                "execute_s": execute_s,
                # the replica that ultimately served the window (the
                # re-route target when quarantine moved it)
                "replica_id": batch_rid,
                # a hedged window marks every member it carried
                "hedged": meta["hedged"],
            }
            self._observe_stages(
                outcome, queue_s=outcome["queue_s"],
                batch_wait_s=outcome["batch_wait_s"],
                execute_s=execute_s, fetch_s=fetch_s,
            )
            self._note_latency(outcome, batched=True)
            self._finish(e, outcome, compiles0, batch_id=batch_id,
                         batch_members=len(runnable))

    @staticmethod
    def _batch_wait_s(e: _BatchEntry):
        """Admission-window wait of one member (None before flush)."""
        if e.flushed_at is None:
            return None
        return max(0.0, e.flushed_at - e.enqueued_at)

    @staticmethod
    def _queue_wait_s(e: _BatchEntry, exec_start: float):
        """Pool wait between window flush and batch-worker start."""
        if e.flushed_at is None:
            return None
        return max(0.0, exec_start - e.flushed_at)

    def _solo_fallback(self, e: _BatchEntry, compiles0) -> None:
        """Degrade one batch member to the solo execution chain."""
        self._count("batch_fallback_solo")
        trace_id = getattr(e.request, "trace_id", None)
        span_id = uuid.uuid4().hex[:16]
        exec_t0 = time.perf_counter()
        meta = {"retries": 0, "hedged": False}
        try:
            record, degraded, error, replica_id = self._run_chain(
                e.request, e.program, e.machine, e.fingerprint,
                trace_id=trace_id, span_id=span_id, meta=meta,
            )
            if record is not None and not degraded:
                self.cache.put(e.fingerprint, record)
        except Exception as exc:
            record, degraded, error, replica_id = None, [], repr(exc), None
        execute_s = time.perf_counter() - exec_t0
        self._count("completed" if record is not None else "failed")
        if degraded:
            self._count("degraded")
        outcome = {
            "record": record,
            "cache": "miss",
            "degraded": degraded,
            "error": error,
            "latency_s": round(time.perf_counter() - e.enqueued_at, 6),
            "mrc_digest": (
                obs_ledger.mrc_digest(record["mrc"])
                if record is not None else None
            ),
            "trace_id": trace_id,
            "span_id": span_id,
            "batch_wait_s": self._batch_wait_s(e),
            "execute_s": execute_s,
            "replica_id": replica_id,
            "retries": meta["retries"],
            "hedged": meta["hedged"],
        }
        self._observe_stages(
            outcome, batch_wait_s=outcome["batch_wait_s"],
            execute_s=execute_s,
        )
        self._note_latency(outcome, batched=False)
        self._finish(e, outcome, compiles0)

    def _expire_queued(self, e: _BatchEntry) -> None:
        """Fail a member whose deadline passed while it sat in the
        admission window — immediately, instead of riding the batch
        and discarding the result afterward (the deadline fix)."""
        self._count("deadline_abandoned")
        self._count("failed")
        outcome = {
            "record": None,
            "cache": None,
            "degraded": [],
            "error": (
                f"deadline {e.request.deadline_s}s expired in the "
                "batch admission window (deadline_abandoned)"
            ),
            "latency_s": round(time.perf_counter() - e.enqueued_at, 6),
            "mrc_digest": None,
            "trace_id": getattr(e.request, "trace_id", None),
            "span_id": None,
            "batch_wait_s": round(
                time.perf_counter() - e.enqueued_at, 6
            ),
        }
        self._observe_stages(
            outcome, batch_wait_s=outcome["batch_wait_s"]
        )
        compiles0 = (
            telemetry.compile_counters_snapshot()
            if self.ledger_path else None
        )
        self._finish(e, outcome, compiles0)

    def _finish(self, e: _BatchEntry, outcome: dict, compiles0,
                batch_id: str | None = None,
                batch_members: int | None = None) -> None:
        """Ledger + future resolution for one batch member."""
        if e.preflight is not None:
            outcome.setdefault("preflight", e.preflight)
        self._attribute_utilization(outcome, compiles0)
        self._record_flight(
            e.request, outcome,
            extra=(
                {"batch_id": batch_id, "batch_members": batch_members}
                if batch_id is not None else None
            ),
        )
        if self.ledger_path:
            extra = {}
            if batch_id is not None:
                extra = {"batch_id": batch_id,
                         "batch_members": batch_members}
            self._append_ledger_row(
                e.request, e.fingerprint, outcome, compiles0,
                extra=extra,
            )
        e.future.set_result(outcome)

    def _append_ledger_row(self, request, fingerprint: str,
                           outcome: dict, compiles0: dict,
                           extra: dict | None = None) -> None:
        """One ledger row per execution (cache hits included, since a
        served response is an execution of the SERVICE even when the
        engine never ran; coalesced callers share the executing row).
        A ledger failure must never sink the request — it is counted
        and dropped."""
        record = outcome["record"]
        now = telemetry.compile_counters_snapshot()
        compile_delta = {
            k: v - compiles0.get(k, 0)
            for k, v in now.items()
            if v - compiles0.get(k, 0)
        }
        row = {
            "kind": "request",
            "source": "service",
            "ok": record is not None,
            "fingerprint": fingerprint,
            "engine_requested": request.engine,
            "engine_used": (
                record.get("engine_used") if record else None
            ),
            "model": request.model,
            "n": request.n,
            "latency_s": outcome["latency_s"],
            "cache": outcome["cache"],
            "degraded": outcome["degraded"],
            "compile_delta": {
                k: round(v, 4) if isinstance(v, float) else v
                for k, v in compile_delta.items()
            },
            "mrc_digest": outcome["mrc_digest"],
        }
        # v2 trace context + per-stage timings + singleflight join
        # count: the row must reproduce the live counters' view of
        # this request (submitted = 1 + coalesced) and join its
        # (possibly shared) execution span on span_id
        row["trace_id"] = outcome.get("trace_id")
        row["span_id"] = outcome.get("span_id")
        if outcome.get("replica_id") is not None:
            row["replica_id"] = outcome["replica_id"]
        if self.worker_id is not None:
            row["worker_id"] = self.worker_id
        # the full request payload makes the ledger replayable: warm
        # start (--warmup-from-ledger) rebuilds the row's program/
        # machine/sampler config from it to pre-compile the kernels a
        # restarted serve process is about to need
        try:
            row["request"] = request.payload()
        except Exception:
            pass
        pf = outcome.get("preflight")
        if isinstance(pf, dict) and pf.get("verdict"):
            # schema-v2 optional field: the preflight verdict string
            # ("ok" | "race"; rejections write their own row from the
            # service with verdict "invalid")
            row["preflight"] = pf["verdict"]
            if pf.get("signature"):
                # custom (inline-program) rows carry the structural
                # signature, so a model:"custom" row is attributable
                # to a nest shape without replaying the document
                row["signature"] = pf["signature"]
        # schema-v2 resilience outcomes: only stamped when they
        # happened, so pre-resilience rows and quiet requests keep the
        # exact same shape (and bytes) as before
        if outcome.get("shed"):
            row["shed"] = True
        if outcome.get("hedged"):
            row["hedged"] = True
        if outcome.get("retries"):
            row["retries"] = int(outcome["retries"])
        # schema-v2 progressive-precision columns: stamped only for
        # progressive executions, so every other row keeps its exact
        # pre-progressive bytes. band_width is finite by the time a
        # round has completed; guard anyway so a ledger row can never
        # carry a non-JSON float
        if outcome.get("rounds") is not None:
            row["rounds"] = int(outcome["rounds"])
        bw = outcome.get("band_width")
        if bw is not None and math.isfinite(float(bw)):
            row["band_width"] = round(float(bw), 6)
        if outcome.get("converged") is not None:
            row["converged"] = bool(outcome["converged"])
        for stage in ("queue_s", "batch_wait_s", "execute_s"):
            v = outcome.get(stage)
            if v is not None:
                row[stage] = round(float(v), 6)
        # schema-v2 utilization attribution block: stamped only when
        # the attribution layer produced one, so rows without it keep
        # their exact pre-attribution bytes
        if outcome.get("utilization") is not None:
            row["utilization"] = outcome["utilization"]
        with self._lock:
            row["coalesced"] = self._coalesced_by_fp.pop(
                fingerprint, 0
            )
        if outcome["error"] is not None:
            row["error"] = str(outcome["error"])[:300]
        if extra:
            row.update(extra)
        try:
            obs_ledger.append(self.ledger_path, row)
            self._count("ledger_rows")
        except Exception:
            self._count("ledger_write_failed")

    def _breaker(self, engine: str) -> CircuitBreaker:
        """The lazily-created per-engine circuit breaker."""
        with self._lock:
            br = self._breakers.get(engine)
            if br is None:
                r = self._resilience
                br = CircuitBreaker(
                    failures=r.breaker_failures,
                    probation_s=r.breaker_probation_s,
                    escalation=r.breaker_escalation,
                    probation_max_s=r.breaker_probation_max_s,
                )
                self._breakers[engine] = br
            return br

    def _fire_partial(self, fingerprint: str, doc: dict) -> None:
        """Deliver one interim-round doc to every partial subscriber
        of this fingerprint (executor + coalesced joiners). A
        subscriber blow-up is ITS problem — counted, never allowed to
        sink the executing round loop."""
        with self._lock:
            subs = list(self._partial_subs.get(fingerprint, ()))
        for cb in subs:
            try:
                cb(doc)
            except Exception:
                self._count("partial_emit_failed")

    def _run_progressive(self, request, program, machine, fingerprint,
                         trace_id: str | None = None,
                         span_id: str | None = None,
                         meta: dict | None = None):
        """The progressive-precision execution path (same return shape
        as _run_chain): rounds of increasing sample prefixes with a
        bootstrap confidence band between rounds, streaming one
        `partial` doc per completed round to the subscribers.

        Deadline handling is COOPERATIVE, not an engine downgrade:
        when the request deadline expires at a round boundary, the
        tightest band reached so far IS the answer — returned as a
        `partial_final` record with a `precision:band=<w>@round=<r>`
        degrade hop. The hop makes the result degraded, so the
        existing cache guard keeps it out of the persistent cache;
        converged runs (band under tolerance, or the full schedule —
        which is bit-identical to the one-shot sampled run) return
        undegraded and cache under the normal fingerprint."""
        from ..sampler.sampled import run_sampled_progressive

        deadline = (
            None if request.deadline_s is None
            else time.perf_counter() + request.deadline_s
        )
        v2 = request.runtime == "v2"

        def should_stop() -> bool:
            return (deadline is not None
                    and time.perf_counter() >= deadline)

        def on_round(info) -> None:
            self._count("partials_emitted")
            self._fire_partial(fingerprint, {
                "partial": True,
                "round": info["round"],
                "rounds_total": info["rounds_total"],
                "band_width": float(info["band_width"]),
                "converged": bool(info["converged"]),
                "mrc_digest": obs_ledger.mrc_digest(info["mrc"]),
                "mrc_len": int(len(info["mrc"])),
                "mrc_lines": report.mrc_lines(
                    info["mrc"], header=False
                ),
            })

        attrs = {"engine": "sampled", "program": program.name,
                 "progressive": True}
        if trace_id is not None:
            attrs["trace_id"] = trace_id
        if span_id is not None:
            attrs["span_id"] = span_id
        try:
            with telemetry.span("service_exec", **attrs):
                faults.fire("engine_execute", key=fingerprint,
                            engine="sampled", model=program.name)
                state, results, info = run_sampled_progressive(
                    program, machine, sampler_config(request), v2=v2,
                    on_round=on_round, should_stop=should_stop,
                    fault_key=fingerprint,
                )
                record = build_record(
                    request, machine, "sampled", fingerprint,
                    _sampled_namespace(state, results), results,
                )
        except Exception as e:
            return None, [], repr(e), None
        degraded: list[dict] = []
        prog = {
            "rounds": info["rounds"],
            "band_width": info["band_width"],
            "converged": info["converged"],
        }
        if info["stopped"] == "deadline":
            # NOT an engine downgrade: sampled answered, just at a
            # looser precision than a full schedule would have
            prog["partial_final"] = True
            self._count("partial_final")
            self._note_degrade(
                degraded, fingerprint, "sampled", "sampled",
                "precision:band={:.4g}@round={}".format(
                    info["band_width"], info["rounds"],
                ),
            )
        else:
            self._count("progressive_converged")
        if meta is not None:
            meta["progressive"] = prog
        return record, degraded, None, None

    def _run_chain(self, request, program, machine, fingerprint,
                   trace_id: str | None = None,
                   span_id: str | None = None,
                   meta: dict | None = None):
        """Walk the degradation chain under the request deadline.
        Returns (record|None, degraded events, error|None,
        replica_id|None — the replica that served the successful
        attempt). `meta` collects resilience bookkeeping (retries,
        hedged) for the outcome/ledger row.

        Per engine: the circuit breaker gates the attempt (open =
        skip down the chain for free), then up to 1 + max_retries
        attempts run under the per-attempt budget — the request
        deadline on non-final engines (the pre-resilience behavior),
        tightened everywhere by the opt-in attempt_timeout_s. Retry
        backoff is deterministic (runtime/faults.py::backoff_delay —
        seeded jitter keyed by (fingerprint, engine, attempt), so a
        chaos replay waits the same milliseconds). An attempt TIMEOUT
        never trips the breaker: the abandoned thread may still be
        computing a perfectly good answer; only raised failures
        count."""
        if progressive_requested(request):
            return self._run_progressive(
                request, program, machine, fingerprint,
                trace_id=trace_id, span_id=span_id, meta=meta,
            )
        chain = degrade_chain(request.engine)
        deadline = (
            None if request.deadline_s is None
            else time.perf_counter() + request.deadline_s
        )
        degraded: list[dict] = []
        last_error = None
        res = self._resilience
        for i, engine in enumerate(chain):
            is_last = i == len(chain) - 1
            remaining = (
                None if deadline is None
                else deadline - time.perf_counter()
            )
            if remaining is not None and remaining <= 0 and not is_last:
                # budget already spent: jump toward the cheapest
                # engine rather than starting one we would abandon
                self._note_degrade(
                    degraded, fingerprint, engine, chain[i + 1],
                    "deadline exhausted before attempt",
                )
                continue
            br = self._breaker(engine)
            if not br.allow():
                # fail fast past a repeatedly-failing engine: no
                # attempt budget burned, no side thread spawned
                self._count("breaker_open_skips")
                telemetry.event("service_breaker_open_skip",
                                engine=engine, fingerprint=fingerprint)
                reason = f"engine {engine!r} circuit breaker open"
                if is_last:
                    return None, degraded, last_error or reason, None
                self._note_degrade(
                    degraded, fingerprint, engine, chain[i + 1], reason
                )
                continue
            attempt = 0
            fail_reason = None
            while True:
                remaining = (
                    None if deadline is None
                    else deadline - time.perf_counter()
                )
                if (remaining is not None and remaining <= 0
                        and not is_last):
                    fail_reason = (
                        f"deadline {request.deadline_s}s overrun"
                    )
                    break
                budget = (
                    remaining
                    if remaining is not None and not is_last
                    else None
                )
                if res.attempt_timeout_s is not None:
                    budget = (
                        res.attempt_timeout_s if budget is None
                        else min(budget, res.attempt_timeout_s)
                    )
                # which bound would an overrun have hit? the request
                # deadline means degrade (retrying cannot help); the
                # attempt timeout means the attempt was slow and a
                # retry may land on a healthier replica
                deadline_limited = (
                    remaining is not None
                    and not is_last
                    and (budget is None or budget >= remaining)
                )
                try:
                    if budget is None:
                        record, rid, events = self._execute_routed(
                            lambda eng=engine: execute_request(
                                request, program, machine, eng,
                                fingerprint, self.runner,
                                trace_id=trace_id, span_id=span_id,
                            ),
                            trace_id=trace_id, meta=meta,
                        )
                    else:
                        hit = self._attempt_with_timeout(
                            request, program, machine, engine,
                            fingerprint, budget, trace_id=trace_id,
                            span_id=span_id, meta=meta,
                        )
                        if hit is None:
                            raise _AttemptTimeout()
                        record, rid, events = hit
                except _AttemptTimeout:
                    if deadline_limited:
                        fail_reason = (
                            f"deadline {request.deadline_s}s overrun"
                        )
                        break
                    last_error = fail_reason = (
                        f"attempt timeout {res.attempt_timeout_s}s "
                        f"overrun on {engine!r}"
                    )
                except Exception as e:
                    last_error = repr(e)
                    fail_reason = f"engine failed: {last_error[:200]}"
                    telemetry.count("service_exec_failed")
                    if br.failure():
                        self._count("breaker_opened")
                        telemetry.event(
                            "service_breaker_opened", engine=engine,
                            fingerprint=fingerprint,
                        )
                else:
                    if br.success():
                        self._count("breaker_reclosed")
                        telemetry.event(
                            "service_breaker_reclosed", engine=engine
                        )
                    self._absorb_replica_events(
                        degraded, events, fingerprint
                    )
                    return record, degraded, None, rid
                if attempt >= res.max_retries:
                    break
                delay = faults.backoff_delay(
                    attempt, res.backoff_base_s, res.backoff_max_s,
                    res.backoff_seed, fingerprint, engine,
                )
                if deadline is not None and (
                    deadline - time.perf_counter() - delay <= 0
                ):
                    break  # no budget left to retry into
                time.sleep(delay)
                attempt += 1
                self._count("retried")
                if meta is not None:
                    meta["retries"] = meta.get("retries", 0) + 1
            if is_last:
                return (
                    None, degraded,
                    last_error or fail_reason or "no engine attempted",
                    None,
                )
            self._note_degrade(
                degraded, fingerprint, engine, chain[i + 1],
                fail_reason or "engine failed",
            )
        return None, degraded, last_error or "no engine attempted", None

    def _attempt_with_timeout(self, request, program, machine, engine,
                              fingerprint, budget_s: float,
                              trace_id=None, span_id=None,
                              meta: dict | None = None):
        """Run one attempt in a side thread and wait at most budget_s.
        None = overrun (the attempt thread is abandoned; Python offers
        no preemption, so its work completes unobserved). On success
        returns (record, replica_id|None, re-route events)."""
        box: dict = {}

        def target():
            try:
                box["result"] = self._execute_routed(
                    lambda: execute_request(
                        request, program, machine, engine,
                        fingerprint, self.runner,
                        trace_id=trace_id, span_id=span_id,
                    ),
                    trace_id=trace_id, meta=meta,
                )
            except Exception as e:
                box["error"] = e

        t = threading.Thread(
            target=target, daemon=True,
            name=f"pluss-service-attempt-{engine}",
        )
        t.start()
        t.join(budget_s)
        if t.is_alive():
            self._count("deadline_abandoned")
            return None
        if "error" in box:
            raise box["error"]
        return box["result"]

    def _note_degrade(self, degraded, fingerprint, from_engine,
                      to_engine, reason: str) -> None:
        info = {
            "from": from_engine,
            "to": to_engine,
            "reason": reason,
        }
        degraded.append(info)
        # counted per REQUEST at completion (in _process /
        # _solo_fallback), not per chain step, so all three counter
        # surfaces agree on what "degraded" means: requests that
        # completed degraded. The per-step detail stays in the event.
        telemetry.event(
            "service_degraded", fingerprint=fingerprint, **info
        )
