"""Multi-process serving fabric: a fingerprint-sharded router over N
engine workers.

Layers (each its own module, each independently testable):

    wire.py    length-delimited JSON frames over sockets (the fabric
               wire protocol: hello/request/response/ping/pong/
               shutdown/bye/error), with a hard frame cap
    ring.py    consistent hashing of request fingerprints onto worker
               ids — affinity, restart stability, bounded failover
    worker.py  WorkerServer: a TCP front over ONE full serving stack
               (AnalysisService: executor + replica pool + preflight
               + in-memory LRU over its own device slice), parsing
               forwarded request lines with serve_jsonl's exact
               per-line semantics
    router.py  Router: the dispatch plane — heartbeats, bounded
               reconnect, exactly-once re-dispatch to ring
               successors, file/stdin AND TCP serving fronts

The fabric invariant: same MRC bytes and same fingerprints for one
process vs N workers, cold and warm, solo and batched
(tests/test_fabric.py pins it; tools/check_fabric.py gates it in CI
with real subprocesses).
"""

from .ring import HashRing
from .router import Entry, Router, WorkerLink
from .wire import (
    MAX_FRAME_BYTES,
    WIRE_VERSION,
    Conn,
    ConnectionClosed,
    FrameTooLarge,
    WireError,
    connect,
    encode_frame,
    parse_hostport,
)
from .worker import WorkerServer, handle_line, response_doc

__all__ = [
    "HashRing",
    "Entry",
    "Router",
    "WorkerLink",
    "WorkerServer",
    "handle_line",
    "response_doc",
    "Conn",
    "ConnectionClosed",
    "FrameTooLarge",
    "WireError",
    "connect",
    "encode_frame",
    "parse_hostport",
    "MAX_FRAME_BYTES",
    "WIRE_VERSION",
]
