"""Consistent-hash ring: fingerprint -> worker assignment.

The router hashes every request fingerprint onto a ring of virtual
nodes (`vnodes` sha256 points per worker id), so:

- **Affinity**: a fingerprint always lands on the same worker, making
  that worker's in-memory LRU and structure-keyed jit caches hit
  naturally (the disk store stays the shared tier behind everyone).
- **Stability**: the ring is a pure function of the worker ID SET —
  not of addresses, connection order, or time — so assignment is
  identical across router restarts (tools/check_fabric.py pins it)
  and adding worker K+1 moves only ~1/(K+1) of the space.
- **Bounded failover**: when a worker dies, its fingerprints fall to
  their ring successor among the survivors; everyone else's
  assignment is untouched. The `preference` order makes the failover
  target auditable offline: a ledger row's worker_id must be one of
  the first few entries of preference(fingerprint)
  (tools/check_ledger.py --stats validates exactly that).

Pure stdlib (hashlib + bisect) — jax-free, deterministic everywhere.
"""

from __future__ import annotations

import bisect
import hashlib


def _point(label: str) -> int:
    """64-bit ring coordinate of a label (sha256 prefix)."""
    return int(hashlib.sha256(label.encode("utf-8")).hexdigest()[:16],
               16)


class HashRing:
    """Consistent hashing over integer worker ids."""

    def __init__(self, worker_ids, vnodes: int = 64):
        ids = sorted(set(int(w) for w in worker_ids))
        if not ids:
            raise ValueError("ring needs at least one worker id")
        if vnodes < 1:
            raise ValueError("vnodes must be >= 1")
        self.worker_ids = tuple(ids)
        self.vnodes = vnodes
        points = []
        for wid in ids:
            for v in range(vnodes):
                points.append((_point(f"worker:{wid}#{v}"), wid))
        points.sort()
        self._points = [p for p, _ in points]
        self._owners = [w for _, w in points]

    def preference(self, fingerprint: str, k: int | None = None
                   ) -> list[int]:
        """The first k DISTINCT worker ids in ring order from the
        fingerprint's position: preference[0] is the primary
        assignment, preference[1] the re-dispatch successor when the
        primary dies, and so on."""
        if k is None:
            k = len(self.worker_ids)
        k = min(k, len(self.worker_ids))
        start = bisect.bisect_right(
            self._points, _point(f"fp:{fingerprint}")
        )
        out: list[int] = []
        n = len(self._owners)
        for i in range(n):
            wid = self._owners[(start + i) % n]
            if wid not in out:
                out.append(wid)
                if len(out) >= k:
                    break
        return out

    def assign(self, fingerprint: str, alive=None) -> int:
        """The owner of `fingerprint`: the first preference entry, or
        — when an `alive` id set is given — the first LIVE one (the
        ring successor rule the router's re-dispatch follows). Raises
        LookupError when no candidate is alive."""
        for wid in self.preference(fingerprint):
            if alive is None or wid in alive:
                return wid
        raise LookupError("no live worker for fingerprint")
