"""Fabric router: fingerprint-sharded dispatch over N engine workers.

The router is the fabric's thin control plane: it accepts the
EXISTING serve JSONL protocol — from a file/stdin batch (exactly like
`serve` mode) and from TCP clients speaking plain JSONL lines — and
forwards each request line, RAW, to one worker chosen by consistent-
hashing the request's service fingerprint (service/fingerprint.py)
onto the worker ring (service/fabric/ring.py). Raw-line forwarding is
the bit-identity lever: the worker parses/validates/fingerprints the
same bytes serve_jsonl would, so the fabric can never change what a
request means — only where it runs. The router executes no engine
work and never initializes a device backend; it parses lines only to
compute the routing fingerprint (jax-free code: models + frontend +
service/fingerprint.py).

Routing rules, in order:
- oversize lines (> api.MAX_REQUEST_LINE_BYTES) are refused AT the
  router with serve_jsonl's exact error + best-effort id echo (the
  payload never travels);
- control lines answer AT the router: `healthz` with the fabric view
  (link states, per-link heartbeat RTT), `stats`/`metrics` with the
  MERGED fleet view (per-worker sections polled over `stats` frames
  plus numeric fleet sums / summed registry snapshots), and
  `dump_debug` by fanning out to every worker and writing a router
  bundle that indexes the per-worker bundles by trace_id; unknown
  types and malformed lines still forward by content digest — the
  owning worker produces the identical structured error serve_jsonl
  would;
- everything else routes by its service fingerprint, computed here
  exactly as the worker will compute it (memoized per canonical
  payload), falling back to the line's content digest when the line
  cannot be parsed/built.

Failure semantics: each worker link runs per-connection heartbeats
(ping/pong every FabricConfig.hb_interval_s; silence past
hb_timeout_s fails the link) and a BOUNDED reconnect schedule. A
reconnect re-sends that link's in-flight frames (the worker's
re-submission coalesces or cache-hits bit-identically). Exhausted
reconnects declare the worker DEAD: its in-flight requests re-dispatch
to each fingerprint's ring successor among the survivors — EXACTLY
once per hop, recorded in the response's degrade chain as
{"from": "worker:K", "to": "worker:J", "reason":
"worker_disconnect"}, the same shape replica re-routes use. Entry
ownership makes resolution exactly-once: a response is accepted only
from a seq's current owner, so a zombie link's late answer is dropped.

Chaos: every request-frame send fires the `worker_conn` site —
latency/hang delay the send; raise/disconnect sever that link
(bounded reconnect, then re-dispatch), which is the seeded partition
scenario tools/check_chaos.py pins.
"""

from __future__ import annotations

import json
import re
import socket
import threading
import time
import uuid
from collections import deque

from ...runtime import faults
from ...runtime.obs import ledger as obs_ledger
from ...runtime.obs import metrics as obs_metrics
from .. import api
from ..fingerprint import content_digest
from . import wire
from .ring import HashRing

# Live-registry histogram names the router observes into (module-level
# obs_metrics.observe: no-ops when no registry is enabled). Per-link
# series ride the same names with a `_worker_<id>` suffix.
HB_RTT_HISTOGRAM = "fabric_hb_rtt_s"
WIRE_HISTOGRAM = "fabric_wire_s"


def _id_echo(line: str) -> str | None:
    """serve_jsonl's best-effort id echo for refused lines."""
    m = re.search(r'"id"\s*:\s*"([^"\\]{1,120})"', line[:4096])
    return m.group(1) if m else None


class Entry:
    """One routed request line: resolved exactly once."""

    __slots__ = ("seq", "line", "line_no", "req_id", "fp", "owner",
                 "hops", "degrade", "doc", "trace_id", "span_id",
                 "meta", "t_created", "t_routed", "t_sent", "_event",
                 "_callback", "_lock", "on_partial")

    def __init__(self, seq: int, line: str, line_no: int):
        self.seq = seq
        self.line = line
        self.line_no = line_no
        # streamed progressive-precision round docs forward through
        # this callback (set by the serving front before routing)
        self.on_partial = None
        self.req_id: str | None = None
        self.fp: str | None = None
        self.owner: int | None = None
        self.hops = 0
        self.degrade: list = []
        self.doc: dict | None = None
        # trace context + span stamps (router-local perf_counter —
        # every span is a single-host monotonic delta)
        self.trace_id: str | None = None
        self.span_id: str | None = None
        self.meta: dict | None = None  # parsed model/n/engine
        self.t_created = time.perf_counter()
        self.t_routed: float | None = None
        self.t_sent: float | None = None
        self._event = threading.Event()
        self._callback = None
        self._lock = threading.Lock()

    def on_done(self, fn) -> None:
        """Run fn(doc) at resolution (immediately if already done)."""
        with self._lock:
            if self.doc is None:
                self._callback = fn
                return
        fn(self.doc)

    def wait(self, timeout: float | None = None) -> dict | None:
        self._event.wait(timeout)
        return self.doc

    @property
    def resolved(self) -> bool:
        return self.doc is not None


class WorkerLink:
    """One router->worker connection with heartbeats and bounded
    reconnect. Owns the in-flight entries routed to its worker."""

    def __init__(self, router: "Router", index: int,
                 host: str, port: int):
        self.router = router
        self.index = index
        self.worker_id = index  # refined by the worker's hello
        self.host = host
        self.port = port
        self.state = "connecting"  # connecting | up | dead
        self.inflight: dict[int, Entry] = {}
        self.dispatched = 0
        self.reconnects = 0
        # heartbeat RTTs (token-matched pongs) + the wall time of the
        # last pong, for healthz's rtt_p95_s / last_pong_age_s
        self.rtts: deque = deque(maxlen=64)
        self.last_pong: float | None = None
        # the worker's latest periodic telemetry snapshot (stats
        # frames), feeding the merged fleet stats//metrics view
        self.last_snapshot: dict | None = None
        self.last_snapshot_at: float | None = None
        self._stats_waiters: dict = {}  # token -> [Event, payload]
        self._conn: wire.Conn | None = None
        self._lock = threading.Lock()
        self._closed = threading.Event()
        self._bye = threading.Event()
        self._up_once = threading.Event()
        self._thread = threading.Thread(
            target=self._run, name=f"pluss-fabric-link-{index}",
            daemon=True,
        )

    def start(self) -> None:
        self._thread.start()

    def wait_up(self, timeout: float | None = None) -> bool:
        self._up_once.wait(timeout)
        return self.state == "up"

    # -- dispatch ------------------------------------------------------

    def dispatch(self, entry: Entry) -> None:
        """Adopt the entry (it survives reconnects in `inflight`) and
        push its frame if the link is up — a down link sends it on
        reconnect, a dying one hands it to re-dispatch."""
        with self._lock:
            self.inflight[entry.seq] = entry
            entry.owner = self.worker_id
            self.dispatched += 1
        if self.state == "up":
            self._send_request(entry)

    def _send_request(self, entry: Entry) -> None:
        conn = self._conn
        if conn is None:
            return
        frame = {"type": "request", "seq": entry.seq,
                 "line": entry.line, "line_no": entry.line_no}
        if entry.trace_id is not None:
            frame["trace"] = {
                "trace_id": entry.trace_id,
                "span_id": entry.span_id,
                "sent_s": round(time.perf_counter(), 6),
            }
        try:
            faults.fire("worker_conn", key=entry.seq,
                        worker_id=self.worker_id)
            entry.t_sent = time.perf_counter()
            conn.send(frame)
        except wire.FrameTooLarge:
            # this entry can never travel: answer it, don't kill the
            # link (pop first so re-dispatch cannot double-answer)
            with self._lock:
                self.inflight.pop(entry.seq, None)
            self.router._resolve(entry, {
                "id": entry.req_id or _id_echo(entry.line),
                "ok": False, "line": entry.line_no,
                "error": "request line does not fit a fabric frame",
            })
        except (faults.FaultInjected, wire.WireError, OSError):
            # injected or real send failure: sever the link — the
            # reader notices, reconnect re-sends everything in flight
            conn.close()

    # -- connection lifecycle ------------------------------------------

    def _run(self) -> None:
        fabric = self.router.fabric
        attempts = 0
        while not self._closed.is_set():
            conn = None
            try:
                conn = wire.connect(
                    self.host, self.port,
                    timeout=fabric.connect_timeout_s,
                )
                conn.send({"type": "hello",
                           "wire_version": wire.WIRE_VERSION,
                           "role": "router"})
                hello = conn.recv(timeout=fabric.connect_timeout_s)
                if hello is None or hello.get("type") != "hello":
                    raise wire.WireError(
                        "handshake refused: "
                        + str((hello or {}).get("error")
                              or "no hello reply")
                    )
                wid = hello.get("worker_id")
                if isinstance(wid, int):
                    self.worker_id = wid
                self._conn = conn
                self.state = "up"
                attempts = 0
                self._up_once.set()
                # re-send everything still in flight: the responses
                # lost with the old socket re-materialize from the
                # worker's cache/singleflight, bit-identical
                with self._lock:
                    pending = list(self.inflight.values())
                for entry in pending:
                    self._send_request(entry)
                self._read_loop(conn)
                return  # clean exit (bye/close)
            except (wire.WireError, OSError, socket.timeout):
                pass
            finally:
                if conn is not None and self._conn is conn:
                    self._conn = None
                if conn is not None:
                    conn.close()
            if self._closed.is_set():
                return
            self.state = "connecting"
            attempts += 1
            self.reconnects += 1
            if attempts > fabric.reconnect_attempts:
                self.state = "dead"
                self.router._on_link_dead(self)
                return
            time.sleep(fabric.reconnect_delay_s)

    def _read_loop(self, conn: wire.Conn) -> None:
        fabric = self.router.fabric
        while not self._closed.is_set():
            frame = conn.recv(timeout=fabric.hb_timeout_s)
            if frame is None:
                raise wire.ConnectionClosed("worker closed the link")
            kind = frame.get("type")
            if kind == "response":
                self.router._on_response(self, frame)
            elif kind == "partial":
                self.router._on_partial(self, frame)
            elif kind == "pong":
                self._on_pong(frame)
            elif kind == "stats":
                self._on_stats(frame)
            elif kind == "bye":
                self._bye.set()
                return
            # error frames are just liveness traffic

    def ping(self) -> None:
        conn = self._conn
        if self.state == "up" and conn is not None:
            try:
                # the token is this process's perf_counter: the echo
                # yields the link RTT from one monotonic clock
                conn.send({"type": "ping",
                           "t": time.perf_counter()})
            except (wire.WireError, OSError):
                conn.close()

    def _on_pong(self, frame: dict) -> None:
        """Pongs used to be discarded liveness traffic; the echoed
        token now yields the per-link heartbeat RTT."""
        self.last_pong = time.time()
        t = frame.get("t")
        if not isinstance(t, (int, float)):
            return
        rtt = time.perf_counter() - float(t)
        if rtt < 0:  # a pre-restart token echoed late
            return
        self.rtts.append(rtt)
        obs_metrics.observe(HB_RTT_HISTOGRAM, rtt)
        obs_metrics.observe(
            f"{HB_RTT_HISTOGRAM}_worker_{self.worker_id}", rtt
        )

    def rtt_p95_s(self) -> float | None:
        rtts = sorted(self.rtts)
        if not rtts:
            return None
        return rtts[min(len(rtts) - 1, int(0.95 * (len(rtts) - 1)))]

    # -- fleet telemetry ----------------------------------------------

    def request_stats(self, want, extra: dict | None = None,
                      timeout: float = 5.0) -> dict | None:
        """Synchronously poll this worker's telemetry snapshot over a
        `stats` frame; None when the link is down or the worker does
        not answer inside `timeout`."""
        conn = self._conn
        if self.state != "up" or conn is None:
            return None
        token = self.router._next_stats_token()
        waiter = [threading.Event(), None]
        with self._lock:
            self._stats_waiters[token] = waiter
        frame = {"type": "stats", "token": token, "want": list(want)}
        if extra:
            frame.update(extra)
        try:
            conn.send(frame)
        except (wire.WireError, OSError):
            with self._lock:
                self._stats_waiters.pop(token, None)
            conn.close()
            return None
        waiter[0].wait(timeout)
        with self._lock:
            self._stats_waiters.pop(token, None)
        snap = waiter[1]
        return snap if isinstance(snap, dict) else None

    def _on_stats(self, frame: dict) -> None:
        with self._lock:
            waiter = self._stats_waiters.pop(frame.get("token"), None)
        if waiter is not None:
            waiter[1] = frame.get("snapshot")
            waiter[0].set()

    def drain_inflight(self) -> list[Entry]:
        with self._lock:
            entries = list(self.inflight.values())
            self.inflight.clear()
        return entries

    def take(self, seq: int) -> Entry | None:
        with self._lock:
            return self.inflight.pop(seq, None)

    def peek(self, seq: int) -> Entry | None:
        """Non-removing inflight lookup — partial frames observe the
        entry without resolving it (the response frame still pops)."""
        with self._lock:
            return self.inflight.get(seq)

    def shutdown(self, timeout: float) -> bool:
        """Graceful: ask the worker to drain, wait for its bye."""
        conn = self._conn
        if conn is not None and self.state == "up":
            try:
                conn.send({"type": "shutdown"})
            except (wire.WireError, OSError):
                pass
            self._bye.wait(timeout)
        self.close()
        return self._bye.is_set()

    def close(self) -> None:
        self._closed.set()
        conn = self._conn
        if conn is not None:
            conn.close()
        if self._thread.is_alive():
            self._thread.join(timeout=5.0)


class Router:
    """The fabric's dispatch plane over a set of worker addresses."""

    def __init__(self, worker_addrs, fabric=None,
                 ledger_path: str | None = None):
        from ...config import FabricConfig

        if not worker_addrs:
            raise ValueError("router needs at least one worker "
                             "address")
        self.fabric = fabric if fabric is not None else FabricConfig()
        # the router's OWN schema-v2 rows (source fabric.router): one
        # per traced response, carrying the span block that
        # tools/assemble_trace.py joins with the worker's row
        self.ledger_path = ledger_path
        # burn-rate parameters forwarded with every periodic stats
        # poll so workers pre-digest slo_inputs; the CLI sets this
        # from SLOConfig when the fleet sentinel is wired
        self.slo_params: dict | None = None
        self.slo_sentinel = None  # fleet SLOSentinel (CLI-attached)
        self.links = [
            WorkerLink(self, i, host, port)
            for i, (host, port) in enumerate(worker_addrs)
        ]
        self._ring: HashRing | None = None
        self._seq = 0
        self._stats_token = 0
        self._lock = threading.Lock()
        self._fp_memo: dict[str, str] = {}
        self._draining = False
        self._listener: socket.socket | None = None
        self._client_threads: list[threading.Thread] = []
        self._ticker: threading.Thread | None = None
        self._stats_ticker: threading.Thread | None = None
        self._stop = threading.Event()
        # trace_id -> worker_id for the last traced responses: the
        # dump_debug fan-out bundle's per-request index
        self._recent_traces: deque = deque(maxlen=256)
        self.counters = {
            "lines": 0, "routed": 0, "local": 0, "redispatched": 0,
            "responses": 0, "dropped_stale": 0, "no_worker": 0,
            "partials_forwarded": 0, "partials_dropped_stale": 0,
            "tcp_clients": 0, "stats_polls": 0, "router_rows": 0,
            "ledger_write_failed": 0,
        }

    def _next_stats_token(self) -> int:
        with self._lock:
            self._stats_token += 1
            return self._stats_token

    # -- lifecycle -----------------------------------------------------

    def start(self, wait_up: bool = True) -> "Router":
        """Connect every link (handshakes resolve worker ids), build
        the ring over the REPORTED ids — a pure function of the id
        set, so assignment is stable across router restarts — and
        start the heartbeat ticker."""
        for link in self.links:
            link.start()
        if wait_up:
            deadline = time.time() + self.fabric.connect_timeout_s
            for link in self.links:
                link.wait_up(max(0.1, deadline - time.time()))
        self._ring = HashRing(
            [link.worker_id for link in self.links],
            vnodes=self.fabric.ring_vnodes,
        )
        self._by_id = {link.worker_id: link for link in self.links}
        self._ticker = threading.Thread(
            target=self._heartbeat_loop, name="pluss-fabric-hb",
            daemon=True,
        )
        self._ticker.start()
        self._stats_ticker = threading.Thread(
            target=self._stats_loop, name="pluss-fabric-stats",
            daemon=True,
        )
        self._stats_ticker.start()
        return self

    def _heartbeat_loop(self) -> None:
        while not self._stop.wait(self.fabric.hb_interval_s):
            for link in self.links:
                link.ping()

    def _stats_loop(self) -> None:
        """Periodic fleet telemetry poll: refresh every live link's
        snapshot so stats//metrics/GET /metrics and the fleet SLO
        sentinel read recent per-worker data without blocking."""
        interval = self.fabric.stats_interval_s
        while not self._stop.wait(interval):
            if self._draining:
                continue
            try:
                self.poll_workers(
                    ("stats", "metrics", "slo_inputs"),
                    timeout=min(interval, 5.0), store=True,
                )
            except Exception:
                pass  # telemetry must never take routing down

    def alive_ids(self) -> set:
        return {link.worker_id for link in self.links
                if link.state != "dead"}

    # -- routing -------------------------------------------------------

    def _routing_fingerprint(self, line: str
                             ) -> tuple[str, dict | None]:
        """(fingerprint, meta) for this line. The fingerprint is the
        worker's service fingerprint — computed HERE with the same
        parse/build path (jax-free), memoized per canonical payload;
        content digest for lines a worker will refuse (their errors
        need determinism, not affinity; meta is None for those).
        `meta` carries the parsed serving metadata the router's own
        ledger row needs (model/n/engine + any caller trace_id)."""
        try:
            request = api.parse_request_line(line)
            key = json.dumps(request.payload(), sort_keys=True,
                             default=str)
            fp = self._fp_memo.get(key)
            if fp is None:
                fp = request.fingerprint()
                if len(self._fp_memo) >= 4096:
                    self._fp_memo.clear()
                self._fp_memo[key] = fp
            return fp, {
                "model": request.model, "n": request.n,
                "engine": request.engine,
                "trace_id": request.trace_id,
            }
        except Exception:
            return content_digest({"line": line}), None

    def submit_line(self, line: str, line_no: int = 0,
                    on_partial=None) -> Entry:
        """Route one JSONL line; returns its Entry (resolving to the
        serve-protocol response dict). `on_partial` receives any
        progressive-precision round docs the owning worker streams
        ahead of the final response (already id-tagged)."""
        with self._lock:
            self._seq += 1
            entry = Entry(self._seq, line.strip(), line_no)
        entry.on_partial = on_partial
        self.counters["lines"] += 1
        line = entry.line
        if len(line) > api.MAX_REQUEST_LINE_BYTES:
            entry.req_id = _id_echo(line)
            self.counters["local"] += 1
            self._resolve(entry, {
                "id": entry.req_id, "ok": False, "line": line_no,
                "error": (
                    f"request line of {len(line)} bytes exceeds the "
                    f"{api.MAX_REQUEST_LINE_BYTES}-byte limit"
                ),
            })
            return entry
        try:
            doc = json.loads(line)
        except ValueError:
            doc = None
        if isinstance(doc, dict):
            entry.req_id = doc.get("id")
        if isinstance(doc, dict) and doc.get("type") in (
            "healthz", "stats", "metrics", "dump_debug"
        ):
            # fabric-local introspection: the router IS the authority
            # on link/dispatch state AND — via the stats-frame fan-out
            # — on the merged fleet view, so no control line rides to
            # one arbitrary worker anymore: stats/metrics answer with
            # per-worker sections plus fleet sums, dump_debug makes
            # EVERY worker (and the router) write a bundle
            kind = doc["type"]
            try:
                payload = {
                    "healthz": self.healthz,
                    "stats": self.fleet_stats,
                    "metrics": self.fleet_metrics,
                    "dump_debug": self.fleet_dump_debug,
                }[kind]()
                out = {"id": entry.req_id, "ok": True,
                       "type": kind, kind: payload}
            except Exception as e:
                out = {"id": entry.req_id, "ok": False,
                       "line": line_no,
                       "error": f"introspection failed: {e!r}"}
            self.counters["local"] += 1
            self._resolve(entry, out)
            return entry
        if self._draining:
            self._resolve(entry, {
                "id": entry.req_id, "ok": False, "line": line_no,
                "shed": True,
                "error": "shed: router shutting down",
            })
            return entry
        entry.fp, entry.meta = self._routing_fingerprint(line)
        if self.fabric.trace_enabled and entry.meta is not None:
            # adopt the caller's trace_id when the line names one
            # (the worker parses the same bytes and agrees), mint
            # otherwise — either way router and worker rows join
            entry.trace_id = (entry.meta.get("trace_id")
                              or uuid.uuid4().hex[:16])
            entry.span_id = uuid.uuid4().hex[:16]
        self._route(entry)
        return entry

    def _route(self, entry: Entry) -> None:
        entry.t_routed = time.perf_counter()
        try:
            wid = self._ring.assign(entry.fp, alive=self.alive_ids())
        except LookupError:
            self.counters["no_worker"] += 1
            self._resolve(entry, {
                "id": entry.req_id, "ok": False,
                "line": entry.line_no,
                "error": "no live fabric workers",
            })
            return
        self.counters["routed"] += 1
        self._by_id[wid].dispatch(entry)

    # -- link events ---------------------------------------------------

    def _on_response(self, link: WorkerLink, frame: dict) -> None:
        t_done = time.perf_counter()
        seq = frame.get("seq")
        doc = frame.get("doc")
        entry = link.take(seq) if isinstance(seq, int) else None
        if entry is None or entry.owner != link.worker_id:
            # a zombie link answering a re-dispatched seq: the current
            # owner's answer is the one that counts — exactly-once
            self.counters["dropped_stale"] += 1
            return
        if not isinstance(doc, dict):
            doc = {"id": entry.req_id, "ok": False,
                   "line": entry.line_no,
                   "error": "malformed response frame from worker"}
        if entry.degrade:
            # the re-dispatch hops this entry survived, ahead of any
            # engine-level degradation the worker recorded — the same
            # chain shape replica re-routes use
            doc = dict(doc)
            doc["degraded"] = entry.degrade + list(
                doc.get("degraded") or []
            )
        self.counters["responses"] += 1
        if entry.trace_id is not None:
            try:
                self._record_spans(link, entry, frame, doc, t_done)
            except Exception:
                self.counters["ledger_write_failed"] += 1
        self._resolve(entry, doc)

    def _record_spans(self, link: WorkerLink, entry: Entry,
                      frame: dict, doc: dict, t_done: float) -> None:
        """Per-request router spans: every duration is a delta on THIS
        process's perf_counter; the worker contributes only its own
        recv->send delta (`worker_s`), so the wire split needs no
        cross-host clock agreement. wire_s = RTT - worker_s, halved
        into out/back (symmetric-path estimate, Cristian's
        algorithm)."""
        trace = frame.get("trace")
        worker_s = (trace.get("worker_s")
                    if isinstance(trace, dict) else None)
        rtt = (t_done - entry.t_sent
               if entry.t_sent is not None else None)
        wire_s = None
        if (rtt is not None and isinstance(worker_s, (int, float))):
            wire_s = max(0.0, rtt - float(worker_s))
        self._recent_traces.append(
            {"trace_id": entry.trace_id, "worker_id": link.worker_id}
        )
        if wire_s is not None:
            obs_metrics.observe(WIRE_HISTOGRAM, wire_s,
                                exemplar=entry.trace_id)
            obs_metrics.observe(
                f"{WIRE_HISTOGRAM}_worker_{link.worker_id}", wire_s
            )
        if self.ledger_path is None or entry.meta is None:
            return

        def _span(v):
            return None if v is None else round(float(v), 6)

        cache = doc.get("cache")
        row = {
            "kind": "request",
            "source": obs_ledger.ROUTER_SOURCE,
            "ok": bool(doc.get("ok")),
            "fingerprint": entry.fp,
            "engine_requested": entry.meta["engine"],
            "engine_used": doc.get("engine_used"),
            "model": entry.meta["model"],
            "n": entry.meta["n"],
            "latency_s": _span(t_done - entry.t_created),
            "cache": (cache if cache in obs_ledger.CACHE_TIERS
                      else None),
            "degraded": list(doc.get("degraded") or []),
            "mrc_digest": doc.get("mrc_digest"),
            "trace_id": entry.trace_id,
            "span_id": entry.span_id,
            "router": {
                "worker_id": link.worker_id,
                "hops": entry.hops,
                "router_queue_s": _span(
                    entry.t_routed - entry.t_created
                    if entry.t_routed is not None else None),
                "route_s": _span(
                    entry.t_sent - entry.t_routed
                    if entry.t_sent is not None
                    and entry.t_routed is not None else None),
                "worker_rtt_s": _span(rtt),
                "worker_s": _span(worker_s),
                "wire_s": _span(wire_s),
                "wire_out_s": _span(
                    wire_s / 2 if wire_s is not None else None),
                "wire_back_s": _span(
                    wire_s / 2 if wire_s is not None else None),
            },
        }
        try:
            obs_ledger.append(self.ledger_path, row)
            self.counters["router_rows"] += 1
        except Exception:
            self.counters["ledger_write_failed"] += 1

    def _on_partial(self, link: WorkerLink, frame: dict) -> None:
        """A streamed progressive-precision round from a worker:
        forward to the seq's CURRENT owner's client, never resolve.
        The same exactly-once ownership rule responses obey applies —
        a zombie link's stream for a re-dispatched seq is dropped (the
        new owner re-streams its own rounds)."""
        seq = frame.get("seq")
        doc = frame.get("doc")
        entry = link.peek(seq) if isinstance(seq, int) else None
        if (entry is None or entry.owner != link.worker_id
                or not isinstance(doc, dict)):
            self.counters["partials_dropped_stale"] += 1
            return
        self.counters["partials_forwarded"] += 1
        cb = entry.on_partial
        if cb is not None:
            try:
                cb(doc)
            except Exception:
                pass  # a client write failure never takes the link down

    def _on_link_dead(self, link: WorkerLink) -> None:
        """Reconnects exhausted: re-dispatch the dead worker's
        in-flight entries to each fingerprint's ring successor."""
        entries = link.drain_inflight()
        for entry in entries:
            entry.hops += 1
            if entry.hops >= len(self.links):
                self._resolve(entry, {
                    "id": entry.req_id, "ok": False,
                    "line": entry.line_no,
                    "error": ("no live fabric workers after "
                              f"{entry.hops} re-dispatch(es)"),
                })
                continue
            old = entry.owner
            alive = self.alive_ids()
            try:
                new = self._ring.assign(entry.fp, alive=alive)
            except LookupError:
                self.counters["no_worker"] += 1
                self._resolve(entry, {
                    "id": entry.req_id, "ok": False,
                    "line": entry.line_no,
                    "error": "no live fabric workers",
                })
                continue
            entry.degrade.append({
                "from": f"worker:{old}", "to": f"worker:{new}",
                "reason": "worker_disconnect",
            })
            self.counters["redispatched"] += 1
            self._by_id[new].dispatch(entry)

    def _resolve(self, entry: Entry, doc: dict) -> None:
        with entry._lock:
            if entry.doc is not None:
                return
            entry.doc = doc
            callback = entry._callback
            entry._callback = None
        entry._event.set()
        if callback is not None:
            try:
                callback(doc)
            except Exception:
                pass

    # -- introspection -------------------------------------------------

    def healthz(self) -> dict:
        now = time.time()
        return {
            "status": ("ok" if self.alive_ids() else "no_workers"),
            "role": "router",
            "workers": {
                str(link.worker_id): {
                    "addr": f"{link.host}:{link.port}",
                    "state": link.state,
                    "in_flight": len(link.inflight),
                    "rtt_p95_s": link.rtt_p95_s(),
                    "last_pong_age_s": (
                        round(now - link.last_pong, 3)
                        if link.last_pong is not None else None
                    ),
                }
                for link in self.links
            },
            "ring": list(self._ring.worker_ids) if self._ring else [],
        }

    def stats(self) -> dict:
        return {
            "role": "router",
            "counters": dict(self.counters),
            "workers": {
                str(link.worker_id): {
                    "state": link.state,
                    "dispatched": link.dispatched,
                    "in_flight": len(link.inflight),
                    "reconnects": link.reconnects,
                }
                for link in self.links
            },
        }

    # -- fleet telemetry ----------------------------------------------

    def poll_workers(self, want, timeout: float = 5.0,
                     store: bool = False) -> dict:
        """Fan a `stats` frame out to every live link (one thread
        each — a stuck worker can't serialize the poll) and collect
        {worker_id: snapshot}. `store` keeps each snapshot on its link
        for the non-blocking readers (GET /metrics, the sentinel)."""
        extra = ({"slo": self.slo_params}
                 if self.slo_params is not None else None)
        results: dict = {}
        lock = threading.Lock()

        def _one(link: WorkerLink) -> None:
            snap = link.request_stats(want, extra=extra,
                                      timeout=timeout)
            if snap is None:
                return
            with lock:
                results[link.worker_id] = snap
            if store:
                # merge by section: a narrow poll (say metrics-only)
                # must not blank the slo_inputs the sentinel reads
                link.last_snapshot = {
                    **(link.last_snapshot or {}), **snap
                }
                link.last_snapshot_at = time.time()

        threads = []
        for link in self.links:
            if link.state != "up":
                continue
            t = threading.Thread(
                target=_one, args=(link,),
                name=f"pluss-fabric-poll-{link.worker_id}",
                daemon=True,
            )
            t.start()
            threads.append(t)
        for t in threads:
            t.join(timeout + 1.0)
        self.counters["stats_polls"] += 1
        return results

    def _worker_snapshots(self, want, refresh: bool,
                          timeout: float = 5.0) -> dict:
        """{worker_id: snapshot} — freshly polled, or each link's last
        periodic snapshot when `refresh` is False (falling back to one
        live poll if nothing has been collected yet)."""
        if refresh:
            return self.poll_workers(want, timeout=timeout,
                                     store=True)
        snaps = {
            link.worker_id: link.last_snapshot
            for link in self.links
            if link.last_snapshot is not None
        }
        if snaps:
            return snaps
        return self.poll_workers(want, timeout=timeout, store=True)

    def fleet_stats(self, refresh: bool = True) -> dict:
        """The `stats` control line's fleet answer: the router-local
        view plus each worker's `stats` section and the numeric fleet
        sums (runtime/obs/fleet.py) — consistent with the
        single-process shapes per worker, summed per fleet."""
        from ...runtime.obs import fleet as obs_fleet

        snaps = self._worker_snapshots(
            ("stats", "metrics", "slo_inputs"), refresh
        )
        return obs_fleet.fleet_stats(self.stats(), snaps)

    def fleet_metrics(self, refresh: bool = True) -> dict:
        """The `metrics` control line's fleet answer: per-worker
        registry snapshots merged with the router's own registry
        (counters/histogram buckets summed — the same shape a
        single-process `metrics` response has), plus the per-worker
        originals and the fleet SLO report when a sentinel runs."""
        from ...runtime.obs import fleet as obs_fleet

        snaps = self._worker_snapshots(("metrics",), refresh)
        reg = obs_metrics.get()
        out = obs_fleet.fleet_metrics(
            reg.snapshot() if reg is not None else None, snaps
        )
        if self.slo_sentinel is not None:
            out["slo"] = self.slo_sentinel.last_report
        return out

    def fleet_prometheus_text(self, prefix: str = "pluss_") -> str:
        """GET /metrics for the router: the merged fleet exposition
        (router registry + every worker's last-polled snapshot summed
        bucket-by-bucket). Reads the periodic snapshots — a scrape
        never blocks on N workers."""
        from ...runtime.obs import fleet as obs_fleet

        snaps = self._worker_snapshots(("metrics",), refresh=False,
                                       timeout=2.0)
        reg = obs_metrics.get()
        merged = obs_fleet.merge_registry_snapshots(
            ([reg.snapshot()] if reg is not None else [])
            + [s.get("metrics") for s in snaps.values()
               if isinstance(s.get("metrics"), dict)]
        )
        gauges = merged.setdefault("gauges", {})
        gauges["fabric_workers_up"] = sum(
            1 for link in self.links if link.state == "up"
        )
        for link in self.links:
            gauges[f"fabric_in_flight_worker_{link.worker_id}"] = len(
                link.inflight
            )
        from ...runtime.obs import exporters

        return "\n".join(exporters.prometheus_registry_lines(
            merged, prefix=prefix
        )) + "\n"

    def fleet_dump_debug(self) -> dict:
        """The `dump_debug` control line's fleet answer: every worker
        writes its own bundle (stats-frame fan-out), then the router
        writes one more whose trigger indexes the per-worker bundle
        paths and the recent trace_id -> worker_id routing decisions —
        one request, one joined post-mortem."""
        from ...runtime.obs import recorder as obs_recorder

        snaps = self.poll_workers(
            ("dump_debug",), timeout=self.fabric.drain_timeout_s
        )
        workers = {
            str(wid): snap.get("dump_debug")
            for wid, snap in snaps.items()
        }
        rec = obs_recorder.get()
        out: dict = {
            "enabled": rec is not None or any(
                isinstance(w, dict) and w.get("enabled")
                for w in workers.values()
            ),
            "fleet": True,
            "workers": workers,
            "trace_index": list(self._recent_traces),
        }
        if rec is not None:
            out["bundle"] = rec.dump("dump_debug", trigger={
                "fan_out": {
                    wid: (w or {}).get("bundle")
                    for wid, w in workers.items()
                    if isinstance(w, dict)
                },
                "trace_index": list(self._recent_traces),
            })
            out["bundle_dir"] = rec.bundle_dir
            out["recorder"] = rec.stats()
            out["bundles"] = rec.bundle_index()
        return out

    # -- serving fronts ------------------------------------------------

    def serve_stream(self, fin, fout) -> int:
        """The serve-mode front: read a JSONL batch, dispatch every
        line up front (affinity batches per worker; duplicates
        coalesce ON the owning worker), then emit responses in input
        order. Returns the failure count, like serve_jsonl. A
        GracefulShutdown in either pass stops reading and answers
        everything already dispatched."""
        entries: list[Entry] = []
        # partials stream from link reader threads while this thread
        # is still reading/emitting: one lock per output stream
        wlock = threading.Lock()

        def _stream_partial(doc: dict) -> None:
            with wlock:
                fout.write(json.dumps(doc) + "\n")
                fout.flush()

        try:
            for line_no, line in enumerate(fin, start=1):
                if not line.strip():
                    continue
                entries.append(self.submit_line(
                    line, line_no, on_partial=_stream_partial
                ))
        except api.GracefulShutdown:
            self._draining = True
        failures = 0
        for entry in entries:
            while True:
                try:
                    doc = entry.wait(
                        timeout=self.fabric.drain_timeout_s
                    )
                    break
                except api.GracefulShutdown:
                    self._draining = True
                    continue
            if doc is None:
                doc = {"id": entry.req_id, "ok": False,
                       "line": entry.line_no,
                       "error": "fabric response timed out"}
                self._resolve(entry, doc)
                doc = entry.doc
            if not doc.get("ok"):
                failures += 1
            with wlock:
                fout.write(json.dumps(doc) + "\n")
                fout.flush()
        return failures

    def serve_tcp(self, host: str = "127.0.0.1", port: int = 0
                  ) -> tuple[str, int]:
        """The TCP front: clients speak plain JSONL lines (loadgen
        --connect drives this); responses stream back AS READY —
        clients match them by `id`, since affinity dispatch makes
        input-order completion meaningless across workers."""
        ls = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        ls.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        ls.bind((host, port))
        ls.listen(16)
        self._listener = ls
        bound = ls.getsockname()[:2]
        t = threading.Thread(target=self._accept_clients,
                             name="pluss-fabric-tcp", daemon=True)
        t.start()
        return bound

    def _accept_clients(self) -> None:
        while not self._stop.is_set():
            try:
                sock, _addr = self._listener.accept()
            except OSError:
                return
            self.counters["tcp_clients"] += 1
            t = threading.Thread(
                target=self._serve_client, args=(sock,),
                name="pluss-fabric-client", daemon=True,
            )
            t.start()
            self._client_threads.append(t)

    def _serve_client(self, sock: socket.socket) -> None:
        wlock = threading.Lock()
        pending: list[Entry] = []
        rfile = sock.makefile("r", encoding="utf-8", newline="\n")
        wfile = sock.makefile("w", encoding="utf-8", newline="\n")

        def _emit(doc: dict) -> None:
            with wlock:
                try:
                    wfile.write(json.dumps(doc) + "\n")
                    wfile.flush()
                except (OSError, ValueError):
                    pass  # client went away; nothing to answer

        try:
            for line_no, line in enumerate(rfile, start=1):
                if not line.strip():
                    continue
                entry = self.submit_line(line, line_no,
                                         on_partial=_emit)
                pending.append(entry)
                entry.on_done(_emit)
            for entry in pending:
                entry.wait(timeout=self.fabric.drain_timeout_s)
        except (OSError, ValueError):
            pass
        finally:
            try:
                rfile.close()
                wfile.close()
                sock.close()
            except OSError:
                pass

    # -- shutdown ------------------------------------------------------

    def begin_shutdown(self) -> None:
        """Stop accepting: the TCP listener closes, later lines shed
        with structured responses; dispatched work keeps draining."""
        self._draining = True
        if self._listener is not None:
            try:
                self._listener.close()
            except OSError:
                pass

    def close(self, graceful: bool = True) -> None:
        """Tear the fabric's router side down. Graceful: drain
        in-flight entries, ask every live worker to drain (`shutdown`
        frame -> `bye`), then close links."""
        self.begin_shutdown()
        self._stop.set()
        if graceful:
            deadline = time.time() + self.fabric.drain_timeout_s
            for link in self.links:
                with link._lock:
                    snapshot = list(link.inflight.values())
                for entry in snapshot:
                    entry.wait(timeout=max(0.1,
                                           deadline - time.time()))
            for link in self.links:
                link.shutdown(timeout=max(
                    0.1, deadline - time.time()
                ))
        for link in self.links:
            link.close()
        # anything still unresolved (dead workers mid-drain) answers
        # as an error so no caller blocks forever
        for link in self.links:
            for entry in link.drain_inflight():
                self._resolve(entry, {
                    "id": entry.req_id, "ok": False,
                    "line": entry.line_no,
                    "error": "router closed before a worker answered",
                })
        if self._ticker is not None and self._ticker.is_alive():
            self._ticker.join(timeout=2.0)
        if (self._stats_ticker is not None
                and self._stats_ticker.is_alive()):
            self._stats_ticker.join(timeout=2.0)
