"""Fabric router: fingerprint-sharded dispatch over N engine workers.

The router is the fabric's thin control plane: it accepts the
EXISTING serve JSONL protocol — from a file/stdin batch (exactly like
`serve` mode) and from TCP clients speaking plain JSONL lines — and
forwards each request line, RAW, to one worker chosen by consistent-
hashing the request's service fingerprint (service/fingerprint.py)
onto the worker ring (service/fabric/ring.py). Raw-line forwarding is
the bit-identity lever: the worker parses/validates/fingerprints the
same bytes serve_jsonl would, so the fabric can never change what a
request means — only where it runs. The router executes no engine
work and never initializes a device backend; it parses lines only to
compute the routing fingerprint (jax-free code: models + frontend +
service/fingerprint.py).

Routing rules, in order:
- oversize lines (> api.MAX_REQUEST_LINE_BYTES) are refused AT the
  router with serve_jsonl's exact error + best-effort id echo (the
  payload never travels);
- `healthz`/`stats` control lines answer ROUTER-locally with the
  fabric view (link states, dispatch counters); `metrics`/
  `dump_debug` (and unknown types, and malformed lines) forward by
  content digest — the owning worker produces the identical
  structured response/error serve_jsonl would;
- everything else routes by its service fingerprint, computed here
  exactly as the worker will compute it (memoized per canonical
  payload), falling back to the line's content digest when the line
  cannot be parsed/built.

Failure semantics: each worker link runs per-connection heartbeats
(ping/pong every FabricConfig.hb_interval_s; silence past
hb_timeout_s fails the link) and a BOUNDED reconnect schedule. A
reconnect re-sends that link's in-flight frames (the worker's
re-submission coalesces or cache-hits bit-identically). Exhausted
reconnects declare the worker DEAD: its in-flight requests re-dispatch
to each fingerprint's ring successor among the survivors — EXACTLY
once per hop, recorded in the response's degrade chain as
{"from": "worker:K", "to": "worker:J", "reason":
"worker_disconnect"}, the same shape replica re-routes use. Entry
ownership makes resolution exactly-once: a response is accepted only
from a seq's current owner, so a zombie link's late answer is dropped.

Chaos: every request-frame send fires the `worker_conn` site —
latency/hang delay the send; raise/disconnect sever that link
(bounded reconnect, then re-dispatch), which is the seeded partition
scenario tools/check_chaos.py pins.
"""

from __future__ import annotations

import json
import re
import socket
import threading
import time

from ...runtime import faults
from .. import api
from ..fingerprint import content_digest
from . import wire
from .ring import HashRing


def _id_echo(line: str) -> str | None:
    """serve_jsonl's best-effort id echo for refused lines."""
    m = re.search(r'"id"\s*:\s*"([^"\\]{1,120})"', line[:4096])
    return m.group(1) if m else None


class Entry:
    """One routed request line: resolved exactly once."""

    __slots__ = ("seq", "line", "line_no", "req_id", "fp", "owner",
                 "hops", "degrade", "doc", "_event", "_callback",
                 "_lock")

    def __init__(self, seq: int, line: str, line_no: int):
        self.seq = seq
        self.line = line
        self.line_no = line_no
        self.req_id: str | None = None
        self.fp: str | None = None
        self.owner: int | None = None
        self.hops = 0
        self.degrade: list = []
        self.doc: dict | None = None
        self._event = threading.Event()
        self._callback = None
        self._lock = threading.Lock()

    def on_done(self, fn) -> None:
        """Run fn(doc) at resolution (immediately if already done)."""
        with self._lock:
            if self.doc is None:
                self._callback = fn
                return
        fn(self.doc)

    def wait(self, timeout: float | None = None) -> dict | None:
        self._event.wait(timeout)
        return self.doc

    @property
    def resolved(self) -> bool:
        return self.doc is not None


class WorkerLink:
    """One router->worker connection with heartbeats and bounded
    reconnect. Owns the in-flight entries routed to its worker."""

    def __init__(self, router: "Router", index: int,
                 host: str, port: int):
        self.router = router
        self.index = index
        self.worker_id = index  # refined by the worker's hello
        self.host = host
        self.port = port
        self.state = "connecting"  # connecting | up | dead
        self.inflight: dict[int, Entry] = {}
        self.dispatched = 0
        self.reconnects = 0
        self._conn: wire.Conn | None = None
        self._lock = threading.Lock()
        self._closed = threading.Event()
        self._bye = threading.Event()
        self._up_once = threading.Event()
        self._thread = threading.Thread(
            target=self._run, name=f"pluss-fabric-link-{index}",
            daemon=True,
        )

    def start(self) -> None:
        self._thread.start()

    def wait_up(self, timeout: float | None = None) -> bool:
        self._up_once.wait(timeout)
        return self.state == "up"

    # -- dispatch ------------------------------------------------------

    def dispatch(self, entry: Entry) -> None:
        """Adopt the entry (it survives reconnects in `inflight`) and
        push its frame if the link is up — a down link sends it on
        reconnect, a dying one hands it to re-dispatch."""
        with self._lock:
            self.inflight[entry.seq] = entry
            entry.owner = self.worker_id
            self.dispatched += 1
        if self.state == "up":
            self._send_request(entry)

    def _send_request(self, entry: Entry) -> None:
        conn = self._conn
        if conn is None:
            return
        try:
            faults.fire("worker_conn", key=entry.seq,
                        worker_id=self.worker_id)
            conn.send({"type": "request", "seq": entry.seq,
                       "line": entry.line, "line_no": entry.line_no})
        except wire.FrameTooLarge:
            # this entry can never travel: answer it, don't kill the
            # link (pop first so re-dispatch cannot double-answer)
            with self._lock:
                self.inflight.pop(entry.seq, None)
            self.router._resolve(entry, {
                "id": entry.req_id or _id_echo(entry.line),
                "ok": False, "line": entry.line_no,
                "error": "request line does not fit a fabric frame",
            })
        except (faults.FaultInjected, wire.WireError, OSError):
            # injected or real send failure: sever the link — the
            # reader notices, reconnect re-sends everything in flight
            conn.close()

    # -- connection lifecycle ------------------------------------------

    def _run(self) -> None:
        fabric = self.router.fabric
        attempts = 0
        while not self._closed.is_set():
            conn = None
            try:
                conn = wire.connect(
                    self.host, self.port,
                    timeout=fabric.connect_timeout_s,
                )
                conn.send({"type": "hello",
                           "wire_version": wire.WIRE_VERSION,
                           "role": "router"})
                hello = conn.recv(timeout=fabric.connect_timeout_s)
                if hello is None or hello.get("type") != "hello":
                    raise wire.WireError(
                        "handshake refused: "
                        + str((hello or {}).get("error")
                              or "no hello reply")
                    )
                wid = hello.get("worker_id")
                if isinstance(wid, int):
                    self.worker_id = wid
                self._conn = conn
                self.state = "up"
                attempts = 0
                self._up_once.set()
                # re-send everything still in flight: the responses
                # lost with the old socket re-materialize from the
                # worker's cache/singleflight, bit-identical
                with self._lock:
                    pending = list(self.inflight.values())
                for entry in pending:
                    self._send_request(entry)
                self._read_loop(conn)
                return  # clean exit (bye/close)
            except (wire.WireError, OSError, socket.timeout):
                pass
            finally:
                if conn is not None and self._conn is conn:
                    self._conn = None
                if conn is not None:
                    conn.close()
            if self._closed.is_set():
                return
            self.state = "connecting"
            attempts += 1
            self.reconnects += 1
            if attempts > fabric.reconnect_attempts:
                self.state = "dead"
                self.router._on_link_dead(self)
                return
            time.sleep(fabric.reconnect_delay_s)

    def _read_loop(self, conn: wire.Conn) -> None:
        fabric = self.router.fabric
        while not self._closed.is_set():
            frame = conn.recv(timeout=fabric.hb_timeout_s)
            if frame is None:
                raise wire.ConnectionClosed("worker closed the link")
            kind = frame.get("type")
            if kind == "response":
                self.router._on_response(self, frame)
            elif kind == "bye":
                self._bye.set()
                return
            # pong/error frames are just liveness traffic

    def ping(self) -> None:
        conn = self._conn
        if self.state == "up" and conn is not None:
            try:
                conn.send({"type": "ping", "t": time.time()})
            except (wire.WireError, OSError):
                conn.close()

    def drain_inflight(self) -> list[Entry]:
        with self._lock:
            entries = list(self.inflight.values())
            self.inflight.clear()
        return entries

    def take(self, seq: int) -> Entry | None:
        with self._lock:
            return self.inflight.pop(seq, None)

    def shutdown(self, timeout: float) -> bool:
        """Graceful: ask the worker to drain, wait for its bye."""
        conn = self._conn
        if conn is not None and self.state == "up":
            try:
                conn.send({"type": "shutdown"})
            except (wire.WireError, OSError):
                pass
            self._bye.wait(timeout)
        self.close()
        return self._bye.is_set()

    def close(self) -> None:
        self._closed.set()
        conn = self._conn
        if conn is not None:
            conn.close()
        if self._thread.is_alive():
            self._thread.join(timeout=5.0)


class Router:
    """The fabric's dispatch plane over a set of worker addresses."""

    def __init__(self, worker_addrs, fabric=None):
        from ...config import FabricConfig

        if not worker_addrs:
            raise ValueError("router needs at least one worker "
                             "address")
        self.fabric = fabric if fabric is not None else FabricConfig()
        self.links = [
            WorkerLink(self, i, host, port)
            for i, (host, port) in enumerate(worker_addrs)
        ]
        self._ring: HashRing | None = None
        self._seq = 0
        self._lock = threading.Lock()
        self._fp_memo: dict[str, str] = {}
        self._draining = False
        self._listener: socket.socket | None = None
        self._client_threads: list[threading.Thread] = []
        self._ticker: threading.Thread | None = None
        self._stop = threading.Event()
        self.counters = {
            "lines": 0, "routed": 0, "local": 0, "redispatched": 0,
            "responses": 0, "dropped_stale": 0, "no_worker": 0,
            "tcp_clients": 0,
        }

    # -- lifecycle -----------------------------------------------------

    def start(self, wait_up: bool = True) -> "Router":
        """Connect every link (handshakes resolve worker ids), build
        the ring over the REPORTED ids — a pure function of the id
        set, so assignment is stable across router restarts — and
        start the heartbeat ticker."""
        for link in self.links:
            link.start()
        if wait_up:
            deadline = time.time() + self.fabric.connect_timeout_s
            for link in self.links:
                link.wait_up(max(0.1, deadline - time.time()))
        self._ring = HashRing(
            [link.worker_id for link in self.links],
            vnodes=self.fabric.ring_vnodes,
        )
        self._by_id = {link.worker_id: link for link in self.links}
        self._ticker = threading.Thread(
            target=self._heartbeat_loop, name="pluss-fabric-hb",
            daemon=True,
        )
        self._ticker.start()
        return self

    def _heartbeat_loop(self) -> None:
        while not self._stop.wait(self.fabric.hb_interval_s):
            for link in self.links:
                link.ping()

    def alive_ids(self) -> set:
        return {link.worker_id for link in self.links
                if link.state != "dead"}

    # -- routing -------------------------------------------------------

    def _routing_fingerprint(self, line: str) -> str:
        """The worker's service fingerprint for this line — computed
        HERE with the same parse/build path (jax-free), memoized per
        canonical payload; content digest for lines a worker will
        refuse (their errors need determinism, not affinity)."""
        try:
            request = api.parse_request_line(line)
            key = json.dumps(request.payload(), sort_keys=True,
                             default=str)
            fp = self._fp_memo.get(key)
            if fp is None:
                fp = request.fingerprint()
                if len(self._fp_memo) >= 4096:
                    self._fp_memo.clear()
                self._fp_memo[key] = fp
            return fp
        except Exception:
            return content_digest({"line": line})

    def submit_line(self, line: str, line_no: int = 0) -> Entry:
        """Route one JSONL line; returns its Entry (resolving to the
        serve-protocol response dict)."""
        with self._lock:
            self._seq += 1
            entry = Entry(self._seq, line.strip(), line_no)
        self.counters["lines"] += 1
        line = entry.line
        if len(line) > api.MAX_REQUEST_LINE_BYTES:
            entry.req_id = _id_echo(line)
            self.counters["local"] += 1
            self._resolve(entry, {
                "id": entry.req_id, "ok": False, "line": line_no,
                "error": (
                    f"request line of {len(line)} bytes exceeds the "
                    f"{api.MAX_REQUEST_LINE_BYTES}-byte limit"
                ),
            })
            return entry
        try:
            doc = json.loads(line)
        except ValueError:
            doc = None
        if isinstance(doc, dict):
            entry.req_id = doc.get("id")
        if isinstance(doc, dict) and doc.get("type") in ("healthz",
                                                         "stats"):
            # fabric-local introspection: the router IS the authority
            # on link/dispatch state; per-process engine introspection
            # rides metrics/dump_debug lines to a worker instead
            kind = doc["type"]
            payload = (self.healthz() if kind == "healthz"
                       else self.stats())
            self.counters["local"] += 1
            self._resolve(entry, {"id": entry.req_id, "ok": True,
                                  "type": kind, kind: payload})
            return entry
        if self._draining:
            self._resolve(entry, {
                "id": entry.req_id, "ok": False, "line": line_no,
                "shed": True,
                "error": "shed: router shutting down",
            })
            return entry
        entry.fp = self._routing_fingerprint(line)
        self._route(entry)
        return entry

    def _route(self, entry: Entry) -> None:
        try:
            wid = self._ring.assign(entry.fp, alive=self.alive_ids())
        except LookupError:
            self.counters["no_worker"] += 1
            self._resolve(entry, {
                "id": entry.req_id, "ok": False,
                "line": entry.line_no,
                "error": "no live fabric workers",
            })
            return
        self.counters["routed"] += 1
        self._by_id[wid].dispatch(entry)

    # -- link events ---------------------------------------------------

    def _on_response(self, link: WorkerLink, frame: dict) -> None:
        seq = frame.get("seq")
        doc = frame.get("doc")
        entry = link.take(seq) if isinstance(seq, int) else None
        if entry is None or entry.owner != link.worker_id:
            # a zombie link answering a re-dispatched seq: the current
            # owner's answer is the one that counts — exactly-once
            self.counters["dropped_stale"] += 1
            return
        if not isinstance(doc, dict):
            doc = {"id": entry.req_id, "ok": False,
                   "line": entry.line_no,
                   "error": "malformed response frame from worker"}
        if entry.degrade:
            # the re-dispatch hops this entry survived, ahead of any
            # engine-level degradation the worker recorded — the same
            # chain shape replica re-routes use
            doc = dict(doc)
            doc["degraded"] = entry.degrade + list(
                doc.get("degraded") or []
            )
        self.counters["responses"] += 1
        self._resolve(entry, doc)

    def _on_link_dead(self, link: WorkerLink) -> None:
        """Reconnects exhausted: re-dispatch the dead worker's
        in-flight entries to each fingerprint's ring successor."""
        entries = link.drain_inflight()
        for entry in entries:
            entry.hops += 1
            if entry.hops >= len(self.links):
                self._resolve(entry, {
                    "id": entry.req_id, "ok": False,
                    "line": entry.line_no,
                    "error": ("no live fabric workers after "
                              f"{entry.hops} re-dispatch(es)"),
                })
                continue
            old = entry.owner
            alive = self.alive_ids()
            try:
                new = self._ring.assign(entry.fp, alive=alive)
            except LookupError:
                self.counters["no_worker"] += 1
                self._resolve(entry, {
                    "id": entry.req_id, "ok": False,
                    "line": entry.line_no,
                    "error": "no live fabric workers",
                })
                continue
            entry.degrade.append({
                "from": f"worker:{old}", "to": f"worker:{new}",
                "reason": "worker_disconnect",
            })
            self.counters["redispatched"] += 1
            self._by_id[new].dispatch(entry)

    def _resolve(self, entry: Entry, doc: dict) -> None:
        with entry._lock:
            if entry.doc is not None:
                return
            entry.doc = doc
            callback = entry._callback
            entry._callback = None
        entry._event.set()
        if callback is not None:
            try:
                callback(doc)
            except Exception:
                pass

    # -- introspection -------------------------------------------------

    def healthz(self) -> dict:
        return {
            "status": ("ok" if self.alive_ids() else "no_workers"),
            "role": "router",
            "workers": {
                str(link.worker_id): {
                    "addr": f"{link.host}:{link.port}",
                    "state": link.state,
                    "in_flight": len(link.inflight),
                }
                for link in self.links
            },
            "ring": list(self._ring.worker_ids) if self._ring else [],
        }

    def stats(self) -> dict:
        return {
            "role": "router",
            "counters": dict(self.counters),
            "workers": {
                str(link.worker_id): {
                    "state": link.state,
                    "dispatched": link.dispatched,
                    "in_flight": len(link.inflight),
                    "reconnects": link.reconnects,
                }
                for link in self.links
            },
        }

    # -- serving fronts ------------------------------------------------

    def serve_stream(self, fin, fout) -> int:
        """The serve-mode front: read a JSONL batch, dispatch every
        line up front (affinity batches per worker; duplicates
        coalesce ON the owning worker), then emit responses in input
        order. Returns the failure count, like serve_jsonl. A
        GracefulShutdown in either pass stops reading and answers
        everything already dispatched."""
        entries: list[Entry] = []
        try:
            for line_no, line in enumerate(fin, start=1):
                if not line.strip():
                    continue
                entries.append(self.submit_line(line, line_no))
        except api.GracefulShutdown:
            self._draining = True
        failures = 0
        for entry in entries:
            while True:
                try:
                    doc = entry.wait(
                        timeout=self.fabric.drain_timeout_s
                    )
                    break
                except api.GracefulShutdown:
                    self._draining = True
                    continue
            if doc is None:
                doc = {"id": entry.req_id, "ok": False,
                       "line": entry.line_no,
                       "error": "fabric response timed out"}
                self._resolve(entry, doc)
                doc = entry.doc
            if not doc.get("ok"):
                failures += 1
            fout.write(json.dumps(doc) + "\n")
            fout.flush()
        return failures

    def serve_tcp(self, host: str = "127.0.0.1", port: int = 0
                  ) -> tuple[str, int]:
        """The TCP front: clients speak plain JSONL lines (loadgen
        --connect drives this); responses stream back AS READY —
        clients match them by `id`, since affinity dispatch makes
        input-order completion meaningless across workers."""
        ls = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        ls.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        ls.bind((host, port))
        ls.listen(16)
        self._listener = ls
        bound = ls.getsockname()[:2]
        t = threading.Thread(target=self._accept_clients,
                             name="pluss-fabric-tcp", daemon=True)
        t.start()
        return bound

    def _accept_clients(self) -> None:
        while not self._stop.is_set():
            try:
                sock, _addr = self._listener.accept()
            except OSError:
                return
            self.counters["tcp_clients"] += 1
            t = threading.Thread(
                target=self._serve_client, args=(sock,),
                name="pluss-fabric-client", daemon=True,
            )
            t.start()
            self._client_threads.append(t)

    def _serve_client(self, sock: socket.socket) -> None:
        wlock = threading.Lock()
        pending: list[Entry] = []
        rfile = sock.makefile("r", encoding="utf-8", newline="\n")
        wfile = sock.makefile("w", encoding="utf-8", newline="\n")

        def _emit(doc: dict) -> None:
            with wlock:
                try:
                    wfile.write(json.dumps(doc) + "\n")
                    wfile.flush()
                except (OSError, ValueError):
                    pass  # client went away; nothing to answer

        try:
            for line_no, line in enumerate(rfile, start=1):
                if not line.strip():
                    continue
                entry = self.submit_line(line, line_no)
                pending.append(entry)
                entry.on_done(_emit)
            for entry in pending:
                entry.wait(timeout=self.fabric.drain_timeout_s)
        except (OSError, ValueError):
            pass
        finally:
            try:
                rfile.close()
                wfile.close()
                sock.close()
            except OSError:
                pass

    # -- shutdown ------------------------------------------------------

    def begin_shutdown(self) -> None:
        """Stop accepting: the TCP listener closes, later lines shed
        with structured responses; dispatched work keeps draining."""
        self._draining = True
        if self._listener is not None:
            try:
                self._listener.close()
            except OSError:
                pass

    def close(self, graceful: bool = True) -> None:
        """Tear the fabric's router side down. Graceful: drain
        in-flight entries, ask every live worker to drain (`shutdown`
        frame -> `bye`), then close links."""
        self.begin_shutdown()
        self._stop.set()
        if graceful:
            deadline = time.time() + self.fabric.drain_timeout_s
            for link in self.links:
                with link._lock:
                    snapshot = list(link.inflight.values())
                for entry in snapshot:
                    entry.wait(timeout=max(0.1,
                                           deadline - time.time()))
            for link in self.links:
                link.shutdown(timeout=max(
                    0.1, deadline - time.time()
                ))
        for link in self.links:
            link.close()
        # anything still unresolved (dead workers mid-drain) answers
        # as an error so no caller blocks forever
        for link in self.links:
            for entry in link.drain_inflight():
                self._resolve(entry, {
                    "id": entry.req_id, "ok": False,
                    "line": entry.line_no,
                    "error": "router closed before a worker answered",
                })
        if self._ticker is not None and self._ticker.is_alive():
            self._ticker.join(timeout=2.0)
