"""Length-delimited JSON frames: the fabric's wire protocol.

Router and workers speak frames over TCP sockets: a 4-byte big-endian
length prefix followed by one UTF-8 JSON object. The frame payload cap
derives from serve_jsonl's per-line budget (api.MAX_REQUEST_LINE_BYTES
is 1 MiB) with headroom for the envelope's JSON re-escaping, so a
request line the serve protocol accepts always fits in one frame and a
hostile frame is refused BEFORE its payload is materialized as
objects.

Frame vocabulary (the `type` field):

    hello     handshake, both directions. Carries `wire_version`; the
              worker's reply carries its `worker_id`. A version
              mismatch is answered with an `error` frame and the
              connection is closed (tests/test_fabric.py pins it).
    request   router -> worker: {"seq": N, "line": <raw JSONL request
              line>, "line_no": M}. The RAW line is forwarded, so the
              worker's parse/validate/fingerprint path is byte-for-
              byte the one serve_jsonl runs — the transport cannot
              change what a request means. May carry an optional
              `trace` block {"trace_id": hex16, "span_id": hex16,
              "sent_s": <sender perf_counter>}: the worker ADOPTS the
              caller's trace_id (unless the raw line itself names
              one, which both sides then agree on), so worker ledger
              rows, exemplars, and bundles join the router's view of
              the same request. Trace context never enters the
              request payload or fingerprint — placement and tracing
              are both invisible to the MRC bytes.
    partial   worker -> router: {"seq": N, "doc": <partial dict>}.
              An interim progressive-precision result for the request
              dispatched as `seq` — `doc` carries `partial: true`,
              the request `id`, `round`/`rounds_total`, `band_width`,
              and the interim MRC digest/lines. Zero or more partials
              precede the request's single `response` frame; the
              router forwards them immediately (never re-ordered,
              never cached) to whichever client owns the seq.
    response  worker -> router: {"seq": N, "doc": <serve response
              dict>}. Out-of-order by design; the router re-orders by
              seq for file mode and matches by id for TCP clients.
              May carry `trace` {"trace_id": hex16, "worker_s":
              <worker-side recv->send delta, its own monotonic
              clock>} so the router can split its measured RTT into
              wire time vs worker time without cross-host clocks.
    ping/pong heartbeats (router pings, worker echoes the `t` token;
              the router matches tokens to measure per-link RTT).
    stats     both directions. Router -> worker {"token": N, "want":
              [...], ...} requests a telemetry snapshot; the worker
              replies {"token": N, "snapshot": {...}} with one key
              per `want` entry (healthz/stats/metrics/slo_inputs/
              dump_debug). This is how the router serves the merged
              fleet view of `stats`/`metrics` and fans `dump_debug`
              out to every worker.
    shutdown  router -> worker: drain in-flight work, answer
              everything, reply `bye`, and stop.
    bye       worker -> router: drain complete, closing.
    error     structured refusal (handshake version mismatch, a
              malformed frame the peer could still answer).

Everything here is pure stdlib — the router process imports this
without touching jax.
"""

from __future__ import annotations

import json
import socket
import struct
import threading

# v2: optional `trace` blocks on request/response frames + the
# `stats` frame type (fleet telemetry).
# v3: the `partial` frame type (streamed progressive-precision
# interim results). The handshake still gates on exact equality —
# both ends ship in this repo.
WIRE_VERSION = 3

# Frame payload cap: the serve protocol's 1 MiB request-line budget,
# times 4 for the envelope's JSON re-escaping (every quote/backslash
# in the forwarded line doubles; control characters sextuple), plus
# 4 KiB for type/seq/line_no. Any line serve_jsonl accepts fits;
# a pathological expansion beyond this is answered by the router with
# a structured error instead of traveling (router._send_request).
MAX_FRAME_BYTES = (1 << 22) + 4096

_LEN = struct.Struct(">I")


class WireError(RuntimeError):
    """A protocol violation on a fabric connection."""


class FrameTooLarge(WireError):
    """A frame announcing (or encoding to) more than MAX_FRAME_BYTES."""


class ConnectionClosed(WireError):
    """The peer closed the connection (clean EOF mid-stream)."""


def encode_frame(doc: dict) -> bytes:
    """One wire frame for `doc` (length prefix + compact JSON)."""
    payload = json.dumps(
        doc, sort_keys=True, separators=(",", ":")
    ).encode("utf-8")
    if len(payload) > MAX_FRAME_BYTES:
        raise FrameTooLarge(
            f"frame of {len(payload)} bytes exceeds the "
            f"{MAX_FRAME_BYTES}-byte limit"
        )
    return _LEN.pack(len(payload)) + payload


class Conn:
    """One framed connection: locked sends, buffered recvs.

    Sends are serialized by a lock so concurrent senders (the worker's
    response callbacks, the router's heartbeat ticker) never interleave
    frame bytes. `recv` honors an optional timeout via the socket
    timeout; a clean EOF between frames returns None, an EOF inside a
    frame raises ConnectionClosed.
    """

    def __init__(self, sock: socket.socket):
        self._sock = sock
        self._send_lock = threading.Lock()
        self._closed = False

    def send(self, doc: dict) -> None:
        data = encode_frame(doc)
        with self._send_lock:
            self._sock.sendall(data)

    def _recv_exact(self, n: int) -> bytes | None:
        buf = b""
        while len(buf) < n:
            chunk = self._sock.recv(n - len(buf))
            if not chunk:
                if buf:
                    raise ConnectionClosed(
                        "connection closed mid-frame"
                    )
                return None
            buf += chunk
        return buf

    def recv(self, timeout: float | None = None) -> dict | None:
        """The next frame's decoded object, or None on clean EOF.

        Raises socket.timeout when `timeout` elapses between frames,
        FrameTooLarge/WireError on protocol violations.
        """
        self._sock.settimeout(timeout)
        head = self._recv_exact(_LEN.size)
        if head is None:
            return None
        (length,) = _LEN.unpack(head)
        if length > MAX_FRAME_BYTES:
            # refuse before reading the body: the cap is the OOM guard
            raise FrameTooLarge(
                f"frame announcing {length} bytes exceeds the "
                f"{MAX_FRAME_BYTES}-byte limit"
            )
        body = self._recv_exact(length)
        if body is None:
            raise ConnectionClosed("connection closed before frame body")
        try:
            doc = json.loads(body.decode("utf-8"))
        except (UnicodeDecodeError, ValueError) as e:
            raise WireError(f"malformed frame payload: {e}") from e
        if not isinstance(doc, dict):
            raise WireError("frame payload must be a JSON object")
        return doc

    def close(self) -> None:
        self._closed = True
        try:
            self._sock.shutdown(socket.SHUT_RDWR)
        except OSError:
            pass
        try:
            self._sock.close()
        except OSError:
            pass

    @property
    def closed(self) -> bool:
        return self._closed


def connect(host: str, port: int, timeout: float | None = None) -> Conn:
    """Dial a fabric peer and wrap the socket."""
    sock = socket.create_connection((host, port), timeout=timeout)
    sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
    return Conn(sock)


def parse_hostport(spec: str) -> tuple[str, int]:
    """"HOST:PORT" -> (host, port); host defaults to 127.0.0.1."""
    host, sep, port = spec.rpartition(":")
    if not sep or not port.isdigit():
        raise ValueError(
            f"expected HOST:PORT (or :PORT), got {spec!r}"
        )
    return (host or "127.0.0.1", int(port))
