"""Fabric worker: one engine process serving framed request lines.

A `WorkerServer` wraps one AnalysisService — the FULL single-process
stack: executor, replica pool, preflight, in-memory LRU over this
process's device slice — behind a TCP listener speaking the fabric
wire protocol (service/fabric/wire.py). The router forwards RAW
request lines, and the worker runs them through the SAME per-line
semantics serve_jsonl applies (oversize cap with best-effort id echo,
the serve_line chaos site, structured per-line errors, control types),
so a request means byte-for-byte the same thing served directly or
through the fabric — the transport can re-route bytes, never change
them.

Concurrency model: one router connection at a time (re-accepted after
a drop — the router's bounded reconnect dials back in). The reader
thread parses/submits each request frame in arrival order (exactly
serve_jsonl's submit pass, so duplicates coalesce); responses are sent
from future done-callbacks as executions finish, out of order, tagged
with the request frame's `seq`. A send on a dead socket is dropped
silently: the router re-dispatches the seq after reconnecting and the
re-submission coalesces or cache-hits to the bit-identical result.

Chaos: every request frame fires the `worker_exec` site —
raise-kind faults become structured error responses; a `disconnect`
fault makes the worker sever the router connection mid-load (the
partition scenario tools/check_chaos.py pins).
"""

from __future__ import annotations

import dataclasses
import json
import re
import socket
import threading
import time
from concurrent.futures import CancelledError

from ...runtime import faults
from .. import api
from . import wire


def handle_line(service, line: str, line_no: int = 0,
                trace_id: str | None = None, on_partial=None):
    """serve_jsonl's per-line read-pass semantics for ONE line.

    Returns ("doc", response_dict) for lines answerable immediately
    (oversize, malformed, control types) or ("ticket", ticket,
    request) for a submitted request — the caller awaits the future
    and builds the response with `response_doc`. Mirrors
    api.serve_jsonl branch for branch so fabric-served lines produce
    identical structured responses.

    `on_partial`, when given, receives each streamed progressive-
    precision round doc (the request `id` stamped in, exactly as
    serve_jsonl emits it) — only requests that actually ask for
    progressive precision register a callback.

    `trace_id` is the router-propagated trace context: a parsed
    request that names no trace_id of its own ADOPTS it (so the worker
    ledger row, exemplars, and bundles join the router's view of the
    request) — a trace_id in the raw line wins, and both sides agree
    on it since the router parses the same bytes. Trace context is
    serving metadata: it never enters the payload or fingerprint.
    """
    line = line.strip()
    doc_id = None
    if len(line) > api.MAX_REQUEST_LINE_BYTES:
        m = re.search(r'"id"\s*:\s*"([^"\\]{1,120})"', line[:4096])
        if m:
            doc_id = m.group(1)
        service.executor._count("frontend_rejected")
        return ("doc", {
            "id": doc_id, "ok": False, "line": line_no,
            "error": (
                f"request line of {len(line)} bytes exceeds the "
                f"{api.MAX_REQUEST_LINE_BYTES}-byte limit"
            ),
        })
    try:
        faults.fire("serve_line", key=line_no)
        doc = json.loads(line)
    except faults.FaultInjected as e:
        return ("doc", {"id": None, "ok": False, "line": line_no,
                        "error": f"fault injected: {e}"})
    except RecursionError:
        m = re.search(r'"id"\s*:\s*"([^"\\]{1,120})"', line[:4096])
        if m:
            doc_id = m.group(1)
        service.executor._count("frontend_rejected")
        return ("doc", {"id": doc_id, "ok": False, "line": line_no,
                        "error": "invalid JSON: nesting too deep"})
    except ValueError as e:
        return ("doc", {"id": None, "ok": False, "line": line_no,
                        "error": f"invalid JSON: {e}"})
    if isinstance(doc, dict):
        doc_id = doc.get("id")
    if isinstance(doc, dict) and doc.get("type") is not None:
        kind = doc.get("type")
        if kind not in api.CONTROL_TYPES:
            return ("doc", {
                "id": doc_id, "ok": False, "line": line_no,
                "error": (
                    f"unknown request type {kind!r} "
                    f"(have {', '.join(api.CONTROL_TYPES)})"
                ),
            })
        # over the fabric every control line evaluates as it arrives:
        # the batch-deterministic deferral serve_jsonl applies to
        # metrics/dump_debug has no meaning when frames from many
        # clients interleave on one worker
        try:
            payload = {
                "healthz": service.healthz,
                "stats": service.stats,
                "metrics": service.metrics,
                "dump_debug": service.dump_debug,
            }[kind]()
            return ("doc", {"id": doc_id, "ok": True, "type": kind,
                            kind: payload})
        except Exception as e:
            return ("doc", {"id": doc_id, "ok": False, "line": line_no,
                            "error": f"introspection failed: {e!r}"})
    try:
        request = api.parse_request_line(line)
        if trace_id and request.trace_id is None:
            request = dataclasses.replace(request, trace_id=trace_id)
        cb = None
        if on_partial is not None and api.progressive_requested(request):
            def cb(doc, _rid=request.id):
                msg = dict(doc)
                msg["id"] = _rid
                on_partial(msg)
        ticket = service.submit(request, on_partial=cb)
        return ("ticket", ticket, request)
    except Exception as e:
        out = {"id": doc_id, "ok": False, "line": line_no,
               "error": api._error_msg(e)}
        diags = getattr(e, "diagnostics", None)
        if diags:
            out["diagnostics"] = diags
        return ("doc", out)


def response_doc(ticket, request, line_no: int = 0) -> dict:
    """Await a ticket and build its serve-protocol response dict —
    serve_jsonl's response-pass semantics for one entry (shed and
    blow-up handling included)."""
    try:
        outcome = ticket.future.result()
        return api._response_from_outcome(
            request, ticket.fingerprint, outcome
        ).to_jsonl_dict()
    except CancelledError:
        return {
            "id": request.id, "ok": False, "line": line_no,
            "shed": True,
            "error": ("shed: service shutting down "
                      "(queued request cancelled)"),
        }
    except Exception as e:
        return {
            "id": request.id, "ok": False, "line": line_no,
            "error": f"execution failed: {e!r}",
        }


# Snapshot sections a `stats` frame may request; also the default
# when the frame names none.
STATS_SECTIONS = ("healthz", "stats", "metrics", "slo_inputs",
                  "dump_debug")
DEFAULT_STATS_WANT = ("stats", "metrics", "slo_inputs")


def _slo_inputs(slo: dict | None) -> dict:
    """Pre-digested burn-rate inputs from THIS process's live
    registry, for the router's fleet SLO sentinel: per-window latency
    violation fraction (against the router-supplied threshold),
    window observation count (the merge weight), the observed p95,
    and the windowed service_* counters. All monotonic-window reads —
    nothing here needs clock agreement with the router."""
    from ...runtime.obs import metrics as obs_metrics
    from ...runtime.obs.slo import LATENCY_HISTOGRAM

    reg = obs_metrics.get()
    if reg is None:
        return {"enabled": False, "windows": {}}
    slo = slo if isinstance(slo, dict) else {}
    threshold = slo.get("threshold")
    labels = slo.get("windows") or list(reg.window_labels())
    hist = reg.snapshot().get("histograms", {}).get(
        LATENCY_HISTOGRAM, {})
    out: dict = {"enabled": True, "threshold": threshold,
                 "histogram": LATENCY_HISTOGRAM, "windows": {}}
    for lbl in labels:
        try:
            win = {
                "latency_count": int(
                    hist.get("windows", {}).get(lbl, {})
                    .get("count") or 0
                ),
                "latency_p95": reg.histogram_quantile(
                    LATENCY_HISTOGRAM, lbl, 0.95
                ),
                "service_submitted": reg.counter_window(
                    "service_submitted", lbl),
                "service_failed": reg.counter_window(
                    "service_failed", lbl),
                "service_degraded": reg.counter_window(
                    "service_degraded", lbl),
            }
            win["latency_frac_over"] = (
                reg.histogram_fraction_over(
                    LATENCY_HISTOGRAM, lbl, float(threshold)
                ) if threshold is not None else None
            )
        except KeyError:
            continue  # a window label this registry doesn't keep
        out["windows"][lbl] = win
    return out


def telemetry_snapshot(service, want=None, slo: dict | None = None
                       ) -> dict:
    """The worker's answer to a `stats` frame: one key per requested
    section. Sections map onto the serve protocol's control responses
    (healthz/stats/metrics/dump_debug) plus the fleet-only
    `slo_inputs`; a section that fails reports {"error": ...} in
    place so one broken subsystem can't blank the whole poll."""
    if not isinstance(want, (list, tuple)) or not want:
        want = DEFAULT_STATS_WANT
    out: dict = {}
    for key in want:
        if key not in STATS_SECTIONS:
            out[str(key)] = {"error": f"unknown section {key!r}"}
            continue
        try:
            if key == "slo_inputs":
                out[key] = _slo_inputs(slo)
            else:
                out[key] = getattr(service, key)()
        except Exception as e:
            out[key] = {"error": repr(e)}
    return out


class WorkerServer:
    """One fabric worker endpoint over an AnalysisService."""

    def __init__(self, service, worker_id: int = 0,
                 host: str = "127.0.0.1", port: int = 0,
                 fabric=None):
        from ...config import FabricConfig

        self.service = service
        self.worker_id = int(worker_id)
        self.fabric = fabric if fabric is not None else FabricConfig()
        self._host = host
        self._port = port
        self._listener: socket.socket | None = None
        self._thread: threading.Thread | None = None
        self._conn: wire.Conn | None = None
        self._stop = threading.Event()
        self._drained = threading.Event()
        # outstanding seq -> Future, so a drain can await everything
        # this worker accepted before saying `bye`
        self._outstanding: dict = {}
        self._lock = threading.Lock()
        self.stats_counters = {
            "connections": 0, "requests": 0, "responses": 0,
            "partials": 0,
            "handshake_rejected": 0, "faults_disconnect": 0,
            "stats_polls": 0,
        }

    # -- lifecycle -----------------------------------------------------

    def start(self) -> tuple[str, int]:
        """Bind, listen, and serve in a daemon thread. Returns the
        bound (host, port) — port 0 resolves to an ephemeral one."""
        ls = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        ls.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        ls.bind((self._host, self._port))
        ls.listen(4)
        self._listener = ls
        self._host, self._port = ls.getsockname()[:2]
        self._thread = threading.Thread(
            target=self._accept_loop,
            name=f"pluss-fabric-worker-{self.worker_id}", daemon=True,
        )
        self._thread.start()
        return (self._host, self._port)

    @property
    def address(self) -> tuple[str, int]:
        return (self._host, self._port)

    def close(self) -> None:
        """Stop accepting and sever the live connection (the abrupt
        worker-kill the chaos gate exercises — no drain)."""
        self._stop.set()
        if self._listener is not None:
            try:
                self._listener.close()
            except OSError:
                pass
        conn = self._conn
        if conn is not None:
            conn.close()
        if self._thread is not None:
            self._thread.join(timeout=10.0)
            self._thread = None

    def join_drained(self, timeout: float | None = None) -> bool:
        """Wait until a `shutdown` frame completed its drain."""
        return self._drained.wait(timeout)

    def drain_local(self) -> None:
        """Signal-initiated drain (no router `shutdown` frame, e.g.
        SIGTERM straight at the worker): stop accepting, await every
        accepted request — done-callbacks still push responses if the
        router link survives — then close."""
        with self._lock:
            pending = list(self._outstanding.values())
        for fut in pending:
            try:
                fut.result(timeout=self.fabric.drain_timeout_s)
            except Exception:
                pass  # its done-callback already sent the error doc
        self.close()
        self._drained.set()

    # -- serving -------------------------------------------------------

    def _accept_loop(self) -> None:
        while not self._stop.is_set():
            try:
                sock, _addr = self._listener.accept()
            except OSError:
                return  # listener closed
            sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            conn = wire.Conn(sock)
            self._conn = conn
            self.stats_counters["connections"] += 1
            try:
                self._serve_conn(conn)
            except (wire.WireError, OSError, socket.timeout):
                pass  # link dropped: back to accept (router redials)
            finally:
                conn.close()
                if self._conn is conn:
                    self._conn = None

    def _handshake(self, conn: wire.Conn) -> bool:
        hello = conn.recv(timeout=self.fabric.connect_timeout_s)
        if hello is None or hello.get("type") != "hello":
            conn.send({
                "type": "error",
                "error": "expected a hello frame",
                "wire_version": wire.WIRE_VERSION,
            })
            return False
        if hello.get("wire_version") != wire.WIRE_VERSION:
            # structured refusal the router (and the mismatch test)
            # can read, then close: no half-agreed protocol
            self.stats_counters["handshake_rejected"] += 1
            conn.send({
                "type": "error",
                "error": (
                    f"wire version mismatch: router speaks "
                    f"{hello.get('wire_version')!r}, worker speaks "
                    f"{wire.WIRE_VERSION}"
                ),
                "wire_version": wire.WIRE_VERSION,
            })
            return False
        conn.send({"type": "hello", "wire_version": wire.WIRE_VERSION,
                   "worker_id": self.worker_id})
        return True

    def _serve_conn(self, conn: wire.Conn) -> None:
        if not self._handshake(conn):
            return
        while not self._stop.is_set():
            frame = conn.recv(timeout=None)
            if frame is None:
                return  # clean EOF: router went away
            kind = frame.get("type")
            if kind == "ping":
                conn.send({"type": "pong", "t": frame.get("t")})
            elif kind == "request":
                self._handle_request(conn, frame)
            elif kind == "stats":
                self._handle_stats(conn, frame)
            elif kind == "shutdown":
                self._drain(conn)
                return
            else:
                conn.send({
                    "type": "error",
                    "error": f"unknown frame type {kind!r}",
                })

    def _send_response(self, conn: wire.Conn, seq, doc: dict,
                       trace: dict | None = None) -> None:
        doc = dict(doc)
        doc["worker_id"] = self.worker_id
        frame = {"type": "response", "seq": seq, "doc": doc}
        if trace is not None:
            frame["trace"] = trace
        try:
            conn.send(frame)
            self.stats_counters["responses"] += 1
        except (wire.WireError, OSError):
            # link already dead — the router will re-dispatch this seq
            # after reconnecting; dropping the send keeps exactly-once
            # resolution at the ROUTER, where it is enforced
            pass

    def _handle_request(self, conn: wire.Conn, frame: dict) -> None:
        seq = frame.get("seq")
        line = frame.get("line")
        line_no = int(frame.get("line_no") or 0)
        t_recv = time.perf_counter()
        trace_in = frame.get("trace")
        trace_id = (trace_in.get("trace_id")
                    if isinstance(trace_in, dict) else None)

        def _trace_out() -> dict | None:
            # the router's RTT minus this delta is the wire time; both
            # deltas are single-host monotonic, so no clock agreement
            # between router and worker is ever assumed
            if trace_id is None:
                return None
            return {"trace_id": trace_id,
                    "worker_s": round(
                        time.perf_counter() - t_recv, 6)}

        self.stats_counters["requests"] += 1
        if not isinstance(line, str):
            self._send_response(conn, seq, {
                "id": None, "ok": False, "line": line_no,
                "error": "request frame without a 'line' string",
            }, trace=_trace_out())
            return
        try:
            faults.fire("worker_exec", key=seq,
                        worker_id=self.worker_id)
        except faults.DisconnectFault:
            # simulate the worker side of a partition: drop the router
            # link mid-load and go back to accept — in-flight
            # executions keep running; their sends fall on the dead
            # socket and the router re-dispatches after reconnect
            self.stats_counters["faults_disconnect"] += 1
            raise wire.ConnectionClosed("injected worker disconnect")
        except faults.FaultInjected as e:
            self._send_response(conn, seq, {
                "id": None, "ok": False, "line": line_no,
                "error": f"fault injected: {e}",
            }, trace=_trace_out())
            return
        def _partial(doc, conn=conn, seq=seq):
            # best-effort stream: a partial lost to a dead link is
            # simply gone (the final response is what the router
            # re-dispatches for; partials are never replayed)
            try:
                conn.send({"type": "partial", "seq": seq, "doc": doc})
                self.stats_counters["partials"] += 1
            except (wire.WireError, OSError):
                pass

        handled = handle_line(self.service, line, line_no,
                              trace_id=trace_id, on_partial=_partial)
        if handled[0] == "doc":
            self._send_response(conn, seq, handled[1],
                                trace=_trace_out())
            return
        _tag, ticket, request = handled
        with self._lock:
            self._outstanding[seq] = ticket.future

        def _done(_fut, conn=conn, seq=seq, ticket=ticket,
                  request=request, line_no=line_no):
            with self._lock:
                self._outstanding.pop(seq, None)
            self._send_response(
                conn, seq, response_doc(ticket, request, line_no),
                trace=_trace_out(),
            )

        ticket.future.add_done_callback(_done)

    # -- fleet telemetry ----------------------------------------------

    def _handle_stats(self, conn: wire.Conn, frame: dict) -> None:
        """`stats` frame: build the requested telemetry snapshot and
        echo the token. A broken section must never take the link (or
        the worker) down — it is reported in place."""
        self.stats_counters["stats_polls"] += 1
        snapshot = telemetry_snapshot(
            self.service, frame.get("want"), slo=frame.get("slo")
        )
        try:
            conn.send({"type": "stats", "token": frame.get("token"),
                       "worker_id": self.worker_id,
                       "snapshot": snapshot})
        except (wire.WireError, OSError):
            pass  # router re-polls after reconnecting

    def _drain(self, conn: wire.Conn) -> None:
        """`shutdown` frame: stop reading, await every accepted
        request (responses flow from their done-callbacks), then
        `bye`. The CLI layer tears the service down afterwards."""
        with self._lock:
            pending = list(self._outstanding.values())
        for fut in pending:
            try:
                fut.result(timeout=self.fabric.drain_timeout_s)
            except Exception:
                pass  # its done-callback already sent the error doc
        try:
            conn.send({"type": "bye", "worker_id": self.worker_id})
        except (wire.WireError, OSError):
            pass
        self._stop.set()
        if self._listener is not None:
            try:
                self._listener.close()
            except OSError:
                pass
        self._drained.set()
