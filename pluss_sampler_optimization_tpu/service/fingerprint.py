"""Canonical content fingerprints for analysis requests.

The service's whole caching story rests on one invariant: the
fingerprint is a pure function of everything that determines the
analysis RESULT and of nothing else. Two requests with equal
fingerprints produce bit-identical MRCs (every engine in the exact
family is pinned bit-identical, and the sampled engine is
deterministic in its seed/ratio/draw path), so a fingerprint match is
a correctness-preserving reuse — the compile-once/serve-many
discipline the mesh kernels already apply to executables, applied to
results.

What goes into the hash:

- the **Program IR itself** (loops, refs, affine maps — via
  `dataclasses.asdict`), NOT the model name: two registry entries that
  build the same IR share one cache slot, and a model whose builder
  changes invalidates naturally;
- the **MachineConfig** (every field — thread_num/chunk_size/ds/cls
  shape the interleaving, cache_kb bounds the MRC support);
- the **engine** and its parameters (runtime v1/v2 semantics, and for
  the sampled family: ratio, seed, and the draw-path selector, since
  the two deterministic draw paths produce different sample SETS —
  see SamplerConfig.device_draw);
- a **FINGERPRINT_VERSION** sentinel, bumped whenever the canonical
  payload shape or the result-record schema changes, so stale stores
  are never misread as current.

`structure_digest` is the same canonicalization applied to the kernel
caches' structural signature tuples (sampler/sampled.py::_kernel_sig
and friends): a short stable digest replaces the ad-hoc raw-tuple key,
so every cache in the repo — compiled-kernel and result alike — keys
on one hashing discipline.
"""

from __future__ import annotations

import dataclasses
import hashlib
import json

from ..config import MachineConfig
from ..ir import Program

# Bump on ANY change to the canonical payload below OR to the service
# result-record schema (service/cache.py::STORE_VERSION documents the
# record side); old on-disk entries then miss cleanly instead of being
# misinterpreted.
FINGERPRINT_VERSION = 2  # v2: ir.Ref grew the `write` marker field


def _canonical(obj):
    """Recursively convert a payload to canonical JSON-serializable
    form: tuples/lists -> lists, dicts keyed by str with sorted keys
    at dump time, dataclasses -> dicts. Rejects types whose repr is
    identity-dependent rather than value-dependent."""
    if dataclasses.is_dataclass(obj) and not isinstance(obj, type):
        return _canonical(dataclasses.asdict(obj))
    if isinstance(obj, dict):
        return {str(k): _canonical(v) for k, v in obj.items()}
    if isinstance(obj, (list, tuple)):
        return [_canonical(v) for v in obj]
    if isinstance(obj, (str, int, float, bool)) or obj is None:
        return obj
    raise TypeError(
        f"fingerprint payload contains non-canonical type "
        f"{type(obj).__name__}: {obj!r}"
    )


def canonical_json(obj) -> str:
    """Deterministic JSON: sorted keys, no whitespace variance."""
    return json.dumps(
        _canonical(obj), sort_keys=True, separators=(",", ":")
    )


def content_digest(obj) -> str:
    """sha256 hex of the canonical JSON form (full 64 hex chars)."""
    return hashlib.sha256(canonical_json(obj).encode()).hexdigest()


def structure_digest(obj) -> str:
    """Short (16-hex) digest for in-memory structural cache keys.

    Used where a hashable-but-ad-hoc tuple key served before (the
    jitted-kernel signature caches): structurally equal signatures map
    to equal digests, distinct ones to distinct digests (collision
    odds at 64 bits are negligible against cache sizes of tens of
    entries). Falls back to repr for values canonical JSON rejects —
    signature tuples are ints/strs/bools/None/tuples, all covered."""
    try:
        s = canonical_json(obj)
    except TypeError:
        s = repr(obj)
    return hashlib.sha256(s.encode()).hexdigest()[:16]


def program_payload(program: Program) -> dict:
    """The Program IR as a canonical dict (name included: it labels
    dumps, and byte-equal dumps are part of the cached record)."""
    return _canonical(program)


def machine_payload(machine: MachineConfig) -> dict:
    return _canonical(machine)


def request_fingerprint(
    program: Program,
    machine: MachineConfig,
    engine: str,
    params: dict | None = None,
) -> str:
    """The content address of one analysis result.

    `params` carries the engine-family knobs that change the result
    (runtime semantics, and ratio/seed/device_draw for the sampled
    family); callers pass only the knobs their engine consumes, so an
    exact request's fingerprint is invariant to sampling parameters.
    """
    return content_digest({
        "fingerprint_version": FINGERPRINT_VERSION,
        "program": program_payload(program),
        "machine": machine_payload(machine),
        "engine": engine,
        "params": params or {},
    })
