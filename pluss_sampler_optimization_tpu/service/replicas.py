"""Replica pool: partition the device set into independent executors.

Before this layer every service execution — solo, singleflighted, or
batched by the admission window — ran on the one implicit default
device set, so a machine with 8 chips served concurrent independent
requests at the throughput of 1. The pool splits `jax.devices()` into
K disjoint device groups (config.py::ReplicaConfig; CLI `--replicas`),
each owning:

- its own 1-D sample mesh over just its devices
  (parallel/mesh.py::build_mesh),
- a work queue and one worker thread (the execution slot),
- a structure-keyed warmup set (service/fingerprint.py::
  structure_digest), so ledger-driven warm start compiles each kernel
  signature once per replica, not once per request.

Scheduling: `submit` routes each work item (a solo request or a whole
flushed batch window) to the least-loaded replica — shortest queue
(executing counts as one), round-robin among ties. An idle replica
whose own queue is empty STEALS the oldest stealable item from the
longest peer queue (`windows_stolen`), so one slow request cannot
strand queued work behind it.

Failure breakers: a replica whose execution raises has its per-
replica circuit breaker OPENED (the service/breakers.py state
machine, embedded here under the pool's condition lock) — removed
from routing for a probation window, its queue drained onto healthy
peers — and the failing item is re-routed ONCE to the least-loaded
healthy replica, recorded as a degradation event (`{"from":
"replica:K", ...}` in the request's degrade chain, a
`replica_quarantined` telemetry event, and the completion counted
`service_degraded` — so PR 9's live registry windows and the SLO
sentinel's error-budget objective both see it). A re-routed item
that fails AGAIN is attributed to the work, not the replica: the
second replica is NOT opened and the exception propagates to the
executor's normal engine-degradation handling.

Unlike PR 10's one-shot quarantine, an open replica RECOVERS: once
its probation elapses the router hands it exactly one work item as a
half-open probe. Probe success re-closes the breaker
(`replica_breaker_reclosed` — the replica rejoins routing with full
standing); probe failure re-opens it with the probation escalated.
When every replica is open, routing falls back to the full set — a
degraded pool still serves best-effort rather than going dark.

Chaos: each worker pickup passes the `replica_dispatch` injection
site (runtime/faults.py), so tools/check_chaos.py can drive the
open/probe/re-close cycle deterministically.

Placement is pure routing (parallel/placement.py): the per-ref sample
streams are seed-derived, never device-derived, so MRC bytes are
bit-identical for any replica count and for any re-route
(tests/test_replicas.py pins both).
"""

from __future__ import annotations

import collections
import threading
import time
from concurrent.futures import Future

from ..config import ReplicaConfig, ResilienceConfig, SamplerConfig
from ..runtime import faults, lockwitness, telemetry


def current_replica_id():
    """Replica id executing on this thread, or None (fault-injection
    tests and runners key on it)."""
    from ..parallel import placement

    return placement.active_replica_id()


class Replica:
    """One device group + queue + counters + breaker state. All
    mutable state is guarded by the owning pool's condition lock."""

    __slots__ = (
        "rid", "devices", "mesh", "queue", "busy", "state",
        "reopen_at", "probation_s", "reclosed",
        "quarantine_reason", "routed", "served", "stolen", "completed",
        "failed", "warmed",
    )

    def __init__(self, rid: int, devices, mesh):
        self.rid = rid
        self.devices = list(devices)
        self.mesh = mesh
        self.queue: collections.deque = collections.deque()
        self.busy = False
        # per-replica breaker: "closed" | "open" | "half_open"
        # (service/breakers.py semantics, embedded under the pool
        # lock so routing and transitions are one atomic step)
        self.state = "closed"
        self.reopen_at = 0.0  # monotonic instant probation ends
        self.probation_s = 0.0  # current (possibly escalated) window
        self.reclosed = 0  # successful half-open probes
        self.quarantine_reason: str | None = None
        self.routed = 0  # work items routed here at submit
        self.served = 0  # requests whose execution completed here
        self.stolen = 0  # work items this replica stole from peers
        self.completed = 0  # work items finished OK here
        self.failed = 0  # work items that raised here
        self.warmed: set = set()  # structure digests warmed here

    @property
    def quarantined(self) -> bool:
        """Out of normal routing (breaker open or probing)."""
        return self.state != "closed"


class _Work:
    """One queued execution: a thunk plus its routing bookkeeping."""

    __slots__ = ("fn", "future", "trace_id", "members", "pinned",
                 "attempts", "events")

    def __init__(self, fn, future, trace_id, members, pinned):
        self.fn = fn
        self.future = future
        self.trace_id = trace_id
        self.members = members  # requests this item carries (window)
        self.pinned = pinned  # pinned items are never stolen/re-routed
        self.attempts = 0
        self.events: list[dict] = []


class ReplicaPool:
    """K independent device-group executors with load-aware routing,
    work stealing, and failure quarantine."""

    def __init__(self, config: ReplicaConfig | None = None,
                 devices=None,
                 resilience: ResilienceConfig | None = None):
        import jax

        from ..parallel.mesh import build_mesh

        devs = list(devices) if devices is not None else jax.devices()
        cfg = config or ReplicaConfig()
        res = resilience or ResilienceConfig()
        self._probation_s = res.breaker_probation_s
        self._escalation = res.breaker_escalation
        self._probation_max_s = res.breaker_probation_max_s
        k = cfg.resolve(len(devs))
        # contiguous near-equal groups: the first (len % k) replicas
        # take one extra device
        base, rem = divmod(len(devs), k)
        self.replicas: list[Replica] = []
        lo = 0
        for rid in range(k):
            hi = lo + base + (1 if rid < rem else 0)
            group = devs[lo:hi]
            lo = hi
            self.replicas.append(
                Replica(rid, group, build_mesh(devices=group))
            )
        self._cv = lockwitness.make_condition("ReplicaPool._cv")
        self._closed = False
        self._rr = 0  # round-robin cursor for routing ties
        self._workers = [
            threading.Thread(
                target=self._worker, args=(r,), daemon=True,
                name=f"pluss-replica-{r.rid}",
            )
            for r in self.replicas
        ]
        for t in self._workers:
            t.start()
        telemetry.gauge("replica_count", k)

    # -- public -------------------------------------------------------

    def __len__(self) -> int:
        return len(self.replicas)

    def submit(self, fn, trace_id: str | None = None,
               members: int = 1, replica_id: int | None = None,
               pinned: bool = False) -> Future:
        """Route one execution; the future resolves to
        (fn's result, executing replica id, re-route events)."""
        fut: Future = Future()
        fut.set_running_or_notify_cancel()
        work = _Work(fn, fut, trace_id, members,
                     pinned or replica_id is not None)
        promoted: list[int] = []
        with self._cv:
            if self._closed:
                raise RuntimeError("replica pool is closed")
            if replica_id is not None:
                target = self.replicas[replica_id]
            else:
                target = self._route_locked(promoted)
            target.queue.append(work)
            target.routed += work.members
            gauges = self._gauges_snapshot_locked()
            self._cv.notify_all()
        # telemetry outside the condition lock (C_SINK_UNDER_LOCK):
        # sinks take their own locks and the recorder leg does work
        self._emit_promotions(promoted)
        self._emit_gauges(gauges)
        telemetry.count("requests_routed", work.members)
        return fut

    def run(self, fn, trace_id: str | None = None, members: int = 1):
        """submit() and wait: (result, replica_id, events). Raises
        what fn raised when no re-route could absorb the failure."""
        return self.submit(fn, trace_id=trace_id,
                           members=members).result()

    def warmup(self, program, machine,
               cfg: SamplerConfig | None = None) -> int:
        """Structure-keyed kernel warmup on every live replica: each
        compiles the program's sampled kernel signatures on ITS
        devices, once per structure digest (repeat calls for the same
        structure are free). Returns the number of (replica,
        structure) compilations performed."""
        from .fingerprint import program_payload, structure_digest

        key = (structure_digest(program_payload(program)),
               machine.thread_num,
               machine.chunk_size,
               None if cfg is None else (cfg.ratio, cfg.device_draw))
        futs = []
        with self._cv:
            todo = [r for r in self.replicas
                    if not r.quarantined and key not in r.warmed]
            for r in todo:
                r.warmed.add(key)
        for r in todo:
            futs.append(self.submit(
                self._warmup_thunk(program, machine, cfg),
                replica_id=r.rid, pinned=True,
            ))
        for f in futs:
            f.result()
        return len(futs)

    @staticmethod
    def _warmup_thunk(program, machine, cfg):
        def thunk():
            from ..sampler.sampled import warmup as sampled_warmup

            sampled_warmup(program, machine, cfg)

        return thunk

    def snapshot(self) -> dict:
        """Per-replica occupancy for serve `stats` (the instance-local
        view; `/metrics` and the ledger aggregate report the same
        counts under requests_routed_r*/replica_id)."""
        now = time.monotonic()
        with self._cv:
            reps = [
                {
                    "replica_id": r.rid,
                    "devices": len(r.devices),
                    "queue_depth": len(r.queue),
                    "executing": int(r.busy),
                    "routed": r.routed,
                    "served": r.served,
                    "stolen": r.stolen,
                    "completed": r.completed,
                    "failed": r.failed,
                    "quarantined": r.quarantined,
                    "breaker": r.state,
                    "breaker_reclosed": r.reclosed,
                    **(
                        {"quarantine_reason": r.quarantine_reason}
                        if r.quarantined else {}
                    ),
                    **(
                        {"reopen_in_s": round(
                            max(0.0, r.reopen_at - now), 3)}
                        if r.state == "open" else {}
                    ),
                }
                for r in self.replicas
            ]
        return {
            "count": len(reps),
            "quarantined": sum(1 for r in reps if r["quarantined"]),
            "replicas": reps,
        }

    def close(self) -> None:
        """Stop the workers; queued-but-unstarted work fails with
        RuntimeError (the executor drains its own pool first, so in
        the normal shutdown order nothing is pending here)."""
        with self._cv:
            self._closed = True
            pending = [w for r in self.replicas for w in r.queue]
            for r in self.replicas:
                r.queue.clear()
            self._cv.notify_all()
        for w in pending:
            w.future.set_exception(
                RuntimeError("replica pool closed")
            )
        for t in self._workers:
            t.join(timeout=5.0)

    # -- routing ------------------------------------------------------

    def _route_locked(self, promoted: list | None = None) -> Replica:
        """Least-loaded live replica (queue + executing), round-robin
        among ties. An OPEN replica whose probation has elapsed is
        promoted to half_open and takes this one work item as its
        probe (success re-closes it in _execute; failure re-opens
        escalated in _handle_failure). All-open pools route across
        the full set: best-effort beats going dark.

        Promotions are appended to `promoted` (replica ids) for the
        caller to emit via _emit_promotions AFTER releasing `_cv` —
        never from inside the critical section."""
        now = time.monotonic()
        for r in self.replicas:
            if r.state == "open" and now >= r.reopen_at:
                r.state = "half_open"
                if promoted is not None:
                    promoted.append(r.rid)
                return r
        live = [r for r in self.replicas if r.state == "closed"]
        if not live:
            live = self.replicas
        load = lambda r: len(r.queue) + (1 if r.busy else 0)
        best = min(load(r) for r in live)
        ties = [r for r in live if load(r) == best]
        self._rr += 1
        return ties[self._rr % len(ties)]

    def try_cancel(self, future) -> bool:
        """Remove a still-QUEUED work item by its future (the hedging
        loser: the executor submits a duplicate to a second replica
        and cancels whichever copy has not started when the first
        result lands). True when the item was found and removed; False
        means it is executing (or done) and will resolve normally."""
        gauges = None
        with self._cv:
            for r in self.replicas:
                for w in r.queue:
                    if w.future is future:
                        r.queue.remove(w)
                        gauges = self._gauges_snapshot_locked()
                        break
                if gauges is not None:
                    break
        if gauges is None:
            return False
        self._emit_gauges(gauges)
        telemetry.count("replica_work_cancelled")
        return True

    def _gauges_snapshot_locked(self) -> list:
        """(name, value) pairs computed under `_cv`; the caller emits
        them with _emit_gauges after release (C_SINK_UNDER_LOCK)."""
        busy = sum(1 for r in self.replicas if r.busy)
        queued = sum(len(r.queue) for r in self.replicas)
        pairs = [
            ("replica_utilization",
             round(busy / len(self.replicas), 4)),
            ("replica_queue_depth", queued),
        ]
        for r in self.replicas:
            pairs.append(
                (f"replica_queue_depth_r{r.rid}", len(r.queue))
            )
        return pairs

    @staticmethod
    def _emit_gauges(pairs: list) -> None:
        for name, value in pairs:
            telemetry.gauge(name, value)

    @staticmethod
    def _emit_promotions(promoted: list) -> None:
        for rid in promoted:
            telemetry.count("replica_breaker_half_open")
            telemetry.event("replica_breaker_half_open", replica=rid)

    # -- worker -------------------------------------------------------

    def _worker(self, replica: Replica) -> None:
        while True:
            work = None
            stolen_members = 0
            with self._cv:
                while work is None:
                    if self._closed:
                        return
                    if replica.queue:
                        work = replica.queue.popleft()
                    elif not replica.quarantined:
                        work = self._steal_locked(replica)
                        if work is not None:
                            stolen_members = work.members
                    if work is None:
                        self._cv.wait()
                replica.busy = True
                gauges = self._gauges_snapshot_locked()
            if stolen_members:
                telemetry.count("windows_stolen", stolen_members)
            self._emit_gauges(gauges)
            self._execute(replica, work)
            with self._cv:
                replica.busy = False
                gauges = self._gauges_snapshot_locked()
                self._cv.notify_all()
            self._emit_gauges(gauges)

    def _steal_locked(self, thief: Replica):
        """Oldest stealable item from the longest peer queue. The
        caller counts windows_stolen after releasing `_cv`."""
        victims = sorted(
            (r for r in self.replicas
             if r is not thief and r.queue),
            key=lambda r: -len(r.queue),
        )
        for victim in victims:
            for work in victim.queue:
                if not work.pinned:
                    victim.queue.remove(work)
                    thief.stolen += 1
                    return work
        return None

    def _execute(self, replica: Replica, work: _Work) -> None:
        from ..parallel import placement
        from ..runtime.obs import metrics as obs_metrics

        t0 = time.perf_counter()
        try:
            faults.fire("replica_dispatch", key=work.trace_id,
                        replica=replica.rid)
            with placement.device_scope(
                replica.devices, mesh=replica.mesh,
                replica_id=replica.rid,
            ):
                result = work.fn()
        except Exception as exc:
            self._handle_failure(replica, work, exc)
            return
        dt = time.perf_counter() - t0
        reclosed = False
        with self._cv:
            replica.completed += 1
            replica.served += work.members
            if replica.state != "closed":
                # successful half-open probe (or a pinned/stolen item
                # that completed here): the breaker re-closes and the
                # replica rejoins routing with full standing
                replica.state = "closed"
                replica.quarantine_reason = None
                replica.probation_s = self._probation_s
                replica.reclosed += 1
                reclosed = True
                self._cv.notify_all()
        if reclosed:
            telemetry.count("replica_breaker_reclosed")
            telemetry.event("replica_breaker_reclosed",
                            replica=replica.rid)
        telemetry.count(f"requests_routed_r{replica.rid}",
                        work.members)
        if obs_metrics.get() is not None:
            obs_metrics.observe(
                f"request_execute_s_r{replica.rid}", dt,
                exemplar=work.trace_id,
            )
        work.future.set_result((result, replica.rid, work.events))

    def _handle_failure(self, replica: Replica, work: _Work,
                        exc: Exception) -> None:
        """Open the replica's breaker (or re-open it escalated after
        a failed half-open probe) and re-route the item once; a
        second failure (or nowhere to go) propagates to the caller."""
        reason = repr(exc)[:200]
        drained: list[_Work] = []
        target = None
        probe_failed = False
        promoted: list[int] = []
        gauges: list = []
        with self._cv:
            replica.failed += 1
            if (work.attempts == 0 and not work.pinned
                    and not self._closed):
                peers = [r for r in self.replicas
                         if r is not replica
                         and r.state == "closed"]
                if peers:
                    if replica.state == "half_open":
                        # failed probe: back to open, probation
                        # escalated (capped) — a flapping replica
                        # gets probed less and less often
                        probe_failed = True
                        replica.probation_s = min(
                            replica.probation_s * self._escalation,
                            self._probation_max_s,
                        )
                    elif replica.state == "closed":
                        replica.probation_s = self._probation_s
                    if replica.state != "open":
                        replica.state = "open"
                        replica.reopen_at = (
                            time.monotonic() + replica.probation_s
                        )
                        replica.quarantine_reason = reason
                        # strand nothing behind an opened replica:
                        # its queued, unpinned items re-route too
                        drained = [w for w in replica.queue
                                   if not w.pinned]
                        for w in drained:
                            replica.queue.remove(w)
                    work.attempts += 1
                    load = lambda r: len(r.queue) + (1 if r.busy else 0)
                    target = min(peers, key=load)
                    work.events.append({
                        "from": f"replica:{replica.rid}",
                        "to": f"replica:{target.rid}",
                        "reason": f"replica quarantined: {reason}",
                    })
                    target.queue.append(work)
                    for w in drained:
                        self._route_locked(promoted).queue.append(w)
                    gauges = self._gauges_snapshot_locked()
                    self._cv.notify_all()
        if target is None:
            work.future.set_exception(exc)
            return
        self._emit_promotions(promoted)
        self._emit_gauges(gauges)
        telemetry.count("replica_quarantined")
        telemetry.event(
            "replica_quarantined", replica=replica.rid,
            rerouted_to=target.rid, drained=len(drained),
            reason=reason, probe_failed=probe_failed,
            probation_s=round(replica.probation_s, 3),
        )
