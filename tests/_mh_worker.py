"""Worker process for the true multi-host test (2 jax processes, 4
virtual CPU devices each, gloo collectives). Run by test_parallel.py.

Must configure the platform BEFORE jax.distributed comes up, and
jax.distributed BEFORE any backend initializes — which the package
guarantees by never creating device values at import time.
"""

import json
import os
import sys

coord, n_proc, pid = sys.argv[1], int(sys.argv[2]), int(sys.argv[3])
os.environ["XLA_FLAGS"] = (
    os.environ.get("XLA_FLAGS", "")
    + " --xla_force_host_platform_device_count=4"
)
os.environ["JAX_PLATFORMS"] = "cpu"
import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

from pluss_sampler_optimization_tpu.config import (  # noqa: E402
    MachineConfig,
    SamplerConfig,
)
from pluss_sampler_optimization_tpu.models import gemm  # noqa: E402
from pluss_sampler_optimization_tpu.parallel import (  # noqa: E402
    build_global_mesh,
    initialize_distributed,
    run_sampled_sharded,
)

initialize_distributed(coord, n_proc, pid)
mesh = build_global_mesh()
assert mesh.devices.size == 4 * n_proc, mesh.devices.size
state, results = run_sampled_sharded(
    gemm(16), MachineConfig(), SamplerConfig(ratio=0.3, seed=0), mesh
)
# second run: device-drawn samples through the multi-host mesh (every
# process replays the identical threefry buffer; only its own rows
# are contributed) — compared against the single-process device path
_, dev_results = run_sampled_sharded(
    gemm(16), MachineConfig(),
    SamplerConfig(ratio=0.3, seed=0, device_draw=True), mesh,
)
def _ser(results):
    return [
        {
            "name": r.name,
            "noshare": {str(k): v for k, v in r.noshare.items()},
            "share": {
                str(k): {str(a): b for a, b in h.items()}
                for k, h in r.share.items()
            },
            "cold": r.cold,
            "n": r.n_samples,
        }
        for r in results
    ]
print("RESULT" + str(pid) + "=" + json.dumps(_ser(results), sort_keys=True))
print("RESULTDEV" + str(pid) + "=" + json.dumps(_ser(dev_results), sort_keys=True))
