"""Test harness: force an 8-device virtual CPU platform.

Multi-chip sharding tests run on a virtual CPU mesh
(xla_force_host_platform_device_count) exactly as the driver's dryrun
validates the multi-chip path; real-TPU benching happens outside the
test suite (bench.py).

This environment auto-registers a TPU PJRT plugin from sitecustomize in
every interpreter and pins JAX_PLATFORMS to it, so plain env overrides
are too late by the time conftest runs. Backend creation is lazy,
though: overriding the jax_platforms *config* here (before any jax
computation initializes a backend) reliably selects CPU, and XLA_FLAGS
is read when the CPU client is created, which also hasn't happened yet.
"""

import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

from pluss_sampler_optimization_tpu._platform import force_virtual_cpu  # noqa: E402

force_virtual_cpu(8)

import jax  # noqa: E402

# Persistent XLA compile cache: the suite's wall time is dominated by
# jit compiles (sharded sampled kernels especially; the replica tests
# add per-leader-device variants); the cache is content-keyed so
# repeat runs skip them.  The low persistence threshold matters: CPU
# kernel compiles here are mostly 0.1-1 s each but number in the
# hundreds, and the suite must fit the tier-1 870 s budget.
try:
    jax.config.update(
        "jax_compilation_cache_dir",
        os.path.join(os.path.dirname(__file__), "..", ".jax_cache", "tests"),
    )
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.05)
except Exception:
    pass


def pytest_configure(config):
    config.addinivalue_line(
        "markers", "slow: excluded from the tier-1 run (-m 'not slow')"
    )
