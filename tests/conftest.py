"""Test harness: force an 8-device virtual CPU platform before jax loads.

Multi-chip sharding tests run on a virtual CPU mesh
(xla_force_host_platform_device_count) exactly as the driver's
dryrun validates the multi-chip path; real-TPU benching happens outside
the test suite (bench.py).
"""

import os

os.environ.setdefault("JAX_PLATFORMS", "cpu")
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8"
    ).strip()
