"""Test harness: force an 8-device virtual CPU platform.

Multi-chip sharding tests run on a virtual CPU mesh
(xla_force_host_platform_device_count) exactly as the driver's dryrun
validates the multi-chip path; real-TPU benching happens outside the
test suite (bench.py).

This environment auto-registers a TPU PJRT plugin from sitecustomize in
every interpreter and pins JAX_PLATFORMS to it, so plain env overrides
are too late by the time conftest runs. Backend creation is lazy,
though: overriding the jax_platforms *config* here (before any jax
computation initializes a backend) reliably selects CPU, and XLA_FLAGS
is read when the CPU client is created, which also hasn't happened yet.
"""

import gc
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

from pluss_sampler_optimization_tpu._platform import force_virtual_cpu  # noqa: E402

force_virtual_cpu(8)

import jax  # noqa: E402

# Persistent XLA compile cache: the suite's wall time is dominated by
# jit compiles (sharded sampled kernels especially; the replica tests
# add per-leader-device variants); the cache is content-keyed so
# repeat runs skip them.  The low persistence threshold matters: CPU
# kernel compiles here are mostly 0.1-1 s each but number in the
# hundreds, and the suite must fit the tier-1 870 s budget.
try:
    jax.config.update(
        "jax_compilation_cache_dir",
        os.path.join(os.path.dirname(__file__), "..", ".jax_cache", "tests"),
    )
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.05)
except Exception:
    pass


def pytest_configure(config):
    config.addinivalue_line(
        "markers", "slow: excluded from the tier-1 run (-m 'not slow')"
    )


# GC tax: jax's in-process caches (jaxprs, lowered/compiled
# executables, const pools) survive every test and are never garbage,
# but the cycle collector rescans them on every gen2 pass.  By the
# back half of the suite ~8M tracked objects make each pass cost
# seconds and heavy tests run 2-14x their standalone time (measured:
# test_cli_profile_dir 8s alone, 107s late in the full run — the
# difference was almost entirely gc).  Collect real garbage at each
# test-file boundary, then freeze the survivors into the permanent
# generation so later passes skip them.  Frozen objects are never
# reclaimed, which is the point — these are process-lifetime caches,
# and the suite peaks well under the host's memory.

_gc_seen_file = [None]


def pytest_runtest_teardown(item):
    fname = str(item.fspath)
    if fname != _gc_seen_file[0]:
        _gc_seen_file[0] = fname
        gc.collect()
        gc.freeze()
