"""AET/MRC: literal loop vs run-based evaluation, and sanity properties."""

import numpy as np
import pytest

from pluss_sampler_optimization_tpu.config import MachineConfig
from pluss_sampler_optimization_tpu.runtime.aet import aet_mrc, mrc_l1_error
from pluss_sampler_optimization_tpu.runtime.report import mrc_lines


def random_hist(rng, n_keys, max_exp=18, with_cold=True):
    keys = np.unique(2 ** rng.integers(0, max_exp, size=n_keys))
    h = {int(k): float(rng.integers(1, 1000)) for k in keys}
    if with_cold:
        h[-1] = float(rng.integers(1, 500))
    return h


@pytest.mark.parametrize("seed", [0, 1, 2, 3, 4])
def test_literal_equals_runs(seed):
    rng = np.random.default_rng(seed)
    h = random_hist(rng, 12)
    machine = MachineConfig(cache_kb=64)  # keep literal loop small
    a = aet_mrc(h, machine, force="literal")
    b = aet_mrc(h, machine, force="runs")
    assert len(a) == len(b)
    assert np.array_equal(a, b)  # bit-exact


def test_mrc_monotone_and_bounded():
    rng = np.random.default_rng(7)
    h = random_hist(rng, 10)
    mrc = aet_mrc(h, MachineConfig(cache_kb=64))
    assert mrc[0] == 1.0
    assert (mrc >= 0).all() and (mrc <= 1).all()
    assert (np.diff(mrc) <= 1e-12).all()  # non-increasing


def test_mrc_all_cold():
    # Only cold misses: P(t) = 1 everywhere it's defined -> flat curve
    mrc = aet_mrc({-1: 10.0}, MachineConfig())
    assert mrc[0] == 1.0


def test_mrc_lines_run_length():
    mrc = np.array([1.0, 1.0, 0.5, 0.5, 0.5, 0.1])
    lines = mrc_lines(mrc)
    assert lines[0] == "miss ratio"
    assert lines[1].startswith("0,")
    assert lines[2].startswith("1,")
    assert lines[3].startswith("2,")
    assert lines[4].startswith("4,")
    assert lines[5].startswith("5,")


def test_l1_error_zero_on_equal():
    mrc = np.array([1.0, 0.5, 0.2])
    assert mrc_l1_error(mrc, mrc) == 0.0
