"""Static IR analyzer: golden verdicts, bounds-vs-oracle, the service
preflight gate, and the determinism lint.

The acceptance pins of the analysis/ package:

- every registry model gets a PINNED analyzer verdict (the golden
  table below — a model whose race classification changes must change
  this test consciously);
- `check_static_bounds` holds against the exact engine's MRCs
  (compulsory-miss bound <= measured misses; the cold-footprint
  asymptote matches the untruncated MRC tail) for gemm, mvt, syrk and
  the triangular race models;
- malformed IR yields the right diagnostic code through BOTH
  tools/check_ir.py and the service preflight rejection path
  (structured error JSON over serve_jsonl, nothing cached, nothing
  ledgered as a success);
- MRC bytes are bit-identical with preflight on vs off;
- tools/lint_determinism.py runs clean over the bit-identity targets
  and still catches synthetic violations.
"""

import io
import json
import os
import sys

import numpy as np
import pytest

from pluss_sampler_optimization_tpu import analysis
from pluss_sampler_optimization_tpu.config import MachineConfig
from pluss_sampler_optimization_tpu.models import REGISTRY, build
from pluss_sampler_optimization_tpu.oracle.serial import run_serial
from pluss_sampler_optimization_tpu.runtime import telemetry
from pluss_sampler_optimization_tpu.runtime.aet import aet_mrc
from pluss_sampler_optimization_tpu.runtime.cri import cri_distribute
from pluss_sampler_optimization_tpu.runtime.obs import (
    ledger as obs_ledger,
)
from pluss_sampler_optimization_tpu.runtime.obs import (
    metrics as obs_metrics,
)
from pluss_sampler_optimization_tpu.service import api

sys.path.insert(
    0,
    os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), "tools"),
)
import check_ir  # noqa: E402
import lint_determinism  # noqa: E402

MACHINE = MachineConfig()

# The golden verdict table: (verdict, race count) per registry model.
# Grounded in the model docstrings' source kernels: bicg's s[j] +=,
# trisolv's x recurrence, and trmm's cross-row B reads are true
# cross-thread conflicts under the static chunk schedule; covariance's
# triangular symmetric write-back is a may-alias the rectangular hull
# cannot refute (conservative race). Everything else is provably
# race-free (the gesummv/heat-3d duplicated *read* maps are marked
# write=False in the IR, so the RMW pair convention does not misfire).
GOLDEN_VERDICTS = {
    "2mm": ("ok", 0),
    "3mm": ("ok", 0),
    "adi": ("ok", 0),
    "atax": ("ok", 0),
    "bicg": ("race", 3),
    "covariance": ("race", 6),
    "doitgen": ("ok", 0),
    "fdtd-2d": ("ok", 0),
    "gemm": ("ok", 0),
    "gemver": ("ok", 0),
    "gesummv": ("ok", 0),
    "heat-3d": ("ok", 0),
    "jacobi-2d": ("ok", 0),
    "mvt": ("ok", 0),
    "syrk": ("ok", 0),
    "syrk-tri": ("ok", 0),
    "trisolv": ("race", 5),
    "trmm": ("race", 4),
}


@pytest.fixture(autouse=True)
def _clean_telemetry():
    telemetry.disable()
    obs_metrics.disable()
    yield
    telemetry.disable()
    obs_metrics.disable()


# -- golden verdicts --------------------------------------------------


def test_golden_verdicts_all_models():
    """Every registry model gets its pinned verdict + race count."""
    assert set(GOLDEN_VERDICTS) == set(REGISTRY)
    got = {}
    for name in sorted(REGISTRY):
        report = analysis.analyze_program(build(name, 24), MACHINE)
        got[name] = (report.verdict, len(report.races))
        assert report.ok
        assert report.signature is not None
        assert report.bounds is not None
    assert got == GOLDEN_VERDICTS


def test_verdicts_size_invariant():
    """The verdict is structural: growing n never changes it."""
    for name in ("gemm", "bicg", "trisolv", "covariance", "adi"):
        small = analysis.analyze_program(build(name, 16), MACHINE)
        large = analysis.analyze_program(build(name, 40), MACHINE)
        assert small.verdict == large.verdict
        assert len(small.races) == len(large.races)
        assert small.signature == large.signature


def test_race_reasons_are_proof_labels():
    """Dependences proven absent carry the deciding test name; adi's
    column-major writes need the modular-interval refinement (plain
    GCD + Banerjee cannot prove them independent)."""
    deps = analysis.analyze_dependences(
        analysis.canonicalize(build("adi", 16))
    )
    assert all(d.kind != analysis.DEP_CARRIED or not d.race
               for d in deps)
    assert any("modular" in d.reason for d in deps)


# -- bounds vs the exact engine ---------------------------------------


@pytest.mark.parametrize("name,n", [
    ("gemm", 24), ("mvt", 64), ("syrk", 24),
    ("trisolv", 48), ("covariance", 16),
])
def test_static_bounds_hold_against_oracle_mrc(name, n):
    """The acceptance cross-check: compulsory-miss lower bound <=
    measured misses, exact access count, exact cold mass, and the
    footprint asymptote against the MRC tail — all through the
    service's own MRC recipe (executor.build_record)."""
    program = build(name, n)
    report = analysis.analyze_program(program, MACHINE)
    res = run_serial(program, MACHINE)
    rih = cri_distribute(
        res.state, MACHINE.thread_num, MACHINE.thread_num
    )
    mrc = aet_mrc(rih, MACHINE)
    assert report.bounds.exact
    assert report.bounds.total_accesses == res.total_accesses
    # static cold footprint == the engine's cold histogram mass,
    # exactly (per-nest LAT flush => sum over (nest, tid, array)
    # distinct line addresses)
    assert rih.get(-1, 0.0) == float(report.bounds.cold_model)
    assert analysis.check_static_bounds(report, mrc, MACHINE) == []


def test_bounds_interval_path_above_exact_limit():
    """Above the enumeration limit the bounds fall back to interval
    analysis: still sound (lower <= exact cold <= upper)."""
    program = build("gemm", 24)
    exact = analysis.analyze_program(program, MACHINE)
    interval = analysis.analyze_program(program, MACHINE,
                                        exact_limit=100)
    assert exact.bounds.exact and not interval.bounds.exact
    assert (interval.bounds.compulsory_lower
            <= exact.bounds.cold_model)
    assert interval.bounds.compulsory_lower >= 1


# -- malformed fixtures: check_ir AND the service rejection path ------


def test_check_ir_fixture_codes():
    """tools/check_ir.py --fixtures: every malformed fixture produces
    exactly its expected diagnostic code."""
    assert check_ir.check_fixtures() == []
    assert check_ir.main(["--fixtures"]) == 0


def test_check_ir_registry_gate():
    assert check_ir.main(["--n", "16"]) == 0


@pytest.mark.parametrize(
    "key", sorted(analysis.malformed_fixtures())
)
def test_service_preflight_rejects_fixture(key, tmp_path,
                                           monkeypatch):
    """Each malformed fixture, submitted as a service request, yields
    a structured error over serve_jsonl carrying its diagnostic code —
    and leaves nothing in the result cache and no success ledger
    row."""
    bad_program, want_code = analysis.malformed_fixtures()[key]
    monkeypatch.setattr(
        api, "build_model", lambda name, n, tsteps: bad_program
    )
    cache_dir = tmp_path / "cache"
    ledger_path = str(tmp_path / "ledger.jsonl")
    with api.AnalysisService(cache_dir=str(cache_dir),
                             ledger_path=ledger_path) as svc:
        out = io.StringIO()
        failures = api.serve_jsonl(
            svc,
            io.StringIO(
                '{"id": "bad1", "model": "gemm", "n": 8, '
                '"engine": "oracle"}\n'
            ),
            out,
        )
        assert failures == 1
        doc = json.loads(out.getvalue())
        assert doc["ok"] is False and doc["id"] == "bad1"
        assert "ir preflight rejected" in doc["error"]
        assert want_code in {d["code"] for d in doc["diagnostics"]}
        assert svc.executor.stats()["preflight_rejected"] == 1
    # nothing cached: the store directory holds no result entries
    stored = [
        f for _root, _dirs, files in os.walk(cache_dir) for f in files
    ]
    assert stored == []
    # the ledger records the rejection, never a success
    rows = obs_ledger.read_rows(ledger_path)
    assert [r["ok"] for r in rows] == [False]
    assert rows[0]["preflight"] == "invalid"
    assert rows[0]["fingerprint"] is None


def test_preflight_rejection_in_ledger_stats(tmp_path, monkeypatch):
    """check_ledger --stats (via format_stats) surfaces the preflight
    rejection count."""
    bad_program, _ = analysis.malformed_fixtures()["depth_overflow"]
    monkeypatch.setattr(
        api, "build_model", lambda name, n, tsteps: bad_program
    )
    ledger_path = str(tmp_path / "ledger.jsonl")
    with api.AnalysisService(ledger_path=ledger_path) as svc:
        with pytest.raises(analysis.PreflightError):
            svc.submit(api.AnalysisRequest(model="gemm", n=8,
                                           engine="oracle"))
    agg = obs_ledger.aggregate(obs_ledger.read_rows(ledger_path))
    assert agg["service"]["preflight_rejected"] == 1
    text = "\n".join(obs_ledger.format_stats(agg))
    assert "preflight: 1 rejected" in text


# -- the serving integration ------------------------------------------


def test_preflight_summary_rides_response_and_ledger(tmp_path):
    """A served request carries the verdict on the response, the wire
    dict, and its ledger row; a race verdict reports the race count."""
    ledger_path = str(tmp_path / "ledger.jsonl")
    with api.AnalysisService(ledger_path=ledger_path) as svc:
        ok = svc.analyze(api.AnalysisRequest(
            model="gemm", n=16, engine="oracle", id="g"))
        racy = svc.analyze(api.AnalysisRequest(
            model="bicg", n=16, engine="oracle", id="b"))
    assert ok.ok and ok.preflight == {"verdict": "ok"}
    assert racy.ok  # a race verdict is a warning, not a failure
    assert racy.preflight == {"verdict": "race", "races": 3}
    assert racy.to_jsonl_dict()["preflight"]["verdict"] == "race"
    by_model = {
        r["model"]: r for r in obs_ledger.read_rows(ledger_path)
    }
    assert by_model["gemm"]["preflight"] == "ok"
    assert by_model["bicg"]["preflight"] == "race"
    agg = obs_ledger.aggregate(obs_ledger.read_rows(ledger_path))
    assert agg["service"]["race_flagged"] == 1


def test_mrc_bit_identical_preflight_on_off():
    """The analyzer never touches the engines: byte-equal MRCs with
    the gate on and off."""
    req = dict(model="trisolv", n=24, engine="oracle")
    with api.AnalysisService(preflight=True) as svc_on:
        on = svc_on.analyze(api.AnalysisRequest(**req))
    with api.AnalysisService(preflight=False) as svc_off:
        off = svc_off.analyze(api.AnalysisRequest(**req))
    assert on.preflight is not None and off.preflight is None
    assert on.mrc.tobytes() == off.mrc.tobytes()
    assert on.mrc_digest == off.mrc_digest


def test_preflight_metrics_and_span(tmp_path):
    """With the live registry enabled: the race_warnings /
    ir_preflight_failures counters land, the request_preflight_s
    stage histogram records, and the ir_preflight span opens."""
    bad_program, _ = analysis.malformed_fixtures()["empty_domain"]
    reg = obs_metrics.enable()
    tele = telemetry.enable()
    try:
        with api.AnalysisService() as svc:
            svc.analyze(api.AnalysisRequest(
                model="bicg", n=16, engine="oracle"))
            import pluss_sampler_optimization_tpu.service.api as apimod
            orig = apimod.build_model
            apimod.build_model = lambda name, n, tsteps: bad_program
            try:
                with pytest.raises(analysis.PreflightError):
                    svc.submit(api.AnalysisRequest(
                        model="gemm", n=8, engine="oracle"))
            finally:
                apimod.build_model = orig
    finally:
        telemetry.disable()
        obs_metrics.disable()
    snap = reg.snapshot()
    assert snap["counters"]["race_warnings"] == 3
    assert snap["counters"]["ir_preflight_failures"] == 1
    assert "request_preflight_s" in snap["histograms"]
    assert "ir_preflight_failures" in reg.prometheus_text()

    def spans(nodes):
        for s in nodes:
            yield s.name
            yield from spans(getattr(s, "children", []))

    assert "ir_preflight" in set(spans(tele.roots))


def test_preflight_memo_skips_reanalysis(monkeypatch):
    """Repeat submissions of one (model, n, machine) hit the memo."""
    calls = []
    real = analysis.analyze_program

    def counting(program, machine=None, **kw):
        calls.append(program.name)
        return real(program, machine, **kw)

    monkeypatch.setattr(analysis, "analyze_program", counting)
    with api.AnalysisService() as svc:
        svc.analyze(api.AnalysisRequest(model="gemm", n=16,
                                        engine="oracle"))
        svc.analyze(api.AnalysisRequest(model="gemm", n=16,
                                        engine="oracle"))
    assert len(calls) == 1


# -- CLI analyze mode -------------------------------------------------


def test_cli_analyze_mode(capsys):
    from pluss_sampler_optimization_tpu.cli import main

    assert main(["analyze", "--model", "trisolv", "--n", "24"]) == 0
    out = capsys.readouterr().out
    assert "verdict race" in out and "W_RACE" in out
    assert main(["analyze", "--model", "gemm", "--n", "16",
                 "--analysis-json"]) == 0
    doc = json.loads(capsys.readouterr().out)
    assert doc["verdict"] == "ok"
    assert doc["bounds"]["cold_model"] == 192


# -- determinism lint -------------------------------------------------


def test_determinism_lint_runs_clean():
    """The bit-identity targets carry no wallclock/entropy/hashseed/
    set-order constructs (modulo the reviewed allowlist), and the
    lint's seeded fixtures still trip their expected rules."""
    assert lint_determinism.run_lint() == []
    assert lint_determinism.main([]) == 0
    assert lint_determinism.main(["--fixtures"]) == 0


def test_determinism_lint_catches_synthetic_violations():
    source = (
        "import time, random, os\n"
        "def digest(x):\n"
        "    t = time.time()\n"
        "    r = random.random()\n"
        "    u = os.urandom(8)\n"
        "    h = hash(x)\n"
        "    for k in {1, 2}:\n"
        "        pass\n"
        "    bad = [v for v in set(x)]\n"
        "    ok = [v for v in sorted(set(x))]\n"
        "    return t\n"
    )
    rules = sorted(
        v.rule for v in lint_determinism.lint_source(
            source, "synthetic.py"
        )
    )
    assert rules == ["entropy", "entropy", "hashseed", "set-order",
                     "set-order", "wallclock"]
    # qualname scoping: restricting to one function keeps the findings
    only = lint_determinism.lint_source(source, "synthetic.py",
                                        qualname="digest")
    assert len(only) == 6
    missing = lint_determinism.lint_source(source, "synthetic.py",
                                           qualname="nope")
    assert missing[0].rule == "missing"


def test_lint_allowlist_suppresses(tmp_path):
    source = "def f():\n    return hash((1, 2))\n"
    v = lint_determinism.lint_source(source, "x.py")[0]
    assert v.id == "x.py::f::hashseed"
    allow = tmp_path / "allow.txt"
    allow.write_text(f"# reviewed\n{v.id}\n")
    assert v.id in lint_determinism.read_allowlist(str(allow))


# -- report plumbing --------------------------------------------------


def test_report_to_dict_and_drift_priors():
    report = analysis.analyze_program(build("gemm", 16), MACHINE)
    doc = report.to_dict()
    assert doc["verdict"] == "ok"
    assert doc["bounds"]["total_accesses"] == 16896
    priors = analysis.drift_priors(report)
    assert priors["bounds_exact"] is True
    assert priors["cold_model"] == 192
    assert priors["compulsory_lower"] <= priors["cold_model"]


def test_drift_audit_carries_static_priors(tmp_path):
    from pluss_sampler_optimization_tpu.runtime.obs.drift import (
        drift_audit,
    )

    row = drift_audit("mvt", n=32, ratio=0.3,
                      ledger_path=str(tmp_path / "ledger.jsonl"))
    priors = row["static_priors"]
    assert priors["bounds_exact"] is True
    assert priors["total_accesses"] > 0
    # the audit's exact curve satisfies the analyzer's own bounds
    assert row["static_bounds_violations"] == []
