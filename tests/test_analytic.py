"""Analytic exact engine: bit-equality vs the oracle on exactly the
classes the periodic engine rejects (round-4 verdict item 4).

The engine's soundness story (sampler/analytic.py docstring) rests on
exact probe evaluations + exact affine fits + the per-period count
identity; these tests pin the end result — bit-identical PRIStates —
for every rejected family at several N, including non-power-of-two N
(multi-phase classes) and machine-geometry variations.
"""

import numpy as np
import pytest

from pluss_sampler_optimization_tpu import MachineConfig
from pluss_sampler_optimization_tpu.models import REGISTRY
from pluss_sampler_optimization_tpu.oracle import run_numpy
from pluss_sampler_optimization_tpu.sampler.analytic import run_analytic
from pluss_sampler_optimization_tpu.sampler.periodic import (
    run_exact,
    validate_periodic,
)

MACHINE = MachineConfig()


def _dump(state):
    return (
        [sorted(h.items()) for h in state.noshare],
        [sorted((k, sorted(v.items())) for k, v in h.items())
         for h in state.share],
    )


# the periodic engine's rejected classes, plus gemm as the rectangular
# control (also covered by periodic, so all three exact engines must
# agree there)
@pytest.mark.parametrize("model,n", [
    ("syrk", 24),        # mixed parallel coefficients on array A
    ("syrk", 40),        # and a second size
    ("syrk-tri", 24),    # triangular family
    ("syrk-tri", 33),    # non-pow2: multi-phase v0 classes
    ("trmm", 24),
    ("trisolv", 32),
    ("covariance", 24),
    ("gemm", 24),        # rectangular control
])
def test_analytic_bit_exact_vs_oracle(model, n):
    prog = REGISTRY[model](n)
    a = run_analytic(prog, MACHINE, batch=1 << 12)
    o = run_numpy(prog, MACHINE)
    assert a.total_accesses == o.total_accesses
    assert _dump(a.state) == _dump(o.state)


def test_analytic_odd_geometry():
    """Non-default simulated machine: different thread/chunk counts
    change the class structure (chunk positions, tails)."""
    m = MachineConfig(thread_num=3, chunk_size=5)
    prog = REGISTRY["syrk-tri"](26)
    a = run_analytic(prog, m, batch=1 << 12)
    o = run_numpy(prog, m)
    assert _dump(a.state) == _dump(o.state)


def test_exact_router_covers_rejected_classes():
    """--engine exact must route periodic-rejected programs to the
    analytic engine (not the 0.05x dense path), stay bit-exact, and
    report the engine it chose (bench's secondary row records it)."""
    for model, n in (("syrk", 24), ("syrk-tri", 24)):
        prog = REGISTRY[model](n)
        with pytest.raises(NotImplementedError):
            validate_periodic(prog, MACHINE)
        r = run_exact(prog, MACHINE)
        assert r.engine == "analytic"
        o = run_numpy(prog, MACHINE)
        assert _dump(r.state) == _dump(o.state)
    assert run_exact(REGISTRY["gemm"](24), MACHINE).engine == "periodic"


@pytest.mark.parametrize("seed", range(6))
def test_analytic_fuzz_models_geometries(seed):
    """Random (model, N, machine geometry) at sizes where the affine
    FIT machinery actually engages (N >= _ROW_FIT_MIN rows, enough
    periods for v0 classes): odd thread/chunk counts change the class
    structure, tails, and coincidence sets. Bit-equality vs the numpy
    oracle is the whole assertion — any fit accepting a wrong model
    fails here."""
    rng = np.random.default_rng(1000 + seed)
    # round-robin, not rng.choice: every model family — syrk's mixed
    # coefficients included — must be exercised at fit-engaging sizes
    models = ["syrk", "syrk-tri", "trmm", "trisolv", "covariance",
              "gemm"]
    model = models[seed % len(models)]
    n = int(rng.integers(100, 170))
    m = MachineConfig(
        thread_num=int(rng.integers(2, 6)),
        chunk_size=int(rng.integers(2, 7)),
    )
    prog = REGISTRY[model](n)
    a = run_analytic(prog, m, batch=1 << 14)
    o = run_numpy(prog, m)
    assert a.total_accesses == o.total_accesses, (model, n)
    assert _dump(a.state) == _dump(o.state), (model, n)


def test_analytic_count_identity_guard():
    """The engine self-checks sum(slot counts)+cold == box size for
    every fitted class; a healthy run raises nothing and matches the
    oracle total exactly (this is the cheap always-on invariant that
    keeps a wrong count formula from passing silently)."""
    prog = REGISTRY["syrk"](32)
    a = run_analytic(prog, MACHINE, batch=1 << 12)
    # total accesses == sum over state of... the state holds weighted
    # bins; the invariant surfaced here is the total access count
    assert a.total_accesses == run_numpy(prog, MACHINE).total_accesses
    total_folded = sum(
        sum(h.values()) for h in a.state.noshare
    ) + sum(
        sum(sum(hh.values()) for hh in h.values()) for h in a.state.share
    )
    assert total_folded == a.total_accesses
