"""Analytic exact engine: bit-equality vs the oracle on exactly the
classes the periodic engine rejects (round-4 verdict item 4).

The engine's soundness story (sampler/analytic.py docstring) rests on
exact probe evaluations + exact affine fits + the per-period count
identity; these tests pin the end result — bit-identical PRIStates —
for every rejected family at several N, including non-power-of-two N
(multi-phase classes) and machine-geometry variations.
"""

import re

import numpy as np
import pytest

from pluss_sampler_optimization_tpu import MachineConfig
from pluss_sampler_optimization_tpu.models import REGISTRY
from pluss_sampler_optimization_tpu.oracle import run_numpy
from pluss_sampler_optimization_tpu.sampler.analytic import run_analytic
from pluss_sampler_optimization_tpu.sampler.periodic import (
    run_exact,
    validate_periodic,
)

MACHINE = MachineConfig()


def _dump(state):
    return (
        [sorted(h.items()) for h in state.noshare],
        [sorted((k, sorted(v.items())) for k, v in h.items())
         for h in state.share],
    )


# the periodic engine's rejected classes, plus gemm as the rectangular
# control (also covered by periodic, so all three exact engines must
# agree there)
@pytest.mark.parametrize("model,n", [
    ("syrk", 24),        # mixed parallel coefficients on array A
    ("syrk", 40),        # and a second size
    ("syrk-tri", 24),    # triangular family
    ("syrk-tri", 33),    # non-pow2: multi-phase v0 classes
    ("trmm", 24),
    ("trisolv", 32),
    ("covariance", 24),
    ("gemm", 24),        # rectangular control
])
def test_analytic_bit_exact_vs_oracle(model, n):
    # host_cutoff=0 forces the period/fit ENGINE path at these small
    # sizes (the default would fold them through the host lexsort,
    # which is the oracle itself — exact, but not the machinery under
    # test here; the default route is covered below)
    prog = REGISTRY[model](n)
    a = run_analytic(prog, MACHINE, batch=1 << 12, host_cutoff=0)
    o = run_numpy(prog, MACHINE)
    assert a.total_accesses == o.total_accesses
    assert _dump(a.state) == _dump(o.state)


def test_analytic_odd_geometry():
    """Non-default simulated machine: different thread/chunk counts
    change the class structure (chunk positions, tails)."""
    m = MachineConfig(thread_num=3, chunk_size=5)
    prog = REGISTRY["syrk-tri"](26)
    a = run_analytic(prog, m, batch=1 << 12, host_cutoff=0)
    o = run_numpy(prog, m)
    assert _dump(a.state) == _dump(o.state)


def test_analytic_host_fold_default_routes_small_nests():
    """Nests under the host-fold cutoff take the host lexsort (the
    numpy oracle's own code) — same bits, milliseconds instead of
    per-ref kernel costs. Both routes must agree with the oracle AND
    each other."""
    prog = REGISTRY["syrk"](24)
    o = run_numpy(prog, MACHINE)
    a_host = run_analytic(prog, MACHINE, batch=1 << 12)  # default
    a_engine = run_analytic(prog, MACHINE, batch=1 << 12, host_cutoff=0)
    assert _dump(a_host.state) == _dump(o.state)
    assert _dump(a_engine.state) == _dump(a_host.state)
    assert a_host.total_accesses == o.total_accesses


@pytest.mark.parametrize("model,kw", [
    ("adi", {}),          # the round-5 crawl case: 4 nests/tstep, 18
    ("adi", {"tsteps": 2}),  # distinct ref structures, descending loops
    ("fdtd-2d", {"tsteps": 2}),  # 4 nests/tstep incl. a constant ref
])
def test_analytic_batched_stencils_bit_exact(model, kw):
    """Multi-nest stencils through run_analytic's batched dispatch:
    the adi class crawled at one dispatch per (ref, period) before the
    round-6 batching (52.9 s at N=20); the acceptance bar is exactness
    at interactive speed. Checks BOTH routes: the default (host fold
    at these sizes) and the forced engine path whose period blocks are
    the batched mega-dispatches."""
    prog = REGISTRY[model](12, **kw)
    o = run_numpy(prog, MACHINE)
    a = run_analytic(prog, MACHINE)
    assert a.total_accesses == o.total_accesses
    assert _dump(a.state) == _dump(o.state)
    a2 = run_analytic(prog, MACHINE, batch=1 << 12, host_cutoff=0)
    assert _dump(a2.state) == _dump(o.state)


def test_exact_router_adi_is_fast_and_exact():
    """The acceptance case pinned as a regression guard: run_exact on
    adi N=20 must route to analytic, match the oracle bit for bit, and
    stay interactive (the pre-round-6 crawl was ~50 s; the bound here
    is generous against CI noise while catching any return of
    per-period dispatch)."""
    import time

    prog = REGISTRY["adi"](20)
    t0 = time.perf_counter()
    r = run_exact(prog, MACHINE)
    wall = time.perf_counter() - t0
    assert r.engine == "analytic"
    o = run_numpy(prog, MACHINE)
    assert r.total_accesses == o.total_accesses
    assert _dump(r.state) == _dump(o.state)
    assert wall < 5.0, f"adi N=20 exact path took {wall:.1f}s"


def test_exact_router_covers_rejected_classes():
    """--engine exact must route periodic-rejected programs to the
    analytic engine (not the 0.05x dense path), stay bit-exact, and
    report the engine it chose (bench's secondary row records it)."""
    for model, n in (("syrk", 24), ("syrk-tri", 24)):
        prog = REGISTRY[model](n)
        with pytest.raises(NotImplementedError):
            validate_periodic(prog, MACHINE)
        r = run_exact(prog, MACHINE)
        assert r.engine == "analytic"
        o = run_numpy(prog, MACHINE)
        assert _dump(r.state) == _dump(o.state)
    assert run_exact(REGISTRY["gemm"](24), MACHINE).engine == "periodic"


@pytest.mark.parametrize("seed", range(6))
def test_analytic_fuzz_models_geometries(seed):
    """Random (model, N, machine geometry) at sizes where the affine
    FIT machinery actually engages (N >= _ROW_FIT_MIN rows, enough
    periods for v0 classes): odd thread/chunk counts change the class
    structure, tails, and coincidence sets. Bit-equality vs the numpy
    oracle is the whole assertion — any fit accepting a wrong model
    fails here."""
    rng = np.random.default_rng(1000 + seed)
    # round-robin, not rng.choice: every model family — syrk's mixed
    # coefficients included — must be exercised at fit-engaging sizes
    models = ["syrk", "syrk-tri", "trmm", "trisolv", "covariance",
              "gemm"]
    model = models[seed % len(models)]
    n = int(rng.integers(100, 170))
    m = MachineConfig(
        thread_num=int(rng.integers(2, 6)),
        chunk_size=int(rng.integers(2, 7)),
    )
    prog = REGISTRY[model](n)
    a = run_analytic(prog, m, batch=1 << 14, host_cutoff=0)
    o = run_numpy(prog, m)
    assert a.total_accesses == o.total_accesses, (model, n)
    assert _dump(a.state) == _dump(o.state), (model, n)


def test_analytic_count_identity_guard():
    """The engine self-checks sum(slot counts)+cold == box size for
    every fitted class; a healthy run raises nothing and matches the
    oracle total exactly (this is the cheap always-on invariant that
    keeps a wrong count formula from passing silently)."""
    prog = REGISTRY["syrk"](32)
    a = run_analytic(prog, MACHINE, batch=1 << 12, host_cutoff=0)
    # total accesses == sum over state of... the state holds weighted
    # bins; the invariant surfaced here is the total access count
    assert a.total_accesses == run_numpy(prog, MACHINE).total_accesses
    total_folded = sum(
        sum(h.values()) for h in a.state.noshare
    ) + sum(
        sum(sum(hh.values()) for hh in h.values()) for h in a.state.share
    )
    assert total_folded == a.total_accesses


def test_audited_family_parity_with_name_prefix_matcher():
    """audited_family is now derived from structural signatures of the
    audited builders, not name prefixes. Pin exact parity with the old
    prefix matcher across the whole registry (names and Programs), so
    the warning surface is unchanged, and keep the monkeypatch
    contract: shrinking AUDITED_FAMILIES shrinks the audited set."""
    from pluss_sampler_optimization_tpu.sampler import analytic

    def old_matcher(name: str) -> bool:
        fam = re.split(r"-\d", name)[0]
        return fam in analytic.AUDITED_FAMILIES

    for name in sorted(REGISTRY):
        for n in (8, 24):
            for tsteps in (1, 3):
                try:
                    prog = REGISTRY[name](n, tsteps=tsteps)
                except TypeError:
                    if tsteps != 1:
                        continue
                    prog = REGISTRY[name](n)
                want = old_matcher(prog.name)
                assert analytic.audited_family(prog.name) == want, (
                    name, n, tsteps)
                assert analytic.audited_family(prog) == want, (
                    name, n, tsteps)
    # unregistered families fall back to plain membership
    assert not analytic.audited_family("mystery-64")
    # monkeypatch contract (test_telemetry relies on this): dropping a
    # family from AUDITED_FAMILIES un-audits its programs
    orig = analytic.AUDITED_FAMILIES
    try:
        analytic.AUDITED_FAMILIES = frozenset(orig - {"gemm"})
        assert not analytic.audited_family(REGISTRY["gemm"](8))
        assert analytic.audited_family(REGISTRY["syrk"](8))
    finally:
        analytic.AUDITED_FAMILIES = orig
    assert analytic.audited_family(REGISTRY["gemm"](8))
