"""Recorded-baseline persistence (runtime/baseline.py)."""

import dataclasses

from pluss_sampler_optimization_tpu import MachineConfig
from pluss_sampler_optimization_tpu.runtime.baseline import (
    baseline_path,
    load_baseline,
    save_baseline,
    state_from_json,
    state_to_json,
)
from pluss_sampler_optimization_tpu.runtime.hist import PRIState


def make_state():
    st = PRIState(thread_num=2)
    st.update_noshare(0, 5, 3.0)   # pow2-bins to 4
    st.update_noshare(1, -1, 2.0)  # cold bin passes through
    st.update_share(0, 3, 16513, 1.5)
    return st


def test_state_json_roundtrip():
    st = make_state()
    back = state_from_json(state_to_json(st))
    assert back.noshare == st.noshare
    assert back.share == st.share
    assert back.thread_num == st.thread_num
    assert back.bin_noshare == st.bin_noshare


def test_save_load_roundtrip(tmp_path):
    m = MachineConfig()
    st = make_state()
    path = str(tmp_path / "gemm8.json.gz")
    save_baseline("gemm", 8, m, 1.25, 1000, st, path=path)
    doc = load_baseline("gemm", 8, m, path=path)
    assert doc is not None
    assert doc["serial_seconds"] == 1.25
    assert doc["total_accesses"] == 1000
    assert doc["state"].noshare == st.noshare
    assert doc["state"].share == st.share


def test_load_rejects_machine_mismatch(tmp_path):
    m = MachineConfig()
    path = str(tmp_path / "b.json.gz")
    save_baseline("gemm", 8, m, 1.0, 10, make_state(), path=path)
    other = MachineConfig(thread_num=3)
    assert load_baseline("gemm", 8, other, path=path) is None
    # cache_kb only parameterizes AET->MRC, not the recorded serial run
    aet_only = MachineConfig(cache_kb=1024)
    assert load_baseline("gemm", 8, aet_only, path=path) is not None


def test_load_missing_returns_none(tmp_path):
    assert load_baseline(
        "gemm", 8, MachineConfig(), path=str(tmp_path / "absent.json.gz")
    ) is None


def test_baseline_path_encodes_machine():
    m = MachineConfig()
    assert baseline_path("gemm", 128, m).endswith("gemm128.json.gz")
    odd = dataclasses.replace(m, thread_num=3)
    assert "t3" in baseline_path("gemm", 128, odd)