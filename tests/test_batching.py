"""Cross-request continuous batching: the engine-level multi-job
runner (run_sampled_multi) and the service's admission window
(service/executor.py::BatchScheduler).

The ISSUE-7 acceptance invariants are pinned here: every batch
member's results and MRC are BIT-IDENTICAL to its solo run across
mixed models, mixed N, and capacity regrows; N distinct concurrent
submissions merge into at most ceil(refs / batch_max_refs) engine
executions; a queued member whose deadline expires fails immediately
instead of riding the window; and a batch-level failure degrades
members to the solo chain rather than failing them collectively.
"""

import dataclasses
import json
import os
import sys
import time

import numpy as np
import pytest

from pluss_sampler_optimization_tpu import MachineConfig, SamplerConfig
from pluss_sampler_optimization_tpu.models import REGISTRY
from pluss_sampler_optimization_tpu.runtime import telemetry
from pluss_sampler_optimization_tpu.runtime.aet import aet_mrc
from pluss_sampler_optimization_tpu.runtime.cri import cri_distribute
from pluss_sampler_optimization_tpu.runtime.obs import (
    ledger as obs_ledger,
)
from pluss_sampler_optimization_tpu.sampler.sampled import (
    run_sampled,
    run_sampled_multi,
)
from pluss_sampler_optimization_tpu.service import (
    AnalysisRequest,
    AnalysisService,
    serve_jsonl,
)

sys.path.insert(
    0,
    os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), "tools"),
)
import check_ledger  # noqa: E402

MACHINE = MachineConfig()

# mixed models AND mixed N, each with its own sampling stream: the
# two gemm jobs share kernel-signature buckets (numeric bounds ride
# the vals operands), 2mm contributes its own
JOBS = [
    ("gemm", 24, SamplerConfig(ratio=0.3, seed=5)),
    ("gemm", 32, SamplerConfig(ratio=0.2, seed=7)),
    ("2mm", 12, SamplerConfig(ratio=0.25, seed=11)),
]


@pytest.fixture(autouse=True)
def _clean_telemetry():
    telemetry.disable()
    yield
    telemetry.disable()


def _mrc(state, machine=MACHINE):
    T = machine.thread_num
    return aet_mrc(cri_distribute(state, T, T), machine)


def _sampled_req(**kw):
    base = dict(model="gemm", n=16, engine="sampled", ratio=0.3,
                seed=1)
    base.update(kw)
    return AnalysisRequest(**base)


def _solo_mrc(req):
    """The canonical solo-engine MRC for a service request."""
    machine = req.machine()
    state, _results = run_sampled(
        req.build_program(), machine,
        SamplerConfig(ratio=req.ratio, seed=req.seed),
    )
    return _mrc(state, machine)


# -- engine layer -----------------------------------------------------


def test_multi_job_bit_identical_to_solo_mixed_models():
    """The tentpole contract at engine grain: one run_sampled_multi
    over mixed models and mixed N returns, per job, the same per-ref
    results and MRC bytes as that job's own run_sampled — while
    actually merging the jobs into a UNION bucket plan (fewer buckets
    than the solo runs dispatch in total)."""
    jobs = [(REGISTRY[m](n), MACHINE, cfg, False)
            for m, n, cfg in JOBS]
    tele = telemetry.enable()
    outs = run_sampled_multi(jobs)
    telemetry.disable()
    assert len(outs) == len(JOBS)
    assert tele.gauges["batch_jobs"] == len(JOBS)
    assert tele.gauges["ref_buckets_union"] == tele.gauges["ref_buckets"]
    assert tele.counters.get("dispatches_batched", 0) >= 1
    bound = (
        tele.gauges["ref_buckets_union"]
        * tele.gauges["expected_chunks"]
        + tele.counters.get("capacity_regrows", 0)
    )
    assert tele.counters["dispatches"] <= bound

    solo_buckets = 0
    for (m, n, cfg), (state, results) in zip(JOBS, outs):
        prog = REGISTRY[m](n)
        s_state, s_results = run_sampled(prog, MACHINE, cfg)
        assert results == s_results
        assert _mrc(state).tobytes() == _mrc(s_state).tobytes()
        t_solo = telemetry.enable()
        run_sampled(prog, MACHINE,
                    dataclasses.replace(cfg, fuse_refs=True))
        telemetry.disable()
        solo_buckets += t_solo.gauges["ref_buckets"]
    # the merge is real: the union plan dispatches fewer buckets than
    # the three solo fused plans combined (the two gemm jobs share)
    assert tele.gauges["ref_buckets_union"] < solo_buckets


def test_multi_job_regrow_bit_identical():
    """A capacity regrow under batching re-dispatches the whole merged
    group — and still decodes every member bit-equal to its solo run
    at the same starting capacity."""
    spec = [
        ("gemm", 16, SamplerConfig(ratio=0.3, seed=2)),
        ("gemm", 24, SamplerConfig(ratio=0.25, seed=3)),
    ]
    tele = telemetry.enable()
    outs = run_sampled_multi(
        [(REGISTRY[m](n), MACHINE, c, False) for m, n, c in spec],
        capacity=1,
    )
    telemetry.disable()
    assert tele.counters.get("capacity_regrows", 0) >= 1
    for (m, n, c), (_state, results) in zip(spec, outs):
        _s, solo = run_sampled(REGISTRY[m](n), MACHINE, c, capacity=1)
        assert results == solo


# -- service layer ----------------------------------------------------


def test_service_batches_concurrent_distinct_requests(tmp_path):
    """Three DISTINCT concurrent sampled requests inside one admission
    window: ONE engine execution, per-request MRC bytes equal the solo
    runs, every member lands in the cache under its own fingerprint
    (a fresh service serves all three warm with zero executions), and
    the ledger rows share one batch_id the aggregate rolls up."""
    ledger_path = str(tmp_path / "ledger.jsonl")
    reqs = [
        _sampled_req(model=m, n=n, ratio=cfg.ratio, seed=cfg.seed)
        for m, n, cfg in JOBS
    ]
    tele = telemetry.enable()
    with AnalysisService(
        cache_dir=str(tmp_path / "store"), ledger_path=ledger_path,
        batch_window_ms=400.0,
    ) as svc:
        tickets = [svc.submit(r) for r in reqs]
        resps = [svc.result(t, timeout=300) for t in tickets]
        stats = svc.executor.stats()
    telemetry.disable()
    assert all(r.ok for r in resps)
    assert all(r.cache == "miss" for r in resps)
    assert tele.counters.get("service_exec_started") == 1
    assert tele.counters.get("batches_formed") == 1
    assert tele.counters.get("batch_members") == len(reqs)
    assert stats["batches_formed"] == 1
    assert stats["batch_members"] == len(reqs)
    assert stats["batch_occupancy_p50"] == len(reqs)
    assert "batched_p50_latency_s" in stats

    for req, resp in zip(reqs, resps):
        want = _solo_mrc(req)
        assert np.asarray(resp.mrc).tobytes() == want.tobytes()
        assert resp.mrc_digest == obs_ledger.mrc_digest(want)

    rows = obs_ledger.read_rows(ledger_path)
    batched_rows = [r for r in rows if r.get("batch_id")]
    assert len(batched_rows) == len(reqs)
    assert len({r["batch_id"] for r in batched_rows}) == 1
    assert all(r["batch_members"] == len(reqs) for r in batched_rows)
    agg = obs_ledger.aggregate(rows)["batching"]
    assert agg["batches"] == 1
    assert agg["batched_requests"] == len(reqs)
    assert agg["occupancy_p50"] == len(reqs)

    # satellite 1 payoff: warm repeats on a FRESH service instance
    # need zero executions for EVERY member
    tele2 = telemetry.enable()
    with AnalysisService(
        cache_dir=str(tmp_path / "store"), batch_window_ms=50.0,
    ) as svc2:
        warm = [svc2.analyze(r, timeout=120) for r in reqs]
    telemetry.disable()
    assert tele2.counters.get("service_exec_started", 0) == 0
    assert all(w.cache in ("mem", "disk") for w in warm)
    assert ([w.mrc_digest for w in warm]
            == [r.mrc_digest for r in resps])


def test_batch_max_refs_overflow_splits(tmp_path):
    """max_refs bounds the merge: four concurrent requests at twice
    the per-request tracked-ref budget flush as exactly
    ceil(total_refs / max_refs) = 2 batches / engine executions, and
    every member still completes."""
    reqs = [
        _sampled_req(n=n, ratio=0.2, seed=s)
        for n, s in ((16, 1), (20, 2), (24, 3), (28, 4))
    ]
    refs_per = sum(
        len(nest.refs) for nest in reqs[0].build_program().nests
    )
    tele = telemetry.enable()
    with AnalysisService(
        cache_dir=str(tmp_path / "store"),
        batch_window_ms=250.0, batch_max_refs=2 * refs_per,
    ) as svc:
        tickets = [svc.submit(r) for r in reqs]
        resps = [svc.result(t, timeout=300) for t in tickets]
    telemetry.disable()
    assert all(r.ok for r in resps)
    assert tele.counters["batch_members"] == len(reqs)
    assert tele.counters["batches_formed"] == 2
    assert tele.counters["service_exec_started"] == 2


def test_batch_failure_degrades_members_to_solo(tmp_path):
    """A blown shared dispatch never fails members collectively: each
    re-runs down the solo chain and still serves its canonical MRC."""
    def broken_batch_runner(jobs):
        raise RuntimeError("shared dispatch exploded")

    reqs = [
        _sampled_req(n=16, seed=1),
        _sampled_req(n=20, seed=2),
    ]
    tele = telemetry.enable()
    with AnalysisService(
        cache_dir=str(tmp_path / "store"), batch_window_ms=300.0,
    ) as svc:
        svc.executor.batch_runner = broken_batch_runner
        tickets = [svc.submit(r) for r in reqs]
        resps = [svc.result(t, timeout=300) for t in tickets]
        stats = svc.executor.stats()
    telemetry.disable()
    assert all(r.ok for r in resps)
    assert tele.counters["batches_formed"] >= 1
    assert (tele.counters["service_batch_failed"]
            == tele.counters["batches_formed"])
    assert tele.counters["service_batch_fallback_solo"] == len(reqs)
    assert stats["batch_fallback_solo"] == len(reqs)
    for req, resp in zip(reqs, resps):
        want = _solo_mrc(req)
        assert np.asarray(resp.mrc).tobytes() == want.tobytes()


def test_queued_deadline_expires_immediately():
    """The deadline fix: a member whose deadline passes while it sits
    in the admission window fails RIGHT THEN (deadline_abandoned),
    well before the window flushes; its batchmates are unaffected."""
    doomed = _sampled_req(n=16, seed=1, deadline_s=0.05, id="doomed")
    fine = _sampled_req(n=20, seed=2, id="fine")
    tele = telemetry.enable()
    with AnalysisService(batch_window_ms=500.0) as svc:
        t_doomed = svc.submit(doomed)
        t_fine = svc.submit(fine)
        t0 = time.perf_counter()
        r_doomed = svc.result(t_doomed, timeout=60)
        doomed_wait = time.perf_counter() - t0
        r_fine = svc.result(t_fine, timeout=300)
    telemetry.disable()
    assert not r_doomed.ok
    assert "deadline_abandoned" in r_doomed.error
    # resolved by the window loop's deadline wake-up, not the flush
    assert doomed_wait < 0.45
    assert r_fine.ok
    assert tele.counters["service_deadline_abandoned"] == 1
    # only the surviving member rode the batch
    assert tele.counters.get("batch_members", 0) == 1


# -- serving / observability surface ----------------------------------


def test_serve_stats_and_ledger_surface_batching(tmp_path, capsys):
    """serve_jsonl with a batch window: healthz reports the admission
    queue, the post-batch stats snapshot carries the occupancy/latency
    counters, and the ledger's batch_id rows survive the offline
    auditor (check_ledger --stats prints the batching aggregate)."""
    import io

    ledger_path = str(tmp_path / "ledger.jsonl")
    svc = AnalysisService(
        cache_dir=str(tmp_path / "store"), ledger_path=ledger_path,
        batch_window_ms=60.0,
    )
    fin = io.StringIO("\n".join([
        json.dumps({"id": "h", "type": "healthz"}),
        json.dumps({"id": "r1", "model": "gemm", "n": 16,
                    "engine": "sampled", "ratio": 0.3, "seed": 1}),
        json.dumps({"id": "r2", "model": "gemm", "n": 20,
                    "engine": "sampled", "ratio": 0.3, "seed": 2}),
        json.dumps({"id": "s", "type": "stats"}),
    ]) + "\n")
    fout = io.StringIO()
    try:
        failures = serve_jsonl(svc, fin, fout)
        post = svc.stats()
    finally:
        svc.close()
    assert failures == 0
    h, r1, r2, s = [
        json.loads(ln) for ln in fout.getvalue().splitlines()
    ]
    assert h["ok"] and "batch_queue_depth" in h["healthz"]
    assert r1["ok"] and r2["ok"]
    # the inline stats line snapshots BEFORE the window flushed; the
    # batch keys are still present (zero-valued at worst)
    assert "batches_formed" in s["stats"]["executor"]
    assert "batching" in s["stats"]
    # the post-serve snapshot has the real counts: both requests were
    # submitted before any result was awaited, so they shared a window
    ex = post["executor"]
    assert ex["batch_members"] == 2
    assert ex["batches_formed"] >= 1
    assert "batch_occupancy_p50" in ex
    agg = post["batching"]
    assert agg["batched_requests"] == 2
    assert agg["batches"] == ex["batches_formed"]

    assert check_ledger.main([ledger_path, "--stats"]) == 0
    out = capsys.readouterr().out
    assert "batching:" in out


def test_cli_batch_window_flags(tmp_path, capsys):
    """--batch-window-ms routes one-shot runs through the batching
    service (needs --cache-dir) and rejects the flag without it."""
    with pytest.raises(SystemExit):
        from pluss_sampler_optimization_tpu.cli import main
        main(["acc", "--model", "gemm", "--n", "16", "--engine",
              "sampled", "--batch-window-ms", "30"])
    from pluss_sampler_optimization_tpu.cli import main
    rc = main([
        "acc", "--model", "gemm", "--n", "16", "--engine", "sampled",
        "--cache-dir", str(tmp_path / "store"),
        "--batch-window-ms", "30", "--batch-max-refs", "8",
    ])
    capsys.readouterr()
    assert rc == 0
