"""bench.py must always print one parseable JSON line (the driver
consumes it unattended)."""

import contextlib
import json
import os
import subprocess
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)
import bench  # noqa: E402  (repo-root module)


@contextlib.contextmanager
def _marker_absent():
    """Run with the shared probe-marker cache absent, then restore its
    prior state — deleting it for good would force the next real bench
    run to re-probe a healthy backend."""
    saved = None
    if os.path.exists(bench._PROBE_MARKER):
        saved = bench._PROBE_MARKER + ".test-saved"
        os.replace(bench._PROBE_MARKER, saved)
    try:
        yield
    finally:
        if os.path.exists(bench._PROBE_MARKER):
            os.remove(bench._PROBE_MARKER)  # probe succeeded mid-test
        if saved:
            os.replace(saved, bench._PROBE_MARKER)


def test_probe_budget_contract():
    """The probe must never block past --device-timeout: attempt
    schedule plus the optional relay TCP scan stay within the budget
    (the scan is skipped entirely when the budget cannot absorb it)."""
    import time

    with _marker_absent():
        t0 = time.perf_counter()
        ok, evidence = bench.probe_accelerator(8.0)
        wall = time.perf_counter() - t0
    assert wall <= 8.0 + 3.0  # subprocess spawn slack
    attempts = [e for e in evidence if "attempt" in e]
    assert sum(e["seconds"] for e in attempts) <= 8.0 + 1.0
    # budget <= 10s: the relay scan must have been skipped
    assert not any("relay_tcp" in e for e in evidence)
    if ok:  # healthy accelerator: nothing more to assert
        return
    # any non-zero outcome is a valid failure: "timeout", a positive
    # exit code, or a negative rc when the probe subprocess died on a
    # signal (OOM kill, crashing PJRT plugin)
    rc = attempts[0]["rc"]
    assert rc == "timeout" or rc != 0


def test_stale_marker_watchdog_bounds_backend_init():
    """Round-2 gap: a cached accel_ok marker (< 1h old) skips the
    subprocess probe, and the main process then touched the backend
    with NO bound — a tunnel that died inside the marker TTL hung the
    bench exactly the way --device-timeout exists to prevent. The
    first backend touch now runs under guarded_backend_init; this pins
    its budget with a cached marker present and a simulated stuck
    claim loop."""
    import threading
    import time

    with _marker_absent():
        # a fresh marker: the probe trusts it and skips its attempts
        os.makedirs(os.path.dirname(bench._PROBE_MARKER), exist_ok=True)
        with open(bench._PROBE_MARKER, "w"):
            pass
        ok, evidence = bench.probe_accelerator(8.0)
        assert ok and evidence == [{"cached": True}]

        release = threading.Event()
        fired = []

        def stuck_claim_loop():
            release.wait(30.0)
            return "backend"

        t0 = time.perf_counter()
        out = bench.guarded_backend_init(
            stuck_claim_loop, 1.0,
            on_timeout=lambda: (fired.append(True), release.set()),
        )
        wall = time.perf_counter() - t0
        assert fired, "watchdog did not fire on a hung init"
        assert wall < 5.0, f"budget not enforced: {wall:.1f}s"
        assert out == "backend"  # init_fn's value still propagates

    # the fast path: a healthy init must not trip the watchdog
    fired2 = []
    assert bench.guarded_backend_init(
        lambda: 42, 5.0, on_timeout=lambda: fired2.append(True)
    ) == 42
    assert not fired2


def test_emit_result_survives_tail_capture(tmp_path, capsys):
    """The driver tails stdout and parses the LAST line. Round 4's
    evidence fields grew the single output line past the tail capture
    and the round's headline number was lost (BENCH_r04 parsed:null).
    emit_result's contract: however large the evidence, the final line
    is a compact headline that parses from a 2000-byte tail."""
    extra = {
        "blob": "x" * 100_000,  # oversized evidence, worst case
        "device": "cpu",
        "mrc_l1_err": 1.3e-4,
        "periodic_exact": {"vs_baseline": 113.71},
    }
    line = bench.emit_result(
        {"metric": "gemm4096_sampled_throughput", "value": 5.13e6,
         "unit": "samples/s/chip", "vs_baseline": 158.4},
        extra, sidecar_dir=str(tmp_path),
    )
    out = capsys.readouterr().out
    doc = json.loads(out[-2000:].strip().splitlines()[-1])
    assert doc["value"] == 5.13e6 and doc["vs_baseline"] == 158.4
    assert doc["device"] == "cpu"
    assert doc["periodic_exact_vs"] == 113.71
    # stamped sidecar: the headline names THIS run's evidence file,
    # filed under bench_out/ so repeated runs don't litter the root
    assert doc["evidence"].startswith("bench_out/BENCH_EVIDENCE_")
    assert len(line.encode()) <= bench.HEADLINE_MAX_BYTES
    # the full record is still available: earlier stdout line + sidecar
    full = json.loads(out.strip().splitlines()[0])
    assert full["extra"]["blob"] == extra["blob"]
    sidecar = json.loads((tmp_path / doc["evidence"]).read_text())
    assert sidecar == full
    # the fixed name stays a `latest` pointer to the stamped file
    latest = tmp_path / bench.EVIDENCE_SIDECAR
    if latest.is_symlink():
        assert json.loads(latest.read_text()) == full
    else:
        assert json.loads(latest.read_text()) == {
            "latest": doc["evidence"]
        }


def test_emit_result_back_to_back_runs_do_not_clobber(tmp_path, capsys):
    """Two invocations keep two evidence files, each headline naming
    its own (round-5 weak point 4: one fixed sidecar held whichever
    run wrote last while every headline pointed at it)."""
    lines = [
        bench.emit_result(
            {"metric": m, "value": v, "unit": "samples/s/chip",
             "vs_baseline": 1.0},
            {"device": "cpu", "v": v}, sidecar_dir=str(tmp_path),
        )
        for m, v in (("gemm64_sampled_throughput", 1.0),
                     ("syrk64_exact_throughput", 2.0))
    ]
    refs = [json.loads(l)["evidence"] for l in lines]
    assert refs[0] != refs[1]
    for ref, v in zip(refs, (1.0, 2.0)):
        assert json.loads((tmp_path / ref).read_text())["value"] == v


def test_emit_result_enforces_headline_cap(tmp_path, capsys):
    """Oversized REQUIRED fields (the drop loop only removes optional
    keys) must truncate down to the <500-byte contract, not silently
    overrun it (ADVICE round 5, low #3)."""
    line = bench.emit_result(
        {"metric": "m" * 2000, "value": 1.0, "unit": "samples/s/chip",
         "vs_baseline": 1.0},
        {"device": "cpu"}, sidecar_dir=str(tmp_path),
    )
    capsys.readouterr()
    assert len(line.encode()) <= bench.HEADLINE_MAX_BYTES
    doc = json.loads(line)  # still one parseable JSON object
    assert doc["value"] == 1.0


def test_emit_result_headline_carries_analytic_secondary(tmp_path, capsys):
    """The exact-router secondary row's engine label must reach the
    driver's tail (the headline), not just the full record."""
    line = bench.emit_result(
        {"metric": "gemm4096_sampled_throughput", "value": 1.0,
         "unit": "samples/s/chip", "vs_baseline": 100.0},
        {"device": "cpu",
         "analytic_exact": {"model": "syrk", "n": 1024,
                            "engine": "analytic", "vs_baseline": 4.2}},
        sidecar_dir=str(tmp_path),
    )
    capsys.readouterr()
    doc = json.loads(line)
    assert doc["exact_secondary"]["engine"] == "analytic"
    assert doc["exact_secondary"]["vs_baseline"] == 4.2


def test_bench_emits_json_line(tmp_path):
    # marker held absent so --device-timeout is honored end-to-end
    # (and restored afterward for real bench runs). The analytic
    # secondary row runs at a small size (the default syrk N=1024
    # would measure a live serial baseline for minutes here); its
    # engine label is asserted below.
    before = set(os.listdir(REPO))
    bench_out = os.path.join(REPO, "bench_out")
    before_out = (set(os.listdir(bench_out))
                  if os.path.isdir(bench_out) else set())
    with _marker_absent():
        proc = subprocess.run(
            [sys.executable, os.path.join(REPO, "bench.py"),
             "--n", "64", "--device-timeout", "1",
             "--exact-model", "syrk", "--exact-n", "64"],
            capture_output=True, text=True, timeout=900, cwd=REPO,
        )
    # the stamped sidecars (evidence + telemetry) land under
    # bench_out/ (the refreshed latest pointer stays next to bench.py);
    # drop what this test created so repeat runs stay clean — but first
    # pin the telemetry sidecar's contract: it exists and validates
    # against the documented schema
    created = set(os.listdir(REPO)) - before
    created_out = ((set(os.listdir(bench_out))
                    if os.path.isdir(bench_out) else set()) - before_out)
    tele_files = [n for n in created_out
                  if n.startswith("BENCH_TELEMETRY")]
    try:
        assert proc.returncode == 0, proc.stderr[-2000:]
        assert len(tele_files) == 1, created_out
        sys.path.insert(0, os.path.join(REPO, "tools"))
        try:
            import check_telemetry_schema
        finally:
            sys.path.pop(0)
        with open(os.path.join(bench_out, tele_files[0])) as f:
            tele_doc = json.load(f)
        assert check_telemetry_schema.validate(tele_doc) == []
        assert tele_doc["counters"].get("dispatches", 0) > 0
        # the run ledger got this run's headline row (bench appends by
        # default), schema-valid and carrying the MRC digest
        from pluss_sampler_optimization_tpu.runtime.obs import (
            ledger as obs_ledger,
        )

        rows = obs_ledger.read_rows(os.path.join(REPO, "LEDGER.jsonl"))
        bench_rows = [r for r in rows if r["kind"] == "bench"]
        assert bench_rows, "bench run appended no ledger row"
        last = bench_rows[-1]
        assert last["metric"].startswith("gemm64_")
        assert last["value"] > 0
        assert len(last["mrc_digest"]) == 16
        # every row self-identifies whether it came from a
        # probe-fallback (CPU) run — silent fallback is the hazard
        assert isinstance(last["device_fallback"], bool)
    finally:
        for name in created_out:
            if name.startswith(("BENCH_EVIDENCE", "BENCH_TELEMETRY")):
                os.remove(os.path.join(bench_out, name))
        for name in created:
            if name.startswith(("BENCH_EVIDENCE", "BENCH_TELEMETRY")):
                os.remove(os.path.join(REPO, name))
            if name == "LEDGER.jsonl":
                os.remove(os.path.join(REPO, name))
    json_lines = [
        l for l in proc.stdout.splitlines() if l.startswith("{")
    ]
    assert len(json_lines) == 2, proc.stdout[-2000:]
    # the driver's view: the headline must parse from the tail alone
    final = json.loads(proc.stdout[-2000:].strip().splitlines()[-1])
    assert len(json_lines[1].encode()) <= bench.HEADLINE_MAX_BYTES
    assert final["unit"] == "samples/s/chip"
    assert final["value"] > 0
    assert final["vs_baseline"] > 0
    assert final["device"]
    assert final["evidence"].startswith("bench_out/BENCH_EVIDENCE_")
    # the analytic secondary row reaches the tail with its engine label
    assert final["exact_secondary"]["engine"] == "analytic"
    doc = json.loads(json_lines[0])  # the full record
    # evidence names its telemetry sidecar so the two cross-reference
    assert doc["extra"]["telemetry"].startswith(
        "bench_out/BENCH_TELEMETRY_")
    # ... and the run-ledger path, closing the evidence<->ledger loop
    assert doc["extra"]["ledger"] == "LEDGER.jsonl"
    assert doc["extra"]["mrc_digest"]
    assert doc["extra"]["analytic_exact"]["engine"] == "analytic"
    assert doc["extra"]["analytic_exact"]["mrc_l1_err"] == 0.0
    # static-analyzer evidence: every registry model analyzed, timed,
    # and carrying its pinned verdict
    ip = doc["extra"]["ir_preflight"]
    assert "error" not in ip
    assert len(ip["models"]) == 18
    assert ip["models"]["gemm"]["verdict"] == "ok"
    assert ip["models"]["bicg"]["verdict"] == "race"
    assert ip["models"]["bicg"]["races"] == 3
    assert ip["total_wall_ms"] > 0
    # flight-recorder evidence: the on-vs-off overhead measurement ran
    # and a clean engine run wrote no spurious bundles (the budget
    # verdict itself lives in the evidence — wall-clock ratios at
    # n=64 are too noisy to gate a test on)
    fr = doc["extra"]["flight_recorder"]
    assert "error" not in fr, fr
    # fused-kernel roofline evidence: both CPU backends measured with
    # per-stage spans, the native hot loop compared against the
    # fused-XLA baseline, MRC digests identical across backends, and
    # the three-way (xla/pallas/native) parity pin on the bounded
    # mini program all-identical
    kr = doc["extra"]["kernel_roofline"]
    assert "error" not in kr, kr
    for b in ("xla", "native"):
        row = kr["backends"][b]
        assert "error" not in row, row
        assert row["wall_s"] > 0
        assert set(row["stage_s"]) == {"draw", "dispatch", "fetch",
                                       "merge"}
        assert row["samples"] > 0
        assert len(row["mrc_digest"]) == 16
    assert kr["backends"]["native"]["hot_loop_speedup_vs_xla"] > 0
    assert kr["digests_identical"] is True
    dp = kr["digest_parity"]
    assert set(dp["digests"]) == {"xla", "pallas", "native"}
    assert dp["identical"] is True
    ro = fr["recorder_overhead"]
    assert ro["disabled_s"] > 0 and ro["enabled_s"] > 0
    assert ro["budget_pct"] == 2.0
    assert ro["bundles_written"] == 0
    assert doc["unit"] == "samples/s/chip"
    assert doc["value"] == final["value"]
    assert doc["vs_baseline"] > 0  # native baseline must have run
    assert doc["extra"]["mrc_l1_err"] < 0.05
    # contention diagnostics: one cpu/wall record per rep
    reps = doc["extra"]["rep_cpu_wall"]
    assert len(reps) == len(doc["extra"]["engine_s_all"])
    assert all(r["cpu_wall"] > 0 for r in reps)
    # slow-but-quiet diagnostics (round-3 weak point 1): host identity,
    # measured speed probe, and compile-cache hit/miss evidence
    host = doc["extra"]["host"]
    assert host["speed_probe_s"] > 0
    assert len(host["cpu_features_hash"]) == 8
    cc = doc["extra"]["compile_cache"]
    # CPU-fallback runs scope the cache per machine so another host's
    # AOT executables are never loaded (timing skew + SIGILL hazard);
    # the warm-up must have issued at least one persistent-cache
    # request — all-zero counters would mean the monitoring listeners
    # silently stopped matching this jax version's event names
    if "device_fallback" in doc["extra"]:
        assert cc["dir"].endswith(host["cpu_features_hash"])
        assert cc["total"]["compile_requests"] > 0


def test_bench_require_accelerator_refuses_cpu():
    """--require-accelerator turns the silent CPU fallback into a
    refusal: on this accelerator-less host the probe fails and bench
    must exit 2 BEFORE benchmarking (no evidence/telemetry sidecars,
    no ledger row — a refused run leaves nothing to misfile)."""
    before = set(os.listdir(REPO))
    bench_out = os.path.join(REPO, "bench_out")
    before_out = (set(os.listdir(bench_out))
                  if os.path.isdir(bench_out) else set())
    with _marker_absent():
        proc = subprocess.run(
            [sys.executable, os.path.join(REPO, "bench.py"),
             "--n", "16", "--device-timeout", "1",
             "--require-accelerator"],
            capture_output=True, text=True, timeout=300, cwd=REPO,
        )
    assert proc.returncode == 2, (proc.returncode, proc.stderr[-2000:])
    assert "--require-accelerator" in proc.stderr
    created = set(os.listdir(REPO)) - before
    created_out = ((set(os.listdir(bench_out))
                    if os.path.isdir(bench_out) else set()) - before_out)
    assert not any(
        n.startswith(("BENCH_EVIDENCE", "BENCH_TELEMETRY"))
        or n == "LEDGER.jsonl"
        for n in created | created_out
    ), (created, created_out)
