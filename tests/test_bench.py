"""bench.py must always print one parseable JSON line (the driver
consumes it unattended)."""

import json
import os
import subprocess
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)
import bench  # noqa: E402  (repo-root module)


def test_probe_budget_contract():
    """The probe must never block past --device-timeout: attempt
    schedule plus the optional relay TCP scan stay within the budget
    (the scan is skipped entirely when the budget cannot absorb it)."""
    import time

    if os.path.exists(bench._PROBE_MARKER):
        os.remove(bench._PROBE_MARKER)
    t0 = time.perf_counter()
    ok, evidence = bench.probe_accelerator(8.0)
    wall = time.perf_counter() - t0
    assert wall <= 8.0 + 3.0  # subprocess spawn slack
    attempts = [e for e in evidence if "attempt" in e]
    assert sum(e["seconds"] for e in attempts) <= 8.0 + 1.0
    # budget <= 10s: the relay scan must have been skipped
    assert not any("relay_tcp" in e for e in evidence)
    if ok:  # healthy accelerator: nothing more to assert
        return
    assert attempts and attempts[0]["rc"] in ("timeout", 1)


def test_bench_emits_json_line():
    # a cached successful probe would bypass --device-timeout and let
    # the subprocess block on a stalled accelerator tunnel
    if os.path.exists(bench._PROBE_MARKER):
        os.remove(bench._PROBE_MARKER)
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, "bench.py"),
         "--n", "64", "--device-timeout", "1"],
        capture_output=True, text=True, timeout=900, cwd=REPO,
    )
    assert proc.returncode == 0, proc.stderr[-2000:]
    json_lines = [
        l for l in proc.stdout.splitlines() if l.startswith("{")
    ]
    assert len(json_lines) == 1, proc.stdout[-2000:]
    doc = json.loads(json_lines[0])
    assert doc["unit"] == "samples/s/chip"
    assert doc["value"] > 0
    assert doc["vs_baseline"] > 0  # native baseline must have run
    assert doc["extra"]["mrc_l1_err"] < 0.05
