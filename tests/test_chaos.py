"""Chaos-hardened serving (ISSUE 14): deterministic fault injection
(runtime/faults.py), retry/hedging/circuit breakers, and
admission-controlled load shedding (service/executor.py), plus the
graceful-shutdown and cache-quarantine satellites.

The acceptance invariants pinned here:

- ZERO-OVERHEAD DEFAULT: with the fault layer compiled in but no
  injector installed and no resilience config, MRC bytes are
  bit-identical to the direct engine pipeline — the chaos layer is
  invisible until armed.
- Fault decisions and backoff jitter are pure functions of
  (seed, path): same spec, same seed => same decisions, so a chaos
  run replays exactly (the multi-seed gate is tools/check_chaos.py,
  wired in below).
- A corrupted disk record is atomically quarantined to `*.corrupt`,
  counted, and transparently recomputed to the same digest.
- Under a full queue, low-priority work sheds before normal before
  high; a shed is a structured `shed: true` response in
  microseconds, stamped on its own ledger row.
- begin_shutdown() drains: in-flight work finishes and answers,
  queued work cancels, later submits shed; a real serve process
  under SIGTERM exits cleanly with the drain summary, a flushed
  ledger, and a final flight-recorder bundle.
"""

import glob
import json
import os
import signal
import subprocess
import sys
import threading
import time
from concurrent.futures import CancelledError

import numpy as np
import pytest

from pluss_sampler_optimization_tpu import SamplerConfig
from pluss_sampler_optimization_tpu.config import (
    FaultConfig,
    ResilienceConfig,
)
from pluss_sampler_optimization_tpu.runtime import faults, telemetry
from pluss_sampler_optimization_tpu.runtime.aet import aet_mrc
from pluss_sampler_optimization_tpu.runtime.cri import cri_distribute
from pluss_sampler_optimization_tpu.runtime.obs import (
    ledger as obs_ledger,
)
from pluss_sampler_optimization_tpu.sampler.sampled import run_sampled
from pluss_sampler_optimization_tpu.service import (
    AnalysisRequest,
    AnalysisService,
)
from pluss_sampler_optimization_tpu.service.executor import (
    default_runner,
)

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, os.path.join(REPO_ROOT, "tools"))
import check_chaos  # noqa: E402


@pytest.fixture(autouse=True)
def _no_leftover_injector():
    """A leaked injector from a failed test would silently arm every
    later service run in the process."""
    if faults.get() is not None:
        faults.uninstall()
    yield
    if faults.get() is not None:
        faults.uninstall()


def _sampled_req(**kw):
    base = dict(model="gemm", n=16, engine="sampled", ratio=0.3,
                seed=1)
    base.update(kw)
    return AnalysisRequest(**base)


def _solo_mrc(req):
    machine = req.machine()
    state, _results = run_sampled(
        req.build_program(), machine,
        SamplerConfig(ratio=req.ratio, seed=req.seed),
    )
    T = machine.thread_num
    return aet_mrc(cri_distribute(state, T, T), machine)


def _blocking_runner(started, release):
    """Holds every execution on `release`; `started` flags the first
    pickup — the deterministic way to pin one request in-flight."""

    def runner(engine, program, machine, request):
        started.set()
        if not release.wait(30):
            raise RuntimeError("test runner never released")
        return default_runner(engine, program, machine, request)

    return runner


# -- zero-overhead default path ---------------------------------------


def test_fault_layer_disabled_is_bit_identical():
    """The acceptance pin: fault sites compiled into every hot path,
    no injector installed, no resilience config — the response MRC
    bytes equal the direct engine pipeline's bytes exactly."""
    assert faults.get() is None
    req = _sampled_req()
    with AnalysisService() as svc:
        resp = svc.analyze(req, timeout=300)
    assert resp.ok and not resp.shed and not resp.hedged
    assert resp.retries == 0
    assert np.asarray(resp.mrc).tobytes() == _solo_mrc(req).tobytes()


# -- seeded determinism ------------------------------------------------


def test_counter_and_backoff_replay_from_seed():
    us = [faults.counter_u01(7, "site", i) for i in range(64)]
    assert us == [faults.counter_u01(7, "site", i) for i in range(64)]
    assert all(0.0 <= u < 1.0 for u in us)
    assert len(set(us)) > 60  # distinct draws, not a constant
    assert us != [faults.counter_u01(8, "site", i) for i in range(64)]

    ds = [faults.backoff_delay(a, 0.1, 0.8, 3, "k")
          for a in range(6)]
    assert ds == [faults.backoff_delay(a, 0.1, 0.8, 3, "k")
                  for a in range(6)]
    for a, d in enumerate(ds):
        full = min(0.8, 0.1 * (2 ** a))
        assert full * 0.5 <= d <= full  # jitter in [0.5, 1.0] x cap


def test_injector_decisions_replay_and_respect_max_fires():
    cfg = FaultConfig(seed=11, rules=(
        {"site": "engine_execute", "kind": "raise", "p": 0.5,
         "max_fires": 3},
    ))

    def decisions():
        inj = faults.install(cfg)
        try:
            out = []
            for i in range(40):
                # 20 distinct request keys, 2 occurrences each (the
                # retry shape): max_fires budgets each KEY separately
                try:
                    faults.fire("engine_execute", key=f"fp-{i % 20}")
                    out.append(False)
                except faults.FaultInjected:
                    out.append(True)
            return out, inj.total_fired()
        finally:
            faults.uninstall()

    first, fired = decisions()
    assert (first, fired) == decisions()  # pure f(seed, spec, calls)
    assert 0 < fired == sum(first)
    # a single key saturates its per-key budget, then goes quiet
    inj = faults.install(cfg)
    try:
        hits = 0
        for _ in range(64):
            try:
                faults.fire("engine_execute", key="one-fp")
            except faults.FaultInjected:
                hits += 1
        assert hits == 3  # max_fires caps the p=0.5 rule per key
    finally:
        faults.uninstall()


# -- cache corruption quarantine (satellite 1) -------------------------


def test_corrupt_disk_record_quarantined_and_recomputed(tmp_path):
    req = _sampled_req(seed=5)
    store = str(tmp_path / "store")
    with AnalysisService(cache_dir=store) as svc:
        want = svc.analyze(req, timeout=300)
    assert want.ok
    (path,) = glob.glob(os.path.join(store, "*", "*.json"))
    with open(path, "w") as f:
        f.write('{"truncated": tru')

    tele = telemetry.enable()
    with AnalysisService(cache_dir=store) as svc:
        again = svc.analyze(req, timeout=300)
        stats = svc.cache.stats()
    telemetry.disable()

    assert again.ok and again.cache == "miss"  # recomputed, not served
    assert again.mrc_digest == want.mrc_digest
    # the bad bytes moved aside atomically and were counted; the
    # recompute then stored a FRESH record back at the original path
    assert os.path.exists(path + ".corrupt")
    assert json.load(open(path))  # valid again (the recompute's write)
    assert stats["corrupt"] == 1
    assert stats["corrupt_quarantined"] == 1
    assert tele.counters.get("service_cache_corrupt_quarantined") == 1
    # the recompute overwrote the record: a third read is a disk hit
    with AnalysisService(cache_dir=store) as svc:
        third = svc.analyze(req, timeout=300)
    assert third.ok and third.cache == "disk"
    assert third.mrc_digest == want.mrc_digest


# -- admission control / shedding --------------------------------------


def test_shed_order_low_before_normal_before_high(tmp_path):
    """queue_limit=4 with one blocked worker: headroom fractions give
    low 2 queue slots, normal 3, high 4 — so as the queue fills, each
    class sheds exactly when ITS limit is reached, and every shed is
    a structured immediate response with its own ledger row."""
    started, release = threading.Event(), threading.Event()
    ledger_path = str(tmp_path / "ledger.jsonl")
    res = ResilienceConfig(queue_limit=4)
    with AnalysisService(
        max_workers=1, runner=_blocking_runner(started, release),
        resilience=res, ledger_path=ledger_path,
    ) as svc:
        t0 = svc.submit(_sampled_req(seed=100))
        assert started.wait(30)  # in-flight: depth 0
        q1 = svc.submit(_sampled_req(seed=101))  # depth 1
        q2 = svc.submit(_sampled_req(seed=102))  # depth 2
        low = svc.submit(_sampled_req(seed=103, priority="low"))
        n1 = svc.submit(_sampled_req(seed=104))  # depth 3
        n2 = svc.submit(_sampled_req(seed=105))
        h1 = svc.submit(
            _sampled_req(seed=106, priority="high")
        )  # depth 4
        h2 = svc.submit(_sampled_req(seed=107, priority="high"))

        # shed futures resolve BEFORE the worker is released
        shed_low = svc.result(low, timeout=5)
        shed_n = svc.result(n2, timeout=5)
        shed_h = svc.result(h2, timeout=5)
        release.set()
        served = [svc.result(t, timeout=300) for t in (t0, q1, q2, n1,
                                                       h1)]
        st = svc.stats()["executor"]
    assert all(r.ok for r in served)
    for resp in (shed_low, shed_n, shed_h):
        assert resp.shed and not resp.ok
        assert resp.error.startswith("shed: queue depth")
        assert resp.mrc is None
    # low shed at depth 2 while normal still had room; normal shed at
    # depth 3 while high still had room
    assert "priority 'low'" in shed_low.error
    assert "depth 2" in shed_low.error
    assert "depth 3" in shed_n.error
    assert "depth 4" in shed_h.error
    assert st["shed"] == 3 and st["queue_limit"] == 4

    rows = [r for r in obs_ledger.read_rows(ledger_path)
            if r.get("kind") == "request"]
    shed_rows = [r for r in rows if r.get("shed")]
    assert len(shed_rows) == 3
    assert all(not r.get("ok") for r in shed_rows)


# -- graceful shutdown (satellite 2) -----------------------------------


def test_begin_shutdown_drains_in_process():
    """drain(): the running execution finishes and answers ok, the
    queued one cancels, and a post-drain submit sheds with the
    draining reason."""
    started, release = threading.Event(), threading.Event()
    with AnalysisService(
        max_workers=1, runner=_blocking_runner(started, release),
    ) as svc:
        running = svc.submit(_sampled_req(seed=200))
        assert started.wait(30)
        queued = svc.submit(_sampled_req(seed=201))
        svc.begin_shutdown()
        late = svc.result(svc.submit(_sampled_req(seed=202)),
                          timeout=5)
        assert late.shed and "draining" in late.error
        with pytest.raises(CancelledError):
            svc.result(queued, timeout=5)
        release.set()
        done = svc.result(running, timeout=300)
        st = svc.stats()["executor"]
    assert done.ok and not done.shed
    assert st["draining"] is True
    assert st["shed"] == 2  # the cancelled queued item + the late one


def test_serve_sigterm_graceful_subprocess(tmp_path):
    """A real serve process: answer one request, then SIGTERM while
    blocked on stdin — the process drains, prints the shutdown
    summary, flushes the ledger, writes the final flight-recorder
    bundle, and exits 0."""
    env = {**os.environ, "JAX_PLATFORMS": "cpu"}
    env.pop("XLA_FLAGS", None)
    ledger_path = str(tmp_path / "ledger.jsonl")
    bundle_dir = str(tmp_path / "bundles")
    resp_path = str(tmp_path / "resps.jsonl")
    proc = subprocess.Popen(
        [
            sys.executable, "-m",
            "pluss_sampler_optimization_tpu.cli", "serve",
            "--cache-dir", str(tmp_path / "store"),
            "--ledger", ledger_path,
            "--responses", resp_path,
            "--debug-bundle-dir", bundle_dir,
        ],
        stdin=subprocess.PIPE, stdout=subprocess.PIPE,
        stderr=subprocess.PIPE, text=True, cwd=REPO_ROOT, env=env,
    )
    try:
        proc.stdin.write(json.dumps(
            {"id": "g1", "model": "gemm", "n": 16, "engine": "oracle"}
        ) + "\n")
        proc.stdin.flush()
        # serve_jsonl answers in its SECOND pass, after stdin ends —
        # so watch the ledger (appended at execution completion) to
        # know the request is done, then SIGTERM while the reader is
        # still blocked on stdin
        deadline = time.time() + 240
        while time.time() < deadline:
            if os.path.exists(ledger_path) and any(
                r.get("kind") == "request"
                for r in obs_ledger.read_rows(ledger_path)
            ):
                break
            time.sleep(0.1)
        else:
            raise AssertionError("serve never executed the request")
        proc.send_signal(signal.SIGTERM)
        _out, err = proc.communicate(timeout=120)
    finally:
        if proc.poll() is None:
            proc.kill()
            proc.communicate()
    assert proc.returncode == 0
    assert "graceful shutdown" in err
    entries = [json.loads(ln)
               for ln in open(resp_path).read().splitlines()]
    assert entries and entries[0]["id"] == "g1" and entries[0]["ok"]
    rows = [r for r in obs_ledger.read_rows(ledger_path)
            if r.get("kind") == "request"]
    assert rows and rows[0]["ok"]
    shutdown_bundles = glob.glob(
        os.path.join(bundle_dir, "BUNDLE_*_shutdown.json")
    )
    assert shutdown_bundles, "no final flight-recorder bundle on " \
        f"shutdown (dir has {os.listdir(bundle_dir)})"
    doc = json.load(open(shutdown_bundles[0]))
    assert (doc.get("trigger") or {}).get("reason") == \
        "graceful_shutdown"


# -- the multi-seed chaos gate (satellite 5 wiring) --------------------


def test_check_chaos_gate_two_seeds(capsys):
    """The full seeded gate in-process: baseline vs chaos
    bit-identity, replay, quarantine, breaker recovery, attempt
    timeouts, hedging, serve-line faults, and the fast overload
    comparison, at two seeds."""
    assert check_chaos.main(["--seeds", "2"]) == 0
    out = capsys.readouterr().out
    assert "0 problem(s)" in out


@pytest.mark.slow
def test_check_chaos_overload_soak():
    """The pinned-SLO overload soak (shed-on p95 within budget while
    the shed-off baseline collapses) — heavier, so slow-marked."""
    assert check_chaos.main(["--seeds", "1", "--slow"]) == 0
