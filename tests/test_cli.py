"""CLI driver: the reference's acc/speed/sample harness as one command.

The reference's accuracy protocol is "run each implementation, append
the dumps to output.txt, diff" (README.md:10-12, Makefile:39-41);
test_acc_dumps_identical_across_engines automates exactly that diff.
"""

import pytest

from pluss_sampler_optimization_tpu.cli import main


def _dump(capsys, argv):
    assert main(argv) == 0
    return capsys.readouterr().out


def test_acc_dumps_identical_across_engines(capsys):
    outs = {}
    engines = ["oracle", "numpy", "dense", "stream", "periodic", "exact"]
    try:
        from pluss_sampler_optimization_tpu import native

        if native.available():
            engines += ["native", "native-par"]
    except Exception:
        pass
    for engine in engines:
        outs[engine] = _dump(
            capsys, ["acc", "--model", "gemm", "--n", "16", "--engine", engine]
        )
    base = outs["oracle"]
    for engine, out in outs.items():
        assert out == base, f"{engine} dumps differ from oracle"


def test_exact_engine_falls_back_when_periodic_rejects(capsys):
    """--engine exact must route triangular models (periodic-rejected)
    through the dense path and still match the oracle byte for byte."""
    a = _dump(capsys, ["acc", "--model", "trmm", "--n", "9",
                       "--engine", "exact"])
    b = _dump(capsys, ["acc", "--model", "trmm", "--n", "9",
                       "--engine", "oracle"])
    assert a == b


def test_speed_mode(capsys):
    out = _dump(
        capsys,
        ["speed", "--model", "gemm", "--n", "16", "--engine", "oracle",
         "--reps", "2"],
    )
    assert "run 0" in out and "run 1" in out and "best" in out


def test_sample_mode_writes_mrc(tmp_path, capsys):
    path = tmp_path / "mrc.txt"
    out = _dump(
        capsys,
        ["sample", "--model", "gemm", "--n", "16", "--ratio", "0.3",
         "--mrc-out", str(path)],
    )
    assert "ref B0" in out and "samples" in out
    lines = path.read_text().splitlines()
    assert lines[0] == "miss ratio"
    assert lines[1].startswith("0, 1")


def test_sample_mode_sharded_multidevice(capsys):
    """The user-facing sharded entry on a real multi-device mesh.

    The library path (run_sampled_sharded) has 8-device coverage in
    test_parallel.py; this pins the CLI flow — argument plumbing,
    build_mesh() over every visible device, dump emission — so it
    cannot regress separately. Dumps must match the single-device
    sampled engine byte for byte."""
    import jax

    assert jax.device_count() == 8  # the conftest virtual CPU mesh
    args = ["sample", "--model", "gemm", "--n", "16", "--ratio", "0.3"]
    out_sharded = _dump(capsys, args + ["--engine", "sharded"])
    out_sampled = _dump(capsys, args + ["--engine", "sampled"])
    assert out_sharded == out_sampled
    # the CLI's own diff harness agrees
    _dump(capsys, args + ["--engine", "sharded", "--diff-against", "sampled"])


def test_all_models_build(capsys):
    from pluss_sampler_optimization_tpu.models import REGISTRY

    for model in REGISTRY:
        out = _dump(
            capsys,
            ["acc", "--model", model, "--n", "8", "--engine", "oracle"],
        )
        assert "miss ratio" in out


def test_tsteps_flag(capsys):
    # reaches every time-stepped model; rejected where it has no meaning
    for model in ["jacobi-2d", "fdtd-2d", "heat-3d"]:
        out = _dump(
            capsys,
            ["acc", "--model", model, "--n", "6", "--tsteps", "2",
             "--engine", "oracle"],
        )
        assert "miss ratio" in out
    with pytest.raises(SystemExit):
        main(["acc", "--model", "gemm", "--n", "8", "--tsteps", "2"])


def test_unknown_engine():
    with pytest.raises(SystemExit):
        main(["acc", "--engine", "bogus"])


def test_diff_against_identical(capsys):
    assert main(["acc", "--model", "gemm", "--n", "12", "--engine",
                 "dense", "--diff-against", "oracle"]) == 0
    assert "acc dumps identical" in capsys.readouterr().out


def test_diff_against_engine_pairs(capsys):
    # sampled == sharded (same draws), dense == stream (same traversal)
    assert main(["sample", "--n", "16", "--engine", "sampled",
                 "--diff-against", "sharded", "--ratio", "0.2"]) == 0
    capsys.readouterr()
    assert main(["acc", "--n", "12", "--engine", "dense",
                 "--diff-against", "stream"]) == 0
    capsys.readouterr()


def test_diff_against_mismatch(capsys):
    # a sampled run cannot reproduce the full traversal's dumps
    assert main(["acc", "--n", "16", "--engine", "sampled",
                 "--diff-against", "dense", "--ratio", "0.2"]) == 1
    out = capsys.readouterr().out
    assert "acc dumps DIFFER" in out and "---" in out
