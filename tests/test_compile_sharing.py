"""Compile-sharing contract of the sampled engine's kernels.

Round-4 verdict item 2: kernels used to be compiled per (ref, N) —
`highs` and every trace number were baked into the jaxpr, so each
(ref, N) pair paid its own ~1-1.5 min compile through the tunneled AOT
helper (BASELINE.md "Compile costs through the tunnel"). Now the
structure lives in a signature-keyed kernel cache
(sampler/sampled.py::_kernel_sig) and every N-dependent number rides in
as a device operand (nt.vals, padded highs, the traced ref index rx).

These tests pin the two halves of that contract:

1. sharing: one compiled kernel serves every N and every structurally
   identical ref — GEMM collapses to 4 kernels (C0/C1 pair, C2/C3
   pair, A0, B0) and a second N adds ZERO jit cache entries;
2. no leakage: a kernel built at one N produces bit-identical results
   at another N to a kernel built fresh at that N (a concrete value
   accidentally read from the builder trace instead of the operands
   would break this).
"""

import numpy as np

from pluss_sampler_optimization_tpu import MachineConfig, SamplerConfig
from pluss_sampler_optimization_tpu.core.trace import ProgramTrace
from pluss_sampler_optimization_tpu.models import REGISTRY
from pluss_sampler_optimization_tpu.sampler import sampled as S

MACHINE = MachineConfig()


def _state_dump(state):
    return (
        [sorted(h.items()) for h in state.noshare],
        [sorted((k, sorted(v.items())) for k, v in h.items())
         for h in state.share],
    )


def test_kernel_signature_invariant_across_n():
    """The structural signature — everything a compiled kernel bakes
    in — must not depend on N once the band plans stabilize."""
    for model in ("gemm", "2mm", "jacobi-2d"):
        t1 = ProgramTrace(REGISTRY[model](128), MACHINE)
        t2 = ProgramTrace(REGISTRY[model](512), MACHINE)
        for nt1, nt2 in zip(t1.nests, t2.nests):
            for ri in range(nt1.tables.n_refs):
                assert S._kernel_sig(nt1, ri) == S._kernel_sig(nt2, ri), (
                    f"{model} ref {ri}: signature differs across N"
                )


def test_gemm_cold_warmup_kernel_count():
    """Cold GEMM = 4 distinct kernels at any N: the round-4 verdict's
    'one compiled kernel per (depth, batch, capacity) serves every N
    and ref'. C0/C1 (2-deep C pair) and C2/C3 (3-deep C pair) each
    share one compile; A0 and B0 are structurally distinct."""
    S._SIG_KERNELS.clear()
    S._program_kernels.cache_clear()
    S._program_kernels(REGISTRY["gemm"](256), MACHINE)
    assert len(S._SIG_KERNELS) == 4
    S._program_kernels(REGISTRY["gemm"](4096), MACHINE)
    assert len(S._SIG_KERNELS) == 4  # another N adds nothing


def test_no_recompile_and_no_leakage_across_n():
    """Running a second N through kernels built at a first N must (a)
    add zero jit cache entries — same shapes, same structure, values as
    operands — and (b) produce results bit-identical to kernels built
    fresh at that N."""
    # ratio/batch chosen so every ref's sample count exceeds the batch:
    # all chunks pad to exactly `batch` and shapes match across N
    cfg = SamplerConfig(ratio=0.4, seed=3)
    kw = dict(batch=1 << 10)

    S._SIG_KERNELS.clear()
    S._program_kernels.cache_clear()
    st_a, _ = S.run_sampled(REGISTRY["gemm"](128), MACHINE, cfg, **kw)
    compiles_after_first = sum(
        e["plain"]._cache_size() for e in S._SIG_KERNELS.values()
    )
    st_b, _ = S.run_sampled(REGISTRY["gemm"](160), MACHINE, cfg, **kw)
    compiles_after_second = sum(
        e["plain"]._cache_size() for e in S._SIG_KERNELS.values()
    )
    assert compiles_after_second == compiles_after_first, (
        "second N retraced shared kernels"
    )

    # leakage check: fresh kernels built AT N=160 must agree bit-exactly
    S._SIG_KERNELS.clear()
    S._program_kernels.cache_clear()
    st_fresh, _ = S.run_sampled(REGISTRY["gemm"](160), MACHINE, cfg, **kw)
    assert _state_dump(st_b) == _state_dump(st_fresh)


def test_cross_model_sharing_is_structural_only():
    """2mm's GEMM-shaped nests may share kernels with gemm ONLY when
    the full signature matches; a signature mismatch must yield
    distinct kernels rather than a wrong shared one. (The leakage test
    above is the behavioral guarantee; this pins that the cache key is
    the signature and nothing looser.)"""
    S._SIG_KERNELS.clear()
    S._program_kernels.cache_clear()
    S._program_kernels(REGISTRY["gemm"](128), MACHINE)
    n_gemm = len(S._SIG_KERNELS)
    S._program_kernels(REGISTRY["2mm"](128), MACHINE)
    trace = ProgramTrace(REGISTRY["2mm"](128), MACHINE)
    sigs = {
        S._kernel_sig(nt, ri)
        for nt in trace.nests
        for ri in range(nt.tables.n_refs)
    }
    assert len(S._SIG_KERNELS) == n_gemm + len(
        sigs - {
            S._kernel_sig(nt, ri)
            for nt in ProgramTrace(REGISTRY["gemm"](128), MACHINE).nests
            for ri in range(nt.tables.n_refs)
        }
    )


def test_warmup_compiles_fused_shapes():
    """warmup() parity with cross-ref fusion (ISSUE 6): warmup must
    compile the fused kernels at the per-bucket STACKED shapes the
    fused runner will dispatch — (R, group*batch) for the host path —
    so a post-warmup fused run adds ZERO jit cache entries."""
    import dataclasses

    cfg = SamplerConfig(ratio=0.4, seed=3, fuse_refs=True)
    kw = dict(batch=1 << 10)

    def fused_compiles():
        return sum(
            e["fused"]._cache_size() for e in S._SIG_KERNELS.values()
        )

    S._SIG_KERNELS.clear()
    S._program_kernels.cache_clear()
    S.warmup(REGISTRY["gemm"](128), MACHINE, cfg, **kw)
    after_warmup = fused_compiles()
    assert after_warmup > 0, "warmup never touched the fused kernels"
    st_w, _ = S.run_sampled(REGISTRY["gemm"](128), MACHINE, cfg, **kw)
    assert fused_compiles() == after_warmup, (
        "post-warmup fused run recompiled: warmup misses the stacked "
        "bucket shapes"
    )
    # and the warmed fused run is still bit-identical to unfused
    st_s, _ = S.run_sampled(
        REGISTRY["gemm"](128), MACHINE,
        dataclasses.replace(cfg, fuse_refs=False), **kw,
    )
    assert _state_dump(st_w) == _state_dump(st_s)


def test_padded_highs_decode_roundtrip():
    """Padded highs (1s beyond the ref depth) decode exactly like the
    unpadded radix for keys in the ref's own space."""
    highs = [7, 5]
    keys = np.arange(35, dtype=np.int64)
    a = np.asarray(S.decode_sample_keys(keys, tuple(highs)))
    b = np.asarray(S.decode_sample_keys(keys, S._pad_highs(highs)))
    assert (b[:, : len(highs)] == a).all()
    assert (b[:, len(highs):] == 0).all()
