"""Concurrency analyzer + lockdep witness: the standing gate is
clean on the repo, every seeded fixture trips its expected C_* code
with a nonzero exit, the static lock-order graph is cycle-free, both
lint gates share one JSON report shape, and the runtime witness
detects inversions / long holds while staying a pure observer.
"""

import json
import os
import sys
import threading
import time

import pytest

from pluss_sampler_optimization_tpu.analysis import concurrency
from pluss_sampler_optimization_tpu.analysis.lint_common import (
    check_fixtures,
)
from pluss_sampler_optimization_tpu.runtime import lockwitness

sys.path.insert(
    0,
    os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), "tools"),
)
import check_concurrency  # noqa: E402
import lint_determinism  # noqa: E402


# -- the standing gate ------------------------------------------------


def test_gate_runs_clean_on_repo():
    """Zero unreviewed findings across the serving runtime — the
    same invariant tools/check_concurrency.py enforces in CI."""
    assert check_concurrency.main([]) == 0


def test_repo_lock_graph_is_cycle_free():
    res = concurrency.analyze_files()
    assert res.n_files >= 10
    assert res.n_functions > 50
    assert not any(v.rule == "C_LOCK_CYCLE" for v in res.violations)
    # edge pairs are exactly the keys of the site map, sorted
    assert res.edge_pairs() == sorted(res.edges)


def test_inventory_covers_known_primitives():
    inv = concurrency.analyze_files().inventory
    lock_ids = {d["id"] for d in inv["locks"]}
    assert {"RequestExecutor._lock", "BatchScheduler._cv",
            "ResultCache._lock", "telemetry._lock"} <= lock_ids
    assert inv["signal_handlers"]  # cli._serve registers handlers


def test_fixtures_all_trip_expected_codes():
    problems = check_fixtures(concurrency.FIXTURES,
                              concurrency.lint_source)
    assert problems == []
    assert check_concurrency.main(["--fixtures"]) == 0


@pytest.mark.parametrize("name", sorted(concurrency.FIXTURES))
def test_each_fixture_fails_the_gate(name, capsys):
    """The per-fixture acceptance criterion: the gate exits nonzero
    on every seeded bug."""
    assert check_concurrency.main(["--fixture", name]) == 1
    err = capsys.readouterr().err
    assert concurrency.FIXTURES[name][1] in err


def test_unknown_fixture_is_an_error():
    assert check_concurrency.main(["--fixture", "no_such"]) == 2


def test_both_gates_share_report_shape(capsys):
    """Satellite invariant: lint_determinism and check_concurrency
    emit the same machine-readable report document."""
    assert check_concurrency.main(["--json"]) == 0
    conc = json.loads(capsys.readouterr().out)
    assert lint_determinism.main(["--json"]) == 0
    det = json.loads(capsys.readouterr().out)
    for doc, tool in ((conc, "check_concurrency"),
                      (det, "lint_determinism")):
        assert doc["tool"] == tool
        assert doc["ok"] is True
        assert doc["violations"] == []
        assert {"tool", "targets", "violations", "suppressed",
                "ok"} <= set(doc)


def test_allowlist_suppression_is_reviewed_not_silent(capsys):
    """Every allowlisted id must still exist in the raw analysis —
    a stale allowlist line means the finding was fixed and the
    entry should be deleted."""
    from pluss_sampler_optimization_tpu.analysis import lint_common

    allow = lint_common.read_allowlist(
        check_concurrency.ALLOWLIST_PATH)
    raw = {v.id for v in concurrency.analyze_files().violations}
    assert allow  # the cli signal-handler entry is reviewed-in
    assert allow <= raw, sorted(allow - raw)


# -- the runtime witness ----------------------------------------------


@pytest.fixture
def witness():
    lockwitness.reset()
    lockwitness.enable()
    yield lockwitness
    lockwitness.disable()
    lockwitness.reset()


def test_factories_return_plain_primitives_when_disabled():
    assert not lockwitness.enabled()
    lk = lockwitness.make_lock("T._plain")
    assert type(lk) is type(threading.Lock())
    cv = lockwitness.make_condition("T._plaincv")
    assert isinstance(cv, threading.Condition)
    # the wrapper-vs-plain decision is taken at creation time: a
    # lock minted while disabled stays unwitnessed after enable()
    lockwitness.enable()
    try:
        with lk:
            assert lockwitness.held_names() == ()
    finally:
        lockwitness.disable()
        lockwitness.reset()


def test_witness_records_edges_and_detects_inversion(witness):
    a = witness.make_lock("T._a")
    b = witness.make_lock("T._b")
    with a:
        assert witness.held_names() == ("T._a",)
        with b:
            assert witness.held_names() == ("T._a", "T._b")
    assert ("T._a", "T._b") in witness.observed_edges()
    assert witness.report()["inversion_count"] == 0
    with b:
        with a:  # reverse order: the inversion the witness exists for
            pass
    doc = witness.report()
    assert doc["inversion_count"] == 1
    assert ("T._b", "T._a") in witness.observed_edges()
    assert witness.held_names() == ()


def test_witness_flags_long_holds(witness):
    witness.enable(long_hold_s=0.01)
    lk = witness.make_lock("T._slow")
    with lk:
        time.sleep(0.05)
    doc = witness.report()
    assert doc["long_hold_count"] >= 1
    assert any(h["name"] == "T._slow" for h in doc["long_holds"])


def test_condition_wait_does_not_count_as_holding(witness):
    """wait() releases the underlying lock; the witness must unrecord
    for the wait window — otherwise every batch-scheduler idle wait
    would read as a long hold."""
    witness.enable(long_hold_s=0.1)
    cv = witness.make_condition("T._cv")
    with cv:
        cv.wait(timeout=0.3)  # 3x the long-hold bar, all waiting
        assert witness.held_names() == ("T._cv",)
    doc = witness.report()
    assert not any(h["name"] == "T._cv" for h in doc["long_holds"])


def test_witness_emit_report_fires_telemetry_events(witness):
    from pluss_sampler_optimization_tpu.runtime import telemetry

    a = witness.make_lock("E._a")
    b = witness.make_lock("E._b")
    with a:
        with b:
            pass
    with b:
        with a:
            pass
    tele = telemetry.enable()
    try:
        doc = witness.emit_report()
    finally:
        telemetry.disable()
    assert doc["inversion_count"] == 1
    assert tele.counters.get("lock_witness_inversions") == 1
    assert any(e["name"] == "lock_witness_inversion"
               for e in tele.events)


def test_witness_edges_cross_thread(witness):
    """Inversions between two threads (the real deadlock shape) are
    caught: T1 takes a->b, T2 takes b->a."""
    a = witness.make_lock("X._a")
    b = witness.make_lock("X._b")
    done = threading.Event()

    def t1():
        with a:
            with b:
                pass
        done.set()

    th = threading.Thread(target=t1)
    th.start()
    th.join(timeout=10)
    assert done.is_set()
    with b:
        with a:
            pass
    assert witness.report()["inversion_count"] == 1


def test_telemetry_emitted_outside_every_service_lock(
        witness, monkeypatch, tmp_path):
    """The satellite-1 regression pin: every telemetry sink call
    (count/gauge/event fans out to subsystems with their own locks)
    must fire with ZERO witnessed locks held. This is the deferred-
    emission contract the fixes in cache.py, executor.py,
    replicas.py, and recorder.py established — a relapse (emitting
    under `_lock`/`_cv` again) puts the source lock back on the held
    stack at sink time and fails here."""
    from pluss_sampler_optimization_tpu.runtime import (
        telemetry as tele_mod,
    )
    from pluss_sampler_optimization_tpu.runtime.obs import (
        recorder as obs_recorder,
    )
    from pluss_sampler_optimization_tpu.service import (
        AnalysisRequest,
        AnalysisService,
        ResultCache,
    )

    bad: list = []

    def _probe(fn):
        def wrapped(*a, **kw):
            held = lockwitness.held_names()
            if held:
                bad.append((fn.__name__, a and a[0], held))
            return fn(*a, **kw)
        return wrapped

    monkeypatch.setattr(tele_mod, "count", _probe(tele_mod.count))
    monkeypatch.setattr(tele_mod, "gauge", _probe(tele_mod.gauge))
    monkeypatch.setattr(tele_mod, "event", _probe(tele_mod.event))

    tele = tele_mod.enable()
    rec = obs_recorder.enable(str(tmp_path / "bundles"))
    try:
        # cache tier: mem hits, disk hits, puts, LRU evictions
        cache = ResultCache(cache_dir=str(tmp_path / "store"),
                            mem_entries=2)
        for i in range(5):
            cache.put(f"f{i:02d}" * 32, {"store_version": 1})
        cache.get("f00" * 32)
        # executor + replica pool + batcher under real threads; the
        # poisoned seed drives the replica failure-handling and the
        # anomaly -> recorder.trigger paths
        from pluss_sampler_optimization_tpu.service.executor import (
            default_runner,
        )

        def flaky_runner(engine, program, machine, request):
            if request.id == "e-bad":
                raise RuntimeError("seeded failure")
            return default_runner(engine, program, machine, request)

        reqs = [
            AnalysisRequest(model="gemm", n=16, engine="sampled",
                            ratio=0.2, seed=s, id=f"e-{s}")
            for s in (0, 1, 2)
        ]
        with AnalysisService(max_workers=2, replicas=2,
                             batch_window_ms=20.0,
                             runner=flaky_runner) as svc:
            tickets = [svc.submit(r) for r in reqs]
            resps = [svc.result(t, timeout=120) for t in tickets]
            # exact engine => not batchable => the custom runner (and
            # the replica failure path) actually runs it
            fail = svc.analyze(
                AnalysisRequest(model="gemm", n=16, engine="exact",
                                seed=3, id="e-bad"),
                timeout=120,
            )
        assert all(r.ok for r in resps)
        assert not fail.ok
        assert rec.stats()["records_seen"] > 0
    finally:
        obs_recorder.disable()
        tele_mod.disable()
    assert tele.counters.get("service_cache_evictions") == 3
    assert bad == [], bad
    assert witness.report()["inversion_count"] == 0


def test_static_graph_superset_of_witnessed_service_run(witness):
    """Soundness on the real system: serve a few requests through a
    witnessed AnalysisService; every runtime lock order must already
    be in the static analyzer's graph, with zero inversions."""
    from pluss_sampler_optimization_tpu.service import (
        AnalysisRequest,
        AnalysisService,
    )

    reqs = [
        AnalysisRequest(model="gemm", n=16, engine="sampled",
                        ratio=0.2, seed=s, id=f"w-{s}")
        for s in (0, 1)
    ]
    with AnalysisService(max_workers=2) as svc:
        resps = [svc.analyze(r, timeout=120) for r in reqs]
    assert all(r.ok for r in resps)
    static = set(concurrency.analyze_files().edge_pairs())
    assert witness.observed_edges() <= static
    assert witness.report()["inversion_count"] == 0
