"""CRI model unit tests: NBD spread, racetrack split, distribute."""

import math

import pytest

from pluss_sampler_optimization_tpu.config import MachineConfig
from pluss_sampler_optimization_tpu.runtime.cri import (
    R10Quirks,
    cri_distribute,
    nbd_spread,
    negative_binomial_pmf,
    noshare_distribute,
    racetrack,
)
from pluss_sampler_optimization_tpu.runtime.hist import (
    PRIState,
    hist_update,
    pow2_floor,
    share_classify,
)


def test_pow2_floor():
    assert pow2_floor(1) == 1
    assert pow2_floor(2) == 2
    assert pow2_floor(3) == 2
    assert pow2_floor(16513) == 16384
    assert pow2_floor(2**40 + 5) == 2**40


def test_hist_update_binning():
    h = {}
    hist_update(h, 514, 1.0)  # pow2 round-down
    hist_update(h, 512, 2.0)
    hist_update(h, -1, 3.0)  # negative keys bypass binning
    assert h == {512: 3.0, -1: 3.0}


def test_share_classify_gemm_thresholds():
    thr = (1 * 128 + 1) * 128 + 1  # 16513, ...ri-omp-seq.cpp:203
    assert thr == 16513
    assert not share_classify(514, thr)  # private B reuse
    assert share_classify(62194, thr)  # cross-c0 B reuse
    assert share_classify(16513, thr)
    assert not share_classify(8256, thr)  # below midpoint
    assert share_classify(8258, thr)


def test_nbd_pmf_against_direct_formula():
    # pmf(k; p, n) = C(n+k-1, k) p^n (1-p)^k for integer n
    p, n = 0.25, 5
    for k in range(0, 20):
        direct = math.comb(n + k - 1, k) * p**n * (1 - p) ** k
        assert negative_binomial_pmf(k, p, n) == pytest.approx(direct, rel=1e-12)


def test_nbd_spread_small_n():
    d = nbd_spread(4, 10, thread_num=4)
    assert min(d) == 10  # k=0 bin sits at n
    assert sum(d.values()) > 0.9999
    assert sum(d.values()) <= 1.0 + 1e-9


def test_nbd_spread_point_mass():
    # n >= 4000*(T-1)/T -> point mass at THREAD_NUM*n (pluss_utils.h:993-998)
    d = nbd_spread(4, 5000, thread_num=4)
    assert d == {20000: 1.0}
    # r10 variant bins the point mass (rs-ri-opt-r10.cpp:48-52)
    d = nbd_spread(4, 5000, thread_num=4, point_mass_pow2=True)
    assert d == {4 * 4096: 1.0}


def test_noshare_distribute_negative_passthrough():
    rih = {}
    noshare_distribute({-1: 7.0}, rih, 4, 4)
    assert rih == {-1: 7.0}


def test_noshare_distribute_single_thread_identity():
    rih = {}
    noshare_distribute({100: 2.0}, rih, 1, 4)
    assert rih == {64: 2.0}  # pow2-binned on insert into _RIHist


def test_racetrack_split_probabilities():
    # For ri'=8, n=3: P(2^{i-1} <= ri < 2^i) = (1-2^{i-1}/8)^3 - (1-2^i/8)^3
    state = PRIState(4)
    state.update_share(0, 3, 8, 1.0)
    rih = {}
    # thread_cnt=1 -> passthrough
    racetrack(state.merged_share(), rih, 1, 4)
    assert rih == {8: 1.0}
    # thread_cnt>1: NBD spread then split; use quirks to force the
    # degenerate point mass so the split input is deterministic (4*8=32)
    rih = {}
    racetrack(
        state.merged_share(), rih, 4, 4,
        quirks=R10Quirks(share_exponent_minus_one=False, share_nbd_degenerate=True),
        in_log_format=True,
    )
    n = 3.0
    ri = 32
    expected = {}
    probs = {}
    s = 0.0
    for i in range(1, 6):  # 2^5 = 32 <= 32
        probs[i] = (1 - 2 ** (i - 1) / ri) ** n - (1 - 2**i / ri) ** n
        s += probs[i]
    probs[5] = 1 - s  # reference's last-bin overwrite (pluss_utils.h:1088-1093)
    for i, p in probs.items():
        k = 2 ** (i - 1)
        expected[k] = expected.get(k, 0.0) + p
    assert set(rih) == set(expected)
    for k in expected:
        assert rih[k] == pytest.approx(expected[k], rel=1e-12)


def test_cri_distribute_mass_conservation():
    # Noshare mass is preserved up to the 0.9999 NBD cutoff. Share mass
    # is NOT: the reference's racetrack overwrites the last bin with
    # 1 - prob_sum where prob_sum already includes that bin
    # (pluss_utils.h:1088-1093), discarding the bin's own probability.
    state = PRIState(4)
    for t in range(4):
        state.update_noshare(t, 514, 10.0)
        state.update_noshare(t, -1, 3.0)
    rih = cri_distribute(state, 4, 4)
    assert sum(rih.values()) == pytest.approx(state.total_counts(), rel=2e-4)

    share_state = PRIState(4)
    for t in range(4):
        share_state.update_share(t, 3, 62194, 2.0)
    rih = cri_distribute(share_state, 4, 4)
    # NBD point mass at 4n = 248776; split bins i=1..17; reference keeps
    # 1 - sum(p_1..p_17) in bin 17 instead of p_17.
    ri, n = 4 * 62194, 3.0
    probs = [
        (1 - 2 ** (i - 1) / ri) ** n - (1 - 2**i / ri) ** n for i in range(1, 18)
    ]
    expected_total = sum(probs[:-1]) + (1 - sum(probs))
    assert sum(rih.values()) == pytest.approx(8.0 * expected_total, rel=1e-9)


def test_r10_degenerate_share_path():
    state = PRIState(4)
    state.update_share(0, 3, 62194, 1.0)
    rih = {}
    racetrack(state.merged_share(), rih, 4, 4, quirks=R10Quirks(),
              in_log_format=False)
    # point mass at 4*pow2_floor(62194) = 4*32768 = 131072 = 2^17, then
    # split with exponent n-1=2; last-bin overwrite discards p_17 = 0.25
    ri, e = 131072, 2.0
    probs = [
        (1 - 2 ** (i - 1) / ri) ** e - (1 - 2**i / ri) ** e for i in range(1, 18)
    ]
    expected_total = sum(probs[:-1]) + (1 - sum(probs))
    assert sum(rih.values()) == pytest.approx(expected_total, rel=1e-9)
    assert max(rih) <= 131072
