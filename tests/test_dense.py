"""TPU dense sampler vs numpy oracle: bit-exact histogram parity."""

import pytest

from pluss_sampler_optimization_tpu.config import MachineConfig
from pluss_sampler_optimization_tpu.models import (
    adi,
    atax,
    bicg,
    covariance,
    doitgen,
    fdtd2d,
    gemm,
    gemver,
    gesummv,
    heat3d,
    jacobi2d,
    mm2,
    mm3,
    mvt,
    syrk_rect,
    syrk_tri,
    trisolv,
    trmm,
)
from pluss_sampler_optimization_tpu.oracle import run_numpy
from pluss_sampler_optimization_tpu.sampler import run_dense

PROGRAMS = [
    gemm(8),
    gemm(13),
    gemm(16),
    gemm(32),
    mm2(8),
    mm3(6),
    syrk_rect(8),
    jacobi2d(10, tsteps=2),
    mvt(16),
    bicg(13, 17),
    gesummv(16),
    atax(13, 9),
    gemver(12),
    doitgen(3, 4, 8),
    fdtd2d(10, 9, tsteps=2),
    heat3d(9),
    syrk_tri(9),
    syrk_tri(13, 7),
    trmm(9),
    trmm(8, 11),
    trisolv(13),
    covariance(9, 7),
    adi(9, tsteps=2),
]


@pytest.mark.parametrize("program", PROGRAMS, ids=lambda p: p.name)
def test_dense_matches_numpy(program):
    machine = MachineConfig()
    ref = run_numpy(program, machine)
    got = run_dense(program, machine)
    assert got.total_accesses == ref.total_accesses
    assert got.per_tid_accesses == ref.per_tid_accesses
    for t in range(machine.thread_num):
        assert got.state.noshare[t] == ref.state.noshare[t], f"tid {t}"
        assert got.state.share[t] == ref.state.share[t], f"tid {t}"


def test_dense_gemm128_full_pipeline():
    """The PR1 reference config: GEMM full traversal at N=128."""
    machine = MachineConfig()
    program = gemm(128)
    ref = run_numpy(program, machine)
    got = run_dense(program, machine)
    assert got.total_accesses == 4 * 128**3 + 2 * 128**2
    for t in range(4):
        assert got.state.noshare[t] == ref.state.noshare[t]
        assert got.state.share[t] == ref.state.share[t]


def test_dense_triangular_odd_machine():
    """Triangular base tables under non-default thread/chunk geometry."""
    from pluss_sampler_optimization_tpu.models import syrk_tri, trmm

    for m in (MachineConfig(thread_num=3, chunk_size=5),
              MachineConfig(thread_num=5, chunk_size=2)):
        for prog in (syrk_tri(11), trmm(9, 7)):
            ref = run_numpy(prog, m)
            got = run_dense(prog, m)
            assert got.total_accesses == ref.total_accesses
            assert got.per_tid_accesses == ref.per_tid_accesses
            for t in range(m.thread_num):
                assert got.state.noshare[t] == ref.state.noshare[t]
                assert got.state.share[t] == ref.state.share[t]
