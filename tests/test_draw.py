"""Device-side sample drawing (sampler/draw.py): exactness, coverage,
determinism, and fallback routing."""

import numpy as np
import pytest

from pluss_sampler_optimization_tpu import MachineConfig, SamplerConfig
from pluss_sampler_optimization_tpu.core.trace import ProgramTrace
from pluss_sampler_optimization_tpu.models import gemm, syrk_tri
from pluss_sampler_optimization_tpu.runtime.aet import aet_mrc, mrc_l1_error
from pluss_sampler_optimization_tpu.runtime.cri import cri_distribute
from pluss_sampler_optimization_tpu.sampler import draw as D
from pluss_sampler_optimization_tpu.sampler.sampled import (
    _sample_highs,
    decode_sample_keys,
    run_sampled,
)

MACHINE = MachineConfig()


def _drawn_keys(nt, ri, cfg, seed, batch=1 << 14):
    out = D.draw_sample_keys_device(nt, ri, cfg, seed=seed, batch=batch)
    assert out is not None
    keys, chosen, s, _highs = out
    k = np.asarray(keys)[np.asarray(chosen)]
    return k, s


def test_rect_exact_count_distinct_in_range():
    trace = ProgramTrace(gemm(64), MACHINE)
    nt = trace.nests[0]
    cfg = SamplerConfig(ratio=0.2, seed=0)
    for ri in (0, 5):  # a 3-deep and the 2-deep C3 ref
        highs, s = _sample_highs(nt, ri, cfg)
        k, s_got = _drawn_keys(nt, ri, cfg, seed=ri)
        assert s_got == s
        assert len(k) == s
        assert len(np.unique(k)) == s  # distinct
        space = int(np.prod(highs))
        assert (k >= 0).all() and (k < space).all()


def test_tri_draw_respects_bounds():
    trace = ProgramTrace(syrk_tri(48), MACHINE)
    # find a tri nest/ref with depth >= 2
    for nt in trace.nests:
        if nt.tri and int(nt.tables.ref_levels[0]) >= 1:
            break
    else:
        pytest.skip("no tri nest")
    cfg = SamplerConfig(ratio=0.3, seed=1)
    highs, s = _sample_highs(nt, 0, cfg)
    k, s_got = _drawn_keys(nt, 0, cfg, seed=3)
    assert s_got == s and len(k) == s == len(np.unique(k))
    cols = np.asarray(decode_sample_keys(k, tuple(highs)))
    lv = int(nt.tables.ref_levels[0])
    v0 = nt.nest.loops[0].start + cols[:, 0] * nt.nest.loops[0].step
    excl = 1
    for l in range(1, lv + 1):
        assert (cols[:, l] < nt.nest.loops[l].trip_at(v0) - excl).all()


def test_deterministic_and_seed_sensitive():
    trace = ProgramTrace(gemm(32), MACHINE)
    nt = trace.nests[0]
    cfg = SamplerConfig(ratio=0.3, seed=0)
    a, _ = _drawn_keys(nt, 0, cfg, seed=42)
    b, _ = _drawn_keys(nt, 0, cfg, seed=42)
    c, _ = _drawn_keys(nt, 0, cfg, seed=43)
    assert (a == b).all()
    assert len(a) == len(c) and (np.sort(a) != np.sort(c)).any()


def test_over_budget_falls_back_to_host(monkeypatch):
    """A ref whose buffer exceeds the device budget routes to the host
    numpy draw inside sampled_outputs and still produces results."""
    monkeypatch.setattr(D, "DEVICE_DRAW_MAX_SLOTS", 1 << 10)
    machine = MACHINE
    # device_draw=True explicitly: the None default resolves to the
    # host path on CPU runners and would skip the routing under test
    cfg = SamplerConfig(ratio=0.3, seed=2, device_draw=True)
    assert D.plan_draw(
        ProgramTrace(gemm(64), machine).nests[0], 0, cfg, 1 << 14
    ) is None
    state, results = run_sampled(gemm(64), machine, cfg)
    assert sum(r.n_samples for r in results) > 0


def test_bias_bound_routes_huge_spaces_to_host():
    """plan_draw declines boxes at/above _DEVICE_DRAW_MAX_SPACE (2^46):
    randint's modulo bias there would exceed the documented 2^-18
    relative bound, so those refs take the unbiased host numpy draw."""
    from pluss_sampler_optimization_tpu.models import gemm as gemm_model

    cfg = SamplerConfig(ratio=1e-9, seed=0, device_draw=True)

    def deep_ref(nt):
        for j in range(nt.tables.n_refs):
            if int(nt.tables.ref_levels[j]) == 2:
                return j
        raise AssertionError("no depth-3 ref")

    # N=65536 depth-3 refs: box ~ (N-1)^3 ~ 2^48 >= 2^46 -> declined
    nt = ProgramTrace(gemm_model(65536), MACHINE).nests[0]
    assert D.plan_draw(nt, deep_ref(nt), cfg, 1 << 14) is None
    # well under the cap: the plan stands
    nt_small = ProgramTrace(gemm_model(256), MACHINE).nests[0]
    assert D.plan_draw(
        nt_small, deep_ref(nt_small), cfg, 1 << 14
    ) is not None


def test_device_and_host_paths_agree_statistically():
    """Same config, both draw paths: MRCs agree to sampling noise."""
    machine = MACHINE
    prog = gemm(64)
    mrcs = []
    for dev in (True, False):
        cfg = SamplerConfig(ratio=0.4, seed=9, device_draw=dev)
        state, results = run_sampled(prog, machine, cfg)
        T = machine.thread_num
        mrcs.append(aet_mrc(cri_distribute(state, T, T), machine))
    assert mrc_l1_error(mrcs[0], mrcs[1]) < 0.05
    # and the sample counts are identical: s is draw-path independent


def test_masked_kernel_matches_prefix_kernel():
    """The two per-ref kernel forms — valid-prefix (host draw) and
    selection-mask (device draw) — must produce identical packed
    pairs and cold counts for the same sample set."""
    import jax.numpy as jnp

    from pluss_sampler_optimization_tpu.sampler.sampled import (
        _build_ref_kernel,
        _build_ref_kernel_masked,
        _pad_highs,
        pad_keys,
    )

    trace = ProgramTrace(gemm(48), MACHINE)
    nt = trace.nests[0]
    cfg = SamplerConfig(ratio=0.3, seed=5)
    for ri in (0, 5):
        out = D.draw_sample_keys_device(nt, ri, cfg, seed=ri, batch=1 << 12)
        assert out is not None
        keys, chosen, _s, highs = out
        # masked form: the buffer exactly as the device path feeds it
        km = _build_ref_kernel_masked(nt, ri)
        mk, mc, mu, mcold = km(
            keys, chosen, _pad_highs(highs), nt.vals, np.int64(ri), 64
        )
        # prefix form: compact the chosen keys, pad like the host path
        compact = np.asarray(keys)[np.asarray(chosen)]
        chunk, n_valid = pad_keys(compact, 1)
        kp = _build_ref_kernel(nt, ri)
        pk, pc, pu, pcold = kp(
            jnp.asarray(chunk), n_valid, _pad_highs(highs), nt.vals,
            np.int64(ri), 64
        )

        def pairs(k, c):
            k, c = np.asarray(k), np.asarray(c)
            return sorted((int(a), int(b)) for a, b in zip(k, c) if b > 0)

        assert pairs(mk, mc) == pairs(pk, pc)
        assert int(mu) == int(pu)
        assert int(mcold) == int(pcold)


def test_scan_capacity_regrow_device_draw():
    """A deliberately tiny starting capacity must regrow (the scan
    kernel reports max per-chunk/merged unique counts; the drain loop
    recompiles larger) and converge to results identical to a
    roomy-capacity run."""
    cfg = SamplerConfig(ratio=0.4, seed=2, device_draw=True)
    state_small, res_small = run_sampled(gemm(16), MACHINE, cfg, capacity=2)
    state_big, res_big = run_sampled(gemm(16), MACHINE, cfg)
    for a, b in zip(res_small, res_big):
        assert a.name == b.name
        assert a.noshare == b.noshare
        assert a.share == b.share
        assert a.cold == b.cold and a.n_samples == b.n_samples


def test_bucket_draw_matches_per_ref_draw():
    """The vmapped bucket draw (ISSUE 6 fused dispatch) is the twin of
    the per-ref device draw: for every member of a multi-ref bucket,
    draw_bucket_keys_device must return the SAME sorted-key buffer and
    selection mask, bit for bit, as draw_sample_keys_device with the
    same seed — same threefry fold sequence, just stacked rows."""
    from pluss_sampler_optimization_tpu.sampler import sampled as S

    trace = ProgramTrace(gemm(32), MACHINE)
    nt = trace.nests[0]
    cfg = SamplerConfig(ratio=0.3, seed=7, device_draw=True)
    by_sig = {}
    for ri in range(nt.tables.n_refs):
        by_sig.setdefault(S._kernel_sig(nt, ri), []).append(ri)
    buckets = [m for m in by_sig.values() if len(m) >= 2]
    assert buckets, "gemm must have at least one multi-ref bucket"
    batch = 1 << 12
    for members in buckets:
        seeds = [cfg.seed * 1000003 + ri for ri in members]
        out = D.draw_bucket_keys_device(nt, members, cfg, seeds, batch)
        assert out is not None and len(out) == len(members)
        for (ri, sd), got in zip(zip(members, seeds), out):
            assert got is not None
            ref = D.draw_sample_keys_device(
                nt, ri, cfg, seed=sd, batch=batch
            )
            assert ref is not None
            assert np.array_equal(np.asarray(got[0]), np.asarray(ref[0]))
            assert np.array_equal(np.asarray(got[1]), np.asarray(ref[1]))
            assert got[2] == ref[2]
            assert tuple(got[3]) == tuple(ref[3])
    # a singleton "bucket" routes straight to the per-ref path
    solo = [m for m in by_sig.values() if len(m) == 1]
    if solo:
        ri = solo[0][0]
        out = D.draw_bucket_keys_device(
            nt, [ri], cfg, [cfg.seed * 1000003 + ri], batch
        )
        ref = D.draw_sample_keys_device(
            nt, ri, cfg, seed=cfg.seed * 1000003 + ri, batch=batch
        )
        assert (out is None) == (ref is None)
        if out is not None:
            assert np.array_equal(
                np.asarray(out[0][0]), np.asarray(ref[0])
            )
