"""Multi-process serving fabric (ISSUE 18): wire framing, the
consistent-hash ring, the router/worker pair, and the CLI supervisor.

The acceptance invariants pinned here:

- BIT-IDENTITY: the same mixed request set (solo fingerprints, a
  byte-different duplicate, an inline custom program) served by
  serve_jsonl directly, by a 1-worker fabric, and by a 3-worker
  fabric yields identical (ok, fingerprint, mrc_digest, engine_used)
  per id — cold cache and warm cache, batched stream and
  one-at-a-time solo submits. Sharding is invisible in the bytes.
- The wire layer enforces the frame cap BEFORE materializing hostile
  payloads, distinguishes clean EOF from mid-frame EOF, and refuses
  malformed frames with typed errors.
- The ring is a pure function of the worker-id set: restart-stable,
  order-independent, minimal movement on membership change, and
  dead-worker failover follows the preference order.
- Router edges: an oversized line is refused AT the router with the
  serve protocol's 1 MiB budget and best-effort id echo; a malformed
  line still produces exactly one structured error response; a
  handshake version mismatch is a structured `error` frame.
- A real `serve-router --workers 2` fabric under SIGTERM drains:
  exit 0, responses answered, and a final flight-recorder bundle per
  process (router + each worker).
- tools/check_fabric.py (subprocess supervisor, 1-vs-2-worker digest
  identity, restart-stable sharding, worker-kill re-dispatch, zero
  orphans, fleet telemetry) passes from tier-1.
- TRACING (ISSUE 19): a client-supplied trace_id propagates over the
  wire into the worker's own ledger row; the router writes one span
  row per request; fleet stats equal the sum of the workers' own
  counters; runtime/obs/fleet.py assembles one Chrome trace per
  request from ledger rows alone; and MRC bytes are bit-identical
  with tracing on vs off.
"""

import glob
import io
import json
import os
import signal
import socket
import struct
import subprocess
import sys
import time

import pytest

from pluss_sampler_optimization_tpu.config import FabricConfig
from pluss_sampler_optimization_tpu.frontend import program_to_json
from pluss_sampler_optimization_tpu.models import build
from pluss_sampler_optimization_tpu.service import (
    AnalysisService,
    serve_jsonl,
)
from pluss_sampler_optimization_tpu.service.fabric import (
    HashRing,
    Router,
    WorkerServer,
    wire,
)

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, os.path.join(REPO_ROOT, "tools"))

TIMEOUT_S = 300.0

# test-speed fabric: fast heartbeats, quick bounded reconnect
_CFG = FabricConfig(hb_interval_s=0.2, hb_timeout_s=5.0,
                    reconnect_attempts=2, reconnect_delay_s=0.1,
                    connect_timeout_s=10.0, drain_timeout_s=60.0)


# -- wire framing ------------------------------------------------------


def _pair():
    a, b = socket.socketpair()
    return wire.Conn(a), wire.Conn(b)


def test_wire_roundtrip_and_eof_semantics():
    ca, cb = _pair()
    ca.send({"type": "ping", "t": 7})
    ca.send({"type": "request", "seq": 1, "line": "x" * 2048})
    assert cb.recv(timeout=5) == {"type": "ping", "t": 7}
    assert cb.recv(timeout=5)["seq"] == 1
    # clean EOF between frames is None, not an exception
    ca.close()
    assert cb.recv(timeout=5) is None
    cb.close()


def test_wire_refuses_oversized_and_malformed_frames():
    ca, cb = _pair()
    with pytest.raises(wire.FrameTooLarge):
        ca.send({"pad": "x" * (wire.MAX_FRAME_BYTES + 16)})
    # an announced length over the cap is refused BEFORE the body is
    # read — the receiver never allocates for it
    ca._sock.sendall(struct.pack(">I", wire.MAX_FRAME_BYTES + 1))
    with pytest.raises(wire.FrameTooLarge):
        cb.recv(timeout=5)
    ca.close()
    cb.close()

    ca, cb = _pair()
    ca._sock.sendall(struct.pack(">I", 8) + b"not-json")
    with pytest.raises(wire.WireError):
        cb.recv(timeout=5)
    ca.close()
    cb.close()

    # EOF inside a frame is a ConnectionClosed, not a silent None
    ca, cb = _pair()
    ca._sock.sendall(struct.pack(">I", 64) + b"partial")
    ca.close()
    with pytest.raises(wire.ConnectionClosed):
        cb.recv(timeout=5)
    cb.close()


def test_parse_hostport():
    assert wire.parse_hostport("10.0.0.2:80") == ("10.0.0.2", 80)
    assert wire.parse_hostport(":9100") == ("127.0.0.1", 9100)
    for bad in ("nope", "host:", "host:abc"):
        with pytest.raises(ValueError):
            wire.parse_hostport(bad)


# -- the consistent-hash ring ------------------------------------------


def test_ring_pure_function_of_id_set():
    fps = [f"fp-{i:04d}" for i in range(256)]
    r = HashRing([0, 1, 2])
    again = HashRing((2, 0, 1))  # order/type must not matter
    assert [r.assign(f) for f in fps] == [again.assign(f) for f in fps]
    # all workers actually used, preference lists are distinct ids
    owners = {r.assign(f) for f in fps}
    assert owners == {0, 1, 2}
    pref = r.preference(fps[0])
    assert sorted(pref) == [0, 1, 2]
    assert pref[0] == r.assign(fps[0])


def test_ring_minimal_movement_and_failover():
    fps = [f"fp-{i:04d}" for i in range(256)]
    r3 = HashRing([0, 1, 2])
    r2 = HashRing([0, 2])
    for f in fps:
        primary = r3.assign(f)
        if primary != 1:
            # fingerprints not on the removed worker must not move
            assert r2.assign(f) == primary
        # dead-worker failover equals the shrunken ring's assignment
        assert r3.assign(f, alive={0, 2}) == r2.assign(f)
    with pytest.raises(LookupError):
        r3.assign(fps[0], alive=set())


# -- in-process fabric helpers -----------------------------------------


def _mixed_lines() -> list[str]:
    """3 solo fingerprints + a byte-different duplicate of fb-0 + an
    inline custom program that is fb-0's structural twin."""
    base = {"model": "gemm", "n": 16, "engine": "sampled",
            "ratio": 0.2}
    lines = [
        json.dumps({**base, "seed": 7100 + k, "threads": 2 + (k % 3),
                    "id": f"fb-{k}"})
        for k in range(3)
    ]
    lines.append(json.dumps({**base, "seed": 7100, "threads": 2,
                             "id": "fb-dup"}))
    lines.append(json.dumps({
        "id": "fb-custom",
        "program": program_to_json(build("gemm", 16)),
        "engine": "sampled", "ratio": 0.2, "seed": 7100, "threads": 2,
    }))
    return lines


def _run_fabric(n_workers: int, cache_dir, lines,
                solo: bool = False, cfg: FabricConfig = _CFG,
                ledger: str | None = None,
                probe: dict | None = None) -> dict:
    """Serve `lines` through an in-process router over n real worker
    stacks; returns {id: response doc}. solo=True submits one line at
    a time (each awaited before the next), the anti-batch. `ledger`
    gives every worker AND the router the same ledger file; `probe`
    is filled with live fleet telemetry (polled over `stats` wire
    frames) before the router closes."""
    services = [
        AnalysisService(cache_dir=str(cache_dir), max_workers=2,
                        ledger_path=ledger, worker_id=i)
        for i in range(n_workers)
    ]
    workers = []
    try:
        for i, svc in enumerate(services):
            ws = WorkerServer(svc, worker_id=i, fabric=cfg)
            ws.start()
            workers.append(ws)
        router = Router([ws.address for ws in workers], cfg,
                        ledger_path=ledger)
        router.start()
        try:
            if solo:
                docs = []
                for no, ln in enumerate(lines, start=1):
                    entry = router.submit_line(ln, no)
                    doc = entry.wait(timeout=TIMEOUT_S)
                    assert doc is not None
                    docs.append(doc)
            else:
                fout = io.StringIO()
                router.serve_stream(
                    io.StringIO("\n".join(lines) + "\n"), fout
                )
                docs = [json.loads(ln)
                        for ln in fout.getvalue().splitlines()]
            if probe is not None:
                probe["stats"] = router.fleet_stats(refresh=True)
                probe["prometheus"] = router.fleet_prometheus_text()
        finally:
            router.close(graceful=True)
    finally:
        for ws in workers:
            ws.close()
        for svc in services:
            svc.close()
    assert len(docs) == len(lines)
    return {d["id"]: d for d in docs}


def _sig(doc: dict) -> tuple:
    return (doc.get("ok"), doc.get("fingerprint"),
            doc.get("mrc_digest"), doc.get("engine_used"))


# -- the tentpole invariant --------------------------------------------

# the client-supplied trace id pinned on fb-1 (ISSUE 19): it must
# ride the wire into the worker's own ledger row
TRACE_PIN = "cafe" * 4


@pytest.fixture(scope="module")
def fabric3_cold(tmp_path_factory):
    """ONE cold 3-worker ledger-backed fabric run shared by the
    bit-identity tentpole and the tracing/fleet tests (a fabric spin
    costs seconds; the invariants they pin are independent reads of
    the same run). Tracing is on (the default) and fb-1 carries a
    client-supplied trace_id."""
    tmp = tmp_path_factory.mktemp("fabric3")
    lines = []
    for ln in _mixed_lines():
        d = json.loads(ln)
        if d["id"] == "fb-1":
            d["trace_id"] = TRACE_PIN
        lines.append(json.dumps(d))
    ledger = str(tmp / "ledger.jsonl")
    probe: dict = {}
    docs = _run_fabric(3, tmp / "store", lines, ledger=ledger,
                       probe=probe)
    return {"lines": lines, "store": tmp / "store",
            "ledger": ledger, "docs": docs, "probe": probe}


def test_bit_identity_1_vs_3_workers_cold_warm_solo_batched(
        tmp_path, fabric3_cold):
    """Same bytes no matter the topology: serve_jsonl directly vs a
    1-worker fabric vs a 3-worker fabric, cold and warm, batched
    stream and solo submits — identical (ok, fingerprint, mrc_digest,
    engine_used) per id, and the duplicate/custom twins coalesce onto
    fb-0's fingerprint through the fabric exactly as in-process.
    The solo warm run additionally disables fabric tracing
    (FabricConfig.trace_enabled=False): trace context is serving
    metadata on the frame, never part of the forwarded line, the
    fingerprint, or the result — so tracing on vs off changes no
    bytes either."""
    import dataclasses

    lines = fabric3_cold["lines"]
    with AnalysisService(cache_dir=str(tmp_path / "direct"),
                         max_workers=2) as svc:
        fout = io.StringIO()
        serve_jsonl(svc, io.StringIO("\n".join(lines) + "\n"), fout)
    direct = {d["id"]: d for d in
              (json.loads(ln) for ln in fout.getvalue().splitlines())}
    assert all(d["ok"] for d in direct.values())
    want = {i: _sig(d) for i, d in direct.items()}
    # the twins really are twins — the fabric must keep them together
    assert direct["fb-dup"]["fingerprint"] \
        == direct["fb-custom"]["fingerprint"] \
        == direct["fb-0"]["fingerprint"]

    one = _run_fabric(1, tmp_path / "f1", lines)
    three = fabric3_cold["docs"]
    store = fabric3_cold["store"]
    warm_batched = _run_fabric(3, store, lines)
    warm_solo = _run_fabric(
        3, store, lines, solo=True,
        cfg=dataclasses.replace(_CFG, trace_enabled=False))

    for tag, docs in (("1w-cold", one), ("3w-cold", three),
                      ("3w-warm", warm_batched),
                      ("3w-warm-solo-notrace", warm_solo)):
        assert {i: _sig(d) for i, d in docs.items()} == want, tag
        assert all("worker_id" in d for d in docs.values()), tag
    # warm runs on the shared disk tier: fresh processes, zero misses
    for docs in (warm_batched, warm_solo):
        assert all(d["cache"] != "miss" for d in docs.values())
    # 3 workers: affinity keeps equal fingerprints on one worker
    by_fp = {}
    for d in three.values():
        by_fp.setdefault(d["fingerprint"], set()).add(d["worker_id"])
    assert all(len(ws) == 1 for ws in by_fp.values())


# -- router edge cases -------------------------------------------------


def test_router_oversized_and_malformed_lines(tmp_path):
    from pluss_sampler_optimization_tpu.service import api

    with AnalysisService(cache_dir=str(tmp_path / "c"),
                         max_workers=2) as svc:
        ws = WorkerServer(svc, worker_id=0, fabric=_CFG)
        ws.start()
        router = Router([ws.address], _CFG)
        router.start()
        try:
            # oversized: refused AT the router, id echoed, never sent
            big = ('{"id": "big-id", "model": "gemm", "pad": "'
                   + "x" * (api.MAX_REQUEST_LINE_BYTES + 64) + '"}')
            doc = router.submit_line(big, 1).wait(timeout=30)
            assert doc is not None and not doc["ok"]
            assert doc["id"] == "big-id"
            assert str(api.MAX_REQUEST_LINE_BYTES) in doc["error"]
            assert router.counters["routed"] == 0

            # malformed JSON: routed by content digest, answered with
            # exactly one structured error (id stays None — the
            # serve_jsonl contract for unparseable lines, mirrored
            # byte-for-byte by the worker)
            doc = router.submit_line(
                '{"id": "mal", "model": ', 2
            ).wait(timeout=60)
            assert doc is not None and not doc["ok"]
            assert doc["id"] is None
            assert "invalid JSON" in doc["error"]

            # unknown request field: the worker's serve path answers
            doc = router.submit_line(
                '{"id": "uf", "model": "gemm", "bogus": 1}', 3
            ).wait(timeout=60)
            assert doc is not None and not doc["ok"]
            assert doc["id"] == "uf" and "bogus" in doc["error"]
        finally:
            router.close(graceful=True)
            ws.close()


def test_worker_rejects_handshake_version_mismatch(tmp_path):
    with AnalysisService(cache_dir=None, max_workers=1) as svc:
        ws = WorkerServer(svc, worker_id=0, fabric=_CFG)
        host, port = ws.start()
        try:
            conn = wire.connect(host, port, timeout=5)
            conn.send({"type": "hello", "wire_version": 99})
            reply = conn.recv(timeout=10)
            assert reply["type"] == "error"
            assert "wire version mismatch" in reply["error"]
            assert reply["wire_version"] == wire.WIRE_VERSION
            # and the connection is closed — no half-agreed protocol
            try:
                assert conn.recv(timeout=10) is None
            except wire.ConnectionClosed:
                pass
            conn.close()
            assert ws.stats_counters["handshake_rejected"] == 1
        finally:
            ws.close()


# -- TCP front + loadgen -----------------------------------------------


def test_tcp_front_loadgen_connect_and_hostile_lines(tmp_path):
    """The router's JSONL TCP front: loadgen --connect machinery gets
    every response back bit-matched by id, and hostile client lines
    (malformed, oversized) are answered in-stream without killing the
    connection."""
    import loadgen

    with AnalysisService(cache_dir=str(tmp_path / "c"),
                         max_workers=2) as svc:
        ws = WorkerServer(svc, worker_id=0, fabric=_CFG)
        ws.start()
        router = Router([ws.address], _CFG)
        router.start()
        host, port = router.serve_tcp("127.0.0.1", 0)
        try:
            reqs = loadgen.make_requests(3, seed=5)
            offs = loadgen.arrival_offsets(3, 200.0, seed=5)
            report = loadgen.connect_run(f"{host}:{port}", reqs, offs,
                                         timeout_s=TIMEOUT_S)
            assert report["submitted"] == 3 and report["ok"] == 3
            assert report["failed"] == 0 and report["missing"] == 0
            assert report["latency_p95_s"] is not None

            sock = socket.create_connection((host, port), timeout=10)
            rf = sock.makefile("r", encoding="utf-8")
            wf = sock.makefile("w", encoding="utf-8")
            wf.write('{"id": "bad-json", "model": \n')
            wf.write(json.dumps(
                {"id": "hz", "type": "healthz"}) + "\n")
            wf.flush()
            got = [json.loads(rf.readline()) for _ in range(2)]
            # the malformed line answers with id None (the serve
            # protocol's unparseable-line contract), in-stream
            bad = [d for d in got if not d.get("ok")]
            assert len(bad) == 1 and bad[0]["id"] is None
            assert "invalid JSON" in bad[0]["error"]
            hz = [d for d in got if d.get("id") == "hz"][0]
            assert hz["ok"] and hz["healthz"]["role"] == "router"
            sock.close()
        finally:
            router.close(graceful=True)
            ws.close()


# -- whole-fabric SIGTERM drain (subprocess) ---------------------------


def test_fabric_sigterm_drain_subprocess(tmp_path):
    """A real supervisor fabric under SIGTERM: the router stops
    accepting, the workers drain, every process writes its final
    flight-recorder bundle, and the tree exits 0 with no orphans."""
    env = {**os.environ, "JAX_PLATFORMS": "cpu"}
    env.pop("XLA_FLAGS", None)
    bundles = str(tmp_path / "bundles")
    err_path = str(tmp_path / "router.err")
    cmd = [
        sys.executable, "-m", "pluss_sampler_optimization_tpu.cli",
        "serve-router", "--workers", "2", "--listen", "127.0.0.1:0",
        "--cache-dir", str(tmp_path / "store"),
        "--ledger", str(tmp_path / "ledger.jsonl"),
        "--debug-bundle-dir", bundles,
        "--compilation-cache-dir",
        os.path.join(REPO_ROOT, ".jax_cache", "tests"),
    ]
    with open(err_path, "w") as errf:
        proc = subprocess.Popen(cmd, cwd=REPO_ROOT, env=env,
                                stdout=subprocess.DEVNULL,
                                stderr=errf, text=True)
    try:
        addr = None
        deadline = time.time() + TIMEOUT_S
        while time.time() < deadline:
            text = open(err_path).read()
            if "JSONL TCP front on " in text:
                spec = text.split("JSONL TCP front on ", 1)[1]
                addr = wire.parse_hostport(spec.splitlines()[0])
                break
            assert proc.poll() is None, f"router died: {text[-800:]}"
            time.sleep(0.25)
        assert addr is not None, "fabric never opened its TCP front"

        sock = socket.create_connection(addr, timeout=30)
        rf = sock.makefile("r", encoding="utf-8")
        wf = sock.makefile("w", encoding="utf-8")
        wf.write(json.dumps({"id": "st-1", "model": "gemm", "n": 16,
                             "engine": "oracle"}) + "\n")
        wf.flush()
        doc = json.loads(rf.readline())
        assert doc["id"] == "st-1" and doc["ok"]
        sock.close()

        proc.send_signal(signal.SIGTERM)
        assert proc.wait(timeout=120) == 0
    finally:
        if proc.poll() is None:
            proc.kill()
            proc.wait(timeout=10)
    err = open(err_path).read()
    assert "graceful shutdown" in err
    # one final flight-recorder bundle per PROCESS: the router's in
    # the root bundle dir, each worker's under worker{i}/
    assert glob.glob(os.path.join(bundles, "BUNDLE_*_shutdown.json"))
    for wid in (0, 1):
        got = glob.glob(os.path.join(bundles, f"worker{wid}",
                                     "BUNDLE_*_shutdown.json"))
        assert got, f"worker {wid} wrote no shutdown bundle " \
            f"({err[-500:]})"


# -- fabric-wide tracing & fleet telemetry (ISSUE 19) ------------------


def test_trace_propagation_fleet_stats_and_assembly(fabric3_cold):
    """Reads of the shared 3-worker ledger-backed run: a
    client-supplied trace_id rides the request line through the
    router INTO the worker's own ledger row; the router writes one
    span row per request (no top-level worker_id); fleet stats polled
    over `stats` wire frames sum to the workers' own counters; and
    runtime/obs/fleet.py assembles one Chrome trace per request from
    the ledger rows alone."""
    import check_ledger

    from pluss_sampler_optimization_tpu.runtime.obs import (
        fleet as obs_fleet,
        ledger as obs_ledger,
    )

    lines = fabric3_cold["lines"]
    docs = fabric3_cold["docs"]
    assert all(d["ok"] for d in docs.values())
    # worker-side stage timings ride the response — the loadgen
    # --connect overhead split feeds on execute_s
    assert all(d.get("execute_s") is not None for d in docs.values())

    # fleet stats polled over `stats` frames while the router was
    # live: fleet == sum(workers), per-INSTANCE executor counters
    # (the shared in-process registry can't tell workers apart; the
    # subprocess check_fabric fleet phase covers registry merging)
    fs = fabric3_cold["probe"]["stats"]
    assert fs["role"] == "router"
    assert fs["fleet"]["workers"] == 3
    assert len(fs["worker_stats"]) == 3
    per = [w["executor"]["submitted"]
           for w in fs["worker_stats"].values()]
    assert fs["fleet"]["executor"]["submitted"] == sum(per)
    assert sum(per) == len(lines)
    # the merged Prometheus plane names the fabric gauges
    assert "pluss_fabric_workers_up 3" \
        in fabric3_cold["probe"]["prometheus"]

    rows = obs_ledger.read_rows(fabric3_cold["ledger"])
    router_rows = [r for r in rows
                   if r.get("source") == obs_ledger.ROUTER_SOURCE]
    worker_rows = [r for r in rows
                   if r.get("kind") == "request"
                   and r.get("worker_id") is not None]
    assert len(router_rows) == len(lines)
    # router span rows never carry a top-level worker_id — sharding
    # attribution lives in the nested `router` block
    assert all("worker_id" not in r for r in router_rows)
    assert all(r["router"]["worker_id"] in (0, 1, 2)
               for r in router_rows)
    # every worker request row joins a router trace (the
    # check_ledger gate's trace-join validation agrees)
    assert check_ledger.check_trace_join(rows) == []
    # the client-supplied trace id survived the whole wire path
    assert any(r["trace_id"] == TRACE_PIN for r in router_rows)
    assert any(r.get("trace_id") == TRACE_PIN for r in worker_rows)

    # one Chrome trace per request, from the ledger rows alone
    traces = obs_fleet.assemble_traces(rows)
    assert set(traces) == {r["trace_id"] for r in router_rows}
    doc = traces[TRACE_PIN]
    names = [ev["name"] for ev in doc["traceEvents"]]
    assert names[:2] == ["process_name", "process_name"]
    assert names[2:8] == ["request", "router_queue", "route",
                          "worker_rtt", "wire_out", "wire_back"]
    assert "worker" in names and "execute" in names
    # the worker span sits INSIDE the router's RTT window (the wire
    # split places it; 5 us of slack absorbs 6-dp rounding)
    by = {ev["name"]: ev for ev in doc["traceEvents"]
          if ev.get("ph") == "X"}
    rtt = by["worker_rtt"]
    wk = by["worker"]
    assert rtt["ts"] <= wk["ts"] + 5.0
    assert wk["ts"] + wk["dur"] <= rtt["ts"] + rtt["dur"] + 5.0


def test_assemble_chrome_trace_golden():
    """Pinned layout: given fixed span values, the assembled Chrome
    trace is byte-deterministic and every event lands exactly where
    the monotonic-delta arithmetic puts it (t=0 at router submit, the
    worker track at queue+route+wire_out)."""
    from pluss_sampler_optimization_tpu.runtime.obs import (
        fleet as obs_fleet,
    )

    router_row = {
        "trace_id": "feedface00000001", "span_id": "r1",
        "fingerprint": "fp", "model": "gemm",
        "engine_requested": "sampled", "ok": True, "cache": "miss",
        "latency_s": 0.01, "source": "fabric.router",
        "router": {"worker_id": 1, "hops": 1,
                   "router_queue_s": 0.001, "route_s": 0.0005,
                   "worker_rtt_s": 0.008, "worker_s": 0.006,
                   "wire_s": 0.002, "wire_out_s": 0.001,
                   "wire_back_s": 0.001},
    }
    worker_row = {"worker_id": 1, "span_id": "w1", "cache": "miss",
                  "latency_s": 0.006, "queue_s": 0.001,
                  "execute_s": 0.005}
    doc = obs_fleet.assemble_chrome_trace(router_row, [worker_row])
    spans = [(e["name"], e["ts"], e["dur"])
             for e in doc["traceEvents"] if e.get("ph") == "X"]
    assert spans == [
        ("request", 0.0, 10000.0),
        ("router_queue", 0.0, 1000.0),
        ("route", 1000.0, 500.0),
        ("worker_rtt", 1500.0, 8000.0),
        ("wire_out", 1500.0, 1000.0),
        ("wire_back", 8500.0, 1000.0),
        ("worker", 2500.0, 6000.0),
        ("queue", 2500.0, 1000.0),
        ("execute", 3500.0, 5000.0),
    ]
    text = obs_fleet.trace_text(doc)
    assert text == obs_fleet.trace_text(
        obs_fleet.assemble_chrome_trace(router_row, [worker_row]))
    assert json.loads(text)["otherData"]["trace_id"] \
        == "feedface00000001"


# -- progressive partial streaming (ISSUE 20) --------------------------


def _prog_line(i: int) -> str:
    return json.dumps({
        "id": f"pp-{i}", "model": "gemm", "n": 16, "engine": "sampled",
        "ratio": 0.2, "seed": 7600 + i, "tolerance": 0.0,
        "max_rounds": 3,
    })


def test_fabric_streams_partials_interleaved_and_failover():
    """Progressive requests through a 2-worker fabric: every `partial`
    frame forwards with the owning request's id, per id the round
    indices arrive strictly in submission order 1..N even when the
    requests interleave across workers, the final digest matches a
    direct serve_jsonl run of the same line, and after a worker dies
    (bounded reconnect -> re-dispatch) partials still stream with the
    right id from the surviving worker."""
    import threading

    services = [
        AnalysisService(cache_dir=None, max_workers=2, worker_id=i)
        for i in range(2)
    ]
    workers = []
    partials: list = []
    plock = threading.Lock()

    def on_partial(doc):
        with plock:
            partials.append(dict(doc))

    try:
        for i, svc in enumerate(services):
            ws = WorkerServer(svc, worker_id=i, fabric=_CFG)
            ws.start()
            workers.append(ws)
        router = Router([ws.address for ws in workers], _CFG)
        router.start()
        try:
            lines = [_prog_line(i) for i in range(4)]
            entries = [
                router.submit_line(ln, no, on_partial=on_partial)
                for no, ln in enumerate(lines, start=1)
            ]
            docs = [e.wait(timeout=TIMEOUT_S) for e in entries]
            assert all(d is not None and d.get("ok") for d in docs)
            assert all(d.get("converged") for d in docs)
            assert router.counters["partials_dropped_stale"] == 0
            assert router.counters["partials_forwarded"] == len(partials)

            # per-id round order: every request streamed rounds 1..3
            per: dict = {}
            for p in partials:
                assert p.get("partial") is True
                per.setdefault(p["id"], []).append(p["round"])
            assert set(per) == {f"pp-{i}" for i in range(4)}
            for rounds in per.values():
                assert rounds == [1, 2, 3]

            # digest parity with a direct serve_jsonl run of pp-0
            with AnalysisService(cache_dir=None) as solo_svc:
                fout = io.StringIO()
                serve_jsonl(solo_svc, io.StringIO(lines[0] + "\n"),
                            fout)
            solo_docs = [json.loads(ln)
                         for ln in fout.getvalue().splitlines()]
            solo_final = [d for d in solo_docs if not d.get("partial")]
            fabric_final = {d["id"]: d for d in docs}["pp-0"]
            assert solo_final[0]["mrc_digest"] \
                == fabric_final["mrc_digest"]
            assert solo_final[0]["fingerprint"] \
                == fabric_final["fingerprint"]

            # kill worker 0; the router's bounded reconnect fails and
            # re-dispatches to the survivor — partial frames still
            # stream under the new owner with the right id
            workers[0].close()
            with plock:
                partials.clear()
            line = json.dumps({
                "id": "pp-f", "model": "gemm", "n": 16,
                "engine": "sampled", "ratio": 0.2, "seed": 7650,
                "tolerance": 0.0, "max_rounds": 3,
            })
            entry = router.submit_line(line, 99,
                                       on_partial=on_partial)
            doc = entry.wait(timeout=TIMEOUT_S)
            assert doc is not None and doc.get("ok")
            with plock:
                got = [p for p in partials if p["id"] == "pp-f"]
            assert [p["round"] for p in got] == [1, 2, 3]
        finally:
            router.close(graceful=True)
    finally:
        for ws in workers:
            ws.close()
        for svc in services:
            svc.close()


# -- the subprocess CI gate --------------------------------------------


def test_check_fabric_gate():
    """The full tools/check_fabric.py gate: supervisor subprocesses,
    1-vs-2-worker digest identity cold+warm, restart-stable sharding,
    the SIGKILL re-dispatch path, zero orphans."""
    import check_fabric

    assert check_fabric.main([]) == 0
