"""Program frontend: schema round-trip, strict parse diagnostics,
service integration of inline programs, serve robustness, the CLI
dump/load paths, and the generative fuzz smoke.

The acceptance invariants pinned here:
- parse(dump(m)) is fingerprint-identical to m for the WHOLE registry;
- a custom nest structurally equal to gemm produces the same
  fingerprint AND byte-identical MRC (same mrc_digest) as the
  registry request, via the service and via serve_jsonl;
- warm repeat of a custom nest = zero engine executions;
- hostile documents (oversize / over-deep / non-numeric / huge bounds
  products) are structured per-line errors with the id echoed and the
  `frontend_rejected` counter bumped — never a crash;
- 25 fuzz seeds pass the cheap contract in tier-1 (sampled drift
  sweep behind -m slow; the standing gate is tools/fuzz_ir.py).
"""

from __future__ import annotations

import io
import json
import os
import sys

import numpy as np
import pytest

from pluss_sampler_optimization_tpu.cli import main
from pluss_sampler_optimization_tpu.config import MachineConfig
from pluss_sampler_optimization_tpu.frontend import (
    FrontendError,
    malformed_doc_fixtures,
    parse_program,
    parse_program_doc,
    program_to_json,
)
from pluss_sampler_optimization_tpu.frontend import fuzz
from pluss_sampler_optimization_tpu.models import REGISTRY, build
from pluss_sampler_optimization_tpu.runtime import telemetry
from pluss_sampler_optimization_tpu.service import (
    AnalysisRequest,
    AnalysisService,
    serve_jsonl,
)

sys.path.insert(
    0,
    os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), "tools"),
)
import check_ir  # noqa: E402
import fuzz_ir  # noqa: E402


@pytest.fixture(autouse=True)
def _clean_telemetry():
    telemetry.disable()
    yield
    telemetry.disable()


def _build(name: str, n: int):
    try:
        return build(name, n, 2)
    except ValueError:
        return build(name, n, 1)


# -- schema round-trip ------------------------------------------------


def test_roundtrip_whole_registry_fingerprint_identical():
    """parse(dump(m)) == m, structurally AND by request fingerprint,
    for every registry model (dumps carry the program name, so the
    fingerprint identity comes for free)."""
    from pluss_sampler_optimization_tpu.service.fingerprint import (
        request_fingerprint,
    )

    machine = MachineConfig()
    for name in sorted(REGISTRY):
        program = _build(name, 8)
        # through JSON text, as a serve payload would arrive
        doc = json.loads(json.dumps(program_to_json(program)))
        parsed = parse_program(doc)
        assert parsed == program, name
        assert (
            request_fingerprint(parsed, machine, "exact", {})
            == request_fingerprint(program, machine, "exact", {})
        ), name


def test_dump_is_explicit_and_versioned():
    doc = program_to_json(build("gemm", 16))
    assert doc["ir_version"] == 1
    assert doc["name"] == "gemm-16x16x16"
    for lp in doc["nests"][0]["loops"]:
        assert set(lp) == {"trip", "start", "step", "trip_coeff",
                           "start_coeff"}
    for r in doc["nests"][0]["refs"]:
        assert set(r) == {"name", "array", "level", "coeffs", "const",
                          "slot", "share_threshold", "share_ratio",
                          "write"}


def test_machine_knobs_roundtrip():
    m = MachineConfig(thread_num=2, chunk_size=3)
    doc = program_to_json(build("gemm", 8), machine=m)
    assert doc["machine"]["thread_num"] == 2
    from pluss_sampler_optimization_tpu.frontend import machine_from_doc

    merged = machine_from_doc(doc, MachineConfig())
    assert merged.thread_num == 2 and merged.chunk_size == 3


# -- strict parse diagnostics ----------------------------------------


def test_every_malformed_doc_fixture_yields_its_code():
    for name, (doc, want) in malformed_doc_fixtures().items():
        res = parse_program_doc(doc)
        assert res.program is None, name
        codes = [d.code for d in res.errors()]
        assert want in codes, (name, want, codes)


def test_parse_program_raises_frontend_error_with_dict_diagnostics():
    from pluss_sampler_optimization_tpu.analysis import PreflightError

    doc, want = malformed_doc_fixtures()["step_zero"]
    with pytest.raises(FrontendError) as ei:
        parse_program(doc)
    # FrontendError IS a PreflightError: every preflight-rejection
    # consumer (serve_jsonl structured errors) handles it unchanged
    assert isinstance(ei.value, PreflightError)
    diags = ei.value.diagnostics
    assert diags and isinstance(diags[0], dict)
    assert any(d["code"] == want for d in diags)


def test_custom_nest_rejects_like_malformed_registry_model():
    """The no-drift property: a semantically bad custom nest gets the
    SAME V_* code/path the shared validator gives malformed IR."""
    from pluss_sampler_optimization_tpu import analysis

    bag, want = analysis.malformed_fixtures()["step_zero"]
    report = analysis.analyze_program(bag)
    ir_codes = {d.code for d in report.diagnostics}
    doc, _ = malformed_doc_fixtures()["step_zero"]
    doc_codes = {d.code for d in parse_program_doc(doc).errors()}
    assert want in ir_codes and want in doc_codes


def test_access_cap_blocks_hostile_bounds_without_materializing():
    doc, want = malformed_doc_fixtures()["hostile_bounds_product"]
    res = parse_program_doc(doc)
    assert res.program is None
    assert [d.code for d in res.errors()] == [want]


# -- service integration ----------------------------------------------


def _gemm_doc(n: int = 16) -> dict:
    return program_to_json(build("gemm", n))


def test_custom_gemm_twin_same_fingerprint_and_mrc(tmp_path):
    """The tentpole acceptance: a custom nest structurally equal to
    gemm coalesces onto the registry request's cache slot and serves
    byte-identical MRC bytes — and the warm custom repeat runs zero
    engine work."""
    tele = telemetry.enable()
    ledger = str(tmp_path / "ledger.jsonl")
    with AnalysisService(ledger_path=ledger) as svc:
        reg = svc.analyze(AnalysisRequest(model="gemm", n=16,
                                          engine="numpy"))
        assert reg.ok and tele.counters["service_exec_started"] == 1
        custom = svc.analyze(AnalysisRequest(
            model="custom", program=_gemm_doc(), engine="numpy"))
        assert custom.ok
        # identical content address -> served from cache, no engine
        assert custom.fingerprint == reg.fingerprint
        assert custom.cache == "mem"
        assert custom.mrc_digest == reg.mrc_digest
        assert np.array_equal(custom.mrc, reg.mrc)
        assert tele.counters["service_exec_started"] == 1
        # custom preflight carries the structural signature
        assert custom.preflight["verdict"] == "ok"
        assert len(custom.preflight["signature"]) == 16
    rows = [json.loads(ln) for ln in open(ledger)]
    custom_rows = [r for r in rows if r.get("model") == "custom"]
    assert custom_rows and custom_rows[0]["signature"] \
        == custom.preflight["signature"]
    # the embedded document makes the row replayable
    assert custom_rows[0]["request"]["program"] == _gemm_doc()


def test_custom_request_validation():
    with pytest.raises(ValueError):
        AnalysisRequest(model="gemm", program=_gemm_doc())
    with pytest.raises(ValueError):
        AnalysisRequest(model="custom")
    with pytest.raises(ValueError):
        AnalysisRequest(model="custom", program="not a dict")


def test_custom_document_machine_overrides_request_fields():
    doc = program_to_json(build("gemm", 8),
                          machine=MachineConfig(thread_num=2))
    req = AnalysisRequest(model="custom", program=doc, threads=8)
    assert req.machine().thread_num == 2


def test_registry_payload_shape_unchanged():
    """Registry records keep their pre-frontend payload shape exactly
    (no `program` key), so stored record bytes are pinned."""
    payload = AnalysisRequest(model="gemm", n=8).payload()
    assert "program" not in payload
    assert "program" in AnalysisRequest(
        model="custom", program=_gemm_doc()).payload()


# -- serve_jsonl: inline programs + robustness ------------------------


def _serve(svc, lines):
    out = io.StringIO()
    serve_jsonl(svc, io.StringIO("\n".join(lines) + "\n"), out)
    return [json.loads(ln) for ln in out.getvalue().splitlines()]


def test_serve_inline_program_matches_registry_line():
    tele = telemetry.enable()
    with AnalysisService() as svc:
        docs = _serve(svc, [
            json.dumps({"id": "r", "model": "gemm", "n": 16,
                        "engine": "numpy"}),
            json.dumps({"id": "c", "program": _gemm_doc(),
                        "engine": "numpy"}),
        ])
    assert docs[0]["ok"] and docs[1]["ok"]
    assert docs[0]["fingerprint"] == docs[1]["fingerprint"]
    assert docs[0]["mrc_digest"] == docs[1]["mrc_digest"]
    # both lines submit before any result is awaited, so the custom
    # twin singleflight-coalesces onto the registry line's execution
    assert tele.counters["service_exec_started"] == 1


def test_serve_rejects_hostile_documents_structured():
    bad_nests = {"ir_version": 1, "nests": [{
        "loops": [{"trip": 1 << 12}, {"trip": 1 << 12},
                  {"trip": 1 << 12}],
        "refs": [{"name": "R0", "array": "A", "level": 2,
                  "coeffs": [1 << 24, 1 << 12, 1]}] * 2}] * 16}
    non_numeric = {"ir_version": 1, "nests": [{
        "loops": [{"trip": "16"}],
        "refs": [{"name": "R0", "array": "A", "level": 0,
                  "coeffs": [1]}]}]}
    deep = '{"id": "deep", "program": ' + "[" * 4000 + "]" * 4000 + "}"
    big = json.dumps({"id": "big", "model": "gemm",
                      "pad": "x" * (1 << 21)})
    with AnalysisService() as svc:
        docs = _serve(svc, [
            json.dumps({"id": "hb", "program": bad_nests}),
            json.dumps({"id": "nn", "program": non_numeric}),
            deep,
            big,
            json.dumps({"id": "clash", "program": _gemm_doc(8),
                        "model": "gemm"}),
            json.dumps({"id": "ok", "model": "gemm", "n": 8,
                        "engine": "numpy"}),
        ])
        stats = svc.executor.stats()
    by_id = {d["id"]: d for d in docs}
    assert not by_id["hb"]["ok"]
    assert any(d["code"] == "F_ACCESSES"
               for d in by_id["hb"]["diagnostics"])
    assert not by_id["nn"]["ok"]
    assert any(d["code"] == "V_COEFF_SHAPE"
               for d in by_id["nn"]["diagnostics"])
    # hostile JSON nesting and oversize lines: refused with the id
    # echoed, never an unhandled exception
    assert not by_id["deep"]["ok"] and "deep" in by_id["deep"]["error"]
    assert not by_id["big"]["ok"] and "exceeds" in by_id["big"]["error"]
    assert not by_id["clash"]["ok"]
    assert "mutually exclusive" in by_id["clash"]["error"]
    assert by_id["ok"]["ok"]
    assert stats["frontend_rejected"] == 4  # hb, nn, deep, big


def test_serve_custom_rejection_writes_ledger_row(tmp_path):
    ledger = str(tmp_path / "ledger.jsonl")
    doc, _ = malformed_doc_fixtures()["step_zero"]
    with AnalysisService(ledger_path=ledger) as svc:
        docs = _serve(svc, [json.dumps({"id": "x", "program": doc})])
    assert not docs[0]["ok"] and docs[0]["diagnostics"]
    rows = [json.loads(ln) for ln in open(ledger)]
    assert rows and rows[0]["model"] == "custom"
    assert rows[0]["preflight"] == "invalid"


# -- CLI --------------------------------------------------------------


def test_cli_dump_ir_roundtrips(capsys):
    assert main(["--dump-ir", "gemm", "--n", "8"]) == 0
    doc = json.loads(capsys.readouterr().out)
    assert parse_program(doc) == build("gemm", 8)


def test_cli_dump_ir_dir_covers_registry(tmp_path, capsys):
    assert main(["--dump-ir-dir", str(tmp_path), "--n", "8"]) == 0
    files = sorted(p for p in os.listdir(tmp_path))
    assert files == sorted(f"{m}.json" for m in REGISTRY)
    for f in files:
        doc = json.load(open(tmp_path / f))
        assert parse_program_doc(doc).ok, f


def test_cli_program_json_acc_byte_identical(tmp_path, capsys):
    """Direct CLI path: acc output through --program-json is byte-
    identical to the registry model's run."""
    assert main(["--dump-ir", "gemm", "--n", "8"]) == 0
    doc_text = capsys.readouterr().out
    path = tmp_path / "gemm8.json"
    path.write_text(doc_text)
    assert main(["acc", "--engine", "numpy", "--model", "gemm",
                 "--n", "8"]) == 0
    registry_out = capsys.readouterr().out
    assert main(["acc", "--engine", "numpy",
                 "--program-json", str(path)]) == 0
    custom_out = capsys.readouterr().out
    assert custom_out == registry_out


def test_cli_program_json_rejection_exits_with_diagnostics(tmp_path):
    doc, _ = malformed_doc_fixtures()["step_zero"]
    path = tmp_path / "bad.json"
    path.write_text(json.dumps(doc))
    with pytest.raises(SystemExit) as ei:
        main(["analyze", "--program-json", str(path)])
    assert "V_STEP_ZERO" in str(ei.value)


def test_cli_analyze_program_json(tmp_path, capsys):
    path = tmp_path / "gemm.json"
    path.write_text(json.dumps(_gemm_doc(8)))
    assert main(["analyze", "--program-json", str(path)]) == 0
    assert "verdict ok" in capsys.readouterr().out


# -- tools ------------------------------------------------------------


def test_check_ir_tool_validates_files(tmp_path, capsys):
    good = tmp_path / "good.json"
    good.write_text(json.dumps(_gemm_doc(8)))
    bad = tmp_path / "bad.json"
    bad_doc, _ = malformed_doc_fixtures()["parallel_triangular"]
    bad.write_text(json.dumps(bad_doc))
    assert check_ir.main(["--ir-json", str(good)]) == 0
    out = capsys.readouterr().out
    assert "ok" in out and "gemm-8x8x8" in out
    assert check_ir.main(["--ir-json", str(good), str(bad),
                          "--json"]) == 1
    lines = [json.loads(ln)
             for ln in capsys.readouterr().out.splitlines()]
    assert lines[0]["verdict"] == "ok"
    assert lines[1]["verdict"] == "invalid"
    assert any(d["code"] == "V_PARALLEL_TRIANGULAR"
               for d in lines[1]["diagnostics"])


def test_check_ir_fixtures_include_doc_set(capsys):
    assert check_ir.main(["--fixtures"]) == 0
    out = capsys.readouterr().out
    # 11 IR fixtures + the frontend document set, all passing
    n = 11 + len(malformed_doc_fixtures())
    assert f"{n}/{n}" in out


def test_fuzz_ir_tool_fails_on_mismatch(monkeypatch, capsys):
    """The gate exits nonzero when any seed reports errors."""
    def fake_check_seed(seed, **kw):
        return {"seed": seed, "ok": False, "program": f"fuzz{seed}",
                "depth": 1, "refs": 1, "accesses": 0,
                "sampled_drift": 9.9, "mutants_rejected": "0/0",
                "errors": ["exact: synthetic mismatch"]}

    monkeypatch.setattr(fuzz, "check_seed", fake_check_seed)
    assert fuzz_ir.main(["--seeds", "2"]) == 1


# -- fuzz smoke -------------------------------------------------------


@pytest.mark.parametrize("seed", range(25))
def test_fuzz_smoke(seed):
    """25-seed tier-1 smoke of the cheap contract: round-trip,
    exact-engine bit-identity vs the numpy oracle, every mutant
    rejected with its expected code. The sampled drift sweep rides
    the slow marker below and the tools/fuzz_ir.py standing gate."""
    r = fuzz.check_seed(seed, sampled=False)
    assert r["ok"], r["errors"]
    assert r["accesses"] >= fuzz.MIN_ACCESSES


def test_fuzz_batched_and_sharded_bit_identity():
    """One-seed tier-1 smoke of the batched (run_sampled_multi
    union bucket) and sharded (run_sampled_sharded, 2-device mesh)
    contract arms: both must be bit-identical to the solo sampled
    run. The multi-seed sweep is `tools/fuzz_ir.py --batched
    --sharded`."""
    r = fuzz.check_seed(0, sampled=False, batched=True, sharded=True)
    assert r["ok"], r["errors"]


@pytest.mark.slow
def test_fuzz_deep_with_sampled_drift():
    summary = fuzz.run_seeds(40, sampled=True)
    assert summary["failed"] == 0, summary["failures"]
