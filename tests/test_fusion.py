"""Cross-ref fused dispatch + async pipeline: the bit-identity
contract (ISSUE 6).

The fused sampled path (sampler/sampled.py::_sampled_outputs_fused)
stacks refs sharing a kernel-signature bucket into ONE vmapped
dispatch and overlaps device->host transfers with the next bucket's
draw. Every one of its reductions is exact and the per-ref sample
streams are unchanged, so fusion on vs off MUST produce the same MRC
bytes — on rectangular and triangular models, under both draw paths,
through capacity regrows, checkpoint resume, and the sharded engine.
"""

import dataclasses
import glob
import os

import pytest

from pluss_sampler_optimization_tpu import MachineConfig, SamplerConfig
from pluss_sampler_optimization_tpu.models import REGISTRY, gemm, syrk_tri
from pluss_sampler_optimization_tpu.parallel import (
    build_mesh,
    run_sampled_sharded,
)
from pluss_sampler_optimization_tpu.runtime import telemetry
from pluss_sampler_optimization_tpu.sampler.sampled import run_sampled

MACHINE = MachineConfig()
BASE = SamplerConfig(ratio=0.25, seed=3, fuse_refs=True)


@pytest.fixture(autouse=True)
def _clean_telemetry():
    telemetry.disable()
    yield
    telemetry.disable()


def _state_dump(state):
    return (
        [sorted(h.items()) for h in state.noshare],
        [sorted((k, sorted(v.items())) for k, v in h.items())
         for h in state.share],
    )


def _run(prog, cfg, **kw):
    tele = telemetry.enable()
    state, results = run_sampled(prog, MACHINE, cfg, **kw)
    telemetry.disable()
    return state, results, tele


def _assert_identical(prog, cfg, **kw):
    st_f, r_f, t_f = _run(prog, dataclasses.replace(cfg, fuse_refs=True),
                          **kw)
    st_s, r_s, t_s = _run(prog, dataclasses.replace(cfg, fuse_refs=False),
                          **kw)
    assert _state_dump(st_f) == _state_dump(st_s)
    assert len(r_f) == len(r_s)
    for a, b in zip(r_f, r_s):
        assert a == b  # full SampledRefResult equality, field by field
    return t_f, t_s


@pytest.mark.parametrize("device_draw", [False, True])
def test_fused_bit_identical_gemm(device_draw):
    """The headline contract on the headline model: fusion on vs off,
    same MRC bytes — and fewer dispatches with fusion on."""
    cfg = dataclasses.replace(BASE, device_draw=device_draw)
    t_f, t_s = _assert_identical(gemm(16), cfg)
    assert t_f.counters["dispatches"] < t_s.counters["dispatches"]
    assert t_f.counters["dispatches_fused"] >= 1
    assert "dispatches_fused" not in t_s.counters
    # the bucket plan the dispatch-stats checker audits
    assert t_f.gauges["ref_buckets"] == 4  # {C0,C1} {C2,C3} {A0} {B0}
    assert t_f.gauges["refs_per_dispatch"] == pytest.approx(1.5)
    assert (
        t_f.counters["dispatches"]
        <= t_f.gauges["ref_buckets"] * t_f.gauges["expected_chunks"]
        + t_f.counters.get("capacity_regrows", 0)
    )


def test_fused_bit_identical_triangular():
    """Triangular refs land in singleton buckets (their signatures pin
    ref_idx), so fusion must degrade gracefully to the per-ref kernels
    there — still bit-identical, still counted against the plan."""
    t_f, _t_s = _assert_identical(syrk_tri(12), BASE)
    assert (
        t_f.counters["dispatches"]
        <= t_f.gauges["ref_buckets"] * t_f.gauges["expected_chunks"]
        + t_f.counters.get("capacity_regrows", 0)
    )


def test_capacity_regrow_under_fusion():
    """Force regrows with capacity=1 and pin that (a) the regrown
    fused dispatch is bit-identical to the serial regrow path and (b)
    capacity_regrows counts once per regrown BUCKET dispatch, not once
    per ref. jacobi-2d is the probe: its five stencil reads of A share
    ONE bucket, and two of them individually hold >1 distinct
    (reuse, class) pairs — the serial path regrows each of those refs
    (2 counts), the fused path regrows their shared bucket dispatch
    exactly once."""
    cfg = dataclasses.replace(BASE, ratio=0.4, seed=11)
    prog = REGISTRY["jacobi-2d"](16)
    # establish how many refs individually exceed capacity 1
    _, r_big, _ = _run(prog, cfg, capacity=4096)
    n_overflowing = sum(
        1 for r in r_big
        if len(r.noshare) + sum(len(h) for h in r.share.values()) > 1
    )
    assert n_overflowing >= 2
    st_f, r_f, t_f = _run(prog, cfg, capacity=1)
    st_s, r_s, t_s = _run(
        prog, dataclasses.replace(cfg, fuse_refs=False), capacity=1
    )
    # the regrown fused run matches the serial regrow path AND the
    # amply-provisioned run, ref by ref
    assert _state_dump(st_f) == _state_dump(st_s)
    for a, b, c in zip(r_f, r_s, r_big):
        assert a == b
        assert a == c
    assert t_f.counters["capacity_regrows"] >= 1
    # once per regrown bucket dispatch: strictly fewer counts than
    # overflowing refs (a per-ref accounting would reach at least
    # n_overflowing, which is what the serial loop records)
    assert t_f.counters["capacity_regrows"] < n_overflowing
    assert (
        t_f.counters["capacity_regrows"]
        < t_s.counters["capacity_regrows"]
    )
    # and the regrown run still satisfies the dispatch-plan bound
    assert (
        t_f.counters["dispatches"]
        <= t_f.gauges["ref_buckets"] * t_f.gauges["expected_chunks"]
        + t_f.counters["capacity_regrows"]
    )


def test_resume_mid_bucket_masks_checkpointed_refs(tmp_path):
    """Checkpoint resume composes with fusion: a bucket whose OTHER
    member already checkpointed re-dispatches with the finished ref
    masked out of the stack (fewer rows, same kernel) — and the
    resumed run's output is byte-identical to the uninterrupted one."""
    ckpt = str(tmp_path / "ck")
    os.makedirs(ckpt)
    st_full, r_full, _ = _run(gemm(16), BASE, checkpoint_dir=ckpt)
    files = sorted(glob.glob(os.path.join(ckpt, "ref_*.json")))
    assert len(files) == len(r_full) == 6
    # kill ref 1 (C1) — the second member of the first {C0, C1}
    # bucket; C0's checkpoint survives, so the bucket resumes with a
    # single-row stack
    os.remove(os.path.join(ckpt, "ref_001.json"))
    st_res, r_res, t_res = _run(gemm(16), BASE, checkpoint_dir=ckpt)
    assert _state_dump(st_res) == _state_dump(st_full)
    for a, b in zip(r_res, r_full):
        assert a == b
    # only the one de-checkpointed ref recomputed, alone in its bucket
    assert t_res.gauges["ref_buckets"] == 1
    assert t_res.gauges["refs_per_dispatch"] == pytest.approx(1.0)
    # and a fully-checkpointed rerun dispatches nothing at all
    _st, r_all, t_all = _run(gemm(16), BASE, checkpoint_dir=ckpt)
    for a, b in zip(r_all, r_full):
        assert a == b
    assert "dispatches" not in t_all.counters
    assert t_all.gauges["ref_buckets"] == 0


def test_pipeline_depth_knob_and_stalls():
    """--pipeline-depth bounds the in-flight dispatches; a depth-1
    pipeline drains after every dispatch (a stall per dispatch) yet
    results stay bit-identical; deeper pipelines stall less."""
    d1 = dataclasses.replace(BASE, pipeline_depth=1)
    st_1, r_1, t_1 = _run(gemm(16), d1)
    st_4, r_4, t_4 = _run(gemm(16), BASE)  # default depth 4
    assert _state_dump(st_1) == _state_dump(st_4)
    for a, b in zip(r_1, r_4):
        assert a == b
    assert t_1.counters["pipeline_stalls"] == t_1.counters["dispatches"]
    assert (
        t_4.counters.get("pipeline_stalls", 0)
        < t_1.counters["pipeline_stalls"]
    )
    assert t_1.gauges["pipeline_depth"] == 1
    assert t_4.gauges["pipeline_depth"] == 4
    # the serial (unfused) host path honors the same knob
    s1 = dataclasses.replace(BASE, fuse_refs=False, pipeline_depth=1)
    st_s1, _r, t_s1 = _run(gemm(64), s1)
    st_s4, _r, _ = _run(gemm(64), dataclasses.replace(
        BASE, fuse_refs=False))
    assert _state_dump(st_s1) == _state_dump(st_s4)
    assert t_s1.counters.get("pipeline_stalls", 0) >= 1


@pytest.mark.parametrize("device_draw", [False, True])
def test_sharded_fusion_bit_identical(device_draw):
    """The sharded engine's fused bucket path must match its own
    per-ref loop under both draw streams on the 8-device virtual mesh.
    (Equality with the unsharded engine follows transitively: the
    sharded serial loop is pinned against the unsharded serial loop in
    test_parallel, and unsharded fused-vs-serial in the tests above.)
    """
    mesh = build_mesh(8)
    cfg = dataclasses.replace(BASE, device_draw=device_draw)
    _, sh_f = run_sampled_sharded(
        gemm(16), MACHINE, dataclasses.replace(cfg, fuse_refs=True),
        mesh=mesh,
    )
    _, sh_s = run_sampled_sharded(
        gemm(16), MACHINE, dataclasses.replace(cfg, fuse_refs=False),
        mesh=mesh,
    )
    for a, b in zip(sh_f, sh_s):
        assert a == b


@pytest.mark.slow
def test_sharded_fused_capacity_regrow():
    """Bucket-grain regrow on the mesh: capacity 1 forces the fused
    sharded drain loop to regrow and re-dispatch whole buckets; the
    result must still match the amply-provisioned unsharded engine."""
    mesh = build_mesh(8)
    cfg = dataclasses.replace(BASE, ratio=0.4, seed=11)
    tele = telemetry.enable()
    _, small = run_sampled_sharded(
        gemm(16), MACHINE, cfg, mesh=mesh, capacity=1
    )
    telemetry.disable()
    _, big = run_sampled(gemm(16), MACHINE, cfg, capacity=4096)
    for a, b in zip(small, big):
        assert a == b
    assert tele.counters["capacity_regrows"] >= 1
    assert (
        tele.counters["dispatches"]
        <= tele.gauges["ref_buckets"] * tele.gauges["expected_chunks"]
        + tele.counters["capacity_regrows"]
    )
