"""Generative fuzz: random affine programs through every engine.

The curated model suite pins 18 known kernels; this generates random
programs *within the documented caps* (README "Model-family limits":
depth <= 3, positive suffix-product strides so the head dominates,
rectangular parallel loop, unit-step triangular) and checks, for each:

- numpy oracle vs dense engine: bit-exact PRIState equality;
- sampled closed-form next-use vs brute-force trace search, for every
  valid iteration point of every reference (the strongest check).

Seeds are fixed, so failures reproduce; the generator is deliberately
adversarial about shapes the curated models underuse (post slots,
zeroed coefficients, nonzero starts, strided rectangular levels, odd
thread/chunk geometries, zero-trip triangular iterations).
"""

from __future__ import annotations

import numpy as np
import pytest

from pluss_sampler_optimization_tpu.config import MachineConfig
from pluss_sampler_optimization_tpu.ir import Loop, ParallelNest, Program, Ref
from pluss_sampler_optimization_tpu.oracle import run_numpy
from pluss_sampler_optimization_tpu.sampler import run_dense

from test_sampled import _check_exhaustive_next_use


def _random_program(seed: int) -> Program:
    rng = np.random.default_rng(seed)
    depth = int(rng.integers(1, 4))
    tri = depth >= 2 and rng.random() < 0.4

    loops = []
    for l in range(depth):
        start = int(rng.integers(0, 3))
        step = 1 if tri else int(rng.choice([1, 1, 2]))
        trip = int(rng.integers(2, 8))
        if tri and l == depth - 1:
            tc = int(rng.choice([-1, 1]))
            if tc < 0:
                # size the base trip INSIDE the parallel value range so
                # the top v0 values clamp trip_at to zero — the
                # zero-trip path must actually be exercised
                lp0 = loops[0]
                v0_max = lp0.start + (lp0.trip - 1) * lp0.step
                trip = int(rng.integers(1, max(2, v0_max + 1)))
            loops.append(Loop(trip, start=start, step=1, trip_coeff=tc,
                              start_coeff=int(rng.choice([0, 1]))))
        else:
            loops.append(Loop(trip, start=start, step=step))
    nest_loops = tuple(loops)

    # per-level value extents bound every reachable loop value — exact,
    # by enumerating the (small) parallel range; suffix products of
    # them make row-major-style strides whose head always dominates the
    # residual span (the band-candidate cap's requirement)
    lp0 = nest_loops[0]
    v0s = [lp0.start + i * lp0.step for i in range(lp0.trip)]
    extents = []
    for lp in nest_loops:
        vmax = 0
        for v0 in v0s:
            tr = lp.trip_at(v0)
            if tr > 0:
                vmax = max(vmax, lp.start_at(v0) + (tr - 1) * lp.step)
        extents.append(max(1, vmax) + 1)

    refs = []
    n_refs = int(rng.integers(1, 6))
    for r in range(n_refs):
        lv = int(rng.integers(0, depth))
        coeffs = []
        for l in range(lv + 1):
            c = 1
            for k in range(l + 1, lv + 1):
                c *= extents[k]
            coeffs.append(c)
        # zero a random strict subset (B0-style maps that drop levels)
        if lv >= 1 and rng.random() < 0.4:
            z = int(rng.integers(0, lv + 1))
            coeffs[z] = 0
            if all(c == 0 for c in coeffs):
                coeffs[lv] = 1
        slot = "pre"
        if lv < depth - 1 and rng.random() < 0.25:
            slot = "post"
        thr = int(rng.integers(1, 60)) if rng.random() < 0.3 else None
        refs.append(Ref(
            name=f"R{r}", array=rng.choice(["A", "B"]), level=lv,
            coeffs=tuple(coeffs), const=int(rng.integers(0, 3)),
            slot=slot, share_threshold=thr,
        ))

    return Program(name=f"fuzz{seed}", nests=(ParallelNest(
        loops=nest_loops, refs=tuple(refs)),))


def _random_machine(seed: int) -> MachineConfig:
    rng = np.random.default_rng(seed + 7919)
    return MachineConfig(
        thread_num=int(rng.integers(2, 6)),
        chunk_size=int(rng.integers(1, 6)),
    )


# 20 in CI (~75 s both checks); swept clean offline with zero
# mismatches (2026-07-31): dense, periodic, stream, AND the device
# draw, all at seeds 20-299
SEEDS = list(range(20))


@pytest.mark.parametrize("seed", SEEDS)
def test_fuzz_dense_matches_oracle(seed):
    program = _random_program(seed)
    machine = _random_machine(seed)
    ref = run_numpy(program, machine)
    got = run_dense(program, machine)
    assert got.total_accesses == ref.total_accesses
    assert got.per_tid_accesses == ref.per_tid_accesses
    for t in range(machine.thread_num):
        assert got.state.noshare[t] == ref.state.noshare[t], f"tid {t}"
        assert got.state.share[t] == ref.state.share[t], f"tid {t}"


@pytest.mark.parametrize("seed", SEEDS)
def test_fuzz_sampled_next_use_exhaustive(seed):
    _check_exhaustive_next_use(_random_program(seed), _random_machine(seed))


@pytest.mark.parametrize("seed", SEEDS)
def test_fuzz_periodic_matches_oracle_or_rejects(seed):
    """The periodic engine on random programs: every accepted program
    must be bit-exact vs the oracle, every rejection must come from
    the documented validator (NotImplementedError), never a wrong
    histogram. The generator's random zeroed coefficients, mixed
    arrays, post slots, and odd geometries probe exactly the
    precondition tiers (equal-c0, contiguity, phases). Seeds 20-299
    were swept offline (2026-07-31): 139 accepted all bit-exact, 141
    rejected by the validator, zero mismatches."""
    from pluss_sampler_optimization_tpu.sampler.periodic import (
        run_periodic,
        validate_periodic,
    )

    program = _random_program(seed)
    machine = _random_machine(seed)
    try:
        validate_periodic(program, machine)
    except NotImplementedError:
        return  # documented fallback; dense/stream cover these
    ref = run_numpy(program, machine)
    got = run_periodic(program, machine)
    assert got.total_accesses == ref.total_accesses
    assert got.per_tid_accesses == ref.per_tid_accesses
    for t in range(machine.thread_num):
        assert got.state.noshare[t] == ref.state.noshare[t], f"tid {t}"
        assert got.state.share[t] == ref.state.share[t], f"tid {t}"


@pytest.mark.parametrize("seed", SEEDS)
def test_fuzz_device_draw_exactness(seed):
    """Device-drawn sample keys on random programs: every accepted
    (nest, ref) must yield exactly s distinct in-range keys, with
    triangular draws respecting the per-v0 bounds — the generator's
    odd geometries (nonzero starts, strided rectangular levels,
    zero-trip triangular tails) probe the box-scaling and rejection
    margins the curated models underuse. Seeds 20-299 swept offline
    (2026-07-31): 274 programs with accepted refs all exact, 6
    all-declined programs all with genuinely empty drawable spaces,
    zero drawing defects."""
    from pluss_sampler_optimization_tpu.config import SamplerConfig
    from pluss_sampler_optimization_tpu.core.trace import ProgramTrace
    from pluss_sampler_optimization_tpu.sampler import draw as D
    from pluss_sampler_optimization_tpu.sampler.sampled import (
        _sample_plan,
        decode_sample_keys,
    )

    program = _random_program(seed)
    machine = _random_machine(seed)
    cfg = SamplerConfig(ratio=0.35, seed=seed)
    checked = declined = 0
    for nt in ProgramTrace(program, machine).nests:
        if nt.tri and any(lp.step != 1 for lp in nt.nest.loops):
            continue  # the sampled engine rejects these nests
        for ri in range(nt.tables.n_refs):
            out = D.draw_sample_keys_device(
                nt, ri, cfg, seed=seed * 31 + ri, batch=1 << 12
            )
            if out is None:
                # a decline must be genuine: at these tiny sizes the
                # budget/int64 caps cannot fire, so the only valid
                # reason is an empty drawable space (zero-trip
                # triangular tails) — some seeds produce programs
                # where EVERY ref declines this way
                _, plan_s, plan_space = _sample_plan(nt, ri, cfg)
                assert plan_s == 0 or plan_space == 0
                declined += 1
                continue
            keys, chosen, s, highs = out
            k = np.asarray(keys)[np.asarray(chosen)]
            plan_highs, plan_s, _ = _sample_plan(nt, ri, cfg)
            assert s == plan_s and list(highs) == list(plan_highs)
            assert len(k) == s == len(np.unique(k))
            space_box = int(np.prod(np.asarray(highs, dtype=np.int64)))
            assert (k >= 0).all() and (k < space_box).all()
            lv = int(nt.tables.ref_levels[ri])
            if nt.tri and lv >= 1:
                cols = np.asarray(decode_sample_keys(k, tuple(highs)))
                v0 = nt.nest.loops[0].start + cols[:, 0] * (
                    nt.nest.loops[0].step
                )
                for l in range(1, lv + 1):
                    assert (
                        cols[:, l] < nt.nest.loops[l].trip_at(v0) - 1
                    ).all()
            checked += 1
    assert checked + declined > 0
