"""Device histogram primitives: the hash-round unique reduction must be
exactly equivalent to the sorted reference reduction."""

import numpy as np
import pytest

from pluss_sampler_optimization_tpu.ops.histogram import (
    exp_hist,
    fixed_k_unique,
    sorted_k_unique,
)


def _as_dict(keys, counts):
    return {int(x): int(c) for x, c in zip(keys, counts) if c > 0}


@pytest.mark.parametrize("seed", range(5))
def test_hash_unique_matches_sorted(seed):
    rng = np.random.default_rng(seed)
    n = int(rng.integers(5, 1 << 15))
    pool = rng.integers(0, 1 << 55, int(rng.integers(1, 300)))
    vals = rng.choice(pool, n)
    valid = rng.random(n) < 0.7
    ka, ca, na = sorted_k_unique(vals, valid, 256)
    kb, cb, nb = fixed_k_unique(vals, valid, 256)
    assert int(na) == int(nb)
    assert _as_dict(ka, ca) == _as_dict(kb, cb)


def test_hash_unique_sorted_fallback():
    """More distinct keys than hash slots with a single round leaves
    unresolved losers by pigeonhole, forcing the in-graph lax.cond
    sorted fallback — results must still be exact. rounds=0 takes the
    direct sorted path."""
    rng = np.random.default_rng(3)
    vals = rng.permutation(np.arange(5000, dtype=np.int64) * 104729)
    valid = np.ones(5000, dtype=bool)
    ka, ca, na = sorted_k_unique(vals, valid, 64)
    for rounds in (0, 1):
        kb, cb, nb = fixed_k_unique(vals, valid, 64, rounds=rounds)
        assert int(na) == int(nb) == 5000
        # both over capacity: the k returned keys must agree
        assert _as_dict(ka, ca) == _as_dict(kb, cb)


def test_hash_unique_overflow_reports_true_count():
    """More distinct keys than capacity: n_unique is the true distinct
    count (the regrow/raise paths key off it), matching the sorted
    reduction."""
    vals = np.arange(1000, dtype=np.int64) * 7919
    valid = np.ones(1000, dtype=bool)
    _, _, na = sorted_k_unique(vals, valid, 64)
    _, _, nb = fixed_k_unique(vals, valid, 64)
    assert int(na) == int(nb) == 1000


def test_hash_unique_hostile_keys():
    """Keys colliding with internal markers (-1 matches the empty-slot
    key field; large keys near the sorted path's sentinel) must still
    count exactly — emptiness is signalled by count 0, not by key."""
    vals = np.array([-1, -1, 5, 7, (1 << 61), (1 << 61)], dtype=np.int64)
    valid = np.ones(len(vals), dtype=bool)
    ka, ca, na = sorted_k_unique(vals, valid, 8)
    kb, cb, nb = fixed_k_unique(vals, valid, 8)
    want = {-1: 2, 5: 1, 7: 1, 1 << 61: 2}
    assert _as_dict(ka, ca) == want
    assert _as_dict(kb, cb) == want
    assert int(na) == int(nb) == 4


@pytest.mark.parametrize("k", [32, 64, 128, 256, 512])
def test_hash_unique_adaptive_rounds_full_load(k):
    """A full k-distinct load at every capacity tier (rounds resolves
    2 below 64, 3 above) stays exact whether the hash rounds resolve
    everything or the in-graph sorted fallback fires."""
    rng = np.random.default_rng(k)
    pool = rng.integers(0, 1 << 55, k)
    vals = rng.choice(pool, 1 << 14)
    valid = np.ones(len(vals), dtype=bool)
    ka, ca, na = sorted_k_unique(vals, valid, k)
    kb, cb, nb = fixed_k_unique(vals, valid, k)
    assert int(na) == int(nb)
    assert _as_dict(ka, ca) == _as_dict(kb, cb)


def test_exp_hist_mass():
    vals = np.array([1, 2, 3, 8, 9, 1 << 40], dtype=np.int64)
    w = np.ones(len(vals), dtype=np.int64)
    h = exp_hist(vals, w)
    assert int(h.sum()) == len(vals)
    assert int(h[0]) == 1 and int(h[1]) == 2 and int(h[3]) == 2
    assert int(h[40]) == 1
