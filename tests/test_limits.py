"""Regression tests for the documented model-family limits.

Each cap in README.md's "Model-family limits" table must fail fast with
a clean, named error — never silently truncate, mis-solve, or unroll an
unbounded traced graph. One test per guard site.
"""

from __future__ import annotations

import pytest

from pluss_sampler_optimization_tpu import (
    Loop,
    MachineConfig,
    ParallelNest,
    Program,
    Ref,
    SamplerConfig,
)
from pluss_sampler_optimization_tpu.core.trace import ProgramTrace
from pluss_sampler_optimization_tpu.sampler.sampled import run_sampled


def test_depth_cap():
    with pytest.raises(ValueError, match="depth is 1..3"):
        ParallelNest(
            loops=(Loop(4), Loop(4), Loop(4), Loop(4)),
            refs=(Ref("A0", "A", level=0, coeffs=(1,)),),
        )
    with pytest.raises(ValueError, match=r"level must be in \[0,3\)"):
        Ref("A0", "A", level=3, coeffs=(1, 1, 1, 1))


def test_parallel_loop_must_be_rectangular():
    # doubly-triangular nests (lu/cholesky) are out of scope: the
    # parallel loop's own bounds may not depend on anything
    with pytest.raises(ValueError, match="parallel loop must be rectangular"):
        ParallelNest(
            loops=(Loop(8, trip_coeff=-1), Loop(8)),
            refs=(Ref("A0", "A", level=1, coeffs=(8, 1)),),
        )


def test_negative_stride_rejected():
    prog = Program(
        name="negstride",
        nests=(
            ParallelNest(
                loops=(Loop(8), Loop(8)),
                refs=(Ref("A0", "A", level=1, coeffs=(8, -1), const=7),),
            ),
        ),
    )
    with pytest.raises(NotImplementedError, match="negative stride"):
        run_sampled(prog, MachineConfig(), SamplerConfig(ratio=0.5, seed=0))


def test_band_candidate_cap():
    # flat = i + j: comparable coefficients; the head stride does not
    # dominate the residual span, so the band enumeration would be
    # O(trip) instead of O(1) — must raise, not unroll ~260 candidates
    # into the traced graph
    n = 256
    prog = Program(
        name="antidiag",
        nests=(
            ParallelNest(
                loops=(Loop(n), Loop(n)),
                refs=(Ref("A0", "A", level=1, coeffs=(1, 1)),),
            ),
        ),
    )
    with pytest.raises(NotImplementedError, match="does not dominate"):
        run_sampled(prog, MachineConfig(), SamplerConfig(ratio=0.01, seed=0))


def test_share_ratio_radix_cap():
    # share ratio defaults to thread_num-1. The sampled engine packs
    # (reuse, slot) with radix 16 (slot 15 = the noshare marker, so
    # ratio < 15); the dense engine's packed key uses radix 8
    from pluss_sampler_optimization_tpu.models.gemm import gemm
    from pluss_sampler_optimization_tpu.sampler.dense import run_dense

    with pytest.raises(NotImplementedError, match="share ratio"):
        run_sampled(
            gemm(32), MachineConfig(thread_num=16),
            SamplerConfig(ratio=0.2, seed=0),
        )
    with pytest.raises(NotImplementedError, match="share ratio"):
        run_dense(gemm(16), MachineConfig(thread_num=9))


def test_triangular_nonunit_step_sampled_engine():
    prog = Program(
        name="tri-step2",
        nests=(
            ParallelNest(
                loops=(Loop(8), Loop(8, step=2, trip_coeff=-1)),
                refs=(Ref("A0", "A", level=1, coeffs=(8, 1)),),
            ),
        ),
    )
    with pytest.raises(NotImplementedError, match="unit steps only"):
        run_sampled(prog, MachineConfig(), SamplerConfig(ratio=0.5, seed=0))


def test_sample_space_int64_cap():
    """Flat-space sample keys are int64 mixed-radix; a nest whose
    drawable space exceeds 2^63 must raise a typed error (not a bare
    assert that vanishes under python -O, and never a silently wrapped
    draw range)."""
    from pluss_sampler_optimization_tpu.core.trace import ProgramTrace
    from pluss_sampler_optimization_tpu.sampler.sampled import (
        draw_sample_keys,
    )

    n = 3_000_000  # (3e6 - 1)^3 > 2^63
    prog = Program(
        name="hugespace",
        nests=(
            ParallelNest(
                loops=(Loop(n), Loop(n), Loop(n)),
                refs=(Ref("A0", "A", level=2, coeffs=(n * n, n, 1)),),
            ),
        ),
    )
    nt = ProgramTrace(prog, MachineConfig()).nests[0]
    with pytest.raises(NotImplementedError, match="sample space"):
        draw_sample_keys(nt, 0, SamplerConfig(ratio=1e-9, seed=0), seed=0)


def test_negative_element_index_rejected():
    from pluss_sampler_optimization_tpu.sampler.dense import run_dense

    prog = Program(
        name="negaddr",
        nests=(
            ParallelNest(
                loops=(Loop(8), Loop(8)),
                refs=(Ref("A0", "A", level=1, coeffs=(8, 1), const=-4),),
            ),
        ),
    )
    with pytest.raises(NotImplementedError, match="negative"):
        run_dense(prog, MachineConfig())


def test_position_width_audit():
    """Pins the int32/int64 crossover README documents: GEMM per-thread
    trace positions fit int32 through N=1024 and overflow it by N=2048,
    so jax_enable_x64 is a correctness requirement at north-star sizes."""
    from pluss_sampler_optimization_tpu.models.gemm import gemm

    def max_pos(n):
        trace = ProgramTrace(gemm(n), MachineConfig())
        return max(
            trace.nests[0].tid_length(t)
            for t in range(MachineConfig().thread_num)
        )

    assert max_pos(1024) < 2**31
    assert max_pos(2048) > 2**31


def test_rect_models_within_band_cap():
    """The whole shipped model family stays under the band-candidate cap
    (the guard must never fire for supported programs). The guard only
    runs inside the per-ref classification kernels, so actually run the
    sampled engine, not just trace construction."""
    from pluss_sampler_optimization_tpu.models.gemm import gemm
    from pluss_sampler_optimization_tpu.models.jacobi2d import jacobi2d
    from pluss_sampler_optimization_tpu.models.mm2 import mm2

    for prog in (gemm(128), mm2(24), jacobi2d(24)):
        _, results = run_sampled(
            prog, MachineConfig(), SamplerConfig(ratio=0.02, seed=0)
        )
        assert sum(r.n_samples for r in results) > 0
