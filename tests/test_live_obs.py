"""Live serving observability: the rolling metrics registry and its
scrape surface, end-to-end trace propagation through singleflight and
the batch scheduler, the ledger v2 trace/stage columns, and the SLO
burn-rate sentinel plus its offline gate (tools/check_slo.py).

The ISSUE-9 acceptance invariants are pinned here: serve mode exposes
a live Prometheus scrape whose per-stage histograms populate under a
concurrent batched workload; every ledger row carries a trace_id
joining it to its (possibly shared) execution span; the three counter
surfaces (serve `stats`, the registry/Prometheus export, and
check_ledger --stats) agree on submitted/coalesced/completed/failed/
degraded over one workload; the SLO gate exits nonzero on an injected
latency breach and zero on a healthy run; and MRC outputs are
byte-identical with the registry enabled vs disabled.
"""

import io
import json
import os
import sys
import threading
import time
import urllib.error
import urllib.request

import numpy as np
import pytest

from pluss_sampler_optimization_tpu import MachineConfig, SamplerConfig
from pluss_sampler_optimization_tpu.cli import main
from pluss_sampler_optimization_tpu.config import SLOConfig
from pluss_sampler_optimization_tpu.models import REGISTRY
from pluss_sampler_optimization_tpu.runtime import telemetry
from pluss_sampler_optimization_tpu.runtime.aet import aet_mrc
from pluss_sampler_optimization_tpu.runtime.cri import cri_distribute
from pluss_sampler_optimization_tpu.runtime.obs import (
    exporters,
    ledger as obs_ledger,
    metrics as obs_metrics,
    profiler as obs_profiler,
    slo as obs_slo,
)
from pluss_sampler_optimization_tpu.sampler.sampled import run_sampled
from pluss_sampler_optimization_tpu.service import (
    AnalysisRequest,
    AnalysisService,
    serve_jsonl,
)
from pluss_sampler_optimization_tpu.service.executor import (
    default_runner,
)

sys.path.insert(
    0,
    os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), "tools"),
)
import check_ledger  # noqa: E402
import check_slo  # noqa: E402


@pytest.fixture(autouse=True)
def _clean_slate():
    telemetry.disable()
    obs_metrics.disable()
    obs_profiler.disable()
    yield
    telemetry.disable()
    obs_metrics.disable()
    obs_profiler.disable()


def _req(**kw):
    base = dict(model="gemm", n=16, engine="oracle")
    base.update(kw)
    return AnalysisRequest(**base)


# -- registry instruments ---------------------------------------------


def test_registry_counters_windows_and_expiry():
    reg = obs_metrics.MetricsRegistry()
    t0 = 1000.0
    reg.inc("reqs", 3, now=t0)
    reg.inc("reqs", 2, now=t0 + 1.0)
    reg.set_gauge("depth", 7)
    assert reg.counter("reqs") == 5
    assert reg.gauge_value("depth") == 7
    assert reg.counter_window("reqs", "30s", now=t0 + 1.0) == 5
    assert reg.counter_window("reqs", "5m", now=t0 + 1.0) == 5
    # the 30s ring expires, the lifetime total and 5m window persist
    assert reg.counter_window("reqs", "30s", now=t0 + 40.0) == 0
    assert reg.counter_window("reqs", "5m", now=t0 + 40.0) == 5
    assert reg.counter_window("reqs", "5m", now=t0 + 400.0) == 0
    assert reg.counter("reqs") == 5
    assert reg.counter("never_written") == 0.0
    with pytest.raises(KeyError):
        reg.counter_window("reqs", "2h", now=t0)


def test_rolling_histogram_quantiles_fractions_and_expiry():
    reg = obs_metrics.MetricsRegistry()
    t0 = 2000.0
    for v in (0.002, 0.002, 0.02, 0.02, 0.02, 0.02, 0.02, 2.0):
        reg.observe("lat", v, now=t0)
    # p50 lands in the (0.01, 0.025] bucket; interpolation keeps it
    # inside the bucket bounds
    p50 = reg.histogram_quantile("lat", "30s", 0.50, now=t0)
    assert 0.01 < p50 <= 0.025
    # exactly 1/8 of observations sit above 1s
    frac = reg.histogram_fraction_over("lat", "30s", 1.0, now=t0)
    assert abs(frac - 1 / 8) < 1e-9
    assert reg.histogram_fraction_over("lat", "30s", 100.0, now=t0) \
        <= 1 / 8
    # window expiry: 30s empties (None), lifetime snapshot persists
    assert reg.histogram_quantile("lat", "30s", 0.5,
                                  now=t0 + 60.0) is None
    snap = reg.snapshot(now=t0)["histograms"]["lat"]
    assert snap["count"] == 8
    assert snap["buckets"]["+Inf"] == 8
    assert snap["buckets"]["0.0025"] == 2
    assert snap["windows"]["30s"]["count"] == 8
    # absent histogram reads as None, not an error
    assert reg.histogram_quantile("nope", "30s", 0.5) is None
    assert reg.histogram_fraction_over("nope", "30s", 1.0) is None


def test_prometheus_registry_text_histograms_and_exemplars():
    reg = obs_metrics.MetricsRegistry()
    reg.inc("service_submitted", 4)
    reg.set_gauge("service_queue_depth", 2)
    reg.observe("request_total_s", 0.02, exemplar="deadbeefcafe0123")
    text = reg.prometheus_text()
    assert "# TYPE pluss_service_submitted_total counter" in text
    assert "pluss_service_submitted_total 4" in text
    assert "pluss_service_queue_depth 2" in text
    assert "# TYPE pluss_request_total_s histogram" in text
    # cumulative buckets: everything at and above 0.025 counts the obs
    assert 'pluss_request_total_s_bucket{le="0.025"} 1' in text
    assert 'pluss_request_total_s_bucket{le="+Inf"} 1' in text
    assert 'pluss_request_total_s_bucket{le="0.01"} 0' in text
    assert "pluss_request_total_s_count 1" in text
    # the exemplar joins the bucket to the trace
    assert '# {trace_id="deadbeefcafe0123"} 0.02' in text
    assert text.endswith("\n")


def test_prometheus_name_collisions_suffix_deterministically():
    # two raw telemetry names that sanitize identically must not
    # overwrite each other in the exposition
    pairs = [(("counter", "cache/hits"), "pluss_cache_hits_total"),
             (("counter", "cache.hits"), "pluss_cache_hits_total"),
             (("counter", "other"), "pluss_other_total")]
    names = exporters.resolve_prometheus_names(pairs)
    assert names[("counter", "other")] == "pluss_other_total"
    vals = {names[("counter", "cache/hits")],
            names[("counter", "cache.hits")]}
    assert len(vals) == 2
    assert "pluss_cache_hits_total" in vals
    suffixed = next(v for v in vals if v != "pluss_cache_hits_total")
    assert suffixed.startswith("pluss_cache_hits_total_")
    assert len(suffixed.rsplit("_", 1)[1]) == 8
    # deterministic across calls and insertion orders
    assert exporters.resolve_prometheus_names(list(reversed(pairs))) \
        == names

    reg = obs_metrics.MetricsRegistry()
    reg.inc("cache/hits", 1)
    reg.inc("cache.hits", 2)
    text = reg.prometheus_text()
    emitted = [ln.split()[0] for ln in text.splitlines()
               if ln and not ln.startswith("#")]
    assert len(emitted) == len(set(emitted))
    assert sum(
        1 for n in emitted if n.startswith("pluss_cache_hits_total")
    ) == 2


def test_telemetry_write_path_feeds_registry_without_a_run():
    """count/gauge/counted_lru_cache mirror into the live registry
    even when no per-run Telemetry is enabled — the two views share
    one write path."""
    reg = obs_metrics.enable()
    calls = []

    @telemetry.counted_lru_cache(maxsize=8, counter="live_test_cache")
    def f(x):
        calls.append(x)
        return x * 2

    assert f(3) == 6 and f(3) == 6
    telemetry.count("live_only", 5)
    telemetry.gauge("live_gauge", 1.5)
    assert reg.counter("live_only") == 5
    assert reg.gauge_value("live_gauge") == 1.5
    assert reg.counter("live_test_cache_hits") == 1
    assert reg.counter("live_test_cache_misses") == 1
    assert len(calls) == 1
    obs_metrics.disable()
    telemetry.count("live_only", 5)  # no sink: must not blow up
    assert reg.counter("live_only") == 5  # and the old registry froze


def test_metrics_server_scrapes_live_registry():
    reg = obs_metrics.MetricsRegistry()
    reg.inc("scrape_me", 9)
    with obs_metrics.MetricsServer(reg, port=0) as srv:
        assert srv.port > 0
        url = f"http://{srv.host}:{srv.port}/metrics"
        with urllib.request.urlopen(url, timeout=10) as resp:
            assert resp.status == 200
            assert resp.headers["Content-Type"].startswith(
                "text/plain; version=0.0.4"
            )
            body = resp.read().decode()
        assert "pluss_scrape_me_total 9" in body
        with pytest.raises(urllib.error.HTTPError):
            urllib.request.urlopen(
                f"http://{srv.host}:{srv.port}/nope", timeout=10
            )
    # after close() the port no longer answers
    with pytest.raises(Exception):
        urllib.request.urlopen(url, timeout=0.5)


def test_metrics_server_profile_route_off_is_structured_404():
    """With no profiler running, /debug/profile answers a machine-
    readable JSON 404 body — pollers must never have to parse the
    stdlib HTML error page to learn the profiler is off."""
    reg = obs_metrics.MetricsRegistry()
    with obs_metrics.MetricsServer(
        reg, port=0, profile=obs_profiler.snapshot
    ) as srv:
        with pytest.raises(urllib.error.HTTPError) as ei:
            urllib.request.urlopen(
                f"http://{srv.host}:{srv.port}/debug/profile",
                timeout=10,
            )
        assert ei.value.code == 404
        body = json.loads(ei.value.read().decode())
        assert body["error"] == "profiler not running"
        assert body["status"] == 404
        assert "--profile-hz" in body["hint"]


def test_metrics_server_concurrent_scrapes_during_execution():
    """N parallel scrapers hammering /metrics and /debug/profile while
    spans execute: every response is a well-formed 200, every profile
    snapshot validates — concurrent scrapes must never corrupt or
    crash the registry/profiler read paths."""
    telemetry.enable()
    reg = obs_metrics.enable()
    prof = obs_profiler.enable(hz=300.0)
    stop = threading.Event()

    def busy_requests():
        while not stop.is_set():
            with telemetry.span("service_request", engine="sampled"):
                with telemetry.span("execute"):
                    telemetry.count("scrape_test_reqs")
                    sum(range(2000))

    failures: list = []
    snapshots: list = []

    def scraper(base):
        try:
            for _ in range(5):
                with urllib.request.urlopen(
                    base + "/metrics", timeout=10
                ) as resp:
                    assert resp.status == 200
                    assert "pluss_" in resp.read().decode()
                with urllib.request.urlopen(
                    base + "/debug/profile", timeout=10
                ) as resp:
                    assert resp.status == 200
                    snap = json.loads(resp.read().decode())
                    errs = obs_profiler.validate_snapshot(snap)
                    assert errs == [], errs
                    snapshots.append(snap)
        except Exception as e:  # pragma: no cover - failure detail
            failures.append(repr(e))

    worker = threading.Thread(target=busy_requests, daemon=True)
    worker.start()
    try:
        with obs_metrics.MetricsServer(
            reg, port=0, profile=obs_profiler.snapshot
        ) as srv:
            base = f"http://{srv.host}:{srv.port}"
            scrapers = [
                threading.Thread(target=scraper, args=(base,))
                for _ in range(6)
            ]
            for t in scrapers:
                t.start()
            for t in scrapers:
                t.join(timeout=60)
    finally:
        stop.set()
        worker.join(timeout=10)
        obs_profiler.disable()
    assert not failures, failures
    assert len(snapshots) == 30
    # snapshots are monotone: later scrapes never report fewer samples
    # than earlier ones from the same collector (consistency under
    # concurrent folding)
    assert all(s["profile_version"] == obs_profiler.PROFILE_VERSION
               for s in snapshots)
    assert max(s["samples"] for s in snapshots) >= 1
    final = prof.snapshot()
    assert final["samples_attributed"] >= 1


# -- serve surface ----------------------------------------------------


def test_serve_metrics_request_reports_live_state(tmp_path):
    """The `metrics` control line: disabled → {"enabled": false};
    enabled → counters, rolling windows, per-stage histograms, and
    the Prometheus text, reflecting the batch's own submissions."""
    svc = AnalysisService(cache_dir=str(tmp_path / "store"))
    fin = io.StringIO(json.dumps({"id": "m", "type": "metrics"}) + "\n")
    fout = io.StringIO()
    try:
        assert serve_jsonl(svc, fin, fout) == 0
    finally:
        svc.close()
    line = json.loads(fout.getvalue())
    assert line["ok"] and line["metrics"] == {"enabled": False}

    obs_metrics.enable()
    svc = AnalysisService(cache_dir=str(tmp_path / "store2"))
    fin = io.StringIO("\n".join([
        json.dumps({"id": "r1", "model": "gemm", "n": 16,
                    "engine": "oracle"}),
        json.dumps({"id": "m", "type": "metrics"}),
    ]) + "\n")
    fout = io.StringIO()
    try:
        assert serve_jsonl(svc, fin, fout) == 0
    finally:
        svc.close()
    r1, m = [json.loads(ln) for ln in fout.getvalue().splitlines()]
    assert r1["ok"] and r1["trace_id"] and r1["span_id"]
    payload = m["metrics"]
    assert payload["enabled"] is True
    assert payload["counters"]["service_submitted"] == 1
    assert payload["counter_windows"]["service_submitted"]["30s"] == 1
    hist = payload["histograms"]["request_total_s"]
    assert hist["count"] == 1
    assert hist["windows"]["30s"]["count"] == 1
    assert "pluss_service_submitted_total 1" in payload["prometheus"]
    assert "pluss_request_total_s_bucket" in payload["prometheus"]


def test_three_counter_surfaces_agree_on_one_workload(tmp_path, capsys):
    """Satellite 1: serve `stats`, the live registry, and
    check_ledger --stats report IDENTICAL submitted/coalesced/
    completed/failed/degraded over a workload that exercises
    coalescing and degradation."""
    release = threading.Event()

    def runner(engine, program, machine, request):
        if engine == "exact":
            raise RuntimeError("exact exploded")
        release.wait(timeout=30)
        return default_runner(engine, program, machine, request)

    ledger_path = str(tmp_path / "ledger.jsonl")
    reg = obs_metrics.enable()
    with AnalysisService(max_workers=4, runner=runner,
                         ledger_path=ledger_path) as svc:
        # two identical submissions: the second must join in flight
        # (the worker is parked on the event)
        t1 = svc.submit(_req())
        deadline = time.time() + 30
        while not svc.executor._inflight and time.time() < deadline:
            time.sleep(0.01)
        t2 = svc.submit(_req())
        release.set()
        r1 = svc.result(t1, timeout=60)
        r2 = svc.result(t2, timeout=60)
        # one degraded completion: exact fails, the chain lands it
        r3 = svc.analyze(_req(model="gemm", n=8, engine="exact",
                              ratio=0.3), timeout=120)
        stats = svc.executor.stats()
    assert r1.ok and r2.ok and r3.ok and r3.degraded
    assert r1.fingerprint == r2.fingerprint

    want = {"submitted": 3, "coalesced": 1, "completed": 2,
            "failed": 0, "degraded": 1}
    assert {k: stats[k] for k in want} == want
    assert {k: int(reg.counter(f"service_{k}")) for k in want} == want
    agg = obs_ledger.aggregate(obs_ledger.read_rows(ledger_path))
    assert {k: agg["service"][k] for k in want} == want
    # and the CLI auditor prints the same line
    assert check_ledger.main([ledger_path, "--stats"]) == 0
    out = capsys.readouterr().out
    assert ("service: submitted=3 coalesced=1 completed=2 "
            "failed=0 degraded=1") in out


# -- trace propagation ------------------------------------------------


def test_singleflight_joiners_share_trace_and_ledger_row(tmp_path):
    """N identical concurrent requests: one execution, one ledger row
    whose trace_id/span_id every response shares, and the row counts
    its joiners."""
    release = threading.Event()

    def slow_runner(engine, program, machine, request):
        release.wait(timeout=30)
        return default_runner(engine, program, machine, request)

    ledger_path = str(tmp_path / "ledger.jsonl")
    obs_metrics.enable()
    with AnalysisService(max_workers=4, runner=slow_runner,
                         ledger_path=ledger_path) as svc:
        first = svc.submit(_req())
        deadline = time.time() + 30
        while not svc.executor._inflight and time.time() < deadline:
            time.sleep(0.01)
        rest = [svc.submit(_req()) for _ in range(3)]
        release.set()
        resps = [svc.result(t, timeout=60) for t in [first] + rest]
    assert all(r.ok for r in resps)
    assert len({r.trace_id for r in resps}) == 1
    assert len({r.span_id for r in resps}) == 1
    assert resps[0].trace_id and resps[0].span_id

    rows = [r for r in obs_ledger.read_rows(ledger_path)
            if r["kind"] == "request"]
    assert len(rows) == 1
    assert rows[0]["trace_id"] == resps[0].trace_id
    assert rows[0]["span_id"] == resps[0].span_id
    assert rows[0]["coalesced"] == 3
    assert rows[0]["queue_s"] >= 0


def test_batched_members_share_execution_span(tmp_path):
    """N distinct batched requests: each response/row keeps its own
    trace_id but all join ONE execution span; rows carry the
    per-stage timings; the per-stage histograms populate; exemplars
    surface real trace ids in the scrape text."""
    reqs = [
        AnalysisRequest(model=m, n=n, engine="sampled", ratio=0.3,
                        seed=s)
        for m, n, s in (("gemm", 24, 5), ("gemm", 32, 7),
                        ("2mm", 12, 11))
    ]
    # Deterministic batch formation: a wall-clock window alone is
    # flaky on a loaded host (the scheduler can flush before the
    # third submit lands). Set max_refs to the EXACT tracked-ref
    # total of the three programs, so the early-flush fires on the
    # third enqueue and the (long) window is pure fallback.
    total_refs = sum(
        sum(len(nest.refs) for nest in REGISTRY[r.model](r.n).nests)
        for r in reqs
    )
    ledger_path = str(tmp_path / "ledger.jsonl")
    reg = obs_metrics.enable()
    tele = telemetry.enable()
    with AnalysisService(cache_dir=str(tmp_path / "store"),
                         ledger_path=ledger_path,
                         batch_window_ms=30000.0,
                         batch_max_refs=total_refs) as svc:
        tickets = [svc.submit(r) for r in reqs]
        resps = [svc.result(t, timeout=300) for t in tickets]
    telemetry.disable()
    assert all(r.ok for r in resps)
    assert tele.counters.get("batches_formed") == 1
    assert len({r.trace_id for r in resps}) == len(reqs)
    assert len({r.span_id for r in resps}) == 1
    span_id = resps[0].span_id
    # the shared execution span carries the same span_id attribute
    exec_spans = tele.find_spans("service_exec")
    assert [s.attrs.get("span_id") for s in exec_spans] == [span_id]

    rows = [r for r in obs_ledger.read_rows(ledger_path)
            if r["kind"] == "request"]
    assert len(rows) == len(reqs)
    assert {r["span_id"] for r in rows} == {span_id}
    assert ({r["trace_id"] for r in rows}
            == {r.trace_id for r in resps})
    for row in rows:
        assert row["ledger_version"] == 2
        assert row["batch_wait_s"] >= 0
        assert row["queue_s"] >= 0
        assert row["execute_s"] > 0

    snap = reg.snapshot()["histograms"]
    for name in ("request_total_s", "request_batch_wait_s",
                 "request_execute_s", "request_queue_s"):
        assert snap[name]["count"] == len(reqs), name
    text = reg.prometheus_text()
    for r in resps:
        assert f'trace_id="{r.trace_id}"' in text


def test_ledger_v1_rows_still_validate_v2_is_stamped(tmp_path):
    """Satellite 3 migration: pre-existing v1 rows stay valid, new
    appends stamp v2, and the v2 trace/stage columns are
    type-checked."""
    v1 = {
        "ledger_version": 1, "ts": 1.0, "kind": "request",
        "source": "service", "ok": True, "engine_requested": "oracle",
        "engine_used": "oracle", "model": "gemm", "n": 16,
        "latency_s": 0.01, "cache": "miss", "degraded": [],
        "fingerprint": "f" * 64, "mrc_digest": None,
    }
    assert obs_ledger.validate_row(v1) == []
    v2 = dict(v1, ledger_version=2, trace_id="t" * 16,
              span_id="s" * 16, queue_s=0.001, batch_wait_s=0.002,
              execute_s=0.05, coalesced=2)
    assert obs_ledger.validate_row(v2) == []
    assert obs_ledger.validate_row(dict(v2, trace_id=5)) != []
    assert obs_ledger.validate_row(dict(v2, execute_s="slow")) != []
    assert obs_ledger.validate_row(dict(v1, ledger_version=3)) != []

    path = str(tmp_path / "ledger.jsonl")
    with open(path, "w") as f:
        f.write(json.dumps(v1) + "\n")
    stamped = obs_ledger.append(path, {
        k: v for k, v in v1.items()
        if k not in ("ledger_version", "ts")
    })
    assert stamped["ledger_version"] == obs_ledger.LEDGER_VERSION == 2
    rows = obs_ledger.read_rows(path)
    assert [r["ledger_version"] for r in rows] == [1, 2]
    assert obs_ledger.aggregate(rows)["rows"] == 2


def test_mrc_bit_identical_with_registry_enabled(tmp_path):
    """The acceptance bit-identity check: enabling the live registry
    must not perturb engine numerics."""
    prog = REGISTRY["gemm"](16)
    machine = MachineConfig()
    cfg = SamplerConfig(ratio=0.3, seed=3)

    def mrc_bytes():
        state, _ = run_sampled(prog, machine, cfg)
        T = machine.thread_num
        return aet_mrc(
            cri_distribute(state, T, T), machine
        ).tobytes()

    off = mrc_bytes()
    obs_metrics.enable()
    on = mrc_bytes()
    obs_metrics.disable()
    assert on == off
    assert np.frombuffer(off, dtype=np.float64).size > 0


# -- SLO sentinel -----------------------------------------------------


def test_burn_check_requires_both_windows():
    mk = obs_slo._burn_check
    assert mk("x", {"30s": 0.5, "5m": 0.5}, 0.05, 1.0, {})["ok"] \
        is False  # burn 10 in both
    # fast-window spike alone is not a breach
    assert mk("x", {"30s": 0.5, "5m": 0.0}, 0.05, 1.0, {})["ok"]
    # no evidence anywhere: healthy
    assert mk("x", {"30s": None, "5m": None}, 0.05, 1.0, {})["ok"]
    assert mk("x", {}, 0.05, 1.0, {})["ok"]
    burn = mk("x", {"30s": 0.5, "5m": None}, 0.05, 1.0, {})
    assert burn["ok"] and burn["burn"]["30s"] == 10.0


def test_slo_sentinel_registry_breach_and_events():
    reg = obs_metrics.enable()
    tele = telemetry.enable()
    now = 5000.0
    for _ in range(20):
        reg.observe("request_total_s", 0.8, now=now)
        reg.inc("service_submitted", now=now)
    config = SLOConfig(latency_p95_s=0.1, error_budget=0.5)
    sentinel = obs_slo.SLOSentinel(config, registry=reg)
    report = sentinel.evaluate_once(now=now)
    telemetry.disable()
    assert report["ok"] is False
    by_name = {c["name"]: c for c in report["checks"]}
    lat = by_name["latency_p95"]
    assert not lat["ok"]
    assert all(b > 1.0 for b in lat["burn"].values())
    assert by_name["error_budget"]["ok"]  # nothing failed
    assert sentinel.last_report is report
    assert tele.counters.get("slo_evaluations") == 1
    assert tele.counters.get("slo_breach") == 1
    ev = [e for e in tele.events if e["name"] == "slo_breach"]
    assert ev and ev[0]["check"] == "latency_p95"
    assert ev[0]["burn_30s"] > 1.0
    # the breach itself is scrapeable: the counter mirrored back in
    assert reg.counter("slo_breach") == 1
    lines = obs_slo.format_report(report)
    assert any("latency_p95: BREACH" in ln for ln in lines)
    assert lines[-1] == "slo overall: BREACH"

    # healthy run: fast requests, no breach, no event
    reg2 = obs_metrics.enable()
    for _ in range(20):
        reg2.observe("request_total_s", 0.01, now=now)
        reg2.inc("service_submitted", now=now)
    healthy = obs_slo.SLOSentinel(config, registry=reg2)
    assert healthy.evaluate_once(now=now)["ok"]


def test_slo_sentinel_background_thread_runs():
    reg = obs_metrics.enable()
    tele = telemetry.enable()
    sentinel = obs_slo.SLOSentinel(
        SLOConfig(error_budget=0.5), registry=reg, interval_s=0.05
    ).start()
    deadline = time.time() + 10
    while (tele.counters.get("slo_evaluations", 0) < 2
           and time.time() < deadline):
        time.sleep(0.01)
    sentinel.close()
    telemetry.disable()
    assert tele.counters.get("slo_evaluations", 0) >= 2
    assert sentinel.last_report is not None
    assert sentinel.last_report["ok"]


def _ledger_with_latencies(path, latencies, ts=10_000.0):
    for i, lat in enumerate(latencies):
        obs_ledger.append(path, {
            "ts": ts + i * 0.001, "kind": "request",
            "source": "service", "ok": True,
            "engine_requested": "sampled", "engine_used": "sampled",
            "model": "gemm", "n": 16, "latency_s": lat,
            "cache": "miss", "degraded": [], "fingerprint": None,
            "mrc_digest": None,
        })


def test_check_slo_gate_exit_codes(tmp_path, capsys):
    """Satellite 6 / acceptance: the offline gate trips on an
    injected latency breach and stays green on a healthy ledger."""
    healthy = str(tmp_path / "healthy.jsonl")
    _ledger_with_latencies(healthy, [0.01] * 12)
    assert check_slo.main([healthy, "--latency-p95-s", "1.0"]) == 0
    out = capsys.readouterr().out
    assert "slo latency_p95: ok" in out
    assert "slo overall: ok" in out

    slow = str(tmp_path / "slow.jsonl")
    _ledger_with_latencies(slow, [2.0] * 12)
    assert check_slo.main([slow, "--latency-p95-s", "0.1"]) == 1
    out = capsys.readouterr().out
    assert "slo latency_p95: BREACH" in out
    assert "slo overall: BREACH" in out
    # without a latency objective the same ledger is inside budget
    assert check_slo.main([slow]) == 0
    capsys.readouterr()

    # degraded completions burn the error budget
    bad = str(tmp_path / "bad.jsonl")
    _ledger_with_latencies(bad, [0.01] * 4)
    obs_ledger.append(bad, {
        "ts": 10_000.5, "kind": "request", "source": "service",
        "ok": True, "engine_requested": "exact",
        "engine_used": "sampled", "model": "gemm", "n": 16,
        "latency_s": 0.01, "cache": "miss",
        "degraded": [{"from": "exact", "to": "sampled",
                      "reason": "x"}],
        "fingerprint": None, "mrc_digest": None,
    })
    assert check_slo.main([bad, "--error-budget", "0.01"]) == 1
    assert check_slo.main([bad, "--error-budget", "0.5"]) == 0
    capsys.readouterr()

    assert check_slo.main([str(tmp_path / "missing.jsonl")]) == 1
    empty = str(tmp_path / "empty.jsonl")
    open(empty, "w").close()
    assert check_slo.main([empty]) == 0
    capsys.readouterr()


# -- CLI surface ------------------------------------------------------


def test_cli_rejects_live_flags_outside_serve(tmp_path):
    base = ["acc", "--model", "gemm", "--n", "8", "--engine",
            "oracle"]
    with pytest.raises(SystemExit):
        main(base + ["--metrics-port", "0"])
    with pytest.raises(SystemExit):
        main(base + ["--slo-latency-p95-s", "1.0"])


def test_cli_serve_scrape_endpoint_and_slo(tmp_path, capsys):
    """serve --metrics-port 0: the scrape URL is announced on stderr
    and (scraped mid-run via a metrics control line) exposes the
    per-stage histograms; the SLO sentinel reports the injected
    latency breach on stderr."""
    requests = tmp_path / "requests.jsonl"
    requests.write_text("\n".join([
        json.dumps({"id": "r1", "model": "gemm", "n": 16,
                    "engine": "oracle"}),
        json.dumps({"id": "m", "type": "metrics"}),
    ]) + "\n")
    responses = tmp_path / "responses.jsonl"
    assert main([
        "serve", "--requests", str(requests),
        "--responses", str(responses),
        "--cache-dir", str(tmp_path / "store"),
        "--metrics-port", "0",
        "--slo-latency-p95-s", "1e-9", "--slo-interval-s", "60",
    ]) == 0
    err = capsys.readouterr().err
    assert "serve: live metrics on http://" in err
    # the injected (absurd) latency objective must trip the final
    # sentinel evaluation
    assert "slo latency_p95: BREACH" in err
    lines = [json.loads(ln)
             for ln in responses.read_text().splitlines()]
    r1, m = lines
    assert r1["ok"] and r1["trace_id"]
    payload = m["metrics"]
    assert payload["enabled"] is True
    assert payload["histograms"]["request_total_s"]["count"] == 1
    assert payload["slo"] is None or isinstance(payload["slo"], dict)
    assert "pluss_request_total_s_bucket" in payload["prometheus"]
    # serve tears the global registry down on exit
    assert obs_metrics.get() is None
