"""Native C++ serial runtime: bit-exact parity with the Python oracle."""

import pytest

from pluss_sampler_optimization_tpu import MachineConfig
from pluss_sampler_optimization_tpu.models.bicg import bicg
from pluss_sampler_optimization_tpu.models.gemm import gemm
from pluss_sampler_optimization_tpu.models.gesummv import gesummv
from pluss_sampler_optimization_tpu.models.jacobi2d import jacobi2d
from pluss_sampler_optimization_tpu.models.mm2 import mm2
from pluss_sampler_optimization_tpu.models.mm3 import mm3
from pluss_sampler_optimization_tpu.models.mvt import mvt
from pluss_sampler_optimization_tpu.models.syrk import syrk_rect
from pluss_sampler_optimization_tpu.oracle.serial import run_serial

native = pytest.importorskip("pluss_sampler_optimization_tpu.native")

pytestmark = pytest.mark.skipif(
    not native.available(), reason="no native toolchain"
)

MACHINE = MachineConfig()


def _results_equal(a, b):
    assert a.total_accesses == b.total_accesses
    assert a.per_tid_accesses == b.per_tid_accesses
    for ha, hb in zip(a.state.noshare, b.state.noshare):
        assert ha == hb
    for sa, sb in zip(a.state.share, b.state.share):
        assert set(sa) == set(sb)
        for ratio in sa:
            assert sa[ratio] == sb[ratio]


@pytest.mark.parametrize(
    "prog",
    [gemm(16), gemm(17), mm2(12), mm3(8), syrk_rect(12),
     jacobi2d(10, tsteps=2), mvt(16), bicg(13, 17), gesummv(16)],
    ids=lambda p: p.name,
)
def test_native_matches_python_oracle(prog):
    _results_equal(
        run_serial(prog, MACHINE), native.run_serial_native(prog, MACHINE)
    )


def test_native_odd_machine():
    m = MachineConfig(thread_num=3, chunk_size=5, ds=4, cls=32)
    prog = gemm(14)
    _results_equal(run_serial(prog, m), native.run_serial_native(prog, m))


def test_native_share_capacity_regrows():
    """An undersized share capacity regrows from the ABI-reported need
    and re-walks instead of raising (syrk-tri N=2048 needs ~4.6e5
    pairs, far past any useful fixed default); the result must match a
    comfortably-sized run bit for bit."""
    small = native.run_serial_native(gemm(24), MACHINE, share_cap=1)
    big = native.run_serial_native(gemm(24), MACHINE, share_cap=1 << 16)
    _results_equal(small, big)


@pytest.mark.parametrize(
    "prog",
    [gemm(16), gemm(17), mm2(12), jacobi2d(10, tsteps=2), bicg(13, 17)],
    ids=lambda p: p.name,
)
def test_native_parallel_matches_serial(prog):
    """One OS thread per simulated thread, thread-local histograms
    merged at join: the output must be bit-identical to the serial
    native walk (every piece of sampler state is tid-owned)."""
    _results_equal(
        native.run_serial_native(prog, MACHINE),
        native.run_parallel_native(prog, MACHINE),
    )


def test_native_parallel_odd_machines():
    for m in (MachineConfig(thread_num=3, chunk_size=5),
              MachineConfig(thread_num=7, chunk_size=2)):
        for prog in (gemm(14), mm2(10)):
            _results_equal(
                native.run_serial_native(prog, m),
                native.run_parallel_native(prog, m),
            )


def test_native_parallel_triangular():
    from pluss_sampler_optimization_tpu.models import syrk_tri, trmm

    for prog in (syrk_tri(9), trmm(8, 11)):
        _results_equal(
            native.run_serial_native(prog, MACHINE),
            native.run_parallel_native(prog, MACHINE),
        )


def test_native_triangular_models():
    from pluss_sampler_optimization_tpu.models import (
        covariance,
        syrk_tri,
        trisolv,
        trmm,
    )
    from pluss_sampler_optimization_tpu.oracle import run_serial

    machine = MachineConfig()
    for prog in (syrk_tri(9), trmm(8, 11), trisolv(13), covariance(9, 7)):
        a = run_serial(prog, machine)
        b = native.run_serial_native(prog, machine)
        assert a.total_accesses == b.total_accesses
        assert a.per_tid_accesses == b.per_tid_accesses
        for ha, hb in zip(a.state.noshare, b.state.noshare):
            assert ha == hb
        for sa, sb in zip(a.state.share, b.state.share):
            assert sa == sb
