"""Observability subsystem: run ledger, Chrome-trace/Prometheus
exporters, accuracy-drift monitoring, the ledger tools, and the CLI
surface (--ledger / --trace-out / --metrics-out / stats mode).

The ISSUE-4 acceptance invariants are pinned here: one serve session
plus acc/speed runs produce a single valid ledger that
tools/check_ledger.py validates and `cli stats` aggregates; the
--trace-out span tree matches Telemetry.to_json's; drift audits pass
on gemm + one non-gemm model with their metrics in the ledger; and
engine output is bit-identical with observability enabled vs
disabled.
"""

import json
import os
import re
import sys
import time

import pytest

from pluss_sampler_optimization_tpu.cli import main
from pluss_sampler_optimization_tpu.runtime import telemetry
from pluss_sampler_optimization_tpu.runtime.obs import (
    drift,
    exporters,
    ledger,
)

sys.path.insert(
    0,
    os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), "tools"),
)
import check_drift  # noqa: E402
import check_ledger  # noqa: E402


@pytest.fixture(autouse=True)
def _clean_telemetry():
    telemetry.disable()
    yield
    telemetry.disable()


def _request_row(**kw):
    row = {
        "kind": "request", "source": "test", "ok": True,
        "fingerprint": "ab" * 32, "engine_requested": "exact",
        "engine_used": "periodic", "model": "gemm", "n": 16,
        "latency_s": 0.5, "cache": "miss", "degraded": [],
        "mrc_digest": "0" * 16,
    }
    row.update(kw)
    return row


# -- ledger -----------------------------------------------------------


def test_ledger_append_validate_read_round_trip(tmp_path):
    path = str(tmp_path / "ledger.jsonl")
    r1 = ledger.append(path, _request_row())
    assert r1["ledger_version"] == ledger.LEDGER_VERSION
    assert r1["ts"] > 0
    ledger.append(path, _request_row(cache="mem", latency_s=0.001))
    rows = ledger.read_rows(path)
    assert len(rows) == 2
    assert rows[0]["cache"] == "miss" and rows[1]["cache"] == "mem"
    assert ledger.tail(path, 1) == [rows[1]]
    assert ledger.tail(str(tmp_path / "absent.jsonl")) == []
    # each line is self-contained JSON (the append-only contract)
    for line in open(path).read().splitlines():
        assert json.loads(line)["kind"] == "request"


def test_ledger_rejects_invalid_rows_before_write(tmp_path):
    path = str(tmp_path / "ledger.jsonl")
    with pytest.raises(ValueError):
        ledger.append(path, {"kind": "nope", "source": "t", "ok": True})
    with pytest.raises(ValueError):
        ledger.append(path, _request_row(cache="warm"))  # bad tier
    with pytest.raises(ValueError):
        ledger.append(path, _request_row(degraded="yes"))
    assert not os.path.exists(path)  # nothing hit the file
    assert ledger.validate_row(_request_row(
        ledger_version=1, ts=1.0)) == []
    assert ledger.validate_row("nope")
    assert any(
        "ledger_version" in e
        for e in ledger.validate_row({"ledger_version": 99})
    )


def test_ledger_skips_truncated_tail_line(tmp_path):
    """A crash mid-append leaves at most one partial line; readers
    skip it and the validator reports it."""
    path = str(tmp_path / "ledger.jsonl")
    ledger.append(path, _request_row())
    with open(path, "a") as f:
        f.write('{"kind": "requ')  # torn write
    rows = ledger.read_rows(path)
    assert len(rows) == 1
    entries = list(ledger.iter_rows(path))
    assert len(entries) == 2
    assert entries[1][1] is None and "invalid JSON" in entries[1][2]


def test_mrc_digest_stability_and_sensitivity():
    a = [1.0, 0.5, 0.25]
    assert ledger.mrc_digest(a) == ledger.mrc_digest(list(a))
    assert len(ledger.mrc_digest(a)) == 16
    assert ledger.mrc_digest(a) != ledger.mrc_digest([1.0, 0.5, 0.2501])
    import numpy as np

    assert ledger.mrc_digest(np.asarray(a)) == ledger.mrc_digest(a)


def test_ledger_aggregate_and_format():
    rows = [
        _request_row(ledger_version=1, ts=1.0),
        _request_row(ledger_version=1, ts=2.0, cache="mem",
                     latency_s=0.002),
        _request_row(ledger_version=1, ts=3.0, ok=False, cache=None,
                     latency_s=2.0, engine_used=None,
                     degraded=[{"from": "exact", "to": "sampled",
                                "reason": "x"}]),
        {"kind": "drift", "source": "t", "ok": True, "breach": False,
         "model": "gemm", "n": 32, "max_abs_delta": 0.1,
         "mean_abs_delta": 0.01, "ledger_version": 1, "ts": 4.0},
        {"kind": "bench", "source": "bench", "ok": True,
         "metric": "gemm4096_sampled_throughput", "value": 1e8,
         "ledger_version": 1, "ts": 5.0},
    ]
    for row in rows:
        assert ledger.validate_row(row) == [], row
    agg = ledger.aggregate(rows)
    assert agg["rows"] == 5
    assert agg["by_kind"] == {"request": 3, "drift": 1, "bench": 1}
    ex = agg["requests"]["exact"]
    assert ex["count"] == 3 and ex["ok"] == 2 and ex["failed"] == 1
    assert ex["degraded"] == 1
    assert ex["cache"] == {"mem": 1, "disk": 0, "miss": 1, "direct": 1}
    assert ex["cache_hit_rate"] == 0.5  # 1 warm / 2 served
    assert ex["p50_latency_s"] == 0.5
    assert ex["p95_latency_s"] == 2.0
    assert agg["drift"][0]["model"] == "gemm"
    assert agg["bench_rows"] == 1
    text = "\n".join(ledger.format_stats(agg))
    assert "exact" in text and "drift gemm" in text


# -- exporters --------------------------------------------------------


def _make_run():
    tele = telemetry.enable()
    with telemetry.span("outer", tag="a"):
        time.sleep(0.002)
        with telemetry.span("inner1"):
            time.sleep(0.002)
        with telemetry.span("inner2"):
            with telemetry.span("leaf", k=1):
                time.sleep(0.002)
    with telemetry.span("second_root"):
        pass
    telemetry.count("dispatches", 3)
    telemetry.count("service_cache_hit_mem")
    telemetry.gauge("queue_depth", 2)
    telemetry.gauge("label", "not-a-number")  # must be skipped
    telemetry.event("note", detail="x")
    telemetry.disable()
    return tele


def test_chrome_trace_preserves_span_nesting():
    tele = _make_run()
    events = exporters.chrome_trace_events(tele)
    spans = [e for e in events if e.get("cat") == "span"]
    # per-root tracks, preorder within each
    assert [(e["name"], e["tid"]) for e in spans] == [
        ("outer", 1), ("inner1", 1), ("inner2", 1), ("leaf", 1),
        ("second_root", 2),
    ]
    by_name = {e["name"]: e for e in spans}
    for child, parent in (("inner1", "outer"), ("inner2", "outer"),
                          ("leaf", "inner2")):
        c, p = by_name[child], by_name[parent]
        assert c["tid"] == p["tid"]
        assert c["ts"] >= p["ts"] - 2.0  # trace times are micros
        assert c["ts"] + c["dur"] <= p["ts"] + p["dur"] + 2.0
    # attrs ride in args; instant events carry telemetry events
    assert by_name["outer"]["args"] == {"tag": "a"}
    assert by_name["leaf"]["args"] == {"k": 1}
    inst = [e for e in events if e.get("ph") == "i"]
    assert inst and inst[0]["name"] == "note"
    assert inst[0]["args"]["detail"] == "x"
    # trace_event phase/shape sanity for every span record
    for e in spans:
        assert e["ph"] == "X" and e["dur"] >= 0 and e["pid"] == 1


def test_chrome_trace_sync_timings_preserved():
    tele = telemetry.enable(device_sync=True)
    with telemetry.span("dispatch") as sp:
        sp.block([1, 2, 3])
    telemetry.disable()
    events = exporters.chrome_trace_events(tele)
    span = next(e for e in events if e.get("cat") == "span")
    assert span["args"]["sync_s"] >= 0


def test_exporters_accept_doc_and_are_byte_stable(tmp_path):
    tele = _make_run()
    doc = tele.to_json()
    # repeated exports of one stopped run are byte-identical, and the
    # doc form (a saved --telemetry-out file) equals the live form
    t1 = exporters.chrome_trace_text(tele)
    t2 = exporters.chrome_trace_text(tele)
    t3 = exporters.chrome_trace_text(doc)
    assert t1 == t2 == t3
    p1 = exporters.prometheus_text(tele)
    assert p1 == exporters.prometheus_text(doc)
    out = tmp_path / "trace.json"
    exporters.write_chrome_trace(str(out), tele)
    parsed = json.loads(out.read_text())
    assert parsed["traceEvents"]  # valid JSON with the event list
    # telemetry.exporters resolves to this module (the documented
    # import surface)
    assert telemetry.exporters is exporters


_PROM_NAME = re.compile(r"^[a-zA-Z_:][a-zA-Z0-9_:]*$")


def test_prometheus_names_and_values():
    tele = _make_run()
    lines = exporters.prometheus_lines(tele)
    samples = {}
    for line in lines:
        if line.startswith("# TYPE "):
            _, _, name, mtype = line.split()
            assert _PROM_NAME.match(name), name
            assert mtype in ("counter", "gauge")
        else:
            name, value = line.split()
            assert _PROM_NAME.match(name), name
            samples[name] = float(value)
    assert samples["pluss_dispatches_total"] == 3
    assert samples["pluss_service_cache_hit_mem_total"] == 1
    assert samples["pluss_queue_depth"] == 2
    assert samples["pluss_run_duration_seconds"] > 0
    assert "pluss_label" not in samples  # non-numeric gauge skipped
    # counters sanitize through arbitrary telemetry names
    assert _PROM_NAME.match(
        exporters.prometheus_metric_name("weird/name:with-dots.x")
    )


def test_counted_lru_cache_exports_size_gauge():
    """Satellite: cache occupancy vs maxsize is visible as gauges (and
    therefore in the Prometheus export)."""

    @telemetry.counted_lru_cache(maxsize=4, counter="test_cache")
    def double(x):
        return x * 2

    tele = telemetry.enable()
    assert double(1) == 2
    assert double(1) == 2
    assert double(2) == 4
    telemetry.disable()
    assert tele.counters["test_cache_misses"] == 2
    assert tele.counters["test_cache_hits"] == 1
    assert tele.gauges["test_cache_size"] == 2
    assert tele.gauges["test_cache_maxsize"] == 4
    text = exporters.prometheus_text(tele)
    assert "pluss_test_cache_size 2" in text
    assert "pluss_test_cache_maxsize 4" in text


# -- CLI surface ------------------------------------------------------


def test_cli_trace_out_matches_telemetry_span_tree(tmp_path, capsys):
    """Acceptance: --trace-out emits Chrome-trace JSON whose span tree
    matches Telemetry.to_json — same names, same preorder per root,
    same timings."""
    tele_out = str(tmp_path / "tele.json")
    trace_out = str(tmp_path / "trace.json")
    metrics_out = str(tmp_path / "metrics.prom")
    assert main([
        "acc", "--model", "gemm", "--n", "16", "--engine", "exact",
        "--telemetry-out", tele_out, "--trace-out", trace_out,
        "--metrics-out", metrics_out,
    ]) == 0
    capsys.readouterr()
    tele_doc = json.load(open(tele_out))
    trace_doc = json.load(open(trace_out))

    def preorder(span, depth, out):
        out.append((span["name"], depth,
                    round(span["start_s"] * 1e6, 3),
                    round(span["wall_s"] * 1e6, 3)))
        for c in span["children"]:
            preorder(c, depth + 1, out)

    per_root = []
    for root in tele_doc["spans"]:
        out = []
        preorder(root, 0, out)
        per_root.append(out)
    span_events = [
        e for e in trace_doc["traceEvents"] if e.get("cat") == "span"
    ]
    for tid, expected in enumerate(per_root, start=1):
        got = [
            (e["name"], e["ts"], e["dur"])
            for e in span_events if e["tid"] == tid
        ]
        assert got == [
            (name, ts, dur) for name, _depth, ts, dur in expected
        ]
    assert len(span_events) == sum(len(x) for x in per_root) >= 3
    # the Prometheus export carries the same counters
    prom = open(metrics_out).read()
    assert "pluss_dispatches_total" in prom


def test_cli_obs_flags_bit_identical_output(tmp_path, capsys):
    """Acceptance: MRCs (the full acc dump) are bit-identical with
    observability enabled vs disabled."""
    argv = ["acc", "--model", "syrk", "--n", "20", "--engine", "exact"]
    assert main(argv) == 0
    plain = capsys.readouterr().out
    assert main(argv + [
        "--ledger", str(tmp_path / "ledger.jsonl"),
        "--trace-out", str(tmp_path / "trace.json"),
        "--metrics-out", str(tmp_path / "metrics.prom"),
    ]) == 0
    observed = capsys.readouterr().out
    assert observed == plain


def test_cli_single_ledger_across_serve_and_runs(tmp_path, capsys):
    """Acceptance: a full serve session plus acc and speed runs append
    to ONE ledger; tools/check_ledger.py validates it and `cli stats`
    aggregates it."""
    led = str(tmp_path / "ledger.jsonl")
    store = str(tmp_path / "store")
    # serve session (cold + duplicate + control lines)
    reqs = tmp_path / "reqs.jsonl"
    resps = tmp_path / "resps.jsonl"
    reqs.write_text("\n".join([
        json.dumps({"id": "a", "model": "gemm", "n": 16,
                    "engine": "oracle"}),
        json.dumps({"id": "dup", "model": "gemm", "n": 16,
                    "engine": "oracle"}),
        json.dumps({"id": "s", "type": "stats"}),
    ]) + "\n")
    assert main([
        "serve", "--requests", str(reqs), "--responses", str(resps),
        "--cache-dir", store, "--ledger", led,
    ]) == 0
    # the stats response's ledger tail points into the same file
    stats_line = json.loads(resps.read_text().splitlines()[-1])
    assert stats_line["stats"]["ledger"] == led
    # direct acc run + service speed run into the same ledger
    assert main([
        "acc", "--model", "gemm", "--n", "16", "--engine", "exact",
        "--ledger", led,
    ]) == 0
    assert main([
        "speed", "--model", "gemm", "--n", "16", "--engine", "oracle",
        "--reps", "2", "--cache-dir", store, "--ledger", led,
    ]) == 0
    capsys.readouterr()

    rows = ledger.read_rows(led)
    entries = list(ledger.iter_rows(led))
    assert len(rows) == len(entries)  # every line valid
    sources = {r["source"] for r in rows}
    assert sources == {"service", "cli"}
    # serve wrote one row per EXECUTION (the duplicate coalesced or
    # hit the memory tier, either way at most one engine execution)
    serve_rows = [r for r in rows if r["source"] == "service"]
    assert len(serve_rows) >= 1
    assert all(len(r["fingerprint"]) == 64 for r in rows
               if r["fingerprint"])
    # direct and served runs join on digest fields
    cli_rows = [r for r in rows if r["source"] == "cli"]
    assert cli_rows and cli_rows[0]["mrc_digest"]

    assert check_ledger.main([led]) == 0
    out = capsys.readouterr().out
    assert f"{len(rows)} valid, 0 invalid" in out

    assert main(["stats", "--ledger", led]) == 0
    stats_out = capsys.readouterr().out
    assert "ledger:" in stats_out
    assert "oracle" in stats_out and "exact" in stats_out


def test_cli_stats_flag_validation(tmp_path):
    with pytest.raises(SystemExit):
        main(["stats"])  # needs --ledger
    with pytest.raises(SystemExit):
        main(["stats", "--ledger", str(tmp_path / "absent.jsonl")])
    with pytest.raises(SystemExit):
        main(["trace", "--ledger", str(tmp_path / "l.jsonl")])


def test_check_ledger_gc_compacts(tmp_path, capsys):
    led = str(tmp_path / "ledger.jsonl")
    for i in range(4):
        ledger.append(led, _request_row(latency_s=float(i)))
    with open(led, "a") as f:
        f.write("{torn\n")
    old = _request_row()
    old["ledger_version"] = 1
    old["ts"] = time.time() - 10 * 86400
    with open(led, "a") as f:
        f.write(json.dumps(old) + "\n")
    assert check_ledger.main([led, "--max-age-days", "1"]) == 1
    err = capsys.readouterr().err
    assert "INVALID" in err and "stale" in err
    assert check_ledger.main(
        [led, "--max-age-days", "1", "--max-rows", "3", "--gc"]
    ) == 0
    capsys.readouterr()
    rows = ledger.read_rows(led)
    assert len(rows) == 3  # newest 3 of the 4 fresh rows
    assert [r["latency_s"] for r in rows] == [1.0, 2.0, 3.0]
    assert check_ledger.main([led]) == 0
    assert check_ledger.main([str(tmp_path / "absent")]) == 1


# -- drift monitoring -------------------------------------------------


def test_drift_audit_records_ledger_row(tmp_path):
    led = str(tmp_path / "ledger.jsonl")
    tele = telemetry.enable()
    row = drift.drift_audit(
        "gemm", n=24,
        thresholds={"max_abs_delta": 1.0, "mean_abs_delta": 1.0},
        ledger_path=led,
    )
    telemetry.disable()
    assert row["ok"] and not row["breach"]
    assert row["kind"] == "drift"
    assert 0 <= row["max_abs_delta"] <= 1.0
    assert 0 <= row["mean_abs_delta"] <= row["max_abs_delta"]
    assert row["support"] > 0
    assert len(row["mrc_digest_exact"]) == 16
    assert len(row["mrc_digest_sampled"]) == 16
    assert row["mrc_digest_exact"] != row["mrc_digest_sampled"]
    stored = ledger.read_rows(led)
    assert len(stored) == 1 and stored[0]["model"] == "gemm"
    assert stored[0]["engine_exact"] in (
        "periodic", "analytic", "dense"
    )
    # the audit ran under the active telemetry run
    assert tele.find_spans("drift_audit")
    assert not tele.counters.get("drift_breach")


def test_drift_breach_flags_telemetry_and_exit(tmp_path):
    led = str(tmp_path / "ledger.jsonl")
    tele = telemetry.enable()
    row = drift.drift_audit(
        "gemm", n=24,
        thresholds={"max_abs_delta": 1e-6, "mean_abs_delta": 1e-6},
        ledger_path=led,
    )
    telemetry.disable()
    assert row["breach"] and not row["ok"]
    assert tele.counters["drift_breach"] == 1
    events = [e for e in tele.events if e["name"] == "drift_breach"]
    assert events and events[0]["model"] == "gemm"
    assert ledger.read_rows(led)[0]["breach"] is True
    # the gate turns the breach into a nonzero exit
    assert check_drift.main(
        ["--models", "gemm", "--n", "24", "--max-abs", "1e-6"]
    ) == 1


def test_check_drift_gate_passes_gemm_and_non_gemm(tmp_path, capsys):
    """Acceptance: the drift gate passes with DEFAULT thresholds on
    gemm plus a non-gemm model, with the metrics recorded in the
    ledger."""
    led = str(tmp_path / "ledger.jsonl")
    assert check_drift.main(
        ["--models", "gemm,mvt", "--n", "24", "--ledger", led]
    ) == 0
    out = capsys.readouterr().out
    assert "gemm" in out and "mvt" in out and "BREACH" not in out
    rows = ledger.read_rows(led)
    assert [r["model"] for r in rows] == ["gemm", "mvt"]
    assert all(r["kind"] == "drift" and r["ok"] for r in rows)
    assert all(
        r["max_abs_delta"] <= drift.DRIFT_THRESHOLDS["max_abs_delta"]
        for r in rows
    )
    assert check_ledger.main([led]) == 0


# -- bench ledger row shape (bench.py appends this) -------------------


def test_bench_row_shape_validates_and_aggregates(tmp_path):
    """The row bench.py appends (kind='bench' with the headline
    metric + MRC digest) is schema-valid and lands in the stats
    aggregate, so BENCH evidence and the ledger cross-reference."""
    led = str(tmp_path / "ledger.jsonl")
    ledger.append(led, {
        "kind": "bench", "source": "bench", "ok": True,
        "metric": "gemm4096_sampled_throughput", "value": 1.2e8,
        "unit": "samples/s/chip", "vs_baseline": 40.0,
        "engine": "sampled", "model": "gemm", "n": 4096,
        "latency_s": 2.2, "device": "cpu",
        "mrc_l1_err": 0.001, "mrc_digest": "ab" * 8,
    })
    agg = ledger.aggregate(ledger.read_rows(led))
    assert agg["bench_rows"] == 1
    assert check_ledger.main([led]) == 0
