"""Serial dict-walk oracle vs vectorized numpy sampler: bit-exact parity."""

import pytest

from pluss_sampler_optimization_tpu.config import MachineConfig
from pluss_sampler_optimization_tpu.models import (
    adi,
    atax,
    bicg,
    covariance,
    doitgen,
    fdtd2d,
    gemm,
    gemver,
    gesummv,
    heat3d,
    jacobi2d,
    mm2,
    mm3,
    mvt,
    syrk_rect,
    syrk_tri,
    trisolv,
    trmm,
)
from pluss_sampler_optimization_tpu.oracle import run_numpy, run_serial

PROGRAMS = [
    gemm(8),
    gemm(12),
    gemm(13),  # short last chunk
    gemm(16),
    mm2(8),
    mm3(6),
    syrk_rect(8),
    jacobi2d(10, tsteps=2),
    mvt(16),
    bicg(13, 17),  # rectangular + short last chunk
    gesummv(16),
    atax(13, 9),  # interchanged y-update, written share tmp
    gemver(12),  # four nests of mixed depth over one shared A
    doitgen(3, 4, 8),  # collapsed (r,q) parallel loop
    fdtd2d(10, 9, tsteps=2),  # constant ref, boundary starts
    heat3d(9),  # 3-coefficient refs
    syrk_tri(9),  # ascending triangular inner level
    syrk_tri(13, 7),
    trmm(9),  # descending triangular + post after triangular subloop
    trmm(8, 11),
    trisolv(13),  # zero-trip first iterations, diagonal ref
    covariance(9, 7),  # mixed rectangular + triangular nests
    adi(9, tsteps=2),  # descending (step -1) inner loops
]


def assert_states_equal(a, b):
    assert len(a.noshare) == len(b.noshare)
    for t, (ha, hb) in enumerate(zip(a.noshare, b.noshare)):
        assert ha == hb, f"noshare mismatch tid={t}"
    for t, (sa, sb) in enumerate(zip(a.share, b.share)):
        assert sa == sb, f"share mismatch tid={t}"


@pytest.mark.parametrize("program", PROGRAMS, ids=lambda p: p.name)
def test_numpy_matches_serial(program):
    machine = MachineConfig()
    ser = run_serial(program, machine)
    vec = run_numpy(program, machine)
    assert ser.total_accesses == vec.total_accesses
    assert ser.per_tid_accesses == vec.per_tid_accesses
    assert_states_equal(ser.state, vec.state)


def test_gemm_share_present():
    """B0 must produce share-classified reuses once N is large enough."""
    machine = MachineConfig()
    res = run_serial(gemm(16), machine)
    total_share = sum(
        sum(h.values()) for per in res.state.share for h in per.values()
    )
    assert total_share > 0
    # share ratio recorded at THREAD_NUM-1 (...ri-omp-seq.cpp:204)
    for per in res.state.share:
        for ratio in per:
            assert ratio == machine.thread_num - 1


def test_total_accesses_formula():
    res = run_serial(gemm(12), MachineConfig())
    assert res.total_accesses == 4 * 12**3 + 2 * 12**2


def test_per_nest_lat_flush():
    """Reuse must not cross a parallel-nest boundary: the reference
    flushes -1 and clears LAT after every parallel loop
    (...ri-omp-seq.cpp:303-319). Two identical nests touching the same
    array must yield twice the cold lines and no cross-nest reuses."""
    from pluss_sampler_optimization_tpu.ir import Loop, ParallelNest, Program, Ref

    n = 8
    nest = ParallelNest(
        loops=(Loop(n), Loop(n)),
        refs=(Ref("A0", "A", level=1, coeffs=(n, 1)),),
    )
    two = Program(name="twice", nests=(nest, nest))
    machine = MachineConfig()
    res = run_serial(two, machine)
    vec = run_numpy(two, machine)
    assert_states_equal(res.state, vec.state)
    # n=8, chunk=4: 2 chunks -> only tids 0 and 1 run, 4 rows each.
    # One row (8 doubles) = 1 line, touched 8x consecutively -> 7 reuses
    # of interval 1 per row per nest; 4 cold lines per nest per tid.
    # Were LAT carried across nests, nest 2's rows would be interval-~64
    # reuses instead of cold.
    for t in (0, 1):
        h = res.state.noshare[t]
        assert set(h) == {1, -1}
        assert h[-1] == 4 * 2  # 4 lines per nest x 2 nests
        assert h[1] == 7 * 4 * 2
    for t in (2, 3):
        assert res.state.noshare[t] == {}


def test_triangular_odd_machine_serial_numpy():
    from pluss_sampler_optimization_tpu.models import trisolv, trmm

    for m in (MachineConfig(thread_num=3, chunk_size=5),
              MachineConfig(thread_num=6, chunk_size=1)):
        for prog in (trmm(8, 6), trisolv(17)):
            ser = run_serial(prog, m)
            vec = run_numpy(prog, m)
            assert ser.total_accesses == vec.total_accesses
            assert_states_equal(ser.state, vec.state)
