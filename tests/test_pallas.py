"""Pallas pow2-histogram kernel vs the portable exp_hist (interpret
mode on CPU; the same kernel compiles for TPU via pow2_hist_auto)."""

import jax.numpy as jnp
import numpy as np
import pytest

from pluss_sampler_optimization_tpu.ops.histogram import exp_hist
from pluss_sampler_optimization_tpu.ops.pallas_hist import pow2_hist


@pytest.mark.parametrize("n", [1, 100, 1024, 5000])
def test_pallas_hist_matches_exp_hist(n):
    rng = np.random.default_rng(n)
    exp = rng.integers(0, 62, size=n)
    vals = (1 << exp.astype(np.int64)) + rng.integers(0, 1 << 20, size=n)
    vals = np.minimum(np.maximum(vals, 1), (1 << 62) - 1)
    w = rng.integers(0, 2, size=n)
    ref = exp_hist(jnp.asarray(vals), jnp.asarray(w))
    got = pow2_hist(jnp.asarray(vals), jnp.asarray(w), interpret=True)
    np.testing.assert_array_equal(np.asarray(ref), np.asarray(got))


@pytest.mark.parametrize("n", [100, 5000])
def test_pallas_hist_additive_weights(n):
    """Weights are additive multiplicities, not a mask: counts > 1 per
    element must accumulate (distinguishes the kernel from w > 0)."""
    rng = np.random.default_rng(n + 7)
    vals = rng.integers(1, 1 << 40, size=n)
    w = rng.integers(0, 5, size=n)
    ref = exp_hist(jnp.asarray(vals), jnp.asarray(w))
    got = pow2_hist(jnp.asarray(vals), jnp.asarray(w), interpret=True)
    np.testing.assert_array_equal(np.asarray(ref), np.asarray(got))


def test_pallas_hist_boundary_values():
    vals = np.array(
        [1, 2, 3, 4, (1 << 31) - 1, 1 << 31, (1 << 32) - 1, 1 << 32,
         (1 << 32) + 1, (1 << 62) - 1, 1 << 40],
        dtype=np.int64,
    )
    w = np.ones(len(vals), dtype=np.int64)
    ref = exp_hist(jnp.asarray(vals), jnp.asarray(w))
    got = pow2_hist(jnp.asarray(vals), jnp.asarray(w), interpret=True)
    np.testing.assert_array_equal(np.asarray(ref), np.asarray(got))


def test_pallas_hist_all_masked():
    vals = np.ones(300, dtype=np.int64)
    got = pow2_hist(
        jnp.asarray(vals), jnp.zeros(300, dtype=jnp.int64), interpret=True
    )
    assert int(np.asarray(got).sum()) == 0
