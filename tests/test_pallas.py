"""Pallas pow2-histogram kernel vs the portable exp_hist (interpret
mode on CPU; the same kernel compiles for TPU via pow2_hist_auto),
the per-call weight-total overflow guard, and engine-level parity of
the fused draw+classify+histogram backends (pallas interpret / native
vs the xla oracle)."""

import dataclasses as dc

import jax.numpy as jnp
import numpy as np
import pytest

from pluss_sampler_optimization_tpu import native
from pluss_sampler_optimization_tpu.config import (
    MachineConfig,
    SamplerConfig,
)
from pluss_sampler_optimization_tpu.frontend.fuzz import (
    _fold_mrc,
    _states_equal,
)
from pluss_sampler_optimization_tpu.ir import (
    Loop,
    ParallelNest,
    Program,
    Ref,
)
from pluss_sampler_optimization_tpu.models import REGISTRY
from pluss_sampler_optimization_tpu.ops.histogram import exp_hist
from pluss_sampler_optimization_tpu.ops.pallas_hist import pow2_hist
from pluss_sampler_optimization_tpu.sampler.sampled import run_sampled


@pytest.mark.parametrize("n", [1, 100, 1024, 5000])
def test_pallas_hist_matches_exp_hist(n):
    rng = np.random.default_rng(n)
    exp = rng.integers(0, 62, size=n)
    vals = (1 << exp.astype(np.int64)) + rng.integers(0, 1 << 20, size=n)
    vals = np.minimum(np.maximum(vals, 1), (1 << 62) - 1)
    w = rng.integers(0, 2, size=n)
    ref = exp_hist(jnp.asarray(vals), jnp.asarray(w))
    got = pow2_hist(jnp.asarray(vals), jnp.asarray(w), interpret=True)
    np.testing.assert_array_equal(np.asarray(ref), np.asarray(got))


@pytest.mark.parametrize("n", [100, 5000])
def test_pallas_hist_additive_weights(n):
    """Weights are additive multiplicities, not a mask: counts > 1 per
    element must accumulate (distinguishes the kernel from w > 0)."""
    rng = np.random.default_rng(n + 7)
    vals = rng.integers(1, 1 << 40, size=n)
    w = rng.integers(0, 5, size=n)
    ref = exp_hist(jnp.asarray(vals), jnp.asarray(w))
    got = pow2_hist(jnp.asarray(vals), jnp.asarray(w), interpret=True)
    np.testing.assert_array_equal(np.asarray(ref), np.asarray(got))


def test_pallas_hist_boundary_values():
    vals = np.array(
        [1, 2, 3, 4, (1 << 31) - 1, 1 << 31, (1 << 32) - 1, 1 << 32,
         (1 << 32) + 1, (1 << 62) - 1, 1 << 40],
        dtype=np.int64,
    )
    w = np.ones(len(vals), dtype=np.int64)
    ref = exp_hist(jnp.asarray(vals), jnp.asarray(w))
    got = pow2_hist(jnp.asarray(vals), jnp.asarray(w), interpret=True)
    np.testing.assert_array_equal(np.asarray(ref), np.asarray(got))


def test_pallas_hist_all_masked():
    vals = np.ones(300, dtype=np.int64)
    got = pow2_hist(
        jnp.asarray(vals), jnp.zeros(300, dtype=jnp.int64), interpret=True
    )
    assert int(np.asarray(got).sum()) == 0


def test_pow2_hist_weight_total_overflow_boundary():
    """Regression: a per-call weight total of exactly 2^31 must take
    the widened path and stay exact. Two heavy entries land in the
    SAME lane (elements 0 and 128 of the (rows, 128) layout), so the
    fast path's int32 per-lane partial would wrap to negative — the
    forced-fast run below documents exactly the hazard the auto guard
    exists for."""
    n = 1024  # one full (8, 128) block
    vals = np.full(n, 1 << 10, dtype=np.int64)
    w = np.zeros(n, dtype=np.int64)
    w[0] = 1 << 30
    w[128] = 1 << 30  # same lane as element 0
    expect = np.zeros(64, dtype=np.int64)
    expect[10] = 1 << 31

    got = pow2_hist(jnp.asarray(vals), jnp.asarray(w), interpret=True)
    np.testing.assert_array_equal(np.asarray(got), expect)

    wrapped = pow2_hist(jnp.asarray(vals), jnp.asarray(w),
                        interpret=True, widen=False)
    assert int(np.asarray(wrapped)[10]) < 0  # int32 partial wrapped

    # one below the boundary the fast path is still exact (and is
    # what auto picks), pinning the guard's threshold from both sides
    w[128] -= 1
    expect[10] -= 1
    near = pow2_hist(jnp.asarray(vals), jnp.asarray(w), interpret=True)
    np.testing.assert_array_equal(np.asarray(near), expect)
    fast = pow2_hist(jnp.asarray(vals), jnp.asarray(w),
                     interpret=True, widen=False)
    np.testing.assert_array_equal(np.asarray(fast), expect)


def test_pow2_hist_widen_explicit_matches_exp_hist():
    """The widened path (16-bit weight planes + super-chunked grid)
    is exact over ordinary inputs too, not just at the boundary."""
    rng = np.random.default_rng(19)
    vals = rng.integers(1, 1 << 40, size=700)
    w = rng.integers(0, 1 << 20, size=700)
    ref = exp_hist(jnp.asarray(vals), jnp.asarray(w))
    got = pow2_hist(jnp.asarray(vals), jnp.asarray(w),
                    interpret=True, widen=True)
    np.testing.assert_array_equal(np.asarray(ref), np.asarray(got))


# --- engine-level kernel_backend parity ------------------------------
#
# The fused pallas draw+classify+histogram kernel compiles one
# pallas_call per ref; in interpret mode XLA compiles the resulting
# HLO from scratch, which costs tens of seconds per ref on CPU. The
# tier-1 parity pin therefore runs the smallest program that still
# exercises both kernel forms (a noshare-only ref and a share ref):
# larger models ride tools/fuzz_ir.py --kernel-backend and the slow
# marker, not tier-1.

_MINI = Program(
    name="parity-mini",
    nests=(ParallelNest(
        loops=(Loop(8), Loop(8)),
        refs=(Ref("A0", "A", level=1, coeffs=(8, 1)),
              Ref("B0", "B", level=1, coeffs=(0, 1),
                  share_threshold=9)),
    ),),
)


def _assert_backend_parity(program, backend):
    machine = MachineConfig()
    cfg = SamplerConfig(ratio=0.3, seed=3)
    state_x, _ = run_sampled(
        program, machine, dc.replace(cfg, kernel_backend="xla"))
    state_b, _ = run_sampled(
        program, machine, dc.replace(cfg, kernel_backend=backend))
    assert _states_equal(state_b, state_x, machine.thread_num)
    assert (_fold_mrc(state_b, machine).tobytes()
            == _fold_mrc(state_x, machine).tobytes())


def test_engine_pallas_parity_interpret():
    """run_sampled(kernel_backend="pallas") folds bit-identical to the
    xla oracle (interpret mode on this CPU host)."""
    _assert_backend_parity(_MINI, "pallas")


def test_engine_native_parity():
    """run_sampled(kernel_backend="native") folds bit-identical to the
    xla oracle on a real (small) model."""
    if not native.available():
        pytest.skip("native runtime unavailable on this host")
    _assert_backend_parity(REGISTRY["gemm"](16), "native")
