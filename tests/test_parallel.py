"""Multi-chip layer: sharded engines must equal the single-device ones.

Runs on the virtual 8-device CPU platform (conftest.py), the same
configuration the driver's dryrun uses.
"""

import numpy as np
import pytest

import jax

from pluss_sampler_optimization_tpu import MachineConfig, SamplerConfig
from pluss_sampler_optimization_tpu.models.gemm import gemm
from pluss_sampler_optimization_tpu.models.mm2 import mm2
from pluss_sampler_optimization_tpu.parallel import (
    build_mesh,
    run_dense_sharded,
    run_sampled_sharded,
    sampled_outputs_sharded,
)
from pluss_sampler_optimization_tpu.runtime.hist import pow2_floor
from pluss_sampler_optimization_tpu.sampler.dense import run_dense
from pluss_sampler_optimization_tpu.sampler.sampled import (
    run_sampled,
    sampled_outputs,
)

MACHINE = MachineConfig()


def _states_equal(a, b):
    assert len(a.noshare) == len(b.noshare)
    for ha, hb in zip(a.noshare, b.noshare):
        assert ha == hb
    for sa, sb in zip(a.share, b.share):
        assert set(sa) == set(sb)
        for ratio in sa:
            assert sa[ratio] == sb[ratio]


def test_eight_virtual_devices():
    assert len(jax.devices()) == 8


@pytest.mark.parametrize("n_dev", [1, 2, 8])
def test_sampled_sharded_matches_unsharded(n_dev):
    prog = gemm(16)
    cfg = SamplerConfig(ratio=0.25, seed=3)
    mesh = build_mesh(n_dev)
    state_ref, results_ref = run_sampled(prog, MACHINE, cfg)
    state_sh, results_sh = run_sampled_sharded(prog, MACHINE, cfg, mesh)
    _states_equal(state_ref, state_sh)
    for ra, rb in zip(results_ref, results_sh):
        assert ra.name == rb.name
        assert ra.noshare == rb.noshare
        assert ra.share == rb.share
        assert ra.cold == rb.cold


def test_sampled_sharded_multinest(eight=8):
    prog = mm2(8)
    cfg = SamplerConfig(ratio=0.5, seed=1)
    state_ref, _ = run_sampled(prog, MACHINE, cfg)
    state_sh, _ = run_sampled_sharded(prog, MACHINE, cfg, build_mesh(eight))
    _states_equal(state_ref, state_sh)


def test_dense_psum_histogram_matches_exact_pairs():
    """The psum'd dense noshare histogram must agree with the exact
    sparse pairs after pow2 binning."""
    prog = gemm(16)
    cfg = SamplerConfig(ratio=0.25, seed=3)
    exact = sampled_outputs(prog, MACHINE, cfg)
    _, dense = sampled_outputs_sharded(
        prog, MACHINE, cfg, mesh=build_mesh(8)
    )
    for r, nh in zip(exact, dense):
        from_pairs = {}
        for ri_val, cnt in r.noshare.items():
            k = pow2_floor(max(int(ri_val), 1))
            from_pairs[k] = from_pairs.get(k, 0) + int(cnt)
        from_dense = {
            1 << e: int(c) for e, c in enumerate(nh) if c > 0
        }
        assert from_pairs == from_dense


@pytest.mark.parametrize("n_dev", [2, 4])
def test_dense_sharded_matches_unsharded(n_dev):
    prog = gemm(12)
    ref = run_dense(prog, MACHINE)
    sh = run_dense_sharded(prog, MACHINE, mesh=build_mesh(n_dev))
    assert ref.total_accesses == sh.total_accesses
    assert ref.per_tid_accesses == sh.per_tid_accesses
    _states_equal(ref.state, sh.state)


def test_dense_sharded_rejects_bad_mesh():
    with pytest.raises(ValueError):
        run_dense_sharded(gemm(8), MACHINE, mesh=build_mesh(3))


def test_sharded_capacity_overflow_recovers():
    """The mesh path regrows per-device pair capacity like the
    single-device engine instead of aborting."""
    from pluss_sampler_optimization_tpu.config import SamplerConfig
    from pluss_sampler_optimization_tpu.models import gemm
    from pluss_sampler_optimization_tpu.parallel import (
        build_mesh,
        run_sampled_sharded,
    )
    from pluss_sampler_optimization_tpu.sampler.sampled import run_sampled

    cfg = SamplerConfig(ratio=0.4, seed=11)
    mesh = build_mesh(devices=jax.devices()[:2])
    _, small = run_sampled_sharded(gemm(16), MACHINE, cfg, mesh, capacity=2)
    _, big = run_sampled(gemm(16), MACHINE, cfg, capacity=4096)
    for a, b in zip(small, big):
        assert a.name == b.name and a.noshare == b.noshare
        assert a.share == b.share and a.cold == b.cold


def test_sampled_sharded_triangular_matches_unsharded():
    from pluss_sampler_optimization_tpu.models import syrk_tri
    from pluss_sampler_optimization_tpu.parallel import (
        build_mesh,
        run_sampled_sharded,
    )
    from pluss_sampler_optimization_tpu.sampler.sampled import run_sampled

    machine = MachineConfig()
    cfg = SamplerConfig(ratio=0.4, seed=5)
    prog = syrk_tri(12)
    _, unsh = run_sampled(prog, machine, cfg)
    _, sh = run_sampled_sharded(prog, machine, cfg, build_mesh(4))
    for a, b in zip(unsh, sh):
        assert a.name == b.name
        assert a.noshare == b.noshare
        assert a.share == b.share
        assert a.cold == b.cold
