"""Multi-chip layer: sharded engines must equal the single-device ones.

Runs on the virtual 8-device CPU platform (conftest.py), the same
configuration the driver's dryrun uses.
"""

import numpy as np
import pytest

import jax

from pluss_sampler_optimization_tpu import MachineConfig, SamplerConfig
from pluss_sampler_optimization_tpu.models.gemm import gemm
from pluss_sampler_optimization_tpu.models.mm2 import mm2
from pluss_sampler_optimization_tpu.parallel import (
    build_mesh,
    run_dense_sharded,
    run_sampled_sharded,
    sampled_outputs_sharded,
)
from pluss_sampler_optimization_tpu.runtime.hist import pow2_floor
from pluss_sampler_optimization_tpu.sampler.dense import run_dense
from pluss_sampler_optimization_tpu.sampler.sampled import (
    run_sampled,
    sampled_outputs,
)

MACHINE = MachineConfig()


def _states_equal(a, b):
    assert len(a.noshare) == len(b.noshare)
    for ha, hb in zip(a.noshare, b.noshare):
        assert ha == hb
    for sa, sb in zip(a.share, b.share):
        assert set(sa) == set(sb)
        for ratio in sa:
            assert sa[ratio] == sb[ratio]


def test_eight_virtual_devices():
    assert len(jax.devices()) == 8


@pytest.mark.parametrize("n_dev", [1, 2, 8])
def test_sampled_sharded_matches_unsharded(n_dev):
    prog = gemm(16)
    cfg = SamplerConfig(ratio=0.25, seed=3)
    mesh = build_mesh(n_dev)
    state_ref, results_ref = run_sampled(prog, MACHINE, cfg)
    state_sh, results_sh = run_sampled_sharded(prog, MACHINE, cfg, mesh)
    _states_equal(state_ref, state_sh)
    for ra, rb in zip(results_ref, results_sh):
        assert ra.name == rb.name
        assert ra.noshare == rb.noshare
        assert ra.share == rb.share
        assert ra.cold == rb.cold


def test_sampled_sharded_multinest(eight=8):
    prog = mm2(8)
    cfg = SamplerConfig(ratio=0.5, seed=1)
    state_ref, _ = run_sampled(prog, MACHINE, cfg)
    state_sh, _ = run_sampled_sharded(prog, MACHINE, cfg, build_mesh(eight))
    _states_equal(state_ref, state_sh)


def test_sampled_sharded_device_draw_nondividing_mesh_raises():
    """Explicit device_draw=True with a mesh size that does not divide
    the batch must raise, not silently sample the host stream (which
    would break bit-identity with run_sampled)."""
    cfg = SamplerConfig(ratio=0.25, seed=3, device_draw=True)
    with pytest.raises(ValueError, match="dividing the batch"):
        run_sampled_sharded(gemm(16), MACHINE, cfg, build_mesh(3))


def test_sampled_sharded_auto_draw_nondividing_mesh_warns(monkeypatch):
    """The auto default (device_draw=None) on a non-dividing mesh
    downgrades to the host draw stream — visibly: a warning flags the
    cross-engine bit-identity loss instead of a silent divergence.
    On CPU backends the auto default already resolves to the host
    stream before the divisibility check, so force the accelerator
    resolution path by patching the backend probe."""
    import warnings as _w

    from pluss_sampler_optimization_tpu.parallel import sharded as SH

    cfg = SamplerConfig(ratio=0.25, seed=3, device_draw=None)
    monkeypatch.setattr(
        SH, "_use_device_draw",
        lambda c: True if c.device_draw is None else c.device_draw,
    )
    with _w.catch_warnings(record=True) as rec:
        _w.simplefilter("always")
        run_sampled_sharded(gemm(16), MACHINE, cfg, build_mesh(3))
    assert any("downgrades to the host draw" in str(r.message)
               for r in rec)


@pytest.mark.parametrize("n_dev", [2, 8])
def test_sampled_sharded_device_draw_matches_unsharded(n_dev):
    """Device-drawn samples through the mesh: same threefry stream as
    the single-device device path (same seed + batch bucketing), exact
    merges — bit-identical across mesh sizes."""
    prog = gemm(16)
    cfg = SamplerConfig(ratio=0.25, seed=3, device_draw=True)
    state_ref, results_ref = run_sampled(prog, MACHINE, cfg)
    state_sh, results_sh = run_sampled_sharded(
        prog, MACHINE, cfg, build_mesh(n_dev)
    )
    _states_equal(state_ref, state_sh)
    for ra, rb in zip(results_ref, results_sh):
        assert ra.name == rb.name
        assert ra.noshare == rb.noshare
        assert ra.share == rb.share
        assert ra.cold == rb.cold
        assert ra.n_samples == rb.n_samples


def test_dense_psum_histogram_matches_exact_pairs():
    """The psum'd dense noshare histogram must agree with the exact
    sparse pairs after pow2 binning."""
    prog = gemm(16)
    cfg = SamplerConfig(ratio=0.25, seed=3)
    exact = sampled_outputs(prog, MACHINE, cfg)
    _, dense = sampled_outputs_sharded(
        prog, MACHINE, cfg, mesh=build_mesh(8)
    )
    for r, nh in zip(exact, dense):
        from_pairs = {}
        for ri_val, cnt in r.noshare.items():
            k = pow2_floor(max(int(ri_val), 1))
            from_pairs[k] = from_pairs.get(k, 0) + int(cnt)
        from_dense = {
            1 << e: int(c) for e, c in enumerate(nh) if c > 0
        }
        assert from_pairs == from_dense


@pytest.mark.parametrize("n_dev", [2, 4])
def test_dense_sharded_matches_unsharded(n_dev):
    prog = gemm(12)
    ref = run_dense(prog, MACHINE)
    sh = run_dense_sharded(prog, MACHINE, mesh=build_mesh(n_dev))
    assert ref.total_accesses == sh.total_accesses
    assert ref.per_tid_accesses == sh.per_tid_accesses
    _states_equal(ref.state, sh.state)


def test_dense_sharded_rejects_bad_mesh():
    with pytest.raises(ValueError):
        run_dense_sharded(gemm(8), MACHINE, mesh=build_mesh(3))


def test_sharded_capacity_overflow_recovers():
    """The mesh path regrows per-device pair capacity like the
    single-device engine instead of aborting."""
    from pluss_sampler_optimization_tpu.config import SamplerConfig
    from pluss_sampler_optimization_tpu.models import gemm
    from pluss_sampler_optimization_tpu.parallel import (
        build_mesh,
        run_sampled_sharded,
    )
    from pluss_sampler_optimization_tpu.sampler.sampled import run_sampled

    cfg = SamplerConfig(ratio=0.4, seed=11)
    mesh = build_mesh(devices=jax.devices()[:2])
    _, small = run_sampled_sharded(gemm(16), MACHINE, cfg, mesh, capacity=2)
    _, big = run_sampled(gemm(16), MACHINE, cfg, capacity=4096)
    for a, b in zip(small, big):
        assert a.name == b.name and a.noshare == b.noshare
        assert a.share == b.share and a.cold == b.cold


def test_sampled_sharded_triangular_matches_unsharded():
    from pluss_sampler_optimization_tpu.models import syrk_tri
    from pluss_sampler_optimization_tpu.parallel import (
        build_mesh,
        run_sampled_sharded,
    )
    from pluss_sampler_optimization_tpu.sampler.sampled import run_sampled

    machine = MachineConfig()
    cfg = SamplerConfig(ratio=0.4, seed=5)
    prog = syrk_tri(12)
    _, unsh = run_sampled(prog, machine, cfg)
    _, sh = run_sampled_sharded(prog, machine, cfg, build_mesh(4))
    for a, b in zip(unsh, sh):
        assert a.name == b.name
        assert a.noshare == b.noshare
        assert a.share == b.share
        assert a.cold == b.cold


@pytest.mark.parametrize("n_dev", [2, 8])
def test_periodic_sharded_matches_unsharded(n_dev):
    """Exact periodic engine with the merged-window axis over the
    mesh: bit-identical PRIState to the single-device loop (the
    vmapped window body is the same integer computation)."""
    from pluss_sampler_optimization_tpu.parallel import (
        run_periodic_sharded,
    )
    from pluss_sampler_optimization_tpu.sampler.periodic import (
        run_periodic,
    )

    prog = gemm(16)
    ref = run_periodic(prog, MACHINE)
    sh = run_periodic_sharded(prog, MACHINE, build_mesh(n_dev))
    assert ref.total_accesses == sh.total_accesses
    assert ref.per_tid_accesses == sh.per_tid_accesses
    _states_equal(ref.state, sh.state)


def test_periodic_sharded_multiphase_windows():
    """A non-pow2 stencil size produces multiple phase classes (more
    merged windows than devices on a small mesh — exercises padding
    and >1 window per device)."""
    from pluss_sampler_optimization_tpu.models import jacobi2d
    from pluss_sampler_optimization_tpu.parallel import (
        run_periodic_sharded,
    )
    from pluss_sampler_optimization_tpu.sampler.periodic import (
        run_periodic,
    )

    prog = jacobi2d(37)
    ref = run_periodic(prog, MACHINE)
    sh = run_periodic_sharded(prog, MACHINE, build_mesh(8))
    _states_equal(ref.state, sh.state)


@pytest.mark.parametrize("model_n", [("syrk_rect", 24), ("syrk_tri", 24)])
def test_analytic_sharded_matches_unsharded(model_n):
    """Analytic exact engine with its classify key axis GSPMD-sharded
    over the mesh: bit-identical to single-device. host_cutoff=0
    forces the engine path — at these sizes the default host-lexsort
    shortcut would leave no device dispatch to shard."""
    import pluss_sampler_optimization_tpu.models as models
    from pluss_sampler_optimization_tpu.parallel import (
        run_analytic_sharded,
    )
    from pluss_sampler_optimization_tpu.sampler.analytic import (
        run_analytic,
    )

    name, n = model_n
    prog = getattr(models, name)(n)
    ref = run_analytic(prog, MACHINE, batch=1 << 12, host_cutoff=0)
    sh = run_analytic_sharded(
        prog, MACHINE, build_mesh(8), batch=1 << 12, host_cutoff=0
    )
    assert ref.total_accesses == sh.total_accesses
    _states_equal(ref.state, sh.state)


def test_exact_sharded_router_matches_and_labels():
    """run_exact_sharded routes like run_exact (periodic for gemm,
    analytic for the periodic-rejected syrk family), labels the
    engine, and stays bit-identical to the unsharded router."""
    from pluss_sampler_optimization_tpu.models import syrk_rect, syrk_tri
    from pluss_sampler_optimization_tpu.parallel import run_exact_sharded
    from pluss_sampler_optimization_tpu.sampler.periodic import run_exact

    mesh = build_mesh(8)
    for prog, want in ((gemm(16), "periodic"),
                       (syrk_rect(16), "analytic"),
                       (syrk_tri(12), "analytic")):
        ref = run_exact(prog, MACHINE)
        sh = run_exact_sharded(prog, MACHINE, mesh)
        assert ref.engine == sh.engine == want
        assert ref.total_accesses == sh.total_accesses
        _states_equal(ref.state, sh.state)


def test_cli_shard_flag():
    """--shard runs the exact router mesh-sharded through the CLI and
    is rejected for engines without a sharded exact form."""
    from pluss_sampler_optimization_tpu.cli import main

    assert main(["acc", "--model", "syrk", "--n", "16",
                 "--engine", "exact", "--shard"]) == 0
    with pytest.raises(SystemExit, match="--shard applies"):
        main(["acc", "--model", "gemm", "--n", "8",
              "--engine", "dense", "--shard"])


def test_distributed_single_process_mesh():
    """initialize_distributed + build_global_mesh in the degenerate
    single-process setting. jax.distributed must come up before any
    backend initializes, so this runs in a fresh interpreter (the suite
    process already has the CPU backend live)."""
    import os
    import subprocess
    import sys

    import socket

    s = socket.socket()
    s.bind(("localhost", 0))
    port = s.getsockname()[1]
    s.close()
    script = f"""
import os
os.environ["XLA_FLAGS"] = (
    os.environ.get("XLA_FLAGS", "") + " --xla_force_host_platform_device_count=8"
)
os.environ["JAX_PLATFORMS"] = "cpu"
import jax
jax.config.update("jax_platforms", "cpu")
from pluss_sampler_optimization_tpu.models import gemm
from pluss_sampler_optimization_tpu.parallel import (
    build_global_mesh, initialize_distributed, run_sampled_sharded,
)
from pluss_sampler_optimization_tpu.config import MachineConfig, SamplerConfig
initialize_distributed("localhost:{port}", 1, 0)
initialize_distributed("localhost:{port}", 1, 0)  # idempotent
mesh = build_global_mesh()
assert mesh.devices.size == len(jax.devices()) == 8
state, results = run_sampled_sharded(
    gemm(16), MachineConfig(), SamplerConfig(ratio=0.3, seed=0), mesh
)
assert sum(r.n_samples for r in results) > 0
print("distributed-ok")
"""
    env = dict(os.environ)
    env["PYTHONPATH"] = os.pathsep.join(
        [os.path.dirname(os.path.dirname(os.path.abspath(__file__)))]
        + env.get("PYTHONPATH", "").split(os.pathsep)
    )
    proc = subprocess.run(
        [sys.executable, "-c", script], capture_output=True, text=True,
        timeout=300, env=env,
    )
    assert proc.returncode == 0, proc.stderr[-2000:]
    assert "distributed-ok" in proc.stdout


def test_two_process_multihost_matches_single():
    """A REAL 2-process run (jax.distributed over gloo, 4 virtual CPU
    devices per process, 8-device global mesh): both hosts must produce
    identical results, equal to the single-process sampled engine."""
    import json
    import os
    import socket
    import subprocess
    import sys

    from pluss_sampler_optimization_tpu.models import gemm
    from pluss_sampler_optimization_tpu.sampler.sampled import run_sampled

    s = socket.socket()
    s.bind(("localhost", 0))
    port = s.getsockname()[1]
    s.close()
    worker = os.path.join(os.path.dirname(__file__), "_mh_worker.py")
    env = dict(os.environ)
    env["PYTHONPATH"] = os.pathsep.join(
        [os.path.dirname(os.path.dirname(worker))]
        + env.get("PYTHONPATH", "").split(os.pathsep)
    )
    procs = [
        subprocess.Popen(
            [sys.executable, worker, f"localhost:{port}", "2", str(p)],
            stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True,
            env=env,
        )
        for p in range(2)
    ]
    outs = []
    for p, pr in enumerate(procs):
        o, e = pr.communicate(timeout=420)
        if pr.returncode != 0:
            # capability probe, not a pass: some CPU backends ship
            # without multiprocess collectives (gloo). Only that
            # specific inability skips; any other failure is real.
            markers = ("aren't implemented", "UNIMPLEMENTED",
                       "INVALID_ARGUMENT", "gloo")
            if any(m in e for m in markers):
                for other in procs:
                    if other.poll() is None:
                        other.kill()
                pytest.skip(
                    "multiprocess collectives unavailable on this "
                    f"backend: {e.strip().splitlines()[-1][-200:]}"
                )
            assert pr.returncode == 0, (p, e[-3000:])
        outs.append(o)
    def _per_host(tag):
        docs = [
            json.loads(
                [ln for ln in outs[p].splitlines()
                 if ln.startswith(f"{tag}{p}=")][0].split("=", 1)[1]
            )
            for p in range(2)
        ]
        assert docs[0] == docs[1], f"hosts disagree on {tag}"
        return docs[0]

    def _assert_matches(got, want):
        assert [g["name"] for g in got] == [r.name for r in want]
        for g, r in zip(got, want):
            assert {int(k): v for k, v in g["noshare"].items()} == r.noshare
            assert {
                int(k): {int(a): b for a, b in h.items()}
                for k, h in g["share"].items()
            } == r.share
            assert g["cold"] == r.cold and g["n"] == r.n_samples

    _, want = run_sampled(
        gemm(16), MachineConfig(), SamplerConfig(ratio=0.3, seed=0)
    )
    _assert_matches(_per_host("RESULT"), want)

    # device-drawn samples over the 2-host mesh: bit-identical to the
    # single-process device path (same threefry stream, exact merges)
    _, want_dev = run_sampled(
        gemm(16), MachineConfig(),
        SamplerConfig(ratio=0.3, seed=0, device_draw=True),
    )
    _assert_matches(_per_host("RESULTDEV"), want_dev)
