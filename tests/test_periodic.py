"""Periodic exact engine vs the numpy oracle: bit-exact parity, sound
rejection of nests where a reuse could skip a period, and the dense
engine's memory auto-route."""

import numpy as np
import pytest

from pluss_sampler_optimization_tpu import (
    Loop,
    MachineConfig,
    ParallelNest,
    Program,
    Ref,
)
from pluss_sampler_optimization_tpu.models import (
    gemm,
    heat3d,
    jacobi2d,
    mm2,
    mm3,
    mvt,
    syrk_rect,
    syrk_tri,
)
from pluss_sampler_optimization_tpu.oracle import run_numpy
from pluss_sampler_optimization_tpu.sampler.periodic import (
    run_periodic,
    validate_periodic,
)

PROGRAMS = [
    gemm(16),
    gemm(13),  # ragged: short last chunk
    gemm(32),
    mm2(8),
    mm3(6),
    jacobi2d(10, tsteps=2),
    heat3d(16),  # stencil union -> equal-c0 window tier
    mvt(16),  # transposed single ref -> exhaustive tier
]


def _assert_bit_exact(program, machine):
    a = run_numpy(program, machine)
    b = run_periodic(program, machine)
    P = machine.thread_num
    assert a.total_accesses == b.total_accesses
    for t in range(P):
        assert a.state.noshare[t] == b.state.noshare[t], (program.name, t)
        assert a.state.share[t] == b.state.share[t], (program.name, t)
    assert a.per_tid_accesses == b.per_tid_accesses


@pytest.mark.parametrize("program", PROGRAMS, ids=lambda p: p.name)
def test_periodic_matches_oracle(program):
    _assert_bit_exact(program, MachineConfig())


@pytest.mark.parametrize("threads,chunk", [(3, 5), (7, 3)],
                         ids=lambda v: str(v))
def test_periodic_odd_geometries(threads, chunk):
    # odd geometries change the in-tid period sequence (jump deltas,
    # ragged tails) — exactly what the signature decomposition models
    machine = MachineConfig(thread_num=threads, chunk_size=chunk)
    _assert_bit_exact(gemm(13), machine)
    _assert_bit_exact(mm2(8), machine)


def test_periodic_rejects_triangular():
    with pytest.raises(NotImplementedError, match="triangular"):
        validate_periodic(syrk_tri(9), MachineConfig())


def test_periodic_rejects_mixed_parallel_coefficients():
    """syrk's A[i][k] (c0=N) and A[j][k] (c0=0) share array A: the
    window histogram then depends on the absolute parallel value (the
    fixed ref re-touches the translating ref's row at a v0-dependent
    position), so representative-window scaling is unsound even though
    reuses never skip a period. Regression for a round-3 review
    finding: N=8 (one cache line per row) masked the divergence, N=10
    exposed it — the validator must reject every size."""
    for n in (8, 10, 16):
        with pytest.raises(NotImplementedError, match="mix parallel"):
            validate_periodic(syrk_rect(n), MachineConfig())


def test_periodic_rejects_period_skipping_reuse():
    """Two refs on one array whose windows touch a line several
    periods apart with nothing between: the exhaustive tier must
    reject (accepting would record a cold miss where the oracle
    records a long reuse)."""
    n = 16
    prog = Program(
        name="skipgap",
        nests=(
            ParallelNest(
                loops=(Loop(n), Loop(2)),
                refs=(
                    Ref("A0", "A", level=1, coeffs=(8, 1)),
                    Ref("A1", "A", level=1, coeffs=(8, 1), const=32),
                ),
            ),
        ),
    )
    with pytest.raises(NotImplementedError):
        validate_periodic(prog, MachineConfig())
    # and the oracle confirms the period-skipping reuse is real: on a
    # single simulated thread (where periods are consecutive), A1 at
    # period q and A0 at period q+4 touch the same line — raw distance
    # 13 accesses (4-period skip x 4 accesses/period - 3), pow2-binned
    # to 8, far beyond anything a two-period window could see. The
    # only shorter reuses in this model are the within-period distance
    # 2 pairs.
    one = MachineConfig(thread_num=1)
    with pytest.raises(NotImplementedError):
        validate_periodic(prog, one)
    res = run_numpy(prog, one)
    assert 8 in res.state.noshare[0], sorted(res.state.noshare[0])


def test_dense_auto_routes_past_memory_cliff(monkeypatch, capsys):
    """run_dense must reroute (not OOM) when the predicted sort
    working set exceeds available memory, and the routed result stays
    bit-identical."""
    from pluss_sampler_optimization_tpu.sampler import dense as D

    prog = gemm(16)
    machine = MachineConfig()
    want = run_numpy(prog, machine)
    monkeypatch.setattr(D, "_available_bytes", lambda: 1024)
    routed = D.run_dense(prog, machine)
    err = capsys.readouterr().err
    assert "routing to the periodic engine" in err
    for t in range(4):
        assert routed.state.noshare[t] == want.state.noshare[t]
        assert routed.state.share[t] == want.state.share[t]
    # a model the periodic engine rejects routes to stream instead
    tri = syrk_tri(9)
    want_tri = run_numpy(tri, machine)
    routed_tri = D.run_dense(tri, machine)
    err = capsys.readouterr().err
    assert "routing to the stream engine" in err
    for t in range(4):
        assert routed_tri.state.noshare[t] == want_tri.state.noshare[t]


def test_dense_bytes_estimate_scales():
    """The estimate must grow ~N^3 for GEMM and predict the recorded
    N=1024 cliff (>200 GB, BASELINE.md) while N=128 stays small."""
    from pluss_sampler_optimization_tpu.sampler.dense import (
        dense_bytes_estimate,
    )

    small = dense_bytes_estimate(gemm(128), MachineConfig())
    big = dense_bytes_estimate(gemm(1024), MachineConfig())
    assert small < 2e9
    assert big > 100e9
