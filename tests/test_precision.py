"""Progressive precision (ISSUE 20): confidence-bounded adaptive
sampling (sampler/sampled.py::run_sampled_progressive +
sampler/confidence.py), streamed partial results, and
deadline-graceful band degradation.

The acceptance invariants pinned here:

- PREFIX BIT-IDENTITY: a full-schedule progressive run folds the
  exact one-shot sample set — MRC bytes, per-ref sample counts and
  histograms identical to run_sampled at the same (ratio, seed) —
  and through the service the converged response carries the same
  fingerprint and digest as a plain sampled request (the progressive
  knobs live OUTSIDE the fingerprint, like fuse_refs).
- The bootstrap band is a pure function of (blocks, seed, round):
  same inputs => bit-equal band, no clock, no entropy
  (tools/lint_determinism.py lints the whole module).
- Streamed bands never widen round over round; a generous tolerance
  stops the schedule early and says so.
- Band-aware drift verdicts: rows carrying `band_width` breach on
  delta > band; band-less rows keep the global DRIFT_THRESHOLDS path
  byte-for-byte (the ledger-migration contract).
- Ledger schema v2 accepts the optional `rounds` / `band_width` /
  `converged` request columns and rejects malformed values.
- tools/check_precision.py (prefix identity, monotone bands,
  deadline mid-round -> exactly one partial_final, exact replay)
  passes from tier-1.
"""

import json
import os
import sys

import numpy as np
import pytest

from pluss_sampler_optimization_tpu import SamplerConfig
from pluss_sampler_optimization_tpu.config import MachineConfig
from pluss_sampler_optimization_tpu.models import build
from pluss_sampler_optimization_tpu.runtime.aet import aet_mrc
from pluss_sampler_optimization_tpu.runtime.cri import cri_distribute
from pluss_sampler_optimization_tpu.runtime.obs import (
    drift,
    ledger as obs_ledger,
)
from pluss_sampler_optimization_tpu.sampler import confidence
from pluss_sampler_optimization_tpu.sampler.sampled import (
    run_sampled,
    run_sampled_progressive,
)
from pluss_sampler_optimization_tpu.service import (
    AnalysisRequest,
    AnalysisService,
)

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, os.path.join(REPO_ROOT, "tools"))
import check_precision  # noqa: E402


# -- schedules ---------------------------------------------------------


def test_resolve_schedule_defaults_and_validation():
    assert confidence.resolve_schedule(SamplerConfig()) \
        == (0.125, 0.25, 0.5, 1.0)
    assert confidence.resolve_schedule(
        SamplerConfig(max_rounds=3)) == (0.25, 0.5, 1.0)
    assert confidence.resolve_schedule(
        SamplerConfig(max_rounds=1)) == (1.0,)
    assert confidence.resolve_schedule(
        SamplerConfig(round_schedule=(0.1, 1.0))) == (0.1, 1.0)
    for bad in ((), (0.5, 0.5, 1.0), (0.5, 0.25, 1.0),
                (0.0, 1.0), (0.25, 0.5)):
        with pytest.raises(ValueError):
            confidence.resolve_schedule(
                SamplerConfig(round_schedule=bad))


def test_round_counts_cumulative_and_final_pinned():
    assert confidence.round_counts(10, (0.25, 0.5, 1.0)) == [3, 5, 10]
    assert confidence.round_counts(1, (0.125, 0.25, 0.5, 1.0)) \
        == [1, 1, 1, 1]
    # final entry is ALWAYS the exact total — the bit-identity pin
    assert confidence.round_counts(7, (0.3, 1.0))[-1] == 7
    assert confidence.round_counts(0, (0.5, 1.0)) == [0, 0]


def test_block_bounds_partition_exactly():
    assert confidence.block_bounds(5, 5) == []
    assert confidence.block_bounds(0, 2, blocks=4) == [(0, 1), (1, 2)]
    bounds = confidence.block_bounds(3, 103, blocks=4)
    assert bounds[0][0] == 3 and bounds[-1][1] == 103
    assert all(a < b for a, b in bounds)
    assert all(b0[1] == b1[0] for b0, b1 in zip(bounds, bounds[1:]))


# -- bootstrap determinism --------------------------------------------


def _toy_blocks():
    return [
        [({2: 5.0, 4: 1.0}, {}, 1), ({3: 2.0}, {}, 0),
         ({1: 4.0}, {2: {5: 1.0}}, 2)],
        [({7: 3.0}, {}, 0), ({2: 1.0, 9: 2.0}, {}, 1)],
    ]


def test_resample_weights_replay_and_shape():
    blocks = _toy_blocks()
    w1 = confidence._resample_weights(blocks, seed=11, round_idx=2,
                                      replicate=3)
    w2 = confidence._resample_weights(blocks, seed=11, round_idx=2,
                                      replicate=3)
    assert w1 == w2  # pure function of (blocks, seed, round, rep)
    assert [len(m) for m in w1] == [3, 2]
    assert [sum(m) for m in w1] == [3, 2]  # with-replacement, n draws
    others = [
        confidence._resample_weights(blocks, seed=11, round_idx=2,
                                     replicate=r)
        for r in range(8)
    ]
    assert any(w != w1 for w in others)  # replicates actually differ


def test_bootstrap_band_deterministic_and_none_weight_exact():
    machine = MachineConfig()
    blocks = _toy_blocks()
    b1 = confidence.bootstrap_band(blocks, machine, seed=5,
                                   round_idx=1)
    b2 = confidence.bootstrap_band(blocks, machine, seed=5,
                                   round_idx=1)
    assert b1 == b2 and np.isfinite(b1) and b1 >= 0.0
    assert confidence.bootstrap_band([], machine, seed=5,
                                     round_idx=0) == float("inf")
    # weights=None folds the cumulative state exactly once per block
    st = confidence.fold_blocks(blocks, machine.thread_num, False)
    ones = [[1] * len(b) for b in blocks]
    st2 = confidence.fold_blocks(blocks, machine.thread_num, False,
                                 weights=ones)
    m1 = confidence.mrc_from_state(st, machine)
    m2 = confidence.mrc_from_state(st2, machine)
    assert np.array_equal(m1, m2)


# -- the engine: prefix bit-identity and early stop --------------------


def test_progressive_full_schedule_bit_identical_to_one_shot():
    program = build("gemm", 24)
    machine = MachineConfig()
    cfg = SamplerConfig(ratio=0.3, seed=3, max_rounds=3)
    bands = []
    state_p, results_p, info = run_sampled_progressive(
        program, machine, cfg,
        on_round=lambda i: bands.append(i["band_width"]),
    )
    state_o, results_o = run_sampled(program, machine, cfg)
    T = machine.thread_num
    mrc_p = aet_mrc(cri_distribute(state_p, T, T), machine)
    mrc_o = aet_mrc(cri_distribute(state_o, T, T), machine)
    assert np.array_equal(mrc_p, mrc_o)
    for rp, ro in zip(results_p, results_o):
        assert rp.n_samples == ro.n_samples
        assert rp.noshare == ro.noshare and rp.share == ro.share
    assert info["rounds"] == info["rounds_total"] == 3
    assert info["converged"] and info["stopped"] in (None, "converged")
    # streamed bands never widen
    assert all(b <= a for a, b in zip(bands, bands[1:]))


def test_progressive_generous_tolerance_stops_early():
    program = build("gemm", 24)
    machine = MachineConfig()
    cfg = SamplerConfig(ratio=0.3, seed=3, max_rounds=4,
                        tolerance=10.0)  # any band satisfies this
    _state, _results, info = run_sampled_progressive(
        program, machine, cfg,
    )
    assert info["converged"] and info["stopped"] == "converged"
    assert info["rounds"] == 1 < info["rounds_total"]
    assert info["band_width"] <= 10.0


def test_progressive_should_stop_mid_schedule():
    program = build("gemm", 24)
    machine = MachineConfig()
    cfg = SamplerConfig(ratio=0.3, seed=3, max_rounds=3,
                        tolerance=0.0)
    calls = []

    def stop():
        calls.append(1)
        return len(calls) >= 2  # allow round 0, stop before round 2

    _state, _results, info = run_sampled_progressive(
        program, machine, cfg, should_stop=stop,
    )
    assert info["stopped"] == "deadline" and not info["converged"]
    assert 1 <= info["rounds"] < info["rounds_total"]
    assert np.isfinite(info["band_width"])


# -- the service: out-of-fingerprint knobs, converged == one-shot ------


def test_service_converged_response_matches_plain_sampled():
    base = dict(model="gemm", n=16, engine="sampled", ratio=0.2,
                seed=41)
    with AnalysisService(cache_dir=None) as svc:
        plain = svc.result(svc.submit(AnalysisRequest(**base)))
    with AnalysisService(cache_dir=None) as svc:
        prog = svc.result(svc.submit(AnalysisRequest(
            **base, tolerance=0.0, max_rounds=3)))
    assert plain.ok and prog.ok
    # knobs are OUT of the fingerprint; the converged bytes match
    assert prog.fingerprint == plain.fingerprint
    assert prog.mrc_digest == plain.mrc_digest
    assert prog.converged and not prog.partial_final
    assert prog.rounds == 3 and prog.band_width is not None
    assert plain.rounds is None and plain.band_width is None
    assert not plain.degraded and not prog.degraded


# -- drift: band-aware verdicts + migration contract -------------------


def test_breach_verdict_band_aware_and_migration():
    metrics = {"max_abs_delta": 0.2, "mean_abs_delta": 0.01}
    # global path: 0.2 < 0.35 and 0.01 < 0.05 -> no breach
    assert drift.breach_verdict(metrics) is False
    # band-aware: delta beyond the band is a breach, inside is not
    assert drift.breach_verdict(metrics, band_width=0.1) is True
    assert drift.breach_verdict(metrics, band_width=0.3) is False
    assert drift.breach_verdict(metrics, band_width=0.0) is True
    # non-usable band values fall back to the global thresholds
    for bogus in (None, True, False, float("inf"), float("nan"), -0.5):
        assert drift.breach_verdict(metrics, band_width=bogus) is False
    # row_breach: the ledger-migration contract — a band-less row
    # (every row written before bands existed) re-evaluates on the
    # global path byte-for-byte
    old_row = dict(metrics)
    assert drift.row_breach(old_row) == drift.breach_verdict(metrics)
    banded = {**metrics, "band_width": 0.1}
    assert drift.row_breach(banded) is True


# -- ledger schema: optional progressive columns -----------------------


def _req_row(**extra):
    row = {
        "ledger_version": 2, "ts": 1.0, "kind": "request",
        "source": "test", "ok": True, "id": "r1",
        "engine_requested": "sampled", "engine_used": "sampled",
        "model": "gemm", "n": 16, "degraded": [],
        "fingerprint": "f" * 16, "cache": "miss", "latency_s": 0.1,
        "mrc_digest": "d" * 16,
    }
    row.update(extra)
    return row


def test_ledger_accepts_and_validates_progressive_columns(tmp_path):
    ok_row = _req_row(rounds=3, band_width=0.02, converged=True)
    assert obs_ledger.validate_row(ok_row) == []
    assert obs_ledger.validate_row(
        _req_row(rounds=None, band_width=None)) == []
    assert obs_ledger.validate_row(_req_row()) == []  # columns optional
    errs = obs_ledger.validate_row(
        _req_row(rounds="three", band_width="wide", converged="yes"))
    assert len(errs) == 3
    # and a written row round-trips through the file
    path = str(tmp_path / "ledger.jsonl")
    obs_ledger.append(path, ok_row)
    with open(path) as f:
        back = json.loads(f.read().splitlines()[-1])
    assert back["rounds"] == 3 and back["converged"] is True


# -- the CI gate -------------------------------------------------------


def test_check_precision_gate_engine_level():
    """Prefix identity + monotone bands over 2 seeds, no service
    spin-up (the deadline/replay half runs in the slow gate below)."""
    assert check_precision.main(
        ["--seeds", "0,1", "--models", "gemm", "--skip-deadline"]
    ) == 0


def test_check_precision_gate_deadline_and_replay():
    """The full gate for one seed: deadline mid-round -> exactly one
    partial_final with the last streamed band and a `precision:*`
    degrade hop, never cached, and an exact replay."""
    assert check_precision.main(
        ["--seeds", "0", "--models", "gemm"]
    ) == 0
