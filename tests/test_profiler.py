"""Ground-truth profiler: real execution + exact RI accounting."""

import numpy as np
import pytest

from pluss_sampler_optimization_tpu import MachineConfig
from pluss_sampler_optimization_tpu.models.gemm import gemm
from pluss_sampler_optimization_tpu.models.mm2 import mm2
from pluss_sampler_optimization_tpu.oracle.profiler import (
    ContiguousSchedule,
    execute_gemm,
    gemm_init,
    profile_gemm,
    profile_program,
)
from pluss_sampler_optimization_tpu.oracle.serial import run_serial
from pluss_sampler_optimization_tpu.runtime.hist import pow2_floor


def _binned(h):
    """pow2-bin a raw histogram, keeping -1; drop zero counts."""
    out = {}
    for k, v in h.items():
        key = pow2_floor(int(k)) if k > 0 else int(k)
        out[key] = out.get(key, 0.0) + v
    return {k: v for k, v in out.items() if v}


def _oracle_binned(state, tid):
    """Oracle noshare (already binned) + share (raw) as one binned hist."""
    h = dict(state.noshare[tid])
    for ratio_h in state.share[tid].values():
        for k, v in ratio_h.items():
            key = pow2_floor(int(k)) if k > 0 else int(k)
            h[key] = h.get(key, 0.0) + v
    return {k: v for k, v in h.items() if v}


def test_execute_gemm_matches_closed_form():
    C0, A, B = gemm_init(12, 12, 12)
    out = execute_gemm(12, 12, 12, thread_num=4)
    np.testing.assert_allclose(out, 1.2 * C0 + 1.5 * A @ B, rtol=1e-12)


def test_contiguous_schedule_uneven_split():
    s = ContiguousSchedule(trip=10, threads=4)
    counts = [s.local_count(t) for t in range(4)]
    assert counts == [3, 3, 2, 2]
    vals = [s.local_to_value(t, m) for t in range(4) for m in range(counts[t])]
    assert vals == list(range(10))


def test_profiler_single_thread_matches_oracle():
    machine = MachineConfig(thread_num=1)
    prog = gemm(16)
    prof = profile_program(prog, machine)
    oracle = run_serial(prog, machine)
    assert prof.per_tid_accesses == oracle.per_tid_accesses
    assert _binned(prof.hists[0]) == _oracle_binned(oracle.state, 0)


def test_profiler_multinest_single_thread():
    machine = MachineConfig(thread_num=1)
    prog = mm2(8)
    prof = profile_program(prog, machine)
    oracle = run_serial(prog, machine)
    assert _binned(prof.hists[0]) == _oracle_binned(oracle.state, 0)


def test_profiler_matches_oracle_when_schedules_coincide():
    """Round-robin with n_chunks == threads IS the contiguous split."""
    n, t = 16, 4
    machine = MachineConfig(thread_num=t, chunk_size=n // t)
    prog = gemm(n)
    prof = profile_program(prog, machine)
    oracle = run_serial(prog, machine)
    assert prof.per_tid_accesses == oracle.per_tid_accesses
    for tid in range(t):
        assert _binned(prof.hists[tid]) == _oracle_binned(oracle.state, tid)


def test_profile_gemm_entry():
    res = profile_gemm(8)
    assert res.output is not None
    assert len(res.hists) == 4
    assert sum(res.per_tid_accesses) == 8 * 8 * (2 + 4 * 8)
    merged = res.merged()
    assert merged[-1] > 0  # cold first touches recorded as -1
