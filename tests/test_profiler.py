"""Ground-truth profiler: real execution + exact RI accounting — plus
the sampling wall-clock profiler / utilization-attribution layer
(runtime/obs/profiler.py + attribution.py) and its offline gate
(tools/check_profile.py, wired into tier-1 here)."""

import json
import os
import subprocess
import sys
import threading
import time

import numpy as np
import pytest

from pluss_sampler_optimization_tpu import MachineConfig
from pluss_sampler_optimization_tpu.models.gemm import gemm
from pluss_sampler_optimization_tpu.models.mm2 import mm2
from pluss_sampler_optimization_tpu.oracle.profiler import (
    ContiguousSchedule,
    execute_gemm,
    gemm_init,
    profile_gemm,
    profile_program,
)
from pluss_sampler_optimization_tpu.oracle.serial import run_serial
from pluss_sampler_optimization_tpu.runtime import telemetry
from pluss_sampler_optimization_tpu.runtime.hist import pow2_floor
from pluss_sampler_optimization_tpu.runtime.obs import (
    attribution,
    ledger as obs_ledger,
    metrics as obs_metrics,
    profiler as obs_profiler,
)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

sys.path.insert(0, os.path.join(REPO, "tools"))
import check_ledger  # noqa: E402

sys.path.pop(0)


@pytest.fixture(autouse=True)
def _obs_clean_slate():
    telemetry.disable()
    obs_profiler.disable()
    obs_metrics.disable()
    yield
    telemetry.disable()
    obs_profiler.disable()
    obs_metrics.disable()


def _binned(h):
    """pow2-bin a raw histogram, keeping -1; drop zero counts."""
    out = {}
    for k, v in h.items():
        key = pow2_floor(int(k)) if k > 0 else int(k)
        out[key] = out.get(key, 0.0) + v
    return {k: v for k, v in out.items() if v}


def _oracle_binned(state, tid):
    """Oracle noshare (already binned) + share (raw) as one binned hist."""
    h = dict(state.noshare[tid])
    for ratio_h in state.share[tid].values():
        for k, v in ratio_h.items():
            key = pow2_floor(int(k)) if k > 0 else int(k)
            h[key] = h.get(key, 0.0) + v
    return {k: v for k, v in h.items() if v}


def test_execute_gemm_matches_closed_form():
    C0, A, B = gemm_init(12, 12, 12)
    out = execute_gemm(12, 12, 12, thread_num=4)
    np.testing.assert_allclose(out, 1.2 * C0 + 1.5 * A @ B, rtol=1e-12)


def test_contiguous_schedule_uneven_split():
    s = ContiguousSchedule(trip=10, threads=4)
    counts = [s.local_count(t) for t in range(4)]
    assert counts == [3, 3, 2, 2]
    vals = [s.local_to_value(t, m) for t in range(4) for m in range(counts[t])]
    assert vals == list(range(10))


def test_profiler_single_thread_matches_oracle():
    machine = MachineConfig(thread_num=1)
    prog = gemm(16)
    prof = profile_program(prog, machine)
    oracle = run_serial(prog, machine)
    assert prof.per_tid_accesses == oracle.per_tid_accesses
    assert _binned(prof.hists[0]) == _oracle_binned(oracle.state, 0)


def test_profiler_multinest_single_thread():
    machine = MachineConfig(thread_num=1)
    prog = mm2(8)
    prof = profile_program(prog, machine)
    oracle = run_serial(prog, machine)
    assert _binned(prof.hists[0]) == _oracle_binned(oracle.state, 0)


def test_profiler_matches_oracle_when_schedules_coincide():
    """Round-robin with n_chunks == threads IS the contiguous split."""
    n, t = 16, 4
    machine = MachineConfig(thread_num=t, chunk_size=n // t)
    prog = gemm(n)
    prof = profile_program(prog, machine)
    oracle = run_serial(prog, machine)
    assert prof.per_tid_accesses == oracle.per_tid_accesses
    for tid in range(t):
        assert _binned(prof.hists[tid]) == _oracle_binned(oracle.state, tid)


def test_profile_gemm_entry():
    res = profile_gemm(8)
    assert res.output is not None
    assert len(res.hists) == 4
    assert sum(res.per_tid_accesses) == 8 * 8 * (2 + 4 * 8)
    merged = res.merged()
    assert merged[-1] > 0  # cold first touches recorded as -1


# -- sampling wall-clock profiler (runtime/obs/profiler.py) -----------


_FIXED_LOG = [
    ("service_request/execute/draw",
     ("cli.py:main:10", "sampler/sampled.py:run_sampled:40",
      "sampler/draw.py:draw:25"), 7),
    ("service_request/execute/fetch",
     ("cli.py:main:10", "runtime/telemetry.py:fetch_to_host:470"), 3),
    ("service_request/queue", ("service/executor.py:_admit:120",), 2),
    ("", ("threading.py:_bootstrap:900",), 4),
]


def _ingest_all(prof, log):
    for path, frames, count in log:
        prof.ingest(path, frames, count)
    prof._duration_s = 1.0  # pin wall time out of the snapshot
    return prof


def test_wallclock_fold_deterministic_and_byte_stable(tmp_path):
    """Same sample log, any fold order -> one snapshot, identical
    export bytes (the check_profile determinism claim, in-process)."""
    a = _ingest_all(obs_profiler.SamplingProfiler(hz=100.0),
                    _FIXED_LOG)
    b = _ingest_all(obs_profiler.SamplingProfiler(hz=100.0),
                    list(reversed(_FIXED_LOG)))
    snap = a.snapshot()
    assert obs_profiler.validate_snapshot(snap) == []
    assert snap == b.snapshot()
    assert snap["samples"] == 16
    assert snap["samples_attributed"] == 12
    assert snap["samples_in_request"] == 12
    assert snap["attribution_completeness"] == 1.0
    # stacks sorted by weight; seconds = count / hz
    assert snap["stacks"][0]["span"] == "service_request/execute/draw"
    assert snap["stacks"][0]["seconds"] == 0.07
    assert snap["span_seconds"]["unattributed"] == 0.04
    paths = {}
    for name, prof in (("a", a), ("b", b)):
        ss = str(tmp_path / f"{name}.speedscope.json")
        cl = str(tmp_path / f"{name}.collapsed")
        prof.write_speedscope(ss)
        prof.write_collapsed(cl)
        paths[name] = (open(ss, "rb").read(), open(cl, "rb").read())
    assert paths["a"] == paths["b"]
    # re-export is byte-identical too
    a.write_speedscope(str(tmp_path / "a2.json"))
    assert (tmp_path / "a2.json").read_bytes() == paths["a"][0]
    # collapsed format: "span:<path>;frame;... count" lines
    first = paths["a"][1].decode().splitlines()[0]
    assert first.startswith("span:") and first.rsplit(" ", 1)[1].isdigit()
    # speedscope schema essentials
    doc = json.loads(paths["a"][0])
    assert doc["profiles"][0]["type"] == "sampled"
    assert len(doc["profiles"][0]["samples"]) == len(_FIXED_LOG)


def test_wallclock_fold_table_bounded():
    """Past max_stacks the fold table stops growing; overflow samples
    are counted, never dropped silently."""
    p = obs_profiler.SamplingProfiler(hz=100.0, max_stacks=2)
    for i in range(5):
        p.ingest("s", (f"f{i}:g:1",), 3)
    snap = p.snapshot()
    assert len(snap["stacks"]) == 2
    assert snap["stacks_overflowed"] == 9
    assert snap["samples"] == 15  # totals still count everything


def test_wallclock_profiler_attributes_live_spans():
    """The cross-thread join: a worker inside telemetry spans is
    sampled by the background profiler thread and lands attributed."""
    telemetry.enable()

    def work():
        with telemetry.span("service_request", engine="sampled"):
            with telemetry.span("execute"):
                t0 = time.perf_counter()
                while time.perf_counter() - t0 < 0.3:
                    sum(range(500))

    prof = obs_profiler.enable(hz=500.0)
    try:
        t = threading.Thread(target=work)
        t.start()
        t.join()
    finally:
        obs_profiler.disable()
    snap = prof.snapshot()
    assert snap["samples"] > 0
    hits = [p for p in snap["span_seconds"]
            if p == "service_request/execute"]
    assert hits, snap["span_seconds"]
    assert snap["samples_attributed"] > 0
    assert obs_profiler.validate_snapshot(snap) == []
    # module-level snapshot() reads None once disabled
    assert obs_profiler.snapshot() is None


# -- per-request utilization attribution ------------------------------


def test_utilization_block_fractions_and_validation():
    u = attribution.request_utilization(
        wall_s=1.0, execute_s=0.6, sync_s=0.2, queue_s=0.1,
        batch_wait_s=0.05, fetch_s=0.02, compile_s=0.3,
        modeled_bytes=1000, modeled_flops=5000,
    )
    assert attribution.validate_block(u) == []
    total = sum(u[k] for k in attribution.FRACTION_KEYS)
    assert abs(total - 1.0) < 0.02
    assert u["busy_fraction"] == pytest.approx(
        u["executing_fraction"] + u["sync_fraction"], abs=1e-6
    )
    assert u["device_idle_fraction"] == pytest.approx(
        1.0 - u["busy_fraction"], abs=1e-6
    )
    assert u["modeled_bytes"] == 1000 and u["modeled_flops"] == 5000
    assert u["compile_s"] == 0.3

    # overlapping stage timers (execute ~ wall AND queue+fetch on top)
    # normalize proportionally instead of overflowing past 1.0
    u2 = attribution.request_utilization(
        wall_s=1.0, execute_s=1.0, queue_s=0.5, fetch_s=0.3,
    )
    assert attribution.validate_block(u2) == []
    total2 = sum(u2[k] for k in attribution.FRACTION_KEYS)
    assert abs(total2 - 1.0) < 0.02
    assert u2["unattributed_fraction"] == 0.0

    # degenerate wall yields no block rather than division noise
    assert attribution.request_utilization(wall_s=0.0) is None
    assert attribution.request_utilization(wall_s=None) is None


def test_utilization_validate_block_rejects_bad_shapes():
    good = attribution.request_utilization(wall_s=1.0, execute_s=0.5)
    for mutate, frag in (
        ({"wall_s": -1.0}, "wall_s"),
        ({"executing_fraction": 1.5}, "executing_fraction"),
        ({"unattributed_fraction": "x"}, "unattributed_fraction"),
        ({"modeled_bytes": -3}, "modeled_bytes"),
    ):
        bad = dict(good)
        bad.update(mutate)
        errs = attribution.validate_block(bad)
        assert errs and any(frag in e for e in errs), (mutate, errs)
    assert attribution.validate_block("nope")


def test_sample_breakdown_groups_by_span_leaf():
    p = obs_profiler.SamplingProfiler(hz=100.0)
    p.ingest("service_request/execute", ("a:b:1",), 6)
    p.ingest("service_request/execute/fetch", ("a:b:1",), 2)
    p.ingest("service_request/queue", ("a:b:1",), 1)
    p.ingest("", ("t:u:1",), 1)
    br = attribution.sample_breakdown(p.snapshot())
    assert br["samples"] == 10
    assert br["executing_samples"] == 6
    assert br["sync_samples"] == 2
    assert br["queue_samples"] == 1
    assert br["unattributed_samples"] == 1
    total = (br["executing_fraction"] + br["sync_fraction"]
             + br["queue_fraction"] + br["unattributed_fraction"])
    assert total == pytest.approx(1.0, abs=1e-6)


def test_utilization_ledger_roundtrip_and_stats_line(tmp_path, capsys):
    """Rows carrying a utilization block survive append -> validate ->
    aggregate, and check_ledger --stats prints the new utilization
    aggregate line (mean busy, p95 unattributed, per engine)."""
    path = str(tmp_path / "ledger.jsonl")
    for busy, unattr in ((0.8, 0.1), (0.6, 0.3)):
        u = attribution.request_utilization(
            wall_s=1.0, execute_s=busy, queue_s=1.0 - busy - unattr,
        )
        assert u["busy_fraction"] == pytest.approx(busy, abs=0.01)
        obs_ledger.append(path, {
            "kind": "request", "source": "test", "ok": True,
            "fingerprint": "ab" * 32, "engine_requested": "sampled",
            "engine_used": "sampled", "model": "gemm", "n": 16,
            "latency_s": 1.0, "cache": "miss", "degraded": [],
            "mrc_digest": "0" * 16, "utilization": u,
        })
    rows = obs_ledger.read_rows(path)
    assert all(obs_ledger.validate_row(r) == [] for r in rows)
    agg = obs_ledger.aggregate(rows)["requests"]["sampled"]
    assert agg["utilization_rows"] == 2
    assert agg["mean_busy_fraction"] == pytest.approx(0.7, abs=0.01)
    assert agg["p95_unattributed_fraction"] >= 0.25
    assert check_ledger.main([path, "--stats"]) == 0
    out = capsys.readouterr().out
    assert "utilization: sampled busy=0.70" in out

    # a malformed block is rejected at append time, not read time
    bad = attribution.request_utilization(wall_s=1.0, execute_s=0.5)
    bad["busy_fraction"] = 7.0
    with pytest.raises(ValueError):
        obs_ledger.append(path, {
            "kind": "request", "source": "test", "ok": True,
            "fingerprint": "ab" * 32, "engine_requested": "sampled",
            "engine_used": "sampled", "model": "gemm", "n": 16,
            "latency_s": 1.0, "cache": "miss", "degraded": [],
            "mrc_digest": "0" * 16, "utilization": bad,
        })


def test_executor_stamps_utilization_end_to_end(tmp_path):
    """A real service request lands in the ledger with a utilization
    block whose fractions sum to ~1, and the live registry carries the
    busy/idle/unattributed gauges."""
    from pluss_sampler_optimization_tpu.service import (
        AnalysisRequest,
        AnalysisService,
    )

    ledger_path = str(tmp_path / "ledger.jsonl")
    reg = obs_metrics.enable()
    with AnalysisService(max_workers=2,
                         ledger_path=ledger_path) as svc:
        ticket = svc.submit(AnalysisRequest(model="gemm", n=16,
                                            engine="oracle"))
        resp = svc.result(ticket, timeout=60)
        assert resp.ok
    rows = [r for r in obs_ledger.read_rows(ledger_path)
            if r["kind"] == "request"]
    assert rows
    u = rows[-1]["utilization"]
    assert attribution.validate_block(u) == []
    total = sum(u[k] for k in attribution.FRACTION_KEYS)
    assert abs(total - 1.0) < 0.02
    for g in ("utilization_busy_fraction",
              "utilization_device_idle_fraction",
              "utilization_unattributed_fraction"):
        assert reg.gauge_value(g) is not None, g


def test_check_profile_gate_passes():
    """The tier-1 wiring for tools/check_profile.py: determinism,
    <3% overhead with MRC digests bit-identical, and the attribution
    completeness floor, on the real sampled engine.  The overhead arm
    is a timing measurement on a shared host: one failed process gets
    one fresh process before the test fails (the gate already retries
    internally; a genuine regression fails both)."""
    for attempt in (0, 1):
        proc = subprocess.run(
            [sys.executable, os.path.join(REPO, "tools",
                                          "check_profile.py"),
             "--json"],
            capture_output=True, text=True, timeout=600, cwd=REPO,
            env={**os.environ, "JAX_PLATFORMS": "cpu"},
        )
        if proc.returncode == 0:
            break
    assert proc.returncode == 0, proc.stdout[-3000:] + proc.stderr[-2000:]
    doc = json.loads(proc.stdout)
    assert doc["ok"]
    assert doc["determinism"]["exports_order_independent"]
    eng = doc["engine"]
    assert eng["mrc_bit_identical"]
    assert eng["overhead_pct"] < eng["overhead_budget_pct"]
