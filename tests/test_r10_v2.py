"""r10 per-ref distribute path and runtime-v2 histogram semantics."""

import math

from pluss_sampler_optimization_tpu import MachineConfig, SamplerConfig
from pluss_sampler_optimization_tpu.cli import main
from pluss_sampler_optimization_tpu.models.gemm import gemm
from pluss_sampler_optimization_tpu.oracle.serial import run_serial
from pluss_sampler_optimization_tpu.runtime.cri import r10_distribute
from pluss_sampler_optimization_tpu.sampler.sampled import (
    fold_results,
    run_sampled,
    sampled_outputs,
)

MACHINE = MachineConfig()
CFG = SamplerConfig(ratio=0.25, seed=2)


def _is_pow2(x: int) -> bool:
    return x > 0 and (x & (x - 1)) == 0


def test_r10_distribute_merged_keys_and_mass():
    results = sampled_outputs(gemm(16, share_threshold_variant="r10"),
                              MACHINE, CFG)
    merged, per_ref = r10_distribute(results, MACHINE.thread_num)
    assert set(per_ref) == {"C0", "C1", "A0", "B0", "C2", "C3"}
    # merge pow2-bins on insertion (pluss_histogram_update default)
    for k in merged:
        assert k == -1 or _is_pow2(k)
    # mass conservation: NBD truncates at prob_sum > 0.999 (r10 :60),
    # racetrack folds its remainder exactly
    mass_in = sum(
        sum(r.noshare.values())
        + r.cold
        + sum(sum(h.values()) for h in r.share.values())
        for r in results
    )
    mass_out = sum(merged.values())
    assert mass_in > 0
    assert math.isclose(mass_out, mass_in, rel_tol=0.01)


def test_r10_share_point_mass():
    """r10's share path degenerates to a point mass at
    THREAD_NUM * pow2_floor(ri) before the racetrack split
    (...rs-ri-opt-r10.cpp:94 passing 1.0/THREAD_NUM as int)."""
    results = sampled_outputs(gemm(16, share_threshold_variant="r10"),
                              MACHINE, CFG)
    b0 = next(r for r in results if r.name == "B0")
    if not any(b0.share.values()):
        return  # no share reuse sampled at this tiny size
    _, per_ref = r10_distribute(results, MACHINE.thread_num)
    # racetrack output keys are powers of two (2^(b-1)); none may exceed
    # the point mass THREAD_NUM * pow2_floor(max ri)
    max_ri = max(k for h in b0.share.values() for k in h)
    bound = MACHINE.thread_num * (1 << (max_ri.bit_length() - 1))
    share_keys = [k for k in per_ref["B0"] if k > 0]
    assert all(k <= bound for k in share_keys)


def test_v2_oracle_raw_noshare_keys():
    prog = gemm(16)
    v1 = run_serial(prog, MACHINE)
    v2 = run_serial(prog, MACHINE, v2=True)
    assert v1.total_accesses == v2.total_accesses
    for tid in range(MACHINE.thread_num):
        assert sum(v1.state.noshare[tid].values()) == sum(
            v2.state.noshare[tid].values()
        )
    # v2 keeps raw keys: GEMM has reuses that are not powers of two
    raw_keys = {k for h in v2.state.noshare for k in h if k > 0}
    assert any(not _is_pow2(k) for k in raw_keys)
    # share side identical (share was never binned in either runtime)
    for a, b in zip(v1.state.share, v2.state.share):
        assert a == b


def test_v2_fold_matches_raw_pairs():
    _, results = run_sampled(gemm(16), MACHINE, CFG)
    state = fold_results(results, MACHINE.thread_num, v2=True)
    raw = {}
    for r in results:
        for k, v in r.noshare.items():
            raw[k] = raw.get(k, 0.0) + v
    folded = {k: v for k, v in state.noshare[0].items() if k > 0}
    assert folded == raw


def test_cli_r10_and_v2(capsys):
    assert main(["sample", "--model", "gemm", "--n", "16", "--ratio",
                 "0.3", "--r10"]) == 0
    out = capsys.readouterr().out
    assert "B0" in out and "miss ratio" in out
    assert main(["acc", "--model", "gemm", "--n", "16", "--engine",
                 "oracle", "--runtime", "v2"]) == 0
    out = capsys.readouterr().out
    assert "miss ratio" in out
