"""Flight recorder: anomaly-triggered post-mortem bundles, tail-based
trace retention, and the ledger-driven performance regression sentinel
(runtime/obs/recorder.py, runtime/obs/regress.py, the serve wiring,
and the tools/check_bundle.py / check_regression.py gates).

The ISSUE-12 acceptance invariants are pinned here: each of the five
trigger paths — SLO breach, request failure, replica quarantine, drift
breach, and explicit `dump_debug` — produces exactly one atomic,
schema-valid bundle containing the retained span trees and a registry
snapshot; tail-based retention keeps error/outlier records and evicts
the boring majority under ring pressure; `check_regression` exits
nonzero on an injected latency regression and clean over the repo's
real BENCH_r*.json history; serve-mode ledger GC compacts in place;
the scrape server answers /healthz, /stats, and /debug/bundles; and
MRC bytes are bit-identical with the recorder on vs off.
"""

import json
import threading
import time
import urllib.error
import urllib.request

import glob
import os
import sys

import numpy as np
import pytest

from pluss_sampler_optimization_tpu import MachineConfig, SamplerConfig
from pluss_sampler_optimization_tpu.cli import main
from pluss_sampler_optimization_tpu.config import SLOConfig
from pluss_sampler_optimization_tpu.models import REGISTRY
from pluss_sampler_optimization_tpu.runtime import telemetry
from pluss_sampler_optimization_tpu.runtime.aet import aet_mrc
from pluss_sampler_optimization_tpu.runtime.cri import cri_distribute
from pluss_sampler_optimization_tpu.runtime.obs import (
    drift as obs_drift,
    ledger as obs_ledger,
    metrics as obs_metrics,
    recorder as obs_recorder,
    regress as obs_regress,
    slo as obs_slo,
)
from pluss_sampler_optimization_tpu.sampler.sampled import run_sampled
from pluss_sampler_optimization_tpu.service import (
    AnalysisRequest,
    AnalysisService,
    serve_jsonl,
)
from pluss_sampler_optimization_tpu.service.executor import (
    default_runner,
)

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, os.path.join(REPO_ROOT, "tools"))
import check_bundle  # noqa: E402
import check_regression  # noqa: E402


@pytest.fixture(autouse=True)
def _clean_slate():
    telemetry.disable()
    obs_metrics.disable()
    obs_recorder.disable()
    yield
    telemetry.disable()
    obs_metrics.disable()
    obs_recorder.disable()


def _req(**kw):
    base = dict(model="gemm", n=16, engine="oracle")
    base.update(kw)
    return AnalysisRequest(**base)


def _bundles(bundle_dir):
    """BUNDLE_*.json names in the dir, sorted (oldest first by the
    timestamp+seq embedded in the name)."""
    return sorted(
        n for n in os.listdir(bundle_dir)
        if n.startswith("BUNDLE_") and n.endswith(".json")
    )


def _load_bundle(bundle_dir, name):
    with open(os.path.join(bundle_dir, name)) as f:
        return json.load(f)


def _flaky_runner(fail_times: int):
    state = {"left": fail_times}
    lock = threading.Lock()

    def runner(engine, program, machine, request):
        with lock:
            if state["left"] > 0:
                state["left"] -= 1
                raise RuntimeError("injected replica fault")
        return default_runner(engine, program, machine, request)

    return runner


# -- ring mechanics / tail retention ----------------------------------


def test_span_tree_synthesis():
    rec = {
        "trace_id": "t1", "span_id": "s1", "engine_used": "sampled",
        "cache": "miss", "latency_s": 0.4, "queue_s": 0.1,
        "execute_s": 0.25, "batch_wait_s": None,
    }
    tree = obs_recorder._span_tree(rec)
    assert tree["name"] == "request" and tree["wall_s"] == 0.4
    assert tree["attrs"]["trace_id"] == "t1"
    # null stages are skipped; present ones nest in pipeline order
    # with cumulative offsets
    names = [c["name"] for c in tree["children"]]
    assert names == ["queue", "execute"]
    assert tree["children"][0]["start_s"] == 0.0
    assert tree["children"][1]["start_s"] == pytest.approx(0.1)
    assert tree["children"][1]["wall_s"] == 0.25
    # no timings at all still yields a valid (empty) tree
    bare = obs_recorder._span_tree({})
    assert bare["wall_s"] == 0.0 and bare["children"] == []


def test_tail_retention_keeps_interesting_evicts_boring(tmp_path):
    """The tentpole retention invariant: under ring pressure the
    error and latency-outlier records survive in the keep set while
    the boring majority is dropped."""
    tele = telemetry.enable()
    rec = obs_recorder.FlightRecorder(
        str(tmp_path / "bundles"), capacity=8, retain_capacity=4,
        outlier_min_count=20,
    )
    for i in range(30):
        rec.record_request({
            "trace_id": f"ok{i}", "ok": True, "latency_s": 0.01,
        })
    # outlier: far above the windowed p99 of the 0.01s majority
    rec.record_request({"trace_id": "slow", "ok": True,
                        "latency_s": 5.0})
    rec.record_request({"trace_id": "bad", "ok": False,
                        "error": "boom", "latency_s": 0.01})
    # push both out of the ring with more boring traffic
    for i in range(20):
        rec.record_request({
            "trace_id": f"tail{i}", "ok": True, "latency_s": 0.01,
        })
    st = rec.stats()
    assert st["records_seen"] == 52
    assert st["ring"] == 8
    assert st["evicted"] > 0
    kept = {(r["trace_id"], r["retained"]) for r in rec._retained}
    assert kept == {("slow", "latency_outlier"), ("bad", "error")}
    # the failure also fired the request_failure trigger: one bundle
    assert st["triggers"] == {"request_failure": 1}
    assert len(_bundles(str(tmp_path / "bundles"))) == 1
    assert tele.counters["recorder_records"] == 52
    telemetry.disable()


def test_event_records_and_retention_classes(tmp_path):
    rec = obs_recorder.FlightRecorder(
        str(tmp_path / "b"), capacity=2, retain_capacity=4,
        min_interval_s=0.0,
    )
    # routine events ride the ring and age out; anomaly events retain
    rec.record_event("ledger_gc", {"dropped": 3})
    rec.record_event("export_failed", {"path": "x"})
    rec.record_event("ledger_gc", {"dropped": 1})
    rec.record_event("ledger_gc", {"dropped": 2})
    names = {(r["name"], r["retained"]) for r in rec._retained}
    assert ("export_failed", "event") in names
    assert not any(n == "ledger_gc" for n, _c in names)
    # trigger events write a bundle named for their reason
    rec.record_event("drift_breach", {"model": "gemm", "n": 16})
    files = _bundles(str(tmp_path / "b"))
    assert len(files) == 1 and files[0].endswith("_drift_breach.json")


def test_rate_limit_one_bundle_per_reason_window(tmp_path):
    rec = obs_recorder.FlightRecorder(
        str(tmp_path / "b"), min_interval_s=3600.0,
    )
    assert rec.trigger("slo_breach", {"check": "x"}) is not None
    assert rec.trigger("slo_breach", {"check": "x"}) is None
    # a DIFFERENT reason is not suppressed by slo_breach's window
    assert rec.trigger("drift_breach", {}) is not None
    # force (the dump_debug / SIGUSR2 path) bypasses the limit
    assert rec.dump("dump_debug") is not None
    assert rec.dump("dump_debug") is not None
    st = rec.stats()
    assert st["bundles_suppressed"] == 1
    assert st["bundles_written"] == 4
    assert len(_bundles(str(tmp_path / "b"))) == 4


# -- bundle schema ----------------------------------------------------


def test_validate_bundle_schema_violations(tmp_path):
    rec = obs_recorder.FlightRecorder(str(tmp_path / "b"))
    rec.record_request({"trace_id": "t", "ok": True,
                        "latency_s": 0.01})
    path = rec.dump("dump_debug", trigger={"who": "test"})
    doc = json.load(open(path))
    assert obs_recorder.validate_bundle(doc) == []
    assert doc["reason"] == "dump_debug"
    assert doc["trigger"] == {"who": "test"}
    assert doc["records"][0]["span_tree"]["name"] == "request"
    assert isinstance(doc["host"], dict)
    assert isinstance(doc["compile_counters"], dict)

    assert obs_recorder.validate_bundle([]) \
        == ["bundle is not a JSON object"]
    bad = dict(doc, bundle_version=99)
    assert any("bundle_version" in e
               for e in obs_recorder.validate_bundle(bad))
    bad = dict(doc, reason="nope")
    assert any("'reason'" in e
               for e in obs_recorder.validate_bundle(bad))
    bad = dict(doc, records=[{"kind": "weird"}])
    errs = obs_recorder.validate_bundle(bad)
    assert any("records[0].kind" in e for e in errs)
    assert any("records[0].ts" in e for e in errs)
    bad = dict(doc, records=[dict(doc["records"][0],
                                  retained="whatever")])
    assert any("retained" in e
               for e in obs_recorder.validate_bundle(bad))
    bad = dict(doc)
    del bad["ledger_tail"]
    assert any("ledger_tail" in e
               for e in obs_recorder.validate_bundle(bad))


# -- the five trigger paths -------------------------------------------


def test_request_failure_trigger_writes_one_valid_bundle(tmp_path):
    def broken_runner(engine, program, machine, request):
        raise RuntimeError("no dice")

    bundle_dir = str(tmp_path / "bundles")
    tele = telemetry.enable()
    obs_recorder.enable(bundle_dir, ledger_path=None,
                        config={"mode": "test"})
    with AnalysisService(runner=broken_runner) as svc:
        r1 = svc.result(svc.submit(_req()), timeout=300)
        r2 = svc.result(svc.submit(_req(n=32)), timeout=300)
    rec = obs_recorder.get()
    stats = rec.stats()
    telemetry.disable()

    assert not r1.ok and "no dice" in r1.error
    assert not r2.ok
    # two failures inside one rate-limit window: exactly one bundle
    files = _bundles(bundle_dir)
    assert len(files) == 1 and files[0].endswith(
        "_request_failure.json")
    assert stats["triggers"] == {"request_failure": 1}
    assert stats["bundles_suppressed"] == 1
    assert tele.counters["debug_bundles_written"] == 1

    doc = _load_bundle(bundle_dir, files[0])
    assert obs_recorder.validate_bundle(doc) == []
    assert doc["reason"] == "request_failure"
    assert doc["config"] == {"mode": "test"}
    assert "no dice" in doc["trigger"]["error"]
    failed = [r for r in doc["records"]
              if r["kind"] == "request" and not r["ok"]]
    assert failed and failed[0]["span_tree"]["name"] == "request"
    assert failed[0]["retained"] == "error"


def test_slo_breach_trigger_via_record_sink(tmp_path):
    """The sentinel's slo_breach event reaches the recorder through
    telemetry.set_record_sink — the emit site knows nothing about
    bundles."""
    bundle_dir = str(tmp_path / "bundles")
    reg = obs_metrics.enable()
    telemetry.enable()
    obs_recorder.enable(bundle_dir)
    now = 5000.0
    for _ in range(20):
        reg.observe("request_total_s", 0.8, now=now)
        reg.inc("service_submitted", now=now)
    sentinel = obs_slo.SLOSentinel(
        SLOConfig(latency_p95_s=0.1, error_budget=0.5), registry=reg,
    )
    report = sentinel.evaluate_once(now=now)
    telemetry.disable()

    assert report["ok"] is False
    files = _bundles(bundle_dir)
    assert len(files) == 1 and files[0].endswith("_slo_breach.json")
    doc = _load_bundle(bundle_dir, files[0])
    assert obs_recorder.validate_bundle(doc) == []
    assert doc["trigger"]["event"] == "slo_breach"
    assert doc["trigger"]["check"] == "latency_p95"
    # the registry snapshot rides the bundle
    assert doc["registry"]["histograms"]["request_total_s"]["count"] \
        == 20


def test_replica_quarantine_trigger(tmp_path):
    bundle_dir = str(tmp_path / "bundles")
    tele = telemetry.enable()
    obs_recorder.enable(bundle_dir)
    with AnalysisService(
        cache_dir=str(tmp_path / "store"),
        replicas=2, runner=_flaky_runner(1),
    ) as svc:
        resp = svc.result(svc.submit(_req(
            engine="sampled", ratio=0.3, seed=1)), timeout=300)
    telemetry.disable()

    assert resp.ok and resp.degraded  # re-routed, not failed
    assert tele.counters.get("replica_quarantined") == 1
    files = _bundles(bundle_dir)
    assert len(files) == 1 and files[0].endswith(
        "_replica_quarantine.json")
    doc = _load_bundle(bundle_dir, files[0])
    assert obs_recorder.validate_bundle(doc) == []
    assert doc["trigger"]["event"] == "replica_quarantined"


def test_drift_breach_trigger(tmp_path):
    bundle_dir = str(tmp_path / "bundles")
    telemetry.enable()
    obs_recorder.enable(bundle_dir)
    # negative thresholds: any nonzero delta (even zero) breaches
    row = obs_drift.drift_audit(
        "gemm", n=16,
        thresholds={"max_abs_delta": -1.0, "mean_abs_delta": -1.0},
    )
    telemetry.disable()

    assert row["breach"]
    files = _bundles(bundle_dir)
    assert len(files) == 1 and files[0].endswith("_drift_breach.json")
    doc = _load_bundle(bundle_dir, files[0])
    assert obs_recorder.validate_bundle(doc) == []
    assert doc["trigger"]["event"] == "drift_breach"
    assert doc["trigger"]["model"] == "gemm"


def test_perf_regression_trigger_from_sentinel(tmp_path):
    """The regression leg of the sentinel tick: a ledger tail whose
    recent half is 5x slower trips regress.evaluate, and the
    perf_regression event lands a bundle."""
    bundle_dir = str(tmp_path / "bundles")
    ledger_path = str(tmp_path / "ledger.jsonl")
    _ledger_with_latencies(ledger_path, [0.01] * 10 + [0.05] * 10)
    tele = telemetry.enable()
    obs_recorder.enable(bundle_dir, ledger_path=ledger_path)
    sentinel = obs_slo.SLOSentinel(
        SLOConfig(), ledger_path=ledger_path,
    )
    sentinel.evaluate_once()
    telemetry.disable()

    assert sentinel.last_regression is not None
    assert sentinel.last_regression["ok"] is False
    assert tele.counters.get("perf_regression") == 1
    files = _bundles(bundle_dir)
    assert len(files) == 1 and files[0].endswith(
        "_perf_regression.json")
    doc = _load_bundle(bundle_dir, files[0])
    assert obs_recorder.validate_bundle(doc) == []
    assert any("latency_p50_s" in c
               for c in doc["trigger"]["regressed"])
    # the recorder pulled the ledger tail into the bundle
    assert len(doc["ledger_tail"]) == 20


def test_serve_dump_debug_control_line(tmp_path):
    """The explicit path: a dump_debug line in the serve stream is
    answered in the response pass, so its bundle's ring records
    include the request completed above it."""
    bundle_dir = str(tmp_path / "bundles")
    obs_recorder.enable(bundle_dir)
    lines = [
        json.dumps({"id": "r1", "model": "gemm", "n": 16,
                    "engine": "oracle"}),
        json.dumps({"id": "d", "type": "dump_debug"}),
    ]
    import io as io_mod

    out = io_mod.StringIO()
    with AnalysisService() as svc:
        failures = serve_jsonl(
            svc, io_mod.StringIO("\n".join(lines) + "\n"), out)
    assert failures == 0
    r1, d = [json.loads(ln) for ln in out.getvalue().splitlines()]
    assert r1["ok"]
    payload = d["dump_debug"]
    assert payload["enabled"] is True
    assert os.path.isfile(payload["bundle"])
    assert payload["bundle_dir"] == bundle_dir
    assert payload["bundles"] and \
        payload["bundles"][-1]["reason"] == "dump_debug"
    doc = json.load(open(payload["bundle"]))
    assert obs_recorder.validate_bundle(doc) == []
    traces = [r.get("trace_id") for r in doc["records"]
              if r["kind"] == "request"]
    assert r1["trace_id"] in traces

    # without a recorder the control line degrades, not errors
    obs_recorder.disable()
    out2 = io_mod.StringIO()
    with AnalysisService() as svc:
        serve_jsonl(
            svc,
            io_mod.StringIO(
                json.dumps({"id": "d2", "type": "dump_debug"}) + "\n"
            ),
            out2,
        )
    d2 = json.loads(out2.getvalue())
    assert d2["ok"] and d2["dump_debug"] == {"enabled": False}


# -- bit-identity -----------------------------------------------------


def test_mrc_bit_identical_with_recorder_enabled(tmp_path):
    """The acceptance bit-identity check: the flight recorder is
    observation-only — enabling it must not perturb engine numerics."""
    prog = REGISTRY["gemm"](16)
    machine = MachineConfig()
    cfg = SamplerConfig(ratio=0.3, seed=3)

    def mrc_bytes():
        state, _ = run_sampled(prog, machine, cfg)
        T = machine.thread_num
        return aet_mrc(
            cri_distribute(state, T, T), machine
        ).tobytes()

    off = mrc_bytes()
    obs_recorder.enable(str(tmp_path / "bundles"))
    on = mrc_bytes()
    obs_recorder.disable()
    assert on == off
    assert np.frombuffer(off, dtype=np.float64).size > 0


# -- scrape server JSON routes ----------------------------------------


def _http_get(url):
    with urllib.request.urlopen(url, timeout=10) as resp:
        return resp.status, resp.headers.get("Content-Type"), \
            resp.read().decode()


def test_metrics_server_json_routes(tmp_path):
    reg = obs_metrics.MetricsRegistry()
    reg.inc("reqs", 2)
    with obs_metrics.MetricsServer(
        reg, port=0,
        healthz=lambda: {"status": "ok", "service": True},
        stats=lambda: {"executor": {"submitted": 2}},
        bundles=lambda: {"bundle_dir": str(tmp_path), "bundles": []},
    ) as srv:
        base = f"http://{srv.host}:{srv.port}"
        status, ctype, body = _http_get(base + "/healthz")
        assert status == 200 and ctype == "application/json"
        assert json.loads(body) == {"status": "ok", "service": True}
        _status, _ctype, body = _http_get(base + "/stats")
        assert json.loads(body)["executor"]["submitted"] == 2
        _status, _ctype, body = _http_get(base + "/debug/bundles")
        assert json.loads(body)["bundles"] == []
        # Prometheus text still served on /metrics and /
        _status, ctype, body = _http_get(base + "/metrics")
        assert ctype.startswith("text/plain")
        assert "pluss_reqs_total 2" in body
        with pytest.raises(urllib.error.HTTPError) as exc:
            _http_get(base + "/nope")
        assert exc.value.code == 404

    # bare server (no callables): /healthz answers liveness, the
    # optional JSON routes 404
    with obs_metrics.MetricsServer(reg, port=0) as srv:
        base = f"http://{srv.host}:{srv.port}"
        _status, _ctype, body = _http_get(base + "/healthz")
        assert json.loads(body) == {"status": "ok", "service": False}
        for path in ("/stats", "/debug/bundles"):
            with pytest.raises(urllib.error.HTTPError) as exc:
                _http_get(base + path)
            assert exc.value.code == 404


# -- ledger GC --------------------------------------------------------


def _ledger_with_latencies(path, latencies, ts=10_000.0):
    for i, lat in enumerate(latencies):
        obs_ledger.append(path, {
            "ts": ts + i * 0.001, "kind": "request",
            "source": "service", "ok": True,
            "engine_requested": "sampled", "engine_used": "sampled",
            "model": "gemm", "n": 16, "latency_s": lat,
            "cache": "miss", "degraded": [], "fingerprint": None,
            "mrc_digest": None,
        })


def test_ledger_scan_compact_and_gc(tmp_path):
    path = str(tmp_path / "ledger.jsonl")
    _ledger_with_latencies(path, [0.01] * 10)
    with open(path, "a") as f:
        f.write("not json at all\n")

    s = obs_ledger.scan(path)
    assert len(s["valid"]) == 10 and len(s["invalid"]) == 1

    tele = telemetry.enable()
    gc = obs_ledger.LedgerGC(path, interval_s=3600.0, max_rows=4)
    s = gc.run_once()
    telemetry.disable()
    assert s["dropped"] == 7  # 1 invalid + 6 surplus
    rows = obs_ledger.read_rows(path)
    assert len(rows) == 4
    # the newest rows survive
    assert [r["ts"] for r in rows] == sorted(r["ts"] for r in rows)
    assert rows[-1]["ts"] == pytest.approx(10_000.009)
    assert tele.counters["ledger_gc_runs"] == 1
    assert tele.counters["ledger_gc_dropped"] == 7
    assert any(e["name"] == "ledger_gc" and e["dropped"] == 7
               for e in tele.events)
    # an already-clean ledger is left untouched
    before = os.stat(path).st_mtime_ns
    assert gc.run_once()["dropped"] == 0
    assert os.stat(path).st_mtime_ns == before


def test_ledger_gc_background_thread(tmp_path):
    path = str(tmp_path / "ledger.jsonl")
    _ledger_with_latencies(path, [0.01] * 6)
    tele = telemetry.enable()
    gc = obs_ledger.LedgerGC(path, interval_s=0.05, max_rows=3).start()
    deadline = time.time() + 10
    while (tele.counters.get("ledger_gc_runs", 0) < 2
           and time.time() < deadline):
        time.sleep(0.01)
    gc.close()
    telemetry.disable()
    assert tele.counters.get("ledger_gc_runs", 0) >= 2
    assert len(obs_ledger.read_rows(path)) == 3


# -- offline gates ----------------------------------------------------


def test_check_regression_clean_on_real_bench_history(capsys):
    """Acceptance: the gate runs clean over the repo's own BENCH_r*
    evidence trail."""
    paths = sorted(glob.glob(os.path.join(REPO_ROOT, "BENCH_r*.json")))
    assert len(paths) >= 3
    assert check_regression.main(["--bench"] + paths) == 0
    out = capsys.readouterr().out
    assert out.startswith("regression: ok")
    assert "bench:" in out


def test_check_regression_trips_on_injected_regression(tmp_path,
                                                       capsys):
    path = str(tmp_path / "ledger.jsonl")
    _ledger_with_latencies(path, [0.01] * 10 + [0.05] * 10)
    assert check_regression.main(["--ledger", path]) == 1
    out = capsys.readouterr().out
    assert "REGRESSED" in out
    assert "ledger:sampled:latency_p50_s" in out

    # a flat history inside the noise band passes
    flat = str(tmp_path / "flat.jsonl")
    _ledger_with_latencies(flat, [0.01] * 20)
    assert check_regression.main(["--ledger", flat]) == 0
    capsys.readouterr()

    # too little history = nothing to regress against (vacuous pass)
    thin = str(tmp_path / "thin.jsonl")
    _ledger_with_latencies(thin, [0.01] * 4)
    assert check_regression.main(["--ledger", thin]) == 0
    out = capsys.readouterr().out
    assert "insufficient history" in out

    assert check_regression.main(
        ["--ledger", str(tmp_path / "missing.jsonl")]) == 1
    with pytest.raises(SystemExit):
        check_regression.main([])  # nothing to check


def test_check_bundle_gate(tmp_path, capsys):
    bundle_dir = str(tmp_path / "bundles")
    rec = obs_recorder.FlightRecorder(bundle_dir, min_interval_s=0.0)
    rec.record_request({"trace_id": "t", "ok": True,
                        "latency_s": 0.01})
    first = rec.dump("dump_debug")
    assert check_bundle.main([bundle_dir]) == 0
    capsys.readouterr()

    # corrupt file trips the gate; --gc removes it and goes green
    corrupt = os.path.join(bundle_dir, "BUNDLE_corrupt.json")
    with open(corrupt, "w") as f:
        f.write("{broken")
    assert check_bundle.main([bundle_dir]) == 1
    assert "INVALID" in capsys.readouterr().err
    assert check_bundle.main([bundle_dir, "--gc"]) == 0
    capsys.readouterr()
    assert not os.path.exists(corrupt)
    assert check_bundle.main([bundle_dir]) == 0
    capsys.readouterr()

    # --max-bundles: the oldest becomes surplus once a newer exists
    rec.dump("dump_debug")
    assert check_bundle.main([bundle_dir, "--max-bundles", "1"]) == 1
    capsys.readouterr()
    assert check_bundle.main(
        [bundle_dir, "--max-bundles", "1", "--gc"]) == 0
    capsys.readouterr()
    assert not os.path.exists(first)
    assert len(_bundles(bundle_dir)) == 1

    assert check_bundle.main([str(tmp_path / "nosuch")]) == 1
    capsys.readouterr()


# -- CLI surface ------------------------------------------------------


def test_cli_rejects_recorder_flags_outside_serve(tmp_path):
    base = ["acc", "--model", "gemm", "--n", "8", "--engine",
            "oracle"]
    with pytest.raises(SystemExit):
        main(base + ["--debug-bundle-dir", str(tmp_path)])
    with pytest.raises(SystemExit):
        main(base + ["--regress-bench", "BENCH_r*.json"])
    with pytest.raises(SystemExit):
        main(base + ["--ledger-gc-interval-s", "60"])
    # serve mode still needs --ledger for GC
    with pytest.raises(SystemExit):
        main(["serve", "--requests", "/dev/null",
              "--ledger-gc-interval-s", "60"])


def test_cli_serve_flight_recorder_end_to_end(tmp_path, capsys):
    """serve --debug-bundle-dir: the recorder is announced, the
    dump_debug control line writes a validated bundle carrying the
    resolved config and the request's record, the ledger GC compacts
    on exit, and the recorder is torn down."""
    requests = tmp_path / "requests.jsonl"
    requests.write_text("\n".join([
        json.dumps({"id": "r1", "model": "gemm", "n": 16,
                    "engine": "oracle"}),
        json.dumps({"id": "d", "type": "dump_debug"}),
    ]) + "\n")
    responses = tmp_path / "responses.jsonl"
    bundle_dir = tmp_path / "bundles"
    ledger = tmp_path / "ledger.jsonl"
    assert main([
        "serve", "--requests", str(requests),
        "--responses", str(responses),
        "--cache-dir", str(tmp_path / "store"),
        "--ledger", str(ledger),
        "--debug-bundle-dir", str(bundle_dir),
        "--ledger-gc-interval-s", "3600", "--ledger-max-rows", "100",
    ]) == 0
    err = capsys.readouterr().err
    assert "serve: flight recorder on" in err

    r1, d = [json.loads(ln)
             for ln in responses.read_text().splitlines()]
    assert r1["ok"] and r1["trace_id"]
    payload = d["dump_debug"]
    assert payload["enabled"] is True
    doc = json.load(open(payload["bundle"]))
    assert obs_recorder.validate_bundle(doc) == []
    assert doc["config"]["debug_bundle_dir"] == str(bundle_dir)
    assert doc["config"]["ledger_max_rows"] == 100
    traces = [r.get("trace_id") for r in doc["records"]
              if r["kind"] == "request"]
    assert r1["trace_id"] in traces
    # live serving state rode along via the state provider, and the
    # always-on serve registry snapshot carries the stage histograms
    assert doc["state"] and "healthz" in doc["state"]
    assert doc["registry"]["histograms"]["request_total_s"]["count"] \
        >= 1

    # the bundle dir validates clean under the offline gate
    assert check_bundle.main([str(bundle_dir)]) == 0
    capsys.readouterr()
    # serve tears the recorder down on exit
    assert obs_recorder.get() is None
    assert os.path.isfile(ledger)
