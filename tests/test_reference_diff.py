"""Differential test against the ACTUAL reference implementation.

Every other parity test in this suite is port-vs-port inside this repo;
a shared misreading of the reference would be invisible to them. This
test closes that hole the way the reference itself validates accuracy
(c_lib/test/Makefile:39-41, README.md:10-12 — diff dumps across
implementations): it compiles the reference's own serial accuracy
oracle (c_lib/test/sampler/gemm-t4-pluss-pro-model-ri-omp-seq.cpp) with
g++, runs its `acc` mode, and byte-compares the noshare/share/RIHist
histogram dumps and the MRC against our oracle engine's CLI output.

GSL is not installed in this image; the only live GSL symbol is
`gsl_ran_negative_binomial_pdf` (pluss_utils.h:1002 — the geometric-cdf
use at :1177 is inside `#if 0`), so the build stubs it with the same
lgamma-space pmf formula our runtime/cri.py uses. The sampler hard-codes
N=128 (loop bounds are baked into the generated code), so the compare
runs at exactly that config.
"""

from __future__ import annotations

import hashlib
import os
import shutil
import subprocess
import sys

import pytest

REF = "/root/reference/c_lib/test"
_REPO = os.path.abspath(os.path.join(os.path.dirname(__file__), ".."))

_GSL_RANDIST_STUB = """\
#ifndef GSL_STUB_RANDIST_H
#define GSL_STUB_RANDIST_H
#include <cmath>
/* Stub of GSL's negative-binomial pmf:
   Gamma(n+k)/(Gamma(k+1)Gamma(n)) * p^n * (1-p)^k, in log space
   (the same formula runtime/cri.py's nbd_pmf evaluates). */
static inline double gsl_ran_negative_binomial_pdf(unsigned int k, double p, double n)
{
    double lg = std::lgamma(n + (double)k) - std::lgamma((double)k + 1.0)
        - std::lgamma(n);
    return std::exp(lg + n * std::log(p) + (double)k * std::log1p(-p));
}
#endif
"""

_EMPTY_GUARD = "#ifndef GSL_STUB_{0}_H\n#define GSL_STUB_{0}_H\n#endif\n"

# The reference's parallel-hashmap submodule (.gitmodules:1-3) is not
# initialized in this checkout (the directory is empty), so runtime v2
# builds against this stub instead. phmap::flat_hash_map is drop-in
# API-compatible with std::unordered_map for everything the runtime
# instantiates (Histogram = flat_hash_map<long,double>, _SharePRI's
# flat_hash_map<int,Histogram> — pluss_utils_v2.h:18,24), and every
# print path sorts through an ordered std::map first
# (pluss_utils_v2.h's _pluss_histogram_print), so the container swap
# cannot change dump content or order.
_PHMAP_STUB = """\
#ifndef PHMAP_STUB_H
#define PHMAP_STUB_H
#include <unordered_map>
namespace phmap {
template <class K, class V>
using flat_hash_map = std::unordered_map<K, V>;
}
#endif
"""


# Deterministic replacement for the libc rand() stream, injected into
# the r10 build via -include. The r10 sampler never calls srand (its
# mt19937 generators are initialized from time(NULL) but unused), so
# its rand() draws come from glibc's shared, lock-serialized default
# stream — deterministic per-thread partitioning is impossible because
# six sampler threads race for the next value (:3203-3251). A
# thread_local LCG seeded with a fixed constant gives every sampler
# thread its own identical, reproducible stream; the Python test
# replicates the same LCG to hand our engine the exact sample sets the
# binary drew.
_RAND_SHIM = """\
#ifndef PLUSS_TEST_RAND_SHIM_H
#define PLUSS_TEST_RAND_SHIM_H
#include <cstdlib>
inline thread_local unsigned long long _pluss_det_rand_state =
    0x243F6A8885A308D3ULL;
inline int _pluss_det_rand(void)
{
    _pluss_det_rand_state =
        _pluss_det_rand_state * 6364136223846793005ULL
        + 1442695040888963407ULL;
    return (int)((_pluss_det_rand_state >> 33) & 0x7fffffffULL);
}
/* libstdc++ spells std::rand in <bits/stl_algo.h>; the using-decl
   makes the macro expansion valid in both qualified and unqualified
   forms. */
namespace std { using ::_pluss_det_rand; }
#define rand _pluss_det_rand
#endif
"""


# Serializes the r10 binary's six sampler std::threads: pthread_create
# runs the sampler inline on the main thread (join becomes a no-op), so
# the -DDEBUG event log comes out in deterministic per-sampler order
# instead of six interleaved (and line-torn) streams. Definitions in
# the executable override libpthread's. Each "thread" resets the rand
# shim's thread_local state first, reproducing exactly the per-thread
# fresh streams the parallel binary gets from `thread_local` — the
# sample sets are identical either way.
_PTHREAD_SERIAL_SHIM = """\
#ifndef PLUSS_TEST_PTHREAD_SERIAL_H
#define PLUSS_TEST_PTHREAD_SERIAL_H
#include <pthread.h>
/* weak: the -include lands this header in every TU; the executable's
   (weak) definitions still win over libpthread's at dynamic link. */
extern "C" __attribute__((weak)) int pthread_create(
    pthread_t *t, const pthread_attr_t *, void *(*fn)(void *), void *arg)
{
    static unsigned long long _pluss_serial_tid = 1;
    *t = (pthread_t)_pluss_serial_tid++;
    _pluss_det_rand_state = 0x243F6A8885A308D3ULL;
    fn(arg);
    return 0;
}
extern "C" __attribute__((weak)) int pthread_join(pthread_t, void **)
{ return 0; }
extern "C" __attribute__((weak)) int pthread_detach(pthread_t)
{ return 0; }
extern "C" __attribute__((weak)) int pthread_setaffinity_np(
    pthread_t, size_t, const cpu_set_t *) { return 0; }
#endif
"""


def _build_reference(
    tmp_path_factory, threads: int, chunk: int,
    variant: str = "ri-omp-seq",
) -> str:
    """Build (once, cached) a reference sampler binary.

    THREAD_NUM/CHUNK_SIZE are the reference's compile-time -D macros
    (Makefile:14-15), so each machine geometry is its own binary —
    which lets the diff anchor our schedule arithmetic against the
    real reference at odd geometries too, not just the default 4x4.
    `variant` picks the sampler source: "ri-omp-seq" (the serial
    accuracy oracle), "ri-omp" (the PARA binary run.sh's acc protocol
    pairs with it; its omp pragma pins num_threads(1)), "ri-opt" (the
    fused-body sampler linking runtime v2 + the vendored
    parallel-hashmap, Makefile:22-23), or "rs-ri-opt-r10" (the
    random-start sampled binary, built with the deterministic rand
    shim above and -pthread for its six sampler threads).
    """
    if not os.path.isdir(REF):
        pytest.skip("reference checkout not present")
    if shutil.which("g++") is None:
        pytest.skip("no C++ toolchain")

    debug = variant.endswith("-debug")
    src_variant = variant[: -len("-debug")] if debug else variant
    runtime_src = "pluss_utils_v2" if src_variant == "ri-opt" else "pluss_utils"
    sources = [
        f"{REF}/sampler/gemm-t4-pluss-pro-model-{src_variant}.cpp",
        f"{REF}/runtime/pluss.cpp",
        f"{REF}/runtime/{runtime_src}.cpp",
    ]
    shim = _RAND_SHIM if src_variant == "rs-ri-opt-r10" else ""
    serial = _PTHREAD_SERIAL_SHIM if debug else ""
    # Flags from the reference Makefile:20-21, minus GSL/LTO (stubbed /
    # irrelevant for a correctness diff). {build} is substituted below.
    cmd_tail = [
        "-std=c++17", "-O2", "-fopenmp", f"-I{REF}/runtime",
        f"-DTHREAD_NUM={threads}", f"-DCHUNK_SIZE={chunk}",
        "-DDS=8", "-DCLS=64",
        *(["-DDEBUG"] if debug else []),
        *(["-pthread"] if shim else []),
        *sources, "-lm",
    ]
    # Cache key covers the stub, the compile line, and the reference
    # source contents — editing any of them rebuilds instead of
    # silently diffing against a stale oracle binary.
    h = hashlib.sha256()
    h.update(_GSL_RANDIST_STUB.encode())
    h.update(shim.encode())
    h.update(serial.encode())
    if src_variant == "ri-opt":
        h.update(_PHMAP_STUB.encode())
    h.update(" ".join(cmd_tail).encode())
    for src in sources + [f"{REF}/runtime/pluss.h", f"{REF}/runtime/{runtime_src}.h"]:
        with open(src, "rb") as f:
            h.update(f.read())
    cached = os.path.join(
        _REPO, ".refbuild",
        f"{variant}-t{threads}c{chunk}-{h.hexdigest()[:12]}",
    )
    if os.path.exists(cached):
        return cached

    build = tmp_path_factory.mktemp("refbuild")
    gsl = build / "gsl"
    gsl.mkdir()
    (gsl / "gsl_randist.h").write_text(_GSL_RANDIST_STUB)
    (gsl / "gsl_rng.h").write_text(_EMPTY_GUARD.format("RNG"))
    (gsl / "gsl_cdf.h").write_text(_EMPTY_GUARD.format("CDF"))
    if src_variant == "ri-opt":
        ph = build / "parallel_hashmap"
        ph.mkdir()
        (ph / "phmap.h").write_text(_PHMAP_STUB)

    out = build / "ri-omp-seq"
    pre = []
    if shim:
        (build / "rand_shim.h").write_text(shim)
        pre = ["-include", str(build / "rand_shim.h")]
    if serial:
        (build / "serial_shim.h").write_text(serial)
        pre += ["-include", str(build / "serial_shim.h")]
    cmd = ["g++", f"-I{build}", *pre, *cmd_tail, "-o", str(out)]
    proc = subprocess.run(cmd, capture_output=True, text=True, timeout=300)
    assert proc.returncode == 0, f"reference build failed:\n{proc.stderr}"

    os.makedirs(os.path.dirname(cached), exist_ok=True)
    shutil.copy2(out, cached)
    return cached


def _sections(text: str) -> dict[str, list[str]]:
    """Split an acc dump into its titled sections (order-preserving)."""
    titles = (
        "Start to dump noshare private reuse time",
        "Start to dump share private reuse time",
        "Start to dump reuse time",
        "miss ratio",
    )
    out: dict[str, list[str]] = {}
    current: list[str] | None = None
    for line in text.splitlines():
        line = line.strip()
        if line in titles:
            current = out.setdefault(line, [])
        elif line.startswith(
            ("max iteration", "SEQ C++", "PARA C++", "OPENMP C++")
        ) or not line:
            current = None
        elif current is not None:
            current.append(line)
    return out


def _max_iterations(text: str) -> int:
    lines = text.splitlines()
    for i, line in enumerate(lines):
        if line.startswith("max iteration traversed"):
            return int(lines[i + 1])  # reference format
        if line.startswith("max iteration count:"):
            return int(line.split(":")[1].split()[0])  # our CLI format
    raise AssertionError("no max-iteration line found")


# default machine, plus odd geometries that stress the chunk/ownership
# arithmetic (short last chunks, non-divisible thread counts)
# 3x5: 128 = 25*5 + 3 (short last chunk), 26 chunks % 3 threads != 0;
# 7x3: 128 = 42*3 + 2 (short last chunk), 43 chunks % 7 threads != 0
GEOMETRIES = [(4, 4), (3, 5), (7, 3)]


@pytest.mark.parametrize(
    "threads,chunk", GEOMETRIES, ids=lambda v: str(v)
)
def test_acc_dump_matches_reference(tmp_path_factory, threads, chunk):
    binary = _build_reference(tmp_path_factory, threads, chunk)
    ref = subprocess.run(
        [binary, "acc"], capture_output=True, text=True, timeout=300
    )
    assert ref.returncode == 0, ref.stderr

    ours = subprocess.run(
        [sys.executable, "-m", "pluss_sampler_optimization_tpu", "acc",
         "--model", "gemm", "--n", "128", "--engine", "oracle",
         "--threads", str(threads), "--chunk", str(chunk)],
        capture_output=True, text=True, timeout=600, cwd=_REPO,
        env={**os.environ, "JAX_PLATFORMS": "cpu"},
    )
    assert ours.returncode == 0, ours.stderr

    ref_sec = _sections(ref.stdout)
    our_sec = _sections(ours.stdout)
    assert set(ref_sec) == set(our_sec)
    for title in ref_sec:
        # Byte-equality line by line: same keys, same counts, same
        # 6-significant-digit fractions, same order.
        assert our_sec[title] == ref_sec[title], (
            f"t{threads}c{chunk} section {title!r} differs"
        )

    assert _max_iterations(ours.stdout) == _max_iterations(ref.stdout)


@pytest.mark.parametrize("threads,chunk", GEOMETRIES, ids=lambda v: str(v))
def test_acc_dump_matches_reference_v2_ri_opt(
    tmp_path_factory, threads, chunk
):
    """Third variant row: the fused-body `ri-opt` binary linking
    runtime v2 (phmap Histogram, raw noshare keys —
    pluss_utils_v2.h:915-918) vs our oracle engine under runtime-v2
    semantics. Its acc mode dumps the three histogram sections and an
    iteration count (ri-opt.cpp:332-358).

    One quirk is applied to OUR side before the byte-compare instead
    of being baked into the engine: ri-opt's `#pragma omp parallel for
    num_threads(1)` runs the tids serially, and every tid except the
    last breaks at the `!isInBound()` check (ri-opt.cpp:89-92) before
    reaching the termination block (:274-291) — so only tid
    THREAD_NUM-1 flushes its surviving LAT entries as -1 and adds its
    access clock to max_iteration_count. Our engine flushes every
    tid's survivors (the v1 oracle semantics every other variant
    shares); the test zeroes the other tids' -1 counts and expects the
    last tid's access clock, then byte-compares all three sections."""
    binary = _build_reference(tmp_path_factory, threads, chunk, "ri-opt")
    ref = subprocess.run(
        [binary, "acc"], capture_output=True, text=True, timeout=300
    )
    assert ref.returncode == 0, ref.stderr

    import numpy as np

    from pluss_sampler_optimization_tpu import MachineConfig
    from pluss_sampler_optimization_tpu.core.trace import ProgramTrace
    from pluss_sampler_optimization_tpu.models import REGISTRY
    from pluss_sampler_optimization_tpu.oracle import run_serial
    from pluss_sampler_optimization_tpu.runtime import report
    from pluss_sampler_optimization_tpu.runtime.cri import cri_distribute

    machine = MachineConfig(thread_num=threads, chunk_size=chunk)
    prog = REGISTRY["gemm"](128)
    res = run_serial(prog, machine, v2=True)

    # apply the last-tid-only flush quirk to a copy of the state
    last = threads - 1
    for tid in range(threads):
        if tid != last and -1 in res.state.noshare[tid]:
            del res.state.noshare[tid][-1]

    lines = report.noshare_dump(res.state)
    lines += report.share_dump(res.state)
    lines += report.rih_dump(
        cri_distribute(res.state, threads, threads)
    )
    our_sec = _sections("\n".join(lines))
    ref_sec = _sections(ref.stdout)
    # a parse/title drift must fail loudly, not compare zero sections
    assert set(ref_sec) == {
        "Start to dump noshare private reuse time",
        "Start to dump share private reuse time",
        "Start to dump reuse time",
    }
    for title, ref_lines in ref_sec.items():
        assert our_sec[title] == ref_lines, (
            f"v2 t{threads}c{chunk} section {title!r} differs"
        )

    # max_iteration_count == the last tid's access clock: per owned
    # c0, each ref contributes prod(trips of its inner levels)
    nt = ProgramTrace(prog, machine).nests[0]
    owner = np.asarray(
        nt.schedule.owner_tid(np.arange(nt.nest.loops[0].trip))
    )
    per_c0 = sum(
        int(np.prod([nt.nest.loops[l].trip
                     for l in range(1, int(nt.tables.ref_levels[j]) + 1)]))
        for j in range(nt.tables.n_refs)
    )
    expect = int((owner == last).sum()) * per_c0
    assert _max_iterations(ref.stdout) == expect


class _DetRand:
    """Python twin of the _RAND_SHIM LCG (same constants, same output
    derivation), used to replicate the binary's sample draws."""

    MUL = 6364136223846793005
    INC = 1442695040888963407

    def __init__(self):
        self.s = 0x243F6A8885A308D3

    def __call__(self) -> int:
        self.s = (self.s * self.MUL + self.INC) & 0xFFFFFFFFFFFFFFFF
        return (self.s >> 33) & 0x7FFFFFFF


def _draw_like_r10(depth: int, num_samples: int, mod: int):
    """Replicate one r10 sampler thread's draw loop: per attempt, one
    rand()%mod per loop level (:159-169 — mod = trip-1 excludes the last
    iteration), label-dedup'd until num_samples unique tuples (:177).
    Every sampler thread starts from the same thread_local shim state,
    so every same-depth ref draws this exact set."""
    import numpy as np

    rng = _DetRand()
    seen: set = set()
    out: list = []
    while len(out) < num_samples:
        t = tuple(rng() % mod for _ in range(depth))
        if t in seen:
            continue
        seen.add(t)
        out.append(t)
    return np.asarray(out, dtype=np.int64)


def _parse_r10_dump(text: str):
    """The r10 binary's stdout -> ({section title: {key: count}},
    run-length MRC points). Sections: six per-ref histograms titled by
    bare ref name (_pluss_histogram_print("C3", ...), :3281-3286), the
    merged "Start to dump reuse time" (:3287), and "miss ratio"
    (:3288); the timer line is a bare float and parses as neither."""
    hists: dict[str, dict] = {}
    mrc_pts: list = []
    titles = {"C3", "C2", "A0", "C0", "B0", "C1",
              "Start to dump reuse time"}
    current: dict | None = None
    in_mrc = False
    for raw in text.splitlines():
        line = raw.strip()
        if line in titles:
            current, in_mrc = hists.setdefault(line, {}), False
            continue
        if line == "miss ratio":
            current, in_mrc = None, True
            continue
        if line == "max iteration traversed":
            current, in_mrc = None, False
            continue
        parts = line.split(",")
        if in_mrc and len(parts) == 2:
            mrc_pts.append((int(parts[0]), float(parts[1])))
        elif current is not None and len(parts) == 3:
            current[int(parts[0])] = float(parts[1])
    return hists, mrc_pts


def _dense_mrc(points):
    """Run-length MRC points -> dense array (piecewise-constant fill;
    within a printed segment the true values deviate < 1e-5 from the
    segment head, pluss_utils.h:863)."""
    import numpy as np

    n = points[-1][0] + 1
    out = np.empty(n, dtype=np.float64)
    for (i, v), (j, _) in zip(points, points[1:] + [(n, 0.0)]):
        out[i:j] = v
    return out


def _rel_l1(a: dict, b: dict, normalize: bool = False) -> float:
    """sum |a-b| / sum a over the union support; `normalize` first
    scales both to unit mass (shape-only comparison)."""
    sa, sb = sum(a.values()), sum(b.values())
    fa, fb = (1.0 / sa, 1.0 / sb) if normalize else (1.0, 1.0)
    diff = sum(
        abs(a.get(k, 0.0) * fa - b.get(k, 0.0) * fb)
        for k in set(a) | set(b)
    )
    return diff / (1.0 if normalize else sa)


def test_r10_sampled_matches_reference(tmp_path_factory):
    """External anchor for the sampled path: run the ACTUAL r10 binary
    (deterministic rand shim) and diff its per-ref histograms, merged
    RIHist, and MRC against our explicit-sample engine fed the
    IDENTICAL sample sets (replicated draw loop), distributed with the
    R10Quirks model (runtime/cri.py).

    The comparison is shape-normalized, not byte-exact, for two
    walk-scheduling artifacts our sample-independent engine does not
    (and should not) reproduce:

    - the out-of-order check `samples_meet.size() >= samples.size()`
      (:356,:417,:499 and per-sampler copies) terminates the WHOLE
      sampler once the historically-met count reaches the remaining
      queue size — samples_meet is never pruned, so late in the run
      this drops still-unprocessed samples (measured: ~5.4% of A0's
      2098, ~1.9% of C0/C1's 164, ~0.3% of C3/C2);
    - a later sample's walk rewinds other simulated threads' cursors
      and can re-register an already-processed sample (sample_names is
      never pruned, :549-556), double-counting its reuse.

    Both scale every bin of a ref's histogram uniformly, so comparing
    unit-normalized histograms (plus a mass-ratio guard bounding the
    artifact) still pins the whole quirk model — exponent n-1, pow2
    point mass, 0.999 stop, degenerate share NBD, per-ref local
    distributes, B0 threshold 65792 — against the real binary: a
    misread quirk shifts histogram regions, not overall mass, and
    fails loudly."""
    binary = _build_reference(tmp_path_factory, 4, 4, "rs-ri-opt-r10")
    ref = subprocess.run(
        [binary], capture_output=True, text=True, timeout=600
    )
    assert ref.returncode == 0, ref.stderr
    ref_hists, ref_mrc_pts = _parse_r10_dump(ref.stdout)
    assert set(ref_hists) == {
        "C3", "C2", "A0", "C0", "B0", "C1", "Start to dump reuse time"
    }

    # Our side: identical sample sets through the closed-form engine +
    # r10 quirk distributes. Sample counts are the generated constants
    # (2098 for 3-deep refs :156, 164 for 2-deep :1688) at N=128,
    # mod 127 (the rand()%(trip-1) draw, :159).
    from pluss_sampler_optimization_tpu import MachineConfig
    from pluss_sampler_optimization_tpu.models import REGISTRY
    from pluss_sampler_optimization_tpu.runtime.aet import (
        aet_mrc,
        mrc_l1_error,
    )
    from pluss_sampler_optimization_tpu.runtime.cri import r10_distribute
    from pluss_sampler_optimization_tpu.sampler.sampled import (
        results_from_samples,
    )

    s3 = _draw_like_r10(3, 2098, 127)
    s2 = _draw_like_r10(2, 164, 127)
    machine = MachineConfig()
    results = results_from_samples(
        REGISTRY["gemm"](128), machine,
        {"C3": s3, "C2": s3, "A0": s3, "B0": s3, "C0": s2, "C1": s2},
    )
    assert {r.name: r.n_samples for r in results} == {
        "C3": 2098, "C2": 2098, "A0": 2098, "B0": 2098,
        "C0": 164, "C1": 164,
    }
    merged, per_ref = r10_distribute(results, machine.thread_num)

    for name in ("C3", "C2", "A0", "C0", "B0", "C1"):
        # bin support must agree exactly on every bin carrying >=1% of
        # the ref's mass (walk double-counting can add trace-mass bins)
        tot = sum(ref_hists[name].values())
        major_ref = {k for k, v in ref_hists[name].items() if v >= tot / 100}
        major_ours = {
            k for k, v in per_ref[name].items()
            if v >= sum(per_ref[name].values()) / 100
        }
        assert major_ref == major_ours, f"{name} major-bin support"
        assert _rel_l1(
            ref_hists[name], per_ref[name], normalize=True
        ) < 0.02, name
        # mass-ratio guard: the binary's early-exit drop is bounded
        # (<=6% observed on A0); a model error would not show up as a
        # uniform deficit on the reference side only
        ratio = tot / sum(per_ref[name].values())
        assert 0.90 < ratio < 1.005, f"{name} mass ratio {ratio}"

    assert _rel_l1(
        ref_hists["Start to dump reuse time"], merged, normalize=True
    ) < 0.02
    ours_mrc = aet_mrc(merged, machine)
    ref_mrc = _dense_mrc(ref_mrc_pts)
    assert mrc_l1_error(ours_mrc, ref_mrc) < 1e-2


def test_r10_exact_replay(tmp_path_factory):
    """EXACT external anchor for the sampled path (round-4 verdict
    item 5 — upgrades the 2%-band test above to per-ref bin equality).

    The band test tolerates two deterministic walk-scheduling
    artifacts; this test replays them exactly instead. The r10 binary
    is rebuilt with -DDEBUG and a pthread-serializing shim (its six
    sampler threads run inline, so the event log is ordered and
    untorn), and its OWN debug trace supplies the walk schedule: which
    samples were activated (met), which closed, in what order. Our
    side supplies every numeric quantity — each sample's closed-form
    reuse interval, share classification, owning thread and cache line
    (sampler/sampled.py closed forms), replayed through the walk's LAT
    semantics:

    - activation inserts the sample at (tid, line); a same-(tid, line)
      activation OVERWRITES the earlier entry (LAT[tid][addr] = count,
      r10 :616 — the shadowed sample never closes and never flushes);
    - a close records the sample's reuse (same value as the closed
      form: the walk visits every access of the sample's thread
      between source and sink) and erases the (tid, line) entry;
    - each walk start and the final END_SAMPLE flush surviving LAT
      entries as -1 cold — with the reference's own quirk that the
      tid-keyed loop `for (i < LAT.size()) { update(-1, LAT[i].size());
      LAT.clear(); }` clears inside the loop body, so ONLY simulated
      thread 0's survivors are ever counted (:196-200, :669-674);
    - samples with no activation at all (the samples_meet early-exit
      drop set, :356 etc.) contribute nothing.

    The replayed raw histograms then run our R10Quirks distributes and
    must match the binary's printed per-ref histograms bin for bin (to
    the dump's 6-significant-digit precision) — no band, no mass
    guard. A misread of ANY piece — reuse closed forms, share
    thresholds, LAT semantics, quirk distributes — breaks equality.
    """
    import re

    import numpy as np

    binary = _build_reference(
        tmp_path_factory, 4, 4, "rs-ri-opt-r10-debug"
    )
    proc = subprocess.Popen(
        [binary], stdout=subprocess.PIPE, stderr=subprocess.DEVNULL,
        text=True, bufsize=1 << 22,
    )
    pat = re.compile(r"(C3|C2|C0|C1|A0|B0) \((-?\d+(?:,-?\d+)*)\)")

    def ident(line):
        m = pat.search(line)
        assert m, line
        return m.group(1), tuple(int(x) for x in m.group(2).split(","))

    events: dict[str, list] = {}
    dump_lines: list[str] = []
    assert proc.stdout is not None
    for line in proc.stdout:
        line = line.rstrip("\n")
        if line.startswith("Start tracking sample "):
            name, ivs = ident(line)
            events.setdefault(name, []).append(("walk", ivs))
        elif line.startswith(("Meet the start sample ",
                              "Meet a new sample ")):
            name, ivs = ident(line)
            events.setdefault(name, []).append(("meet", ivs))
        elif line.startswith("delete sample ") or (
            "] for last sample " in line
        ):
            name, ivs = ident(line)
            events.setdefault(name, []).append(("close", ivs))
        elif (" @ " in line or line.startswith(
            ("Move ", "Jump ", "Skip ", "[", "sample_c", "Start track")
        )):
            continue  # high-volume walk noise
        else:
            dump_lines.append(line)
    assert proc.wait(timeout=600) == 0
    ref_hists, ref_mrc_pts = _parse_r10_dump("\n".join(dump_lines))

    # per-sample closed forms for the exact sets the binary drew
    from pluss_sampler_optimization_tpu import MachineConfig
    from pluss_sampler_optimization_tpu.core.trace import ProgramTrace
    from pluss_sampler_optimization_tpu.models import REGISTRY
    from pluss_sampler_optimization_tpu.runtime.aet import (
        aet_mrc,
        mrc_l1_error,
    )
    from pluss_sampler_optimization_tpu.runtime.cri import r10_distribute
    from pluss_sampler_optimization_tpu.sampler.sampled import (
        SampledRefResult,
        _sample_geometry,
        classify_samples,
    )

    machine = MachineConfig()
    prog = REGISTRY["gemm"](128)
    trace = ProgramTrace(prog, machine)
    nt = trace.nests[0]
    names = list(nt.tables.ref_names)
    s3 = _draw_like_r10(3, 2098, 127)
    s2 = _draw_like_r10(2, 164, 127)
    samples_by_ref = {
        "C3": s3, "C2": s3, "A0": s3, "B0": s3, "C0": s2, "C1": s2,
    }
    attrs: dict[str, dict] = {}
    for name, arr in samples_by_ref.items():
        ri = names.index(name)
        import jax.numpy as jnp

        sj = jnp.asarray(arr)
        packed, reuse, is_share, found = classify_samples(nt, ri, sj)
        tid, _p0, line, _m0 = _sample_geometry(nt, ri, sj)
        ratio = int(nt.tables.ref_share_ratios[ri])
        attrs[name] = {
            tuple(int(x) for x in row): {
                "reuse": int(rv), "share": bool(sh), "found": bool(fo),
                "tid": int(td), "line": int(ln), "ratio": ratio,
            }
            for row, rv, sh, fo, td, ln in zip(
                np.asarray(arr), np.asarray(reuse), np.asarray(is_share),
                np.asarray(found), np.asarray(tid), np.asarray(line),
            )
        }

    results = []
    for name in ("C3", "C2", "A0", "B0", "C0", "C1"):
        nosh: dict = {}
        share: dict = {}
        cold = 0.0
        lat: dict[int, dict] = {}
        first_walk = True
        for kind, ivs in events.get(name, []):
            a = attrs[name][ivs]
            if kind == "walk":
                if not first_walk:
                    cold += len(lat.get(0, {}))
                lat = {}
                first_walk = False
            elif kind == "meet":
                lat.setdefault(a["tid"], {})[a["line"]] = ivs
            else:  # close
                assert a["found"], (name, ivs)
                if a["share"]:
                    h = share.setdefault(a["ratio"], {})
                    h[a["reuse"]] = h.get(a["reuse"], 0.0) + 1.0
                else:
                    nosh[a["reuse"]] = nosh.get(a["reuse"], 0.0) + 1.0
                inner = lat.get(a["tid"])
                if inner is not None:
                    inner.pop(a["line"], None)
        cold += len(lat.get(0, {}))  # END_SAMPLE flush, same quirk
        results.append(SampledRefResult(
            name=name, noshare=nosh, share=share, cold=cold,
            n_samples=len(samples_by_ref[name]),
        ))
    merged, per_ref = r10_distribute(results, machine.thread_num)

    for name in ("C3", "C2", "A0", "B0", "C0", "C1"):
        ours = {k: v for k, v in per_ref[name].items() if v != 0.0}
        # the binary's walk-start flush calls update(-1, LAT[0].size())
        # even when tid 0 has no survivors, minting a zero-count -1 bin
        # (:196-200); compare nonzero support on both sides
        theirs = {k: v for k, v in ref_hists[name].items() if v != 0.0}
        assert set(ours) == set(theirs), (
            f"{name}: support differs "
            f"(ours-only {sorted(set(ours) - set(theirs))[:5]}, "
            f"theirs-only {sorted(set(theirs) - set(ours))[:5]})"
        )
        for k in ours:
            assert np.isclose(ours[k], theirs[k], rtol=2e-5), (
                name, k, ours[k], theirs[k]
            )
    merged_nz = {k: v for k, v in merged.items() if v != 0.0}
    ref_merged_nz = {
        k: v for k, v in ref_hists["Start to dump reuse time"].items()
        if v != 0.0
    }
    assert set(merged_nz) == set(ref_merged_nz)
    for k, v in merged_nz.items():
        assert np.isclose(v, ref_merged_nz[k], rtol=2e-5)
    ours_mrc = aet_mrc(merged, machine)
    ref_mrc = _dense_mrc(ref_mrc_pts)
    assert mrc_l1_error(ours_mrc, ref_mrc) < 1e-5


def test_acc_protocol_para_and_seq(tmp_path_factory):
    """The reference acc protocol runs the PARA binary then the SEQ
    binary and diffs (run.sh acc, Makefile:39-41). Reproduce it: both
    binaries' histogram sections must agree with each other and with
    our oracle dump (PARA emits no MRC section, so the comparison
    covers the three histogram dumps and the iteration count)."""
    seq = _build_reference(tmp_path_factory, 4, 4, "ri-omp-seq")
    para = _build_reference(tmp_path_factory, 4, 4, "ri-omp")
    out = {}
    for name, binary in (("seq", seq), ("para", para)):
        proc = subprocess.run(
            [binary, "acc"], capture_output=True, text=True, timeout=300
        )
        assert proc.returncode == 0, proc.stderr
        out[name] = proc
    ours = subprocess.run(
        [sys.executable, "-m", "pluss_sampler_optimization_tpu", "acc",
         "--model", "gemm", "--n", "128", "--engine", "oracle"],
        capture_output=True, text=True, timeout=600, cwd=_REPO,
        env={**os.environ, "JAX_PLATFORMS": "cpu"},
    )
    assert ours.returncode == 0, ours.stderr

    seq_sec = _sections(out["seq"].stdout)
    para_sec = _sections(out["para"].stdout)
    our_sec = _sections(ours.stdout)
    # a parse/title drift must fail loudly, not compare zero sections
    assert set(para_sec) == {
        "Start to dump noshare private reuse time",
        "Start to dump share private reuse time",
        "Start to dump reuse time",
    }
    for title, lines in para_sec.items():
        assert lines == seq_sec[title], f"PARA vs SEQ: {title!r}"
        assert lines == our_sec[title], f"PARA vs ours: {title!r}"
    for name in ("seq", "para"):
        assert _max_iterations(out[name].stdout) == _max_iterations(
            ours.stdout
        )
