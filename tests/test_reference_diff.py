"""Differential test against the ACTUAL reference implementation.

Every other parity test in this suite is port-vs-port inside this repo;
a shared misreading of the reference would be invisible to them. This
test closes that hole the way the reference itself validates accuracy
(c_lib/test/Makefile:39-41, README.md:10-12 — diff dumps across
implementations): it compiles the reference's own serial accuracy
oracle (c_lib/test/sampler/gemm-t4-pluss-pro-model-ri-omp-seq.cpp) with
g++, runs its `acc` mode, and byte-compares the noshare/share/RIHist
histogram dumps and the MRC against our oracle engine's CLI output.

GSL is not installed in this image; the only live GSL symbol is
`gsl_ran_negative_binomial_pdf` (pluss_utils.h:1002 — the geometric-cdf
use at :1177 is inside `#if 0`), so the build stubs it with the same
lgamma-space pmf formula our runtime/cri.py uses. The sampler hard-codes
N=128 (loop bounds are baked into the generated code), so the compare
runs at exactly that config.
"""

from __future__ import annotations

import hashlib
import os
import shutil
import subprocess
import sys

import pytest

REF = "/root/reference/c_lib/test"
_REPO = os.path.abspath(os.path.join(os.path.dirname(__file__), ".."))

_GSL_RANDIST_STUB = """\
#ifndef GSL_STUB_RANDIST_H
#define GSL_STUB_RANDIST_H
#include <cmath>
/* Stub of GSL's negative-binomial pmf:
   Gamma(n+k)/(Gamma(k+1)Gamma(n)) * p^n * (1-p)^k, in log space
   (the same formula runtime/cri.py's nbd_pmf evaluates). */
static inline double gsl_ran_negative_binomial_pdf(unsigned int k, double p, double n)
{
    double lg = std::lgamma(n + (double)k) - std::lgamma((double)k + 1.0)
        - std::lgamma(n);
    return std::exp(lg + n * std::log(p) + (double)k * std::log1p(-p));
}
#endif
"""

_EMPTY_GUARD = "#ifndef GSL_STUB_{0}_H\n#define GSL_STUB_{0}_H\n#endif\n"


def _build_reference(
    tmp_path_factory, threads: int, chunk: int,
    variant: str = "ri-omp-seq",
) -> str:
    """Build (once, cached) a reference sampler binary.

    THREAD_NUM/CHUNK_SIZE are the reference's compile-time -D macros
    (Makefile:14-15), so each machine geometry is its own binary —
    which lets the diff anchor our schedule arithmetic against the
    real reference at odd geometries too, not just the default 4x4.
    `variant` picks the sampler source: "ri-omp-seq" (the serial
    accuracy oracle) or "ri-omp" (the PARA binary run.sh's acc
    protocol pairs with it; its omp pragma pins num_threads(1)).
    """
    if not os.path.isdir(REF):
        pytest.skip("reference checkout not present")
    if shutil.which("g++") is None:
        pytest.skip("no C++ toolchain")

    sources = [
        f"{REF}/sampler/gemm-t4-pluss-pro-model-{variant}.cpp",
        f"{REF}/runtime/pluss.cpp",
        f"{REF}/runtime/pluss_utils.cpp",
    ]
    # Flags from the reference Makefile:20-21, minus GSL/LTO (stubbed /
    # irrelevant for a correctness diff). {build} is substituted below.
    cmd_tail = [
        "-std=c++17", "-O2", "-fopenmp", f"-I{REF}/runtime",
        f"-DTHREAD_NUM={threads}", f"-DCHUNK_SIZE={chunk}",
        "-DDS=8", "-DCLS=64",
        *sources, "-lm",
    ]
    # Cache key covers the stub, the compile line, and the reference
    # source contents — editing any of them rebuilds instead of
    # silently diffing against a stale oracle binary.
    h = hashlib.sha256()
    h.update(_GSL_RANDIST_STUB.encode())
    h.update(" ".join(cmd_tail).encode())
    for src in sources + [f"{REF}/runtime/pluss.h", f"{REF}/runtime/pluss_utils.h"]:
        with open(src, "rb") as f:
            h.update(f.read())
    cached = os.path.join(
        _REPO, ".refbuild",
        f"{variant}-t{threads}c{chunk}-{h.hexdigest()[:12]}",
    )
    if os.path.exists(cached):
        return cached

    build = tmp_path_factory.mktemp("refbuild")
    gsl = build / "gsl"
    gsl.mkdir()
    (gsl / "gsl_randist.h").write_text(_GSL_RANDIST_STUB)
    (gsl / "gsl_rng.h").write_text(_EMPTY_GUARD.format("RNG"))
    (gsl / "gsl_cdf.h").write_text(_EMPTY_GUARD.format("CDF"))

    out = build / "ri-omp-seq"
    cmd = ["g++", f"-I{build}", *cmd_tail, "-o", str(out)]
    proc = subprocess.run(cmd, capture_output=True, text=True, timeout=300)
    assert proc.returncode == 0, f"reference build failed:\n{proc.stderr}"

    os.makedirs(os.path.dirname(cached), exist_ok=True)
    shutil.copy2(out, cached)
    return cached


def _sections(text: str) -> dict[str, list[str]]:
    """Split an acc dump into its titled sections (order-preserving)."""
    titles = (
        "Start to dump noshare private reuse time",
        "Start to dump share private reuse time",
        "Start to dump reuse time",
        "miss ratio",
    )
    out: dict[str, list[str]] = {}
    current: list[str] | None = None
    for line in text.splitlines():
        line = line.strip()
        if line in titles:
            current = out.setdefault(line, [])
        elif line.startswith(
            ("max iteration", "SEQ C++", "PARA C++", "OPENMP C++")
        ) or not line:
            current = None
        elif current is not None:
            current.append(line)
    return out


def _max_iterations(text: str) -> int:
    lines = text.splitlines()
    for i, line in enumerate(lines):
        if line.startswith("max iteration traversed"):
            return int(lines[i + 1])  # reference format
        if line.startswith("max iteration count:"):
            return int(line.split(":")[1].split()[0])  # our CLI format
    raise AssertionError("no max-iteration line found")


# default machine, plus odd geometries that stress the chunk/ownership
# arithmetic (short last chunks, non-divisible thread counts)
# 3x5: 128 = 25*5 + 3 (short last chunk), 26 chunks % 3 threads != 0;
# 7x3: 128 = 42*3 + 2 (short last chunk), 43 chunks % 7 threads != 0
GEOMETRIES = [(4, 4), (3, 5), (7, 3)]


@pytest.mark.parametrize(
    "threads,chunk", GEOMETRIES, ids=lambda v: str(v)
)
def test_acc_dump_matches_reference(tmp_path_factory, threads, chunk):
    binary = _build_reference(tmp_path_factory, threads, chunk)
    ref = subprocess.run(
        [binary, "acc"], capture_output=True, text=True, timeout=300
    )
    assert ref.returncode == 0, ref.stderr

    ours = subprocess.run(
        [sys.executable, "-m", "pluss_sampler_optimization_tpu", "acc",
         "--model", "gemm", "--n", "128", "--engine", "oracle",
         "--threads", str(threads), "--chunk", str(chunk)],
        capture_output=True, text=True, timeout=600, cwd=_REPO,
        env={**os.environ, "JAX_PLATFORMS": "cpu"},
    )
    assert ours.returncode == 0, ours.stderr

    ref_sec = _sections(ref.stdout)
    our_sec = _sections(ours.stdout)
    assert set(ref_sec) == set(our_sec)
    for title in ref_sec:
        # Byte-equality line by line: same keys, same counts, same
        # 6-significant-digit fractions, same order.
        assert our_sec[title] == ref_sec[title], (
            f"t{threads}c{chunk} section {title!r} differs"
        )

    assert _max_iterations(ours.stdout) == _max_iterations(ref.stdout)


def test_acc_protocol_para_and_seq(tmp_path_factory):
    """The reference acc protocol runs the PARA binary then the SEQ
    binary and diffs (run.sh acc, Makefile:39-41). Reproduce it: both
    binaries' histogram sections must agree with each other and with
    our oracle dump (PARA emits no MRC section, so the comparison
    covers the three histogram dumps and the iteration count)."""
    seq = _build_reference(tmp_path_factory, 4, 4, "ri-omp-seq")
    para = _build_reference(tmp_path_factory, 4, 4, "ri-omp")
    out = {}
    for name, binary in (("seq", seq), ("para", para)):
        proc = subprocess.run(
            [binary, "acc"], capture_output=True, text=True, timeout=300
        )
        assert proc.returncode == 0, proc.stderr
        out[name] = proc
    ours = subprocess.run(
        [sys.executable, "-m", "pluss_sampler_optimization_tpu", "acc",
         "--model", "gemm", "--n", "128", "--engine", "oracle"],
        capture_output=True, text=True, timeout=600, cwd=_REPO,
        env={**os.environ, "JAX_PLATFORMS": "cpu"},
    )
    assert ours.returncode == 0, ours.stderr

    seq_sec = _sections(out["seq"].stdout)
    para_sec = _sections(out["para"].stdout)
    our_sec = _sections(ours.stdout)
    # a parse/title drift must fail loudly, not compare zero sections
    assert set(para_sec) == {
        "Start to dump noshare private reuse time",
        "Start to dump share private reuse time",
        "Start to dump reuse time",
    }
    for title, lines in para_sec.items():
        assert lines == seq_sec[title], f"PARA vs SEQ: {title!r}"
        assert lines == our_sec[title], f"PARA vs ours: {title!r}"
    for name in ("seq", "para"):
        assert _max_iterations(out[name].stdout) == _max_iterations(
            ours.stdout
        )
