"""Replica-pool serving: device-group partitioning, load-aware
routing, work stealing, and failure quarantine
(service/replicas.py + the executor/api/cli wiring around it).

The ISSUE-10 acceptance invariants are pinned here: MRC bytes and
ledger `mrc_digest` are BIT-IDENTICAL at replicas ∈ {1, 2, 4} on the
8-device virtual CPU mesh, batching on AND off (replica count is a
pure perf knob — sample streams are seed-derived, never
device-derived); K distinct concurrent requests land on ≥ 2 distinct
replica ids; a replica whose execution raises is quarantined and its
work re-routes WITHOUT failing the request, visibly in serve `stats`,
the live registry, `check_ledger --stats`, and the SLO error budget;
`--max-workers` below the replica count clamps up with a warning; and
the satellite flags (`--compilation-cache-dir`,
`--warmup-from-ledger`) cut compile work out of the request path,
pinned via per-row compile-counter deltas across real processes.
"""

import json
import os
import subprocess
import sys
import threading
import time

import numpy as np
import pytest

from pluss_sampler_optimization_tpu import MachineConfig, SamplerConfig
from pluss_sampler_optimization_tpu.config import (
    ReplicaConfig,
    ResilienceConfig,
    SLOConfig,
)
from pluss_sampler_optimization_tpu.runtime import (
    lockwitness,
    telemetry,
)
from pluss_sampler_optimization_tpu.runtime.aet import aet_mrc
from pluss_sampler_optimization_tpu.runtime.cri import cri_distribute
from pluss_sampler_optimization_tpu.runtime.obs import (
    ledger as obs_ledger,
)
from pluss_sampler_optimization_tpu.runtime.obs import (
    metrics as obs_metrics,
)
from pluss_sampler_optimization_tpu.runtime.obs import slo as obs_slo
from pluss_sampler_optimization_tpu.sampler.sampled import run_sampled
from pluss_sampler_optimization_tpu.service import (
    AnalysisRequest,
    AnalysisService,
    ReplicaPool,
)
from pluss_sampler_optimization_tpu.service.executor import (
    RequestExecutor,
    default_runner,
)
from pluss_sampler_optimization_tpu.service.cache import ResultCache

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, os.path.join(REPO_ROOT, "tools"))
import check_ledger  # noqa: E402


@pytest.fixture(autouse=True)
def _clean_telemetry():
    telemetry.disable()
    obs_metrics.disable()
    yield
    telemetry.disable()
    obs_metrics.disable()


def _sampled_req(**kw):
    base = dict(model="gemm", n=16, engine="sampled", ratio=0.3,
                seed=1)
    base.update(kw)
    return AnalysisRequest(**base)


def _solo_mrc(req):
    machine = req.machine()
    state, _results = run_sampled(
        req.build_program(), machine,
        SamplerConfig(ratio=req.ratio, seed=req.seed),
    )
    T = machine.thread_num
    return aet_mrc(cri_distribute(state, T, T), machine)


def _flaky_runner(fail_times: int):
    """A runner that raises on its first `fail_times` calls, then
    defers to the real engine — the injected replica fault."""
    state = {"left": fail_times}
    lock = threading.Lock()

    def runner(engine, program, machine, request):
        with lock:
            if state["left"] > 0:
                state["left"] -= 1
                raise RuntimeError("injected replica fault")
        return default_runner(engine, program, machine, request)

    return runner


# -- config / pool mechanics ------------------------------------------


def test_replica_config_resolve():
    import jax

    n = len(jax.devices())
    assert ReplicaConfig().resolve(n) == n  # auto: one per device
    assert ReplicaConfig(count=0).resolve(n) == n  # 0 = auto too
    assert ReplicaConfig(count=2).resolve(n) == 2
    assert ReplicaConfig(count=99).resolve(n) == n  # clamped
    with pytest.raises(ValueError):
        ReplicaConfig(count=-1)
    with pytest.raises(ValueError):
        ReplicaConfig().resolve(0)


def test_pool_partitions_devices_disjointly():
    import jax

    pool = ReplicaPool(ReplicaConfig(count=3))
    try:
        groups = [r.devices for r in pool.replicas]
        flat = [d for g in groups for d in g]
        assert len(flat) == len(jax.devices())
        assert len(set(flat)) == len(flat)  # disjoint
        sizes = sorted(len(g) for g in groups)
        assert sizes[-1] - sizes[0] <= 1  # near-equal
        assert all(r.mesh is not None for r in pool.replicas)
    finally:
        pool.close()


def test_pool_routes_least_loaded_and_steals():
    """A blocked replica cannot strand queued work: unpinned items
    route to the least-loaded replica, and an idle replica steals from
    the longest peer queue (windows_stolen counts it)."""
    tele = telemetry.enable()
    pool = ReplicaPool(ReplicaConfig(count=2))
    try:
        g0, g1 = threading.Event(), threading.Event()
        f0 = pool.submit(lambda: g0.wait(10), replica_id=0,
                         pinned=True)
        f1 = pool.submit(lambda: g1.wait(10), replica_id=1,
                         pinned=True)
        fa = pool.submit(lambda: "a")
        fb = pool.submit(lambda: "b")
        g1.set()  # replica 1 frees first: drains its queue, steals
        assert {fa.result(10)[0], fb.result(10)[0]} == {"a", "b"}
        g0.set()
        f0.result(10)
        f1.result(10)
        snap = pool.snapshot()
        assert sum(r["stolen"] for r in snap["replicas"]) >= 1
        assert tele.counters.get("windows_stolen", 0) >= 1
        assert tele.counters.get("requests_routed", 0) == 4
        assert sum(r["served"] for r in snap["replicas"]) == 4
    finally:
        pool.close()
        telemetry.disable()


def test_pool_close_fails_pending():
    pool = ReplicaPool(ReplicaConfig(count=1))
    gate = threading.Event()
    blocker = pool.submit(lambda: gate.wait(10), replica_id=0,
                          pinned=True)
    pending = pool.submit(lambda: "never")
    gate.set()
    blocker.result(10)
    pool.close()
    # queued-but-unstarted work fails rather than hanging; the
    # blocker itself completed
    if not pending.done():
        pytest.skip("pending item won the race and executed")
    try:
        pending.result(0)
    except RuntimeError as e:
        assert "closed" in str(e)


# -- the tentpole contract: bit-identity ------------------------------


def test_bit_identity_across_replica_counts(tmp_path):
    """MRC bytes and ledger mrc_digest are identical at replicas
    ∈ {1, 2, 4}, batching on AND off, and equal to the solo engine
    run — replica count is a pure perf knob.  The full matrix runs a
    single request (every distinct (shape, leader-device) pair is a
    fresh XLA compile, and this test must fit the tier-1 budget); one
    extra k=2 batched config fuses a two-model pair so multi-model
    windows are covered too."""
    gemm16 = _sampled_req(n=16, seed=1)
    pair = [gemm16, _sampled_req(model="2mm", n=12, ratio=0.25, seed=11)]
    want = {r.fingerprint(): _solo_mrc(r) for r in pair}
    configs = [(k, w, [gemm16])
               for k in (1, 2, 4) for w in (None, 200.0)]
    configs.append((2, 200.0, pair))
    for i, (k, window, reqs) in enumerate(configs):
        tag = f"c{i}_r{k}_w{window}"
        ledger_path = str(tmp_path / f"{tag}.jsonl")
        with AnalysisService(
            cache_dir=str(tmp_path / tag),
            ledger_path=ledger_path, replicas=k,
            batch_window_ms=window,
        ) as svc:
            tickets = [svc.submit(r) for r in reqs]
            resps = [svc.result(t, timeout=300) for t in tickets]
        assert all(r.ok for r in resps), (tag, resps)
        for req, resp in zip(reqs, resps):
            mrc = want[req.fingerprint()]
            assert np.asarray(resp.mrc).tobytes() == mrc.tobytes(), tag
            assert resp.mrc_digest == obs_ledger.mrc_digest(mrc)
            assert resp.replica_id in range(k)
        rows = [r for r in obs_ledger.read_rows(ledger_path)
                if r.get("kind") == "request"]
        assert {r["mrc_digest"] for r in rows} == {
            obs_ledger.mrc_digest(want[r.fingerprint()]) for r in reqs
        }
        assert all(r.get("replica_id") in range(k) for r in rows)


def test_concurrent_requests_spread_across_replicas(tmp_path):
    """K=4 distinct concurrent requests at replicas=4 (batching off)
    execute on ≥ 2 distinct replica ids, and every surface — the
    responses, serve `stats`, the ledger aggregate, and
    check_ledger --stats — reports the same per-replica counts.
    The replica pool runs under the lockdep witness: zero lock-order
    inversions, and MRC bytes bit-identical to a witness-off pass."""
    # distinct fingerprints via seed, IDENTICAL shapes via (n, ratio):
    # the spread proof doesn't need per-request recompiles
    reqs = [_sampled_req(seed=s) for s in (1, 2, 3, 4)]
    ledger_path = str(tmp_path / "ledger.jsonl")
    tele = telemetry.enable()
    lockwitness.reset()
    lockwitness.enable()
    with AnalysisService(
        cache_dir=str(tmp_path / "store"), ledger_path=ledger_path,
        replicas=4,
    ) as svc:
        tickets = [svc.submit(r) for r in reqs]
        resps = [svc.result(t, timeout=300) for t in tickets]
        snap = svc.stats()["executor"]["replicas"]
        health = svc.healthz()
    telemetry.disable()
    witness = lockwitness.report()
    lockwitness.disable()
    lockwitness.reset()
    assert witness["inversion_count"] == 0, witness["inversions"]
    assert all(r.ok for r in resps)
    # pure-observer proof: a witness-off pool serves the same
    # requests with bit-identical MRC bytes
    with AnalysisService(
        cache_dir=str(tmp_path / "store_off"), replicas=4,
    ) as svc_off:
        off = [svc_off.result(t, timeout=300)
               for t in [svc_off.submit(r) for r in reqs]]
    assert all(r.ok for r in off)
    for a, b in zip(resps, off):
        assert np.asarray(a.mrc).tobytes() == np.asarray(b.mrc).tobytes()
    rids = {r.replica_id for r in resps}
    assert len(rids) >= 2  # the concurrency proof
    assert all(r in range(4) for r in rids)
    assert health["replicas"] == 4
    assert health["replicas_quarantined"] == 0

    # stats vs responses
    assert snap["count"] == 4
    by_rid = {r["replica_id"]: r for r in snap["replicas"]}
    for rid in rids:
        assert by_rid[rid]["served"] >= 1
    assert sum(r["served"] for r in snap["replicas"]) == len(reqs)
    assert tele.counters.get("requests_routed") == len(reqs)
    for rid in rids:
        assert tele.counters.get(f"requests_routed_r{rid}", 0) >= 1

    # ledger aggregate vs responses
    rows = obs_ledger.read_rows(ledger_path)
    full_agg = obs_ledger.aggregate(rows)
    agg = full_agg["replicas"]
    assert set(agg) == rids
    assert sum(r["rows"] for r in agg.values()) == len(reqs)
    stats_text = "\n".join(obs_ledger.format_stats(full_agg))
    assert "replicas:" in stats_text


def test_check_ledger_stats_reports_replicas(tmp_path, capsys):
    ledger_path = str(tmp_path / "ledger.jsonl")
    with AnalysisService(
        cache_dir=str(tmp_path / "store"), ledger_path=ledger_path,
        replicas=2,
    ) as svc:
        assert svc.analyze(_sampled_req(), timeout=300).ok
    assert check_ledger.main([ledger_path, "--stats"]) == 0
    out = capsys.readouterr().out
    assert "replicas:" in out


# -- failure quarantine (satellite 4) ---------------------------------


def test_quarantine_reroutes_solo_request(tmp_path):
    """An execution fault quarantines the replica and re-routes the
    request to a healthy peer WITHOUT failing it: the response is ok
    and bit-identical to solo, the re-route is a degradation event,
    and `stats`, the live registry, check_ledger --stats, and the SLO
    error budget all see it."""
    req = _sampled_req()
    ledger_path = str(tmp_path / "ledger.jsonl")
    reg = obs_metrics.enable()
    tele = telemetry.enable()
    with AnalysisService(
        cache_dir=str(tmp_path / "store"), ledger_path=ledger_path,
        replicas=2, runner=_flaky_runner(1),
    ) as svc:
        resp = svc.result(svc.submit(req), timeout=300)
        snap = svc.stats()["executor"]["replicas"]
        health = svc.healthz()
    telemetry.disable()

    assert resp.ok and resp.error is None
    assert np.asarray(resp.mrc).tobytes() == _solo_mrc(req).tobytes()
    assert resp.degraded and any(
        "replica quarantined" in d["reason"] for d in resp.degraded
    )
    hop = resp.degraded[0]
    assert hop["from"].startswith("replica:")
    assert hop["to"] == f"replica:{resp.replica_id}"

    # stats: exactly one replica quarantined, with the reason
    assert health["replicas_quarantined"] == 1
    assert snap["quarantined"] == 1
    bad = [r for r in snap["replicas"] if r["quarantined"]]
    assert len(bad) == 1 and "injected replica fault" in \
        bad[0]["quarantine_reason"]
    assert bad[0]["failed"] == 1

    # telemetry + live registry (PR 9 surface)
    assert tele.counters.get("replica_quarantined") == 1
    assert tele.counters.get("service_degraded") == 1
    assert reg.counter("replica_quarantined") == 1
    ev = [e for e in tele.events if e["name"] == "replica_quarantined"]
    assert ev and ev[0]["replica"] == bad[0]["replica_id"]

    # the SLO error budget burns on the degradation
    sentinel = obs_slo.SLOSentinel(
        SLOConfig(error_budget=0.01), registry=reg
    )
    report = sentinel.evaluate_once()
    budget = {c["name"]: c for c in report["checks"]}["error_budget"]
    assert budget["ok"] is False

    # degraded results are never persisted: a fresh service over the
    # same store must execute again
    tele2 = telemetry.enable()
    with AnalysisService(cache_dir=str(tmp_path / "store")) as svc2:
        again = svc2.analyze(req, timeout=300)
    telemetry.disable()
    assert again.ok and again.cache == "miss"
    assert tele2.counters.get("service_exec_started") == 1

    # ledger row: served by the re-route target, marked degraded
    rows = obs_ledger.read_rows(ledger_path)
    row = [r for r in rows if r.get("kind") == "request"][0]
    assert row["replica_id"] == resp.replica_id
    assert row["degraded"]


def test_quarantine_reroutes_batch_window(tmp_path):
    """The batch path: a fault inside the shared window execution
    re-routes the WHOLE window to a healthy replica; every member
    completes ok, bit-identical to solo, attributed to the peer."""
    # same shapes as the bit-identity pair config: the fused-window
    # kernels are already compiled, only the re-route leader is cold
    reqs = [_sampled_req(n=16, seed=1),
            _sampled_req(model="2mm", n=12, ratio=0.25, seed=11)]
    tele = telemetry.enable()
    with AnalysisService(
        cache_dir=str(tmp_path / "store"),
        ledger_path=str(tmp_path / "ledger.jsonl"),
        replicas=2, batch_window_ms=300.0,
    ) as svc:
        calls = {"n": 0}
        real = svc.executor.batch_runner
        lock = threading.Lock()

        def flaky_batch_runner(jobs):
            with lock:
                calls["n"] += 1
                if calls["n"] == 1:
                    raise RuntimeError("injected window fault")
            return real(jobs)

        svc.executor.batch_runner = flaky_batch_runner
        tickets = [svc.submit(r) for r in reqs]
        resps = [svc.result(t, timeout=300) for t in tickets]
        snap = svc.stats()["executor"]["replicas"]
    telemetry.disable()
    assert all(r.ok for r in resps)
    assert snap["quarantined"] == 1
    # the window re-ran as one unit on the peer — not member-by-member
    assert calls["n"] == 2
    assert len({r.replica_id for r in resps}) == 1
    for req, resp in zip(reqs, resps):
        assert np.asarray(resp.mrc).tobytes() == \
            _solo_mrc(req).tobytes()
        assert resp.degraded and any(
            "replica quarantined" in d["reason"]
            for d in resp.degraded
        )
    assert tele.counters.get("replica_quarantined") == 1


def test_second_failure_propagates_to_engine_chain(tmp_path):
    """A re-routed item that fails AGAIN is the work's fault: the
    second replica is NOT quarantined and the request falls through
    to the normal engine-degradation handling (engine=sampled has no
    fallback, so the request fails — but the pool stays serving)."""
    with AnalysisService(
        cache_dir=str(tmp_path / "store"), replicas=2,
        runner=_flaky_runner(2),
    ) as svc:
        resp = svc.result(svc.submit(_sampled_req()), timeout=300)
        snap = svc.stats()["executor"]["replicas"]
        # the pool still serves: a healthy request after the fault
        ok = svc.result(svc.submit(_sampled_req(seed=9)), timeout=300)
    assert not resp.ok and "injected replica fault" in resp.error
    assert snap["quarantined"] == 1  # only the FIRST replica
    assert ok.ok


def test_broken_replica_recovers_after_probation(tmp_path):
    """ISSUE-14: the one-shot quarantine is now a circuit breaker. A
    replica opened by an execution fault leaves routing only for its
    probation window; the next route after probation is its half-open
    probe, probe success re-closes the breaker, and everything the
    recovered replica serves is bit-identical to solo."""
    res = ResilienceConfig(breaker_probation_s=0.25)
    tele = telemetry.enable()
    with AnalysisService(
        cache_dir=str(tmp_path / "store"), replicas=2,
        runner=_flaky_runner(1), resilience=res,
    ) as svc:
        first = svc.analyze(_sampled_req(seed=21), timeout=300)
        snap_open = svc.stats()["executor"]["replicas"]
        time.sleep(0.3)  # probation elapses; next route is the probe
        after = [svc.analyze(_sampled_req(seed=22 + k), timeout=300)
                 for k in range(3)]
        snap_closed = svc.stats()["executor"]["replicas"]
    telemetry.disable()

    assert first.ok and first.degraded  # the fault re-routed, ok
    assert snap_open["quarantined"] == 1
    (opened,) = [r for r in snap_open["replicas"] if r["quarantined"]]
    assert opened["breaker"] == "open"
    assert opened["reopen_in_s"] <= 0.25

    assert all(r.ok for r in after)
    for k, resp in enumerate(after):
        assert np.asarray(resp.mrc).tobytes() == \
            _solo_mrc(_sampled_req(seed=22 + k)).tobytes()
    # probe success re-closed the breaker: nothing is quarantined and
    # the recovered replica is back with `reclosed` standing
    assert snap_closed["quarantined"] == 0
    rec = [r for r in snap_closed["replicas"]
           if r["replica_id"] == opened["replica_id"]][0]
    assert rec["breaker"] == "closed" and rec["breaker_reclosed"] >= 1
    assert rec["completed"] > opened["completed"]  # it served again
    assert tele.counters.get("replica_breaker_half_open") == 1
    assert tele.counters.get("replica_breaker_reclosed") == 1


# -- max-workers clamp (satellite 3) ----------------------------------


def test_max_workers_clamped_to_replica_count(capsys):
    tele = telemetry.enable()
    ex = RequestExecutor(ResultCache(None), max_workers=1, replicas=4)
    try:
        assert len(ex._replicas) == 4
        assert ex._pool._max_workers == 4
        assert tele.counters.get("max_workers_clamped") == 1
        ev = [e for e in tele.events if e["name"] == "warning"]
        assert ev and "clamped" in ev[0]["message"]
    finally:
        ex.shutdown()
        telemetry.disable()
    assert "clamped" in capsys.readouterr().err


# -- warm start (satellite 2) -----------------------------------------


def test_warm_from_ledger_precompiles(tmp_path):
    """Ledger-driven warm start: a fresh service warms the most
    frequent fingerprints on every replica, so the first real request
    records a zero backend-compile delta in its ledger row."""
    req = _sampled_req(ratio=0.2)
    led1 = str(tmp_path / "run1.jsonl")
    with AnalysisService(
        cache_dir=str(tmp_path / "c1"), ledger_path=led1
    ) as svc:
        assert svc.analyze(req, timeout=300).ok
    rows1 = [r for r in obs_ledger.read_rows(led1)
             if r.get("kind") == "request"]
    assert isinstance(rows1[0].get("request"), dict)

    # "restart": a fresh service over the SAME ledger, fresh result
    # store (so the request really executes)
    with AnalysisService(
        cache_dir=str(tmp_path / "c2"), ledger_path=led1, replicas=2,
    ) as svc2:
        warmed = svc2.warm_from_ledger(4)
        assert warmed == 2  # one structure × two replicas
        assert svc2.warm_from_ledger(4) == 0  # structure-keyed: free
        resp = svc2.analyze(req, timeout=300)
    assert resp.ok
    rows2 = [r for r in obs_ledger.read_rows(led1)
             if r.get("kind") == "request"]
    delta = rows2[-1].get("compile_delta") or {}
    assert delta.get("backend_compiles", 0) == 0


# -- CLI flags --------------------------------------------------------


def test_cli_replica_flag_validation(tmp_path):
    from pluss_sampler_optimization_tpu.cli import main

    base = ["acc", "--model", "gemm", "--n", "12", "--engine",
            "sampled"]
    with pytest.raises(SystemExit):
        main(base + ["--replicas", "2"])  # needs --cache-dir/serve
    with pytest.raises(SystemExit):
        main(base + ["--cache-dir", str(tmp_path / "s"),
                     "--replicas", "-1"])
    with pytest.raises(SystemExit):
        main(base + ["--warmup-from-ledger", "2"])  # serve-only
    with pytest.raises(SystemExit):
        main(["serve", "--warmup-from-ledger", "2"])  # needs --ledger


def test_cli_acc_with_replicas(tmp_path, capsys):
    from pluss_sampler_optimization_tpu.cli import main

    rc = main([
        "acc", "--model", "gemm", "--n", "12", "--engine", "sampled",
        "--cache-dir", str(tmp_path / "store"), "--replicas", "2",
    ])
    capsys.readouterr()
    assert rc == 0


# -- cross-process satellites (1 + 2) ---------------------------------


def test_compilation_cache_and_ledger_warm_across_processes(tmp_path):
    """Satellite 1+2 end to end, across REAL processes: a cold run
    with --compilation-cache-dir populates the persistent jit cache
    and writes replayable ledger rows; a second process hits the
    persistent cache (fewer backend compiles); a serve process with
    --warmup-from-ledger compiles before admitting requests, so its
    request row shows a zero backend-compile delta."""
    comp_dir = str(tmp_path / "jit_cache")
    env = {**os.environ, "JAX_PLATFORMS": "cpu"}
    env.pop("XLA_FLAGS", None)  # single-device child: cheapest

    def run_acc(tag):
        cmd = [
            sys.executable, "-m",
            "pluss_sampler_optimization_tpu.cli", "acc",
            "--model", "gemm", "--n", "12", "--engine", "sampled",
            "--ratio", "0.2",
            "--cache-dir", str(tmp_path / f"store_{tag}"),
            "--ledger", str(tmp_path / f"{tag}.jsonl"),
            "--compilation-cache-dir", comp_dir,
        ]
        subprocess.run(cmd, check=True, cwd=REPO_ROOT, env=env,
                       capture_output=True, timeout=300)
        rows = [r for r in obs_ledger.read_rows(
                    str(tmp_path / f"{tag}.jsonl"))
                if r.get("kind") == "request"]
        return rows[0].get("compile_delta") or {}

    cold = run_acc("cold")
    assert cold.get("backend_compiles", 0) > 0
    assert cold.get("cache_misses", 0) > 0
    assert cold.get("cache_hits", 0) == 0
    assert os.listdir(comp_dir)  # satellite 1: the cache exists

    warm = run_acc("warm")
    # satellite 1 payoff: the warm process's compiles are persistent
    # cache hits, not fresh XLA compilations — misses drop to zero
    # and the backend-compile wall time collapses
    assert warm.get("cache_hits", 0) > 0
    assert warm.get("cache_misses", 0) < cold["cache_misses"]
    assert warm.get("backend_compile_s", 0.0) < \
        cold.get("backend_compile_s", 0.0)

    # satellite 2: serve --warmup-from-ledger replays the cold run's
    # ledger (the restart scenario: the service resumes its own
    # ledger); the request itself then compiles nothing
    import shutil

    serve_ledger = str(tmp_path / "serve.jsonl")
    shutil.copy(str(tmp_path / "cold.jsonl"), serve_ledger)
    line = json.dumps({
        "id": "w", "model": "gemm", "n": 12, "engine": "sampled",
        "ratio": 0.2, "seed": 1,
    }) + "\n"
    out = subprocess.run(
        [
            sys.executable, "-m",
            "pluss_sampler_optimization_tpu.cli", "serve",
            "--cache-dir", str(tmp_path / "store_serve"),
            "--ledger", serve_ledger,
            "--warmup-from-ledger", "2",
        ],
        input=line, text=True, check=True, cwd=REPO_ROOT, env=env,
        capture_output=True, timeout=300,
    )
    assert json.loads(out.stdout.splitlines()[0])["ok"]
    assert "warmed 1" in out.stderr
    rows = [r for r in obs_ledger.read_rows(serve_ledger)
            if r.get("kind") == "request"]
    delta = rows[-1].get("compile_delta") or {}
    assert delta.get("backend_compiles", 0) == 0


# -- bench extra (satellite 6) ----------------------------------------


def test_bench_replica_scaling_extra():
    """The bench evidence extra at test scale: bit-identity across
    all three configurations and all four replicas exercised."""
    sys.path.insert(0, REPO_ROOT)
    import bench

    # distinct fingerprints (seed), one shape set (n=16 @ default
    # ratio, compiled by the earlier tests) — the scaling/bit-identity
    # evidence shape at tier-1 cost
    reqs = [_sampled_req(seed=s) for s in (11, 12, 13, 14)]
    rs = bench.replica_scaling_extra(reqs, timeout=300)
    assert "error" not in rs
    assert rs["bit_identical"]
    for label in ("baseline", "replicas_1", "replicas_4"):
        assert rs[label]["ok"]
    assert rs["baseline"]["distinct_replicas"] == 0
    assert rs["replicas_1"]["replica_ids"] == [0]
    assert rs["replicas_4"]["distinct_replicas"] >= 2
    assert isinstance(rs["replicas_1_overhead_pct"], float)
    assert isinstance(rs["replicas_4_speedup"], float)
