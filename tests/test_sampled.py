"""Sampled engine: closed-form next-use vs brute-force trace search.

The strongest possible check: for EVERY iteration point of every
reference (exhaustive at small N), the solver's reuse interval must
equal the forward next-use distance in the full enumerated trace.
"""

import itertools

import numpy as np
import pytest

from pluss_sampler_optimization_tpu.config import MachineConfig, SamplerConfig
from pluss_sampler_optimization_tpu.core.trace import ProgramTrace
from pluss_sampler_optimization_tpu.models import (
    adi,
    atax,
    bicg,
    covariance,
    doitgen,
    fdtd2d,
    gemm,
    gemver,
    gesummv,
    heat3d,
    jacobi2d,
    mm2,
    mvt,
    syrk_rect,
    syrk_tri,
    trisolv,
    trmm,
)
from pluss_sampler_optimization_tpu.sampler.sampled import (
    draw_samples,
    per_sample_ri,
    run_sampled,
)

INF = 2**62


def nest_trace_arrays(trace, nest_idx, tid):
    """(pos, addr, array) for one (nest, tid), nest-local positions."""
    nt = trace.nests[nest_idx]
    t = nt.tables
    pos_l, addr_l, arr_l = [], [], []
    for ri in range(t.n_refs):
        pos, addr = nt.enumerate_ref(tid, ri)
        pos_l.append(pos)
        addr_l.append(addr)
        arr_l.append(np.full(len(pos), t.ref_arrays[ri], dtype=np.int64))
    return np.concatenate(pos_l), np.concatenate(addr_l), np.concatenate(arr_l)


def brute_ri(trace, nest_idx, tid, p0, array_id, line):
    pos, addr, arr = nest_trace_arrays(trace, nest_idx, tid)
    mask = (arr == array_id) & (addr == line) & (pos > p0)
    if not mask.any():
        return -1
    return int(pos[mask].min() - p0)


PROGRAMS = [
    (gemm(12), None),
    (gemm(13), None),  # short last chunk
    (mm2(8), None),
    (syrk_rect(8), None),
    (jacobi2d(10, tsteps=2), None),
    (mvt(10), None),  # transposed A[j][i]
    (bicg(9, 11), None),  # 1-deep nest + written share refs
    (gesummv(10), None),  # post-slot level-0 refs
    (atax(9, 11), None),  # interchanged transposed y-update
    (gemver(10), None),  # mixed-depth nests over shared A
    (doitgen(3, 4, 5), None),  # collapsed parallel loop
    (fdtd2d(6, 7), None),  # constant ref (no loop variable)
    (heat3d(7), None),  # 3-coefficient flat maps
    (syrk_tri(9), None),  # ascending triangular level
    (syrk_tri(10, 6), None),
    (trmm(8), None),  # descending triangular, post after subloop
    (trmm(7, 9), None),
    (trisolv(13), None),  # zero-trip iterations
    (covariance(8, 6), None),  # mixed rect + triangular nests
    # trip0 > chunk*threads: samples land in second-round chunks, so
    # later_m_pos composes count_below with base-table gathers across
    # the round-robin gap
    (syrk_tri(19, 5), None),
    (trmm(18, 4), None),
    (trisolv(21), None),
    (adi(8), None),  # descending (step -1) backward-substitution loops
]


def _all_points(nt, ri):
    """Every valid iteration point of one ref (triangular-aware)."""
    lv = int(nt.tables.ref_levels[ri])
    lp0 = nt.nest.loops[0]
    pts = []
    for n0 in range(lp0.trip):
        v0 = lp0.start + n0 * lp0.step
        trips = [int(nt.nest.loops[l].trip_at(v0)) for l in range(1, lv + 1)]
        for rest in itertools.product(*[range(tr) for tr in trips]):
            pts.append((n0,) + rest)
    return np.array(pts, dtype=np.int64).reshape(len(pts), lv + 1)


def _check_exhaustive_next_use(program, machine):
    trace = ProgramTrace(program, machine)
    for k, nt in enumerate(trace.nests):
        t = nt.tables
        for ri in range(t.n_refs):
            samples = _all_points(nt, ri)
            if len(samples) == 0:
                continue
            p0, ri_got, sink, found, tid, line = per_sample_ri(
                program, machine, k, ri, samples
            )
            arr_id = int(t.ref_arrays[ri])
            # brute force per tid: precompute traces once
            per_tid_cache = {}
            for s in range(len(samples)):
                tt = int(tid[s])
                if tt not in per_tid_cache:
                    per_tid_cache[tt] = nest_trace_arrays(trace, k, tt)
                pos, addr, arr = per_tid_cache[tt]
                mask = (arr == arr_id) & (addr == int(line[s])) & (pos > int(p0[s]))
                want = int(pos[mask].min() - p0[s]) if mask.any() else -1
                assert int(ri_got[s]) == want, (
                    f"nest {k} ref {t.ref_names[ri]} sample "
                    f"{samples[s].tolist()}: got {int(ri_got[s])}, want {want}"
                )


@pytest.mark.parametrize("program,_", PROGRAMS, ids=lambda p: getattr(p, "name", ""))
def test_exhaustive_next_use(program, _):
    _check_exhaustive_next_use(program, MachineConfig())


# The triangular solver's schedule arithmetic (count_below ownership,
# later_m_context round-robin gathers) bakes thread_num/chunk_size into
# every closed form; the default 4x4 machine hides divisibility bugs, so
# the triangular family is re-checked under odd geometries (the dense
# and oracle engines already have odd-machine triangular tests).
ODD_MACHINES = [
    MachineConfig(thread_num=3, chunk_size=5),
    MachineConfig(thread_num=5, chunk_size=2),
]
TRI_PROGRAMS = [
    syrk_tri(9),
    syrk_tri(17, 4),  # trip0 > chunk*threads under both odd machines
    trmm(8),
    trisolv(13),
    covariance(8, 6),
    adi(8),
]


@pytest.mark.parametrize(
    "machine", ODD_MACHINES, ids=lambda m: f"t{m.thread_num}c{m.chunk_size}"
)
@pytest.mark.parametrize(
    "program", TRI_PROGRAMS, ids=lambda p: getattr(p, "name", "")
)
def test_exhaustive_next_use_odd_machines(program, machine):
    _check_exhaustive_next_use(program, machine)


def test_sampled_gemm128_counts():
    """num_samples reproduces the generated constants at N=128/ratio 10%
    (...rs-ri-opt-r10.cpp:156 and :1688)."""
    cfg = SamplerConfig(ratio=0.1)
    assert cfg.num_samples((128, 128, 128)) == 2098
    assert cfg.num_samples((128, 128)) == 164


def test_draw_samples_dedup_and_range():
    machine = MachineConfig()
    trace = ProgramTrace(gemm(16), machine)
    cfg = SamplerConfig(ratio=0.3, seed=5)
    s = draw_samples(trace.nests[0], 5, cfg, seed=7)  # C3, 3-deep
    assert len(np.unique(s, axis=0)) == len(s)
    # exclude_last: normalized indices in [0, trip-1)
    assert s.min() >= 0 and s.max() <= 14


def test_run_sampled_end_to_end():
    machine = MachineConfig()
    state, results = run_sampled(gemm(32), machine, SamplerConfig(ratio=0.1, seed=3))
    names = [r.name for r in results]
    assert names == ["C0", "C1", "A0", "B0", "C2", "C3"]
    total = sum(sum(r.noshare.values()) + r.cold for r in results) + sum(
        sum(h.values()) for r in results for h in r.share.values()
    )
    assert total == sum(r.n_samples for r in results)
    # B0's share entries (if any) sit at ratio THREAD_NUM-1
    b0 = results[3]
    for ratio in b0.share:
        assert ratio == 3


def test_sampled_reuses_subset_of_dense():
    """Every sampled (noshare) reuse value must appear in the dense
    engine's raw histogram support for the same program."""
    from pluss_sampler_optimization_tpu.oracle import run_numpy

    machine = MachineConfig()
    program = gemm(32)
    dense = run_numpy(program, machine)
    dense_keys = set()
    for t in range(4):
        for k in dense.state.noshare[t]:
            dense_keys.add(k)
        for h in dense.state.share[t].values():
            dense_keys.update(h)
    _, results = run_sampled(program, machine, SamplerConfig(ratio=0.15, seed=1))
    import math

    for r in results:
        for v in r.noshare:
            p2 = 1 << int(math.floor(math.log2(v)))
            assert p2 in dense_keys, (r.name, v)
        for h in r.share.values():
            for v in h:
                assert v in dense_keys, (r.name, v)


def test_sampled_capacity_overflow_recovers():
    """A too-small unique-pair capacity must transparently regrow (the
    pipelined drain checks each entry against its own dispatch
    capacity), producing results identical to an ample capacity."""
    machine = MachineConfig()
    cfg = SamplerConfig(ratio=0.4, seed=11)
    _, small = run_sampled(gemm(16), machine, cfg, capacity=2)
    _, big = run_sampled(gemm(16), machine, cfg, capacity=4096)
    for a, b in zip(small, big):
        assert a.name == b.name
        assert a.noshare == b.noshare
        assert a.share == b.share
        assert a.cold == b.cold


def test_sampled_triangular_end_to_end():
    """Triangular sampled run: mass conservation + every reuse value in
    the exact engine's support."""
    import math

    from pluss_sampler_optimization_tpu.oracle import run_numpy

    machine = MachineConfig()
    program = trmm(14)
    dense = run_numpy(program, machine)
    dense_keys = set()
    for t in range(4):
        dense_keys.update(dense.state.noshare[t])
        for h in dense.state.share[t].values():
            dense_keys.update(h)
    _, results = run_sampled(program, machine, SamplerConfig(ratio=0.3, seed=2))
    total = sum(sum(r.noshare.values()) + r.cold for r in results) + sum(
        sum(h.values()) for r in results for h in r.share.values()
    )
    assert total == sum(r.n_samples for r in results) > 0
    for r in results:
        for v in r.noshare:
            assert (1 << int(math.floor(math.log2(v)))) in dense_keys
        for h in r.share.values():
            for v in h:
                assert v in dense_keys


def test_sampled_rejects_non_unit_step_triangular():
    from pluss_sampler_optimization_tpu.ir import Loop, ParallelNest, Program, Ref

    prog = Program(
        name="tri-step2",
        nests=(ParallelNest(
            loops=(Loop(8, step=2), Loop(trip=1, trip_coeff=1)),
            refs=(Ref("A0", "A", level=1, coeffs=(8, 1)),),
        ),),
    )
    with pytest.raises(NotImplementedError, match="unit steps"):
        run_sampled(prog, MachineConfig(), SamplerConfig(ratio=0.5))


def test_sampled_checkpoint_resume(tmp_path):
    """A checkpointed run resumes: completed refs load from disk (the
    engine is not re-invoked for them), results identical to a fresh
    run; a stale tag forces recompute."""
    import json

    machine = MachineConfig()
    cfg = SamplerConfig(ratio=0.3, seed=7)
    prog = gemm(16)
    ck = str(tmp_path / "ck")
    _, fresh = run_sampled(prog, machine, cfg)
    _, first = run_sampled(prog, machine, cfg, checkpoint_dir=ck)
    files = sorted((tmp_path / "ck").glob("ref_*.json"))
    assert len(files) == len(first) == 6

    # resume must not re-draw: poison BOTH draw paths (host numpy and
    # device threefry — the default) to prove neither is re-invoked
    from pluss_sampler_optimization_tpu.sampler import draw as D
    from pluss_sampler_optimization_tpu.sampler import sampled as S

    def _boom(*a, **k):
        raise AssertionError("resume must not redraw completed refs")

    orig = S.draw_sample_keys
    orig_dev = D.draw_sample_keys_device
    S.draw_sample_keys = _boom
    D.draw_sample_keys_device = _boom
    try:
        _, resumed = run_sampled(prog, machine, cfg, checkpoint_dir=ck)
    finally:
        S.draw_sample_keys = orig
        D.draw_sample_keys_device = orig_dev
    for a, b, c in zip(fresh, first, resumed):
        assert a.name == b.name == c.name
        assert a.noshare == b.noshare == c.noshare
        assert a.share == b.share == c.share
        assert a.cold == b.cold == c.cold
        assert a.n_samples == b.n_samples == c.n_samples

    # a different sampler config invalidates the tag -> recompute works
    d = json.loads(files[0].read_text())
    assert "tag" in d
    _, other = run_sampled(
        prog, machine, SamplerConfig(ratio=0.5, seed=7), checkpoint_dir=ck
    )
    assert sum(r.n_samples for r in other) > sum(r.n_samples for r in fresh)


def test_checkpoint_tag_covers_program_structure(tmp_path):
    """Same-named programs with different structure must not share
    checkpoints (gemm's r10 threshold variant reuses the name)."""
    machine = MachineConfig()
    cfg = SamplerConfig(ratio=0.4, seed=1)
    ck = str(tmp_path / "ck")
    prog_ri = gemm(16)
    prog_r10 = gemm(16, share_threshold_variant="r10")
    assert prog_ri.name == prog_r10.name
    _, a = run_sampled(prog_ri, machine, cfg, checkpoint_dir=ck)
    _, b = run_sampled(prog_r10, machine, cfg, checkpoint_dir=ck)
    _, b_fresh = run_sampled(prog_r10, machine, cfg)
    for x, y in zip(b, b_fresh):
        assert x.noshare == y.noshare and x.share == y.share


def test_checkpoint_foreign_file_recomputes(tmp_path):
    machine = MachineConfig()
    cfg = SamplerConfig(ratio=0.4, seed=1)
    ck = tmp_path / "ck"
    ck.mkdir()
    (ck / "ref_000.json").write_text("[]")  # valid JSON, wrong shape
    (ck / "ref_001.json").write_text("{not json")
    _, got = run_sampled(gemm(16), machine, cfg, checkpoint_dir=str(ck))
    _, want = run_sampled(gemm(16), machine, cfg)
    for x, y in zip(got, want):
        assert x.noshare == y.noshare and x.share == y.share
