"""StaticSchedule closed forms vs a brute-force ChunkDispatcher emulation.

The emulation follows pluss_utils.h:298-317 (init), :386-391
(hasNextStaticChunk), :410-425 (getNextStaticChunk) literally.
"""

import pytest

from pluss_sampler_optimization_tpu.core.schedule import StaticSchedule


def dispatcher_walk(trip, chunk, threads, start=0, step=1):
    """Values each tid visits, per the reference dispatcher."""
    last = start + (trip - 1) * step
    sp = [start + (chunk * step) * t for t in range(threads)]
    out = {t: [] for t in range(threads)}
    for t in range(threads):
        while (step > 0 and sp[t] <= last) or (step < 0 and sp[t] >= last):
            lb = sp[t]
            ub = lb + (chunk - 1) * step
            if step > 0:
                ub = min(ub, last)
            else:
                ub = max(ub, last)
            v = lb
            while (step > 0 and v <= ub) or (step < 0 and v >= ub):
                out[t].append(v)
                v += step
            sp[t] += chunk * threads * step
    return out


CASES = [
    (128, 4, 4, 0, 1),
    (13, 4, 4, 0, 1),
    (8, 4, 4, 0, 1),
    (3, 4, 4, 0, 1),
    (17, 3, 4, 0, 1),
    (126, 4, 4, 1, 1),  # jacobi-style start=1
    (30, 5, 3, 2, 1),
    (16, 4, 2, 0, 1),
    (1, 4, 4, 0, 1),
]


@pytest.mark.parametrize("trip,chunk,threads,start,step", CASES)
def test_local_enumeration_matches_dispatcher(trip, chunk, threads, start, step):
    ref = dispatcher_walk(trip, chunk, threads, start, step)
    s = StaticSchedule(trip=trip, chunk=chunk, threads=threads, start=start, step=step)
    for t in range(threads):
        assert s.local_count(t) == len(ref[t])
        got = [s.local_to_value(t, m) for m in range(s.local_count(t))]
        assert got == ref[t]


@pytest.mark.parametrize("trip,chunk,threads,start,step", CASES)
def test_forward_maps_roundtrip(trip, chunk, threads, start, step):
    s = StaticSchedule(trip=trip, chunk=chunk, threads=threads, start=start, step=step)
    for n in range(trip):
        v = s.value(n)
        assert s.normalize(v) == n
        t = s.owner_tid(n)
        m = s.local_index(n)
        assert s.local_to_normalized(t, m) == n
        assert s.local_to_value(t, m) == v


def test_owner_matches_reference_formula():
    # getStaticTid (pluss_utils.h:429-431) for the canonical config
    import math

    s = StaticSchedule(trip=128, chunk=4, threads=4)
    for i in range(128):
        tid_ref = (i // 4) - math.floor(i / (4 * 4)) * 4
        assert s.owner_tid(i) == tid_ref
        assert s.local_chunk_id(i) == math.floor(i / 16)
        assert s.chunk_pos(i) == i % 4
