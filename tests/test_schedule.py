"""StaticSchedule closed forms vs a brute-force ChunkDispatcher emulation.

The emulation follows pluss_utils.h:298-317 (init), :386-391
(hasNextStaticChunk), :410-425 (getNextStaticChunk) literally.
"""

import pytest

from pluss_sampler_optimization_tpu.core.schedule import StaticSchedule


def dispatcher_walk(trip, chunk, threads, start=0, step=1):
    """Values each tid visits, per the reference dispatcher."""
    last = start + (trip - 1) * step
    sp = [start + (chunk * step) * t for t in range(threads)]
    out = {t: [] for t in range(threads)}
    for t in range(threads):
        while (step > 0 and sp[t] <= last) or (step < 0 and sp[t] >= last):
            lb = sp[t]
            ub = lb + (chunk - 1) * step
            if step > 0:
                ub = min(ub, last)
            else:
                ub = max(ub, last)
            v = lb
            while (step > 0 and v <= ub) or (step < 0 and v >= ub):
                out[t].append(v)
                v += step
            sp[t] += chunk * threads * step
    return out


CASES = [
    (128, 4, 4, 0, 1),
    (13, 4, 4, 0, 1),
    (8, 4, 4, 0, 1),
    (3, 4, 4, 0, 1),
    (17, 3, 4, 0, 1),
    (126, 4, 4, 1, 1),  # jacobi-style start=1
    (30, 5, 3, 2, 1),
    (16, 4, 2, 0, 1),
    (1, 4, 4, 0, 1),
]


@pytest.mark.parametrize("trip,chunk,threads,start,step", CASES)
def test_local_enumeration_matches_dispatcher(trip, chunk, threads, start, step):
    ref = dispatcher_walk(trip, chunk, threads, start, step)
    s = StaticSchedule(trip=trip, chunk=chunk, threads=threads, start=start, step=step)
    for t in range(threads):
        assert s.local_count(t) == len(ref[t])
        got = [s.local_to_value(t, m) for m in range(s.local_count(t))]
        assert got == ref[t]


@pytest.mark.parametrize("trip,chunk,threads,start,step", CASES)
def test_forward_maps_roundtrip(trip, chunk, threads, start, step):
    s = StaticSchedule(trip=trip, chunk=chunk, threads=threads, start=start, step=step)
    for n in range(trip):
        v = s.value(n)
        assert s.normalize(v) == n
        t = s.owner_tid(n)
        m = s.local_index(n)
        assert s.local_to_normalized(t, m) == n
        assert s.local_to_value(t, m) == v


def test_owner_matches_reference_formula():
    # getStaticTid (pluss_utils.h:429-431) for the canonical config
    import math

    s = StaticSchedule(trip=128, chunk=4, threads=4)
    for i in range(128):
        tid_ref = (i // 4) - math.floor(i / (4 * 4)) * 4
        assert s.owner_tid(i) == tid_ref
        assert s.local_chunk_id(i) == math.floor(i / 16)
        assert s.chunk_pos(i) == i % 4


def test_interleaved_order_key_matches_comparator():
    """Sorting by interleaved_order_key reproduces the r10 priority
    queue's pop order (Iteration::compare, src/iteration.rs:63-134):
    cid, then in-chunk pos, then inner loop variables; tid never
    compared."""
    import functools

    import numpy as np

    from pluss_sampler_optimization_tpu.config import MachineConfig
    from pluss_sampler_optimization_tpu.core.schedule import (
        interleaved_order_key,
    )
    from pluss_sampler_optimization_tpu.core.trace import ProgramTrace
    from pluss_sampler_optimization_tpu.models.gemm import gemm

    def compare(sched, a, b):
        # faithful port for one reference's samples (positive steps)
        ca, cb = sched.local_chunk_id(a[0]), sched.local_chunk_id(b[0])
        if ca != cb:
            return -1 if ca < cb else 1
        pa, pb = sched.chunk_pos(a[0]), sched.chunk_pos(b[0])
        if pa != pb:
            return -1 if pa < pb else 1
        for x, y in zip(a[1:], b[1:]):
            if x != y:
                return -1 if x < y else 1
        return 0

    trace = ProgramTrace(gemm(13), MachineConfig())
    nt = trace.nests[0]
    rng = np.random.default_rng(0)
    for ref_idx in (0, 3):  # C0 (2-deep), B0 (3-deep)
        lv = int(nt.tables.ref_levels[ref_idx])
        samples = np.stack(
            [rng.integers(0, 13, size=60) for _ in range(lv + 1)], axis=1
        )
        samples = np.unique(samples, axis=0)
        keys = interleaved_order_key(nt, ref_idx, samples)
        by_key = samples[np.argsort(keys, kind="stable")]
        by_cmp = sorted(
            samples.tolist(),
            key=functools.cmp_to_key(
                lambda a, b: compare(nt.schedule, a, b)
            ),
        )
        assert by_key.tolist() == by_cmp
