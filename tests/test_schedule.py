"""StaticSchedule closed forms vs a brute-force ChunkDispatcher emulation.

The emulation follows pluss_utils.h:298-317 (init), :386-391
(hasNextStaticChunk), :410-425 (getNextStaticChunk) literally.
"""

import pytest

from pluss_sampler_optimization_tpu.core.schedule import StaticSchedule


def dispatcher_walk(trip, chunk, threads, start=0, step=1):
    """Values each tid visits, per the reference dispatcher."""
    last = start + (trip - 1) * step
    sp = [start + (chunk * step) * t for t in range(threads)]
    out = {t: [] for t in range(threads)}
    for t in range(threads):
        while (step > 0 and sp[t] <= last) or (step < 0 and sp[t] >= last):
            lb = sp[t]
            ub = lb + (chunk - 1) * step
            if step > 0:
                ub = min(ub, last)
            else:
                ub = max(ub, last)
            v = lb
            while (step > 0 and v <= ub) or (step < 0 and v >= ub):
                out[t].append(v)
                v += step
            sp[t] += chunk * threads * step
    return out


CASES = [
    (128, 4, 4, 0, 1),
    (13, 4, 4, 0, 1),
    (8, 4, 4, 0, 1),
    (3, 4, 4, 0, 1),
    (17, 3, 4, 0, 1),
    (126, 4, 4, 1, 1),  # jacobi-style start=1
    (30, 5, 3, 2, 1),
    (16, 4, 2, 0, 1),
    (1, 4, 4, 0, 1),
]


@pytest.mark.parametrize("trip,chunk,threads,start,step", CASES)
def test_local_enumeration_matches_dispatcher(trip, chunk, threads, start, step):
    ref = dispatcher_walk(trip, chunk, threads, start, step)
    s = StaticSchedule(trip=trip, chunk=chunk, threads=threads, start=start, step=step)
    for t in range(threads):
        assert s.local_count(t) == len(ref[t])
        got = [s.local_to_value(t, m) for m in range(s.local_count(t))]
        assert got == ref[t]


@pytest.mark.parametrize("trip,chunk,threads,start,step", CASES)
def test_forward_maps_roundtrip(trip, chunk, threads, start, step):
    s = StaticSchedule(trip=trip, chunk=chunk, threads=threads, start=start, step=step)
    for n in range(trip):
        v = s.value(n)
        assert s.normalize(v) == n
        t = s.owner_tid(n)
        m = s.local_index(n)
        assert s.local_to_normalized(t, m) == n
        assert s.local_to_value(t, m) == v


def test_owner_matches_reference_formula():
    # getStaticTid (pluss_utils.h:429-431) for the canonical config
    import math

    s = StaticSchedule(trip=128, chunk=4, threads=4)
    for i in range(128):
        tid_ref = (i // 4) - math.floor(i / (4 * 4)) * 4
        assert s.owner_tid(i) == tid_ref
        assert s.local_chunk_id(i) == math.floor(i / 16)
        assert s.chunk_pos(i) == i % 4


def test_interleaved_order_key_matches_comparator():
    """Sorting by interleaved_order_key reproduces the r10 priority
    queue's pop order (Iteration::compare, src/iteration.rs:63-134):
    cid, then in-chunk pos, then inner loop variables; tid never
    compared."""
    import functools

    import numpy as np

    from pluss_sampler_optimization_tpu.config import MachineConfig
    from pluss_sampler_optimization_tpu.core.schedule import (
        interleaved_order_key,
    )
    from pluss_sampler_optimization_tpu.core.trace import ProgramTrace
    from pluss_sampler_optimization_tpu.models.gemm import gemm

    def compare(sched, a, b):
        # faithful port for one reference's samples (positive steps)
        ca, cb = sched.local_chunk_id(a[0]), sched.local_chunk_id(b[0])
        if ca != cb:
            return -1 if ca < cb else 1
        pa, pb = sched.chunk_pos(a[0]), sched.chunk_pos(b[0])
        if pa != pb:
            return -1 if pa < pb else 1
        for x, y in zip(a[1:], b[1:]):
            if x != y:
                return -1 if x < y else 1
        return 0

    trace = ProgramTrace(gemm(13), MachineConfig())
    nt = trace.nests[0]
    rng = np.random.default_rng(0)
    for ref_idx in (0, 3):  # C0 (2-deep), B0 (3-deep)
        lv = int(nt.tables.ref_levels[ref_idx])
        samples = np.stack(
            [rng.integers(0, 13, size=60) for _ in range(lv + 1)], axis=1
        )
        samples = np.unique(samples, axis=0)
        keys = interleaved_order_key(nt, ref_idx, samples)
        by_key = samples[np.argsort(keys, kind="stable")]
        by_cmp = sorted(
            samples.tolist(),
            key=functools.cmp_to_key(
                lambda a, b: compare(nt.schedule, a, b)
            ),
        )
        assert by_key.tolist() == by_cmp


def test_dynamic_equals_static_for_rectangular():
    """The dynamic dispatcher arm (FIFO under uniform interleaving)
    must coincide with static round-robin whenever chunk costs are
    equal — every rectangular nest. This is the closed-form argument
    for why the static arm alone reproduces the reference's live
    behavior, now executable."""
    from pluss_sampler_optimization_tpu import MachineConfig
    from pluss_sampler_optimization_tpu.models import gemm, mm2
    from pluss_sampler_optimization_tpu.oracle.serial import run_serial

    for prog in (gemm(13), mm2(8)):
        for threads, chunk in ((4, 4), (3, 5)):
            machine = MachineConfig(thread_num=threads, chunk_size=chunk)
            a = run_serial(prog, machine)
            b = run_serial(prog, machine, schedule="dynamic")
            assert a.per_tid_accesses == b.per_tid_accesses
            for t in range(threads):
                assert a.state.noshare[t] == b.state.noshare[t]
                assert a.state.share[t] == b.state.share[t]


def test_dynamic_assignment_fifo_semantics():
    """Unequal costs: the busy thread takes fewer chunks; every chunk
    is handed out exactly once; ties resolve in tid order."""
    from pluss_sampler_optimization_tpu.core.schedule import (
        dynamic_chunk_assignment,
    )

    # chunk 0 is huge: tid0 takes it and stays busy while tids 1-2
    # drain the rest alternately
    out = dynamic_chunk_assignment(6, 3, [100, 1, 1, 1, 1, 1])
    assert out[0] == [0]
    assert sorted(out[1] + out[2]) == [1, 2, 3, 4, 5]
    assert out[1] == [1, 3, 5] and out[2] == [2, 4]

    # equal costs: round-robin
    out = dynamic_chunk_assignment(7, 3, [5] * 7)
    assert out == [[0, 3, 6], [1, 4], [2, 5]]


def test_dynamic_triangular_covers_all_chunks():
    """Triangular nests are where dynamic diverges from static: the
    assignment must still partition the chunk set, and the walk must
    count every access exactly once."""
    from pluss_sampler_optimization_tpu import MachineConfig
    from pluss_sampler_optimization_tpu.models import syrk_tri
    from pluss_sampler_optimization_tpu.oracle.serial import run_serial

    # Monotone NON-DECREASING costs provably keep FIFO == round-robin
    # (per-thread completion sums stay ordered), so lower-triangular
    # nests like syrk_tri do not diverge; an upper-triangular nest
    # (inner trip DECREASING in v0) does — the thread stuck on the
    # expensive first chunk is overtaken
    from pluss_sampler_optimization_tpu import (
        Loop,
        ParallelNest,
        Program,
        Ref,
    )

    lower = syrk_tri(13)
    machine = MachineConfig(thread_num=2, chunk_size=1)
    a = run_serial(lower, machine)
    b = run_serial(lower, machine, schedule="dynamic")
    assert a.per_tid_accesses == b.per_tid_accesses  # monotone: equal

    n = 13
    upper = Program(
        name="tri-upper",
        nests=(
            ParallelNest(
                loops=(Loop(n), Loop(n, trip_coeff=-1)),
                refs=(Ref("A0", "A", level=1, coeffs=(n, 1)),),
            ),
        ),
    )
    a = run_serial(upper, machine)
    b = run_serial(upper, machine, schedule="dynamic")
    assert a.total_accesses == b.total_accesses
    assert a.per_tid_accesses != b.per_tid_accesses
    # dynamic spreads the decreasing costs more evenly than round-robin
    assert (max(b.per_tid_accesses) - min(b.per_tid_accesses)
            <= max(a.per_tid_accesses) - min(a.per_tid_accesses))
