"""Analysis service: fingerprints, the two-tier result store,
singleflight coalescing, deadline degradation, the serve/--cache-dir
CLI surface, atomic sidecar writes, and the store checker.

The ISSUE-3 acceptance invariants are pinned here through telemetry
counters: a warm-cache repeat returns a bit-identical MRC with ZERO
engine executions, and N identical concurrent submissions trigger
exactly ONE.
"""

import json
import os
import sys
import threading
import time

import numpy as np
import pytest

from pluss_sampler_optimization_tpu.cli import main
from pluss_sampler_optimization_tpu.models import REGISTRY, build
from pluss_sampler_optimization_tpu.runtime import (
    lockwitness,
    telemetry,
)
from pluss_sampler_optimization_tpu.runtime.io import (
    atomic_write_json,
    atomic_write_text,
)
from pluss_sampler_optimization_tpu.service import (
    AnalysisRequest,
    AnalysisService,
    ResultCache,
    serve_jsonl,
    structure_digest,
    validate_record,
)
from pluss_sampler_optimization_tpu.service.executor import (
    default_runner,
)

sys.path.insert(
    0,
    os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), "tools"),
)
import check_service_store  # noqa: E402


@pytest.fixture(autouse=True)
def _clean_telemetry():
    telemetry.disable()
    yield
    telemetry.disable()


def _req(**kw):
    base = dict(model="gemm", n=16, engine="oracle")
    base.update(kw)
    return AnalysisRequest(**base)


# -- fingerprints -----------------------------------------------------


def test_fingerprint_stable_and_sensitive():
    fp = _req().fingerprint()
    assert fp == _req().fingerprint()  # deterministic
    assert len(fp) == 64 and set(fp) <= set("0123456789abcdef")
    # anything that changes the result changes the address
    assert _req(n=17).fingerprint() != fp
    assert _req(engine="dense").fingerprint() != fp
    assert _req(threads=8).fingerprint() != fp
    assert _req(cache_kb=1280).fingerprint() != fp
    # serving metadata must NOT change the address
    assert _req(id="abc", deadline_s=5.0).fingerprint() == fp
    # sampling knobs are hashed only for the engines that read them
    assert _req(ratio=0.5).fingerprint() == fp
    s = _req(engine="sampled")
    assert s.fingerprint() != fp
    assert _req(engine="sampled", seed=1).fingerprint() != s.fingerprint()
    assert _req(engine="sampled", ratio=0.2).fingerprint() != (
        s.fingerprint()
    )


def test_fingerprint_hashes_program_ir_not_model_name():
    """Two registry names building the same IR share one address; the
    fingerprint is a function of the Program, not its lookup key."""
    from pluss_sampler_optimization_tpu.service.fingerprint import (
        request_fingerprint,
    )

    prog = build("gemm", 16)
    machine = _req().machine()
    a = request_fingerprint(prog, machine, "oracle", {"runtime": "v1"})
    b = request_fingerprint(
        build("gemm", 16), machine, "oracle", {"runtime": "v1"}
    )
    assert a == b


def test_structure_digest_distinguishes_and_repeats():
    sig1 = (1, (2, 3), "pre", None, True)
    sig2 = (1, (2, 4), "pre", None, True)
    assert structure_digest(sig1) == structure_digest(sig1)
    assert structure_digest(sig1) != structure_digest(sig2)


def test_unknown_engine_rejected():
    with pytest.raises(ValueError):
        AnalysisRequest(model="gemm", engine="bogus")


# -- result cache -----------------------------------------------------


def _fake_record(fp):
    return {
        "store_version": 1,
        "fingerprint": fp,
        "request": {"model": "gemm"},
        "engine_requested": "oracle",
        "engine_used": "oracle",
        "total_accesses": 10,
        "access_label": "accesses",
        "rih": {"1": 2.0},
        "mrc": [1.0, 0.5],
        "dump_lines": ["miss ratio"],
        "created_at": time.time(),
    }


def test_cache_two_tiers_and_corruption_tolerance(tmp_path):
    cache = ResultCache(str(tmp_path / "store"), mem_entries=4)
    fp = "ab" + "0" * 62
    assert cache.get(fp) == (None, "miss")
    cache.put(fp, _fake_record(fp))
    rec, tier = cache.get(fp)
    assert tier == "mem" and rec["mrc"] == [1.0, 0.5]
    # a fresh cache over the same dir reads the disk tier
    cache2 = ResultCache(str(tmp_path / "store"))
    rec, tier = cache2.get(fp)
    assert tier == "disk" and rec["fingerprint"] == fp
    # truncated JSON = miss, never an exception
    path = cache.path_for(fp)
    with open(path, "w") as f:
        f.write('{"store_version": 1, "finge')
    rec, tier = ResultCache(str(tmp_path / "store")).get(fp)
    assert (rec, tier) == (None, "miss")
    # wrong version = miss
    bad = _fake_record(fp)
    bad["store_version"] = 999
    with open(path, "w") as f:
        json.dump(bad, f)
    assert ResultCache(str(tmp_path / "store")).get(fp) == (
        None, "miss"
    )
    # mis-addressed record (fingerprint != filename) = miss
    other = _fake_record("cd" + "1" * 62)
    with open(path, "w") as f:
        json.dump(other, f)
    assert ResultCache(str(tmp_path / "store")).get(fp) == (
        None, "miss"
    )


def test_cache_mem_eviction_counted(tmp_path):
    tele = telemetry.enable()
    cache = ResultCache(None, mem_entries=2)
    for i in range(4):
        fp = f"{i:02d}" + "0" * 62
        cache.put(fp, _fake_record(fp))
    telemetry.disable()
    assert tele.counters.get("service_cache_evictions") == 2


def test_validate_record_catches_shape_drift():
    fp = "ab" + "0" * 62
    assert validate_record(_fake_record(fp), fp) == []
    bad = _fake_record(fp)
    del bad["mrc"]
    assert any("mrc" in e for e in validate_record(bad, fp))
    bad = _fake_record(fp)
    bad["rih"] = {"1": "two"}
    assert validate_record(bad, fp)


# -- the acceptance invariants ---------------------------------------


def test_warm_repeat_bit_identical_mrc_zero_executions(tmp_path):
    """Warm repeats: bit-identical MRC, zero engine executions —
    through the memory tier, AND through the disk tier of a fresh
    service instance."""
    tele = telemetry.enable()
    req = _req()
    with AnalysisService(cache_dir=str(tmp_path / "store")) as svc:
        cold = svc.analyze(req)
        assert cold.ok and cold.cache == "miss"
        assert tele.counters.get("service_exec_started") == 1
        snapshot = dict(tele.counters)
        warm = svc.analyze(req)
    assert warm.ok and warm.cache == "mem"
    assert tele.counters.get("service_exec_started") == 1
    # zero engine work of ANY kind on the warm path: no counter moved
    # except the service's own bookkeeping
    moved = {
        k for k in set(tele.counters) | set(snapshot)
        if tele.counters.get(k, 0) != snapshot.get(k, 0)
    }
    assert all(k.startswith("service_") for k in moved), moved
    assert warm.mrc.dtype == np.float64
    assert np.array_equal(cold.mrc, warm.mrc)
    assert warm.dump_lines == cold.dump_lines

    with AnalysisService(cache_dir=str(tmp_path / "store")) as svc2:
        disk = svc2.analyze(req)
    telemetry.disable()
    assert disk.ok and disk.cache == "disk"
    assert tele.counters.get("service_exec_started") == 1
    assert np.array_equal(cold.mrc, disk.mrc)


def test_identical_concurrent_requests_coalesce_to_one_execution():
    """N identical + M distinct requests fired from threads: exactly
    one execution per distinct fingerprint (telemetry dispatch
    counters), every caller gets the full result. Runs under the
    lockdep witness: the thread-hammered service must show zero
    lock-order inversions and results bit-identical to a witness-off
    pass."""
    release = threading.Event()

    def slow_runner(engine, program, machine, request):
        release.wait(timeout=30)
        return default_runner(engine, program, machine, request)

    tele = telemetry.enable()
    reqs = (
        [_req() for _ in range(8)]
        + [_req(n=18) for _ in range(4)]
        + [_req(model="mvt", n=12) for _ in range(4)]
    )
    lockwitness.reset()
    lockwitness.enable()
    with AnalysisService(max_workers=4, runner=slow_runner) as svc:
        responses = [None] * len(reqs)

        def call(i):
            responses[i] = svc.analyze(reqs[i])

        threads = [
            threading.Thread(target=call, args=(i,))
            for i in range(len(reqs))
        ]
        for t in threads:
            t.start()
        # let every submit land (they coalesce in submit, before any
        # worker can finish: workers are parked on the event)
        deadline = time.time() + 30
        while len(svc.executor._inflight) < 3 and time.time() < deadline:
            time.sleep(0.01)
        release.set()
        for t in threads:
            t.join(timeout=60)
    telemetry.disable()
    witness = lockwitness.report()
    lockwitness.disable()
    lockwitness.reset()
    assert witness["inversion_count"] == 0, witness["inversions"]
    assert all(r is not None and r.ok for r in responses)
    assert tele.counters.get("service_exec_started") == 3
    # every non-executing request either joined an in-flight future or
    # (if it submitted after completion) hit the memory tier
    assert (
        tele.counters.get("service_coalesced", 0)
        + tele.counters.get("service_cache_hit_mem", 0)
    ) == 13
    # coalesced callers share bit-identical results per fingerprint
    for group in (responses[:8], responses[8:12], responses[12:]):
        base = group[0]
        for r in group[1:]:
            assert r.fingerprint == base.fingerprint
            assert np.array_equal(r.mrc, base.mrc)
    fps = {r.fingerprint for r in responses}
    assert len(fps) == 3
    # the witness is a pure observer: the same three fingerprints
    # served witness-off are bit-identical to the hammered run
    assert not lockwitness.enabled()
    with AnalysisService(max_workers=4) as svc:
        for i in (0, 8, 12):
            off = svc.analyze(reqs[i])
            assert np.asarray(off.mrc).tobytes() \
                == np.asarray(responses[i].mrc).tobytes()


def test_deadline_degrades_and_skips_persistent_cache(tmp_path):
    """An exact engine overrunning its deadline degrades to sampled;
    the downgrade is recorded in the response and as a telemetry
    event, and the degraded result is NOT persisted (the fingerprint
    addresses the canonical result of the requested engine)."""

    def stalling_runner(engine, program, machine, request):
        if engine == "exact":
            # overrun the deadline, then abort: the abandoned attempt
            # thread must not run an engine after this test finishes
            # (it would pollute a later test's telemetry run)
            time.sleep(2)
            raise RuntimeError("stalled attempt aborted")
        return default_runner(engine, program, machine, request)

    tele = telemetry.enable()
    req = _req(model="gemm", n=8, engine="exact", ratio=0.3,
               deadline_s=0.3)
    with AnalysisService(
        cache_dir=str(tmp_path / "store"), runner=stalling_runner
    ) as svc:
        resp = svc.analyze(req)
    telemetry.disable()
    assert resp.ok
    assert resp.engine_used == "sampled"
    assert resp.degraded and resp.degraded[0]["from"] == "exact"
    assert resp.degraded[0]["to"] == "sampled"
    assert tele.counters.get("service_degraded") == 1
    assert tele.counters.get("service_deadline_abandoned") == 1
    assert any(
        e["name"] == "service_degraded" for e in tele.events
    )
    # nothing persisted under the request's address
    assert svc.cache._load_disk(resp.fingerprint) is None


def test_engine_failure_falls_down_the_chain():
    def broken_runner(engine, program, machine, request):
        if engine != "sampled":
            raise RuntimeError(f"{engine} exploded")
        return default_runner(engine, program, machine, request)

    tele = telemetry.enable()
    req = _req(model="gemm", n=8, engine="exact", ratio=0.3)
    with AnalysisService(runner=broken_runner) as svc:
        resp = svc.analyze(req)
    telemetry.disable()
    assert resp.ok and resp.engine_used == "sampled"
    assert resp.degraded and "exploded" in resp.degraded[0]["reason"]
    assert tele.counters.get("service_exec_failed") == 1


def test_failure_without_fallback_is_an_error_response():
    def broken_runner(engine, program, machine, request):
        raise RuntimeError("no dice")

    with AnalysisService(runner=broken_runner) as svc:
        resp = svc.analyze(_req())  # oracle has no degrade chain
    assert not resp.ok
    assert "no dice" in resp.error
    assert resp.mrc is None


# -- serve mode / CLI surface ----------------------------------------


def test_serve_jsonl_round_trip(tmp_path, capsys):
    reqs = tmp_path / "reqs.jsonl"
    resps = tmp_path / "resps.jsonl"
    reqs.write_text(
        "\n".join([
            json.dumps({"id": "a", "model": "gemm", "n": 16,
                        "engine": "oracle"}),
            "",  # blank lines are skipped
            json.dumps({"id": "dup", "model": "gemm", "n": 16,
                        "engine": "oracle"}),
            json.dumps({"id": "bad", "model": "nope"}),
            json.dumps({"id": "uf", "model": "gemm", "wat": 1}),
        ]) + "\n"
    )
    rc = main([
        "serve", "--requests", str(reqs), "--responses", str(resps),
        "--cache-dir", str(tmp_path / "store"),
    ])
    assert rc == 0
    lines = [
        json.loads(ln) for ln in resps.read_text().splitlines()
    ]
    # ids echo even on malformed-but-parseable request lines
    assert [d["id"] for d in lines] == ["a", "dup", "bad", "uf"]
    a, dup, bad, uf = lines
    assert a["ok"] and a["engine_used"] == "oracle"
    assert a["mrc_lines"][0].startswith("0, ")
    assert len(a["mrc_digest"]) == 16
    assert dup["ok"] and dup["fingerprint"] == a["fingerprint"]
    assert dup["mrc_digest"] == a["mrc_digest"]
    assert not bad["ok"] and "unknown model" in bad["error"]
    assert not uf["ok"] and "wat" in uf["error"]
    # served dumps match the direct CLI acc output byte for byte
    assert main(["acc", "--model", "gemm", "--n", "16",
                 "--engine", "oracle"]) == 0
    direct = capsys.readouterr().out
    mrc_direct = direct.splitlines()
    i = mrc_direct.index("miss ratio")
    assert a["mrc_lines"] == mrc_direct[i + 1:-1]


def test_serve_jsonl_malformed_lines_never_abort_the_stream(tmp_path):
    """The robustness contract: invalid JSON, a non-object line, an
    unknown control type, and a result() blow-up each yield one
    structured error response; every later line still serves."""
    import io

    svc = AnalysisService()
    fin = io.StringIO("\n".join([
        '{"id": "j1", nope}',                    # invalid JSON
        "[1, 2, 3]",                             # not an object
        '"just a string"',                       # not an object
        json.dumps({"id": "t1", "type": "selfdestruct"}),
        json.dumps({"id": "ok1", "model": "gemm", "n": 16,
                    "engine": "oracle"}),
    ]) + "\n")
    fout = io.StringIO()
    try:
        failures = serve_jsonl(svc, fin, fout)
    finally:
        svc.close()
    lines = [json.loads(ln) for ln in fout.getvalue().splitlines()]
    assert len(lines) == 5
    assert [d["ok"] for d in lines] == [
        False, False, False, False, True,
    ]
    assert failures == 4
    assert "invalid JSON" in lines[0]["error"]
    assert lines[0]["line"] == 1
    assert "JSON object" in lines[1]["error"]
    assert lines[3]["id"] == "t1"
    assert "unknown request type" in lines[3]["error"]
    assert lines[4]["id"] == "ok1" and lines[4]["engine_used"] == "oracle"


def test_serve_jsonl_result_failure_is_per_line(tmp_path):
    """A request whose execution future blows up past the executor's
    own error handling becomes that line's error response, not a
    batch abort."""
    import io

    class _Boom:
        def result(self, timeout=None):
            raise RuntimeError("kaboom")

    svc = AnalysisService()
    real_submit = svc.submit

    def submit(request):
        ticket = real_submit(request)
        if request.id == "boom":
            ticket.future = _Boom()
        return ticket

    svc.submit = submit
    fin = io.StringIO("\n".join([
        json.dumps({"id": "boom", "model": "gemm", "n": 16,
                    "engine": "oracle"}),
        json.dumps({"id": "fine", "model": "gemm", "n": 18,
                    "engine": "oracle"}),
    ]) + "\n")
    fout = io.StringIO()
    try:
        failures = serve_jsonl(svc, fin, fout)
    finally:
        svc.close()
    lines = [json.loads(ln) for ln in fout.getvalue().splitlines()]
    assert failures == 1
    assert lines[0]["id"] == "boom" and not lines[0]["ok"]
    assert "kaboom" in lines[0]["error"]
    assert lines[1]["id"] == "fine" and lines[1]["ok"]


def test_serve_healthz_and_stats_requests(tmp_path):
    """The introspection protocol: healthz reports liveness + the
    engine roster, stats reports executor/cache counters and the
    ledger tail; a trailing stats line observes the batch's own
    submissions."""
    import io

    ledger_path = str(tmp_path / "ledger.jsonl")
    svc = AnalysisService(cache_dir=str(tmp_path / "store"),
                          ledger_path=ledger_path)
    fin = io.StringIO("\n".join([
        json.dumps({"id": "h", "type": "healthz"}),
        json.dumps({"id": "r1", "model": "gemm", "n": 16,
                    "engine": "oracle"}),
        json.dumps({"id": "r2", "model": "gemm", "n": 16,
                    "engine": "oracle"}),
        json.dumps({"id": "s", "type": "stats"}),
    ]) + "\n")
    fout = io.StringIO()
    try:
        failures = serve_jsonl(svc, fin, fout)
    finally:
        svc.close()
    assert failures == 0
    lines = [json.loads(ln) for ln in fout.getvalue().splitlines()]
    h, r1, r2, s = lines
    assert h["ok"] and h["type"] == "healthz"
    assert h["healthz"]["status"] == "ok"
    assert "oracle" in h["healthz"]["engines"]
    assert h["healthz"]["in_flight"] == 0
    assert r1["ok"] and r2["ok"]
    assert s["ok"] and s["type"] == "stats"
    ex = s["stats"]["executor"]
    # the stats snapshot is taken as the line is READ: both earlier
    # submissions (one execution + one coalesce/duplicate) are visible
    assert ex["submitted"] == 2
    assert ex["max_workers"] == 4
    assert set(ex) >= {"coalesced", "completed", "failed",
                       "queue_depth", "in_flight", "degraded"}
    cache = s["stats"]["cache"]
    assert cache["disk_tier"] is True
    assert cache["mem_capacity"] == 128
    assert s["stats"]["ledger"] == ledger_path


def test_service_stats_and_ledger_tail(tmp_path):
    """AnalysisService.stats() outside the serve protocol: lifetime
    counters move with executions and the ledger tail returns the
    appended request rows."""
    ledger_path = str(tmp_path / "ledger.jsonl")
    with AnalysisService(ledger_path=ledger_path) as svc:
        r1 = svc.analyze(_req())
        r2 = svc.analyze(_req())  # warm mem hit
        st = svc.stats()
    assert r1.ok and r2.ok and r2.cache == "mem"
    assert r1.mrc_digest == r2.mrc_digest
    assert st["executor"]["submitted"] == 2
    assert st["executor"]["completed"] == 2
    assert st["cache"]["hit_mem"] == 1
    assert st["cache"]["miss"] == 1
    tail = st["ledger_tail"]
    assert len(tail) == 2
    assert tail[0]["cache"] == "miss" and tail[1]["cache"] == "mem"
    assert tail[0]["mrc_digest"] == r1.mrc_digest
    assert tail[0]["fingerprint"] == r1.fingerprint
    assert tail[1]["source"] == "service"


def test_cli_cache_dir_acc_matches_direct(tmp_path, capsys):
    argv = ["acc", "--model", "gemm", "--n", "16", "--engine", "oracle"]
    assert main(argv) == 0
    direct = capsys.readouterr().out
    cached = argv + ["--cache-dir", str(tmp_path / "store")]
    assert main(cached) == 0
    assert capsys.readouterr().out == direct
    assert main(cached) == 0  # warm: served from the store
    assert capsys.readouterr().out == direct


def test_cli_cache_dir_speed_and_mrc_out(tmp_path, capsys):
    out = tmp_path / "mrc.txt"
    assert main([
        "speed", "--model", "gemm", "--n", "16", "--engine", "oracle",
        "--reps", "2", "--cache-dir", str(tmp_path / "store"),
    ]) == 0
    sout = capsys.readouterr().out
    assert "run 0" in sout and "cache miss" in sout
    assert "run 1" in sout and "cache mem" in sout
    assert main([
        "acc", "--model", "gemm", "--n", "16", "--engine", "oracle",
        "--cache-dir", str(tmp_path / "store"),
        "--mrc-out", str(out),
    ]) == 0
    capsys.readouterr()
    assert out.read_text().splitlines()[0] == "miss ratio"


def test_cli_cache_dir_flag_validation():
    with pytest.raises(SystemExit):
        main(["acc", "--cache-dir", "/tmp/x", "--engine", "native"])
    with pytest.raises(SystemExit):
        main(["sample", "--cache-dir", "/tmp/x", "--r10"])
    with pytest.raises(SystemExit):
        main(["trace", "--cache-dir", "/tmp/x"])
    with pytest.raises(SystemExit):
        main(["acc", "--deadline-s", "5"])  # needs --cache-dir
    with pytest.raises(SystemExit):
        main([])  # mode required unless --list-models


def test_cli_list_models(capsys):
    assert main(["--list-models"]) == 0
    out = capsys.readouterr().out
    for name in REGISTRY:
        assert name in out
    assert "audited" in out and "probe-backed" in out


# -- kernel-cache telemetry (satellite) -------------------------------


def test_kernel_cache_counters_route_to_telemetry():
    from pluss_sampler_optimization_tpu import MachineConfig
    from pluss_sampler_optimization_tpu.sampler import periodic

    periodic._validate_nest.cache_clear()
    periodic._compiled_nest.cache_clear()
    tele = telemetry.enable()
    prog = REGISTRY["gemm"](16)
    periodic.run_periodic(prog, MachineConfig())
    assert tele.counters.get("kernel_cache_misses", 0) >= 1
    misses = tele.counters["kernel_cache_misses"]
    periodic.run_periodic(prog, MachineConfig())
    telemetry.disable()
    assert tele.counters.get("kernel_cache_hits", 0) >= 1
    assert tele.counters["kernel_cache_misses"] == misses


# -- atomic writes (satellite) ---------------------------------------


def test_atomic_writes_leave_no_tmp_and_round_trip(tmp_path):
    p = tmp_path / "doc.json"
    atomic_write_json(str(p), {"pi": 0.1 + 0.2, "xs": [1, 2]})
    assert json.loads(p.read_text()) == {"pi": 0.1 + 0.2,
                                         "xs": [1, 2]}
    atomic_write_text(str(p), "plain\n")
    assert p.read_text() == "plain\n"
    leftovers = [
        f for f in os.listdir(tmp_path) if f.endswith(".tmp")
    ]
    assert leftovers == []


# -- store checker (satellite) ---------------------------------------


def test_check_service_store_validates_and_gcs(tmp_path, capsys):
    store = tmp_path / "store"
    with AnalysisService(cache_dir=str(store)) as svc:
        resp = svc.analyze(_req())
    assert resp.ok
    assert check_service_store.main([str(store)]) == 0
    out = capsys.readouterr().out
    assert "1 valid, 0 corrupt" in out

    # plant a corrupt record, an orphaned tmp, and a stale entry
    bad = store / "ff" / ("ff" + "0" * 62 + ".json")
    bad.parent.mkdir(exist_ok=True)
    bad.write_text("{truncated")
    (store / "orphan.x.tmp").write_text("half")
    old_path = store / "ee" / ("ee" + "0" * 62 + ".json")
    old_path.parent.mkdir(exist_ok=True)
    old = _fake_record("ee" + "0" * 62)
    old["created_at"] = time.time() - 10 * 86400
    old_path.write_text(json.dumps(old))

    assert check_service_store.main(
        [str(store), "--max-age-days", "1"]
    ) == 1
    err = capsys.readouterr().err
    assert "CORRUPT" in err and "stale" in err and "tmp" in err

    assert check_service_store.main(
        [str(store), "--max-age-days", "1", "--gc"]
    ) == 0
    capsys.readouterr()
    assert not bad.exists() and not old_path.exists()
    assert not (store / "orphan.x.tmp").exists()
    # the store is clean again, and the live record survived
    assert check_service_store.main([str(store)]) == 0
    assert "1 valid, 0 corrupt" in capsys.readouterr().out
