"""Streaming dense engine: bit-identical to the one-shot dense engine."""

import pytest

from pluss_sampler_optimization_tpu import MachineConfig
from pluss_sampler_optimization_tpu.models.atax import atax
from pluss_sampler_optimization_tpu.models.doitgen import doitgen
from pluss_sampler_optimization_tpu.models.fdtd2d import fdtd2d
from pluss_sampler_optimization_tpu.models.gemm import gemm
from pluss_sampler_optimization_tpu.models.gesummv import gesummv
from pluss_sampler_optimization_tpu.models.heat3d import heat3d
from pluss_sampler_optimization_tpu.models.jacobi2d import jacobi2d
from pluss_sampler_optimization_tpu.models.mm2 import mm2
from pluss_sampler_optimization_tpu.models.mvt import mvt
from pluss_sampler_optimization_tpu.sampler.dense import run_dense
from pluss_sampler_optimization_tpu.sampler.stream import run_stream

MACHINE = MachineConfig()


def _results_equal(a, b):
    assert a.total_accesses == b.total_accesses
    assert a.per_tid_accesses == b.per_tid_accesses
    for ha, hb in zip(a.state.noshare, b.state.noshare):
        assert ha == hb
    for sa, sb in zip(a.state.share, b.state.share):
        assert set(sa) == set(sb)
        for ratio in sa:
            assert sa[ratio] == sb[ratio]


@pytest.mark.parametrize("chunk_m", [1, 2, None])
def test_stream_matches_dense_gemm(chunk_m):
    prog = gemm(12)
    _results_equal(
        run_dense(prog, MACHINE), run_stream(prog, MACHINE, chunk_m=chunk_m)
    )


def test_stream_matches_dense_ragged():
    # N=17 with chunk 4 over 4 threads: short last chunk + idle raggedness
    prog = gemm(17)
    _results_equal(run_dense(prog, MACHINE), run_stream(prog, MACHINE, 2))


def test_stream_matches_dense_multinest():
    prog = mm2(8)
    _results_equal(run_dense(prog, MACHINE), run_stream(prog, MACHINE, 3))


def test_stream_matches_dense_jacobi():
    prog = jacobi2d(10, tsteps=2)
    _results_equal(run_dense(prog, MACHINE), run_stream(prog, MACHINE, 2))


def test_stream_odd_machine():
    m = MachineConfig(thread_num=3, chunk_size=5)
    prog = gemm(14)
    _results_equal(run_dense(prog, m), run_stream(prog, m, 2))


def test_stream_matches_dense_mvt_gesummv():
    # transposed access + post-slot level-0 refs under the scan carry
    for prog in (mvt(16), gesummv(16)):
        _results_equal(run_dense(prog, MACHINE), run_stream(prog, MACHINE, 3))


def test_stream_matches_dense_new_models():
    # 3-coefficient stencil + constant ref + collapsed parallel loop
    for prog in (heat3d(7), fdtd2d(6, 7), doitgen(3, 4, 5), atax(9, 11)):
        _results_equal(run_dense(prog, MACHINE), run_stream(prog, MACHINE, 2))


def test_stream_matches_dense_triangular():
    # ragged per-iteration body sizes under the scan carry
    from pluss_sampler_optimization_tpu.models import (
        covariance,
        syrk_tri,
        trisolv,
        trmm,
    )

    for prog, cm in ((syrk_tri(9), 2), (trmm(8, 11), 3), (trisolv(13), 2),
                     (covariance(9, 7), 2)):
        _results_equal(run_dense(prog, MACHINE), run_stream(prog, MACHINE, cm))
