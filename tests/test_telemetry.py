"""Telemetry layer: span trees, counters under real dispatch, JSON
schema stability, the disabled-path no-op contract, and the CLI
surface (--telemetry-out)."""

import json
import os
import sys
import time

import pytest

from pluss_sampler_optimization_tpu import MachineConfig
from pluss_sampler_optimization_tpu.cli import main
from pluss_sampler_optimization_tpu.models import REGISTRY
from pluss_sampler_optimization_tpu.runtime import telemetry

sys.path.insert(
    0,
    os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), "tools"),
)
import check_dispatch_stats  # noqa: E402
import check_telemetry_schema  # noqa: E402

MACHINE = MachineConfig()


@pytest.fixture(autouse=True)
def _clean_telemetry():
    """Every test starts and ends with telemetry disabled — the module
    switch is process-global state."""
    telemetry.disable()
    yield
    telemetry.disable()


def _span_count(doc):
    def cnt(s):
        return 1 + sum(cnt(c) for c in s["children"])

    return sum(cnt(s) for s in doc["spans"])


def _dump(state):
    return (
        [sorted(h.items()) for h in state.noshare],
        [sorted((r, sorted(h.items())) for r, h in per.items())
         for per in state.share],
    )


def test_span_nesting_and_ordering():
    tele = telemetry.enable()
    with telemetry.span("outer", tag="a"):
        with telemetry.span("inner1"):
            pass
        with telemetry.span("inner2"):
            with telemetry.span("leaf"):
                pass
    with telemetry.span("second_root"):
        pass
    telemetry.disable()
    assert [r.name for r in tele.roots] == ["outer", "second_root"]
    outer = tele.roots[0]
    assert [c.name for c in outer.children] == ["inner1", "inner2"]
    assert [c.name for c in outer.children[1].children] == ["leaf"]
    assert outer.attrs == {"tag": "a"}
    # children start after (and within) their parent
    for c in outer.children:
        assert c.start_s >= outer.start_s
        assert c.start_s + c.wall_s <= outer.start_s + outer.wall_s + 1e-3
    assert [s.name for s in tele.find_spans("leaf")] == ["leaf"]


def test_counters_and_monitoring_under_real_dispatch():
    """A real jitted dispatch under an enabled run: the engine-side
    counters fire and the jax.monitoring delta records compile
    activity (cache hit or real backend compile — either way, events).
    """
    import jax
    import jax.numpy as jnp

    from pluss_sampler_optimization_tpu.sampler.dense import run_dense

    tele = telemetry.enable()
    run_dense(REGISTRY["gemm"](16), MACHINE)
    # a fresh function object always traces + lowers anew, so the
    # monitoring delta is nonzero regardless of what earlier suite
    # tests already compiled (jax jit caches are per function object)
    jax.jit(lambda x: x * 3 + 1)(jnp.arange(37)).block_until_ready()
    telemetry.count("custom", 2)
    telemetry.gauge("g", 1.5)
    telemetry.disable()
    assert tele.counters["dispatches"] >= 1
    assert tele.counters["fetches"] >= 1
    assert tele.counters["bytes_fetched_to_host"] > 0
    assert tele.counters["custom"] == 2
    assert tele.gauges["g"] == 1.5
    # engine-stage spans from the dense engine
    assert tele.find_spans("engine")
    assert tele.find_spans("dispatch") and tele.find_spans("fetch")
    jd = tele.jax_delta()
    assert sum(jd["events"].values()) + sum(
        d["count"] for d in jd["durations"].values()
    ) > 0, "no jax.monitoring activity recorded for a jitted dispatch"
    # a second enable must report only ITS OWN window's activity
    tele2 = telemetry.enable()
    telemetry.disable()
    jd2 = tele2.jax_delta()
    assert sum(jd2["events"].values()) == 0


def test_check_dispatch_stats_tool(tmp_path):
    """tools/check_dispatch_stats.py audits a fused sampled run's
    dispatch count against its exported bucket plan — a REAL fused run
    passes, an inflated dispatch counter (a silent fusion regression)
    fails, and unfused documents are skipped unless --require-fused."""
    from pluss_sampler_optimization_tpu import SamplerConfig
    from pluss_sampler_optimization_tpu.sampler.sampled import (
        run_sampled,
    )

    tele = telemetry.enable()
    run_sampled(REGISTRY["gemm"](16), MACHINE,
                SamplerConfig(ratio=0.25, seed=3, fuse_refs=True))
    telemetry.disable()
    path = str(tmp_path / "fused.json")
    tele.write_json(path)
    assert check_dispatch_stats.main([path]) == 0

    with open(path) as f:
        doc = json.load(f)
    error, note = check_dispatch_stats.check(doc)
    assert error is None and "buckets" in note
    # a regression: per-ref dispatching sneaking back in
    doc["counters"]["dispatches"] = (
        doc["gauges"]["ref_buckets"] * doc["gauges"]["expected_chunks"]
        + doc["counters"].get("capacity_regrows", 0) + 1
    )
    bad = str(tmp_path / "regressed.json")
    with open(bad, "w") as f:
        json.dump(doc, f)
    assert check_dispatch_stats.main([bad]) == 1
    # unfused runs export no bucket gauges: skipped by default,
    # rejected under --require-fused (the bench sidecar contract)
    del doc["gauges"]["ref_buckets"]
    unfused = str(tmp_path / "unfused.json")
    with open(unfused, "w") as f:
        json.dump(doc, f)
    assert check_dispatch_stats.main([unfused]) == 0
    assert check_dispatch_stats.main(["--require-fused", unfused]) == 1
    assert check_dispatch_stats.main([str(tmp_path / "absent.json")]) == 1


def test_check_dispatch_stats_batched(tmp_path):
    """A batched (cross-request) run exports ref_buckets_union: the
    checker bounds the MERGED execution's dispatches by the union
    bucket plan — K requests must not cost more than one plan's
    ceiling — and still catches an inflated count."""
    from pluss_sampler_optimization_tpu import SamplerConfig
    from pluss_sampler_optimization_tpu.sampler.sampled import (
        run_sampled_multi,
    )

    tele = telemetry.enable()
    run_sampled_multi([
        (REGISTRY["gemm"](16), MACHINE,
         SamplerConfig(ratio=0.25, seed=3), False),
        (REGISTRY["gemm"](24), MACHINE,
         SamplerConfig(ratio=0.2, seed=4), False),
    ])
    telemetry.disable()
    path = str(tmp_path / "batched.json")
    tele.write_json(path)
    assert check_dispatch_stats.main([path]) == 0

    with open(path) as f:
        doc = json.load(f)
    error, note = check_dispatch_stats.check(doc)
    assert error is None and "union buckets" in note
    doc["counters"]["dispatches"] = (
        doc["gauges"]["ref_buckets_union"]
        * doc["gauges"]["expected_chunks"]
        + doc["counters"].get("capacity_regrows", 0) + 1
    )
    bad = str(tmp_path / "batched_regressed.json")
    with open(bad, "w") as f:
        json.dump(doc, f)
    assert check_dispatch_stats.main([bad]) == 1


def test_check_dispatch_stats_native(tmp_path):
    """A kernel_backend="native" run exports its chunk plan as the
    native_chunk_plan counter and stamps every native dispatch; the
    checker enforces dispatches_native <= native_chunk_plan (a hard
    ceiling — native regrows are host-side C re-calls, never
    re-dispatches) and flags a plan-less or over-plan document."""
    from pluss_sampler_optimization_tpu import SamplerConfig, native
    from pluss_sampler_optimization_tpu.sampler.sampled import (
        run_sampled,
    )

    if not native.available():
        pytest.skip("native runtime unavailable on this host")
    tele = telemetry.enable()
    run_sampled(REGISTRY["gemm"](16), MACHINE,
                SamplerConfig(ratio=0.25, seed=3,
                              kernel_backend="native"))
    telemetry.disable()
    path = str(tmp_path / "native.json")
    tele.write_json(path)
    assert check_dispatch_stats.main([path]) == 0

    with open(path) as f:
        doc = json.load(f)
    assert doc["counters"]["dispatches_native"] > 0
    error, note = check_dispatch_stats.check(doc)
    assert error is None and "native" in note
    # a regression: the native path re-dispatching past its plan
    doc["counters"]["dispatches_native"] = (
        doc["counters"]["native_chunk_plan"] + 1
    )
    bad = str(tmp_path / "native_regressed.json")
    with open(bad, "w") as f:
        json.dump(doc, f)
    assert check_dispatch_stats.main([bad]) == 1
    # ... and native dispatches without any exported plan
    del doc["counters"]["native_chunk_plan"]
    planless = str(tmp_path / "native_planless.json")
    with open(planless, "w") as f:
        json.dump(doc, f)
    assert check_dispatch_stats.main([planless]) == 1


def test_json_schema_roundtrip(tmp_path):
    tele = telemetry.enable()
    with telemetry.span("stage"):
        telemetry.count("dispatches")
    telemetry.event("note", detail="x")
    telemetry.disable()
    path = str(tmp_path / "t.json")
    tele.write_json(path)
    with open(path) as f:
        doc = json.load(f)
    assert check_telemetry_schema.validate(doc) == []
    assert doc["schema_version"] == telemetry.SCHEMA_VERSION
    assert _span_count(doc) == 1
    assert doc["counters"]["dispatches"] == 1
    assert doc["events"][0]["name"] == "note"
    assert "cpu_features_hash" in doc["host"]
    # the checker CLI agrees, and rejects a drifted document
    assert check_telemetry_schema.main([path]) == 0
    doc["schema_version"] = 999
    del doc["spans"]
    bad = str(tmp_path / "bad.json")
    with open(bad, "w") as f:
        json.dump(doc, f)
    assert check_telemetry_schema.main([bad]) == 1
    assert check_telemetry_schema.main([str(tmp_path / "absent.json")]) == 1


def test_disabled_mode_is_noop_with_bounded_overhead():
    """Disabled telemetry: nothing records, span() hands back one
    shared no-op object, and the instrumented-path overhead is pinned
    well under a microsecond-per-call budget (200k no-op spans +
    counters in < 1 s — two orders of magnitude of slack on this
    container)."""
    assert telemetry.current() is None
    s1 = telemetry.span("x", attr=1)
    s2 = telemetry.span("y")
    assert s1 is s2  # the shared singleton: zero allocation per call
    with s1 as sp:
        assert sp.block("value") == "value"  # pass-through, no jax
    telemetry.count("nope")
    telemetry.record_fetch([1, 2])
    t0 = time.perf_counter()
    for _ in range(200_000):
        with telemetry.span("hot"):
            pass
        telemetry.count("c")
    dt = time.perf_counter() - t0
    assert dt < 1.0, f"disabled-path overhead too high: {dt:.3f}s"
    assert telemetry.current() is None


def test_results_bit_identical_enabled_vs_disabled():
    """Instrumentation must never change engine output: the same run
    with telemetry enabled and disabled produces bit-identical states
    (spans only observe; Span.block never synchronizes extra without
    device_sync)."""
    from pluss_sampler_optimization_tpu.sampler.periodic import run_exact

    prog = REGISTRY["syrk"](24)
    tele = telemetry.enable()
    r_on = run_exact(prog, MACHINE)
    telemetry.disable()
    assert _span_count(tele.to_json()) >= 3  # engine stages recorded
    r_off = run_exact(prog, MACHINE)
    assert telemetry.current() is None
    assert r_on.total_accesses == r_off.total_accesses
    assert _dump(r_on.state) == _dump(r_off.state)


@pytest.mark.parametrize("mode,n,extra_args", [
    # sizes not used anywhere else in the suite: the per-program jit
    # wrappers must be fresh so each run records its own compile
    # events (a warm in-process kernel cache would legitimately
    # record none)
    ("acc", 44, []),
    ("speed", 52, ["--reps", "2"]),
])
def test_cli_telemetry_out(tmp_path, capsys, mode, n, extra_args):
    """--telemetry-out in acc and speed modes: parseable JSON, valid
    schema, an engine-stage span tree (>= 3 spans), compile-event
    monitoring, and a host fingerprint (the acceptance criterion)."""
    out = str(tmp_path / f"tele_{mode}.json")
    assert main([mode, "--model", "gemm", "--n", str(n), "--engine",
                 "exact", "--telemetry-out", out] + extra_args) == 0
    capsys.readouterr()
    with open(out) as f:
        doc = json.load(f)
    assert check_telemetry_schema.validate(doc) == []
    assert _span_count(doc) >= 3
    names = set()

    def walk(s):
        names.add(s["name"])
        for c in s["children"]:
            walk(c)

    for s in doc["spans"]:
        walk(s)
    assert "engine" in names  # engine-stage spans, not just a wrapper
    assert doc["counters"].get("dispatches", 0) > 0
    assert "cpu_features_hash" in doc["host"]
    jm = doc["jax_monitoring"]
    assert sum(jm["events"].values()) + sum(
        d["count"] for d in jm["durations"].values()
    ) > 0


def test_cli_profile_dir(tmp_path):
    """--profile-dir wraps the run in jax.profiler.trace and leaves a
    trace artifact behind.  Runs in a fresh interpreter: the trace
    dump covers everything the process ever compiled, so in-process
    it inflates from ~8s standalone to minutes late in the suite."""
    import subprocess

    prof = str(tmp_path / "prof")
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    proc = subprocess.run(
        [sys.executable, "-m", "pluss_sampler_optimization_tpu",
         "acc", "--model", "gemm", "--n", "8", "--engine", "dense",
         "--platform", "cpu", "--profile-dir", prof],
        capture_output=True, text=True, timeout=300, cwd=repo,
        env={**os.environ, "JAX_PLATFORMS": "cpu"},
    )
    assert proc.returncode == 0, proc.stderr[-2000:]
    found = []
    for root, _dirs, files in os.walk(prof):
        found += files
    assert found, "profiler trace directory is empty"


def test_exact_router_warns_on_unaudited_family(capsys, monkeypatch):
    """ADVICE medium: run_exact's analytic route must announce (stderr
    + telemetry event) model families outside the audited allowlist
    instead of silently claiming bit-exactness — and stay silent for
    audited ones."""
    from pluss_sampler_optimization_tpu.sampler import analytic
    from pluss_sampler_optimization_tpu.sampler.periodic import run_exact

    prog = REGISTRY["syrk"](24)  # periodic-rejected -> analytic route
    assert analytic.audited_family(prog.name)
    tele = telemetry.enable()
    run_exact(prog, MACHINE)
    telemetry.disable()
    assert not [e for e in tele.events if e["name"] == "warning"]

    # simulate a future unaudited family reaching the analytic route
    monkeypatch.setattr(analytic, "AUDITED_FAMILIES", frozenset({"gemm"}))
    telemetry._warned_once.discard(("analytic_unaudited", "syrk"))
    tele = telemetry.enable()
    capsys.readouterr()
    run_exact(prog, MACHINE)
    telemetry.disable()
    err = capsys.readouterr().err
    assert "outside the audited" in err
    events = [e for e in tele.events if e["name"] == "warning"]
    assert events and events[0]["kind"] == "analytic_unaudited"
    assert events[0]["model"] == prog.name
