"""Timing runtime and debug-trace surfaces."""

from pluss_sampler_optimization_tpu import MachineConfig
from pluss_sampler_optimization_tpu.cli import main
from pluss_sampler_optimization_tpu.models.gemm import gemm
from pluss_sampler_optimization_tpu.oracle.serial import run_serial
from pluss_sampler_optimization_tpu.runtime.debug import (
    access_trace,
    format_reuse_pairs,
    reuse_pairs,
)
from pluss_sampler_optimization_tpu.runtime.timing import (
    Timer,
    flush_cache,
    timed,
)

MACHINE = MachineConfig()


def test_timer_and_flush():
    assert flush_cache() == 0.0
    t = Timer(cycle_accurate=True)
    t.start()
    x = sum(range(1000))
    assert t.stop() > 0
    assert t.cycles > 0 and x == 499500


def test_timed_reps():
    times, result, flushes = timed(lambda: 42, reps=3, flush=False)
    assert len(times) == 3 and result == 42
    assert flushes == [0.0, 0.0, 0.0]  # flush disabled: no cost


def test_timed_flush_cost_separate_from_reps():
    """The satellite contract: the cache flush is timed outside the
    measured region and returned per rep — a slow flush can never leak
    into the reported rep seconds."""
    times, result, flushes = timed(lambda: "x", reps=2, flush=True,
                                   flush_kb=256)
    assert result == "x" and len(flushes) == 2
    assert all(f > 0.0 for f in flushes)
    # the measured region is a constant-return lambda: even on a slow
    # host it is orders of magnitude below the 256 KB flush walk
    assert all(t < f for t, f in zip(times, flushes))


def test_access_trace_order_and_refs():
    rows = access_trace(gemm(8), MACHINE, tid=0, limit=8)
    # GEMM body order: C0, C1, then (A0, B0, C2, C3) per k iteration
    assert [r[3] for r in rows] == [
        "C0", "C1", "A0", "B0", "C2", "C3", "A0", "B0"
    ]
    assert [r[0] for r in rows] == list(range(8))
    assert rows[0][1] == "C" and rows[2][1] == "A" and rows[3][1] == "B"


def test_reuse_pairs_match_oracle_totals():
    """Every reuse pair (threshold 1) is one histogram count; reuse
    never crosses a parallel-nest boundary (multi-nest bicg pins the
    per-nest LAT reset the reference performs after every parallel
    loop, ...ri-omp-seq.cpp:303-319)."""
    from pluss_sampler_optimization_tpu.models.bicg import bicg

    for prog in (gemm(8), bicg(8, 8)):
        total_pairs = 0
        for tid in range(MACHINE.thread_num):
            total_pairs += len(
                reuse_pairs(prog, MACHINE, tid, min_reuse=1, limit=10**9)
            )
        oracle = run_serial(prog, MACHINE)
        total_hist = sum(
            sum(v for k, v in h.items() if k != -1)
            for h in oracle.state.noshare
        ) + sum(
            sum(h2.values())
            for per in oracle.state.share
            for h2 in per.values()
        )
        assert total_pairs == total_hist, prog.name


def test_format_reuse_pairs():
    pairs = reuse_pairs(gemm(8), MACHINE, 0, min_reuse=1, limit=3)
    lines = format_reuse_pairs(pairs)
    assert len(lines) == 3 and all("->" in l for l in lines)


def test_cli_trace_mode(capsys):
    assert main(["trace", "--model", "gemm", "--n", "8", "--min-reuse",
                 "4", "--limit", "10"]) == 0
    out = capsys.readouterr().out
    assert "access trace" in out and "reuse pairs" in out and "->" in out
