"""Trace codec invariants: positions are a bijection onto [0, tid_length)."""

import numpy as np
import pytest

from pluss_sampler_optimization_tpu.config import MachineConfig
from pluss_sampler_optimization_tpu.core.trace import ProgramTrace
from pluss_sampler_optimization_tpu.models import gemm, jacobi2d, mm2, mm3, syrk_rect

PROGRAMS = [
    gemm(8),
    gemm(13),
    gemm(16, ni=12, nj=8, nk=10),
    mm2(8),
    mm3(6),
    syrk_rect(8),
    jacobi2d(10, tsteps=2),
]


@pytest.mark.parametrize("program", PROGRAMS, ids=lambda p: p.name)
def test_positions_are_bijection(program):
    machine = MachineConfig()
    trace = ProgramTrace(program, machine)
    for tid in range(machine.thread_num):
        pos, addr, arr, ref = trace.enumerate_tid(tid)
        n = trace.tid_total_length(tid)
        assert len(pos) == n
        got = np.sort(pos)
        assert np.array_equal(got, np.arange(n, dtype=np.int64))
        assert (addr >= 0).all()


def test_gemm_acc_counts():
    # GEMM body: 2 accesses per (c0,c1) + 4 per (c0,c1,c2)
    # (...ri-omp-seq.cpp:102-265): acc[1] = 4N+2, acc[0] = N*(4N+2).
    program = gemm(128)
    nest = program.nests[0]
    acc = nest.accesses_per_level_iter()
    assert acc == (128 * (4 * 128 + 2), 4 * 128 + 2, 4)
    # total accesses = N^2*(4N+2) = 4*N^3 + 2*N^2
    machine = MachineConfig()
    trace = ProgramTrace(program, machine)
    total = sum(trace.tid_total_length(t) for t in range(4))
    assert total == 4 * 128**3 + 2 * 128**2


def test_access_position_matches_walk_order():
    """Positions must equal the literal state-machine visit order."""
    from pluss_sampler_optimization_tpu.core.schedule import StaticSchedule

    program = gemm(8)
    machine = MachineConfig()
    trace = ProgramTrace(program, machine)
    nt = trace.nests[0]
    nest = program.nests[0]
    for tid in range(4):
        sched = nt.schedule
        visit = []  # (ref_gid, addr) in literal walk order
        for m in range(sched.local_count(tid)):
            c0 = sched.local_to_value(tid, m)
            for c1 in range(8):
                visit.append((0, nt.ref_addr(0, c0, c1)))  # C0
                visit.append((1, nt.ref_addr(1, c0, c1)))  # C1
                for c2 in range(8):
                    visit.append((2, nt.ref_addr(2, c0, c1, c2)))  # A0
                    visit.append((3, nt.ref_addr(3, c0, c1, c2)))  # B0
                    visit.append((4, nt.ref_addr(4, c0, c1, c2)))  # C2
                    visit.append((5, nt.ref_addr(5, c0, c1, c2)))  # C3
        pos, addr, arr, ref = trace.enumerate_tid(tid)
        order = np.argsort(pos)
        got = list(zip(ref[order].tolist(), addr[order].tolist()))
        assert got == visit
