"""Assemble cross-process Chrome traces from a shared run ledger.

A fabric run (service/fabric/) writes two kinds of request rows into
the shared ledger: the router's rows (source `fabric.router`, carrying
the `router` span block — queue/route/wire/RTT splits measured on the
router's clock) and each worker's rows (source `service`, carrying the
worker-side queue_s/batch_wait_s/execute_s stages measured on the
worker's clock). Both carry the same trace_id — the router propagates
it over the wire (service/fabric/wire.py `trace` blocks), so the rows
join offline with no shared clock and no sidecar:

    python tools/assemble_trace.py LEDGER.jsonl --list
    python tools/assemble_trace.py LEDGER.jsonl --trace-id ab12... \
        --out trace.json
    python tools/assemble_trace.py LEDGER.jsonl --out-dir traces/

The output is one Chrome trace (chrome://tracing / Perfetto) per
request: the router track lays out router_queue -> route -> wire_out
-> worker_rtt -> wire_back, and the worker track sits inside the RTT
with the worker's own stages nested. Placement uses only single-host
monotonic deltas (the wire split is RTT minus the worker's
self-reported span, halved) — cross-host timestamps are never
compared, so the picture is honest about what a two-clock system can
know. The JSON is byte-deterministic for a given ledger (sorted keys,
sorted trace ids), so goldens can pin it.

Exit code is 0 when every requested trace assembled, 1 when a
--trace-id was not found (or the ledger has no joinable traces and
one was demanded).
"""

from __future__ import annotations

import argparse
import os
import sys

sys.path.insert(
    0, os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
)


def load_rows(path: str) -> list:
    """Valid ledger rows, via the same scan the auditors use."""
    from pluss_sampler_optimization_tpu.runtime.obs import ledger

    return ledger.scan(path)["valid"]


def main(argv=None) -> int:
    from pluss_sampler_optimization_tpu.runtime.obs import fleet

    ap = argparse.ArgumentParser()
    ap.add_argument("ledger", help="shared run ledger JSONL file")
    ap.add_argument("--trace-id", default=None,
                    help="assemble only this trace id")
    ap.add_argument("--out", default=None,
                    help="write a single assembled trace here "
                    "(requires --trace-id, or a ledger with exactly "
                    "one joinable trace)")
    ap.add_argument("--out-dir", default=None,
                    help="write every assembled trace as "
                    "<out-dir>/<trace_id>.trace.json")
    ap.add_argument("--list", action="store_true",
                    help="list joinable trace ids (router row + "
                    "worker row counts) and exit")
    args = ap.parse_args(argv)

    if not os.path.isfile(args.ledger):
        print(f"{args.ledger}: not a file", file=sys.stderr)
        return 1

    rows = load_rows(args.ledger)
    if args.list:
        idx = fleet.trace_index(rows)
        for tid in sorted(idx):
            slot = idx[tid]
            print(
                f"{tid}: router={'yes' if slot['router'] else 'no'} "
                f"workers={len(slot['workers'])}"
            )
        print(f"{args.ledger}: {len(idx)} trace id(s)")
        return 0

    traces = fleet.assemble_traces(rows, trace_id=args.trace_id)
    if args.trace_id and args.trace_id not in traces:
        print(
            f"{args.ledger}: trace {args.trace_id} not joinable "
            "(no router row)",
            file=sys.stderr,
        )
        return 1
    if not traces:
        print(f"{args.ledger}: no joinable traces", file=sys.stderr)
        return 1

    if args.out:
        if len(traces) != 1:
            print(
                f"--out needs exactly one trace, got {len(traces)} "
                "(use --trace-id or --out-dir)",
                file=sys.stderr,
            )
            return 1
        (tid, doc), = traces.items()
        with open(args.out, "w", encoding="utf-8") as f:
            f.write(fleet.trace_text(doc))
        print(f"{args.out}: trace {tid} "
              f"({len(doc['traceEvents'])} events)")
        return 0

    if args.out_dir:
        os.makedirs(args.out_dir, exist_ok=True)
        for tid in sorted(traces):
            path = os.path.join(args.out_dir,
                                f"{tid}.trace.json")
            with open(path, "w", encoding="utf-8") as f:
                f.write(fleet.trace_text(traces[tid]))
        print(f"{args.out_dir}: {len(traces)} trace(s) written")
        return 0

    for tid in sorted(traces):
        doc = traces[tid]
        spans = {
            ev["name"]: ev["dur"]
            for ev in doc["traceEvents"] if ev.get("ph") == "X"
        }
        total = spans.get("request", 0.0) / 1e6
        rtt = spans.get("worker_rtt", 0.0) / 1e6
        print(
            f"{tid}: total={total:.6f}s rtt={rtt:.6f}s "
            f"events={len(doc['traceEvents'])}"
        )
    return 0


if __name__ == "__main__":
    sys.exit(main())
