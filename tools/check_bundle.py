"""Validate (and optionally garbage-collect) a post-mortem bundle dir.

The flight recorder
(pluss_sampler_optimization_tpu/runtime/obs/recorder.py) writes
atomic, schema-versioned post-mortem bundles (BUNDLE_*.json) on
anomaly triggers; it validates every bundle BEFORE the write with
`validate_bundle`, so in normal operation every file is valid — but a
crashed writer's leftover temp file, a hand-edited bundle, or a
version bump can strand bad files, and a long soak run accumulates
bundles without bound. This tool is the offline auditor, the
tools/check_ledger.py / check_service_store.py pattern applied to the
bundle dir:

- invalid bundles: unparseable JSON or schema violations (via the
  SAME `validate_bundle` the writer uses);
- stale bundles: older than --max-age-days (0 disables the check);
- with --max-bundles N, bundles beyond the newest N are surplus.

With --gc the offending files are deleted and the exit code is 0;
without --gc the exit code is nonzero when anything invalid / stale /
surplus was found, so CI can gate on bundle health.

    python tools/check_bundle.py BUNDLE_DIR [--gc]
        [--max-age-days N] [--max-bundles N]
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

sys.path.insert(
    0, os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
)


def scan_bundles(bundle_dir: str, max_age_days: float = 0.0,
                 max_bundles: int = 0) -> dict:
    """Classify every BUNDLE_*.json in the dir. Returns
    {"valid": [(name, doc)], "invalid": [(name, error)],
    "stale": [name], "surplus": [name]} — stale/surplus are valid
    bundles that --gc would delete (surplus = oldest beyond the
    newest max_bundles, by bundle ts)."""
    from pluss_sampler_optimization_tpu.runtime.obs.recorder import (
        validate_bundle,
    )

    out: dict = {"valid": [], "invalid": [], "stale": [],
                 "surplus": []}
    now = time.time()
    max_age_s = max_age_days * 86400.0
    names = sorted(
        n for n in os.listdir(bundle_dir)
        if n.startswith("BUNDLE_") and n.endswith(".json")
    )
    fresh: list = []
    for name in names:
        path = os.path.join(bundle_dir, name)
        try:
            with open(path) as f:
                doc = json.load(f)
        except (OSError, ValueError) as e:
            out["invalid"].append((name, f"invalid JSON: {e}"))
            continue
        errors = validate_bundle(doc)
        if errors:
            out["invalid"].append((name, "; ".join(errors)))
            continue
        if max_age_s > 0 and (now - float(doc["ts"])) > max_age_s:
            out["stale"].append(name)
            continue
        fresh.append((name, doc))
    fresh.sort(key=lambda nd: float(nd[1]["ts"]))
    if max_bundles > 0 and len(fresh) > max_bundles:
        cut = len(fresh) - max_bundles
        out["surplus"] = [name for name, _doc in fresh[:cut]]
        fresh = fresh[cut:]
    out["valid"] = fresh
    return out


def main(argv=None) -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("bundle_dir",
                    help="flight-recorder bundle directory "
                    "(--debug-bundle-dir of a serve run)")
    ap.add_argument("--gc", action="store_true",
                    help="delete invalid/stale/surplus bundle files "
                    "instead of only reporting them")
    ap.add_argument("--max-age-days", type=float, default=0.0,
                    help="treat bundles older than this as stale "
                    "(0 = no age limit)")
    ap.add_argument("--max-bundles", type=int, default=0,
                    help="keep only the newest N bundles "
                    "(0 = unbounded); older ones are surplus")
    args = ap.parse_args(argv)

    if not os.path.isdir(args.bundle_dir):
        print(f"{args.bundle_dir}: not a directory", file=sys.stderr)
        return 1

    scan = scan_bundles(args.bundle_dir, args.max_age_days,
                        args.max_bundles)
    for name, error in scan["invalid"]:
        print(f"{args.bundle_dir}/{name}: INVALID: {error}",
              file=sys.stderr)
    if scan["stale"]:
        print(
            f"{args.bundle_dir}: {len(scan['stale'])} stale "
            f"bundle(s) (older than {args.max_age_days:g} days)",
            file=sys.stderr,
        )
    if scan["surplus"]:
        print(
            f"{args.bundle_dir}: {len(scan['surplus'])} surplus "
            f"bundle(s) (beyond the newest {args.max_bundles})",
            file=sys.stderr,
        )

    doomed = (
        [name for name, _err in scan["invalid"]]
        + scan["stale"] + scan["surplus"]
    )
    removed = 0
    if args.gc:
        for name in doomed:
            try:
                os.unlink(os.path.join(args.bundle_dir, name))
                removed += 1
            except OSError as e:
                print(f"{args.bundle_dir}/{name}: gc failed: {e}",
                      file=sys.stderr)

    print(
        f"{args.bundle_dir}: {len(scan['valid'])} valid, "
        f"{len(scan['invalid'])} invalid, {len(scan['stale'])} "
        f"stale, {len(scan['surplus'])} surplus"
        + (f"; removed {removed}" if args.gc else "")
    )
    if args.gc:
        return 0 if removed >= len(doomed) else 1
    return 1 if doomed else 0


if __name__ == "__main__":
    sys.exit(main())
