"""Seeded chaos gate for the hardened serving stack.

Fault injection without a gate is a demo, not a test. This checker
arms runtime/faults.py with known seeds and asserts the properties
the resilience layer exists to provide:

  resolve-once   every submitted request resolves exactly once —
                 ok, failed, or shed — never lost, never doubled
  bit-identity   every success under chaos (retried, hedged, served
                 after cache corruption) carries the SAME MRC digest
                 as the fault-free baseline run of the same request
  replay         a chaos run is a pure function of (seed, spec):
                 running it twice yields the same fault counts, the
                 same per-request ok map, the same digests
  quarantine     corrupted disk records are renamed *.corrupt,
                 counted, and transparently recomputed
  timeouts       a hung attempt is abandoned at the per-attempt
                 budget and the seeded-backoff retry serves the
                 request bit-identically
  breakers       consecutive failures open a breaker (later requests
                 fail fast), and once faults stop the half-open
                 probe re-closes it — service recovers by itself
  hedging        a hung replica dispatch is raced by a hedge on a
                 second replica; the winner's result is the result
  shedding       under pinned overload, admission control holds p95
                 while the shed-disabled baseline's p95 collapses
  precision      a seeded round_exec hang mid-schedule makes a
                 progressive-precision request's deadline expire
                 between rounds: the service answers with exactly one
                 partial_final (precision:* degrade hop, confidence
                 band from the last completed round), and the whole
                 outcome replays exactly from (seed, spec)
  fabric         a 3-worker serving fabric (service/fabric/) under a
                 worker_conn partition blip and a hard worker kill
                 mid-load: every submitted line reaches exactly one
                 terminal outcome, re-dispatched requests record the
                 worker_disconnect hop in their degrade chain, and
                 every ok response is bit-identical to the
                 single-process baseline

Phases run per seed (--seeds N => seeds 0..N-1); any violated
property is reported and fails the gate. The heavier overload soak
runs only with --slow. Wired into tier-1 by tests/test_chaos.py.

    python tools/check_chaos.py [--seeds 3] [--slow]
"""

from __future__ import annotations

import argparse
import glob
import io
import json
import os
import shutil
import sys
import tempfile
import time

sys.path.insert(
    0, os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
)
sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

# the replica phases need a multi-device mesh; standalone runs get
# the same 8-device virtual CPU the test harness forces. A no-op (or
# a failure) when a backend already exists — in-process callers
# (tests/test_chaos.py) have already configured the platform.
try:
    from pluss_sampler_optimization_tpu._platform import (
        force_virtual_cpu,
    )

    force_virtual_cpu(8)
except Exception:
    pass

import loadgen  # noqa: E402
from pluss_sampler_optimization_tpu.config import (  # noqa: E402
    FaultConfig,
    ResilienceConfig,
)
from pluss_sampler_optimization_tpu.runtime import (  # noqa: E402
    faults,
    lockwitness,
)

TIMEOUT_S = 120.0


def _requests(n: int, seed: int, unique_frac: float = 1.0) -> list:
    """Deterministic request set with caller-supplied trace ids, so
    replica_dispatch fault decisions (keyed on trace_id) replay."""
    reqs = loadgen.make_requests(n, seed, unique_frac=unique_frac)
    import dataclasses

    return [
        dataclasses.replace(r, trace_id=f"{r.id}-t") for r in reqs
    ]


def _service(cache_dir, resilience, seed, replicas=None,
             service_time_s: float = 0.005):
    from pluss_sampler_optimization_tpu.service import AnalysisService

    return AnalysisService(
        cache_dir=cache_dir, max_workers=4, replicas=replicas,
        runner=loadgen.synthetic_runner(service_time_s, seed=seed),
        resilience=resilience,
    )


def _run_all(svc, reqs) -> list:
    tickets = [svc.submit(r) for r in reqs]
    return [svc.result(t, timeout=TIMEOUT_S) for t in tickets]


def _digests(resps) -> dict:
    return {r.id: r.mrc_digest for r in resps}


def _chaos_resilience(seed: int) -> ResilienceConfig:
    # max_retries covers the summed max_fires of the failing
    # engine_execute rules below (2 raise + 1 compile_failure), so a
    # request can exhaust every injected failure and still succeed.
    # Timing-coupled features stay OUT of this config — no
    # attempt_timeout_s, no hedge_after_s — because this phase also
    # checks exact REPLAY, and a wall-clock race (did the hedge fire
    # before the attempt finished?) would change occurrence counts
    # between runs; hangs/timeouts and hedging get their own phases.
    # breaker_failures sits above any consecutive-failure run the mix
    # can produce (the dedicated breaker phase tests breakers).
    return ResilienceConfig(
        max_retries=4,
        backoff_base_s=0.01, backoff_max_s=0.05, backoff_seed=seed,
        breaker_failures=50, breaker_probation_s=0.2,
    )


def _chaos_spec(seed: int) -> FaultConfig:
    return FaultConfig(seed=seed, rules=(
        {"site": "engine_execute", "kind": "raise", "p": 0.35,
         "max_fires": 2},
        {"site": "engine_execute", "kind": "compile_failure",
         "p": 0.15, "max_fires": 1},
        {"site": "replica_dispatch", "kind": "raise", "p": 0.2,
         "max_fires": 1},
        {"site": "replica_dispatch", "kind": "latency", "p": 0.25,
         "latency_s": 0.03, "max_fires": 2},
        {"site": "cache_store", "kind": "raise", "p": 0.4,
         "max_fires": 1},
    ))


def _chaos_run(seed: int, reqs, cache_dir: str) -> dict:
    """One armed run; returns the replay-comparable summary."""
    injector = faults.install(_chaos_spec(seed))
    try:
        with _service(cache_dir, _chaos_resilience(seed), seed,
                      replicas=2) as svc:
            resps = _run_all(svc, reqs)
            st = svc.executor.stats()
        stats = injector.stats()
    finally:
        faults.uninstall()
    return {
        "ok_by_id": {r.id: r.ok for r in resps},
        "digests": _digests(resps),
        "fired_by_kind": stats["fired_by_kind"],
        "resolved": len(resps),
        "retried": st.get("retried", 0),
        "shed": st.get("shed", 0),
        "errors": {r.id: r.error for r in resps if not r.ok},
    }


def check_chaos_vs_baseline(seed: int, tmp: str,
                            problems: list) -> None:
    """Baseline digests -> chaos run (resolve-once, bit-identity) ->
    replay (determinism) -> corrupt-on-load quarantine."""
    reqs = _requests(8, seed, unique_frac=0.75)

    with _service(os.path.join(tmp, "base"), _chaos_resilience(seed),
                  seed, replicas=2) as svc:
        base = _run_all(svc, reqs)
    if not all(r.ok for r in base):
        problems.append(f"seed {seed}: fault-free baseline failed: "
                        f"{[r.error for r in base if not r.ok]}")
        return
    baseline = _digests(base)

    runs = [
        _chaos_run(seed, reqs, os.path.join(tmp, f"chaos{i}"))
        for i in (0, 1)
    ]
    run = runs[0]
    if run["resolved"] != len(reqs):
        problems.append(
            f"seed {seed}: {run['resolved']} of {len(reqs)} chaos "
            "requests resolved (resolve-once violated)"
        )
    if sum(run["fired_by_kind"].values()) == 0:
        problems.append(f"seed {seed}: chaos run injected nothing — "
                        "the gate tested no faults")
    bad = [i for i, ok in run["ok_by_id"].items() if not ok]
    if bad:
        problems.append(
            f"seed {seed}: chaos requests failed despite a retry "
            f"budget covering every injected fault: "
            f"{ {i: run['errors'][i] for i in bad} }"
        )
    mismatch = {
        i: (d, baseline.get(i))
        for i, d in run["digests"].items()
        if run["ok_by_id"][i] and d != baseline.get(i)
    }
    if mismatch:
        problems.append(f"seed {seed}: chaos successes are NOT "
                        f"bit-identical to baseline: {mismatch}")
    failing = sum(
        run["fired_by_kind"].get(k, 0)
        for k in ("raise", "compile_failure", "hang")
    )
    if failing and run["retried"] == 0:
        problems.append(f"seed {seed}: {failing} failing fault(s) "
                        "fired but nothing was retried")
    if runs[0] != runs[1]:
        diff = {k: (runs[0][k], runs[1][k]) for k in runs[0]
                if runs[0][k] != runs[1][k]}
        problems.append(f"seed {seed}: chaos run did not replay "
                        f"from (seed, spec): {diff}")

    # corruption quarantine: re-read the chaos run's disk store with
    # every first load mangled; records must be quarantined, counted,
    # and recomputed to the baseline digests
    store = os.path.join(tmp, "chaos0")
    n_disk = len(glob.glob(os.path.join(store, "*", "*.json")))
    faults.install(FaultConfig(seed=seed, rules=(
        {"site": "cache_load", "kind": "corrupt", "p": 1.0,
         "max_fires": 1},
    )))
    try:
        with _service(store, _chaos_resilience(seed), seed) as svc:
            resps = _run_all(svc, reqs)
            cache_stats = svc.cache.stats()
    finally:
        faults.uninstall()
    if not all(r.ok for r in resps):
        problems.append(f"seed {seed}: requests failed after cache "
                        "corruption (should recompute)")
    if _digests(resps) != baseline:
        problems.append(f"seed {seed}: post-corruption recomputes "
                        "are not bit-identical to baseline")
    quarantined = cache_stats.get("corrupt_quarantined", 0)
    n_corrupt = len(glob.glob(os.path.join(store, "*", "*.corrupt")))
    if n_disk and quarantined < 1:
        problems.append(f"seed {seed}: {n_disk} disk records but "
                        "none quarantined under corrupt faults")
    if quarantined != n_corrupt:
        problems.append(
            f"seed {seed}: quarantine count {quarantined} != "
            f"{n_corrupt} *.corrupt files on disk"
        )


def check_breaker_recovery(seed: int, problems: list) -> None:
    """Failures open the engine breaker, open fails fast, and after
    faults stop the half-open probe re-closes it; the first request
    served after recovery is bit-identical to its fault-free run."""
    from pluss_sampler_optimization_tpu.service import AnalysisRequest

    reqs = [
        AnalysisRequest(model=loadgen.MODEL, n=loadgen.MODEL_N,
                        engine="sampled", ratio=0.2, seed=9000 + k,
                        id=f"br-{k}", trace_id=f"br-{k}-t")
        for k in range(5)
    ]
    with _service(None, None, seed) as svc:
        want = svc.analyze(reqs[0], timeout=TIMEOUT_S).mrc_digest

    res = ResilienceConfig(breaker_failures=2,
                           breaker_probation_s=0.2)
    faults.install(FaultConfig(seed=seed, rules=(
        {"site": "engine_execute", "kind": "raise", "p": 1.0},
    )))
    try:
        with _service(None, res, seed) as svc:
            r1 = svc.analyze(reqs[1], timeout=TIMEOUT_S)
            r2 = svc.analyze(reqs[2], timeout=TIMEOUT_S)
            r3 = svc.analyze(reqs[3], timeout=TIMEOUT_S)
            if r1.ok or r2.ok:
                problems.append(f"seed {seed}: p=1.0 raise faults "
                                "did not fail requests")
            if r3.ok or "circuit breaker open" not in (r3.error or ""):
                problems.append(
                    f"seed {seed}: third request was not failed fast "
                    f"by the open breaker (error: {r3.error!r})"
                )
            faults.uninstall()
            time.sleep(0.25)  # let probation elapse
            r4 = svc.analyze(reqs[4], timeout=TIMEOUT_S)
            r5 = svc.analyze(reqs[0], timeout=TIMEOUT_S)
            st = svc.executor.stats()
    finally:
        faults.uninstall()
    if not (r4.ok and r5.ok):
        problems.append(f"seed {seed}: service did not recover after "
                        f"probation ({r4.error!r}, {r5.error!r})")
    elif r5.mrc_digest != want:
        problems.append(f"seed {seed}: post-recovery result is not "
                        "bit-identical to the fault-free run")
    br = (st.get("breakers") or {}).get("sampled") or {}
    if st.get("breaker_opened", 0) < 1 \
            or st.get("breaker_open_skips", 0) < 1 \
            or st.get("breaker_reclosed", 0) < 1 \
            or br.get("state") != "closed":
        problems.append(
            f"seed {seed}: breaker lifecycle counters wrong: "
            f"opened={st.get('breaker_opened')} "
            f"skips={st.get('breaker_open_skips')} "
            f"reclosed={st.get('breaker_reclosed')} state={br}"
        )


def check_attempt_timeout(seed: int, problems: list) -> None:
    """A hung attempt overruns the per-attempt budget, is abandoned,
    and the seeded-backoff retry serves the request bit-identically."""
    import dataclasses

    from pluss_sampler_optimization_tpu.service import AnalysisRequest

    req = AnalysisRequest(model=loadgen.MODEL, n=loadgen.MODEL_N,
                          engine="sampled", ratio=0.2, seed=9500,
                          threads=3, id="to-0", trace_id="to-0-t")
    warm = dataclasses.replace(req, seed=9501, id="to-w",
                               trace_id="to-w-t")
    with _service(None, None, seed) as svc:
        want = svc.analyze(req, timeout=TIMEOUT_S).mrc_digest
    res = ResilienceConfig(attempt_timeout_s=0.25, max_retries=2,
                           backoff_base_s=0.01, backoff_max_s=0.02,
                           backoff_seed=seed)
    with _service(None, res, seed) as svc:
        # warm the runner memo with a DIFFERENT fingerprint before
        # arming faults, so the hung request's retry attempt is far
        # inside the 0.25s budget (no spurious second timeout)
        svc.analyze(warm, timeout=TIMEOUT_S)
        faults.install(FaultConfig(seed=seed, rules=(
            {"site": "engine_execute", "kind": "hang", "p": 1.0,
             "hang_s": 0.75, "max_fires": 1},
        )))
        try:
            resp = svc.analyze(req, timeout=TIMEOUT_S)
            st = svc.executor.stats()
        finally:
            faults.uninstall()
    if not resp.ok or resp.retries < 1 or st.get("retried", 0) < 1:
        problems.append(
            f"seed {seed}: hung attempt was not abandoned+retried "
            f"(ok={resp.ok} retries={resp.retries} "
            f"error={resp.error!r})"
        )
    elif resp.mrc_digest != want:
        problems.append(f"seed {seed}: post-timeout retry result is "
                        "not bit-identical to the fault-free run")


def check_hedging(seed: int, problems: list) -> None:
    """Every primary dispatch hangs once; the hedge on the second
    replica must win with bit-identical results."""
    reqs = _requests(3, seed + 31)
    with _service(None, None, seed) as svc:
        want = _digests(_run_all(svc, reqs))
    res = ResilienceConfig(hedge_after_s=0.1, breaker_failures=50)
    faults.install(FaultConfig(seed=seed, rules=(
        {"site": "replica_dispatch", "kind": "hang", "p": 1.0,
         "hang_s": 0.6, "max_fires": 1},
    )))
    try:
        with _service(None, res, seed, replicas=2) as svc:
            resps = [svc.analyze(r, timeout=TIMEOUT_S) for r in reqs]
            st = svc.executor.stats()
    finally:
        faults.uninstall()
    if not all(r.ok for r in resps):
        problems.append(f"seed {seed}: hedged requests failed: "
                        f"{[r.error for r in resps if not r.ok]}")
    elif _digests(resps) != want:
        problems.append(f"seed {seed}: hedged results are not "
                        "bit-identical to unhedged runs")
    if st.get("hedged", 0) < 1:
        problems.append(f"seed {seed}: hung dispatches never "
                        "triggered a hedge")


def check_serve_line_faults(seed: int, problems: list) -> None:
    """serve_jsonl under per-line faults: every input line still gets
    exactly one response entry; faulted lines carry the injected
    error, the rest succeed."""
    from pluss_sampler_optimization_tpu.service import serve_jsonl

    lines = [
        json.dumps({"model": loadgen.MODEL, "n": loadgen.MODEL_N,
                    "engine": "sampled", "ratio": 0.2,
                    "seed": 1000 + k, "id": f"sv-{k}"})
        for k in range(4)
    ]
    injector = faults.install(FaultConfig(seed=seed, rules=(
        {"site": "serve_line", "kind": "raise", "p": 0.5},
    )))
    try:
        with _service(None, None, seed) as svc:
            fout = io.StringIO()
            failures = serve_jsonl(
                svc, io.StringIO("\n".join(lines) + "\n"), fout
            )
        fired = injector.stats()["fired_by_kind"].get("raise", 0)
    finally:
        faults.uninstall()
    entries = [json.loads(ln) for ln in
               fout.getvalue().splitlines() if ln.strip()]
    faulted = [e for e in entries
               if "fault injected" in (e.get("error") or "")]
    if len(entries) != len(lines):
        problems.append(f"seed {seed}: {len(lines)} serve lines -> "
                        f"{len(entries)} responses")
    if len(faulted) != fired or failures != fired:
        problems.append(
            f"seed {seed}: serve_line fired {fired} but "
            f"{len(faulted)} faulted entries / {failures} failures"
        )
    if any(not e.get("ok") for e in entries
           if e not in faulted):
        problems.append(f"seed {seed}: non-faulted serve lines "
                        "failed")


def _fabric_run(seed: int, lines: list[str], cache_dir: str,
                kill_after: int = 0,
                service_time_s: float = 0.2) -> dict:
    """One in-process 3-worker fabric pass over `lines`. With
    kill_after=k, the worker holding the most in-flight work is
    severed (WorkerServer.close — no drain, the abrupt chaos kill)
    right after the k-th submission, while later lines keep arriving.
    Returns docs in submit order plus the router's counters."""
    from pluss_sampler_optimization_tpu.config import FabricConfig
    from pluss_sampler_optimization_tpu.service.fabric import (
        Router,
        WorkerServer,
    )

    fabric = FabricConfig(
        hb_interval_s=0.2, hb_timeout_s=3.0,
        reconnect_attempts=2, reconnect_delay_s=0.1,
        connect_timeout_s=10.0, drain_timeout_s=30.0,
    )
    services, workers = [], []
    docs: list = []
    stats: dict = {}
    killed_wid = None
    try:
        for wid in range(3):
            svc = _service(cache_dir, None, seed,
                           service_time_s=service_time_s)
            ws = WorkerServer(svc, worker_id=wid, fabric=fabric)
            ws.start()
            services.append(svc)
            workers.append(ws)
        router = Router([ws.address for ws in workers], fabric)
        router.start()
        try:
            entries = []
            for i, line in enumerate(lines, start=1):
                entries.append(router.submit_line(line, i))
                if kill_after and i == kill_after:
                    # sever the busiest worker so the kill provably
                    # strands in-flight work for re-dispatch
                    victim = max(router.links,
                                 key=lambda lk: len(lk.inflight))
                    killed_wid = victim.worker_id
                    workers[killed_wid].close()
            docs = [e.wait(timeout=TIMEOUT_S) for e in entries]
            stats = router.stats()
        finally:
            router.close(graceful=True)
    finally:
        for ws in workers:
            ws.close()
        for svc in services:
            svc.close()
    return {"docs": docs, "stats": stats, "killed": killed_wid}


def check_fabric_chaos(seed: int, tmp: str, problems: list) -> None:
    """The fabric phase: single-process baseline digests, then (a) a
    deterministic worker_conn partition blip (first dispatch severed;
    the link reconnects and re-sends, nothing is lost) and (b) a hard
    kill of the busiest of 3 workers mid-load (in-flight work
    re-dispatches to the ring successor, recorded in the degrade
    chain). Both runs must resolve every line exactly once with MRC
    digests bit-identical to the baseline."""
    reqs = _requests(10, seed + 57)
    lines = [loadgen.request_jsonl(r) for r in reqs]
    with _service(os.path.join(tmp, "fab_base"), None, seed) as svc:
        base = _run_all(svc, reqs)
    if not all(r.ok for r in base):
        problems.append(
            f"seed {seed}: fabric baseline failed: "
            f"{[r.error for r in base if not r.ok]}"
        )
        return
    baseline = _digests(base)

    def judge(tag: str, run: dict, want_redispatch: bool) -> None:
        docs = run["docs"]
        got_ids = [d.get("id") for d in docs if d is not None]
        if (len(docs) != len(reqs) or None in docs
                or got_ids != [r.id for r in reqs]):
            problems.append(
                f"seed {seed}: fabric {tag}: {len(reqs)} lines -> "
                f"{len([d for d in docs if d])} responses "
                "(exactly-once violated)"
            )
            return
        bad = {d["id"]: d.get("error") for d in docs
               if not d.get("ok")}
        if bad:
            problems.append(
                f"seed {seed}: fabric {tag}: requests failed: {bad}"
            )
        mismatch = {
            d["id"]: (d.get("mrc_digest"), baseline.get(d["id"]))
            for d in docs
            if d.get("ok")
            and d.get("mrc_digest") != baseline.get(d["id"])
        }
        if mismatch:
            problems.append(
                f"seed {seed}: fabric {tag}: ok responses are NOT "
                f"bit-identical to the baseline: {mismatch}"
            )
        hopped = [
            d["id"] for d in docs
            if any(isinstance(g, dict)
                   and g.get("reason") == "worker_disconnect"
                   for g in (d.get("degraded") or []))
        ]
        if want_redispatch and not hopped:
            problems.append(
                f"seed {seed}: fabric {tag}: worker died with work "
                "in flight but no response records a "
                "worker_disconnect re-dispatch hop"
            )
        if want_redispatch and run["killed"] is not None:
            wrong = [d["id"] for d in docs
                     if d.get("id") in hopped
                     and d.get("worker_id") == run["killed"]]
            if wrong:
                problems.append(
                    f"seed {seed}: fabric {tag}: re-dispatched "
                    f"requests {wrong} still attribute the dead "
                    f"worker {run['killed']}"
                )

    # (a) partition storm: EVERY request's first send is severed
    # mid-frame (p=1; max_fires is per (rule, key) and the router
    # keys worker_conn on the entry seq, so each request blips exactly
    # once and its reconnect re-send passes). The links must ride out
    # one reconnect per dispatch without losing or doubling anything
    injector = faults.install(FaultConfig(seed=seed, rules=(
        {"site": "worker_conn", "kind": "disconnect", "p": 1.0,
         "max_fires": 1},
    )))
    try:
        blip = _fabric_run(seed, lines,
                           os.path.join(tmp, "fab_blip"))
        fired = injector.stats()["fired_by_kind"].get("disconnect", 0)
    finally:
        faults.uninstall()
    judge("partition-blip", blip, want_redispatch=False)
    if fired != len(reqs):
        problems.append(
            f"seed {seed}: fabric partition-blip fired {fired} "
            f"disconnect fault(s), wanted one per request "
            f"({len(reqs)})"
        )
    reconnects = sum(
        w.get("reconnects", 0)
        for w in blip["stats"].get("workers", {}).values()
    )
    if fired and not reconnects:
        problems.append(f"seed {seed}: fabric partition-blip severed "
                        "a link but nothing reconnected")

    # (b) hard kill: 1 of 3 workers dies mid-load with work in flight
    kill = _fabric_run(seed, lines, os.path.join(tmp, "fab_kill"),
                       kill_after=4)
    judge("worker-kill", kill, want_redispatch=True)
    if kill["stats"].get("counters", {}).get("redispatched", 0) < 1:
        problems.append(
            f"seed {seed}: fabric worker-kill redispatched counter "
            "is zero — the dead worker's in-flight work went nowhere"
        )


def check_progressive_deadline(seed: int, problems: list) -> None:
    """A seeded round_exec hang on round 1 (with a deadline sized to
    cover round 0 but not the hang) forces the progressive engine to
    stop at a round boundary: the request must resolve to exactly one
    partial_final carrying a precision:* degrade hop and the last
    streamed round's band, and a second armed run must reproduce the
    identical (rounds, band, digest) tuple — the round count is a
    pure function of (fault spec, deadline), never machine speed.

    Uses a REAL AnalysisService (not the synthetic runner): the
    progressive round loop IS the engine under test."""
    from pluss_sampler_optimization_tpu.service import (
        AnalysisService,
        serve_jsonl,
    )

    line = json.dumps({
        "id": "prog-dl", "model": loadgen.MODEL, "n": 32,
        "engine": "sampled", "ratio": 0.3, "seed": 7000 + seed,
        "tolerance": 0.0, "max_rounds": 3, "deadline_s": 1.0,
    })

    def run():
        faults.install(FaultConfig(seed=seed, rules=(
            {"site": "round_exec", "kind": "hang", "hang_s": 3.0,
             "match": {"round": 1}, "p": 1.0, "max_fires": 1},
        )))
        try:
            with AnalysisService(cache_dir=None) as svc:
                fout = io.StringIO()
                serve_jsonl(svc, io.StringIO(line + "\n"), fout)
        finally:
            faults.uninstall()
        docs = [json.loads(ln)
                for ln in fout.getvalue().splitlines()]
        return ([d for d in docs if d.get("partial")],
                [d for d in docs if not d.get("partial")])

    partials, finals = run()
    if len(finals) != 1 or not finals[0].get("partial_final"):
        problems.append(
            f"seed {seed}: progressive deadline did not yield exactly "
            f"one partial_final ({len(finals)} finals, "
            f"{finals[0] if finals else None})"
        )
        return
    final = finals[0]
    if not any(str(h.get("reason", "")).startswith("precision:")
               for h in (final.get("degraded") or [])):
        problems.append(
            f"seed {seed}: partial_final lacks a precision:* degrade "
            f"hop: {final.get('degraded')}"
        )
    if not partials or final.get("band_width") > \
            partials[-1]["band_width"]:
        problems.append(
            f"seed {seed}: partial_final band "
            f"{final.get('band_width')} exceeds the last streamed "
            f"partial ({partials[-1]['band_width'] if partials else None})"
        )
    partials2, finals2 = run()
    want = (final.get("rounds"), final.get("band_width"),
            final.get("mrc_digest"), len(partials))
    final2 = finals2[0] if finals2 else {}
    got = (final2.get("rounds"), final2.get("band_width"),
           final2.get("mrc_digest"), len(partials2))
    if want != got:
        problems.append(
            f"seed {seed}: progressive deadline replay diverged: "
            f"{want} != {got}"
        )


def check_overload(seed: int, problems: list, slow: bool) -> None:
    """The pinned overload pair: same arrivals, shed on vs off."""
    kw = dict(n=400, rate_rps=400.0, queue_limit=4, max_workers=2,
              service_time_s=0.02, seed=seed) if slow else \
         dict(n=60, rate_rps=300.0, queue_limit=4, max_workers=2,
              service_time_s=0.02, seed=seed)
    cmp = loadgen.overload_comparison(timeout_s=TIMEOUT_S, **kw)
    on, off = cmp["shed_on"], cmp["shed_off"]
    for label, rep in (("shed-on", on), ("shed-off", off)):
        if rep["submitted"] != kw["n"] or rep["failed"]:
            problems.append(
                f"seed {seed}: overload {label} lost/failed requests"
                f" ({rep['submitted']} resolved, {rep['failed']} "
                "failed)"
            )
    if on["shed"] == 0:
        problems.append(f"seed {seed}: overload never shed with the "
                        "admission gate on")
    if off["shed"] != 0:
        problems.append(f"seed {seed}: shed-disabled run shed "
                        f"{off['shed']} requests")
    p95_on = on["latency_p95_s"] or 0.0
    p95_off = off["latency_p95_s"] or 0.0
    if p95_off <= p95_on:
        problems.append(
            f"seed {seed}: shedding showed no tail benefit "
            f"(p95 on={p95_on} off={p95_off})"
        )
    if slow:
        # the soak pins the SLO numbers, not just the ordering
        if p95_on > 0.6:
            problems.append(f"seed {seed}: soak p95 {p95_on}s with "
                            "shedding on blows the 0.6s SLO")
        if p95_off < 1.2:
            problems.append(
                f"seed {seed}: soak baseline p95 {p95_off}s did not "
                "collapse (load too light to prove shedding)"
            )


def check_witness_identity(seed: int, problems: list) -> None:
    """The lock witness must be a pure observer: the same request set
    served witness-off and witness-on yields bit-identical MRC
    digests. Runs only when the gate armed the witness (the off-run
    services are built inside a disable/enable window, so their locks
    come out plain)."""
    reqs = _requests(4, seed + 17)
    lockwitness.disable()
    try:
        with _service(None, None, seed) as svc:
            off = _digests(_run_all(svc, reqs))
    finally:
        lockwitness.enable()
    with _service(None, None, seed) as svc:
        on = _digests(_run_all(svc, reqs))
    if on != off:
        diff = {k: (on[k], off.get(k)) for k in on
                if on[k] != off.get(k)}
        problems.append(
            f"seed {seed}: MRC digests differ witness-on vs "
            f"witness-off: {diff}"
        )


def check_witness_report(problems: list) -> None:
    """After every seed ran under the armed witness: no lock-order
    inversion was observed at runtime, and every observed (held ->
    acquired) pair is in the static analyzer's lock-order graph — the
    static graph is a sound superset of reality."""
    from pluss_sampler_optimization_tpu.analysis import concurrency

    doc = lockwitness.report()
    if doc["inversion_count"]:
        problems.append(
            f"lock witness observed {doc['inversion_count']} "
            f"lock-order inversion(s): {doc['inversions']}"
        )
    static = set(concurrency.analyze_files().edge_pairs())
    unmodeled = lockwitness.observed_edges() - static
    if unmodeled:
        problems.append(
            "runtime lock orders missing from the static graph "
            f"(analyzer unsound): {sorted(unmodeled)}"
        )
    print(f"check_chaos: witness: {len(doc['edges'])} observed "
          f"edge(s), {doc['inversion_count']} inversion(s), "
          f"{len(static)} static edge(s)")


def run_seed(seed: int, slow: bool, witness: bool = False) -> list[str]:
    problems: list[str] = []
    tmp = tempfile.mkdtemp(prefix=f"check_chaos_s{seed}_")
    try:
        t0 = time.perf_counter()
        check_chaos_vs_baseline(seed, tmp, problems)
        check_breaker_recovery(seed, problems)
        check_attempt_timeout(seed, problems)
        check_hedging(seed, problems)
        check_serve_line_faults(seed, problems)
        check_progressive_deadline(seed, problems)
        check_fabric_chaos(seed, tmp, problems)
        check_overload(seed, problems, slow)
        if witness:
            check_witness_identity(seed, problems)
        print(f"check_chaos: seed {seed}: "
              f"{'OK' if not problems else 'FAIL'} "
              f"({time.perf_counter() - t0:.1f}s)")
    finally:
        shutil.rmtree(tmp, ignore_errors=True)
    return problems


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description="seeded chaos gate: fault injection, retries, "
        "hedging, breakers, quarantine, and load shedding"
    )
    ap.add_argument("--seeds", type=int, default=3,
                    help="run seeds 0..N-1 (default 3)")
    ap.add_argument("--slow", action="store_true",
                    help="include the overload soak with pinned SLO "
                    "numbers")
    ap.add_argument("--no-witness", action="store_true",
                    help="run without the lockdep witness (skips the "
                    "inversion/superset and on-vs-off identity checks)")
    args = ap.parse_args(argv)
    if faults.get() is not None:
        # a leftover injector would corrupt every phase's baseline
        faults.uninstall()
    witness = not args.no_witness
    was_enabled = lockwitness.enabled()
    if witness:
        lockwitness.reset()
        lockwitness.enable()
    problems: list[str] = []
    try:
        for seed in range(args.seeds):
            problems += run_seed(seed, args.slow, witness=witness)
        if witness:
            check_witness_report(problems)
    finally:
        # leave the process as found: in-process callers
        # (tests/test_chaos.py) must not inherit an armed witness
        if witness and not was_enabled:
            lockwitness.disable()
            lockwitness.reset()
    for p in problems:
        print(f"check_chaos: FAIL: {p}", file=sys.stderr)
    print(f"check_chaos: {args.seeds} seed(s), "
          f"{len(problems)} problem(s)")
    return 1 if problems else 0


if __name__ == "__main__":
    sys.exit(main())
