"""Concurrency gate: static lock-order / blocking / shared-state
analysis over the serving runtime's own source.

Runs pluss_sampler_optimization_tpu/analysis/concurrency/ over every
threaded module (service/, runtime/obs/, telemetry, faults,
lockwitness, cli) and fails on any unallowlisted C_* diagnostic:

    python tools/check_concurrency.py [--json] [--graph]
        [--fixtures] [--fixture NAME] [--allowlist FILE]

Exit code: nonzero when any violation survives the allowlist.
`--graph` prints the static lock-order graph (the edge set the
runtime witness in runtime/lockwitness.py is checked against — same
lock names, so `observed ⊆ static` is a set comparison; the chaos
gate tools/check_chaos.py enforces it end-to-end). `--fixtures` runs
the ≥10 seeded bad-pattern fixtures and fails unless every one still
trips its expected code; `--fixture NAME` runs the gate over that
single fixture as if it were repo source (exits nonzero — the
per-fixture catch tier-1 asserts). No jax import; the gate is
instant.

Allowlist (tools/check_concurrency_allow.txt): one violation id
(`path::qualname::rule`) per line, '#' comments, added only after
review — the same workflow as tools/lint_determinism_allow.txt.
"""

from __future__ import annotations

import argparse
import os
import sys

sys.path.insert(
    0, os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
)

from pluss_sampler_optimization_tpu.analysis import (  # noqa: E402
    concurrency,
    lint_common,
)

ALLOWLIST_PATH = os.path.join(
    os.path.dirname(os.path.abspath(__file__)),
    "check_concurrency_allow.txt",
)


def run_gate(allowlist_path: str | None = ALLOWLIST_PATH):
    """(kept_violations, suppressed, result) for the repo run."""
    res = concurrency.analyze_files()
    allow = (
        lint_common.read_allowlist(allowlist_path)
        if allowlist_path else set()
    )
    kept, suppressed = lint_common.split_allowed(res.violations,
                                                allow)
    return kept, suppressed, res


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description="static concurrency analysis gate"
    )
    ap.add_argument("--json", action="store_true",
                    help="machine-readable report")
    ap.add_argument("--graph", action="store_true",
                    help="print the static lock-order graph")
    ap.add_argument("--fixtures", action="store_true",
                    help="self-test: every seeded bad pattern must "
                         "trip its expected C_* code")
    ap.add_argument("--fixture", default=None,
                    help="run the gate over one named fixture "
                         "(exits nonzero: the fixture is a seeded "
                         "bug)")
    ap.add_argument("--allowlist", default=ALLOWLIST_PATH,
                    help="violation-id allowlist file")
    args = ap.parse_args(argv)

    if args.fixtures:
        problems = lint_common.check_fixtures(
            concurrency.FIXTURES, concurrency.lint_source
        )
        for p in problems:
            print(f"FIXTURE FAIL: {p}", file=sys.stderr)
        print(
            f"check_concurrency --fixtures: "
            f"{len(concurrency.FIXTURES)} fixture(s), "
            f"{len(problems)} problem(s)"
        )
        return 1 if problems else 0

    if args.fixture is not None:
        if args.fixture not in concurrency.FIXTURES:
            print(
                f"unknown fixture {args.fixture!r}; have: "
                f"{', '.join(sorted(concurrency.FIXTURES))}",
                file=sys.stderr,
            )
            return 2
        source, _want = concurrency.FIXTURES[args.fixture]
        violations = concurrency.lint_source(
            source, f"<fixture:{args.fixture}>"
        )
        doc = lint_common.report_doc(
            "check_concurrency", 1, violations
        )
        lint_common.print_report(doc, args.json)
        return 1 if violations else 0

    kept, suppressed, res = run_gate(args.allowlist)
    extra = {
        "n_files": res.n_files,
        "n_functions": res.n_functions,
        "n_edges": len(res.edges),
    }
    if args.graph or args.json:
        extra["graph"] = [
            {"src": a, "dst": b, "sites": len(sites)}
            for (a, b), sites in sorted(res.edges.items())
        ]
        extra["inventory"] = res.inventory
    doc = lint_common.report_doc(
        "check_concurrency", res.n_files, kept, suppressed, extra
    )
    if args.graph and not args.json:
        for (a, b), sites in sorted(res.edges.items()):
            p, q, ln = sites[0]
            print(f"{a} -> {b}  ({len(sites)} site(s), e.g. "
                  f"{p}:{ln} in {q})")
    lint_common.print_report(doc, args.json)
    return 1 if kept else 0


if __name__ == "__main__":
    raise SystemExit(main())
