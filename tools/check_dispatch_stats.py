"""Audit a sampled run's dispatch economics from its telemetry JSON;
exit nonzero when cross-ref fusion silently regressed.

The fused sampled engine (pluss_sampler_optimization_tpu/sampler/
sampled.py::_sampled_outputs_fused and the sharded twin) promises one
dispatch per kernel-signature bucket per chunk group, and exports the
plan as gauges: `ref_buckets` (buckets that dispatched) and
`expected_chunks` (the largest per-bucket dispatch count). A fusion
regression — refs falling out of their bucket, a chunk plan
fragmenting — shows up as `dispatches` exceeding the bucket plan's
ceiling, long before any wall-time benchmark notices. This checker is
the contract's enforcement point:

    dispatches <= ref_buckets * expected_chunks + capacity_regrows

(each capacity regrow legitimately re-runs one bucket dispatch).
Runs with kernel_backend="native" export their own plan, checked the
same way:

    dispatches_native <= native_chunk_plan

(native regrows are host-side C re-calls, never re-dispatches, so the
plan is a hard ceiling). Exercised from the test suite
(tests/test_telemetry.py) like the other check_* tools, so tier-1
catches regressions.

    python tools/check_dispatch_stats.py TELEMETRY.json [more.json ...]

Documents without the fusion gauges (unfused runs, other engines) are
skipped by default; pass --require-fused to fail on them instead —
the bench sidecar for a --fuse-refs run should never lack the gauges.
"""

from __future__ import annotations

import argparse
import json
import os
import sys

sys.path.insert(
    0, os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
)


def check(doc) -> tuple[str | None, str | None]:
    """(error, note) for one parsed telemetry document. error=None
    means the document passes; note=None means nothing to report.
    Single source of truth for the tool AND the tests."""
    if not isinstance(doc, dict):
        return "document is not a JSON object", None
    counters = doc.get("counters")
    gauges = doc.get("gauges")
    if not isinstance(counters, dict) or not isinstance(gauges, dict):
        return "missing counters/gauges objects", None
    # native fast-path accounting rides the same sidecar: the serial
    # runner exports its chunk plan as the `native_chunk_plan` counter
    # (a counter, not a gauge, so multi-rep bench accumulation keeps
    # the bound meaningful) and stamps every native dispatch into
    # `dispatches_native`. One native chunk is exactly one raw-kernel
    # dispatch, and capacity regrows happen host-side (a C re-call,
    # never a re-dispatch), so the plan is a hard ceiling.
    native = counters.get("dispatches_native")
    native_note = None
    if native is not None:
        plan = counters.get("native_chunk_plan")
        if plan is None:
            return (
                f"dispatches_native {native:g} recorded without a "
                "native_chunk_plan counter — native accounting "
                "regressed",
                None,
            )
        if native > plan:
            return (
                f"dispatches_native {native:g} exceed the chunk "
                f"plan {plan:g} — native fast path re-dispatched",
                None,
            )
        native_note = f"native {native:g} <= plan {plan:g}"
    union = gauges.get("ref_buckets_union")
    buckets = union if union is not None else gauges.get("ref_buckets")
    chunks = gauges.get("expected_chunks")
    if buckets is None or chunks is None:
        if native_note:
            # the native path is serial by construction, so lacking
            # the fusion gauges is its normal shape
            return None, (
                f"{native_note}; no fusion gauges (native/unfused "
                "run) — fusion bound skipped"
            )
        return None, "no fusion gauges (unfused run?) — skipped"
    # batched (cross-request) runs export ref_buckets_union: the bound
    # is over the UNION bucket plan, the whole point of merging —
    # K requests' dispatches must not exceed one union plan's ceiling
    kind = "union buckets" if union is not None else "buckets"
    dispatches = counters.get("dispatches", 0)
    regrows = counters.get("capacity_regrows", 0)
    bound = buckets * chunks + regrows
    if dispatches > bound:
        return (
            f"dispatches {dispatches:g} exceed the bucket plan's "
            f"ceiling {bound:g} ({kind} {buckets:g} * "
            f"expected_chunks {chunks:g} + capacity_regrows "
            f"{regrows:g}) — cross-ref fusion regressed",
            None,
        )
    note = (
        f"dispatches {dispatches:g} <= {bound:g} "
        f"({buckets:g} {kind} * {chunks:g} chunks + {regrows:g} "
        "regrows)"
    )
    if native_note:
        note += f"; {native_note}"
    return None, note


def main(argv=None) -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("paths", nargs="+", help="telemetry JSON file(s)")
    ap.add_argument(
        "--require-fused", action="store_true",
        help="fail documents that lack the fusion gauges instead of "
        "skipping them",
    )
    args = ap.parse_args(argv)
    rc = 0
    for path in args.paths:
        try:
            with open(path) as f:
                doc = json.load(f)
        except (OSError, ValueError) as e:
            print(f"{path}: unreadable ({e})", file=sys.stderr)
            rc = 1
            continue
        error, note = check(doc)
        if error is None and note and "skipped" in note and (
            args.require_fused
        ):
            error, note = f"{note} but --require-fused is set", None
        if error:
            rc = 1
            print(f"{path}: {error}", file=sys.stderr)
        else:
            print(f"{path}: OK ({note})")
    return rc


if __name__ == "__main__":
    sys.exit(main())
